"""The round engine: one walker interval for every peer, fused under jit.

This module is the TPU-native replacement for the reference's entire runtime
loop — ``Dispersy._take_step`` (walker tick), ``Dispersy.on_incoming_packets``
-> ``_on_batch_cache`` (receive pipeline) and ``store_update_forward``
(persistence + forwarding), reference: dispersy.py / community.py — recast as
one pure function

    step(state: PeerState, cfg: CommunityConfig) -> PeerState

advancing *all* peers one walk interval.  Where the reference interleaves
threads (endpoint recv thread -> reactor) and timers, the rebuild is
round-synchronous: every logical packet sent in round t is delivered (or
lost) in round t.  The full 3-hop walk exchange
(introduction-request -> introduction-response + puncture-request ->
puncture) is fused into a single round; walk timeouts therefore resolve at
the end of the round instead of 10.5 s later.  SURVEY.md §7 stage 9 covers
this class of divergence: per-round *distributions* (candidate categories,
coverage curves) are the fidelity contract, not wall-clock offsets.

Phases (each a bounded-shape kernel; see the ops modules they compose):

  0. churn       — Bernoulli rebirth mask (config #4's 5%/round), modeling a
                   process restart with wiped disk.
  1. walk send   — ``dispersy_get_walk_candidate`` sampling + the
                   introduction-request edge list, with the Bloom sync
                   payload piggybacked (``dispersy_claim_sync_bloom_filter``).
  2. request rx  — bounded request inboxes; stumble bookkeeping; third-peer
                   introduction pick; response/puncture edge lists; the sync
                   responder's missing-record selection under the response
                   budget.  Trackers (reference: tool/tracker.py — dedicated
                   introduction servers that never walk and never sync) run
                   a separate high-capacity path: a compact
                   [n_trackers, tracker_inbox] request inbox and a
                   recent-contact ring in their candidate rows.
  3. response rx — walked/introduced bookkeeping, walk success/fail stats.
  4. puncture    — puncture-request -> puncture hop, stumble on the target.
  5. sync insert — delivered records merge into each store
                   (INSERT-with-UNIQUE semantics), global-time fold.

Packet loss applies independently to every logical packet (the caller's
``packet_loss``), as UDP would.  Every stochastic draw is a counter-based
hash (:mod:`dispersy_tpu.ops.rng`) so the pure-Python oracle
(:mod:`dispersy_tpu.oracle.sim`) replays rounds bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from dispersy_tpu.config import (CONTROL_PRIORITY, EMPTY_META, EMPTY_U32,
                                 IDENTITY_PRIORITY,
                                 INTRO_REQUEST_BASE_BYTES,
                                 INTRO_RESPONSE_BYTES, META_AUTHORIZE,
                                 META_DESTROY, META_DYNAMIC, META_IDENTITY,
                                 META_MALICIOUS,
                                 META_REVOKE, META_UNDO_OTHER, META_UNDO_OWN,
                                 MISSING_IDENTITY_BYTES, MISSING_MSG_BYTES,
                                 MISSING_PROOF_BYTES, MISSING_SEQ_BYTES,
                                 NO_PEER, PERM_AUTHORIZE, PERM_REVOKE,
                                 PERM_UNDO, PUNCTURE_BYTES,
                                 PUNCTURE_REQUEST_BYTES, RECORD_BYTES,
                                 SIGNATURE_REQUEST_BYTES,
                                 SIGNATURE_RESPONSE_BYTES, CommunityConfig,
                                 user_perm_mask)
from dispersy_tpu import telemetry as tlm
from dispersy_tpu.faults import (HEALTH_BLOOM_SAT, HEALTH_COUNTER_WRAP,
                                 HEALTH_INBOX_DROP, HEALTH_STORE_INVARIANT)
from dispersy_tpu.ops import bloom, candidates as cand, inbox, rng, store as st
from dispersy_tpu.ops import faults as flt
from dispersy_tpu.ops import intake as ik
from dispersy_tpu.ops import overload as ovl
from dispersy_tpu.ops import recovery as rcv
from dispersy_tpu.parallel import mesh as par
from dispersy_tpu.recovery import NUM_HEALTH_BITS
from dispersy_tpu import storediet as sdiet
from dispersy_tpu import traceplane as trp
from dispersy_tpu.ops import telemetry as tele
from dispersy_tpu.ops import trace as trc
from dispersy_tpu.ops import timeline as tl
from dispersy_tpu.ops.hashing import record_hash
from dispersy_tpu.state import (FLAG_UNDONE, NEVER, PeerState,
                                wipe_instance_memory)

# Loss-draw salt blocks: one disjoint block per packet kind so every logical
# packet flips an independent Bernoulli coin.  Within a block, the normal
# path salts from 0 and the tracker path from _TRACKER_SALT.
_LOSS_REQUEST = 0 << 16
_LOSS_RESPONSE = 1 << 16
_LOSS_PUNCTURE_REQ = 2 << 16
_LOSS_PUNCTURE = 3 << 16
_LOSS_SYNC = 4 << 16
_LOSS_FORWARD = 5 << 16
_LOSS_SIGREQ = 6 << 16
_LOSS_SIGRESP = 7 << 16
_LOSS_PROOF_REQ = 8 << 16
_LOSS_PROOF_RESP = 9 << 16
_LOSS_SEQ_REQ = 10 << 16
_LOSS_SEQ_RESP = 11 << 16
_LOSS_MSG_REQ = 12 << 16
_LOSS_MSG_RESP = 13 << 16
_LOSS_ID_REQ = 14 << 16
_LOSS_ID_RESP = 15 << 16
_TRACKER_SALT = 1 << 15
_TRACKER_INTRO_SALT = 1 << 20
# Chaos-harness salt blocks (dispersy_tpu/faults.py): flood sends draw
# loss from their own block; corruption/duplication draws use dedicated
# PURPOSES (P_CORRUPT/P_DUP) with one sub-block per delivery channel.
_LOSS_FLOOD = 16 << 16
_FAULT_SYNC = 0 << 16
_FAULT_PUSH = 1 << 16


class _EffFaults(NamedTuple):
    """Effective fault-channel knobs for one traced round.

    On the plain path every value is the static config float and every
    ``*_on`` gate mirrors the config's compiled-in/compiled-out decision
    exactly.  Under fleet overrides (dispersy_tpu/fleet.py) the VALUES
    may be traced per-replica f32 scalars while the gates stay Python
    bools — structure (which branches trace, which state leaves exist)
    always comes from the static config, so a whole traced fault grid
    shares ONE compiled program.  Bit-compat invariant: a replica whose
    traced value equals a static config's knob computes the identical
    round, because every consumer compares ``u < jnp.float32(value)``
    either way.
    """
    packet_loss_on: bool
    packet_loss: object          # python float | traced f32 scalar
    ge_on: bool
    ge_p_bad: object
    ge_p_good: object
    ge_loss_good: object
    ge_loss_bad: object
    dup_on: bool
    dup_rate: object
    corrupt_on: bool
    corrupt_rate: object


def effective_faults(cfg: CommunityConfig, overrides=None) -> _EffFaults:
    """Resolve the liftable fault knobs against optional fleet overrides.

    ``overrides`` is duck-typed (``dispersy_tpu.fleet.FleetOverrides`` —
    the engine must not import the fleet plane): any attribute that is
    not ``None`` replaces the static knob's VALUE; which attributes are
    set is part of the jit cache key (pytree structure), so the
    fleet-off path (``overrides=None``) compiles to the byte-identical
    pre-fleet round.  Structural knobs cannot be lifted: GE overrides
    require ``cfg.faults.ge_enabled`` (the ``ge_bad`` leaf must exist)
    and a corrupt override requires the ``msgs_corrupt_dropped`` leaf
    to be compiled in (``corrupt_rate > 0`` or a flood) — FLEET.md's
    traced-vs-static knob table.
    """
    fm = cfg.faults

    def ov(name):
        return getattr(overrides, name, None) if overrides is not None \
            else None

    pl, dup, cor = ov("packet_loss"), ov("dup_rate"), ov("corrupt_rate")
    gpb, gpg = ov("ge_p_bad"), ov("ge_p_good")
    glg, glb = ov("ge_loss_good"), ov("ge_loss_bad")
    if any(v is not None for v in (gpb, gpg, glg, glb)) \
            and not fm.ge_enabled:
        raise ValueError(
            "traced GE overrides need cfg.faults.ge_enabled — the "
            "ge_bad state leaf is zero-width otherwise (FLEET.md)")
    if cor is not None and not (fm.corrupt_rate > 0.0 or fm.flood_enabled):
        raise ValueError(
            "a traced corrupt_rate override needs the corrupt-drop "
            "counter compiled in: set cfg.faults.corrupt_rate > 0 "
            "(any representative value) so stats.msgs_corrupt_dropped "
            "is full-width (FLEET.md)")
    return _EffFaults(
        packet_loss_on=cfg.packet_loss > 0.0 or pl is not None,
        packet_loss=cfg.packet_loss if pl is None else pl,
        ge_on=fm.ge_enabled,
        ge_p_bad=fm.ge_p_bad if gpb is None else gpb,
        ge_p_good=fm.ge_p_good if gpg is None else gpg,
        ge_loss_good=fm.ge_loss_good if glg is None else glg,
        ge_loss_bad=fm.ge_loss_bad if glb is None else glb,
        dup_on=fm.dup_rate > 0.0 or dup is not None,
        dup_rate=fm.dup_rate if dup is None else dup,
        corrupt_on=fm.corrupt_rate > 0.0 or cor is not None,
        corrupt_rate=fm.corrupt_rate if cor is None else cor)


class _EffRecovery(NamedTuple):
    """Effective recovery-plane knobs for one traced round — the
    recovery analogue of :class:`_EffFaults`: the VALUE may be a traced
    per-replica f32 scalar under fleet overrides while every structural
    decision stays on the static ``cfg.recovery``."""
    backoff_decay: object        # python float | traced f32 scalar


def effective_recovery(cfg: CommunityConfig,
                       overrides=None) -> _EffRecovery:
    """Resolve the liftable recovery knobs against optional fleet
    overrides (``recovery.TRACED_RECOVERY_KNOBS``; FLEET.md).  A traced
    ``backoff_decay`` requires the recovery plane compiled in — its
    state leaves are zero-width otherwise."""
    rc = cfg.recovery
    dec = getattr(overrides, "backoff_decay", None) \
        if overrides is not None else None
    if dec is not None and not rc.enabled:
        raise ValueError(
            "a traced backoff_decay override needs cfg.recovery.enabled "
            "— the backoff leaf is zero-width otherwise (FLEET.md)")
    return _EffRecovery(
        backoff_decay=rc.backoff_decay if dec is None else dec)


class _EffOverload(NamedTuple):
    """Effective ingress-protection knobs for one traced round — the
    overload analogue of :class:`_EffFaults`: the refill-rate VALUE may
    be a traced per-replica f32 scalar under fleet overrides while
    every structural decision (enabled, priority_admission,
    bucket_depth) stays on the static ``cfg.overload``."""
    bucket_rate: object          # python float | traced f32 scalar


def effective_overload(cfg: CommunityConfig,
                       overrides=None) -> _EffOverload:
    """Resolve the liftable overload knobs against optional fleet
    overrides (``overload.TRACED_OVERLOAD_KNOBS``; FLEET.md).  A traced
    ``bucket_rate`` requires the overload plane compiled in — its
    ``bucket`` / shed-counter leaves are zero-width otherwise."""
    ov = cfg.overload
    rate = getattr(overrides, "bucket_rate", None) \
        if overrides is not None else None
    if rate is not None and not ov.enabled:
        raise ValueError(
            "a traced bucket_rate override needs cfg.overload.enabled "
            "— the bucket leaf is zero-width otherwise (FLEET.md)")
    return _EffOverload(
        bucket_rate=ov.bucket_rate if rate is None else rate)


def _lost(seed, rnd, edge_peer, salt_base, salt, kn: _EffFaults,
          ge_bad):
    """Per-packet delivery-loss draw: the base i.i.d. Bernoulli
    (``kn.packet_loss``) ORed with the Gilbert–Elliott state-dependent
    loss (``kn.ge_*``).  The GE channel belongs to ``edge_peer``
    — the same peer the base draw has always been keyed on at each call
    site: the sender's uplink on sends, the receiver's downlink on
    receipt pickups (FAULTS.md).  Both draws come from independent
    counter streams (P_LOSS vs P_GE_LOSS) so enabling GE never perturbs
    the base-loss sequence.  ``kn`` is the round's effective-knob view
    (:func:`effective_faults`): static floats normally, traced
    per-replica scalars under fleet overrides."""
    out = None
    if kn.packet_loss_on:
        u = rng.rand_uniform(seed, rnd, edge_peer, rng.P_LOSS,
                             jnp.asarray(salt) + salt_base)
        out = u < jnp.float32(kn.packet_loss)
    if kn.ge_on:
        p = jnp.where(ge_bad[edge_peer], jnp.float32(kn.ge_loss_bad),
                      jnp.float32(kn.ge_loss_good))
        ug = rng.rand_uniform(seed, rnd, edge_peer, rng.P_GE_LOSS,
                              jnp.asarray(salt) + salt_base)
        g = ug < p
        out = g if out is None else out | g
    if out is None:
        return jnp.zeros(jnp.broadcast_shapes(
            jnp.shape(edge_peer), jnp.shape(salt)), bool)
    return out


def _rebirth_wipe(mask, *, tab, stc, fwd, dly, auth, sig, mal,
                  global_time, session, wipe_store=True,
                  sta=None, dig=None):
    """The wiped-disk rebirth wipe on the masked rows — THE one
    inventory, shared by phase 0's churn block and the recovery pass's
    quarantine escalation (the oracle mirrors both call sites): the
    candidate table, store (unless the caller already wiped it inside
    its own lax.cond — the escalation path), forward buffer, delay pen,
    auth table, signature cache, and convictions are emptied; the clock
    resets to 1 and ``session`` bumps.  ``alive``/``loaded``/``health``/
    ``ge_bad`` and the recovery leaves are handled per-caller — their
    semantics differ between churn and quarantine (engine comments at
    each site).  Per-column empty sentinel: EMPTY_U32 truncated to each
    column's dtype (EMPTY_META on the narrowed u8 meta columns)."""
    m1 = mask[:, None]
    tab = cand.CandTable(
        peer=jnp.where(m1, NO_PEER, tab.peer),
        last_walk=jnp.where(m1, NEVER, tab.last_walk),
        last_stumble=jnp.where(m1, NEVER, tab.last_stumble),
        last_intro=jnp.where(m1, NEVER, tab.last_intro))
    if wipe_store:
        stc = _wipe_store_cols(m1, stc)
    if sta is not None and wipe_store:
        # The staging buffer is the store's write buffer — disk, not
        # instance memory: it wipes with the ring (and the digest, its
        # derived claim view) on a wiped-disk rebirth.
        sta = _wipe_store_cols(m1, sta)
    if dig is not None and wipe_store:
        dig = jnp.where(m1, jnp.uint32(0), dig)
    fwd = tuple(jnp.where(m1, jnp.asarray(st.empty_of(c.dtype), c.dtype),
                          c) for c in fwd)
    # The delayed-message pen dies with the process (reference: delayed
    # batches live in the in-memory RequestCache, not the database).
    dly = (jnp.where(m1, jnp.uint32(EMPTY_U32), dly[0]),
           jnp.where(m1, jnp.uint32(EMPTY_U32), dly[1]),
           jnp.where(m1, jnp.uint8(EMPTY_META), dly[2]),
           jnp.where(m1, jnp.uint32(EMPTY_U32), dly[3]),
           jnp.where(m1, jnp.uint32(0), dly[4]),
           jnp.where(m1, jnp.uint32(0), dly[5]),
           jnp.where(m1, NO_PEER, dly[6]))
    # The auth table is folded from the (wiped) store, so it wipes too:
    # a reborn peer re-learns permissions as authorize records re-sync
    # (reference: Timeline is rebuilt from the database on load).
    auth = tl.AuthTable(
        member=jnp.where(m1, jnp.uint32(EMPTY_U32), auth.member),
        mask=jnp.where(m1, jnp.uint32(0), auth.mask),
        gt=jnp.where(m1, jnp.uint32(0), auth.gt),
        rev=jnp.where(m1, False, auth.rev),
        issuer=jnp.where(m1, jnp.uint32(EMPTY_U32), auth.issuer))
    # The signature request cache and convictions die with the process
    # (reference: RequestCache is in-memory only).  The cache leaves
    # are plane-sized (zero-width when double_meta_mask is 0 — the
    # (n,)-mask would not broadcast against them).
    if sig[0].shape[0]:
        sig = (jnp.where(mask, NO_PEER, sig[0]),
               jnp.where(mask, jnp.uint32(0), sig[1]),
               jnp.where(mask, jnp.uint32(0), sig[2]),
               jnp.where(mask, jnp.uint32(0), sig[3]),
               jnp.where(mask, jnp.uint32(0), sig[4]))
    mal = jnp.where(m1, jnp.uint32(EMPTY_U32), mal)
    global_time = jnp.where(mask, jnp.uint32(1), global_time)
    session = session + mask.astype(jnp.uint32)
    return tab, stc, fwd, dly, auth, sig, mal, global_time, session, sta, dig


def _wipe_store_cols(m1, stc: st.StoreCols) -> st.StoreCols:
    """Empty the store/staging columns on the masked rows (dtype-exact:
    the aux column may be the narrowed config.aux_dtype)."""
    return st.StoreCols(
        gt=jnp.where(m1, jnp.uint32(EMPTY_U32), stc.gt),
        member=jnp.where(m1, jnp.uint32(EMPTY_U32), stc.member),
        meta=jnp.where(m1, jnp.uint8(EMPTY_META), stc.meta),
        payload=jnp.where(m1, jnp.uint32(EMPTY_U32), stc.payload),
        aux=jnp.where(m1, jnp.asarray(0, stc.aux.dtype), stc.aux),
        flags=jnp.where(m1, jnp.uint8(0), stc.flags))


def _cand_deq(col: jnp.ndarray, cfg: CommunityConfig) -> jnp.ndarray:
    """Candidate-timestamp leaf -> the walker's f32 sim-seconds.

    Under ``store.cand_bits == 16`` (storediet.py) the leaf is a u16
    round-stamp: 0 is the ``never`` sentinel, stamp s is sim-second
    ``(s - 1) * walk_interval``.  Exact for every value the walker ever
    writes (all are some round's ``r * walk_interval``) inside the u16
    range; identity at the default width."""
    if col.dtype != jnp.uint16:
        return col
    sec = (col.astype(jnp.float32) - jnp.float32(1.0)) \
        * jnp.float32(cfg.walk_interval)
    return jnp.where(col == jnp.uint16(0), jnp.float32(NEVER), sec)


def _cand_quant(col: jnp.ndarray, cfg: CommunityConfig) -> jnp.ndarray:
    """f32 sim-seconds -> the candidate-timestamp leaf (inverse of
    :func:`_cand_deq` on the walker's value set).

    NEVER maps to stamp 0; everything else to
    ``round(sec / walk_interval) + 1`` SATURATED into [1, 65535] — a
    pre-epoch value (seed_overlay's negative eligibility offset) or a
    >65534-round run degrades to a stale-but-ordered stamp, never the
    sentinel.  Identity at the default width."""
    if cfg.store.cand_bits != 16:
        return col
    q = jnp.round(col / jnp.float32(cfg.walk_interval)).astype(jnp.int32) \
        + jnp.int32(1)
    q = jnp.clip(q, 1, 65535)
    return jnp.where(col == jnp.float32(NEVER), jnp.uint16(0),
                     q.astype(jnp.uint16))


def _tab(state: PeerState, cfg: CommunityConfig) -> cand.CandTable:
    return cand.CandTable(peer=state.cand_peer,
                          last_walk=_cand_deq(state.cand_last_walk, cfg),
                          last_stumble=_cand_deq(state.cand_last_stumble,
                                                 cfg),
                          last_intro=_cand_deq(state.cand_last_intro, cfg))


def _store(state: PeerState) -> st.StoreCols:
    return st.StoreCols(gt=state.store_gt, member=state.store_member,
                        meta=state.store_meta, payload=state.store_payload,
                        aux=state.store_aux, flags=state.store_flags)


def _staging(state: PeerState) -> st.StoreCols:
    return st.StoreCols(gt=state.sta_gt, member=state.sta_member,
                        meta=state.sta_meta, payload=state.sta_payload,
                        aux=state.sta_aux, flags=state.sta_flags)


def _auth(state: PeerState) -> tl.AuthTable:
    return tl.AuthTable(member=state.auth_member, mask=state.auth_mask,
                        gt=state.auth_gt, rev=state.auth_rev,
                        issuer=state.auth_issuer)


def _layout_cols(cfg: CommunityConfig, idx: jnp.ndarray):
    """Per-row (boot_base, boot_count, mem_base, mem_count) device arrays.

    Single community: global ranges broadcast.  Multi-community: each row's
    own block ranges, derived from the static ``cfg.communities`` tuple via
    searchsorted over the C block boundaries (C is tiny; the row axis stays
    sharded).  Must stay consistent with ``CommunityConfig.layout()``,
    which the oracle uses.
    """
    n = cfg.n_peers
    if not cfg.communities:
        t = cfg.n_trackers
        return (jnp.zeros((n,), jnp.int32), jnp.full((n,), t, jnp.int32),
                jnp.full((n,), t, jnp.int32),
                jnp.full((n,), n - t, jnp.int32))
    import numpy as np
    t_cum = np.cumsum([0] + [t for _, t in cfg.communities])
    m_cum = np.cumsum([cfg.n_trackers] + [m for m, _ in cfg.communities])
    comm = jnp.where(
        idx < cfg.n_trackers,
        jnp.searchsorted(jnp.asarray(t_cum[1:], jnp.int32), idx,
                         side="right"),
        jnp.searchsorted(jnp.asarray(m_cum[1:], jnp.int32), idx,
                         side="right"))
    take = lambda a: jnp.take(jnp.asarray(a, jnp.int32), comm, axis=0)
    return (take(t_cum[:-1]), take([t for _, t in cfg.communities]),
            take(m_cum[:-1]), take([m for m, _ in cfg.communities]))


def _founder_col(cfg: CommunityConfig, mem_base: jnp.ndarray) -> jnp.ndarray:
    """u32[N]: the founder each row's community answers to.

    Multi-community: the block's first member row (reference: each
    Community has its own master member).  Single: cfg.founder.
    """
    if cfg.communities:
        return mem_base.astype(jnp.uint32)
    return jnp.full((cfg.n_peers,), cfg.founder, jnp.uint32)


def _response_order(stc: st.StoreCols, cfg: CommunityConfig) -> st.StoreCols:
    """The sync responder's serving order over a store.

    Reference: the on_introduction_request responder streams missing
    packets ORDER BY (priority DESC, global_time ASC|DESC per the meta's
    distribution).  The store itself stays gt-sorted; this builds the
    responder's *view*: priority first (control metas fixed at
    CONTROL_PRIORITY so authorize proofs outrun the records they permit),
    then global_time in the meta's declared direction.  Identity when the
    community declares no ordering (every priority equal, all ASC).
    """
    if not cfg.needs_response_order:
        return stc
    nm = cfg.n_meta
    valid = stc.gt != jnp.uint32(EMPTY_U32)
    prio = _priority_vec(cfg, stc.meta)
    key1 = jnp.where(valid, jnp.uint32(255) - prio, jnp.uint32(EMPTY_U32))
    shm = jnp.minimum(stc.meta, jnp.uint32(31))
    desc = ((jnp.uint32(cfg.desc_meta_mask) >> shm) & 1).astype(bool) \
        & (stc.meta < nm)
    key2 = jnp.where(desc, ~stc.gt, stc.gt)
    k1, k2, gt, member, meta, payload, aux, flags = lax.sort(
        (key1, key2, stc.gt, stc.member, stc.meta, stc.payload, stc.aux,
         stc.flags), dimension=-1, num_keys=4)
    return st.StoreCols(gt=gt, member=member, meta=meta, payload=payload,
                        aux=aux, flags=flags)


def killed_mask(store_meta: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: which peers are hard-killed (their store holds the
    founder's dispersy-destroy-community record).  The ONE definition of
    killed-ness — step(), the create paths, and metrics all derive it
    from here (reference: HardKilledCommunity classification is derived
    from the database on load)."""
    return jnp.any(store_meta == jnp.uint32(META_DESTROY), axis=1)


def _priority_vec(cfg: CommunityConfig, meta: jnp.ndarray) -> jnp.ndarray:
    """u32 serving/forwarding priority per record (config.priority_of,
    vectorized): declared per-meta priorities for the user band,
    IDENTITY_PRIORITY for dispersy-identity, CONTROL_PRIORITY otherwise."""
    prio_arr = jnp.asarray(cfg.priorities, jnp.uint32)
    meta_c = jnp.minimum(meta, jnp.uint32(cfg.n_meta - 1)).astype(jnp.int32)
    return jnp.where(meta < cfg.n_meta, jnp.take(prio_arr, meta_c, axis=0),
                     jnp.where(meta == jnp.uint32(META_IDENTITY),
                               jnp.uint32(IDENTITY_PRIORITY),
                               jnp.uint32(CONTROL_PRIORITY)))


def _deliver(cfg: CommunityConfig, *, dst, cols, valid, n_peers, inbox_size,
             cls=None, need_receipts=True, capped=False):
    """Route one full-population delivery through the kernel the config
    asks for: the global ``lax.sort`` scatter when the parallel plane is
    off (``parallel.shards <= 1``), the shard-local ragged exchange
    (:func:`dispersy_tpu.ops.inbox.deliver_ragged`) when it is on.

    ``capped=True`` marks the one channel that rides the capped exchange
    (the push blast — the only channel whose edge count is
    sender-chosen, so the only one a flooder can use to blow up the
    cross-shard buffers); every other channel's worst case is bounded by
    config shapes and uses the exact (budget=0, never-sheds) exchange.
    Returns ``(Delivery, shed)`` where ``shed`` is the bool[E]
    sender-side overflow stream (None unless the cap is armed).
    """
    pp = cfg.parallel
    if pp.shards <= 1:
        return inbox.deliver(dst=dst, cols=cols, valid=valid,
                             n_peers=n_peers, inbox_size=inbox_size,
                             cls=cls), None
    budget = pp.cross_shard_budget if capped else 0
    rd = inbox.deliver_ragged(dst=dst, cols=cols, valid=valid,
                              n_peers=n_peers, inbox_size=inbox_size,
                              shards=pp.shards, budget=budget, cls=cls,
                              need_receipts=need_receipts)
    return rd.delivery, (rd.shed if budget > 0 else None)


# DynamicResolution flip replay: one definition (ops/intake.flip_best)
# serves the author gate, the countersigner check, and the intake check;
# the oracle mirrors it in ``_linear_at``.
_flip_best = ik.flip_best


def _author_linear(state: PeerState, cfg: CommunityConfig, meta: int,
                   gt_at: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: is user meta ``meta`` LinearResolution at ``gt_at`` per each
    row's own stored dynamic-settings flips (DynamicResolution replay; the
    static protected bit when no flip applies or the meta isn't dynamic)."""
    static = bool((cfg.protected_meta_mask >> meta) & 1)
    if not (meta < cfg.n_meta and (cfg.dynamic_meta_mask >> meta) & 1):
        return jnp.full((cfg.n_peers,), static, bool)
    best = _flip_best(_store(state),
                      jnp.full((cfg.n_peers, 1), meta, jnp.uint32),
                      gt_at[:, None])[:, 0]
    return jnp.where(best > 0, (best & 1) == 1, static)


def _rebuild_valid_table(stc: st.StoreCols, cfg: CommunityConfig,
                         founder_col: jnp.ndarray, a_slots: int):
    """(table, rows_unwound): the auth table as a PURE FUNCTION of the
    store — fold every stored authorize/revoke record in canonical store
    order into an empty top-A window, re-walk chain validity
    (tl.revalidate), and compact the survivors.  Convergent stores give
    convergent tables; incremental fold histories do not (an evicted or
    dropped row can never re-fold — its record is in the store, so never
    ``fresh`` again — which left peers with equal stores but permanently
    different windows; adversarial sweep seed 3051).  Rebuild
    bookkeeping (drops/evictions) is not a new loss: uncounted."""
    n = stc.gt.shape[0]
    is_rev_row = stc.meta == jnp.uint32(META_REVOKE)
    is_crow = (stc.meta == jnp.uint32(META_AUTHORIZE)) | is_rev_row
    user_bits = jnp.uint32(user_perm_mask(cfg.n_meta))
    empty_tab = tl.AuthTable(
        member=jnp.full((n, a_slots), EMPTY_U32, jnp.uint32),
        mask=jnp.zeros((n, a_slots), jnp.uint32),
        gt=jnp.zeros((n, a_slots), jnp.uint32),
        rev=jnp.zeros((n, a_slots), bool),
        issuer=jnp.full((n, a_slots), EMPTY_U32, jnp.uint32))
    auth = tl.fold(empty_tab, target=stc.payload,
                   mask=stc.aux & user_bits, gt=stc.gt,
                   is_revoke=is_rev_row,
                   valid=is_crow, issuer=stc.member).table
    keep = tl.revalidate(auth, founder_col, cfg.n_meta)
    live = auth.member != jnp.uint32(EMPTY_U32)
    n_unwound = jnp.sum((live & ~keep).astype(jnp.int32), axis=-1)
    # Compact survivors left (order preserved) so later folds fill from
    # the end again — the same dense-slots invariant fold maintains.
    rank = jnp.cumsum(keep.astype(jnp.int32), axis=-1) - 1
    slot = jnp.where(keep, rank, a_slots)
    auth = tl.AuthTable(
        member=st.rank_compact(auth.member, slot, a_slots, EMPTY_U32),
        mask=st.rank_compact(auth.mask, slot, a_slots, 0),
        gt=st.rank_compact(auth.gt, slot, a_slots, 0),
        rev=st.rank_compact(auth.rev, slot, a_slots, False),
        issuer=st.rank_compact(auth.issuer, slot, a_slots, EMPTY_U32))
    return auth, n_unwound


def _retro_pass(auth: tl.AuthTable, stc: st.StoreCols, cfg: CommunityConfig,
                founder_col: jnp.ndarray):
    """Retroactive permission re-walk after a revoke folds.

    The order-independence half of the Timeline (reference: timeline.py
    ``Timeline.check`` re-validates proof chains lazily, so verdicts never
    depend on arrival order): a revoke that syncs AFTER a grant it
    pre-dates must unwind that grant — and everything downstream of it —
    exactly as if the revoke had arrived first.

    1. ``tl.revalidate`` re-judges every auth-table row by its issuer's
       authority over surviving rows (transitive, fixed-point); failed
       rows are wiped.
    2. Stored control records are re-checked against the cleaned table
       (authorize/revoke via the chain rule, dynamic-settings flips via
       the AUTHORIZE bit) and removed when their authority is gone — so a
       peer that folded grant-then-revoke ends with the same store as one
       that received revoke-then-grant (which never stored the grant).
    3. Stored protected user records are re-checked under the cleaned
       table and the surviving flip set; no-longer-permitted records are
       removed.  Peers still offering removed records get re-refused at
       this peer's intake (the revoke is folded now), so the network
       converges to the full-knowledge fixed point.

    Clocks never rewind (the reference's global_time is likewise
    monotone), and undo marks on surviving records stay — only record
    EXISTENCE is re-decided here.  Returns (auth', store', rows_unwound
    i32[N], records_removed i32[N]).

    Step 0 REBUILDS the table from the store's control records in store
    order before anything else.  Incremental folding alone is not
    order-independent at the bounded window: a row evicted (or dropped)
    while the table was full can never re-fold — its record is already
    in the store, so it is never ``fresh`` again — leaving two peers
    with convergent STORES but permanently different TABLES when their
    eviction histories differed (found by the adversarial sweep, seed
    3051).  Rebuilding from the store's canonical (gt, member, ...)
    order makes the table a pure function of the store, which does
    converge; the trigger set (any revoke fold, any eviction) guarantees
    a rebuild fires whenever windows could have disagreed.
    """
    a_slots = auth.member.shape[-1]
    auth, n_unwound = _rebuild_valid_table(stc, cfg, founder_col, a_slots)

    fcol = founder_col[:, None]
    user_bits = jnp.uint32(user_perm_mask(cfg.n_meta))
    is_sauth = stc.meta == jnp.uint32(META_AUTHORIZE)
    is_srev = stc.meta == jnp.uint32(META_REVOKE)
    ok_auth = ((stc.member == fcol)
               | tl.check_grant(auth, stc.member, stc.aux & user_bits,
                                stc.gt, cfg.n_meta, perm=PERM_AUTHORIZE))
    ok_rev = ((stc.member == fcol)
              | tl.check_grant(auth, stc.member, stc.aux & user_bits,
                               stc.gt, cfg.n_meta, perm=PERM_REVOKE))
    kill = (is_sauth & ~ok_auth) | (is_srev & ~ok_rev)
    if cfg.dynamic_meta_mask:
        is_sflip = stc.meta == jnp.uint32(META_DYNAMIC)
        ok_flip = tl.check(auth, stc.member, stc.payload, stc.gt, fcol,
                           perm=PERM_AUTHORIZE)
        kill = kill | (is_sflip & ~ok_flip)
    r1 = st.store_remove(stc, kill)
    stc = r1.store

    # User records re-checked under the cleaned table + surviving flips
    # (mirrors the intake's protected/permitted computation exactly).
    prot = jnp.uint32(cfg.protected_meta_mask)
    shift = jnp.minimum(stc.meta, jnp.uint32(31))
    protected = (((prot >> shift) & 1) == 1) & (stc.meta < 32)
    if cfg.dynamic_meta_mask:
        dynm = jnp.uint32(cfg.dynamic_meta_mask)
        is_dyn = ((((dynm >> shift) & 1) == 1) & (stc.meta < cfg.n_meta))
        best = _flip_best(stc, stc.meta, stc.gt)
        linear_now = jnp.where(best > 0, (best & 1) == 1, protected)
        protected = jnp.where(is_dyn, linear_now, protected)
    permitted = tl.check(auth, stc.member, stc.meta, stc.gt, fcol)
    if cfg.double_meta_mask & (cfg.protected_meta_mask
                               | cfg.dynamic_meta_mask):
        is_dbl = ((((jnp.uint32(cfg.double_meta_mask) >> shift) & 1) == 1)
                  & (stc.meta < cfg.n_meta))
        permitted = permitted & jnp.where(
            is_dbl, tl.check(auth, stc.aux, stc.meta, stc.gt, fcol), True)
    r2 = st.store_remove(stc, protected & ~permitted)
    stc = r2.store

    # Stored undo-other records re-checked LAST: the undoer's UNDO grant
    # may have been unwound above, and the TARGET may have been
    # retro-removed (a stage-2 casualty) — resolving the target's meta
    # against the post-stage-2 store makes both failure modes converge
    # to the revoke-first peer's view, which never accepted the undo.
    is_sundo = stc.meta == jnp.uint32(META_UNDO_OTHER)
    undo_tmeta = ik.stored_meta_of(stc, stc.payload, stc.aux)
    ok_undo = tl.check(auth, stc.member, undo_tmeta, stc.gt, fcol,
                       perm=PERM_UNDO)
    r3 = st.store_remove(stc, is_sundo & ~ok_undo)
    stc = r3.store
    # Undone marks are DERIVED from stored undo records; removed undos
    # must take their marks with them (revoke-first peers never marked).
    um = ik.undo_marked(stc, stc.member, stc.gt)
    stc = stc._replace(flags=jnp.where(
        (stc.meta < 32) & um,
        stc.flags | jnp.uint8(FLAG_UNDONE),
        stc.flags & ~jnp.uint8(FLAG_UNDONE)))
    # Final rebuild from the POST-prune store: the stage 1-3 removals
    # freed window slots that stored-but-previously-dropped rows must be
    # able to claim, or the table is top-A of a store that no longer
    # exists (the residual order dependence a review pass flagged).
    auth, _ = _rebuild_valid_table(stc, cfg, founder_col, a_slots)
    return (auth, stc, n_unwound,
            r1.n_removed + r2.n_removed + r3.n_removed)


def _fold_gt(own_gt: jnp.ndarray, seen_gt: jnp.ndarray, seen_valid: jnp.ndarray,
             rng_range: int) -> jnp.ndarray:
    """Lamport fold: max over acceptable observed global times.

    Reference: community.py ``update_global_time`` raises the local clock to
    any higher observed global_time, while ``dispersy_acceptable_global_time``
    rejects values more than ``acceptable_global_time_range`` above the local
    clock (clock-jump defense) — those observations are ignored entirely.
    """
    acceptable = seen_valid & (seen_gt <= own_gt[:, None] + jnp.uint32(rng_range))
    best = jnp.max(jnp.where(acceptable, seen_gt, 0), axis=1)
    return jnp.maximum(own_gt, best)


def counter_matrix(stats, n: int) -> jnp.ndarray:
    """``u32[N, len(U64_COUNTERS)]``: every snapshot counter as a
    column, in ``telemetry.U64_COUNTERS`` order.  THE one definition of
    the zero-width padding rule — a compiled-out leaf (e.g.
    ``msgs_corrupt_dropped`` without its fault knobs) reads as a zero
    column, so totals and row layout never depend on fault knobs.
    Shared by the fused row builder and ``metrics.snapshot``'s legacy
    stacked-transfer path, which must reduce identical data."""
    return jnp.stack(
        [c if c.shape[0] == n else jnp.zeros((n,), jnp.uint32)
         for c in (getattr(stats, nm) for nm in tlm.U64_COUNTERS)],
        axis=1)


def _telemetry_row(cfg: CommunityConfig, *, rnd, new_time, members, stats,
                   stc, health, store_cnt, cand_cnt, hists,
                   bucket=None, trace_cov=None,
                   trace_latch=None) -> jnp.ndarray:
    """Pack the fused per-round telemetry row (u32[row_width]).

    Every ``metrics.snapshot`` aggregate, reduced on device and laid out
    by ``telemetry.row_schema`` — counter totals as exact u64 (lo, hi)
    pairs (ops/telemetry.col_sum_u64), occupancy as integer numerators,
    health bits as per-bit counts, histograms as bucket-count blocks.
    The oracle packs the identical row host-side through
    ``telemetry.pack_row_host``; the parity tests pin the two
    bit-for-bit.
    """
    n = cfg.n_peers

    def w(x):
        return jnp.reshape(x.astype(jnp.uint32), (1,))

    vals = {"round": w(rnd + jnp.uint32(1)),
            "sim_time": jnp.reshape(
                lax.bitcast_convert_type(new_time, jnp.uint32), (1,)),
            "alive_members": w(jnp.sum(members, dtype=jnp.int32)),
            "killed": w(jnp.sum(killed_mask(stc.meta), dtype=jnp.int32))}
    # One [N, 17] stack -> one 4-lane reduction for every counter total.
    csum = tele.col_sum_u64(counter_matrix(stats, n))        # [2, 17]
    for i, nm in enumerate(tlm.U64_COUNTERS):
        vals[nm] = csum[:, i]
    vals["store_live"] = tele.sum_u64(store_cnt)
    vals["cand_live"] = tele.sum_u64(
        jnp.where(members, cand_cnt, jnp.uint32(0)))
    # Health words: per-bit flagged-peer counts + the derived OR /
    # nonzero count (zero-width health leaf -> clean zeros, matching
    # faults.health_report).
    hv = jnp.zeros((), jnp.uint32)
    for b, nm in enumerate(tlm.HEALTH_NAMES):
        cnt = jnp.sum(((health >> jnp.uint32(b)) & jnp.uint32(1)),
                      dtype=jnp.uint32)
        vals[f"health_{nm}"] = w(cnt)
        hv = hv | jnp.where(cnt > 0, jnp.uint32(1 << b), jnp.uint32(0))
    vals["health_or"] = w(hv)
    vals["health_flagged"] = w(jnp.sum(health != 0, dtype=jnp.int32))
    asum = tele.col_sum_u64(stats.accepted_by_meta)          # [2, K+1]
    for i in range(cfg.n_meta + 1):
        vals[f"accepted_by_meta_{i}"] = asum[:, i]
    if cfg.trace.enabled:
        # Dissemination-tracing words (traceplane.py; conditional
        # schema words so a trace-off row stays byte-identical):
        # per-slot coverage counts + percentile latches, per-channel
        # useful/duplicate totals, and the redundancy ratio.  The f32
        # ratio is computed op-for-op as traceplane.redundancy_f32 so
        # the oracle's host mirror is bit-exact.
        for k in range(cfg.trace.tracked_slots):
            vals[f"trace_cov_{k}"] = w(trace_cov[k])
            for i, pct in enumerate(trp.LATCH_PCTS):
                vals[f"trace_r{pct}_{k}"] = w(trace_latch[k, i])
        usum = tele.col_sum_u64(stats.trace_delivered)       # [2, 4]
        dsum = tele.col_sum_u64(stats.trace_dup)
        two32 = jnp.float32(4294967296.0)
        useful_f = jnp.float32(0.0)
        dup_f = jnp.float32(0.0)
        for c, nm in enumerate(trp.CHANNEL_NAMES):
            vals[f"trace_delivered_{nm}"] = usum[:, c]
            vals[f"trace_dup_{nm}"] = dsum[:, c]
            useful_f = useful_f + (usum[0, c].astype(jnp.float32)
                                   + usum[1, c].astype(jnp.float32)
                                   * two32)
            dup_f = dup_f + (dsum[0, c].astype(jnp.float32)
                             + dsum[1, c].astype(jnp.float32) * two32)
        ratio = jnp.where(useful_f > jnp.float32(0.0),
                          (useful_f + dup_f) / useful_f,
                          jnp.float32(0.0))
        vals["trace_redundancy"] = jnp.reshape(
            lax.bitcast_convert_type(ratio, jnp.uint32), (1,))
    if cfg.overload.enabled:
        # Ingress-protection words (overload.py; conditional schema
        # words so an overload-off row stays byte-identical): the two
        # shed streams plus the count of post-round-empty buckets —
        # under a flood, the attackers pinned at zero credit.
        osum = tele.col_sum_u64(jnp.stack(
            [stats.msgs_shed_rate, stats.msgs_shed_priority],
            axis=1))                                         # [2, 2]
        vals["msgs_shed_rate"] = osum[:, 0]
        vals["msgs_shed_priority"] = osum[:, 1]
        vals["bucket_exhausted"] = w(
            jnp.sum(bucket == jnp.uint8(0), dtype=jnp.int32))
    if cfg.recovery.enabled:
        # Recovery-plane action totals (recovery.py; conditional schema
        # words so a recovery-off row stays byte-identical): the three
        # per-action counters plus per-health-bit clears — the MTTR
        # denominators (recovery.mttr_report).
        rsum = tele.col_sum_u64(jnp.stack(
            [stats.recov_soft, stats.recov_backoff,
             stats.recov_quarantine], axis=1))               # [2, 3]
        vals["recov_soft"] = rsum[:, 0]
        vals["recov_backoff"] = rsum[:, 1]
        vals["recov_quarantine"] = rsum[:, 2]
        csum2 = tele.col_sum_u64(stats.recov_cleared)        # [2, HB]
        for b, nm in enumerate(tlm.HEALTH_NAMES):
            vals[f"recov_cleared_{nm}"] = csum2[:, b]
    if cfg.telemetry.histograms:
        hb_n = cfg.telemetry.hist_buckets
        for name, kind, cap in tlm.hist_specs(cfg):
            val, mask = hists[name]
            vals[f"hist_{name}"] = (
                tele.hist_linear(val, mask, cap, hb_n) if kind == "linear"
                else tele.hist_log2(val, mask, hb_n))
    return jnp.concatenate([vals[nm] for nm, _ in tlm.row_schema(cfg)])


@functools.partial(jax.jit, static_argnums=(1, 3), donate_argnums=0)
def step(state: PeerState, cfg: CommunityConfig,
         overrides=None, phase: str | None = None) -> PeerState:
    """Advance every peer one walker interval (~5 simulated seconds).

    ``overrides`` (default None — compiled out, the step is byte-
    identical to the pre-fleet round) is a ``fleet.FleetOverrides``-
    shaped pytree of traced per-replica fault-knob scalars; the fleet
    plane vmaps this function over a leading replica axis so a whole
    fault grid advances under ONE compiled program (FLEET.md).

    ``phase`` (static) only matters under the byte-diet store plane
    (``cfg.store.staging > 0`` — dispersy_tpu/storediet.py): ``"sync"``
    compiles the compaction/sync-exchange round, ``"quiet"`` the
    staging-only round, and ``None`` (the default every caller can use
    safely) compiles BOTH behind one ``lax.cond`` on the round
    counter's cadence — bit-identical to the statically-specialized
    forms, which exist so the cost ledger can price each round kind
    separately and cadence-aware drivers can skip the cond.  Without
    the diet the argument is ignored.
    """
    if not cfg.store_diet or phase in ("quiet", "sync"):
        return _step_impl(state, cfg, overrides, phase or "sync")
    if phase is not None:
        raise ValueError(f"unknown step phase {phase!r}: expected "
                         "'sync', 'quiet' or None")
    is_sync = sdiet.sync_round_of(cfg, state.round_index)
    return lax.cond(
        is_sync,
        lambda s: _step_impl(s, cfg, overrides, "sync"),
        lambda s: _step_impl(s, cfg, overrides, "quiet"),
        state)


def _step_impl(state: PeerState, cfg: CommunityConfig,
               overrides=None, phase: str = "sync") -> PeerState:
    n, t = cfg.n_peers, cfg.n_trackers
    idx = jnp.arange(n, dtype=jnp.int32)
    seed = rng.fold_seed(state.key)
    rnd = state.round_index
    now = state.time
    stats = state.stats
    # Byte-diet store plane (dispersy_tpu/storediet.py; STORE section in
    # README): with ``diet``, accepted records land in the staging
    # buffer, the ring merges only on compaction ("sync") rounds, the
    # Bloom claim reads the persistent digest, and the sync exchange
    # runs on sync rounds only.  ``phase`` is static, so a quiet round
    # compiles none of the responder/merge kernels.
    diet = cfg.store_diet
    sync_on = cfg.sync_enabled and (not diet or phase == "sync")
    compact_now = diet and phase == "sync"
    # Cohort staggering (PR 20, storediet.py): with ``cohorts > 1`` a
    # sync round runs the claim/serve/compact path for ONE cohort's
    # N/cohorts block instead of the whole fleet — ``a_coh`` is the
    # round's active cohort, ``ep_a`` its (post-round-exclusive) salt
    # epoch, and the per-PEER epoch leaf replaces the fleet-wide scalar
    # everywhere a salt is derived.  ``stagger`` is static; the
    # ``cohorts=1`` default compiles the identical PR-12 path.
    stagger = sdiet.stagger_of(cfg)
    if stagger:
        # Per-peer salts: peer p's digest lives at its OWN cohort's
        # epoch ([N,1] broadcasts against the [N,B]/[N,1] item hashes).
        ep = state.epoch[:, None]
        a_coh = sdiet.active_cohort(cfg, rnd)
        ep_a = sdiet.epoch_of_cohort(cfg, rnd, a_coh)
    elif diet:
        # Epoch salt: every round of one compaction window shares it,
        # and it rotates at the window boundary — requester digests and
        # responder queries derive it from the same round counter.
        ep = sdiet.epoch_of(cfg, rnd)
    # Chaos harness (dispersy_tpu/faults.py): every fault branch below is
    # gated on a STATIC FaultModel knob, so all-zero knobs compile to the
    # identical fault-free round (FAULTS.md; BENCH.md fault-knob note).
    # ``kn`` resolves the liftable knob VALUES against fleet overrides;
    # its gates are plain bools, so fleet-off tracing is unchanged.
    fm = cfg.faults
    kn = effective_faults(cfg, overrides)
    # Recovery plane (dispersy_tpu/recovery.py): like the fault
    # branches, every recovery branch below is gated on a STATIC
    # RecoveryConfig knob — the default (disabled) plane compiles to
    # the identical recovery-free round (RECOVERY.md).  ``knr``
    # resolves the liftable numeric knob against fleet overrides.
    rc = cfg.recovery
    knr = effective_recovery(cfg, overrides)
    # Ingress-protection plane (dispersy_tpu/overload.py): every branch
    # below is gated on the STATIC OverloadConfig, so the default
    # (disabled) plane compiles to the identical protection-free round
    # (OVERLOAD.md).  ``kno`` resolves the liftable refill rate against
    # fleet overrides; ``bucket_new`` carries the post-round balance
    # (pass-through on rounds without a push phase).
    ov = cfg.overload
    kno = effective_overload(cfg, overrides)
    bucket_new = state.bucket
    if kn.ge_on:
        # Advance each peer's Gilbert–Elliott channel once per round;
        # this round's loss draws condition on the post-transition state.
        ge_bad = flt.ge_advance(state.ge_bad, seed, rnd, idx,
                                kn.ge_p_bad, kn.ge_p_good)
    else:
        ge_bad = state.ge_bad
    if fm.health_checks or cfg.telemetry.histograms:
        # Round-start drop counter: the inbox-overload sentinel compares
        # this round's delta against health_drop_limit at wrap-up, and
        # the telemetry round_drops histogram buckets the same delta.
        # Both bounded-queue families count — request-inbox overflow AND
        # push/store drops (msgs_dropped — where a byzantine flood
        # lands, since junk saturates the push inbox, not the request
        # ring).  u32 sums/deltas are wrap-safe.
        rd0 = state.stats.requests_dropped + state.stats.msgs_dropped
    # Byte-equivalent traffic accounting (endpoint.py total_up/total_down):
    # accumulated per site below, folded into stats at wrap-up.  Sends
    # count pre-loss (sendto), receipts per accepted inbox slot (recvfrom).
    bup = jnp.zeros((n,), jnp.uint32)
    bdown = jnp.zeros((n,), jnp.uint32)
    # On byte-diet quiet rounds the request carries no sync tuple — the
    # responder would not serve it — so it is the sync-disabled request
    # on the wire and in the byte accounting.  Under cohort staggering
    # only the ACTIVE cohort's walkers carry the tuple on a sync round
    # (a per-peer vector; the elementwise bup line below is unchanged,
    # the responder's bdown gathers per request source).
    if stagger and sync_on:
        req_bytes = jnp.where(
            state.cohort.astype(jnp.uint32) == a_coh,
            jnp.uint32(INTRO_REQUEST_BASE_BYTES + 4 * cfg.bloom_words),
            jnp.uint32(INTRO_REQUEST_BASE_BYTES - 20))
    else:
        req_bytes = jnp.uint32(
            INTRO_REQUEST_BASE_BYTES + 4 * cfg.bloom_words
            if sync_on else INTRO_REQUEST_BASE_BYTES - 20)

    # Dissemination-tracing plane (dispersy_tpu/traceplane.py): every
    # branch below is gated on the STATIC TraceConfig, so the default
    # (disabled) plane compiles to the identical trace-free round.
    # Lineage is disk-like state — the per-peer rows wipe with the
    # store at BOTH rebirth sites (churn, quarantine escalation).
    trace_on = cfg.trace.enabled
    tr_first = state.trace_first
    tr_chan = state.trace_chan
    tr_dups = state.trace_dups
    tr_latch = state.trace_latch

    # ---- phase 0: churn -------------------------------------------------
    # A churned peer restarts with a wiped disk: empty store, empty
    # candidate table, reset clock.  Trackers never churn (the reference's
    # bootstrap infrastructure is long-lived).  The wipe itself is
    # _rebirth_wipe — one inventory shared with the recovery plane's
    # quarantine escalation (wrap-up).
    if cfg.churn_rate > 0.0:
        reborn = state.alive & ~state.is_tracker & (
            rng.rand_uniform(seed, rnd, idx, rng.P_CHURN) < cfg.churn_rate)
        # named_scope: metadata-only phase labels for profiler traces /
        # the cost ledger (costmodel.py) — zero effect on the compiled
        # program (the 1M byte-identity pin proves it).
        with jax.named_scope("churn"):
            (tab, stc, fwd, dly, auth, sig, mal, global_time,
             session, sta, dig) = _rebirth_wipe(
                reborn, tab=_tab(state, cfg), stc=_store(state),
                fwd=(state.fwd_gt, state.fwd_member, state.fwd_meta,
                     state.fwd_payload, state.fwd_aux),
                dly=(state.dly_gt, state.dly_member, state.dly_meta,
                     state.dly_payload, state.dly_aux, state.dly_since,
                     state.dly_src),
                auth=_auth(state),
                sig=(state.sig_target, state.sig_meta, state.sig_payload,
                     state.sig_gt, state.sig_since),
                mal=state.mal_member, global_time=state.global_time,
                session=state.session,
                sta=_staging(state) if diet else None,
                dig=(state.digest if diet and cfg.sync_enabled
                     else None))
        if trace_on:
            # Lineage wipes with the store: a reborn peer's disk — and
            # therefore its arrival history — is gone (traceplane.py).
            rb1 = reborn[:, None]
            tr_first = jnp.where(rb1, jnp.uint32(0), tr_first)
            tr_chan = jnp.where(rb1, jnp.uint8(0), tr_chan)
            tr_dups = jnp.where(rb1, jnp.uint32(0), tr_dups)
    else:
        tab, stc = _tab(state, cfg), _store(state)
        fwd = (state.fwd_gt, state.fwd_member, state.fwd_meta,
               state.fwd_payload, state.fwd_aux)
        dly = (state.dly_gt, state.dly_member, state.dly_meta,
               state.dly_payload, state.dly_aux, state.dly_since,
               state.dly_src)
        auth = _auth(state)
        sig = (state.sig_target, state.sig_meta, state.sig_payload,
               state.sig_gt, state.sig_since)
        mal = state.mal_member
        global_time, session = state.global_time, state.session
        sta = _staging(state) if diet else None
        dig = state.digest if diet and cfg.sync_enabled else None

    epoch = state.epoch
    if stagger and cfg.churn_rate > 0.0:
        # The epoch leaf is disk-like (it wipes with the store,
        # state.WIPE_INVENTORY) and is immediately re-derived from the
        # shared round counter + the structural cohort id — a value
        # identity (the leaf is uniform within a cohort), kept explicit
        # so the wiped-disk rebirth semantics stay visible.
        epoch = jnp.where(
            reborn,
            sdiet.epoch_of_cohort(cfg, rnd,
                                  state.cohort.astype(jnp.uint32)),
            epoch)

    if fm.health_checks and cfg.churn_rate > 0.0:
        # A churn rebirth is a wiped-disk restart: the new process starts
        # with a clean health latch (the GE channel state is the LINK's,
        # not the process's — it survives, like the NAT type).
        health = jnp.where(reborn, jnp.uint32(0), state.health)
    else:
        health = state.health
    if rc.enabled and cfg.churn_rate > 0.0:
        # Rebirth resets the PROCESS-memory recovery state (backoff
        # exponent, repair history); the quarantine ostracism is the
        # OVERLAY's decision about the peer and survives, like the NAT
        # type (dispersy_tpu/recovery.py module note).
        backoff = jnp.where(reborn, jnp.uint8(0), state.backoff)
        repair_round = jnp.where(reborn, jnp.uint32(0),
                                 state.repair_round)
    else:
        backoff, repair_round = state.backoff, state.repair_round
    quar_until = state.quar_until

    alive = state.alive
    # Community load state (reference: dispersy.py define_auto_load /
    # get_community(load=True); Community.load_community /
    # unload_community): an UNLOADED peer's community instance is absent
    # — it neither walks, serves, nor takes records in, though its
    # process stays up and its database (the store) persists.  With
    # cfg.auto_load, any community packet arriving at an unloaded peer
    # loads the instance for the NEXT round (one-round spin-up — the
    # reference loads synchronously and dispatches the same packet; a
    # documented round-resolution divergence, like every timer here).
    # A churn rebirth re-loads UNCONDITIONALLY (even with auto_load
    # off): the reborn row is a wiped-disk NEW participant whose join IS
    # an explicit load — unlike checkpoint restart, where the same app
    # resumes its database and an explicit unload can survive (the full
    # re-load boundary is spelled out at engine.unload_members).  The
    # rebirth wipe below covers a SUPERSET of
    # state.INSTANCE_MEMORY_FIELDS (plus store/clock/auth — the disk);
    # keep the two inventories in sync when adding ephemeral leaves.
    if cfg.churn_rate > 0.0:
        loaded = jnp.where(reborn, True, state.loaded)
    else:
        loaded = state.loaded
    act = alive & loaded        # participating this round
    arrivals = jnp.zeros((n,), bool)   # community packets seen (auto-load)

    if cfg.p_symmetric > 0.0:
        # Connection types (reference: candidate.py ``connection_type``):
        # symmetric-NAT membership is a static property of the identity
        # (the router's, not the process's — it survives churn rebirth),
        # drawn once from the round-0 counter stream; trackers are public
        # infrastructure.  Used by the introduction filters and the
        # puncture gate below.
        nat_sym = ((rng.rand_uniform(seed, jnp.uint32(0), idx, rng.P_NAT)
                    < cfg.p_symmetric) & (idx >= t))

        def sym_of(peer):
            """Gather connection types for a peer-index array (NO_PEER and
            out-of-range entries read as public — they are masked out by
            the callers' validity logic anyway)."""
            safe = jnp.clip(peer.astype(jnp.int32), 0, n - 1)
            return nat_sym[safe] & (peer.astype(jnp.int32) >= 0)
    else:
        nat_sym = None

    # Hard-kill state (reference: community.py HardKilledCommunity — once a
    # peer stores the founder's dispersy-destroy-community, its community
    # instance is dead: no walking, no authoring, no intake; its sync
    # responder serves ONLY the destroy record so destruction keeps
    # spreading).  Derived from the (post-churn) store each round, the way
    # the reference derives the classification from the database on load;
    # a churned-out peer forgets the kill and re-learns it by syncing.
    if cfg.timeline_enabled:
        killed = killed_mask(stc.meta)
    else:
        killed = jnp.zeros((n,), bool)

    # ---- phase 1: walker send ------------------------------------------
    # dispersy_get_walk_candidate + create_introduction_request.  Trackers
    # never walk (reference: TrackerCommunity disables the candidate
    # walker — it stays connected purely through inbound requests).
    boot_base, boot_count, mem_base, mem_count = _layout_cols(cfg, idx)
    if cfg.walker_enabled:
        with jax.named_scope("walk"):
            target = cand.sample_walk_target(tab, now, cfg, seed, rnd,
                                             idx, boot_base, boot_count)
        target = jnp.where(act & ~state.is_tracker & ~killed, target,
                           NO_PEER)
        if rc.enabled:
            # Recovery-plane walk gates (RECOVERY.md): a backed-off
            # peer walks one round in 2^backoff (graceful degradation —
            # it stops amplifying load and re-probes cheaply) and a
            # quarantined peer sits out until its release round.
            walk_ok = jnp.ones((n,), bool)
            if rc.backoff_limit > 0:
                walk_ok &= rcv.backoff_gate(rnd, backoff)
            if rc.quarantine_rounds > 0:
                walk_ok &= ~rcv.quarantine_active(rnd, quar_until)
            target = jnp.where(walk_ok, target, NO_PEER)
    else:
        target = jnp.full((n,), NO_PEER, jnp.int32)

    if sync_on and stagger:
        # Cohort-staggered claim (storediet.py): only the active
        # cohort's N/cohorts block syncs this round, and the serve
        # phase gathers everything it needs (the requester's slice AND
        # digest) directly at the block — no fleet-wide claim arrays
        # and no bloom on the modeled wire (the request is the 2-col
        # quiet layout; req_bytes above still charges the active
        # cohort's tuple).
        sl = my_bloom = rec_h = rec_probes = None
    elif sync_on and diet:
        # Byte-diet claim (storediet.py): the slice is recomputed from
        # the ring (unchanged since the last compaction, so this is the
        # compaction-time slice) and the bloom is the persistent DIGEST
        # — a bloom_words read instead of re-hashing and re-reading 4
        # key columns of the full store.  The digest carries the epoch
        # salt and already covers every record staged since the last
        # compaction (the wrap-up's digest_update).
        sl = st.claim_slice_largest(stc.gt, cfg.bloom_capacity)
        my_bloom = dig
        rec_h = rec_probes = None
    elif sync_on:
        # dispersy_claim_sync_bloom_filter: pick a store slice, fill a bloom.
        if cfg.sync_strategy == "modulo":
            sl = st.claim_slice_modulo(stc.gt, cfg.bloom_capacity, rnd)
        else:
            sl = st.claim_slice_largest(stc.gt, cfg.bloom_capacity)
        in_slice = st.slice_mask(stc.gt, sl)                         # [N, M]
        rec_h = record_hash(stc.member, stc.gt, stc.meta, stc.payload)
        # Per-round salt = the reference's per-claim filter prefix: a
        # false positive this round is re-randomized next round, so pull
        # repair converges to 100% even against static stores (see
        # ops/bloom._h1_h2).  Round-synchronous, so the responder derives
        # the identical salt from its own round counter.
        # On gather backends (CPU) the probe tensor materializes ONCE and
        # is shared by the build here and every responder-slot query
        # below — re-deriving the double-hash chain per call was a
        # first-order byte cost of the round (bit-identical either way).
        if bloom.gather_backend():
            rec_probes = bloom.probe_bits(rec_h, cfg.bloom_bits,
                                          cfg.bloom_hashes, salt=rnd)
            with jax.named_scope("bloom_build"):
                my_bloom = bloom.bloom_build_from(
                    rec_probes, in_slice, cfg.bloom_bits,
                    chunks=cfg.parallel.scatter_chunks)
        else:
            rec_probes = None
            with jax.named_scope("bloom_build"):
                my_bloom = bloom.bloom_build(rec_h, in_slice,
                                             cfg.bloom_bits,
                                             cfg.bloom_hashes, salt=rnd)
    else:
        zu = jnp.zeros((n,), jnp.uint32)
        sl = st.SyncSlice(time_low=zu, time_high=zu, modulo=zu, offset=zu)
        my_bloom = jnp.zeros((n, cfg.bloom_words), jnp.uint32)

    # ---- phase 1f: push forwarding (store_update_forward's _forward) ----
    # Last round's fresh records go to `forward_fanout` distinct verified
    # candidates — the epidemic *push* on top of Bloom-sync's pull.  One
    # candidate set per peer per round, shared by the whole batch, exactly
    # like the reference's per-batch candidate pick.
    if cfg.forward_fanout > 0 or fm.flood_enabled:
        # Edge-list segments: the real push fan-out, then (flood_enabled)
        # the byzantine junk blast.  One deliver call serves both — junk
        # competes for the same bounded victim inboxes, which IS the
        # saturation attack (FAULTS.md).
        e_dst, e_valid = [], []
        e_cols: list[list] = [[] for _ in range(5)]
        e_src, e_junk = [], []
        if ov.enabled:
            # Per-sender token buckets (OVERLOAD.md bucket state
            # machine): this round's credit = carried balance + refill,
            # spent by every ATTEMPTED push/flood packet (pre-loss, the
            # sendto boundary) in emission order; attempts beyond the
            # balance are shed at intake — they never occupy any
            # victim's inbox slot — and attributed to the SENDER
            # (msgs_shed_rate: flood-fair attribution).
            ov_credit = ovl.bucket_refill(state.bucket, seed, rnd, idx,
                                          kno.bucket_rate,
                                          ov.bucket_depth)      # u32[N]
            ov_shed = jnp.zeros((n,), jnp.uint32)
            ov_att = jnp.zeros((n,), jnp.int32)
        if cfg.forward_fanout > 0:
            f, c = cfg.forward_buffer, cfg.forward_fanout
            fwd_targets = cand.sample_forward_targets(tab, now, cfg, seed,
                                                      rnd, idx)   # [N, C]
            fwd_gt, fwd_member, fwd_meta, fwd_payload, fwd_aux = fwd
            have_rec = (fwd_gt != jnp.uint32(EMPTY_U32))[:, :, None]
            tgt_ok = (fwd_targets != NO_PEER)[:, None, :]         # [N, 1, C]
            fc_salt = (jnp.arange(f)[:, None] * c
                       + jnp.arange(c)[None, :])[None, :, :]      # [1, F, C]
            push_lost = _lost(seed, rnd, idx[:, None, None], _LOSS_FORWARD,
                              fc_salt, kn, ge_bad)
            if cfg.timeline_enabled:
                # A hard-killed peer pushes NOTHING except destroy records
                # — HardKilledCommunity actively spreads the kill (the
                # creator itself is killed the instant its own destroy
                # stores, so without this the record would never leave
                # the founder).
                send_rec_ok = (act[:, None]
                               & (~killed[:, None]
                                  | (fwd_meta == jnp.uint32(META_DESTROY))
                                  ))[:, :, None]              # [N, F, 1]
            else:
                send_rec_ok = act[:, None, None]
            push_valid = send_rec_ok & have_rec & tgt_ok & ~push_lost
            push_dst = jnp.broadcast_to(fwd_targets[:, None, :], (n, f, c))
            if fm.partitions:
                push_valid = push_valid & ~flt.partition_blocked(
                    jnp.broadcast_to(idx[:, None, None], (n, f, c)),
                    push_dst, fm.partitions)
            if ov.enabled:
                # Rate gate: attempt ordinal per sender in (f, c)
                # emission order; ordinals beyond this round's credit
                # shed (loss-independent — a lost packet still spent
                # its credit, as it left the sender's NIC).
                att = jnp.broadcast_to(send_rec_ok & have_rec & tgt_ok,
                                       (n, f, c)).reshape(n, f * c)
                ordn = jnp.cumsum(att.astype(jnp.int32), axis=1) - 1
                in_budget = att & (ordn < ov_credit.astype(
                    jnp.int32)[:, None])
                ov_shed = ov_shed + jnp.sum(
                    att & ~in_budget, axis=1).astype(jnp.uint32)
                ov_att = ov_att + jnp.sum(att, axis=1, dtype=jnp.int32)
                push_valid = push_valid & in_budget.reshape(n, f, c)

            def bcast(col):
                return jnp.broadcast_to(col[:, :, None],
                                        (n, f, c)).reshape(-1)
            e_dst.append(push_dst.reshape(-1))
            e_valid.append(push_valid.reshape(-1))
            for e_col, col in zip(e_cols, (fwd_gt, fwd_member, fwd_meta,
                                           fwd_payload, fwd_aux)):
                e_col.append(bcast(col))
            # The pen tracks each record's deliverer (the missing-proof
            # request target), so pushes carry their sender.
            e_src.append(jnp.broadcast_to(
                idx[:, None, None].astype(jnp.uint32), (n, f, c)).reshape(-1))
            e_junk.append(jnp.zeros((n * f * c,), bool))
        if fm.flood_enabled:
            fsrc = jnp.asarray(fm.flood_senders, jnp.int32)       # [L]
            fl, ff = len(fm.flood_senders), fm.flood_fanout
            fsalt = jnp.arange(ff)[None, :]                       # [1, Ff]
            victims = (jnp.int32(t) + (
                rng.rand_u32(seed, rnd, fsrc[:, None], rng.P_FLOOD, fsalt)
                % jnp.uint32(n - t)).astype(jnp.int32))           # [L, Ff]

            def junk_field(block):
                return rng.rand_u32(seed, rnd, fsrc[:, None], rng.P_FLOOD,
                                    fsalt + (block << 12))
            alive_f = alive[fsrc]
            fl_lost = _lost(seed, rnd, fsrc[:, None], _LOSS_FLOOD, fsalt,
                            kn, ge_bad)
            fl_valid = alive_f[:, None] & ~fl_lost
            if fm.partitions:
                fl_valid = fl_valid & ~flt.partition_blocked(
                    jnp.broadcast_to(fsrc[:, None], (fl, ff)), victims,
                    fm.partitions)
            if ov.enabled:
                # Flood blasts spend the SAME bucket, with ordinals
                # continuing after the sender's real-push attempts —
                # a flooder that also relays cannot double its share.
                # flood_senders are distinct (config-validated), so the
                # scatter-adds below never collide.
                att_f = jnp.broadcast_to(alive_f[:, None], (fl, ff))
                ordf = (ov_att[fsrc][:, None]
                        + jnp.arange(ff, dtype=jnp.int32)[None, :])
                in_budget_f = att_f & (ordf < ov_credit[fsrc].astype(
                    jnp.int32)[:, None])
                ov_shed = ov_shed.at[fsrc].add(
                    jnp.sum(att_f & ~in_budget_f,
                            axis=1).astype(jnp.uint32), mode="drop")
                ov_att = ov_att.at[fsrc].add(
                    jnp.sum(att_f, axis=1, dtype=jnp.int32),
                    mode="drop")
                fl_valid = fl_valid & in_budget_f
            e_dst.append(victims.reshape(-1))
            e_valid.append(fl_valid.reshape(-1))
            e_cols[0].append(junk_field(1).reshape(-1))           # gt
            e_cols[1].append(junk_field(2).reshape(-1))           # member
            e_cols[2].append((junk_field(3)
                              & jnp.uint32(0xFF)).astype(
                                  jnp.uint8).reshape(-1))         # meta
            e_cols[3].append(junk_field(4).reshape(-1))           # payload
            e_cols[4].append(junk_field(5).reshape(-1))           # aux
            e_src.append(jnp.broadcast_to(fsrc[:, None].astype(jnp.uint32),
                                          (fl, ff)).reshape(-1))
            e_junk.append(jnp.ones((fl * ff,), bool))
            # The flooder pays sendto bytes for every blast, pre-loss
            # (byzantine or not, its NIC moves the packets).
            bup = bup.at[fsrc].add(
                jnp.where(alive_f, jnp.uint32(ff * RECORD_BYTES),
                          jnp.uint32(0)), mode="drop")
        push_cols = [jnp.concatenate(cl) for cl in e_cols]
        if cfg.delay_enabled:
            push_cols.append(jnp.concatenate(e_src))
        if fm.flood_enabled:
            push_cols.append(jnp.concatenate(e_junk))
        if ov.enabled:
            # Spend: in-budget attempts drain the balance (attempts
            # beyond it were shed, not spent); refill happens at the
            # NEXT round's bucket_refill.
            bucket_new = ovl.bucket_spend(
                ov_credit, jnp.maximum(ov_att, 0).astype(jnp.uint32))
            stats = stats.replace(
                msgs_shed_rate=stats.msgs_shed_rate + ov_shed)
        if ov.enabled and ov.priority_admission:
            # Priority admission (OVERLOAD.md class table): the
            # wire-visible meta byte classes each packet, and the
            # delivery kernel sheds lowest-class-last under overflow
            # instead of first-come-first-kept — flood junk with an
            # invalid meta byte ranks dead last.
            push_cls = ovl.admission_class(push_cols[2], cfg.n_meta,
                                           cfg.priorities)
        else:
            push_cls = None
        with jax.named_scope("deliver_push"):
            push, px_shed = _deliver(
                cfg, dst=jnp.concatenate(e_dst), cols=push_cols,
                valid=jnp.concatenate(e_valid), n_peers=n,
                inbox_size=cfg.push_inbox, cls=push_cls,
                need_receipts=False, capped=True)
        if px_shed is not None:
            # cross_shard_budget overflow: shed edges left the sender's
            # NIC (bytes_up already paid above) and died in the
            # exchange — a modeled loss, attributed to the SENDER as
            # backpressure (stats.xshard_shed), segment by segment.
            sh = px_shed.astype(jnp.uint32)
            off = 0
            if cfg.forward_fanout > 0:
                stats = stats.replace(
                    xshard_shed=stats.xshard_shed
                    + jnp.sum(sh[:n * f * c].reshape(n, f * c), axis=1))
                off = n * f * c
            if fm.flood_enabled:
                stats = stats.replace(
                    xshard_shed=stats.xshard_shed.at[fsrc].add(
                        jnp.sum(sh[off:off + fl * ff].reshape(fl, ff),
                                axis=1), mode="drop"))
        ph_gt, ph_member, ph_meta, ph_payload, ph_aux = push.inbox[:5]
        if fm.flood_enabled:
            ph_junk = push.inbox[-1]                              # bool[N, Q]
            # Junk never decodes, so it never auto-loads a community
            # (reference: define_auto_load fires on decoded packets).
            arrivals = arrivals | jnp.any(push.inbox_valid & ~ph_junk,
                                          axis=1)
        else:
            arrivals = arrivals | jnp.any(push.inbox_valid, axis=1)
        ph_ok = push.inbox_valid & act[:, None]
        # Flood-fair drop attribution (OVERLOAD.md): with the overload
        # plane on, push-inbox overflow sheds are ADMISSION decisions —
        # they land in the receiver's msgs_shed_priority stream, which
        # deliberately does NOT feed the health_drop_limit sentinel, so
        # a flooded victim's recovery plane stops punishing the victim.
        if ov.enabled:
            stats = stats.replace(
                msgs_shed_priority=stats.msgs_shed_priority
                + push.n_dropped.astype(jnp.uint32))
        if cfg.forward_fanout > 0:
            stats = stats.replace(
                msgs_forwarded=stats.msgs_forwarded
                + jnp.sum(push_valid, axis=(1, 2)).astype(jnp.uint32))
            if not ov.enabled:
                stats = stats.replace(
                    msgs_dropped=stats.msgs_dropped
                    + push.n_dropped.astype(jnp.uint32))
            push_sent = send_rec_ok & have_rec & tgt_ok          # pre-loss
            bup = bup + jnp.sum(push_sent, axis=(1, 2)).astype(jnp.uint32) \
                * jnp.uint32(RECORD_BYTES)
        elif not ov.enabled:
            stats = stats.replace(
                msgs_dropped=stats.msgs_dropped
                + push.n_dropped.astype(jnp.uint32))
        # recvfrom: every delivered packet (junk included) crosses the
        # receiver's socket before the hash check can reject it.
        bdown = bdown + jnp.sum(ph_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
        if fm.flood_enabled or kn.corrupt_on:
            # Intake hash re-verification (modeled): flood junk always
            # fails it; real records fail with corrupt_rate.  Either way
            # the record is DROPPED and counted — never ingested as
            # garbage (FAULTS.md).
            q_sz = ph_ok.shape[1]
            bad = jnp.zeros_like(ph_ok)
            if fm.flood_enabled:
                bad = bad | (ph_ok & ph_junk)
            if kn.corrupt_on:
                cu = rng.rand_uniform(
                    seed, rnd, idx[:, None], rng.P_CORRUPT,
                    jnp.arange(q_sz)[None, :] + _FAULT_PUSH)
                bad = bad | (ph_ok & (cu < jnp.float32(kn.corrupt_rate)))
            stats = stats.replace(
                msgs_corrupt_dropped=stats.msgs_corrupt_dropped
                + jnp.sum(bad, axis=1).astype(jnp.uint32))
            ph_ok = ph_ok & ~bad
        if cfg.delay_enabled:
            ph_src = jnp.where(ph_ok, push.inbox[5].astype(jnp.int32),
                               NO_PEER)
        if kn.dup_on:
            # Delivery duplication: a clean delivered push arrives twice
            # (the duplicate joins the intake batch's tail segment).
            du = rng.rand_uniform(
                seed, rnd, idx[:, None], rng.P_DUP,
                jnp.arange(ph_ok.shape[1])[None, :] + _FAULT_PUSH)
            ph_dup_ok = ph_ok & (du < jnp.float32(kn.dup_rate))
            bdown = bdown + jnp.sum(ph_dup_ok, axis=1).astype(jnp.uint32) \
                * jnp.uint32(RECORD_BYTES)
    else:
        p0 = jnp.zeros((n, 0), jnp.uint32)
        ph_gt = ph_member = ph_payload = ph_aux = p0
        ph_meta = jnp.zeros((n, 0), jnp.uint8)
        ph_ok = jnp.zeros((n, 0), bool)
        ph_src = jnp.zeros((n, 0), jnp.int32)
        ph_dup_ok = jnp.zeros((n, 0), bool)

    req_lost = _lost(seed, rnd, idx, _LOSS_REQUEST, 0, kn, ge_bad)
    # target is already NO_PEER for dead/tracker/killed peers (phase 1).
    bup = bup + (act & (target != NO_PEER)).astype(jnp.uint32) * req_bytes
    send_ok = act & (target != NO_PEER) & ~req_lost
    if fm.partitions:
        # A partitioned walk edge never delivers (loss with p=1): the
        # whole request/response/sync exchange dies with the request,
        # since partitions sever both directions.
        send_ok = send_ok & ~flt.partition_blocked(idx, target,
                                                   fm.partitions)
    to_tracker = (target >= 0) & (target < t)
    # Every request packet carries the sender's clock *as of round start*:
    # the tracker delivery below must not read a clock already raised by
    # this round's incoming requests (fused-round causality).
    gt_at_send = global_time

    # Normal-peer request inbox: [N, R] with the full sync payload when
    # the sync exchange runs this round; without it (sync disabled, or a
    # byte-diet quiet round) the request is just (src, clock) — the
    # sync tuple would never be served, so it never rides the wire.
    # Under cohort staggering the HBM request layout is ALWAYS the
    # 2-col quiet form: the digest-serve responder below evaluates the
    # requester's bloom against its own resident digest at the active
    # block, so the [N, R, bloom_words] inbox tensor (the sync round's
    # dominant request-side byte term) is never materialized.  The
    # MODELED wire still carries the tuple — req_bytes above.
    wire_sync = sync_on and not stagger
    with jax.named_scope("deliver_request"):
        req, _ = _deliver(
            cfg, dst=target,
            cols=([idx.astype(jnp.uint32), sl.time_low, sl.time_high,
                   sl.modulo, sl.offset, gt_at_send, my_bloom]
                  if wire_sync else [idx.astype(jnp.uint32), gt_at_send]),
            valid=send_ok & ~to_tracker, n_peers=n,
            inbox_size=cfg.request_inbox)
    if wire_sync:
        (rq_src, rq_tlow, rq_thigh, rq_mod, rq_off, rq_gt,
         rq_bloom) = req.inbox
    else:
        rq_src, rq_gt = req.inbox
    arrivals = arrivals | jnp.any(req.inbox_valid, axis=1)
    rq_ok = req.inbox_valid & act[:, None]                   # [N, R]
    rq_src_i = jnp.where(rq_ok, rq_src.astype(jnp.int32), NO_PEER)
    stats = stats.replace(
        requests_dropped=stats.requests_dropped
        + req.n_dropped.astype(jnp.uint32))
    n_rq = jnp.sum(rq_ok, axis=1).astype(jnp.uint32)
    # handled requests: request bytes in, one response each out
    if stagger and sync_on:
        # Per-source request sizes (req_bytes is a vector): the
        # responder's ingress charge gathers each accepted request's
        # own size.
        bdown = bdown + jnp.sum(
            jnp.where(rq_ok, req_bytes[jnp.maximum(rq_src_i, 0)],
                      jnp.uint32(0)), axis=1)
    else:
        bdown = bdown + n_rq * req_bytes
    bup = bup + n_rq * jnp.uint32(INTRO_RESPONSE_BYTES)

    # ---- phase 2: request processing at the responder ------------------
    # on_introduction_request: stumble the requester, pick a third peer,
    # send introduction-response + puncture-request, serve the sync slice.
    r = cfg.request_inbox
    tab = cand.upsert_many(
        tab, upd_peer=rq_src_i,
        upd_kind=jnp.full((n, r), cand.KIND_STUMBLE, jnp.int32),
        upd_valid=rq_ok, now=now, self_idx=idx, n_trackers=t)
    global_time = _fold_gt(global_time, rq_gt, rq_ok,
                           cfg.acceptable_global_time_range)

    # ---- phase 2t: the tracker fast path -------------------------------
    if t > 0:
        rt = cfg.tracker_inbox
        k = cfg.k_candidates
        tidx = jnp.arange(t, dtype=jnp.int32)
        treq = inbox.deliver(
            dst=target, cols=[idx.astype(jnp.uint32), gt_at_send],
            valid=send_ok & to_tracker, n_peers=t, inbox_size=rt)
        tq_src, tq_gt = treq.inbox                           # [T, Rt]
        # Partition-rule pin (parallel/mesh.py): the tracker-row
        # tensors carry NO peer axis — without the explicit replication
        # pin, SPMD partitioning picks a [8,1] layout for some of them
        # and a [2,4] layout for others and bridges the two with
        # involuntary full rematerializations (the exact warnings
        # tests/test_ledger.py used to pin as PRESENT).  Identity when
        # unsharded.
        tq_src = par.pin_replicated(tq_src)
        tq_gt = par.pin_replicated(tq_gt)
        tq_ok = par.pin_replicated(treq.inbox_valid & act[:t][:, None])
        tq_src_i = par.pin_replicated(
            jnp.where(tq_ok, tq_src.astype(jnp.int32), NO_PEER))

        # Recent-contact ring in the tracker's candidate rows: up to K
        # stumbles per round land in rotating unique slots (a tracker's
        # candidate set is just "whoever knocked recently" — reference:
        # TrackerCommunity keeps no long-lived state per community).
        kr = min(rt, k)
        slot = ((rnd * jnp.uint32(rt) + jnp.arange(kr, dtype=jnp.uint32))
                % jnp.uint32(k)).astype(jnp.int32)           # unique [kr]
        slot_b = jnp.broadcast_to(slot[None, :], (t, kr))
        ring_ok = tq_ok[:, :kr]
        ring_src = tq_src_i[:, :kr]
        trows = tidx[:, None]

        # Dedup across rounds: a returning requester's stale ring entry is
        # cleared before the new one lands, so no peer holds two slots (and
        # a doubled introduction probability).
        stale = jnp.any((tab.peer[:t][:, :, None] == ring_src[:, None, :])
                        & ring_ok[:, None, :], axis=-1)       # [T, K]
        tab = cand.CandTable(
            peer=tab.peer.at[:t].set(
                jnp.where(stale, NO_PEER, tab.peer[:t])),
            last_walk=tab.last_walk.at[:t].set(
                jnp.where(stale, NEVER, tab.last_walk[:t])),
            last_stumble=tab.last_stumble.at[:t].set(
                jnp.where(stale, NEVER, tab.last_stumble[:t])),
            last_intro=tab.last_intro.at[:t].set(
                jnp.where(stale, NEVER, tab.last_intro[:t])))

        def ring_write(full, vals, ok):
            cur = jnp.take_along_axis(full[:t], slot_b, axis=1)
            return full.at[trows, slot_b].set(jnp.where(ok, vals, cur),
                                              mode="drop")

        tab = cand.CandTable(
            peer=ring_write(tab.peer, ring_src, ring_ok),
            last_walk=ring_write(tab.last_walk,
                                 jnp.full((t, kr), NEVER, jnp.float32), ring_ok),
            last_stumble=ring_write(tab.last_stumble,
                                    jnp.full((t, kr), now, jnp.float32), ring_ok),
            last_intro=ring_write(tab.last_intro,
                                  jnp.full((t, kr), NEVER, jnp.float32), ring_ok))

        ttab = cand.CandTable(peer=tab.peer[:t], last_walk=tab.last_walk[:t],
                              last_stumble=tab.last_stumble[:t],
                              last_intro=tab.last_intro[:t])
        intro_ring = cand.sample_introductions(
            ttab, now, cfg, seed, rnd, tidx, exclude=tq_src_i,
            salt_base=_TRACKER_INTRO_SALT,
            req_sym=None if nat_sym is None
            else par.pin_replicated(sym_of(tq_src_i)),
            slot_sym=None if nat_sym is None
            else par.pin_replicated(sym_of(ttab.peer)))      # [T, Rt]
        # Under a bootstrap flash-crowd the tracker's richest candidate pool
        # is this round's own inbox: introduce requester s to another
        # requester j != s (both just proved their addresses by knocking).
        # Falls back to the ring pick when the chosen slot is empty.  This is
        # what keeps introductions *diverse* — a K-slot ring alone funnels
        # thousands of bootstrappers onto K peers and melts their inboxes.
        s_ix = jnp.arange(rt, dtype=jnp.uint32)[None, :]
        j = ((s_ix + 1 + rng.rand_u32(seed, rnd, tidx[:, None], rng.P_INTRO,
                                      s_ix + _TRACKER_INTRO_SALT + (1 << 18))
              % jnp.uint32(max(rt - 1, 1))) % jnp.uint32(rt)).astype(jnp.int32)
        intro_inbox = jnp.take_along_axis(tq_src_i, j, axis=1)
        intro_inbox = jnp.where(intro_inbox == tq_src_i, NO_PEER, intro_inbox)
        if nat_sym is not None:
            # The inbox-introduction path is an introduction too: never
            # pair two symmetric-NAT requesters (fall through to the
            # filtered ring pick instead).
            # sym_of gathers from the peer-sharded nat_sym — pin the
            # tracker-row result replicated like every [T, Rt] tensor
            # here, or SPMD bridges the gather's layout with
            # involuntary remats (MULTICHIP_r06 select/and warnings).
            intro_inbox = jnp.where(
                par.pin_replicated(sym_of(tq_src_i) & sym_of(intro_inbox)),
                NO_PEER, intro_inbox)
        intro_t = par.pin_replicated(
            jnp.where(intro_inbox != NO_PEER, intro_inbox, intro_ring))
        global_time = global_time.at[:t].set(
            _fold_gt(global_time[:t], tq_gt, tq_ok,
                     cfg.acceptable_global_time_range))
        stats = stats.replace(
            requests_dropped=stats.requests_dropped.at[:t].add(
                treq.n_dropped.astype(jnp.uint32)))
        n_tq = jnp.sum(tq_ok, axis=1).astype(jnp.uint32)
        if stagger and sync_on:
            # req_bytes is a per-peer vector under staggering — gather
            # each accepted request's own size (normal-responder rule).
            bdown = bdown.at[:t].add(jnp.sum(
                jnp.where(tq_ok, req_bytes[jnp.maximum(tq_src_i, 0)],
                          jnp.uint32(0)), axis=1))
        else:
            bdown = bdown.at[:t].add(n_tq * req_bytes)
        bup = bup.at[:t].add(n_tq * jnp.uint32(INTRO_RESPONSE_BYTES)
                             + jnp.sum(tq_ok & (intro_t != NO_PEER),
                                       axis=1).astype(jnp.uint32)
                             * jnp.uint32(PUNCTURE_REQUEST_BYTES))
    else:
        rt = 0

    intro = cand.sample_introductions(
        tab, now, cfg, seed, rnd, idx, exclude=rq_src_i,
        req_sym=None if nat_sym is None else sym_of(rq_src_i),
        slot_sym=None if nat_sym is None else sym_of(tab.peer))   # [N, R]
    bup = bup + jnp.sum(rq_ok & (intro != NO_PEER),
                        axis=1).astype(jnp.uint32) \
        * jnp.uint32(PUNCTURE_REQUEST_BYTES)

    # Introduction responses are NOT re-routed through a second global sort:
    # the responder's per-slot replies (intro pick, clock) sit where the
    # request landed, and each requester fetches its reply by receipt
    # (``edge_slot``) — a pure gather.  This mirrors the reference, where a
    # response is unicast straight back to the requester's socket address.

    # puncture-request edges: responder -> C, naming the requester.
    salt_r = jnp.arange(r)[None, :]
    pr_lost = _lost(seed, rnd, idx[:, None], _LOSS_PUNCTURE_REQ, salt_r,
                    kn, ge_bad)
    pr_ok_send = rq_ok & (intro != NO_PEER) & ~pr_lost
    if fm.partitions:
        pr_ok_send = pr_ok_send & ~flt.partition_blocked(
            jnp.broadcast_to(idx[:, None], intro.shape), intro,
            fm.partitions)
    pr_dst = [intro.reshape(-1)]
    pr_target = [rq_src_i.reshape(-1).astype(jnp.uint32)]
    pr_valid = [pr_ok_send.reshape(-1)]

    if t > 0:
        salt_rt = jnp.arange(rt)[None, :] + _TRACKER_SALT
        tpr_lost = _lost(seed, rnd, tidx[:, None], _LOSS_PUNCTURE_REQ, salt_rt,
                         kn, ge_bad)
        tpr_ok_send = par.pin_replicated(
            tq_ok & (intro_t != NO_PEER) & ~tpr_lost)
        if fm.partitions:
            tpr_ok_send = tpr_ok_send & ~flt.partition_blocked(
                jnp.broadcast_to(tidx[:, None], intro_t.shape), intro_t,
                fm.partitions)
        pr_dst.append(intro_t.reshape(-1))
        pr_target.append(tq_src_i.reshape(-1).astype(jnp.uint32))
        pr_valid.append(tpr_ok_send.reshape(-1))

    punc_req, _ = _deliver(
        cfg, dst=jnp.concatenate(pr_dst), cols=[jnp.concatenate(pr_target)],
        valid=jnp.concatenate(pr_valid), n_peers=n,
        inbox_size=cfg.request_inbox, need_receipts=False)
    (pq_target,) = punc_req.inbox                             # [N, P]
    arrivals = arrivals | jnp.any(punc_req.inbox_valid, axis=1)
    pq_ok = punc_req.inbox_valid & act[:, None]
    stats = stats.replace(
        punctures=stats.punctures
        + jnp.sum(pq_ok, axis=1).astype(jnp.uint32),
        # Puncture-path inbox overflow is a real (modeled) loss too.
        requests_dropped=stats.requests_dropped
        + punc_req.n_dropped.astype(jnp.uint32))
    n_pq = jnp.sum(pq_ok, axis=1).astype(jnp.uint32)
    bdown = bdown + n_pq * jnp.uint32(PUNCTURE_REQUEST_BYTES)
    bup = bup + n_pq * jnp.uint32(PUNCTURE_BYTES)   # one puncture each out

    # ---- phase 4: puncture hop (C -> requester) ------------------------
    p = cfg.request_inbox
    salt_p = jnp.arange(p)[None, :]
    pu_lost = _lost(seed, rnd, idx[:, None], _LOSS_PUNCTURE, salt_p,
                    kn, ge_bad)
    pu_ok_send = pq_ok & ~pu_lost
    if fm.partitions:
        pu_ok_send = pu_ok_send & ~flt.partition_blocked(
            jnp.broadcast_to(idx[:, None], pq_target.shape),
            pq_target.astype(jnp.int32), fm.partitions)
    if nat_sym is not None:
        # Two address-dependent NATs cannot hole-punch: a puncture from a
        # symmetric C toward a symmetric requester never lands (modeled
        # as delivery failure; the introduction filters make this pairing
        # rare, this gate makes it impossible).
        pu_ok_send = pu_ok_send & ~(nat_sym[:, None] & sym_of(pq_target))
    pu_valid = pu_ok_send.reshape(-1)
    punc, _ = _deliver(
        cfg, dst=pq_target.reshape(-1).astype(jnp.int32),
        cols=[jnp.broadcast_to(idx[:, None].astype(jnp.uint32),
                               (n, p)).reshape(-1)],
        valid=pu_valid, n_peers=n, inbox_size=cfg.request_inbox,
        need_receipts=False)
    (pu_from,) = punc.inbox
    arrivals = arrivals | jnp.any(punc.inbox_valid, axis=1)
    pu_ok = punc.inbox_valid & act[:, None]
    stats = stats.replace(
        requests_dropped=stats.requests_dropped
        + punc.n_dropped.astype(jnp.uint32))
    bdown = bdown + jnp.sum(pu_ok, axis=1).astype(jnp.uint32) \
        * jnp.uint32(PUNCTURE_BYTES)

    # ---- phase 3: response processing at the requester -----------------
    # on_introduction_response: mark the responder walked, the introduced
    # peer introduced; success/failure accounting.  Fused-round timeout: a
    # request that got no response this round is a failed walk, and the
    # stale candidate is dropped (IntroductionRequestCache.on_timeout).
    # Reply pickup by receipt: requester r's reply sits at slot
    # edge_slot[r] of its target's per-slot reply table.
    tgt = jnp.maximum(target, 0)
    slot_n = jnp.maximum(req.edge_slot, 0)
    got_n = (req.edge_slot >= 0) & rq_ok[tgt, slot_n]
    intro_n = intro[tgt, slot_n]
    if t > 0:
        slot_t = jnp.maximum(treq.edge_slot, 0)
        tgt_t = jnp.minimum(tgt, t - 1)
        got_t = (treq.edge_slot >= 0) & tq_ok[tgt_t, slot_t]
        got_raw = jnp.where(to_tracker, got_t, got_n)
        intro_pick = jnp.where(to_tracker, intro_t[tgt_t, slot_t], intro_n)
    else:
        got_raw, intro_pick = got_n, intro_n
    resp_lost = _lost(seed, rnd, idx, _LOSS_RESPONSE, 0, kn, ge_bad)
    got_resp = got_raw & ~resp_lost & act
    bdown = bdown + got_resp.astype(jnp.uint32) \
        * jnp.uint32(INTRO_RESPONSE_BYTES)
    walked = jnp.where(got_resp, target, NO_PEER)
    introduced = jnp.where(got_resp, intro_pick, NO_PEER)
    rs_gt = global_time[tgt][:, None]                         # responder clock
    rs_ok = got_resp[:, None]
    upd_peer = jnp.concatenate(
        [walked[:, None], introduced[:, None],
         jnp.where(pu_ok, pu_from.astype(jnp.int32), NO_PEER)], axis=1)
    upd_kind = jnp.concatenate(
        [jnp.full((n, 1), cand.KIND_WALK, jnp.int32),
         jnp.full((n, 1), cand.KIND_INTRO, jnp.int32),
         jnp.full((n, p), cand.KIND_STUMBLE, jnp.int32)], axis=1)
    tab = cand.upsert_many(tab, upd_peer, upd_kind,
                           upd_valid=upd_peer != NO_PEER, now=now,
                           self_idx=idx, n_trackers=t)
    global_time = _fold_gt(global_time, rs_gt, rs_ok,
                           cfg.acceptable_global_time_range)

    walked_ok = act & (target != NO_PEER)
    failed = walked_ok & ~got_resp
    tab = cand.remove(tab, target, failed)
    stats = stats.replace(
        walk_success=stats.walk_success
        + (walked_ok & got_resp).astype(jnp.uint32),
        walk_fail=stats.walk_fail + failed.astype(jnp.uint32))
    if cfg.telemetry.histograms:
        # Walk-success streak (telemetry walk_streak histogram): +1 on a
        # successful walk, reset on a failed one, untouched on rounds
        # the peer did not walk.  Stats-adjacent — survives churn
        # rebirth like the walk counters it refines (state.py).
        walk_streak = jnp.where(
            walked_ok & got_resp, state.walk_streak + jnp.uint32(1),
            jnp.where(failed, jnp.uint32(0), state.walk_streak))
    else:
        walk_streak = state.walk_streak

    # ---- phase 3s: signature-request/-response exchange ----------------
    # DoubleMemberAuthentication (reference: authentication.py; community.py
    # create_signature_request / on_signature_request / on_signature_response
    # + the signature RequestCache, SURVEY §3.5).  The draft rides to the
    # counterparty ONCE, in the round it was created; the counterparty
    # decides (the app's allow_signature_func, modeled by the
    # countersign_rate draw, plus its own Timeline view for protected
    # metas) and the countersigned record rides back along the same edge
    # by receipt.  A completed record joins this round's intake batch as
    # one more incoming packet; an unanswered request idles until the
    # cache timeout frees the slot — no retransmit, exactly like the
    # reference's one-shot request + cache expiry.
    sg_target, sg_meta, sg_payload, sg_gt, sg_since = sig
    if cfg.double_meta_mask:
        s_sz = cfg.sig_inbox
        sending = act & ~killed & (sg_target != NO_PEER) & (sg_since == rnd)
        srq_lost = _lost(seed, rnd, idx, _LOSS_SIGREQ, 0, kn, ge_bad)
        bup = bup + sending.astype(jnp.uint32) \
            * jnp.uint32(SIGNATURE_REQUEST_BYTES)
        sig_send_ok = sending & ~srq_lost
        if fm.partitions:
            sig_send_ok = sig_send_ok & ~flt.partition_blocked(
                idx, sg_target, fm.partitions)
        sreq, _ = _deliver(
            cfg, dst=jnp.where(sending, sg_target, NO_PEER),
            cols=[idx.astype(jnp.uint32), sg_meta, sg_payload, sg_gt],
            valid=sig_send_ok, n_peers=n, inbox_size=s_sz)
        sq_src, sq_meta, sq_payload, sq_gt = sreq.inbox          # [N, S]
        arrivals = arrivals | jnp.any(sreq.inbox_valid, axis=1)
        # Trackers never countersign (infrastructure, not members); neither
        # do hard-killed peers (their community instance is unloaded).
        sq_ok = (sreq.inbox_valid & act[:, None]
                 & ~state.is_tracker[:, None] & ~killed[:, None])
        if cfg.countersign_rate >= 1.0:
            agree = jnp.ones((n, s_sz), bool)
        elif cfg.countersign_rate <= 0.0:
            agree = jnp.zeros((n, s_sz), bool)
        else:
            agree = rng.rand_uniform(
                seed, rnd, idx[:, None], rng.P_SIGN,
                jnp.arange(s_sz)[None, :]) < jnp.float32(
                    cfg.countersign_rate)
        if cfg.timeline_enabled and ((cfg.protected_meta_mask
                                      | cfg.dynamic_meta_mask)
                                     & cfg.double_meta_mask):
            # on_signature_request runs the draft through B's check
            # pipeline: for a meta that is linear AT THE DRAFT'S
            # global_time (static bit, or B's replayed dynamic flips)
            # both signers need the permit in B's timeline (reference:
            # Timeline.check walks every authentication member).
            founder_b = _founder_col(cfg, mem_base)[:, None]
            shq = jnp.minimum(sq_meta, jnp.uint32(31))
            prot_q = ((((jnp.uint32(cfg.protected_meta_mask) >> shq) & 1)
                       == 1) & (sq_meta < cfg.n_meta))
            if cfg.dynamic_meta_mask & cfg.double_meta_mask:
                dyn_q = ((((jnp.uint32(cfg.dynamic_meta_mask) >> shq) & 1)
                          == 1) & (sq_meta < cfg.n_meta))
                best_q = _flip_best(stc, sq_meta, sq_gt)         # [N, S]
                prot_q = jnp.where(dyn_q,
                                   jnp.where(best_q > 0,
                                             (best_q & 1) == 1, prot_q),
                                   prot_q)
            perm_q = (tl.check(auth, sq_src, sq_meta, sq_gt, founder_b)
                      & tl.check(auth,
                                 jnp.broadcast_to(idx[:, None].astype(
                                     jnp.uint32), (n, s_sz)),
                                 sq_meta, sq_gt, founder_b))
            agree = agree & jnp.where(prot_q, perm_q, True)
        countersign = sq_ok & agree
        n_sq = jnp.sum(sq_ok, axis=1).astype(jnp.uint32)
        n_cs = jnp.sum(countersign, axis=1).astype(jnp.uint32)
        bdown = bdown + n_sq * jnp.uint32(SIGNATURE_REQUEST_BYTES)
        bup = bup + n_cs * jnp.uint32(SIGNATURE_RESPONSE_BYTES)

        # Response pickup by receipt at the author.
        tgt_a = jnp.maximum(jnp.where(sending, sg_target, 0), 0)
        slot_a = jnp.maximum(sreq.edge_slot, 0)
        got_sig = (sreq.edge_slot >= 0) & countersign[tgt_a, slot_a]
        srs_lost = _lost(seed, rnd, idx, _LOSS_SIGRESP, 0, kn, ge_bad)
        completed = sending & got_sig & ~srs_lost
        bdown = bdown + completed.astype(jnp.uint32) \
            * jnp.uint32(SIGNATURE_RESPONSE_BYTES)

        # Cache lifecycle: free on completion, expire on timeout.
        expired = (alive & (sg_target != NO_PEER) & ~completed
                   & (rnd - sg_since >= jnp.uint32(cfg.sig_timeout_rounds)))
        clear = completed | expired
        sig = (jnp.where(clear, NO_PEER, sg_target),
               jnp.where(clear, jnp.uint32(0), sg_meta),
               jnp.where(clear, jnp.uint32(0), sg_payload),
               jnp.where(clear, jnp.uint32(0), sg_gt),
               jnp.where(clear, jnp.uint32(0), sg_since))
        stats = stats.replace(
            sig_signed=stats.sig_signed + n_cs,
            sig_done=stats.sig_done + completed.astype(jnp.uint32),
            sig_expired=stats.sig_expired + expired.astype(jnp.uint32),
            # A signature request lost to inbox overflow is a dropped
            # request like any other.
            requests_dropped=stats.requests_dropped
            + sreq.n_dropped.astype(jnp.uint32))
        # The completed double-signed record, as one intake column.
        db_gt = jnp.where(completed, sg_gt, jnp.uint32(EMPTY_U32))[:, None]
        db_member = idx.astype(jnp.uint32)[:, None]
        # sig_meta stays u32 state (one scalar slot per peer); the record
        # column is the narrowed meta dtype — lossless, meta < n_meta.
        db_meta = sg_meta.astype(jnp.uint8)[:, None]
        db_payload = sg_payload[:, None]
        db_aux = jnp.where(sg_target == NO_PEER, 0,
                           sg_target).astype(jnp.uint32)[:, None]
        db_ok = completed[:, None]
    else:
        d0 = jnp.zeros((n, 0), jnp.uint32)
        db_gt = db_member = db_payload = db_aux = d0
        db_meta = jnp.zeros((n, 0), jnp.uint8)
        db_ok = jnp.zeros((n, 0), bool)

    # ---- phase 2b/5: sync responder + store insert ---------------------
    # The responder fills a per-request-slot *outbox* of up to
    # ``response_budget`` records the requester provably lacks; the
    # requester then fetches its own outbox row by receipt — sync records
    # only ever flow back along the request edge (as in the reference,
    # where sync packets are unicast to the introduction-request sender).
    if sync_on and stagger:
        # Cohort-staggered digest-serve (storediet.py, PR 20): the
        # serve is computed PER REQUESTER on the active cohort's
        # N/cohorts block instead of per responder-slot over the whole
        # fleet.  Equivalence with the per-slot loop below: a request
        # occupies responder slot ``req.edge_slot`` iff
        # ``edge_slot >= 0`` (delivery kept it), and under that gate
        # ``rq_ok[tgt, edge_slot] == act[tgt]`` — so gathering the
        # responder's ring at each block requester's walk target and
        # serving once per requester visits exactly the
        # (requester, slot) pairs the slot loop serves.  The bloom
        # probe runs against the requester's RESIDENT digest block at
        # the cohort's epoch salt — the digest never rides the wire
        # and the responder never re-probes its ring per slot.
        b = cfg.response_budget
        coh = cfg.store.cohorts
        blk = n // coh
        idx_blk = (jnp.arange(blk, dtype=jnp.int32) * coh
                   + a_coh.astype(jnp.int32))          # true peer ids
        tgt_blk = tgt[idx_blk]                          # responders
        edge_ok = (req.edge_slot >= 0)[idx_blk]
        with jax.named_scope("stagger_serve"):
            stv_blk = _response_order(
                st.StoreCols(*(c[tgt_blk] for c in stc)), cfg)
            rec_h2 = record_hash(stv_blk.member, stv_blk.gt,
                                 stv_blk.meta, stv_blk.payload)
            q_probes = (bloom.probe_bits(rec_h2, cfg.bloom_bits,
                                         cfg.bloom_hashes, salt=ep_a)
                        if bloom.gather_backend() else None)
            # The requester's claimed slice, from its own (unchanged
            # since last compaction) ring block.
            sl_blk = st.claim_slice_largest(
                st.cohort_take(stc.gt, a_coh, coh), cfg.bloom_capacity)
            in_sl = st.slice_mask(stv_blk.gt, sl_blk)     # [blk, M]
            if cfg.timeline_enabled:
                # Hard-killed responders serve only the destroy record.
                in_sl = in_sl & (~killed[tgt_blk][:, None]
                                 | (stv_blk.meta
                                    == jnp.uint32(META_DESTROY)))
            dig_blk = st.cohort_take(dig, a_coh, coh)
            if q_probes is not None:
                present = bloom.bloom_query_from(dig_blk, q_probes)
            else:
                present = bloom.bloom_query(dig_blk, rec_h2,
                                            cfg.bloom_bits,
                                            cfg.bloom_hashes, salt=ep_a)
            if cfg.timeline_enabled:
                present = present & ~killed[tgt_blk][:, None]
            missing = in_sl & ~present \
                & (edge_ok & act[tgt_blk])[:, None]
            rank = jnp.cumsum(missing.astype(jnp.int32), axis=1) - 1
            slot = jnp.where(missing & (rank < b), rank, b)
            o_gt, o_member, o_meta, o_payload, o_aux, o_ok = \
                st.rank_compact_many(
                    [(stv_blk.gt, EMPTY_U32), (stv_blk.member, EMPTY_U32),
                     (stv_blk.meta, EMPTY_META),
                     (stv_blk.payload, EMPTY_U32),
                     (stv_blk.aux, 0), (missing, False)], slot, b)
        # Scatter the block outboxes into the full [N, b] pickup layout
        # (zeros elsewhere — every consumer below gates on sy_ok), so
        # the loss/corrupt/dup draws and every downstream intake line
        # key on the requester's TRUE peer index, exactly like the
        # per-slot path.
        zf = jnp.zeros((n, b), jnp.uint32)
        sy_gt = st.cohort_put(zf, o_gt, a_coh, coh)
        sy_member = st.cohort_put(zf, o_member, a_coh, coh)
        sy_meta = st.cohort_put(jnp.zeros((n, b), jnp.uint8), o_meta,
                                a_coh, coh)
        sy_payload = st.cohort_put(zf, o_payload, a_coh, coh)
        sy_aux = st.cohort_put(jnp.zeros((n, b), stc.aux.dtype), o_aux,
                               a_coh, coh)
        sy_cand = st.cohort_put(jnp.zeros((n, b), bool), o_ok,
                                a_coh, coh)
        sync_lost = _lost(seed, rnd, idx[:, None], _LOSS_SYNC,
                          jnp.arange(b)[None, :], kn, ge_bad)
        sy_ok = sy_cand & act[:, None] & ~sync_lost
        # Responder upload: served records leave the responder pre-loss
        # (a scatter-add at the block's walk targets); requester
        # download per accepted record, as on the per-slot path.
        bup = bup.at[tgt_blk].add(
            jnp.sum(o_ok, axis=1).astype(jnp.uint32)
            * jnp.uint32(RECORD_BYTES), mode="drop")
        bdown = bdown + jnp.sum(sy_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
        if kn.corrupt_on:
            cu = rng.rand_uniform(seed, rnd, idx[:, None], rng.P_CORRUPT,
                                  jnp.arange(b)[None, :] + _FAULT_SYNC)
            sy_bad = sy_ok & (cu < jnp.float32(kn.corrupt_rate))
            stats = stats.replace(
                msgs_corrupt_dropped=stats.msgs_corrupt_dropped
                + jnp.sum(sy_bad, axis=1).astype(jnp.uint32))
            sy_ok = sy_ok & ~sy_bad
        if kn.dup_on:
            du = rng.rand_uniform(seed, rnd, idx[:, None], rng.P_DUP,
                                  jnp.arange(b)[None, :] + _FAULT_SYNC)
            sy_dup_ok = sy_ok & (du < jnp.float32(kn.dup_rate))
            bdown = bdown + jnp.sum(sy_dup_ok, axis=1).astype(jnp.uint32) \
                * jnp.uint32(RECORD_BYTES)
    elif sync_on:
        b = cfg.response_budget
        # The responder serves from its ordered view (priority DESC, gt
        # ASC/DESC per meta); identity for default communities — in which
        # case the claim's record hashes (and, on gather backends, the
        # materialized probe tensor) are reused verbatim.  Under the
        # byte-diet the claim read the digest instead of hashing the
        # ring, so the responder derives its own probe tensor here —
        # with the EPOCH salt the requesters' digests were built with.
        stv = _response_order(stc, cfg)
        q_salt = ep if diet else rnd
        if diet or cfg.needs_response_order:
            rec_h2 = record_hash(stv.member, stv.gt, stv.meta, stv.payload)
            q_probes = (bloom.probe_bits(rec_h2, cfg.bloom_bits,
                                         cfg.bloom_hashes, salt=q_salt)
                        if bloom.gather_backend() else None)
        else:
            rec_h2, q_probes = rec_h, rec_probes
        # A hard-killed responder serves nothing but the destroy record —
        # the reference's HardKilledCommunity answers every packet with the
        # packed dispersy-destroy-community message.
        if cfg.timeline_enabled:
            servable = ~killed[:, None] | (stv.meta == jnp.uint32(
                META_DESTROY))                                    # [N, M]
        else:
            servable = None
        gts, members, metas, payloads, auxs, valids = [], [], [], [], [], []
        rows = idx[:, None]
        for s in range(r):
            sl_s = st.SyncSlice(time_low=rq_tlow[:, s], time_high=rq_thigh[:, s],
                                modulo=rq_mod[:, s], offset=rq_off[:, s])
            in_sl = st.slice_mask(stv.gt, sl_s)                   # [N, M]
            if servable is not None:
                in_sl = in_sl & servable
            if q_probes is not None:
                present = bloom.bloom_query_from(rq_bloom[:, s], q_probes)
            else:
                present = bloom.bloom_query(rq_bloom[:, s], rec_h2,
                                            cfg.bloom_bits,
                                            cfg.bloom_hashes, salt=q_salt)
            if cfg.timeline_enabled:
                # A hard-killed responder answers every request with the
                # destroy record UNCONDITIONALLY (reference:
                # HardKilledCommunity replies with the packed destroy
                # message to any packet) — never skipped on a Bloom
                # false-positive, or a saturated filter would stall the
                # kill's spread.
                present = present & ~killed[:, None]
            missing = in_sl & ~present & rq_ok[:, s:s + 1]
            # First `b` missing records in serving order — the view is the
            # responder's ORDER BY under dispersy_sync_response_limit.
            rank = jnp.cumsum(missing.astype(jnp.int32), axis=1) - 1
            slot = jnp.where(missing & (rank < b), rank, b)
            o_gt, o_member, o_meta, o_payload, o_aux, o_ok = \
                st.rank_compact_many(
                    [(stv.gt, EMPTY_U32), (stv.member, EMPTY_U32),
                     (stv.meta, EMPTY_META), (stv.payload, EMPTY_U32),
                     (stv.aux, 0), (missing, False)], slot, b)
            gts.append(o_gt)
            members.append(o_member)
            metas.append(o_meta)
            payloads.append(o_payload)
            auxs.append(o_aux)
            valids.append(o_ok)
        obox = [jnp.stack(c, axis=1)
                for c in (gts, members, metas, payloads, auxs)]
        obox_ok = jnp.stack(valids, axis=1)                       # [N, R, b]

        # Requester pickup by receipt + per-record Bernoulli loss.
        sy_gt, sy_member, sy_meta, sy_payload, sy_aux = (
            c[tgt, slot_n] for c in obox)                         # [N, b]
        sync_lost = _lost(seed, rnd, idx[:, None], _LOSS_SYNC,
                          jnp.arange(b)[None, :], kn, ge_bad)
        sy_ok = (obox_ok[tgt, slot_n] & (req.edge_slot >= 0)[:, None]
                 & act[:, None] & ~sync_lost)
        bup = bup + jnp.sum(obox_ok, axis=(1, 2)).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
        bdown = bdown + jnp.sum(sy_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
        if kn.corrupt_on:
            # In-transit bit-flip: the record crossed the socket (bytes
            # counted above) but fails the intake hash re-check — dropped
            # and counted, never ingested (FAULTS.md).
            cu = rng.rand_uniform(seed, rnd, idx[:, None], rng.P_CORRUPT,
                                  jnp.arange(b)[None, :] + _FAULT_SYNC)
            sy_bad = sy_ok & (cu < jnp.float32(kn.corrupt_rate))
            stats = stats.replace(
                msgs_corrupt_dropped=stats.msgs_corrupt_dropped
                + jnp.sum(sy_bad, axis=1).astype(jnp.uint32))
            sy_ok = sy_ok & ~sy_bad
        if kn.dup_on:
            du = rng.rand_uniform(seed, rnd, idx[:, None], rng.P_DUP,
                                  jnp.arange(b)[None, :] + _FAULT_SYNC)
            sy_dup_ok = sy_ok & (du < jnp.float32(kn.dup_rate))
            bdown = bdown + jnp.sum(sy_dup_ok, axis=1).astype(jnp.uint32) \
                * jnp.uint32(RECORD_BYTES)
    else:
        s0 = jnp.zeros((n, 0), jnp.uint32)
        sy_gt = sy_member = sy_payload = sy_aux = s0
        sy_meta = jnp.zeros((n, 0), jnp.uint8)
        sy_ok = jnp.zeros((n, 0), bool)
        sy_dup_ok = jnp.zeros((n, 0), bool)

    if cfg.delay_enabled:
        dl_gt, dl_member, dl_meta, dl_payload, dl_aux, dl_since, dl_src = dly
        dl_ok = (dl_gt != jnp.uint32(EMPTY_U32)) & act[:, None]
    else:
        z0 = jnp.zeros((n, 0), jnp.uint32)
        dl_gt = dl_member = dl_payload = dl_aux = dl_since = z0
        dl_meta = jnp.zeros((n, 0), jnp.uint8)
        dl_src = jnp.zeros((n, 0), jnp.int32)
        dl_ok = jnp.zeros((n, 0), bool)

    # ---- phase 4p: active missing-proof round trip ---------------------
    # (reference: community.py on_missing_proof — a receiver that delayed
    # a message for its proof sends dispersy-missing-proof(member,
    # global_time) to the message's SENDER, which answers with the stored
    # authorize chain justifying it.)  Round-synchronous recast: each
    # parked record's original deliverer is asked this round; its stored
    # authorize/revoke records targeting the parked record's author ride
    # back by receipt and join THIS round's intake batch — where the
    # parked record (leading the batch via the pen segment) is re-checked
    # against the batch-folded grants — so pen residence is one round
    # trip, not Bloom re-offer luck (config.proof_requests).
    if cfg.delay_enabled and cfg.proof_requests:
        dd_, pb = cfg.delay_inbox, cfg.proof_budget
        have_pen = dl_ok & (dl_src != NO_PEER)                  # [N, D]
        prq_lost = _lost(seed, rnd, idx[:, None], _LOSS_PROOF_REQ,
                         jnp.arange(dd_)[None, :], kn, ge_bad)
        bup = bup + jnp.sum(have_pen, axis=1).astype(jnp.uint32) \
            * jnp.uint32(MISSING_PROOF_BYTES)
        pen_send = have_pen & ~prq_lost
        if fm.partitions:
            pen_send = pen_send & ~flt.partition_blocked(
                jnp.broadcast_to(idx[:, None], dl_src.shape), dl_src,
                fm.partitions)
        preq, _ = _deliver(
            cfg, dst=dl_src.reshape(-1), cols=[dl_member.reshape(-1)],
            valid=pen_send.reshape(-1), n_peers=n,
            inbox_size=cfg.proof_inbox)
        (pq_author,) = preq.inbox                               # [N, Pi]
        arrivals = arrivals | jnp.any(preq.inbox_valid, axis=1)
        pq_pok = preq.inbox_valid & act[:, None]
        if cfg.timeline_enabled:
            pq_pok = pq_pok & ~killed[:, None]
        stats = stats.replace(
            proof_requests=stats.proof_requests
            + jnp.sum(pq_pok, axis=1).astype(jnp.uint32),
            requests_dropped=stats.requests_dropped
            + preq.n_dropped.astype(jnp.uint32))
        bdown = bdown + jnp.sum(pq_pok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(MISSING_PROOF_BYTES)
        # Serve: per request, the proof_budget HIGHEST-global_time stored
        # authorize/revoke rows targeting the author (the store is sorted
        # ascending, so rank from the end — newest proof first, exactly
        # the rows Timeline.check's latest-wins rule needs).
        is_proof_row = ((stc.meta == jnp.uint32(META_AUTHORIZE))
                        | (stc.meta == jnp.uint32(META_REVOKE)))  # [N, M]
        pouts = []
        for s in range(cfg.proof_inbox):
            m_s = (is_proof_row & pq_pok[:, s:s + 1]
                   & (stc.payload == pq_author[:, s:s + 1]))    # [N, M]
            from_end = jnp.cumsum(m_s[:, ::-1].astype(jnp.int32),
                                  axis=1)[:, ::-1] - 1
            pslot = jnp.where(m_s & (from_end < pb), from_end, pb)
            pouts.append(tuple(st.rank_compact(col, pslot, pb, fill)
                               for col, fill in
                               ((stc.gt, EMPTY_U32), (stc.member, EMPTY_U32),
                                (stc.meta, EMPTY_META),
                                (stc.payload, EMPTY_U32), (stc.aux, 0),
                                (m_s, False))))
        pbox = [jnp.stack([o[i] for o in pouts], axis=1)
                for i in range(6)]                              # [N, Pi, pb]
        n_served = jnp.sum(pbox[5], axis=(1, 2)).astype(jnp.uint32)
        bup = bup + n_served * jnp.uint32(RECORD_BYTES)
        # Pickup by receipt at the requester: pen slot (i, d)'s reply sits
        # at edge_slot[i*D + d] of server dl_src[i, d]'s outbox.
        src_flat = jnp.maximum(dl_src.reshape(-1), 0)           # [N*D]
        eslot = jnp.maximum(preq.edge_slot, 0)
        got = ((preq.edge_slot >= 0)
               & pq_pok[src_flat, eslot]).reshape(n, dd_)       # [N, D]

        def pick(col):
            return col[src_flat, eslot].reshape(n, dd_ * pb)
        pr_gt, pr_member, pr_meta, pr_payload, pr_aux = (
            pick(c) for c in pbox[:5])
        prs_lost = _lost(seed, rnd, idx[:, None], _LOSS_PROOF_RESP,
                         jnp.arange(dd_ * pb)[None, :], kn, ge_bad)
        pr_ok = (pick(pbox[5])
                 & jnp.repeat(got, pb, axis=1)
                 & act[:, None] & ~prs_lost)
        pr_src = jnp.repeat(dl_src, pb, axis=1)
        stats = stats.replace(
            proof_records=stats.proof_records
            + jnp.sum(pr_ok, axis=1).astype(jnp.uint32))
        bdown = bdown + jnp.sum(pr_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
    else:
        q0 = jnp.zeros((n, 0), jnp.uint32)
        pr_gt = pr_member = pr_payload = pr_aux = q0
        pr_meta = jnp.zeros((n, 0), jnp.uint8)
        pr_ok = jnp.zeros((n, 0), bool)
        pr_src = jnp.zeros((n, 0), jnp.int32)

    # ---- phase 4s: active missing-sequence round trip ------------------
    # (reference: community.py on_missing_sequence serving
    # dispersy-missing-sequence(member, message, missing_low,
    # missing_high); message.py DelayMessageBySequence parks the gapped
    # record.)  Each SEQ-parked pen entry asks its original deliverer for
    # the missing range [requester's stored max+1, gap-1]; the server's
    # stored in-range records ride back by receipt ASCENDING (chains
    # accept bottom-up within one batch) and join this round's intake —
    # the parked record itself re-chains next round against the advanced
    # stored max.  Shares the proof channel's bounds
    # (config.proof_inbox/proof_budget); config.seq_requests.
    # LOCKSTEP NOTE: this block deliberately mirrors phase 4p's
    # request/serve/receipt scaffolding (and both have oracle
    # mirrors in oracle/sim.py) — a change to either channel's
    # delivery, gating, loss, or accounting must be made in all
    # four places or the trace-equality tests will flag it.
    if cfg.delay_enabled and cfg.seq_requests:
        dd_, qb = cfg.delay_inbox, cfg.proof_budget
        shq = jnp.minimum(dl_meta, jnp.uint32(31))
        dl_is_seq = ((((jnp.uint32(cfg.seq_meta_mask) >> shq) & 1) == 1)
                     & (dl_meta < cfg.n_meta))
        sq_low = ik.seq_stored_max(stc, dl_member, dl_meta) + jnp.uint32(1)
        sq_high = dl_aux - jnp.uint32(1)
        want = (dl_ok & (dl_src != NO_PEER) & dl_is_seq
                & (sq_low <= sq_high))                      # [N, D]
        mrq_lost = _lost(seed, rnd, idx[:, None], _LOSS_SEQ_REQ,
                         jnp.arange(dd_)[None, :], kn, ge_bad)
        bup = bup + jnp.sum(want, axis=1).astype(jnp.uint32) \
            * jnp.uint32(MISSING_SEQ_BYTES)
        seq_send = want & ~mrq_lost
        if fm.partitions:
            seq_send = seq_send & ~flt.partition_blocked(
                jnp.broadcast_to(idx[:, None], dl_src.shape), dl_src,
                fm.partitions)
        qreq, _ = _deliver(
            cfg, dst=dl_src.reshape(-1),
            cols=[dl_member.reshape(-1), dl_meta.reshape(-1),
                  sq_low.reshape(-1), sq_high.reshape(-1)],
            valid=seq_send.reshape(-1), n_peers=n,
            inbox_size=cfg.proof_inbox)
        qq_member, qq_meta, qq_low, qq_high = qreq.inbox    # [N, Qi]
        arrivals = arrivals | jnp.any(qreq.inbox_valid, axis=1)
        qq_ok = qreq.inbox_valid & act[:, None]
        if cfg.timeline_enabled:
            qq_ok = qq_ok & ~killed[:, None]
        stats = stats.replace(
            seq_requests=stats.seq_requests
            + jnp.sum(qq_ok, axis=1).astype(jnp.uint32),
            requests_dropped=stats.requests_dropped
            + qreq.n_dropped.astype(jnp.uint32))
        bdown = bdown + jnp.sum(qq_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(MISSING_SEQ_BYTES)
        # Serve: per request, the proof_budget LOWEST-sequence stored rows
        # in [low, high] for (member, meta) — the store sorts ascending
        # and one member's sequence numbers rise with global_time, so
        # rank-from-start IS ascending-sequence order, which lets a full
        # reply chain accept in one batch.
        live_rows = stc.gt != jnp.uint32(EMPTY_U32)
        qouts = []
        for s in range(cfg.proof_inbox):
            m_s = (live_rows & qq_ok[:, s:s + 1]
                   & (stc.member == qq_member[:, s:s + 1])
                   & (stc.meta == qq_meta[:, s:s + 1])
                   & (stc.aux >= qq_low[:, s:s + 1])
                   & (stc.aux <= qq_high[:, s:s + 1]))      # [N, M]
            from_start = jnp.cumsum(m_s.astype(jnp.int32), axis=1) - 1
            qslot = jnp.where(m_s & (from_start < qb), from_start, qb)
            qouts.append(tuple(st.rank_compact(col, qslot, qb, fill)
                               for col, fill in
                               ((stc.gt, EMPTY_U32), (stc.member, EMPTY_U32),
                                (stc.meta, EMPTY_META),
                                (stc.payload, EMPTY_U32), (stc.aux, 0),
                                (m_s, False))))
        qbox = [jnp.stack([o[i] for o in qouts], axis=1)
                for i in range(6)]                          # [N, Qi, qb]
        bup = bup + jnp.sum(qbox[5], axis=(1, 2)).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
        # Pickup by receipt at the requester (same shape as phase 4p).
        qsrc_flat = jnp.maximum(dl_src.reshape(-1), 0)      # [N*D]
        qeslot = jnp.maximum(qreq.edge_slot, 0)
        qgot = ((qreq.edge_slot >= 0)
                & qq_ok[qsrc_flat, qeslot]).reshape(n, dd_)  # [N, D]

        def qpick(col):
            return col[qsrc_flat, qeslot].reshape(n, dd_ * qb)
        mq_gt, mq_member, mq_meta, mq_payload, mq_aux = (
            qpick(c) for c in qbox[:5])
        mqs_lost = _lost(seed, rnd, idx[:, None], _LOSS_SEQ_RESP,
                         jnp.arange(dd_ * qb)[None, :], kn, ge_bad)
        mq_ok = (qpick(qbox[5])
                 & jnp.repeat(qgot, qb, axis=1)
                 & act[:, None] & ~mqs_lost)
        mq_src = jnp.repeat(dl_src, qb, axis=1)
        stats = stats.replace(
            seq_records=stats.seq_records
            + jnp.sum(mq_ok, axis=1).astype(jnp.uint32))
        bdown = bdown + jnp.sum(mq_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
    else:
        m0 = jnp.zeros((n, 0), jnp.uint32)
        mq_gt = mq_member = mq_payload = mq_aux = m0
        mq_meta = jnp.zeros((n, 0), jnp.uint8)
        mq_ok = jnp.zeros((n, 0), bool)
        mq_src = jnp.zeros((n, 0), jnp.int32)

    # ---- phase 4m: active missing-message round trip -------------------
    # (reference: community.py on_missing_message serving
    # dispersy-missing-message(member, global_times); message.py
    # DelayPacketByMissingMessage parks the dependent packet.)  Each
    # UNDO-OTHER pen entry — parked because its named target record (or
    # the undoer's grant) had not arrived — asks its original deliverer
    # for the exact (member, global_time) record it names; the stored
    # record rides back by receipt into this round's intake, and the
    # parked undo re-checks against it next round.  Budget 1: the store's
    # UNIQUE(member, global_time) key makes the reply a single record.
    # LOCKSTEP NOTE: mirrors phase 4p's request/serve/receipt scaffolding
    # (oracle: sm_batch) — change all four places together.
    if cfg.delay_enabled and cfg.msg_requests:
        dd_ = cfg.delay_inbox
        want_mm = (dl_ok & (dl_src != NO_PEER)
                   & (dl_meta == jnp.uint32(META_UNDO_OTHER)))   # [N, D]
        mmq_lost = _lost(seed, rnd, idx[:, None], _LOSS_MSG_REQ,
                         jnp.arange(dd_)[None, :], kn, ge_bad)
        bup = bup + jnp.sum(want_mm, axis=1).astype(jnp.uint32) \
            * jnp.uint32(MISSING_MSG_BYTES)
        mm_send = want_mm & ~mmq_lost
        if fm.partitions:
            mm_send = mm_send & ~flt.partition_blocked(
                jnp.broadcast_to(idx[:, None], dl_src.shape), dl_src,
                fm.partitions)
        mreq, _ = _deliver(
            cfg, dst=dl_src.reshape(-1),
            cols=[dl_payload.reshape(-1), dl_aux.reshape(-1)],
            valid=mm_send.reshape(-1), n_peers=n,
            inbox_size=cfg.proof_inbox)
        mr_member, mr_gt = mreq.inbox                            # [N, Mi]
        arrivals = arrivals | jnp.any(mreq.inbox_valid, axis=1)
        mr_ok = mreq.inbox_valid & act[:, None]
        if cfg.timeline_enabled:
            mr_ok = mr_ok & ~killed[:, None]
        stats = stats.replace(
            mm_requests=stats.mm_requests
            + jnp.sum(mr_ok, axis=1).astype(jnp.uint32),
            requests_dropped=stats.requests_dropped
            + mreq.n_dropped.astype(jnp.uint32))
        bdown = bdown + jnp.sum(mr_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(MISSING_MSG_BYTES)
        # Serve: the (unique) stored USER row at (member, global_time) —
        # control rows are never undo targets (pre_undone's meta < 32).
        live_rows = (stc.gt != jnp.uint32(EMPTY_U32)) & (stc.meta < 32)
        mouts = []
        for s in range(cfg.proof_inbox):
            m_s = (live_rows & mr_ok[:, s:s + 1]
                   & (stc.member == mr_member[:, s:s + 1])
                   & (stc.gt == mr_gt[:, s:s + 1]))              # [N, M]
            first = jnp.cumsum(m_s.astype(jnp.int32), axis=1) - 1
            mslot = jnp.where(m_s & (first < 1), first, 1)
            mouts.append(tuple(st.rank_compact(col, mslot, 1, fill)
                               for col, fill in
                               ((stc.gt, EMPTY_U32), (stc.member, EMPTY_U32),
                                (stc.meta, EMPTY_META),
                                (stc.payload, EMPTY_U32), (stc.aux, 0),
                                (m_s, False))))
        mbox = [jnp.stack([o[i] for o in mouts], axis=1)
                for i in range(6)]                               # [N, Mi, 1]
        bup = bup + jnp.sum(mbox[5], axis=(1, 2)).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
        msrc_flat = jnp.maximum(dl_src.reshape(-1), 0)           # [N*D]
        meslot = jnp.maximum(mreq.edge_slot, 0)
        mgot = ((mreq.edge_slot >= 0)
                & mr_ok[msrc_flat, meslot]).reshape(n, dd_)      # [N, D]

        def mpick(col):
            return col[msrc_flat, meslot].reshape(n, dd_)
        mm_gt, mm_member, mm_meta, mm_payload, mm_aux = (
            mpick(c[:, :, 0]) for c in mbox[:5])
        mms_lost = _lost(seed, rnd, idx[:, None], _LOSS_MSG_RESP,
                         jnp.arange(dd_)[None, :], kn, ge_bad)
        mm_ok = (mpick(mbox[5][:, :, 0]) & mgot & act[:, None] & ~mms_lost)
        mm_src = dl_src
        stats = stats.replace(
            mm_records=stats.mm_records
            + jnp.sum(mm_ok, axis=1).astype(jnp.uint32))
        bdown = bdown + jnp.sum(mm_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
    else:
        mm0 = jnp.zeros((n, 0), jnp.uint32)
        mm_gt = mm_member = mm_payload = mm_aux = mm0
        mm_meta = jnp.zeros((n, 0), jnp.uint8)
        mm_ok = jnp.zeros((n, 0), bool)
        mm_src = jnp.zeros((n, 0), jnp.int32)

    # ---- phase 4i: active missing-identity round trip ------------------
    # (reference: community.py on_missing_identity serving
    # dispersy-missing-identity(mid); conversion.py raises
    # DelayPacketByMissingMember for packets from unknown members.)  Each
    # pen entry still lacking its author's dispersy-identity record asks
    # its deliverer for it; the identity rides back by receipt into this
    # round's intake, and the parked record re-checks next round.
    # Budget 1: one identity record per member.  LOCKSTEP NOTE: same
    # scaffolding as 4p/4s/4m (oracle: si_batch).
    if cfg.delay_enabled and cfg.identity_requests:
        dd_ = cfg.delay_inbox
        want_id = (dl_ok & (dl_src != NO_PEER)
                   & (dl_meta < cfg.n_meta)
                   & ~ik.identity_stored(stc, dl_member))        # [N, D]
        idq_lost = _lost(seed, rnd, idx[:, None], _LOSS_ID_REQ,
                         jnp.arange(dd_)[None, :], kn, ge_bad)
        bup = bup + jnp.sum(want_id, axis=1).astype(jnp.uint32) \
            * jnp.uint32(MISSING_IDENTITY_BYTES)
        id_send = want_id & ~idq_lost
        if fm.partitions:
            id_send = id_send & ~flt.partition_blocked(
                jnp.broadcast_to(idx[:, None], dl_src.shape), dl_src,
                fm.partitions)
        ireq, _ = _deliver(
            cfg, dst=dl_src.reshape(-1), cols=[dl_member.reshape(-1)],
            valid=id_send.reshape(-1), n_peers=n,
            inbox_size=cfg.proof_inbox)
        (iq_member,) = ireq.inbox                                # [N, Ii]
        arrivals = arrivals | jnp.any(ireq.inbox_valid, axis=1)
        iq_ok = ireq.inbox_valid & act[:, None]
        if cfg.timeline_enabled:
            iq_ok = iq_ok & ~killed[:, None]
        stats = stats.replace(
            id_requests=stats.id_requests
            + jnp.sum(iq_ok, axis=1).astype(jnp.uint32),
            requests_dropped=stats.requests_dropped
            + ireq.n_dropped.astype(jnp.uint32))
        bdown = bdown + jnp.sum(iq_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(MISSING_IDENTITY_BYTES)
        id_rows = stc.meta == jnp.uint32(META_IDENTITY)          # [N, M]
        iouts = []
        for s in range(cfg.proof_inbox):
            m_s = (id_rows & iq_ok[:, s:s + 1]
                   & (stc.member == iq_member[:, s:s + 1]))      # [N, M]
            first = jnp.cumsum(m_s.astype(jnp.int32), axis=1) - 1
            islot = jnp.where(m_s & (first < 1), first, 1)
            iouts.append(tuple(st.rank_compact(col, islot, 1, fill)
                               for col, fill in
                               ((stc.gt, EMPTY_U32), (stc.member, EMPTY_U32),
                                (stc.meta, EMPTY_META),
                                (stc.payload, EMPTY_U32), (stc.aux, 0),
                                (m_s, False))))
        ibox = [jnp.stack([o[i] for o in iouts], axis=1)
                for i in range(6)]                               # [N, Ii, 1]
        bup = bup + jnp.sum(ibox[5], axis=(1, 2)).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
        isrc_flat = jnp.maximum(dl_src.reshape(-1), 0)           # [N*D]
        ieslot = jnp.maximum(ireq.edge_slot, 0)
        igot = ((ireq.edge_slot >= 0)
                & iq_ok[isrc_flat, ieslot]).reshape(n, dd_)      # [N, D]

        def ipick(col):
            return col[isrc_flat, ieslot].reshape(n, dd_)
        ii_gt, ii_member, ii_meta, ii_payload, ii_aux = (
            ipick(c[:, :, 0]) for c in ibox[:5])
        iis_lost = _lost(seed, rnd, idx[:, None], _LOSS_ID_RESP,
                         jnp.arange(dd_)[None, :], kn, ge_bad)
        ii_ok = (ipick(ibox[5][:, :, 0]) & igot & act[:, None] & ~iis_lost)
        ii_src = dl_src
        stats = stats.replace(
            id_records=stats.id_records
            + jnp.sum(ii_ok, axis=1).astype(jnp.uint32))
        bdown = bdown + jnp.sum(ii_ok, axis=1).astype(jnp.uint32) \
            * jnp.uint32(RECORD_BYTES)
    else:
        ii0 = jnp.zeros((n, 0), jnp.uint32)
        ii_gt = ii_member = ii_payload = ii_aux = ii0
        ii_meta = jnp.zeros((n, 0), jnp.uint8)
        ii_ok = jnp.zeros((n, 0), bool)
        ii_src = jnp.zeros((n, 0), jnp.int32)

    # ---- phase 5: combined intake (delayed pen + sync pull + push +
    # completed double-signed + returned proofs) -> store.  One batch per
    # round: the pen's waiting records first (they were delivered in an
    # earlier round — the reference re-processes a delayed batch ahead of
    # fresh arrivals when its proof lands), then sync records, then pushed
    # records, then this round's countersigned completion, then the
    # missing-proof replies, in delivery order — mirroring the reference's
    # _on_batch_cache handling one grouped batch per meta per window.
    segs_gt = [dl_gt, sy_gt, ph_gt, db_gt, pr_gt, mq_gt, mm_gt, ii_gt]
    segs_member = [dl_member, sy_member, ph_member, db_member, pr_member,
                   mq_member, mm_member, ii_member]
    segs_meta = [dl_meta, sy_meta, ph_meta, db_meta, pr_meta, mq_meta,
                 mm_meta, ii_meta]
    segs_payload = [dl_payload, sy_payload, ph_payload, db_payload,
                    pr_payload, mq_payload, mm_payload, ii_payload]
    segs_aux = [dl_aux, sy_aux, ph_aux, db_aux, pr_aux, mq_aux, mm_aux,
                ii_aux]
    segs_ok = [dl_ok, sy_ok, ph_ok, db_ok, pr_ok, mq_ok, mm_ok, ii_ok]
    if kn.dup_on:
        # Delivery duplicates: the same delivered sync/push records again
        # at the batch tail, valid where the dup draw fired — the store's
        # UNIQUE insert and in-batch dedup absorb them (FAULTS.md).
        segs_gt += [sy_gt, ph_gt]
        segs_member += [sy_member, ph_member]
        segs_meta += [sy_meta, ph_meta]
        segs_payload += [sy_payload, ph_payload]
        segs_aux += [sy_aux, ph_aux]
        segs_ok += [sy_dup_ok, ph_dup_ok]
    in_gt = jnp.concatenate(segs_gt, axis=1)                   # [N, B]
    in_member = jnp.concatenate(segs_member, axis=1)
    in_meta = jnp.concatenate(segs_meta, axis=1)
    in_payload = jnp.concatenate(segs_payload, axis=1)
    in_aux = jnp.concatenate(segs_aux, axis=1)
    in_ok = jnp.concatenate(segs_ok, axis=1)
    bb = in_gt.shape[1]
    if cfg.delay_enabled:
        # Round each batch entry was (first) delivered: pen entries keep
        # their parking round, everything else arrived now.
        in_since = jnp.concatenate(
            [dl_since, jnp.broadcast_to(rnd, (n, bb - dl_since.shape[1]))],
            axis=1).astype(jnp.uint32)
        # Each entry's deliverer — the future missing-proof target should
        # it park (sync pulls come from the walk target; pushes carry
        # their sender; a completed double-signed record came back from
        # its countersigner; proof replies from the serving peer).
        sy_src = jnp.where(sy_ok, jnp.broadcast_to(
            target[:, None], sy_ok.shape), NO_PEER)
        # sg_target is the PRE-clear cache target (the cache frees on
        # completion, exactly when the record exists).
        db_src = (jnp.where(db_ok, sg_target[:, None], NO_PEER)
                  if db_ok.shape[1] else
                  jnp.zeros((n, 0), jnp.int32))
        in_src = jnp.concatenate(
            [dl_src, sy_src, ph_src, db_src, pr_src, mq_src, mm_src,
             ii_src] + ([sy_src, ph_src] if kn.dup_on else []),
            axis=1)
    if bb > 0:
        # Clock-jump defense before the store accepts anything.
        in_ok = in_ok & (in_gt <= global_time[:, None] + jnp.uint32(
            cfg.acceptable_global_time_range))
        if cfg.timeline_enabled:
            # A hard-killed peer's community instance is unloaded: it
            # processes no incoming messages at all (reference:
            # HardKilledCommunity drops everything) — applied before ANY
            # intake bookkeeping, including malicious conviction.
            in_ok = in_ok & ~killed[:, None]
        if cfg.double_meta_mask:
            # The structural "signature verify" for double-signed records
            # (whether freshly countersigned or arriving via sync): the
            # countersigner in `aux` must be a real, distinct, non-tracker
            # member of the receiver's community (reference:
            # conversion.py decode rejects a double-signed packet whose
            # second signature does not verify).
            shd = jnp.minimum(in_meta, jnp.uint32(31))
            is_dbl = ((((jnp.uint32(cfg.double_meta_mask) >> shd) & 1) == 1)
                      & (in_meta < cfg.n_meta))
            dbl_ok = ((in_aux != in_member)
                      & (in_aux >= mem_base.astype(jnp.uint32)[:, None])
                      & (in_aux < (mem_base + mem_count).astype(
                          jnp.uint32)[:, None]))
            in_ok = in_ok & jnp.where(is_dbl, dbl_ok, True)
        if cfg.malicious_enabled:
            # Double-sign conviction (reference: dispersy.py malicious-
            # member bookkeeping / dispersy-malicious-proof): an arriving
            # record matching a STORED record's (member, global_time) but
            # differing in content proves its author signed two messages
            # at one time.  Convict locally, then reject this batch's (and
            # every future) record by any convicted member.
            pre_mal = mal
            conflict = in_ok & ik.conflict(
                stc, in_member, in_gt, in_meta, in_payload, in_aux)  # [N, B]
            mf = tl.fold_set(mal, in_member, valid=conflict)
            mal = mf.table
            stats = stats.replace(
                conflicts=stats.conflicts + mf.n_inserted.astype(jnp.uint32),
                msgs_dropped=stats.msgs_dropped
                + mf.n_dropped.astype(jnp.uint32))
            if cfg.malicious_gossip:
                # Gossiped convictions (reference: dispersy.py spreads the
                # conflicting packet pair as dispersy-malicious-proof so
                # non-eyewitnesses convict too).  An arriving claim record
                # convicts its named member here — unless the CLAIMANT is
                # itself already blacklisted (post-eyewitness-fold): a
                # convicted member's traffic, claims included, is dead.
                black0 = jnp.any(mal[:, None, :] == in_member[:, :, None],
                                 axis=-1)
                claims = (in_ok & ~black0
                          & (in_meta == jnp.uint32(META_MALICIOUS)))
                cf = tl.fold_set(mal, in_payload, valid=claims)
                mal = cf.table
                stats = stats.replace(
                    convictions_rx=stats.convictions_rx
                    + cf.n_inserted.astype(jnp.uint32),
                    msgs_dropped=stats.msgs_dropped
                    + cf.n_dropped.astype(jnp.uint32))
                # Eyewitness gossip pick: the batch's first conflict naming
                # a member not blacklisted before this batch; the proof
                # record itself is authored post-insert (below), claiming
                # the NEXT global_time like any create.
                was_black = jnp.any(
                    pre_mal[:, None, :] == in_member[:, :, None], axis=-1)
                gospick = conflict & ~was_black                   # [N, B]
                gossip_now = jnp.any(gospick, axis=1)             # [N]
                gj = jnp.argmax(gospick, axis=1)
                g_member = jnp.take_along_axis(
                    in_member, gj[:, None], 1)[:, 0]              # [N]
                g_gt = jnp.take_along_axis(in_gt, gj[:, None], 1)[:, 0]
            is_black = jnp.any(mal[:, None, :] == in_member[:, :, None],
                               axis=-1)
            stats = stats.replace(
                msgs_rejected=stats.msgs_rejected
                + jnp.sum(in_ok & is_black, axis=1).astype(jnp.uint32))
            in_ok = in_ok & ~is_black
        # Freshness (drives next round's forward batch): not already in the
        # store on the UNIQUE(member, global_time) identity, and not a
        # duplicate of an earlier record in this same batch.
        if diet and cfg.sync_enabled:
            # Byte-diet freshness: membership in the epoch DIGEST
            # instead of the exact [N, B, M] key compare — quiet rounds
            # touch zero ring bytes.  A ~bloom_error_rate false
            # positive drops a fresh record as a duplicate (counted;
            # the pull re-offers it under the next epoch's salt); a
            # false negative (an out-of-slice ring record re-arriving)
            # re-stages one duplicate that store_insert's UNIQUE rule
            # kills at compaction — the ring never corrupts
            # (storediet.py module doc; the oracle mirrors both).
            in_h = record_hash(in_member, in_gt, in_meta, in_payload)
            if bloom.gather_backend():
                in_probes = bloom.probe_bits(in_h, cfg.bloom_bits,
                                             cfg.bloom_hashes, salt=ep)
                in_store = bloom.bloom_query_from(dig, in_probes)
            else:
                in_probes = None
                in_store = bloom.bloom_query(dig, in_h, cfg.bloom_bits,
                                             cfg.bloom_hashes, salt=ep)
        elif diet:
            # Diet without sync: no digest — exact membership against
            # ring AND staging (the logical store is their union).
            in_store = (ik.in_store(stc, in_member, in_gt)
                        | ik.in_store(sta, in_member, in_gt))
            in_h = in_probes = None
        else:
            in_store = ik.in_store(stc, in_member, in_gt)
        dup_in_batch = ik.dup_earlier(in_member, in_gt, in_ok)

        in_flags = jnp.zeros(in_gt.shape, jnp.uint8)
        if cfg.timeline_enabled:
            # The receive pipeline's check step (reference: dispersy.py
            # _on_batch_cache -> meta.check_callback -> timeline.py
            # Timeline.check).  Control records carry their own authority
            # rule; user records with a protected meta need a permit grant.
            founder = _founder_col(cfg, mem_base)[:, None]        # [N, 1]
            is_auth = in_meta == jnp.uint32(META_AUTHORIZE)
            is_rev = in_meta == jnp.uint32(META_REVOKE)
            is_undo_own = in_meta == jnp.uint32(META_UNDO_OWN)
            is_undo_other = in_meta == jnp.uint32(META_UNDO_OTHER)
            is_undo = is_undo_own | is_undo_other
            is_flip = in_meta == jnp.uint32(META_DYNAMIC)
            is_destroy = in_meta == jnp.uint32(META_DESTROY)
            is_ctrl = is_auth | is_rev | is_undo | is_flip | is_destroy
            # destroy: founder-only (the reference's master member signs
            # dispersy-destroy-community).  undo-own: the author undoes
            # itself.  authorize/revoke: founder, or a member holding the
            # AUTHORIZE/REVOKE authority bit for every meta in the grant
            # (chains — pass B below; reference: Timeline.check's
            # recursive proof walk).  undo-other: founder, or the UNDO
            # authority on the *target record's* meta; dynamic-settings:
            # founder, or the AUTHORIZE authority on the flipped meta —
            # both checked against the post-fold table below.
            ctrl_ok0 = jnp.where(is_undo_own, in_member == in_payload,
                                 in_member == founder)

            # Fold freshly learned authorize/revoke records FIRST: a grant
            # and a granted record arriving in one batch must accept (the
            # reference's batch handler processes authorize metas before
            # the messages they permit).  Pass A folds root (founder)
            # grants; pass B validates delegated grants against the
            # updated table and folds those — so a chain link folds one
            # level per round at worst, with Bloom re-offers carrying
            # deeper links across rounds (ops/timeline.check_grant doc).
            # Table rows keep their full nibble masks so folded grants
            # prove chains (the AUTHORIZE/REVOKE bits travel with them).
            fresh0 = in_ok & ~in_store & ~dup_in_batch
            user_bits = jnp.uint32(user_perm_mask(cfg.n_meta))
            grant_mask = in_aux & user_bits
            fr = tl.fold(auth, target=in_payload, mask=grant_mask,
                         gt=in_gt, is_revoke=is_rev,
                         valid=fresh0 & (is_auth | is_rev) & ctrl_ok0,
                         issuer=in_member)
            auth = fr.table
            deleg_ok = ((is_auth | is_rev) & ~ctrl_ok0
                        & jnp.where(
                            is_rev,
                            tl.check_grant(auth, in_member, grant_mask,
                                           in_gt, cfg.n_meta,
                                           perm=PERM_REVOKE),
                            tl.check_grant(auth, in_member, grant_mask,
                                           in_gt, cfg.n_meta,
                                           perm=PERM_AUTHORIZE)))
            fr2 = tl.fold(auth, target=in_payload, mask=grant_mask,
                          gt=in_gt, is_revoke=is_rev,
                          valid=fresh0 & deleg_ok, issuer=in_member)
            auth = fr2.table
            # Granted undo-other: the undoer holds the UNDO permission on
            # the target record's meta (resolved from the receiver's own
            # store; an absent target refuses this round and the Bloom
            # re-offer retries — reference: timeline.py checks u"undo"
            # against the target message's meta).  Granted flips: the
            # AUTHORIZE permission on the flipped meta stands in for the
            # reference's permit on the LinearResolution dynamic-settings
            # meta (authority over a meta's grants extends to its policy).
            undo_tmeta = ik.stored_meta_of(stc, in_payload, in_aux)
            undo_ok = (is_undo_other
                       & tl.check(auth, in_member, undo_tmeta, in_gt,
                                  founder, perm=PERM_UNDO))
            flip_grant_ok = (is_flip
                             & tl.check(auth, in_member, in_payload, in_gt,
                                        founder, perm=PERM_AUTHORIZE))
            ctrl_ok = ctrl_ok0 | deleg_ok | undo_ok | flip_grant_ok

            # LinearResolution check against the updated table.
            prot = jnp.uint32(cfg.protected_meta_mask)
            shift = jnp.minimum(in_meta, jnp.uint32(31))
            protected = (((prot >> shift) & 1) == 1) & (in_meta < 32)
            if cfg.dynamic_meta_mask:
                # DynamicResolution: the policy in force at the record's
                # own global_time is the highest-gt flip at or below it —
                # replayed from the store plus this batch's fresh flips
                # (reference: Timeline.get_resolution_policy walks the
                # stored dispersy-dynamic-settings chain).  A flip's
                # (gt, policy) packs into one sortable key gt*2 | policy.
                dynm = jnp.uint32(cfg.dynamic_meta_mask)
                is_dyn = ((((dynm >> shift) & 1) == 1)
                          & (in_meta < cfg.n_meta))
                best = _flip_best(stc, in_meta, in_gt)            # [N, B]
                flip_ok = (fresh0 & is_flip
                           & (ctrl_ok0 | flip_grant_ok))          # [N, B]
                best = jnp.maximum(best, ik.flip_best_batch(
                    flip_ok, in_payload, in_gt, in_aux, in_meta, in_gt))
                linear_now = jnp.where(best > 0, (best & 1) == 1, protected)
                protected = jnp.where(is_dyn, linear_now, protected)
            permitted = tl.check(auth, in_member, in_meta, in_gt, founder)
            if cfg.double_meta_mask & (cfg.protected_meta_mask
                                       | cfg.dynamic_meta_mask):
                # Both signers of a protected double-signed record need the
                # permit (reference: Timeline.check iterates every
                # authentication member of the message).
                permitted = permitted & jnp.where(
                    is_dbl, tl.check(auth, in_aux, in_meta, in_gt, founder),
                    True)
            accept = in_ok & jnp.where(
                is_ctrl, ctrl_ok, jnp.where(protected, permitted, True))
            if cfg.msg_requests:
                # DelayPacketByMissingMessage recast: a failing undo-other
                # parks (its named target — or the undoer's grant — may
                # still be in flight; phase 4m asks for the target by
                # name) instead of rejecting outright.
                undo_park = is_undo_other & in_ok & ~accept
            else:
                undo_park = jnp.zeros_like(accept)

            # Arriving records whose undo is already stored come in
            # pre-undone (the reference re-marks on re-insert attempts).
            pre_undone = ((in_meta < 32)
                          & ik.undo_marked(stc, in_member, in_gt))
            in_flags = jnp.where(pre_undone, jnp.uint8(FLAG_UNDONE),
                                 jnp.uint8(0))
            stats = stats.replace(
                msgs_dropped=stats.msgs_dropped
                + (fr.n_dropped + fr2.n_dropped
                   + fr.n_evicted + fr2.n_evicted).astype(jnp.uint32))
        else:
            accept = in_ok
            undo_park = jnp.zeros_like(accept)

        if cfg.identity_required:
            # Unknown-member gate (reference: member.py — no public key,
            # no verification; conversion.py DelayPacketByMissingMember):
            # USER records need the author's dispersy-identity record in
            # the receiver's store.  Control records stay exempt (their
            # authority is structural — SURVEY §7 stage 9).  Gated
            # records park via the pen's ~accept path (phase 4i actively
            # fetches the identity) or reject without one.
            have_id = ik.identity_stored(stc, in_member)
            needs_id = in_meta < cfg.n_meta
            id_ok = ~needs_id | have_id
            if cfg.double_meta_mask:
                # both signers must be known (Timeline.check iterates
                # every authentication member; same for identity)
                id_ok = id_ok & jnp.where(
                    is_dbl, ik.identity_stored(stc, in_aux), True)
            accept = accept & id_ok

        if cfg.seq_meta_mask:
            # enable_sequence_number intake: a sequenced record is accepted
            # only when it chains directly onto the highest sequence this
            # peer holds for its (member, meta) — gaps wait for the Bloom
            # pull to re-offer the missing link (the round-synchronous
            # dispersy-missing-sequence; reference: message.py
            # DelayMessageBySequence + community.py on_missing_sequence).
            shm = jnp.minimum(in_meta, jnp.uint32(31))
            is_seq = ((((jnp.uint32(cfg.seq_meta_mask) >> shm) & 1) == 1)
                      & (in_meta < cfg.n_meta))
            # Re-deliveries of already-stored records bypass the chain test
            # (they are plain dups, handled by the UNIQUE insert).
            seq_check = is_seq & ~in_store
            stored_max = ik.seq_stored_max(stc, in_member, in_meta)

            def seq_body(j, carry):
                acc_max, ok = carry
                aux_j = lax.dynamic_index_in_dim(in_aux, j, 1, False)  # [N]
                chain = aux_j == lax.dynamic_index_in_dim(
                    acc_max, j, 1, False) + 1
                chk_j = lax.dynamic_index_in_dim(seq_check, j, 1, False)
                ok_j = jnp.where(chk_j, chain, True)
                ok = lax.dynamic_update_index_in_dim(ok, ok_j, j, 1)
                took = (lax.dynamic_index_in_dim(accept, j, 1, False)
                        & chk_j & chain)
                grp = ((in_member == lax.dynamic_index_in_dim(
                            in_member, j, 1)[:, :1])
                       & (in_meta == lax.dynamic_index_in_dim(
                           in_meta, j, 1)[:, :1]))
                acc_max = jnp.where(grp & took[:, None],
                                    jnp.maximum(acc_max, aux_j[:, None]),
                                    acc_max)
                return acc_max, ok

            _, seq_ok = lax.fori_loop(
                0, bb, seq_body, (stored_max, jnp.ones_like(accept)))
        else:
            seq_ok = jnp.ones_like(accept)

        if cfg.delay_enabled:
            # DelayMessageByProof — and, with config.seq_requests,
            # DelayMessageBySequence: a non-control record that failed
            # ONLY the permission check (for a control record ~accept
            # means a forged authority — never delayable), or only the
            # sequence-chain check, is not already covered (stored, or a
            # dup of an earlier batch entry), and has not exceeded its
            # waiting time, parks in the pen instead of being rejected.
            # First-fit into the bounded pen; overflow rejects like the
            # reference's delay-queue cap.
            gap_wait = ((accept & ~seq_ok) if cfg.seq_requests
                        else jnp.zeros_like(accept))
            waiting = (in_ok & (~is_ctrl | undo_park)
                       & (~accept | gap_wait) & ~in_store
                       & ~dup_in_batch
                       & (rnd - in_since
                          < jnp.uint32(cfg.delay_timeout_rounds)))
            drank = jnp.cumsum(waiting.astype(jnp.int32), axis=1) - 1
            parked = waiting & (drank < cfg.delay_inbox)
        else:
            parked = jnp.zeros_like(accept)
        accept = accept & seq_ok
        if cfg.timeline_enabled or cfg.seq_meta_mask or cfg.identity_required:
            stats = stats.replace(
                msgs_rejected=stats.msgs_rejected
                + jnp.sum(in_ok & ~accept & ~parked,
                          axis=1).astype(jnp.uint32))

        if cfg.direct_meta_mask:
            # DirectDistribution receipt: counted, never stored, never
            # re-forwarded (reference: distribution.py DirectDistribution —
            # one-shot delivery outside the sync store).
            shm = jnp.minimum(in_meta, jnp.uint32(31))
            is_direct = ((((jnp.uint32(cfg.direct_meta_mask) >> shm) & 1)
                          == 1) & (in_meta < cfg.n_meta))
            stats = stats.replace(
                msgs_direct=stats.msgs_direct
                + jnp.sum(accept & is_direct, axis=1).astype(jnp.uint32))
            accept_store = accept & ~is_direct
        else:
            accept_store = accept

        fresh = accept_store & ~in_store & ~dup_in_batch          # [N, B]
        # Per-meta acceptance counters (statistics.py per-message-name
        # success counts): fresh stored records plus direct receipts;
        # control metas share the last bucket.
        counted = fresh
        if cfg.direct_meta_mask:
            counted = fresh | (accept & is_direct)
        bucket = jnp.where(in_meta < cfg.n_meta, in_meta,
                           cfg.n_meta).astype(jnp.int32)          # [N, B]
        contrib = jnp.sum(
            (bucket[:, :, None] == jnp.arange(cfg.n_meta + 1)[None, None, :])
            & counted[:, :, None], axis=1).astype(jnp.uint32)     # [N, K+1]
        stats = stats.replace(
            accepted_by_meta=stats.accepted_by_meta + contrib)
        if diet:
            # Byte-diet landing: fresh records append to the staging
            # buffer in delivery order (O(S+B) — no ring rewrite);
            # duplicates and staging overflow are counted where the
            # legacy merge counted its dup/overflow kills.  msgs_stored
            # is counted at compaction, when records actually enter the
            # ring (store_insert's n_inserted — so the counter keeps
            # its legacy meaning of "records the ring accepted").
            with jax.named_scope("store_stage"):
                stg = st.store_stage(
                    sta,
                    st.StoreCols(gt=in_gt, member=in_member, meta=in_meta,
                                 payload=in_payload, aux=in_aux,
                                 flags=in_flags),
                    new_mask=fresh)
            sta = stg.staging
            stats = stats.replace(
                msgs_dropped=stats.msgs_dropped
                + jnp.sum(accept_store & ~fresh,
                          axis=1).astype(jnp.uint32)
                + stg.n_dropped.astype(jnp.uint32))
            if cfg.sync_enabled and (stagger or not compact_now):
                # Incremental digest: OR the landed arrivals' probe
                # bits in, so next round's claim (and freshness test)
                # covers them.  Compaction rounds rebuild instead.
                # Under cohort staggering the update runs EVERY round
                # (salt = the per-peer epoch): the inactive cohorts
                # must keep absorbing arrivals on another cohort's
                # sync round, and the active cohort's rows are
                # rebuilt—and overwritten—by its compaction below.
                with jax.named_scope("digest_update"):
                    if in_probes is not None:
                        dig = bloom.digest_update(dig, in_probes,
                                                  stg.landed,
                                                  cfg.bloom_bits)
                    else:
                        dig = dig | bloom.bloom_build(
                            in_h, stg.landed, cfg.bloom_bits,
                            cfg.bloom_hashes, salt=ep)
        else:
            with jax.named_scope("store_merge"):
                ins = st.store_insert(
                    stc,
                    st.StoreCols(gt=in_gt, member=in_member, meta=in_meta,
                                 payload=in_payload, aux=in_aux,
                                 flags=in_flags),
                    new_mask=accept_store, history=cfg.history)
            stc = ins.store
        global_time = _fold_gt(global_time, in_gt, accept,
                               cfg.acceptable_global_time_range)
        if not diet:
            stats = stats.replace(
                msgs_stored=stats.msgs_stored
                + ins.n_inserted.astype(jnp.uint32),
                msgs_dropped=stats.msgs_dropped
                + ins.n_dropped.astype(jnp.uint32)
                + ins.n_evicted.astype(jnp.uint32))

        if trace_on:
            # ---- dissemination lineage (traceplane.py) -------------
            # Fold this batch into each tracked slot: the channel is
            # static per batch SEGMENT (the config gate guarantees the
            # only populated segments are sync pulls, pushes, and their
            # fault duplicates — flood junk never survives the hash
            # check, so CH_FLOOD stays structurally zero).  Landing is
            # staging-aware: under the byte diet an arrival counts
            # where it took a staging slot (store_stage's landed mask);
            # the legacy path counts accepted-fresh arrivals (a ring-
            # capacity drop at insert still counts — arrival history).
            ln_landed = stg.landed if diet else fresh
            import numpy as np
            seg_codes = [0, trp.CH_WALK_SYNC, trp.CH_PUSH,
                         0, 0, 0, 0, 0]
            if kn.dup_on:
                seg_codes += [trp.CH_WALK_SYNC, trp.CH_PUSH]
            chan_code = jnp.asarray(np.concatenate(
                [np.full(seg.shape[1], code, np.uint8)
                 for seg, code in zip(segs_gt, seg_codes)]), jnp.uint8)
            with jax.named_scope("trace_lineage"):
                tf_cols, tc_cols, td_cols = [], [], []
                u_acc = jnp.zeros((n, trp.NUM_CHANNELS), jnp.uint32)
                d_acc = jnp.zeros((n, trp.NUM_CHANNELS), jnp.uint32)
                for k in range(cfg.trace.tracked_slots):
                    match = ((in_member == state.trace_member[k])
                             & (in_gt == state.trace_gt[k]))
                    f_k, c_k, d_k, ubc, dbc = trc.slot_lineage(
                        tr_first[:, k], tr_chan[:, k], tr_dups[:, k],
                        match, ln_landed, accept_store, chan_code,
                        rnd + jnp.uint32(1))
                    tf_cols.append(f_k)
                    tc_cols.append(c_k)
                    td_cols.append(d_k)
                    u_acc = u_acc + ubc
                    d_acc = d_acc + dbc
                tr_first = jnp.stack(tf_cols, axis=1)
                tr_chan = jnp.stack(tc_cols, axis=1)
                tr_dups = jnp.stack(td_cols, axis=1)
                stats = stats.replace(
                    trace_delivered=stats.trace_delivered + u_acc,
                    trace_dup=stats.trace_dup + d_acc)

        if cfg.timeline_enabled:
            # Apply this batch's accepted undo records to the (post-insert)
            # store, so an undo and its target landing together still mark
            # (reference: community.py on_undo sets the sync row's `undone`).
            # Control rows are never markable — the reference forbids
            # undoing dispersy-* metas.
            batch_undo = accept & is_undo
            hit = ik.undo_hits_store(stc, in_payload, in_aux, batch_undo)
            hit = hit & (stc.meta < 32)
            stc = stc._replace(flags=jnp.where(
                hit, stc.flags | jnp.uint8(FLAG_UNDONE), stc.flags))

        if cfg.malicious_enabled and cfg.malicious_gossip:
            # The eyewitness authors its dispersy-malicious-proof record
            # now — after the batch landed and the clock folded, exactly
            # like an application create in the same round (reference:
            # dispersy.py authors the proof message on conviction).  One
            # record per round: the first fresh conviction (gospick).
            g_gt_new = global_time + jnp.uint32(1)
            gins = st.store_insert(
                stc,
                st.StoreCols(
                    gt=g_gt_new[:, None],
                    member=idx.astype(jnp.uint32)[:, None],
                    meta=jnp.full((n, 1), META_MALICIOUS, jnp.uint8),
                    payload=g_member[:, None], aux=g_gt[:, None],
                    flags=jnp.zeros((n, 1), jnp.uint8)),
                new_mask=gossip_now[:, None], history=cfg.history)
            stc = gins.store
            global_time = jnp.where(gossip_now, g_gt_new, global_time)
            stats = stats.replace(
                msgs_stored=stats.msgs_stored
                + gins.n_inserted.astype(jnp.uint32),
                msgs_dropped=stats.msgs_dropped
                + (gins.n_dropped + gins.n_evicted).astype(jnp.uint32),
                accepted_by_meta=stats.accepted_by_meta
                .at[:, cfg.n_meta].add(gossip_now.astype(jnp.uint32)))

        # Next round's forward batch = F fresh records of this batch.
        # With a timeline or mixed priorities, the F slots go to the
        # HIGHEST-priority fresh records (ties by delivery order) so a
        # control record (authorize / dynamic-settings / destroy, at
        # CONTROL_PRIORITY) cannot lose its only push to bulk records —
        # the bounded-buffer form of the reference's priority field.
        fb = cfg.forward_buffer
        if cfg.needs_priority_forward:
            assert bb < 4096
            fprio = _priority_vec(cfg, in_meta)
            okey = jnp.where(
                fresh,
                (jnp.uint32(255) - fprio) * jnp.uint32(4096)
                + jnp.arange(bb, dtype=jnp.uint32),
                jnp.uint32(EMPTY_U32))
            rank = jnp.sum((okey[:, None, :] < okey[:, :, None])
                           & fresh[:, None, :], axis=-1)
        else:
            rank = jnp.cumsum(fresh.astype(jnp.int32), axis=1) - 1
        fslot = jnp.where(fresh & (rank < fb), rank, fb)
        # The buffer's aux column persists at the (possibly narrowed)
        # store width — the store_insert truncation rule, applied at
        # the buffer boundary so pushed records match what stored.
        fwd_aux_src = (in_aux if cfg.aux_dtype == "uint32"
                       else in_aux.astype(cfg.aux_dtype))
        fwd = tuple(st.rank_compact_many(
            [(col, st.empty_of(col.dtype))
             for col in (in_gt, in_member, in_meta, in_payload,
                         fwd_aux_src)],
            fslot, fb))
        if cfg.malicious_enabled and cfg.malicious_gossip and fb > 0:
            # The authored proof record claims a forward slot the way
            # create_messages does: first free, displacing the newest
            # relayed entry when full (the conviction must not lose its
            # only push to relay traffic).
            gput = jnp.minimum(st.count_valid(fwd[0]), fb - 1)
            rowsg = jnp.arange(n)

            def gbuf(cur, val):
                return cur.at[rowsg, gput].set(
                    jnp.where(gossip_now, val, cur[rowsg, gput]),
                    mode="drop")
            fwd = (gbuf(fwd[0], g_gt_new),
                   gbuf(fwd[1], idx.astype(jnp.uint32)),
                   gbuf(fwd[2], jnp.full((n,), META_MALICIOUS, jnp.uint8)),
                   gbuf(fwd[3], g_member),
                   gbuf(fwd[4], g_gt))

        if cfg.delay_enabled:
            # Rebuild the pen from this batch's parked records (waiting
            # pen entries re-park with their original since; newly
            # delayed records stamp this round).
            dd = cfg.delay_inbox
            dslot = jnp.where(parked, drank, dd)
            dly = tuple(st.rank_compact_many(
                [(in_gt, EMPTY_U32), (in_member, EMPTY_U32),
                 (in_meta, EMPTY_META), (in_payload, EMPTY_U32),
                 (in_aux, 0), (in_since, 0), (in_src, NO_PEER)],
                dslot, dd))
            stats = stats.replace(
                msgs_delayed=stats.msgs_delayed
                + jnp.sum(parked & (in_since == rnd),
                          axis=1).astype(jnp.uint32))

        if cfg.timeline_enabled:
            # Retroactive re-walk whenever a fresh revoke folded — or a
            # table EVICTION displaced a row — ANYWHERE this round (a
            # scalar trigger; lax.cond skips the pass entirely on quiet
            # rounds, which is nearly all of them).  Revokes and
            # evictions are the two folds that can invalidate
            # already-accepted state; grant inserts only ever add
            # authority, so tables stay chain-consistent in between.
            # See _retro_pass (reference: timeline.py lazy re-validation).
            rev_folded = (jnp.any(fresh0 & is_rev & (ctrl_ok0 | deleg_ok))
                          | jnp.any((fr.n_evicted + fr2.n_evicted) > 0))
            auth, stc, n_unw, n_ret = lax.cond(
                rev_folded,
                lambda a, s: _retro_pass(a, s, cfg, founder[:, 0]),
                lambda a, s: (a, s, jnp.zeros((n,), jnp.int32),
                              jnp.zeros((n,), jnp.int32)),
                auth, stc)
            stats = stats.replace(
                auth_unwound=stats.auth_unwound + n_unw.astype(jnp.uint32),
                msgs_retro=stats.msgs_retro + n_ret.astype(jnp.uint32))
    else:
        e0 = jnp.full((n, cfg.forward_buffer), EMPTY_U32, jnp.uint32)
        fwd = (e0, e0,
               jnp.full((n, cfg.forward_buffer), EMPTY_META, jnp.uint8),
               e0,
               jnp.full((n, cfg.forward_buffer),
                        st.empty_of(cfg.aux_dtype), cfg.aux_dtype))

    if compact_now and stagger:
        # ---- cohort-staggered compaction (storediet.py, PR 20): the
        # ACTIVE cohort's N/cohorts block — and only it — runs the
        # PR-12 compaction verbatim: staging merges into the ring
        # (store_insert semantics unchanged), staging clears, digest
        # rebuilds under the cohort's NEXT epoch salt, and the
        # cohort's epoch leaf bumps.  Block extraction is a reshape +
        # dynamic-slice on the non-peer axis (ops/store.cohort_take) —
        # zero cross-shard bytes, and the round's ring-rewrite cost
        # drops to 1/cohorts of the fleet-synchronized spike. ---
        coh = cfg.store.cohorts
        blk = n // coh
        with jax.named_scope("store_compact"):
            stc_blk = st.cohort_take_cols(stc, a_coh, coh)
            sta_blk = st.cohort_take_cols(sta, a_coh, coh)
            ins = st.store_insert(stc_blk, sta_blk, sta_blk.valid,
                                  history=cfg.history)
            stc = st.cohort_put_cols(stc, ins.store, a_coh, coh)
            sta = st.cohort_put_cols(
                sta, st.empty_records((blk,) + sta.gt.shape[1:],
                                      aux_dtype=sta.aux.dtype),
                a_coh, coh)

        def _coh_add(full, delta):
            return st.cohort_put(
                full, st.cohort_take(full, a_coh, coh)
                + delta.astype(jnp.uint32), a_coh, coh)

        stats = stats.replace(
            msgs_stored=_coh_add(stats.msgs_stored, ins.n_inserted),
            msgs_dropped=_coh_add(stats.msgs_dropped,
                                  ins.n_dropped.astype(jnp.uint32)
                                  + ins.n_evicted.astype(jnp.uint32)))
        with jax.named_scope("digest_rebuild"):
            sl_n = st.claim_slice_largest(ins.store.gt,
                                          cfg.bloom_capacity)
            in_sl_n = st.slice_mask(ins.store.gt, sl_n)
            rh_n = record_hash(ins.store.member, ins.store.gt,
                               ins.store.meta, ins.store.payload)
            if bloom.gather_backend():
                dig_blk = bloom.bloom_build_from(
                    bloom.probe_bits(rh_n, cfg.bloom_bits,
                                     cfg.bloom_hashes,
                                     salt=ep_a + jnp.uint32(1)),
                    in_sl_n, cfg.bloom_bits,
                    chunks=cfg.parallel.scatter_chunks)
            else:
                dig_blk = bloom.bloom_build(rh_n, in_sl_n,
                                            cfg.bloom_bits,
                                            cfg.bloom_hashes,
                                            salt=ep_a + jnp.uint32(1))
            dig = st.cohort_put(dig, dig_blk, a_coh, coh)
        # The compaction closes the cohort's epoch: its per-peer salt
        # advances to the one the rebuilt digest was just built with
        # (the round-start invariant
        # ``epoch[p] == epoch_of_cohort(cfg, rnd, cohort[p])`` holds at
        # rnd + 1 exactly because only the active cohort's quotient
        # increments across this round boundary).
        epoch = epoch + (state.cohort.astype(jnp.uint32)
                         == a_coh).astype(jnp.uint32)
    elif compact_now:
        # ---- byte-diet compaction (storediet.py): merge the staging
        # buffer — this round's arrivals included — into the sorted
        # ring through the unchanged store_insert (UNIQUE / LastSync /
        # capacity semantics all apply here), clear the staging, and
        # rebuild the digest from the fresh ring under the NEXT epoch's
        # salt.  This is the only ring rewrite of the whole window. ---
        with jax.named_scope("store_compact"):
            ins = st.store_insert(stc, sta, sta.valid,
                                  history=cfg.history)
        stc = ins.store
        sta = st.empty_records(sta.gt.shape, aux_dtype=sta.aux.dtype)
        stats = stats.replace(
            msgs_stored=stats.msgs_stored
            + ins.n_inserted.astype(jnp.uint32),
            msgs_dropped=stats.msgs_dropped
            + ins.n_dropped.astype(jnp.uint32)
            + ins.n_evicted.astype(jnp.uint32))
        if cfg.sync_enabled:
            with jax.named_scope("digest_rebuild"):
                sl_n = st.claim_slice_largest(stc.gt, cfg.bloom_capacity)
                in_sl_n = st.slice_mask(stc.gt, sl_n)
                rh_n = record_hash(stc.member, stc.gt, stc.meta,
                                   stc.payload)
                if bloom.gather_backend():
                    dig = bloom.bloom_build_from(
                        bloom.probe_bits(rh_n, cfg.bloom_bits,
                                         cfg.bloom_hashes,
                                         salt=ep + jnp.uint32(1)),
                        in_sl_n, cfg.bloom_bits,
                        chunks=cfg.parallel.scatter_chunks)
                else:
                    dig = bloom.bloom_build(rh_n, in_sl_n,
                                            cfg.bloom_bits,
                                            cfg.bloom_hashes,
                                            salt=ep + jnp.uint32(1))

    # ---- wrap up --------------------------------------------------------
    if cfg.malicious_enabled:
        # Eject convicted members from the candidate table: the walker
        # must not keep visiting a provably malicious peer (reference:
        # candidates of malicious members are dropped).  Guarded on real
        # slots — the EMPTY_U32 sentinel casts to NO_PEER in int32.
        bad = (tab.peer != NO_PEER) & jnp.any(
            tab.peer[:, :, None] == mal.astype(jnp.int32)[:, None, :],
            axis=-1)
        tab = cand.CandTable(
            peer=jnp.where(bad, NO_PEER, tab.peer),
            last_walk=jnp.where(bad, NEVER, tab.last_walk),
            last_stumble=jnp.where(bad, NEVER, tab.last_stumble),
            last_intro=jnp.where(bad, NEVER, tab.last_intro))
    if cfg.auto_load:
        # Any community packet that reached an unloaded peer loads its
        # instance for the next round (define_auto_load semantics).
        loaded = loaded | (arrivals & alive)
    if fm.health_checks:
        # On-device health sentinels (faults.HEALTH_*): latched into the
        # `health` bitmask — graceful degradation (saturate, drop, flag)
        # instead of silent corruption.  The host-side deep checker is
        # faults.debug_validate; metrics.snapshot surfaces the counts.
        hb = jnp.zeros((n,), jnp.uint32)
        wrapped = (((stats.bytes_up + bup) < stats.bytes_up)
                   | ((stats.bytes_down + bdown) < stats.bytes_down))
        hb = hb | jnp.where(wrapped, jnp.uint32(HEALTH_COUNTER_WRAP),
                            jnp.uint32(0))
        hb = hb | jnp.where(
            flt.store_invariant_violated(stc.gt, stc.member),
            jnp.uint32(HEALTH_STORE_INVARIANT), jnp.uint32(0))
        if diet and cfg.store.staging >= 2:
            # Staging valid-prefix invariant (storediet.py): a hole
            # before a live record means a corrupted append — same
            # sentinel bit as the ring's sort/unique/holes check.
            stag_bad = jnp.any(
                (sta.gt[:, :-1] == jnp.uint32(EMPTY_U32))
                & (sta.gt[:, 1:] != jnp.uint32(EMPTY_U32)), axis=1)
            hb = hb | jnp.where(stag_bad,
                                jnp.uint32(HEALTH_STORE_INVARIANT),
                                jnp.uint32(0))
        drop_delta = (stats.requests_dropped
                      + stats.msgs_dropped) - rd0      # u32, wrap-safe
        hb = hb | jnp.where(
            drop_delta >= jnp.uint32(fm.health_drop_limit),
            jnp.uint32(HEALTH_INBOX_DROP), jnp.uint32(0))
        if cfg.sync_enabled:
            # Under the byte-diet the live claim view is the digest
            # (updated this round) — my_bloom is only materialized on
            # sync rounds.
            fill = jnp.sum(flt.popcount_u32(dig if diet else my_bloom),
                           axis=1)
            hb = hb | jnp.where(
                fill * jnp.uint32(8) >= jnp.uint32(cfg.bloom_bits * 7),
                jnp.uint32(HEALTH_BLOOM_SAT), jnp.uint32(0))
        health_pre = health    # pre-latch view: the flight recorder
        #   captures bits that latch THIS round (health & ~health_pre)
        health = health | hb
    if rc.enabled:
        # ---- recovery pass (dispersy_tpu/recovery.py; RECOVERY.md) --
        # Staged repair of the latched sentinels.  Bits visible since a
        # PREVIOUS round (``prev``) are acted on and CLEARED here; this
        # round's fresh latches (``hb``) stay visible for at least one
        # telemetry row.  The *verify* half of detect->repair->verify
        # is the sentinel itself: a persistent condition re-latches the
        # same round it was repaired, and a re-latch within
        # ``requarantine_window`` of the last repair escalates to a
        # quarantined wiped-disk rebirth (hysteresis — no repair flap).
        # Config guarantees fm.health_checks here, so hb/health_pre
        # exist.
        rpost = rnd + jnp.uint32(1)
        prev = health_pre
        prev_on = prev != jnp.uint32(0)
        if rc.quarantine_rounds > 0:
            esc = (prev_on & (repair_round > jnp.uint32(0))
                   & (rpost - repair_round
                      <= jnp.uint32(rc.requarantine_window)))
        else:
            esc = jnp.zeros((n,), bool)
        rep = (prev_on & ~esc) if rc.soft_repair \
            else jnp.zeros((n,), bool)
        bump = jnp.zeros((n,), bool)
        # Store-touching repairs — the (1a) invariant re-sort and the
        # (3) quarantine wipe — run behind ONE lax.cond (the
        # _retro_pass idiom): both fire rarely (the invariant sentinel
        # is a bug detector; escalations need a re-latch inside the
        # hysteresis window), so quiet rounds skip the recovery pass's
        # only store-wide kernels entirely.  Cost analysis still sums
        # the untaken branch (BENCH.md's recovery entry notes this);
        # the runtime cost of a quiet round is the cond's predicate.
        rep_store = (rep & ((prev & jnp.uint32(HEALTH_STORE_INVARIANT))
                            != 0)) if rc.soft_repair \
            else jnp.zeros((n,), bool)

        def _store_recover(s):
            stc_, sta_, dig_ = s
            if rc.soft_repair:
                stc_ = rcv.store_repair(stc_, rep_store)
            if rc.quarantine_rounds > 0:
                em = esc[:, None]
                stc_ = _wipe_store_cols(em, stc_)
                if diet:
                    # A quarantine escalation is a wiped-DISK rebirth:
                    # the staging buffer and digest are the store's
                    # write buffer / claim view and wipe with the ring.
                    sta_ = _wipe_store_cols(em, sta_)
                    if cfg.sync_enabled:
                        dig_ = jnp.where(em, jnp.uint32(0), dig_)
            return stc_, sta_, dig_
        # sta/dig are None (empty pytree leaves) without their planes;
        # the cond carries them untouched in that case.
        stc, sta, dig = lax.cond(
            jnp.any(rep_store) | jnp.any(esc),
            _store_recover, lambda s: s, (stc, sta, dig))
        if rc.soft_repair:
            # (1b) candidate-table flush for the overload sentinel:
            # evict the entries implicated by the drop deltas (the
            # flood/overload source set) and re-walk from the trackers.
            rep_inbox = rep & ((prev & jnp.uint32(HEALTH_INBOX_DROP))
                               != 0)
            ri = rep_inbox[:, None]
            tab = cand.CandTable(
                peer=jnp.where(ri, NO_PEER, tab.peer),
                last_walk=jnp.where(ri, NEVER, tab.last_walk),
                last_stumble=jnp.where(ri, NEVER, tab.last_stumble),
                last_intro=jnp.where(ri, NEVER, tab.last_intro))
            # (2) exponential walk-retry backoff bump on drop-limit
            # trips (HEALTH_BLOOM_SAT / HEALTH_COUNTER_WRAP repairs
            # clear only — the claimed Bloom re-randomizes per round
            # and a wrapped counter cannot un-wrap).
            if rc.backoff_limit > 0:
                bump = rep_inbox & (backoff < jnp.uint8(rc.backoff_limit))
                backoff = backoff + bump.astype(jnp.uint8)
            repair_round = jnp.where(rep, rpost, repair_round)
        if rc.quarantine_rounds > 0:
            # (3) quarantine escalation: deterministic wiped-disk
            # rebirth (the churn-rebirth wipe — store, candidates, auth
            # table, pen, caches, clock; session bumped) + neighbor
            # exclusion below for quarantine_rounds rounds.  The wipe
            # is the SAME _rebirth_wipe the churn block calls (one
            # inventory — only `loaded`/`health`/`ge_bad`/recovery-leaf
            # handling differs per caller); the oracle's esc branch is
            # the mirror to keep in lockstep.
            # (store wipe handled in _store_recover's cond above —
            # wipe_store=False)
            (tab, stc, fwd, dly, auth, sig, mal, global_time,
             session, _, _) = _rebirth_wipe(
                esc, tab=tab, stc=stc, fwd=fwd, dly=dly, auth=auth,
                sig=sig, mal=mal, global_time=global_time,
                session=session, wipe_store=False)
            backoff = jnp.where(esc, jnp.uint8(0), backoff)
            repair_round = jnp.where(esc, jnp.uint32(0), repair_round)
            quar_until = jnp.where(
                esc, rpost + jnp.uint32(rc.quarantine_rounds),
                quar_until)
        # Clear the latch: repaired peers keep only this round's fresh
        # bits; escalated peers restart with a clean (wiped) slate.
        cleared = (jnp.where(rep, prev, jnp.uint32(0))
                   | jnp.where(esc, prev | hb, jnp.uint32(0)))
        health = jnp.where(esc, jnp.uint32(0),
                           jnp.where(rep, hb, health))
        if rc.backoff_limit > 0:
            # Backoff decay on clean rounds (nothing latched at all),
            # at the traced-liftable ``backoff_decay`` rate — one
            # counter draw per peer, so the oracle replays it exactly.
            ud = rng.rand_uniform(seed, rnd, idx, rng.P_RECOVERY)
            dec = ((~(prev_on | (hb != jnp.uint32(0))))
                   & (backoff > jnp.uint8(0))
                   & (ud < jnp.float32(knr.backoff_decay)))
            backoff = backoff - dec.astype(jnp.uint8)
        if rc.quarantine_rounds > 0:
            # Neighbors eject quarantined peers from their candidate
            # tables every wrap-up (PeerSwap-style targeted eviction):
            # with the quarantined peer also not walking, it cannot
            # stumble back in until its release round.
            safe = jnp.clip(tab.peer, 0, n - 1)
            qbad = ((tab.peer != NO_PEER)
                    & rcv.quarantine_active(rpost, quar_until)[safe])
            tab = cand.CandTable(
                peer=jnp.where(qbad, NO_PEER, tab.peer),
                last_walk=jnp.where(qbad, NEVER, tab.last_walk),
                last_stumble=jnp.where(qbad, NEVER, tab.last_stumble),
                last_intro=jnp.where(qbad, NEVER, tab.last_intro))
        if trace_on and rc.quarantine_rounds > 0:
            # A quarantine escalation is a wiped-disk rebirth: the
            # lineage rows wipe with the store (traceplane.py; the
            # churn block's rule, mirrored by the oracle's esc branch).
            em = esc[:, None]
            tr_first = jnp.where(em, jnp.uint32(0), tr_first)
            tr_chan = jnp.where(em, jnp.uint8(0), tr_chan)
            tr_dups = jnp.where(em, jnp.uint32(0), tr_dups)
        stats = stats.replace(
            recov_soft=stats.recov_soft + rep.astype(jnp.uint32),
            recov_backoff=stats.recov_backoff + bump.astype(jnp.uint32),
            recov_quarantine=stats.recov_quarantine
            + esc.astype(jnp.uint32),
            recov_cleared=stats.recov_cleared + jnp.stack(
                [(cleared >> jnp.uint32(b)) & jnp.uint32(1)
                 for b in range(NUM_HEALTH_BITS)], axis=1))
    # Fold the round's byte totals before telemetry packs the row — the
    # row must equal what snapshot() sees on the returned state.
    stats = stats.replace(bytes_up=stats.bytes_up + bup,
                          bytes_down=stats.bytes_down + bdown)
    new_time = now + jnp.float32(cfg.walk_interval)

    # ---- dissemination coverage + percentile latches (traceplane.py;
    # AFTER the recovery wipes so the counts reflect the returned
    # state, BEFORE the telemetry row packs them) --------------------
    if trace_on:
        with jax.named_scope("trace_coverage"):
            tr_members = alive & ~state.is_tracker
            tr_cov = trc.coverage_counts(tr_first, tr_members)
            tr_latch = trc.latch_update(
                tr_latch, tr_cov,
                state.trace_member != jnp.uint32(EMPTY_U32),
                jnp.sum(tr_members, dtype=jnp.int32).astype(jnp.uint32),
                rnd + jnp.uint32(1))
    else:
        tr_cov = None

    # ---- telemetry wrap-up (dispersy_tpu/telemetry.py; every branch is
    # gated on static TelemetryConfig knobs, so disabled telemetry
    # compiles to the identical step — the faults pattern) -------------
    tele_row, tele_ring = state.tele_row, state.tele_ring
    fr_ring, fr_pos = state.fr_ring, state.fr_pos
    if cfg.telemetry.enabled:
        members = alive & ~state.is_tracker
        store_cnt = st.count_valid(stc.gt).astype(jnp.uint32)
        if diet:
            # The logical store is ring ∪ staging (storediet.py).
            store_cnt = store_cnt + st.count_valid(sta.gt).astype(
                jnp.uint32)
        cand_cnt = jnp.sum(tab.peer != NO_PEER, axis=1,
                           dtype=jnp.int32).astype(jnp.uint32)
        if cfg.telemetry.histograms or cfg.telemetry.flight_recorder:
            # This round's dropped packets/records (u32 wrap-safe).
            drop_delta = (stats.requests_dropped + stats.msgs_dropped) - rd0
        if cfg.telemetry.histograms:
            ones = jnp.ones((n,), bool)
            if cfg.sync_enabled:
                bloom_cnt = jnp.sum(
                    flt.popcount_u32(dig if diet else my_bloom), axis=1,
                    dtype=jnp.uint32)
                bloom_mask = ones
            else:
                bloom_cnt = jnp.zeros((n,), jnp.uint32)
                bloom_mask = jnp.zeros((n,), bool)
            # Histogram inputs; masks per telemetry.hist_specs.
            hists = {
                "store_fill": (store_cnt, ones),
                "cand_fill": (cand_cnt, members),
                "req_inbox": (n_rq, ~state.is_tracker),
                "round_drops": (drop_delta, ones),
                "bloom_fill": (bloom_cnt, bloom_mask),
                "walk_streak": (walk_streak, members),
            }
        else:
            hists = None
        with jax.named_scope("telemetry_row"):
            tele_row = _telemetry_row(cfg, rnd=rnd, new_time=new_time,
                                      members=members, stats=stats,
                                      stc=stc, health=health,
                                      store_cnt=store_cnt,
                                      cand_cnt=cand_cnt, hists=hists,
                                      bucket=bucket_new,
                                      trace_cov=tr_cov,
                                      trace_latch=tr_latch)
        if cfg.telemetry.history:
            # Post-step round r+1 lands at slot r % H; the row's own
            # round word identifies the slot at drain time.
            slot_r = (rnd % jnp.uint32(cfg.telemetry.history)).astype(
                jnp.int32)
            tele_ring = state.tele_ring.at[slot_r].set(tele_row,
                                                       mode="drop")
        if cfg.telemetry.flight_recorder:
            # Config-validated: the recorder requires health_checks, so
            # hb/health_pre exist.  Record the first flight_per_round
            # peers whose sentinel NEWLY latched this round.
            newly = hb & ~health_pre
            is_new = newly != jnp.uint32(0)
            fpr = cfg.telemetry.flight_per_round
            frank = jnp.cumsum(is_new.astype(jnp.int32)) - 1
            frslot = jnp.where(is_new & (frank < fpr), frank, fpr)

            def fsel(col, fill):
                return st.rank_compact(col[None, :], frslot[None, :],
                                       fpr, fill)[0]
            recs = jnp.stack(
                [fsel(idx.astype(jnp.uint32), EMPTY_U32),
                 fsel(jnp.broadcast_to(rnd + jnp.uint32(1), (n,)), 0),
                 fsel(newly, 0),
                 fsel(health, 0),
                 fsel(stats.requests_dropped, 0),
                 fsel(stats.msgs_dropped, 0),
                 fsel(drop_delta, 0),
                 fsel(store_cnt, 0)], axis=1)   # [fpr, FLIGHT_WIDTH]
            fvalid = recs[:, 0] != jnp.uint32(EMPTY_U32)
            fr_ring, fr_pos = tele.flight_append(
                state.fr_ring, state.fr_pos, recs, fvalid)
    return state.replace(
        alive=alive, loaded=loaded, session=session,
        global_time=global_time, health=health, ge_bad=ge_bad,
        backoff=backoff, quar_until=quar_until,
        repair_round=repair_round, bucket=bucket_new,
        walk_streak=walk_streak, tele_row=tele_row, tele_ring=tele_ring,
        fr_ring=fr_ring, fr_pos=fr_pos,
        mal_member=mal,
        cand_peer=tab.peer, cand_last_walk=_cand_quant(tab.last_walk, cfg),
        cand_last_stumble=_cand_quant(tab.last_stumble, cfg),
        cand_last_intro=_cand_quant(tab.last_intro, cfg),
        store_gt=stc.gt, store_member=stc.member, store_meta=stc.meta,
        store_payload=stc.payload, store_aux=stc.aux, store_flags=stc.flags,
        **({} if not diet else {
            "sta_gt": sta.gt, "sta_member": sta.member,
            "sta_meta": sta.meta, "sta_payload": sta.payload,
            "sta_aux": sta.aux, "sta_flags": sta.flags,
            **({} if dig is None else {"digest": dig})}),
        **({} if not stagger else {"epoch": epoch}),
        **({} if not trace_on else {
            "trace_first": tr_first, "trace_chan": tr_chan,
            "trace_dups": tr_dups, "trace_latch": tr_latch}),
        fwd_gt=fwd[0], fwd_member=fwd[1], fwd_meta=fwd[2], fwd_payload=fwd[3],
        fwd_aux=fwd[4],
        dly_gt=dly[0], dly_member=dly[1], dly_meta=dly[2], dly_payload=dly[3],
        dly_aux=dly[4], dly_since=dly[5], dly_src=dly[6],
        auth_member=auth.member, auth_mask=auth.mask,
        auth_gt=auth.gt, auth_rev=auth.rev, auth_issuer=auth.issuer,
        sig_target=sig[0], sig_meta=sig[1], sig_payload=sig[2],
        sig_gt=sig[3], sig_since=sig[4],
        stats=stats,
        time=new_time,
        round_index=rnd + jnp.uint32(1),
    )


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=0)
def multi_step(state: PeerState, cfg: CommunityConfig, k: int,
               overrides=None) -> PeerState:
    """Advance ``k`` rounds in ONE dispatch (a ``lax.fori_loop`` over
    :func:`step`'s body).

    The per-call form pays host-dispatch latency every round — measured at
    ~300 us through this environment's TPU tunnel, ~60x the ~5 us the
    device spends computing a 1M-peer round (BENCH.md).  Steady-state
    simulation (the driver's rounds/sec metric, long convergence runs)
    should therefore batch rounds through this entry point and only
    surface to the host when it actually wants to look at the state —
    exactly how the reference amortizes work across its 5-second walker
    ticks without returning to the caller in between.
    """
    return lax.fori_loop(
        0, k, lambda i, s: step.__wrapped__(s, cfg, overrides), state)


def unload_members(state: PeerState, cfg: CommunityConfig,
                   mask) -> PeerState:
    """Unload the community instance on the masked peers (reference:
    community.py ``Community.unload_community``): ``loaded`` off, the
    community-instance memory — candidate table, delay pen, signature
    cache, forward batch, malicious convictions — freed, while the
    store (the database) persists.  Tracker
    rows are silently excluded: the reference's TrackerCommunity
    auto-joins any community generically and has no unload path
    (tool/tracker.py).  Called by both the scenario-event interpreter
    (scenario.Unload) and the rim (Community.unload_community).

    Re-load paths, in one place (the auto_load boundary):
    - any arriving community packet, when ``cfg.auto_load`` (step
      phase intake; reference define_auto_load);
    - an explicit ``load_members`` (reference get_community(load=True));
    - churn rebirth (step phase 0) ALWAYS re-loads — a reborn row is a
      wiped-disk NEW participant whose join IS an explicit load, not the
      old instance resuming;
    - checkpoint restart (`checkpoint.restore(fresh_candidates=True)`)
      re-loads only under ``auto_load`` — the same app restarting on the
      same database honors an explicit pre-crash unload otherwise.
    """
    mj = jnp.asarray(mask) & (jnp.arange(cfg.n_peers) >= cfg.n_trackers)
    state = wipe_instance_memory(state, mj)
    return state.replace(loaded=jnp.where(mj, False, state.loaded))


def load_members(state: PeerState, mask) -> PeerState:
    """Explicitly (re-)load the community instance on the masked peers
    (reference: dispersy.py ``get_community(load=True)`` /
    ``Community.load_community``); they re-walk from the trackers since
    candidates are never persisted."""
    return state.replace(loaded=jnp.asarray(mask) | state.loaded)


def create_messages(state: PeerState, cfg: CommunityConfig,
                    author_mask: jnp.ndarray, meta: int,
                    payload: jnp.ndarray,
                    aux: jnp.ndarray | None = None) -> PeerState:
    """Application send: each masked peer authors one sync-distributed record.

    Mirrors ``Community.create_<message>`` for a FullSyncDistribution meta
    (reference: message.py ``Message.impl`` + community.py
    ``claim_global_time``): the author claims global_time+1, signs (identity
    is the peer index in simulation), and stores locally; epidemic spread
    then happens through the Bloom-sync rounds.

    Control metas (authorize/revoke/undo/dynamic-settings/destroy) only
    exist under a timeline — authoring one with ``timeline_enabled=False``
    is a configuration error, raised loudly rather than synced inertly.

    With ``cfg.timeline_enabled`` the author side of ``Timeline.check`` runs
    too (the reference refuses to create a message the local timeline would
    reject): control metas enforce their authority rule, protected metas
    need a permit grant in the *author's own* table, and accepted
    authorize/revoke/undo records act on the author's own state immediately
    (reference: store_update_forward processes a created message locally).
    """
    if meta in (META_AUTHORIZE, META_REVOKE, META_UNDO_OWN, META_UNDO_OTHER,
                META_DYNAMIC, META_DESTROY) and not cfg.timeline_enabled:
        # (dispersy-identity is deliberately NOT here: identity records are
        # public announcements and enforce nothing.)
        raise ValueError(
            f"meta {meta:#x} is a permission control message; it needs "
            "timeline_enabled=True (declare a Linear/DynamicResolution "
            "meta or set the flag) — without a timeline the record would "
            "sync but enforce nothing")
    if meta < cfg.n_meta and (cfg.double_meta_mask >> meta) & 1:
        # A double-signed record only exists through the countersign
        # exchange; minting one here would forge the second signature.
        raise ValueError(
            f"meta {meta} is DoubleMemberAuthentication — use "
            "create_signature_request, which obtains the counterparty's "
            "signature instead of forging it")
    n = cfg.n_peers
    idx = jnp.arange(n, dtype=jnp.uint32)
    if aux is None:
        aux = jnp.zeros((n,), jnp.uint32)
    aux = jnp.asarray(aux, jnp.uint32).reshape(n)
    payload = jnp.asarray(payload, jnp.uint32).reshape(n)
    # No community instance, nothing to create on (reference: a
    # create_<msg> call needs the loaded Community object).
    author_mask = jnp.asarray(author_mask) & state.loaded
    auth = _auth(state)
    gt_new = state.global_time + jnp.uint32(1)

    is_seq_meta = meta < cfg.n_meta and (cfg.seq_meta_mask >> meta) & 1
    is_direct_meta = meta < cfg.n_meta and (cfg.direct_meta_mask >> meta) & 1
    if is_seq_meta:
        # The author stamps the next sequence number for (self, meta)
        # (reference: FullSyncDistribution.claim_sequence_number).
        own = ((state.store_member == idx[:, None])
               & (state.store_meta == jnp.uint32(meta))
               & (state.store_gt != jnp.uint32(EMPTY_U32)))
        aux = jnp.max(jnp.where(own, state.store_aux, 0),
                      axis=1) + jnp.uint32(1)

    if cfg.timeline_enabled:
        _, _, mem_base, _ = _layout_cols(cfg, jnp.arange(n, dtype=jnp.int32))
        founder_row = _founder_col(cfg, mem_base)
        if meta in (META_AUTHORIZE, META_REVOKE):
            # Founder, or a member holding the matching authority bit
            # (AUTHORIZE for grants, REVOKE for revokes — separable) for
            # every meta in the mask (Timeline.check's author-side gate
            # on create — chains, see ops/timeline).
            deleg = tl.check_grant(
                auth, idx[:, None],
                (aux & jnp.uint32(user_perm_mask(cfg.n_meta)))[:, None],
                gt_new[:, None], cfg.n_meta,
                perm=(PERM_REVOKE if meta == META_REVOKE
                      else PERM_AUTHORIZE))[:, 0]
            allowed = (idx == founder_row) | deleg
        elif meta == META_UNDO_OTHER:
            # Founder, or the UNDO permission on the target record's meta
            # — resolved from the author's OWN store (the reference undoes
            # a message it holds; an unknown target refuses the create).
            tmeta = ik.stored_meta_of(_store(state), payload[:, None],
                                      aux[:, None])               # [N, 1]
            granted = tl.check(auth, idx[:, None], tmeta,
                               gt_new[:, None], founder_row[:, None],
                               perm=PERM_UNDO)[:, 0]
            allowed = (idx == founder_row) | granted
        elif meta == META_DYNAMIC:
            # Founder, or the AUTHORIZE permission on the flipped meta
            # (mirrors the intake's flip_grant_ok rule).
            granted = tl.check(auth, idx[:, None], payload[:, None],
                               gt_new[:, None], founder_row[:, None],
                               perm=PERM_AUTHORIZE)[:, 0]
            allowed = (idx == founder_row) | granted
        elif meta == META_DESTROY:
            allowed = idx == founder_row
        elif meta == META_UNDO_OWN:
            allowed = payload == idx
        elif meta < cfg.n_meta and (cfg.dynamic_meta_mask >> meta) & 1:
            # DynamicResolution author gate: policy at the claimed
            # global_time, replayed from the author's own store.
            linear_now = _author_linear(state, cfg, meta, gt_new)
            permit = tl.check(auth, idx[:, None],
                              jnp.full((n, 1), meta, jnp.uint32),
                              gt_new[:, None], founder_row[:, None])[:, 0]
            allowed = ~linear_now | permit
        elif meta < 32 and (cfg.protected_meta_mask >> meta) & 1:
            allowed = tl.check(auth, idx[:, None],
                               jnp.full((n, 1), meta, jnp.uint32),
                               gt_new[:, None], founder_row[:, None])[:, 0]
        else:
            allowed = jnp.ones((n,), bool)
        # A hard-killed peer's community is unloaded: nothing to create on.
        author_mask = author_mask & allowed & ~killed_mask(state.store_meta)

    new = st.StoreCols(
        gt=gt_new[:, None],
        member=idx[:, None],
        meta=jnp.full((n, 1), meta, jnp.uint8),
        payload=payload[:, None],
        aux=aux[:, None],
        flags=jnp.zeros((n, 1), jnp.uint8))
    # Direct records are one-shot: pushed, never stored anywhere
    # (reference: DirectDistribution messages live outside the sync table).
    store_mask = (jnp.zeros((n,), bool) if is_direct_meta else author_mask)
    ins = st.store_insert(_store(state), new, store_mask[:, None],
                          history=cfg.history)
    stc = ins.store
    sta_updates: dict = {}
    if cfg.store_diet and cfg.sync_enabled:
        # Byte-diet create: authoring is a host-boundary EVENT, not the
        # hot round — the record goes straight into the sorted ring
        # (so the next sync round serves it immediately, exactly like
        # the legacy path), and the digest learns its probe bits under
        # the salt of the round that will claim next
        # (state.round_index's epoch), keeping claim == digest exact.
        # A capacity-dropped create leaves a false-positive bit that
        # the next compaction's rebuild clears — the storediet.py FP
        # argument.
        if cfg.store_stagger:
            # Per-peer salts under cohort staggering: each author's
            # digest lives at its OWN cohort's current epoch (the
            # leaf equals epoch_of_cohort(cfg, round_index, cohort)
            # between rounds — the engine's round-start invariant).
            ep = state.epoch[:, None]
        else:
            ep = sdiet.epoch_of(cfg, state.round_index)
        new_h = record_hash(new.member, new.gt, new.meta, new.payload)
        if bloom.gather_backend():
            dig = bloom.digest_update(
                state.digest,
                bloom.probe_bits(new_h, cfg.bloom_bits,
                                 cfg.bloom_hashes, salt=ep),
                store_mask[:, None], cfg.bloom_bits)
        else:
            dig = state.digest | bloom.bloom_build(
                new_h, store_mask[:, None], cfg.bloom_bits,
                cfg.bloom_hashes, salt=ep)
        sta_updates["digest"] = dig
    create_stored = ins.n_inserted.astype(jnp.uint32)
    if cfg.trace.enabled:
        # Dissemination lineage at the create site (traceplane.py):
        # an authored record matching an already-registered tracked
        # key stamps the author's lineage with the CH_CREATE channel.
        # (Registration AFTER creation instead scans holders —
        # track_record; the two orders commute.)  Like the legacy
        # intake rule, a capacity-dropped insert still counts:
        # lineage is arrival history, not residency.
        newly_any = jnp.zeros((n,), bool)
        tf_cols, tc_cols = [], []
        for k in range(cfg.trace.tracked_slots):
            m_k = (store_mask & (idx == state.trace_member[k])
                   & (gt_new == state.trace_gt[k])
                   & (state.trace_first[:, k] == jnp.uint32(0)))
            tf_cols.append(jnp.where(
                m_k, state.round_index + jnp.uint32(1),
                state.trace_first[:, k]))
            tc_cols.append(jnp.where(m_k, jnp.uint8(trp.CH_CREATE),
                                     state.trace_chan[:, k]))
            newly_any = newly_any | m_k
        sta_updates["trace_first"] = jnp.stack(tf_cols, axis=1)
        sta_updates["trace_chan"] = jnp.stack(tc_cols, axis=1)
        trace_delivered = state.stats.trace_delivered.at[
            :, trp.CH_CREATE - 1].add(newly_any.astype(jnp.uint32))
    else:
        trace_delivered = None

    retro_unw = retro_rm = None
    fold_dropped = None
    if cfg.timeline_enabled and meta in (META_AUTHORIZE, META_REVOKE):
        # The author's own table learns its own grant/revoke at create time.
        fr = tl.fold(auth, target=payload[:, None],
                     mask=(aux
                           & jnp.uint32(user_perm_mask(cfg.n_meta)))[:, None],
                     gt=gt_new[:, None],
                     is_revoke=jnp.full((n, 1), meta == META_REVOKE),
                     valid=author_mask[:, None],
                     issuer=idx[:, None])
        auth = fr.table
        fold_dropped = fr.n_dropped + fr.n_evicted   # own-table overflow,
        #   counted like every bounded-state loss (oracle _auth_fold)
        # A self-created revoke claims clock+1, but the author's table can
        # hold rows at HIGHER global_times (records from faster peers
        # arrive up to acceptable_global_time_range ahead) — the same
        # late-revoke hazard as the intake; an EVICTION can likewise
        # orphan rows the displaced grant proved.  Same re-walk either
        # way (see _retro_pass).
        trigger = jnp.any(fr.n_evicted > 0)
        if meta == META_REVOKE:
            trigger = trigger | jnp.any(author_mask)
        auth, stc, retro_unw, retro_rm = lax.cond(
            trigger,
            lambda a, s: _retro_pass(a, s, cfg, founder_row),
            lambda a, s: (a, s, jnp.zeros((n,), jnp.int32),
                          jnp.zeros((n,), jnp.int32)),
            auth, stc)
    if cfg.timeline_enabled and meta in (META_UNDO_OWN, META_UNDO_OTHER):
        # Mark the target row in the author's own store immediately.
        hit = (author_mask[:, None] & (stc.member == payload[:, None])
               & (stc.gt == aux[:, None]) & (stc.meta < 32))
        stc = stc._replace(flags=jnp.where(
            hit, stc.flags | jnp.uint8(FLAG_UNDONE), stc.flags))

    # A created record ALWAYS enters the forward batch (the reference calls
    # store_update_forward on create — forward=True pushes it
    # unconditionally).  When relayed records already fill the buffer, the
    # newest of them is displaced: an author's own creation must not lose
    # its only push to unrelated relay traffic (with a saturated Bloom
    # slice, a never-pushed record would never spread at all).
    fslot = st.count_valid(state.fwd_gt)                       # first free slot
    can_buf = author_mask if cfg.forward_buffer > 0 else jnp.zeros((n,), bool)
    rows = jnp.arange(n)
    put = (jnp.minimum(fslot, max(cfg.forward_buffer - 1, 0)),)

    def buf(cur, val):
        return cur.at[rows, put[0]].set(
            jnp.where(can_buf, val, cur[rows, put[0]]), mode="drop")
    return state.replace(
        store_gt=stc.gt, store_member=stc.member,
        store_meta=stc.meta, store_payload=stc.payload,
        store_aux=stc.aux, store_flags=stc.flags,
        **sta_updates,
        fwd_gt=buf(state.fwd_gt, new.gt[:, 0]),
        fwd_member=buf(state.fwd_member, new.member[:, 0]),
        fwd_meta=buf(state.fwd_meta, new.meta[:, 0]),
        fwd_payload=buf(state.fwd_payload, new.payload[:, 0]),
        fwd_aux=buf(state.fwd_aux,
                    new.aux[:, 0].astype(state.fwd_aux.dtype)),
        auth_member=auth.member, auth_mask=auth.mask,
        auth_gt=auth.gt, auth_rev=auth.rev, auth_issuer=auth.issuer,
        global_time=jnp.where(author_mask, gt_new, state.global_time),
        stats=state.stats.replace(
            msgs_stored=state.stats.msgs_stored + create_stored,
            accepted_by_meta=state.stats.accepted_by_meta
            .at[:, min(meta, cfg.n_meta)]
            .add(author_mask.astype(jnp.uint32)),
            **({} if trace_delivered is None else {
                "trace_delivered": trace_delivered}),
            **({} if fold_dropped is None else {
                "msgs_dropped": state.stats.msgs_dropped
                + fold_dropped.astype(jnp.uint32)}),
            **({} if retro_unw is None else {
                "auth_unwound": state.stats.auth_unwound
                + retro_unw.astype(jnp.uint32),
                "msgs_retro": state.stats.msgs_retro
                + retro_rm.astype(jnp.uint32)})))


def create_signature_request(state: PeerState, cfg: CommunityConfig,
                             author_mask: jnp.ndarray, meta: int,
                             counterparty: jnp.ndarray,
                             payload: jnp.ndarray) -> PeerState:
    """Draft a double-signed record and open the signature request.

    Mirrors ``Community.create_signature_request`` (reference: community.py
    — draft a DoubleMemberAuthentication message, park it in the
    RequestCache, send ``dispersy-signature-request`` to the counterparty):
    each masked peer claims global_time+1 for the draft and fills its
    one-slot signature cache; the request itself rides in the *next*
    :func:`step` and resolves (or expires) there.  The draft is NOT stored
    locally — only the countersigned completion enters the store, exactly
    as in the reference where the half-signed packet lives in the cache
    only.

    ``counterparty`` is i32[N]: each author's chosen second signer.  A
    request is refused (mask cleared, no side effect) when the author
    already has one in flight, the counterparty is itself / a tracker /
    outside the author's community, or — for protected metas — the author
    lacks the permit in its own timeline.
    """
    if not (meta < cfg.n_meta and (cfg.double_meta_mask >> meta) & 1):
        raise ValueError(f"meta {meta} is not double-signed "
                         f"(double_meta_mask={cfg.double_meta_mask:#x})")
    n = cfg.n_peers
    idx = jnp.arange(n, dtype=jnp.int32)
    counterparty = jnp.asarray(counterparty, jnp.int32).reshape(n)
    payload = jnp.asarray(payload, jnp.uint32).reshape(n)
    _, _, mem_base, mem_count = _layout_cols(cfg, idx)
    gt_new = state.global_time + jnp.uint32(1)
    ok = (jnp.asarray(author_mask, bool) & state.alive & state.loaded
          & ~state.is_tracker
          & (state.sig_target == NO_PEER)
          & (counterparty != idx)
          & (counterparty >= mem_base)
          & (counterparty < mem_base + mem_count))
    if cfg.timeline_enabled:
        ok = ok & ~killed_mask(state.store_meta)
    if (cfg.timeline_enabled
            and ((cfg.protected_meta_mask | cfg.dynamic_meta_mask)
                 >> meta) & 1):
        # The author's own timeline view, dynamic flips included — the
        # same gate create_messages applies (an unpermitted author must
        # not burn a counterparty's signature on a record every intake
        # would reject).
        founder_row = _founder_col(cfg, mem_base)
        permit = tl.check(_auth(state), idx[:, None].astype(jnp.uint32),
                          jnp.full((n, 1), meta, jnp.uint32),
                          gt_new[:, None], founder_row[:, None])[:, 0]
        ok = ok & (~_author_linear(state, cfg, meta, gt_new) | permit)
    return state.replace(
        sig_target=jnp.where(ok, counterparty, state.sig_target),
        sig_meta=jnp.where(ok, jnp.uint32(meta), state.sig_meta),
        sig_payload=jnp.where(ok, payload, state.sig_payload),
        sig_gt=jnp.where(ok, gt_new, state.sig_gt),
        sig_since=jnp.where(ok, state.round_index, state.sig_since),
        global_time=jnp.where(ok, gt_new, state.global_time))


# ---- jitted per-event forms (the scenario runner's entry points) -------
# A SetFault-heavy scenario applies many events between steps; the eager
# forms above re-trace their full op graph on EVERY call (fine for tests,
# ~300 us/dispatch through a TPU tunnel for hundreds of ops — not fine
# for long scripted runs).  These jitted forms compile once per
# (config, meta) signature and replay from cache, so the only recompiles
# a scenario pays are the documented config-swap ones (scenario.py).
# The eager forms stay exported unchanged — the oracle-differential
# suites rely on their call-by-call semantics and compile cost profile.
create_messages_jit = functools.partial(
    jax.jit, static_argnums=(1, 3),
    static_argnames=("cfg", "meta"))(create_messages)
create_signature_request_jit = functools.partial(
    jax.jit, static_argnums=(1, 3),
    static_argnames=("cfg", "meta"))(create_signature_request)
unload_members_jit = functools.partial(
    jax.jit, static_argnums=(1,), static_argnames=("cfg",))(unload_members)
load_members_jit = jax.jit(load_members)


def seed_overlay(state: PeerState, cfg: CommunityConfig,
                 degree: int) -> PeerState:
    """Pre-seed every peer's candidate table with random walked neighbors.

    The driver's configs #2/#3 prescribe a warm overlay ("Erdős–Rényi
    overlay", "static overlay") rather than a cold flash-crowd bootstrap;
    this plays the role of a persisted candidate file handed to a restarted
    peer.  Entries are stamped walked-and-immediately-eligible.
    """
    assert degree <= cfg.k_candidates
    n, t = cfg.n_peers, cfg.n_trackers
    assert n - t > 1, "need at least two non-tracker peers to seed an overlay"
    if cfg.communities:
        assert all(m > 1 for m, _ in cfg.communities), \
            "every community needs at least two members to seed"
    seed = rng.fold_seed(state.key)
    idx = jnp.arange(n, dtype=jnp.int32)
    j = jnp.arange(degree)[None, :]
    # Neighbors are drawn from the row's own community member block:
    # trackers must never enter the walk categories (see
    # ops/candidates.upsert_many), and overlays never cross communities.
    _, _, mem_base, mem_count = _layout_cols(cfg, idx)
    base = mem_base[:, None]
    span = jnp.maximum(mem_count, 1)[:, None]
    nbr = base + (rng.rand_u32(seed, jnp.uint32(0xE1), idx[:, None],
                               rng.P_GOSSIP, j)
                  % span.astype(jnp.uint32)).astype(jnp.int32)
    nbr = jnp.where(nbr == idx[:, None],
                    base + (nbr - base + 1) % span, nbr)
    # One slot per neighbor: the candidate table is keyed by peer (the
    # reference's dict is keyed by address), so a duplicate draw becomes an
    # empty slot instead of two entries for one peer.
    dup = jnp.any(nbr[:, :, None] == jnp.where(
        jnp.arange(degree)[None, :] < jnp.arange(degree)[:, None],
        nbr[:, None, :], NO_PEER), axis=-1)
    nbr = jnp.where(dup, NO_PEER, nbr)
    eligible_at = jnp.float32(0.0) - jnp.float32(cfg.eligibility_delay)
    pad = cfg.k_candidates - degree

    def never_k():  # distinct buffers: aliasing breaks step's donation
        return jnp.full((n, cfg.k_candidates), NEVER, jnp.float32)
    # _cand_quant: identity at the default timestamp width.  Under
    # cand_bits=16 the negative pre-epoch stamp saturates to the oldest
    # live stamp (sim-second 0.0) — a seeded neighbor becomes eligible
    # after eligibility_delay instead of immediately; the documented
    # narrowing degradation (storediet.StoreConfig.cand_bits), mirrored
    # bit-exactly by the oracle.
    return state.replace(
        cand_peer=jnp.concatenate(
            [nbr, jnp.full((n, pad), NO_PEER, jnp.int32)], axis=1),
        cand_last_walk=_cand_quant(jnp.concatenate(
            [jnp.where(nbr == NO_PEER, jnp.float32(NEVER), eligible_at),
             jnp.full((n, pad), NEVER, jnp.float32)], axis=1), cfg),
        cand_last_stumble=_cand_quant(never_k(), cfg),
        cand_last_intro=_cand_quant(never_k(), cfg))


def coverage(state: PeerState, member: int, gt: int, meta: int,
             payload: int) -> jnp.ndarray:
    """Fraction of alive non-tracker peers whose store holds one record.

    The driver's convergence metric (BASELINE.md: rounds-to-99%-coverage).
    Trackers are excluded: they are pure introduction servers and never
    sync (reference: tool/tracker.py TrackerCommunity).
    """
    has = _holds_record(state, member, gt, meta, payload)
    syncing = state.alive & ~state.is_tracker
    has = has & syncing
    return jnp.sum(has) / jnp.maximum(jnp.sum(syncing), 1)


def _holds_record(state: PeerState, member: int, gt: int, meta: int,
                  payload: int) -> jnp.ndarray:
    """bool[N]: does each peer hold the record in its LOGICAL store —
    the sorted ring, plus the byte-diet staging buffer when present
    (ring ∪ staging is the store between compactions, storediet.py)."""
    def _in(g, m, t, p):
        return jnp.any((g == jnp.uint32(gt))
                       & (m == jnp.uint32(member))
                       & (t == jnp.uint32(meta))
                       & (p == jnp.uint32(payload)), axis=1)
    has = _in(state.store_gt, state.store_member, state.store_meta,
              state.store_payload)
    if state.sta_gt.shape[1]:
        has = has | _in(state.sta_gt, state.sta_member, state.sta_meta,
                        state.sta_payload)
    return has


def _track_record_impl(state: PeerState, cfg: CommunityConfig,
                       author: jnp.ndarray, gt: jnp.ndarray,
                       slot: jnp.ndarray) -> PeerState:
    """The traced half of :func:`track_record`: write the (author, gt)
    key into tracked slot ``slot`` and stamp lineage for every peer
    already HOLDING the record in its logical store (ring ∪ staging) —
    attributed to the create channel, the registration-at-creation
    contract (traceplane.py).  ``slot`` is traced, so one compile per
    config serves every registration."""
    t = cfg.trace.tracked_slots
    col = jnp.arange(t, dtype=jnp.uint32) == slot            # bool[T]
    holds = jnp.any((state.store_member == author)
                    & (state.store_gt == gt), axis=1)
    if state.sta_gt.shape[1]:
        holds = holds | jnp.any((state.sta_member == author)
                                & (state.sta_gt == gt), axis=1)
    newly = (holds[:, None] & col[None, :]
             & (state.trace_first == jnp.uint32(0)))         # [N, T]
    rnd_reg = state.round_index + jnp.uint32(1)
    return state.replace(
        trace_member=jnp.where(col, author, state.trace_member),
        trace_gt=jnp.where(col, gt, state.trace_gt),
        trace_first=jnp.where(newly, rnd_reg, state.trace_first),
        trace_chan=jnp.where(newly, jnp.uint8(trp.CH_CREATE),
                             state.trace_chan),
        stats=state.stats.replace(
            trace_delivered=state.stats.trace_delivered
            .at[:, trp.CH_CREATE - 1].add(
                jnp.any(newly, axis=1).astype(jnp.uint32))))


_track_record_jit = functools.partial(
    jax.jit, static_argnums=(1,),
    static_argnames=("cfg",))(_track_record_impl)


def track_record(state: PeerState, cfg: CommunityConfig, author: int,
                 gt: int) -> tuple[PeerState, int]:
    """Register record ``(author, gt)`` for dissemination tracing
    (traceplane.py; the ``scenario.TrackRecord`` event and
    ``Community.track_record`` route here).

    Assigns the first free tracked slot (idempotent: re-registering an
    already-tracked key returns its existing slot untouched) and stamps
    lineage for peers already holding the record — at the intended
    call time, registration at creation, that is exactly the author,
    attributed to the create channel.  Returns ``(state, slot)``;
    raises when the plane is disabled or every slot is taken (slots
    are never freed — size ``trace.tracked_slots`` for the run).
    """
    import numpy as np
    if not cfg.trace.enabled:
        raise ValueError(
            "track_record needs cfg.trace.enabled (the dissemination-"
            "tracing plane; dispersy_tpu/traceplane.py)")
    keys_m = np.asarray(state.trace_member)
    keys_g = np.asarray(state.trace_gt)
    for k in range(cfg.trace.tracked_slots):
        if int(keys_m[k]) == author and int(keys_g[k]) == gt:
            return state, k
    free = [k for k in range(cfg.trace.tracked_slots)
            if int(keys_m[k]) == EMPTY_U32]
    if not free:
        raise ValueError(
            f"all {cfg.trace.tracked_slots} tracked slots are taken "
            "(trace.tracked_slots); slots are never freed")
    slot = free[0]
    state = _track_record_jit(state, cfg, jnp.uint32(author),
                              jnp.uint32(gt), jnp.uint32(slot))
    return state, slot


def coverage_by_community(state: PeerState, cfg: CommunityConfig,
                          member: int, gt: int, meta: int,
                          payload: int) -> jnp.ndarray:
    """f32[C]: per-community fraction of alive members holding one record.

    Multi-community form of :func:`coverage` (driver config #5 reports
    per-community convergence).  A record authored in community c can only
    ever live in block c, so other blocks report 0 for it.
    """
    comm = jnp.asarray(cfg.layout()[0])
    syncing = state.alive & ~state.is_tracker
    has = _holds_record(state, member, gt, meta, payload) & syncing
    out = []
    for c in range(cfg.n_communities):
        in_c = comm == c
        out.append(jnp.sum(has & in_c)
                   / jnp.maximum(jnp.sum(syncing & in_c), 1))
    return jnp.stack(out)
