"""The dissemination-tracing plane: on-device record lineage.

The repo can see its own *cost* (the PR-11 ledger) and *health* (the
telemetry/recovery planes), but until this plane it could not see the
protocol's actual product — how a record spreads.  Tracked-record
coverage was a host-side store query per round (`engine.coverage`),
which kicked `scenario.run` off its batched ring fast path, and nothing
measured first-arrival latency, which delivery channel actually carried
each record, or how many duplicate deliveries the overlay paid per
useful one — the quantities *The Algorithm of Pipelined Gossiping*
makes first-class (dissemination latency under sustained traffic) and
*Verification of GossipSub in ACL2s* formalizes per channel
(delivery/duplicate accounting) — PAPERS.md.

Up to ``TraceConfig.tracked_slots`` records, registered by
``(author, global_time)`` key (``engine.track_record`` /
``scenario.TrackRecord`` / ``Community.track_record``), get per-peer
on-device lineage leaves, updated inside the fused step at every
delivery site:

- ``PeerState.trace_first`` — u32[N, T] first-arrival round (the
  post-step round index the record first LANDED in this peer's logical
  store; 0 = not yet).  Staging-aware: under the byte-diet store plane
  an arrival landing in the staging buffer counts at ARRIVAL, not at
  compaction; a staging-overflow drop does not land and therefore does
  not count as a first arrival (it counts as a duplicate-side delivery
  — the overlay paid for it).  On the legacy every-round-merge path a
  ring-capacity drop at insert still counts: lineage is ARRIVAL
  history, not residency (a LastSync/capacity eviction does not
  un-arrive a record).
- ``PeerState.trace_chan`` — u8[N, T] first-delivery channel code
  (:data:`CH_CREATE` / :data:`CH_WALK_SYNC` / :data:`CH_PUSH` /
  :data:`CH_FLOOD`; 0 = none yet).
- ``PeerState.trace_dups`` — u32[N, T] duplicate-delivery counter: the
  tracked record's arrivals at this peer that were NOT its first
  landing (already stored, in-batch duplicates, digest false
  positives, staging overflow, digest-FN re-stages).

plus the global latches/counters the telemetry row surfaces as
CONDITIONAL words (trace-off rows stay byte-identical; the
recovery/overload rule):

- per-slot coverage counts (alive non-tracker peers whose lineage is
  set — exactly ``engine.coverage``'s numerator),
- per-slot rounds-to-{50,90,99}%-coverage latches
  (``PeerState.trace_latch``, u32[T, 3]; 0 = not reached),
- per-channel useful-delivery and duplicate-delivery totals
  (``Stats.trace_delivered`` / ``Stats.trace_dup``, u32[N, 4]),
- a redundancy ratio (total tracked deliveries / useful ones, f32).

Channel attribution note: byzantine flood junk (FAULTS.md) never
decodes — it always fails the intake hash re-check — so a real record
can never be DELIVERED by the flood channel under this wire model.
:data:`CH_FLOOD` exists so the channel table (and the row schema) is
stable and the structural zero is *measured*, not assumed; the flood's
real cost shows up in the victims' duplicate/drop accounting instead.

Lineage is disk-like state: it rides checkpoints (v15), survives
unload/load and app restarts, and is WIPED with the store by a churn
rebirth or a recovery quarantine escalation (a wiped-disk restart
forgets what it held) — the oracle mirrors every path bit-exactly.

Scope gate (config.validate): the plane's channel table covers exactly
create/walk-sync/push/flood, so ``trace.enabled`` refuses configs that
open other intake segments — the delay pen (``delay_inbox`` and the
request channels riding it), double-signed completions
(``double_meta_mask``), and the in-step eyewitness-proof create of
``malicious_gossip``.  This module is host-side and import-light (no
jax) like :mod:`dispersy_tpu.telemetry`; the traced kernels live in
:mod:`dispersy_tpu.ops.trace` and the registration helpers in
:mod:`dispersy_tpu.engine`.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from dispersy_tpu.exceptions import ConfigError
from dispersy_tpu.ops.contracts import host_helper

# First-delivery channel codes (PeerState.trace_chan values; 0 = no
# delivery yet).  Code c maps to CHANNEL_NAMES[c - 1].
CH_CREATE = 1      # authored locally (engine.create_messages /
#                    holders at engine.track_record registration)
CH_WALK_SYNC = 2   # pulled through the Bloom-sync response on the
#                    walk edge (the `sy` intake segment)
CH_PUSH = 3        # pushed by a forwarding peer (the `ph` segment)
CH_FLOOD = 4       # the byzantine flood blast — structurally zero
#                    under the junk-flood wire model (module doc)
CHANNEL_NAMES = ("create", "walk_sync", "push", "flood")
NUM_CHANNELS = len(CHANNEL_NAMES)

# Coverage-latch percentiles, in trace_latch column order.
LATCH_PCTS = (50, 90, 99)


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Static dissemination-tracing knobs, composed into
    ``CommunityConfig.trace`` (sixth-to-last field, directly before
    ``store`` — checkpoint fingerprint compat).

    Frozen + hashable (a static jit argument).  All defaults off
    compile to exactly the trace-free step; every leaf the plane adds
    (``trace_*`` and the ``Stats.trace_*`` counters) is zero-width
    while ``enabled`` is off.
    """

    # Master switch: compose the lineage updates, coverage counts,
    # latches, and channel accounting into the fused round.
    enabled: bool = False
    # Tracked-record slots (the T axis of every lineage leaf).  Slots
    # are assigned by registration order and never freed — size for
    # the records one run tracks, not for churn.
    tracked_slots: int = 4

    def __post_init__(self) -> None:
        if not (1 <= self.tracked_slots <= 16):
            raise ConfigError(
                f"trace.tracked_slots must be in [1, 16], got "
                f"{self.tracked_slots} (each slot is a u32+u8+u32 "
                "per-peer lineage column)")

    def replace(self, **kw) -> "TraceConfig":
        return dataclasses.replace(self, **kw)


@host_helper
def redundancy_f32(delivered, dup) -> float:
    """The row's redundancy ratio from per-channel useful/duplicate
    totals — float32 op-for-op (the engine computes the identical
    sequence on device, the oracle calls THIS): per channel,
    lo + hi * 2^32 in f32, accumulated in channel order; ratio =
    (useful + dup) / useful, or 0 with no useful delivery yet."""
    two32 = np.float32(4294967296.0)
    useful_f = np.float32(0.0)
    dup_f = np.float32(0.0)
    for c in range(NUM_CHANNELS):
        d = int(delivered[c])
        u = int(dup[c])
        useful_f = np.float32(
            useful_f + np.float32(
                np.float32(d & 0xFFFFFFFF) + np.float32(d >> 32) * two32))
        dup_f = np.float32(
            dup_f + np.float32(
                np.float32(u & 0xFFFFFFFF) + np.float32(u >> 32) * two32))
    if not useful_f > 0:
        return 0.0
    return float(np.float32((useful_f + dup_f) / useful_f))


@host_helper
def trace_totals(state, cfg) -> dict:
    """The trace plane's snapshot keys from a materialized state — the
    legacy (telemetry-off) ``metrics.snapshot`` path's source, emitting
    the SAME key set ``telemetry.row_to_snapshot`` derives from the
    fused row so the two paths stay schema-identical (the dump_binary
    contract).  Cheap: a few [N, T] / [N, 4] transfers."""
    t = cfg.trace.tracked_slots
    first = np.asarray(state.trace_first)
    members = np.asarray(state.alive) & ~np.asarray(state.is_tracker)
    latch = np.asarray(state.trace_latch)
    out: dict = {}
    for k in range(t):
        cov = int(((first[:, k] != 0) & members).sum()) if first.size \
            else 0
        out[f"trace_cov_{k}"] = cov
        for i, pct in enumerate(LATCH_PCTS):
            out[f"trace_r{pct}_{k}"] = (int(latch[k, i])
                                        if latch.size else 0)
    delivered = (np.asarray(state.stats.trace_delivered, np.uint64)
                 .sum(axis=0) if np.asarray(
                     state.stats.trace_delivered).size
                 else np.zeros(NUM_CHANNELS, np.uint64))
    dup = (np.asarray(state.stats.trace_dup, np.uint64).sum(axis=0)
           if np.asarray(state.stats.trace_dup).size
           else np.zeros(NUM_CHANNELS, np.uint64))
    for c, nm in enumerate(CHANNEL_NAMES):
        out[f"trace_delivered_{nm}"] = int(delivered[c])
        out[f"trace_dup_{nm}"] = int(dup[c])
    out["trace_redundancy"] = redundancy_f32(delivered, dup)
    return out


@host_helper
def slots_in_rows(rows) -> list:
    """Tracked-slot indices present in a row log (``trace_cov_<k>``
    keys), sorted."""
    slots: set[int] = set()
    for row in rows:
        for key in row:
            if key.startswith("trace_cov_"):
                try:
                    slots.add(int(key[len("trace_cov_"):]))
                except ValueError:
                    pass
    return sorted(slots)


@host_helper
def coverage_curve(rows, slot: int) -> list:
    """``(round, covered, alive_members)`` triples for one slot, rounds
    ascending — the dissemination curve the reference's experiment
    pipeline mined from its logs."""
    out = []
    for row in sorted(rows, key=lambda r: int(r.get("round", 0))):
        if f"trace_cov_{slot}" not in row:
            continue
        out.append((int(row["round"]), int(row[f"trace_cov_{slot}"]),
                    int(row.get("alive_members", 0))))
    return out


@host_helper
def latency_percentiles(rows, slot: int,
                        pcts=(10, 25, 50, 75, 90, 99)) -> dict:
    """First-arrival latency percentiles for one tracked record, in
    ROUNDS after its first appearance, derived from the coverage curve
    (the p-th percentile of per-peer first-arrival latency is the first
    round where coverage reaches p% of the alive members).  ``None``
    for percentiles the log's window never reached."""
    curve = coverage_curve(rows, slot)
    start = next((rnd for rnd, cov, _ in curve if cov > 0), None)
    out: dict = {"start_round": start}
    for p in pcts:
        hit = next((rnd for rnd, cov, alive in curve
                    if alive > 0 and cov * 100 >= p * alive), None)
        out[f"p{p}"] = None if (hit is None or start is None) \
            else hit - start
    return out


@host_helper
def channel_table(rows) -> dict:
    """Per-channel useful/duplicate totals and useful-delivery shares
    from a row log's LAST row (the counters are cumulative)."""
    last = max(rows, key=lambda r: int(r.get("round", 0)), default={})
    out: dict = {}
    total = 0
    for nm in CHANNEL_NAMES:
        d = int(last.get(f"trace_delivered_{nm}", 0))
        out[f"delivered_{nm}"] = d
        out[f"dup_{nm}"] = int(last.get(f"trace_dup_{nm}", 0))
        total += d
    for nm in CHANNEL_NAMES:
        out[f"share_{nm}"] = (out[f"delivered_{nm}"] / total
                              if total else 0.0)
    out["delivered_total"] = total
    return out


@host_helper
def trace_report(rows) -> dict:
    """Dissemination summary of a run log — the trace analogue of
    ``overload.shed_report`` / ``recovery.mttr_report``, consumed by
    ``tools/telemetry.py gate --trace`` against the committed
    ``artifacts/golden_trace.json`` and by ``tools/trace.py report``.

    All scalar fields (the gate compares field-for-field): per-slot
    final coverage counts and rounds-to-{50,90,99}% latches, per-channel
    delivered/dup totals and shares, and the redundancy ratio.
    """
    rows = [r for r in rows if isinstance(r, dict)]
    out: dict = {"rounds": len(rows)}
    if not rows:
        return out
    last = max(rows, key=lambda r: int(r.get("round", 0)))
    for k in slots_in_rows(rows):
        out[f"slot{k}_cov"] = int(last.get(f"trace_cov_{k}", 0))
        for pct in LATCH_PCTS:
            out[f"slot{k}_r{pct}"] = int(last.get(f"trace_r{pct}_{k}", 0))
    out.update(channel_table(rows))
    out["redundancy"] = float(last.get("trace_redundancy", 0.0))
    return out
