"""Checkpoint/restore of the overlay state.

The reference's checkpoint is its SQLite file: every sync-distributed
message persists in the ``sync`` table, ``Community.load_community``
replays identity/authorize/revoke to rebuild the Timeline and resumes
``global_time``, and candidates are *not* persisted — a restarted peer
re-walks from the trackers (SURVEY.md §5.4).

TPU recast: the whole overlay is one ``PeerState`` pytree, so a checkpoint
is a flat archive of its leaves plus a config fingerprint and the RNG
key/round counter (which the reference has no analogue for — its
randomness is wall-clock; ours must resume bit-exactly).  Two restore
modes:

- ``fresh_candidates=False`` (default): byte-exact resume — stepping the
  restored state replays the identical trajectory, which is what the
  determinism tests pin.
- ``fresh_candidates=True``: the reference's restart semantics — the
  in-memory half dies with the process (candidate tables, the signature
  request cache, the delayed-message pen, malicious convictions) and
  peers re-walk from their trackers; stores, clocks, auth tables and
  stats survive (they live in "the database").

Format: one ``.npz`` with dotted-path keys per leaf.  On a multi-host mesh
each host would save its addressable shards to its own file (orbax-style
sharded layout); this single-file writer covers the single-host bench and
test environments and keeps the format inspectable.
"""

from __future__ import annotations

import io
import os

import jax
import numpy as np

from dispersy_tpu.config import EMPTY_U32, CommunityConfig, NO_PEER
from dispersy_tpu.state import NEVER, PeerState, init_state

# v2: PeerState gained the signature request cache (sig_*) and Stats the
# sig_signed/sig_done/sig_expired counters — v1 archives lack those leaves.
# v3: + the malicious-member blacklist (mal_member) and conflicts counter.
# v4: + the delayed-message pen (dly_*) and msgs_delayed counter.
FORMAT_VERSION = 4


def _fingerprint(cfg: CommunityConfig) -> str:
    """Config identity a checkpoint is only valid against."""
    return repr(cfg)


def _leaves_with_paths(state: PeerState):
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    names = ["/".join(str(getattr(k, "name", k)) for k in path)
             for path, _ in flat]
    return names, [leaf for _, leaf in flat], treedef


def save(path: str, state: PeerState, cfg: CommunityConfig) -> None:
    """Write the full overlay state to ``path`` (.npz)."""
    names, leaves, _ = _leaves_with_paths(state)
    arrays = {f"leaf:{n}": np.asarray(jax.device_get(leaf))
              for n, leaf in zip(names, leaves)}
    arrays["meta:version"] = np.asarray(FORMAT_VERSION)
    arrays["meta:config"] = np.frombuffer(
        _fingerprint(cfg).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:       # atomic-ish: no torn checkpoint files
        f.write(buf.getvalue())
    os.replace(tmp, path)


def restore(path: str, cfg: CommunityConfig,
            fresh_candidates: bool = False) -> PeerState:
    """Load a checkpoint written by :func:`save`.

    Raises ValueError on a config mismatch — a checkpoint is only
    meaningful against the exact static configuration that produced it.
    Re-shard the result afterwards with ``parallel.shard_state`` (the
    archive stores unsharded host arrays).
    """
    with np.load(path) as z:
        version = int(z["meta:version"])
        if version != FORMAT_VERSION:
            raise ValueError(f"checkpoint format {version}, "
                             f"expected {FORMAT_VERSION}")
        stored_cfg = bytes(z["meta:config"]).decode()
        if stored_cfg != _fingerprint(cfg):
            raise ValueError(
                "checkpoint was written under a different config:\n"
                f"  stored: {stored_cfg}\n  given:  {_fingerprint(cfg)}")
        # Template provides the treedef (and validates shapes below).
        template = init_state(cfg, jax.random.PRNGKey(0))
        names, t_leaves, treedef = _leaves_with_paths(template)
        leaves = []
        for n, t in zip(names, t_leaves):
            key = f"leaf:{n}"
            if key not in z:
                raise ValueError(f"checkpoint missing field {n}")
            arr = z[key]
            if arr.shape != t.shape or arr.dtype != t.dtype:
                raise ValueError(
                    f"field {n}: checkpoint {arr.shape}/{arr.dtype} vs "
                    f"config {t.shape}/{t.dtype}")
            leaves.append(arr)
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if fresh_candidates:
        # Reference restart semantics: everything that lives in process
        # memory (not the database) is ephemeral — candidates (the walker
        # re-bootstraps from trackers, SURVEY §5.4), the signature
        # RequestCache, the delayed-message pen, and malicious-member
        # convictions all die with the process, exactly as the engine's
        # churn rebirth models.
        n, k, d = cfg.n_peers, cfg.k_candidates, cfg.delay_inbox
        f = cfg.forward_buffer
        never = np.full((n, k), NEVER, np.float32)
        state = state.replace(
            cand_peer=np.full((n, k), NO_PEER, np.int32),
            cand_last_walk=never,
            cand_last_stumble=never.copy(),
            cand_last_intro=never.copy(),
            fwd_gt=np.full((n, f), EMPTY_U32, np.uint32),
            fwd_member=np.full((n, f), EMPTY_U32, np.uint32),
            fwd_meta=np.full((n, f), EMPTY_U32, np.uint32),
            fwd_payload=np.full((n, f), EMPTY_U32, np.uint32),
            fwd_aux=np.full((n, f), EMPTY_U32, np.uint32),
            sig_target=np.full((n,), NO_PEER, np.int32),
            sig_meta=np.zeros((n,), np.uint32),
            sig_payload=np.zeros((n,), np.uint32),
            sig_gt=np.zeros((n,), np.uint32),
            sig_since=np.zeros((n,), np.uint32),
            mal_member=np.full((n, cfg.k_malicious), EMPTY_U32, np.uint32),
            dly_gt=np.full((n, d), EMPTY_U32, np.uint32),
            dly_member=np.full((n, d), EMPTY_U32, np.uint32),
            dly_meta=np.full((n, d), EMPTY_U32, np.uint32),
            dly_payload=np.full((n, d), EMPTY_U32, np.uint32),
            dly_aux=np.zeros((n, d), np.uint32),
            dly_since=np.zeros((n, d), np.uint32))
    return state
