"""Checkpoint/restore of the overlay state.

The reference's checkpoint is its SQLite file: every sync-distributed
message persists in the ``sync`` table, ``Community.load_community``
replays identity/authorize/revoke to rebuild the Timeline and resumes
``global_time``, and candidates are *not* persisted — a restarted peer
re-walks from the trackers (SURVEY.md §5.4).

TPU recast: the whole overlay is one ``PeerState`` pytree, so a checkpoint
is a flat archive of its leaves plus a config fingerprint and the RNG
key/round counter (which the reference has no analogue for — its
randomness is wall-clock; ours must resume bit-exactly).  Two restore
modes:

- ``fresh_candidates=False`` (default): byte-exact resume — stepping the
  restored state replays the identical trajectory, which is what the
  determinism tests pin.
- ``fresh_candidates=True``: the reference's restart semantics — the
  in-memory half dies with the process (candidate tables, the signature
  request cache, the delayed-message pen, malicious convictions) and
  peers re-walk from their trackers; stores, clocks, auth tables and
  stats survive (they live in "the database").

Format: one ``.npz`` with dotted-path keys per leaf.  On a multi-host mesh
each host would save its addressable shards to its own file (orbax-style
sharded layout); this single-file writer covers the single-host bench and
test environments and keeps the format inspectable.
"""

from __future__ import annotations

import functools
import io
import os
import zipfile
import zlib

import jax
import numpy as np

from dispersy_tpu.config import CommunityConfig
from dispersy_tpu.exceptions import CheckpointError
from dispersy_tpu.faults import FaultModel
from dispersy_tpu.state import PeerState, init_state, wipe_instance_memory
from dispersy_tpu.telemetry import TelemetryConfig

# v2: PeerState gained the signature request cache (sig_*) and Stats the
# sig_signed/sig_done/sig_expired counters — v1 archives lack those leaves.
# v3: + the malicious-member blacklist (mal_member) and conflicts counter.
# v4: + the delayed-message pen (dly_*) and msgs_delayed counter.
# v5: + the pen's deliverer column (dly_src) and the proof_requests /
#     proof_records counters (active missing-proof round trips).
# v6: PeerState gained the `loaded` leaf.
# v7: + auth_issuer (retro re-walk handle) and the auth_unwound/msgs_retro
#     + mm_*/id_* counter leaves.
# v8: store_meta/fwd_meta/dly_meta narrowed to uint8
#     (EMPTY_META holes) and store_flags to uint8 — the bandwidth diet
#     (config.META_DTYPE/FLAGS_DTYPE).  v7 archives still load: the
#     sentinel is EMPTY_U32's low byte, so plain uint32 -> uint8
#     truncation is the lossless up-conversion (_upconvert_v7).
# v9: per-leaf CRC32s (``crc:<leaf>`` keys — a bit-flipped or
#     short-written archive raises CheckpointError instead of silently
#     restoring garbage) + the chaos-harness leaves
#     (health / ge_bad / stats.msgs_corrupt_dropped, knob-sized;
#     dispersy_tpu/faults.py).  v7/v8 archives still load: they carry no
#     CRCs to verify, their missing fault leaves default to the
#     template's empty values, and their config fingerprint predates the
#     ``faults`` field (_legacy_fingerprint) — restoring one under a
#     non-default FaultModel is refused.
# v10: the telemetry-plane leaves (walk_streak /
#     tele_row / tele_ring / fr_ring / fr_pos, knob-sized —
#     dispersy_tpu/telemetry.py).  v7-v9 archives still load: their
#     missing telemetry leaves default to the template's (zero-width)
#     values and their config fingerprint predates the ``telemetry``
#     field — restoring one under a non-default TelemetryConfig is
#     refused (_want_fingerprint strips the ``telemetry=...`` repr
#     component, plus ``faults=...`` for pre-v9).
# v11: fleet archives (dispersy_tpu/fleet.py /
#     FLEET.md) — ``save_fleet`` stamps ``meta:replicas`` and stores
#     every leaf with its leading replica axis, plus the traced
#     per-replica override columns (``leaf:fleetov/<knob>``).  Single-
#     run archives are unchanged leaf-for-leaf (no new leaves), so v10
#     singles load verbatim, and any accepted single-run archive
#     (v7-v10 included) loads through ``restore_fleet`` as a 1-replica
#     fleet; ``restore_replica`` splits one replica back out of a fleet
#     archive for single-run post-mortem tooling.
# v12: the recovery-plane leaves (backoff / quar_until / repair_round
#     + the stats recov_* counters, knob-sized —
#     dispersy_tpu/recovery.py; RECOVERY.md).  v7-v11 archives still
#     load: their missing recovery leaves default to the template's
#     (zero-width) values and their config fingerprint predates the
#     ``recovery`` field (declared third-to-last, directly before
#     ``telemetry``) — restoring one under a non-default RecoveryConfig
#     is refused (_want_fingerprint strips the ``recovery=...`` repr
#     component, plus ``telemetry=`` pre-v10 and ``faults=`` pre-v9).
#     v11 FLEET archives load through ``restore_fleet`` the same way.
# v13: the ingress-protection leaves (bucket +
#     the stats msgs_shed_rate / msgs_shed_priority counters,
#     knob-sized — dispersy_tpu/overload.py; OVERLOAD.md).  v7-v12
#     archives still load: their missing overload leaves default to
#     the template's (zero-width) values and their config fingerprint
#     predates the ``overload`` field (declared fourth-to-last,
#     directly before ``recovery``) — restoring one under a
#     non-default OverloadConfig is refused (_want_fingerprint strips
#     the ``overload=...`` repr component first, then the older
#     planes').  v11/v12 FLEET archives load through ``restore_fleet``
#     the same way.
# v14: the byte-diet store-plane leaves (sta_* +
#     digest, knob-sized — dispersy_tpu/storediet.py; the STORE section
#     in README) plus the PLANE-SIZED community-feature leaves: the
#     auth table / blacklist / signature cache and ~13 feature-gated
#     stats counters are zero-width when their feature is compiled out
#     (state.stats_gates), and the aux columns may be u16 under
#     store.aux_bits=16.  v7-v13 archives still load: missing staging/
#     digest leaves default to the template's (empty) values, their
#     config fingerprint predates the ``store`` field (declared
#     fifth-to-last, directly before ``overload``) — restoring one
#     under a non-default StoreConfig is refused — and a pre-v14
#     archive's FULL-width auth/mal/sig/stats leaves for a plane the
#     config compiles out are CRC-verified, asserted empty, and sized
#     down (_resize_plane_leaf).
# v16: the parallel plane (the cross-shard shed
#     counter ``stats/xshard_shed``, knob-sized — the ragged-exchange
#     backpressure stream of dispersy_tpu/shardplane.py; PARALLEL.md).
#     v7-v15 archives still load: the missing counter defaults to the
#     template's (zero-width) value and their config fingerprint
#     predates the ``parallel`` field (declared seventh-to-last,
#     directly before ``trace``) — restoring one under a non-default
#     ParallelConfig is refused (_want_fingerprint strips the
#     ``parallel=...`` repr component first, then the older planes').
#     v15: the dissemination-tracing leaves (the
#     trace_member/trace_gt key registry, per-peer trace_first/
#     trace_chan/trace_dups lineage, the trace_latch coverage
#     percentiles, and the stats trace_delivered/trace_dup channel
#     counters, knob-sized — dispersy_tpu/traceplane.py;
#     OBSERVABILITY.md "Dissemination tracing").  v11-v15 FLEET
#     archives load through ``restore_fleet`` the same way.
FORMAT_VERSION = 17  # v17: the cohort-staggered compaction leaves
#     (``cohort``/``epoch``, knob-sized — zero-width unless
#     cfg.store_stagger; storediet.cohorts, STORE.md "Cohort cadence")
#     plus the u16 candidate round-stamp narrowing (store.cand_bits=16:
#     the cand_last_walk/stumble/intro leaves become quantized u16).
#     v7-v16 archives still load: the missing cohort/epoch leaves
#     default to the template's (zero-width) values, and their config
#     fingerprint predates StoreConfig's two NEW TRAILING fields —
#     restoring one under non-default cohorts/cand_bits is refused
#     (_want_fingerprint strips the ", cohorts=1, cand_bits=32" repr
#     suffix from the store component, then the older planes').
_ACCEPTED_VERSIONS = (7, 8, 9, 10, 11, 12, 13, 14, 15, 16,
                      FORMAT_VERSION)
_FLEET_VERSIONS = (11, 12, 13, 14, 15, 16, FORMAT_VERSION)

# Leaves whose dtype narrowed u32 -> u8 at v8; a v7 archive's u32 arrays
# convert by truncation (0xFFFFFFFF -> 0xFF, real values < 256 unchanged).
_NARROWED_V8 = frozenset(
    {"store_meta", "store_flags", "fwd_meta", "dly_meta"})

# Leaves that did not exist before v9: filled from the config template
# (all-zero / empty) when restoring an older archive.
_NEW_V9 = frozenset(
    {"health", "ge_bad", "stats/msgs_corrupt_dropped"})

# Leaves that did not exist before v10 (the telemetry plane).  Older
# archives only restore under a default TelemetryConfig (enforced by
# _want_fingerprint), where every one of these is zero-width — the
# template default IS the archived state.
_NEW_V10 = frozenset(
    {"walk_streak", "tele_row", "tele_ring", "fr_ring", "fr_pos"})

# Leaves that did not exist before v12 (the recovery plane).  Older
# archives only restore under a default RecoveryConfig (enforced by
# _want_fingerprint), where every one of these is zero-width.
_NEW_V12 = frozenset(
    {"backoff", "quar_until", "repair_round",
     "stats/recov_soft", "stats/recov_backoff",
     "stats/recov_quarantine", "stats/recov_cleared"})

# Leaves that did not exist before v13 (the ingress-protection plane).
# Older archives only restore under a default OverloadConfig (enforced
# by _want_fingerprint), where every one of these is zero-width.
_NEW_V13 = frozenset(
    {"bucket", "stats/msgs_shed_rate", "stats/msgs_shed_priority"})

# Leaves that did not exist before v14 (the byte-diet store plane).
# Older archives only restore under a default StoreConfig (enforced by
# _want_fingerprint), where every one of these is zero-width.
_NEW_V14 = frozenset(
    {"sta_gt", "sta_member", "sta_meta", "sta_payload", "sta_aux",
     "sta_flags", "digest"})

# Leaves that did not exist before v15 (the dissemination-tracing
# plane).  Older archives only restore under a default TraceConfig
# (enforced by _want_fingerprint), where every one of these is
# zero-width.
_NEW_V15 = frozenset(
    {"trace_member", "trace_gt", "trace_first", "trace_chan",
     "trace_dups", "trace_latch",
     "stats/trace_delivered", "stats/trace_dup"})

# Leaves that did not exist before v16 (the parallel plane).  Older
# archives only restore under a default ParallelConfig (enforced by
# _want_fingerprint), where this counter is zero-width.
_NEW_V16 = frozenset({"stats/xshard_shed"})

# Leaves that did not exist before v17 (cohort-staggered compaction).
# Older archives only restore under default cohorts/cand_bits (enforced
# by _want_fingerprint), where both leaves are zero-width.
_NEW_V17 = frozenset({"cohort", "epoch"})

# The introduction registry, one row per format version that added
# leaves — the machine-readable half of the version-history prose above.
# A NEW leaf MUST be registered here under the bumped FORMAT_VERSION, or
# restoring every older archive raises "checkpoint missing field"
# instead of defaulting the leaf from the template (graftlint R7 checks
# every extracted schema leaf against :func:`leaf_manifest`, and R8
# refuses a leaf change without the version bump).
_NEW_BY_VERSION: dict = {
    9: _NEW_V9, 10: _NEW_V10, 12: _NEW_V12, 13: _NEW_V13,
    14: _NEW_V14, 15: _NEW_V15, 16: _NEW_V16, 17: _NEW_V17,
}


def _missing_ok(name: str, version: int) -> bool:
    """May ``name`` be absent from a ``version`` archive (leaf introduced
    later — restore defaults it from the config template)?"""
    return any(version < v and name in new
               for v, new in _NEW_BY_VERSION.items())


def leaf_manifest(cfg: CommunityConfig | None = None) -> dict:
    """The exported checkpoint leaf manifest: every PeerState leaf path
    -> the format version that introduced it (leaves predating the
    version registry map to the oldest accepted version).  Built from
    the ABSTRACT template (``jax.eval_shape`` — no arrays materialize),
    so it is cheap enough for lint/tooling to call freely."""
    if cfg is None:
        cfg = CommunityConfig()
    template = jax.eval_shape(functools.partial(init_state, cfg),
                              jax.ShapeDtypeStruct((2,), np.uint32))
    names, _leaves, _ = _leaves_with_paths(template)
    manifest = {}
    for name in names:
        introduced = [v for v, new in _NEW_BY_VERSION.items()
                      if name in new]
        manifest[name] = max(introduced) if introduced \
            else _ACCEPTED_VERSIONS[0]
    return manifest

# Leaves v14 PLANE-SIZED (zero-width when their community feature is
# compiled out — state.py init_state / stats_gates): a pre-v14 archive
# carries them at full width but PROVABLY EMPTY (the engine only ever
# writes them under the same feature flags), so restore verifies the
# CRC, asserts every element is the leaf's empty value, and sizes the
# leaf down to the template.  Map: leaf name -> its empty fill.
_PLANE_SIZED_FILLS = {
    "auth_member": 0xFFFFFFFF, "auth_mask": 0, "auth_gt": 0,
    "auth_rev": False, "auth_issuer": 0xFFFFFFFF,
    "mal_member": 0xFFFFFFFF,
    "sig_target": -1, "sig_meta": 0, "sig_payload": 0, "sig_gt": 0,
    "sig_since": 0,
    **{f"stats/{nm}": 0 for nm in (
        "msgs_rejected", "msgs_direct", "msgs_delayed",
        "proof_requests", "proof_records", "seq_requests", "seq_records",
        "mm_requests", "mm_records", "id_requests", "id_records",
        "sig_signed", "sig_done", "sig_expired", "conflicts",
        "convictions_rx", "auth_unwound", "msgs_retro")},
}


def _resize_plane_leaf(name: str, arr: np.ndarray, t,
                       what: str, lead_axes: int = 0) -> np.ndarray:
    """Size a pre-v14 archive's full-width plane leaf down to the
    template's (possibly zero) width, refusing loudly if any content
    would be discarded.  ``lead_axes``: extra leading axes to ignore
    (the fleet reader's replica axis)."""
    if name not in _PLANE_SIZED_FILLS:
        return arr
    t_shape = tuple(t.shape)
    if tuple(arr.shape[lead_axes:]) == t_shape or arr.dtype != t.dtype:
        return arr
    fill = _PLANE_SIZED_FILLS[name]
    if arr.dtype != np.bool_:
        fill = np.asarray(fill, arr.dtype)
    if arr.size and not np.all(arr == fill):
        raise CheckpointError(
            f"checkpoint {what}: field {name} carries data for a "
            "feature the given config compiles out (plane-sized leaf) "
            "— restore under the config that produced it")
    lead = tuple(arr.shape[:lead_axes])
    return np.broadcast_to(np.asarray(fill, t.dtype),
                           lead + t_shape).copy()


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _verify_crc(z, key: str, arr: np.ndarray, what: str) -> None:
    crc_key = f"crc:{key[len('leaf:'):]}"
    if crc_key not in z:
        raise CheckpointError(
            f"checkpoint {what}: CRC entry {crc_key} missing — "
            "truncated or foreign archive")
    want = int(z[crc_key])
    got = _crc(arr)
    if got != want:
        raise CheckpointError(
            f"checkpoint {what}: CRC mismatch on {key} "
            f"(stored {want:#010x}, computed {got:#010x}) — corrupt "
            "archive, refusing to restore")


def _upconvert_v7(name: str, arr: np.ndarray,
                  want_dtype: np.dtype) -> np.ndarray:
    if (name in _NARROWED_V8 and arr.dtype == np.uint32
            and np.dtype(want_dtype) == np.uint8):
        return arr.astype(np.uint8)
    return arr


def _fingerprint(cfg: CommunityConfig) -> str:
    """Config identity a checkpoint is only valid against."""
    return repr(cfg)


def _want_fingerprint(cfg: CommunityConfig, version: int) -> str:
    """The fingerprint an archive of ``version`` should carry for
    ``cfg``.  Pre-v13 archives were written before CommunityConfig grew
    the ``overload`` field (declared fourth-to-last, directly before
    ``recovery``), pre-v12 ones before ``recovery`` (third-to-last,
    directly before ``telemetry``), pre-v10 ones before ``telemetry``
    (second-to-last, directly before ``faults``), and pre-v9 ones
    before ``faults`` (declared LAST) — every repr component strips
    cleanly, but only default models can possibly match what the old
    writer simulated."""
    if version >= 17:
        return _fingerprint(cfg)
    # Pre-v17 archives were written before StoreConfig grew its two
    # TRAILING fields (cohorts / cand_bits — storediet.py pins them
    # last for exactly this strip): only the defaults can match what
    # the old writer simulated, and stripping their repr suffix
    # recovers the old store component in place.
    if cfg.store.cohorts != 1 or cfg.store.cand_bits != 32:
        raise CheckpointError(
            f"checkpoint format {version} predates the cohort-staggered "
            "store fields; it can only restore under the defaults "
            "(cfg.store.cohorts == 1 and cfg.store.cand_bits == 32)")
    full17 = repr(cfg)
    sfields = ", cohorts=1, cand_bits=32"
    if full17.count(sfields) != 1:
        raise CheckpointError(
            "cannot derive pre-v17 fingerprint: cohorts/cand_bits are "
            "no longer StoreConfig's two last fields")
    full17 = full17.replace(sfields, "", 1)
    if version >= 16:
        return full17
    from dispersy_tpu.shardplane import ParallelConfig
    if cfg.parallel != ParallelConfig():
        raise CheckpointError(
            f"checkpoint format {version} predates the parallel plane; "
            "it can only restore under the default ParallelConfig "
            "(cfg.parallel must be ParallelConfig())")
    full16 = full17
    pcomp = f", parallel={cfg.parallel!r}"
    if full16.count(pcomp) != 1:
        raise CheckpointError(
            "cannot derive pre-v16 fingerprint: parallel is no longer "
            "a direct config field directly before trace")
    full16 = full16.replace(pcomp, "", 1)
    if version >= 15:
        return full16
    from dispersy_tpu.traceplane import TraceConfig
    if cfg.trace != TraceConfig():
        raise CheckpointError(
            f"checkpoint format {version} predates the dissemination-"
            "tracing plane; it can only restore under the default "
            "TraceConfig (cfg.trace must be TraceConfig())")
    full = full16
    trcomp = f", trace={cfg.trace!r}"
    if full.count(trcomp) != 1:
        raise CheckpointError(
            "cannot derive pre-v15 fingerprint: trace is no longer a "
            "direct config field directly before store")
    full = full.replace(trcomp, "", 1)
    if version >= 14:
        return full
    from dispersy_tpu.storediet import StoreConfig
    if cfg.store != StoreConfig():
        raise CheckpointError(
            f"checkpoint format {version} predates the byte-diet store "
            "plane; it can only restore under the default StoreConfig "
            "(cfg.store must be StoreConfig())")
    # the v17 trailing fields were already stripped from `full` above —
    # strip them from this component's repr the same way
    scomp = f", store={cfg.store!r}".replace(sfields, "", 1)
    if full.count(scomp) != 1:
        raise CheckpointError(
            "cannot derive pre-v14 fingerprint: store is no longer a "
            "direct config field directly before overload")
    full = full.replace(scomp, "", 1)
    if version >= 13:
        return full
    from dispersy_tpu.overload import OverloadConfig
    if cfg.overload != OverloadConfig():
        raise CheckpointError(
            f"checkpoint format {version} predates the ingress-"
            "protection plane; it can only restore under the default "
            "OverloadConfig (cfg.overload must be OverloadConfig())")
    ocomp = f", overload={cfg.overload!r}"
    if full.count(ocomp) != 1:
        raise CheckpointError(
            "cannot derive pre-v13 fingerprint: overload is no longer "
            "a direct config field directly before recovery")
    full = full.replace(ocomp, "", 1)
    if version >= 12:
        return full
    from dispersy_tpu.recovery import RecoveryConfig
    if cfg.recovery != RecoveryConfig():
        raise CheckpointError(
            f"checkpoint format {version} predates the recovery plane; "
            "it can only restore under the default RecoveryConfig "
            "(cfg.recovery must be RecoveryConfig())")
    rcomp = f", recovery={cfg.recovery!r}"
    if full.count(rcomp) != 1:
        raise CheckpointError(
            "cannot derive pre-v12 fingerprint: recovery is no longer "
            "a direct config field directly before telemetry")
    if version >= 10:
        return full.replace(rcomp, "", 1)
    if cfg.telemetry != TelemetryConfig():
        raise CheckpointError(
            f"checkpoint format {version} predates the telemetry plane; "
            "it can only restore under the default TelemetryConfig "
            "(cfg.telemetry must be TelemetryConfig())")
    full = full.replace(rcomp, "", 1)
    tcomp = f", telemetry={cfg.telemetry!r}"
    if full.count(tcomp) != 1:
        raise CheckpointError(
            "cannot derive pre-v10 fingerprint: telemetry is no longer "
            "a direct config field directly before faults")
    full = full.replace(tcomp, "", 1)
    if version >= 9:
        return full
    if cfg.faults != FaultModel():
        raise CheckpointError(
            f"checkpoint format {version} predates the fault model; it "
            "can only restore under the default FaultModel "
            "(cfg.faults must be FaultModel())")
    suffix = f", faults={cfg.faults!r})"
    if not full.endswith(suffix):
        raise CheckpointError("cannot derive pre-v9 fingerprint: faults "
                              "is no longer the last config field")
    return full[:-len(suffix)] + ")"


def _np_load(path: str):
    """np.load that converts unreadable/truncated archives into
    CheckpointError (a half-written autosave must be REJECTED, and then
    skipped by resume-from-latest-valid — never a raw zipfile crash)."""
    try:
        return np.load(path)
    except CheckpointError:
        raise
    except Exception as e:  # noqa: BLE001 — BadZipFile/EOF/OSError/...
        raise CheckpointError(
            f"checkpoint {path} unreadable ({type(e).__name__}: {e}) — "
            "truncated or torn archive") from e


# What a corrupt archive raises MID-READ: np.load only parses the zip
# directory, so a bit flip inside a member's compressed byte stream
# surfaces from ``z[key]`` as BadZipFile ("Bad CRC-32") / zlib.error —
# long before our own per-leaf CRC can even see the bytes.
_ARCHIVE_ERRORS = (zipfile.BadZipFile, zlib.error, EOFError, OSError,
                   ValueError)


def _archive_guard(fn):
    """Wrap a restore entry point so corruption surfacing mid-read still
    becomes CheckpointError — resume's latest-valid scan must be able to
    skip the snapshot, never crash on a raw zipfile traceback."""
    @functools.wraps(fn)
    def wrapped(path, cfg, *args, **kwargs):
        try:
            return fn(path, cfg, *args, **kwargs)
        except CheckpointError:
            raise
        except _ARCHIVE_ERRORS as e:
            raise CheckpointError(
                f"checkpoint {path}: read failed mid-restore "
                f"({type(e).__name__}: {e}) — corrupt or torn "
                "archive") from e
    return wrapped


def _leaves_with_paths(state: PeerState):
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    names = ["/".join(str(getattr(k, "name", k)) for k in path)
             for path, _ in flat]
    return names, [leaf for _, leaf in flat], treedef


def save(path: str, state: PeerState, cfg: CommunityConfig) -> None:
    """Write the full overlay state to ``path`` (.npz), with one CRC32
    per leaf so restore detects bit-flips/truncation (v9)."""
    names, leaves, _ = _leaves_with_paths(state)
    arrays = {f"leaf:{n}": np.asarray(jax.device_get(leaf))
              for n, leaf in zip(names, leaves)}
    for n in names:
        arrays[f"crc:{n}"] = np.asarray(_crc(arrays[f"leaf:{n}"]),
                                        np.uint32)
    arrays["meta:version"] = np.asarray(FORMAT_VERSION)
    arrays["meta:config"] = np.frombuffer(
        _fingerprint(cfg).encode(), dtype=np.uint8)
    _atomic_npz(path, arrays)


@_archive_guard
def restore(path: str, cfg: CommunityConfig,
            fresh_candidates: bool = False) -> PeerState:
    """Load a checkpoint written by :func:`save`.

    Raises ValueError on a config mismatch — a checkpoint is only
    meaningful against the exact static configuration that produced it.
    Re-shard the result afterwards with ``parallel.shard_state`` (the
    archive stores unsharded host arrays).
    """
    with _np_load(path) as z:
        version = int(z["meta:version"])
        if version not in _ACCEPTED_VERSIONS:
            raise CheckpointError(f"checkpoint format {version}, "
                             f"expected {FORMAT_VERSION}")
        if "meta:replicas" in z:
            raise CheckpointError(
                "this is a FLEET archive (meta:replicas = "
                f"{int(z['meta:replicas'])}); restore it with "
                "restore_fleet, or split one replica out with "
                "restore_replica")
        stored_cfg = bytes(z["meta:config"]).decode()
        want_fp = _want_fingerprint(cfg, version)
        if stored_cfg != want_fp:
            raise CheckpointError(
                "checkpoint was written under a different config:\n"
                f"  stored: {stored_cfg}\n  given:  {want_fp}")
        # Template provides the treedef (and validates shapes below).
        template = init_state(cfg, jax.random.PRNGKey(0))
        names, t_leaves, treedef = _leaves_with_paths(template)
        leaves = []
        for n, t in zip(names, t_leaves):
            key = f"leaf:{n}"
            if key not in z:
                if _missing_ok(n, version):
                    # the leaf postdates this archive's format
                    # (_NEW_BY_VERSION): it starts at its template
                    # default (zero-width / empty latch / all-good
                    # channels)
                    leaves.append(np.asarray(t))
                    continue
                raise CheckpointError(f"checkpoint missing field {n}")
            arr = z[key]
            if version >= 9:
                _verify_crc(z, key, arr, path)
            if version < 8:
                arr = _upconvert_v7(n, arr, t.dtype)
            if version < 14:
                arr = _resize_plane_leaf(n, arr, t, path)
            if arr.shape != t.shape or arr.dtype != t.dtype:
                raise CheckpointError(
                    f"field {n}: checkpoint {arr.shape}/{arr.dtype} vs "
                    f"config {t.shape}/{t.dtype}")
            leaves.append(arr)
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if fresh_candidates:
        state = _wipe_ephemeral(state, cfg)
    return state


def _wipe_ephemeral(state: PeerState, cfg: CommunityConfig) -> PeerState:
    """Reference restart semantics: everything that lives in process
    memory (not the database) is ephemeral — candidates (the walker
    re-bootstraps from trackers, SURVEY §5.4), the signature
    RequestCache, the delayed-message pen, and malicious-member
    convictions all die with the process, like the engine's churn
    rebirth — EXCEPT ``loaded``: rebirth is a wiped-disk NEW participant
    whose join is an explicit load, while this is the SAME app restarting
    on its database, so with ``auto_load`` off an explicit pre-crash
    unload survives (the full boundary: engine.unload_members)."""
    n = cfg.n_peers
    state = wipe_instance_memory(state, np.ones((n,), bool))
    return state.replace(
        # An app restart re-loads its stored communities (reference:
        # Dispersy.start + auto_load), whatever their pre-crash state —
        # but with auto_load OFF only an explicit Load does (config.py
        # contract), so an explicit pre-crash Unload survives restart.
        loaded=(np.ones((n,), bool) if cfg.auto_load
                else np.asarray(state.loaded, bool)))


# ---- fleet archives (v11; dispersy_tpu/fleet.py / FLEET.md) ------------

_FLEETOV_PREFIX = "leaf:fleetov/"


def save_fleet(path: str, fstate: PeerState, cfg: CommunityConfig,
               overrides: dict | None = None) -> None:
    """Write an R-replica fleet archive: every ``PeerState`` leaf with
    its leading replica axis, the replica count, and the traced
    per-replica override columns (``{knob: f32[R]}`` — the values that,
    with the seeds already inside the state's key leaf, fully determine
    each replica's trajectory under the shared static ``cfg``).  One
    CRC32 per entry, like :func:`save`."""
    names, leaves, _ = _leaves_with_paths(fstate)
    n_rep = int(np.shape(jax.device_get(fstate.round_index))[0])
    arrays = {f"leaf:{n}": np.asarray(jax.device_get(leaf))
              for n, leaf in zip(names, leaves)}
    for name, val in (overrides or {}).items():
        col = np.asarray(jax.device_get(val), np.float32)
        if col.shape != (n_rep,):
            raise CheckpointError(
                f"override column {name}: shape {col.shape}, fleet has "
                f"{n_rep} replicas")
        arrays[f"leaf:fleetov/{name}"] = col
    for k in list(arrays):
        arrays[f"crc:{k[len('leaf:'):]}"] = np.asarray(_crc(arrays[k]),
                                                       np.uint32)
    arrays["meta:version"] = np.asarray(FORMAT_VERSION)
    arrays["meta:replicas"] = np.asarray(n_rep)
    arrays["meta:config"] = np.frombuffer(
        _fingerprint(cfg).encode(), dtype=np.uint8)
    _atomic_npz(path, arrays)


@_archive_guard
def restore_fleet(path: str, cfg: CommunityConfig):
    """Load ``(fstate, overrides_dict | None)`` from a fleet archive.

    Any accepted SINGLE-RUN archive (v7-v11) also loads here, coming
    back as a 1-replica fleet with no overrides — old checkpoints feed
    straight into fleet tooling.  Fleet leaves verify per-leaf CRCs and
    shapes ``(R,) + template``; a corrupt/torn archive raises
    ``CheckpointError`` exactly like the single-run reader.
    """
    from dispersy_tpu.state import stack_states

    with _np_load(path) as z:
        if "meta:replicas" not in z:
            pass     # single-run archive: fall through to restore()
        else:
            version = int(z["meta:version"])
            if version not in _FLEET_VERSIONS:
                raise CheckpointError(
                    f"fleet archives exist only at formats "
                    f"{_FLEET_VERSIONS}, got {version}")
            stored_cfg = bytes(z["meta:config"]).decode()
            want_fp = _want_fingerprint(cfg, version)
            if stored_cfg != want_fp:
                raise CheckpointError(
                    "fleet checkpoint was written under a different "
                    f"config:\n  stored: {stored_cfg}\n"
                    f"  given:  {want_fp}")
            n_rep = int(z["meta:replicas"])
            if n_rep < 1:
                raise CheckpointError(f"meta:replicas = {n_rep}")
            template = init_state(cfg, jax.random.PRNGKey(0))
            names, t_leaves, treedef = _leaves_with_paths(template)
            leaves = []
            for n, t in zip(names, t_leaves):
                key = f"leaf:{n}"
                if key not in z:
                    if _missing_ok(n, version):
                        # the leaf postdates this fleet archive's
                        # format (_NEW_BY_VERSION): only accepted under
                        # the default plane config (fingerprint check
                        # above), where every such leaf is zero-width —
                        # replicate the template default.
                        leaves.append(np.zeros((n_rep,) + tuple(t.shape),
                                               t.dtype))
                        continue
                    raise CheckpointError(
                        f"fleet checkpoint missing field {n}")
                arr = z[key]
                _verify_crc(z, key, arr, path)
                if version < 14:
                    arr = _resize_plane_leaf(n, arr, t, path,
                                             lead_axes=1)
                want = (n_rep,) + tuple(t.shape)
                if tuple(arr.shape) != want or arr.dtype != t.dtype:
                    raise CheckpointError(
                        f"field {n}: checkpoint {arr.shape}/{arr.dtype} "
                        f"vs fleet of {n_rep} x config "
                        f"{t.shape}/{t.dtype}")
                leaves.append(arr)
            ov = {}
            for key in z.files:
                if not key.startswith(_FLEETOV_PREFIX):
                    continue
                arr = z[key]
                _verify_crc(z, key, arr, path)
                if arr.shape != (n_rep,):
                    raise CheckpointError(
                        f"override column {key}: shape {arr.shape}, "
                        f"fleet has {n_rep} replicas")
                ov[key[len(_FLEETOV_PREFIX):]] = arr
            return (jax.tree_util.tree_unflatten(treedef, leaves),
                    ov or None)
    # Single-run archive (any accepted version): one replica, no
    # overrides — restore() handles versioning/up-conversion/CRCs.
    single = jax.tree_util.tree_map(np.asarray, restore(path, cfg))
    return stack_states([single]), None


def restore_replica(path: str, cfg: CommunityConfig, i: int) -> PeerState:
    """Split ONE replica out of a fleet archive as an ordinary
    single-run ``PeerState`` (host arrays) — the post-mortem handle:
    feed it to ``debug_validate``, the oracle differ, or re-save it
    with :func:`save` as a plain single-run checkpoint."""
    from dispersy_tpu.state import index_state

    fstate, _ = restore_fleet(path, cfg)
    n_rep = int(np.shape(fstate.round_index)[0])
    if not 0 <= i < n_rep:
        raise CheckpointError(
            f"replica index {i} out of range for a {n_rep}-replica "
            "fleet")
    return jax.tree_util.tree_map(np.asarray, index_state(fstate, i))


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True      # exists, owned by someone else
    except OSError:
        return True      # unknown — do not touch
    return True


def _clean_stale_tmps(path: str) -> None:
    """Remove ``{path}.tmp.<pid>`` orphans left by a saver that crashed
    between the write and the os.replace.  Only tmps whose pid is
    provably dead are removed — a live pid may be a concurrent
    save_sharded rank mid-write (its unique tmp is the whole point).
    Best-effort: same-host pid semantics; cross-host shared directories
    clean their own orphans."""
    import glob as _glob

    for old in _glob.glob(f"{path}.tmp.*"):
        suffix = old.rsplit(".", 1)[-1]
        try:
            pid = int(suffix)
        except ValueError:
            continue
        if pid != os.getpid() and _pid_alive(pid):
            continue
        try:
            os.remove(old)
        except OSError:
            pass


def _atomic_npz(path: str, arrays: dict) -> None:
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    # pid-unique tmp: concurrent multi-process savers (save_sharded with
    # clean_stale=False) all write meta.npz with identical content — a
    # SHARED tmp path would let one rank's os.replace yank another's
    # file mid-write (FileNotFoundError / torn publish); unique tmps
    # make the last replace win harmlessly.  Stale tmps from CRASHED
    # savers are swept first (a crash between write and replace leaks
    # the tmp forever otherwise), and our own tmp is unlinked on any
    # failure so the leak cannot recur.
    _clean_stale_tmps(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:   # atomic-ish: no torn checkpoint files
            f.write(buf.getvalue())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def save_sharded(dirpath: str, state: PeerState,
                 cfg: CommunityConfig, clean_stale: bool = True) -> None:
    """Multi-host sharded layout: one file per device holding only that
    device's addressable shards of the peer-axis leaves.

    Shard keys carry the GLOBAL row range (``leaf:<name>:rows<lo>_<hi>``),
    so reassembly is mesh-shape-agnostic: a checkpoint saved on an 8-way
    mesh restores onto 4-way, 2-way, or a single device bit-exactly
    (:func:`restore_sharded`).  On a real multi-host pod each process
    calls this against a shared directory and writes only its own
    addressable shards — the union of the per-host files is the
    checkpoint, orbax-style; replicated leaves (clock scalars, the RNG
    key) land in ``meta.npz``, which every process writes with identical
    content (pid-unique tmp files make the concurrent replaces safe;
    last writer wins).  Multi-process callers must pass
    ``clean_stale=False`` and clean the directory from exactly one
    process behind a barrier (tools/multihost.py).
    """
    import glob as _glob

    os.makedirs(dirpath, exist_ok=True)
    # A reused directory may hold MORE shard files than this mesh writes
    # (e.g. an older 8-way save overwritten by a 4-way one); stale files
    # would silently win over fresh rows at restore.  Clear them first —
    # UNLESS this is one process of a multi-process save (clean_stale=
    # False): concurrent savers would delete each other's fresh shards,
    # so exactly one process must clean BEFORE a barrier and all save
    # after it (tools/multihost.py does exactly this).
    if clean_stale:
        for old in _glob.glob(os.path.join(dirpath, "shard_*.npz")):
            os.remove(old)
    names, leaves, _ = _leaves_with_paths(state)
    n = cfg.n_peers
    meta = {"meta:version": np.asarray(FORMAT_VERSION),
            "meta:config": np.frombuffer(_fingerprint(cfg).encode(),
                                         dtype=np.uint8)}
    per_dev: dict[int, dict] = {}
    from dispersy_tpu.parallel import partition_kind
    for name, leaf in zip(names, leaves):
        # The partition-rule registry (parallel/mesh.py) decides the
        # shard-vs-meta split by leaf NAME — the old shape heuristic
        # (leading dim == n_peers) would misfile a replicated leaf
        # whose width happens to equal n_peers (e.g. trace_member at
        # n_peers == tracked_slots).  Zero-width plane leaves and
        # host-side saves (no addressable_shards) stay in meta.npz:
        # there is nothing to split.
        peer_sharded = (partition_kind(name) == "peers"
                        and hasattr(leaf, "addressable_shards")
                        and getattr(leaf, "ndim", 0) >= 1
                        and leaf.shape[0] == n and n > 2)
        if not peer_sharded:
            arr = np.asarray(jax.device_get(leaf))
            meta[f"leaf:{name}"] = arr
            meta[f"crc:{name}"] = np.asarray(_crc(arr), np.uint32)
            continue
        for sh in leaf.addressable_shards:
            sl = sh.index[0] if sh.index else slice(None)
            lo = 0 if sl.start is None else int(sl.start)
            hi = n if sl.stop is None else int(sl.stop)
            arr = np.asarray(sh.data)
            dev = per_dev.setdefault(sh.device.id, {})
            dev[f"leaf:{name}:rows{lo}_{hi}"] = arr
            dev[f"crc:{name}:rows{lo}_{hi}"] = np.asarray(_crc(arr),
                                                         np.uint32)
    _atomic_npz(os.path.join(dirpath, "meta.npz"), meta)
    for dev_id, arrays in per_dev.items():
        _atomic_npz(os.path.join(dirpath, f"shard_{dev_id:05d}.npz"),
                    arrays)


@_archive_guard
def restore_sharded(dirpath: str, cfg: CommunityConfig,
                    fresh_candidates: bool = False) -> PeerState:
    """Reassemble a :func:`save_sharded` checkpoint (any mesh shape).

    Returns host arrays; re-shard onto the target mesh with
    ``parallel.shard_state`` — the row-range keys make the source mesh
    width irrelevant.  Raises ValueError on version/config mismatch,
    missing rows (a lost host's shard file), or shape conflicts.
    """
    import glob as _glob

    with _np_load(os.path.join(dirpath, "meta.npz")) as z:
        version = int(z["meta:version"])
        if version not in _ACCEPTED_VERSIONS:
            raise CheckpointError(f"checkpoint format {version}, "
                             f"expected {FORMAT_VERSION}")
        stored_cfg = bytes(z["meta:config"]).decode()
        want_fp = _want_fingerprint(cfg, version)
        if stored_cfg != want_fp:
            raise CheckpointError(
                "checkpoint was written under a different config:\n"
                f"  stored: {stored_cfg}\n  given:  {want_fp}")
        if version >= 9:
            for k in z.files:
                if k.startswith("leaf:"):
                    _verify_crc(z, k, z[k], "meta.npz")
        meta_leaves = {k[len("leaf:"):]: z[k] for k in z.files
                      if k.startswith("leaf:")}
    template = init_state(cfg, jax.random.PRNGKey(0))
    names, t_leaves, treedef = _leaves_with_paths(template)
    n = cfg.n_peers
    filled: dict[str, np.ndarray] = {}
    covered: dict[str, np.ndarray] = {}
    for name, t in zip(names, t_leaves):
        if name not in meta_leaves:
            filled[name] = np.empty(t.shape, t.dtype)
            covered[name] = np.zeros((n,), bool)
    for spath in sorted(_glob.glob(os.path.join(dirpath, "shard_*.npz"))):
        with _np_load(spath) as z:
            for key in z.files:
                if not key.startswith("leaf:"):
                    continue
                if version >= 9:
                    _verify_crc(z, key, z[key], os.path.basename(spath))
                body = key[len("leaf:"):]
                name, _, rng_part = body.rpartition(":rows")
                lo, hi = (int(x) for x in rng_part.split("_"))
                if name not in filled:
                    raise CheckpointError(f"{spath}: unknown leaf {name}")
                arr = z[key]
                want = filled[name]
                if version < 8:
                    arr = _upconvert_v7(name, arr, want.dtype)
                if arr.shape[1:] != want.shape[1:] or arr.dtype != want.dtype:
                    raise CheckpointError(
                        f"field {name} rows [{lo},{hi}): shard "
                        f"{arr.shape}/{arr.dtype} vs config "
                        f"{want.shape}/{want.dtype}")
                want[lo:hi] = arr
                covered[name][lo:hi] = True
    leaves = []
    for name, t in zip(names, t_leaves):
        if name in meta_leaves:
            arr = meta_leaves[name]
            if version < 8:
                arr = _upconvert_v7(name, arr, t.dtype)
            if arr.shape != t.shape or arr.dtype != t.dtype:
                raise CheckpointError(
                    f"field {name}: checkpoint {arr.shape}/{arr.dtype} vs "
                    f"config {t.shape}/{t.dtype}")
            leaves.append(arr)
        elif _missing_ok(name, version) and not covered[name].any():
            # the leaf postdates this archive's format
            # (_NEW_BY_VERSION): template default (state.py)
            leaves.append(np.asarray(t))
        else:
            if not covered[name].all():
                missing = int((~covered[name]).sum())
                raise CheckpointError(
                    f"field {name}: {missing} peer rows missing from the "
                    "shard files (lost host?)")
            leaves.append(filled[name])
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if fresh_candidates:
        state = _wipe_ephemeral(state, cfg)
    return state
