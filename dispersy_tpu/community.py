"""The rim API: declare a Community; it compiles to kernel configuration.

The reference's application surface is a ``Community`` subclass whose
``initiate_meta_messages`` binds each message name to one policy from each
of authentication / resolution / distribution / destination (reference:
community.py ``Community.initiate_meta_messages``, message.py ``Message``,
and the four policy modules).  The rebuild keeps that declaration style at
the rim and *compiles* it down to the static ``CommunityConfig`` the fused
TPU step consumes — policy objects carry no runtime behavior here; they
are configuration, which is exactly what XLA wants them to be.

Mapping of the policy matrix onto kernel knobs:

- ``PublicResolution`` / ``LinearResolution`` -> ``protected_meta_mask``
  bit (+ ``timeline_enabled`` when any meta is linear).
- ``FullSyncDistribution(enable_sequence_number)`` -> ``seq_meta_mask``
  bit; ``priority``/``synchronization_direction`` -> ``meta_priority`` /
  ``desc_meta_mask``.
- ``LastSyncDistribution(history_size)`` -> ``last_sync_history`` entry.
- ``DirectDistribution`` -> ``direct_meta_mask`` bit.
- ``CommunityDestination(node_count)`` -> the push fanout
  (``forward_fanout`` = max node_count across metas; the reference picks
  candidates per message batch the same way).
- ``MemberAuthentication``/``NoAuthentication`` are accepted for API
  parity: in simulation every record's author IS its member id, so
  authentication is structural (SURVEY §7 stage 9: crypto off the hot
  path).

The control metas (``dispersy-authorize``/``revoke``/``undo-*``) are
built in, as in the reference's ``_initialize_meta_messages``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from dispersy_tpu import engine
from dispersy_tpu.exceptions import ConfigError, MetaNotFoundError
from dispersy_tpu.config import (MAX_USER_META, META_AUTHORIZE,
                                 META_DESTROY,
                                 META_DYNAMIC, META_REVOKE, META_UNDO_OTHER,
                                 META_UNDO_OWN, CommunityConfig,
                                 DEFAULT_PRIORITY, perm_mask)
from dispersy_tpu.state import PeerState, init_state


# ---- policy declarations (reference: authentication.py / resolution.py /
#      distribution.py / destination.py) --------------------------------


class NoAuthentication:
    pass


class MemberAuthentication:
    def __init__(self, encoding: str = "sha1"):
        self.encoding = encoding


class DoubleMemberAuthentication:
    """Two signers per record (reference: authentication.py
    DoubleMemberAuthentication + the dispersy-signature-request/-response
    flow).  ``allow_signature_rate`` stands in for the app-supplied
    ``allow_signature_func``: the probability a counterparty countersigns
    (compiled to ``CommunityConfig.countersign_rate``)."""

    def __init__(self, allow_signature_rate: float = 1.0):
        self.allow_signature_rate = allow_signature_rate


class PublicResolution:
    pass


class LinearResolution:
    pass


class DynamicResolution:
    """Runtime-switchable resolution (reference: resolution.py
    DynamicResolution): the founder flips the meta between the candidate
    policies with ``dispersy-dynamic-settings`` records.  ``policies[0]``
    is the initial policy."""

    def __init__(self, *policies):
        if not policies:
            policies = (PublicResolution(), LinearResolution())
        if not all(isinstance(p, (PublicResolution, LinearResolution))
                   for p in policies):
            raise ConfigError("DynamicResolution candidates must be "
                             "Public/LinearResolution instances")
        self.policies = policies


class FullSyncDistribution:
    def __init__(self, enable_sequence_number: bool = False,
                 synchronization_direction: str = "ASC",
                 priority: int = DEFAULT_PRIORITY):
        if synchronization_direction not in ("ASC", "DESC"):
            raise ConfigError("synchronization_direction must be ASC|DESC")
        self.enable_sequence_number = enable_sequence_number
        self.synchronization_direction = synchronization_direction
        self.priority = priority


class LastSyncDistribution:
    def __init__(self, history_size: int,
                 priority: int = DEFAULT_PRIORITY):
        if history_size < 1:
            raise ConfigError("history_size must be >= 1")
        self.history_size = history_size
        self.priority = priority


class DirectDistribution:
    pass


class CommunityDestination:
    def __init__(self, node_count: int = 10):
        self.node_count = node_count


class CandidateDestination:
    """Addressed delivery (the reference sends to explicit candidates).

    In the simulation the control plane (walks, introductions, punctures,
    sync responses) is already candidate-addressed; a user meta declaring
    this routes like Direct but to the author's sampled candidates."""


class Message:
    """One meta-message declaration (reference: message.py ``Message``)."""

    def __init__(self, name: str, authentication, resolution, distribution,
                 destination):
        self.name = name
        self.authentication = authentication
        self.resolution = resolution
        self.distribution = distribution
        self.destination = destination


class Community:
    """Subclass and override ``initiate_meta_messages`` (reference API).

    Simulation knobs (population size, walker timing, bloom sizing, fault
    model) pass through ``__init__`` overrides onto ``CommunityConfig``;
    the policy matrix comes from the declarations.
    """

    def __init__(self, n_peers: int, **overrides):
        metas = self.initiate_meta_messages()
        if len(metas) > MAX_USER_META:
            raise ConfigError(f"at most {MAX_USER_META} user metas")
        names = [m.name for m in metas]
        if len(set(names)) != len(names):
            raise ConfigError(f"duplicate meta names: {names}")
        self.meta_ids = {m.name: i for i, m in enumerate(metas)}
        self.metas = {m.name: m for m in metas}

        n_meta = max(len(metas), 1)
        protected = seq = direct = desc = double = 0
        history = [0] * n_meta
        priority = [DEFAULT_PRIORITY] * n_meta
        fanout = 0
        sign_rates = set()
        dynamic = 0
        for i, m in enumerate(metas):
            if isinstance(m.resolution, LinearResolution):
                protected |= 1 << i
            elif isinstance(m.resolution, DynamicResolution):
                dynamic |= 1 << i
                if isinstance(m.resolution.policies[0], LinearResolution):
                    protected |= 1 << i
            if isinstance(m.authentication, DoubleMemberAuthentication):
                double |= 1 << i
                sign_rates.add(m.authentication.allow_signature_rate)
            d = m.distribution
            if isinstance(d, FullSyncDistribution):
                if d.enable_sequence_number:
                    seq |= 1 << i
                if d.synchronization_direction == "DESC":
                    desc |= 1 << i
                priority[i] = d.priority
            elif isinstance(d, LastSyncDistribution):
                history[i] = d.history_size
                priority[i] = d.priority
            elif isinstance(d, (DirectDistribution, CandidateDestination)):
                direct |= 1 << i
            else:
                raise ConfigError(f"unknown distribution for {m.name}: {d}")
            if isinstance(m.destination, CommunityDestination):
                fanout = max(fanout, m.destination.node_count)
            if isinstance(m.destination, CandidateDestination):
                direct |= 1 << i

        fields = {f.name for f in dataclasses.fields(CommunityConfig)}
        bad = set(overrides) - fields
        if bad:
            raise ConfigError(f"unknown config overrides: {sorted(bad)}")
        if len(sign_rates) > 1:
            raise ConfigError("all DoubleMemberAuthentication metas must "
                             "share one allow_signature_rate (the kernel "
                             "compiles a single countersign_rate)")
        compiled = dict(
            n_peers=n_peers,
            n_meta=n_meta,
            protected_meta_mask=protected,
            seq_meta_mask=seq,
            direct_meta_mask=direct,
            desc_meta_mask=desc,
            last_sync_history=tuple(history),
            meta_priority=tuple(priority),
            dynamic_meta_mask=dynamic,
            timeline_enabled=protected != 0 or dynamic != 0,
        )
        if double:
            compiled["double_meta_mask"] = double
            compiled["countersign_rate"] = sign_rates.pop()
        if fanout:
            k_cand = overrides.get("k_candidates",
                                   CommunityConfig.k_candidates)
            compiled["forward_fanout"] = min(fanout, k_cand)
        conflict = set(compiled) & set(overrides) - {"n_peers"}
        if conflict:
            raise ConfigError(
                f"{sorted(conflict)} are compiled from the meta-message "
                "declarations; override the declarations instead")
        self.config = CommunityConfig(**{**compiled, **overrides})

    # ---- declaration hook (the reference's override point) ----
    def initiate_meta_messages(self) -> list:
        return []

    # ---- runtime conveniences over the engine ----
    def initialize(self, key=None, seed_degree: int | None = None
                   ) -> PeerState:
        state = init_state(self.config, key if key is not None
                           else jax.random.PRNGKey(0))
        if seed_degree:
            state = engine.seed_overlay(state, self.config, seed_degree)
        return state

    def meta_id(self, name: str) -> int:
        if name in self.meta_ids:
            return self.meta_ids[name]
        control = {"dispersy-authorize": META_AUTHORIZE,
                   "dispersy-revoke": META_REVOKE,
                   "dispersy-undo-own": META_UNDO_OWN,
                   "dispersy-undo-other": META_UNDO_OTHER,
                   "dispersy-dynamic-settings": META_DYNAMIC,
                   "dispersy-destroy-community": META_DESTROY}
        if name in control:
            return control[name]
        raise MetaNotFoundError(f"unknown meta {name!r}; "
                       f"declared: {sorted(self.meta_ids)}")

    def create(self, state: PeerState, name: str, author_mask, payload,
               aux=None) -> PeerState:
        """``Community.create_<name>`` — author one record per masked peer."""
        return engine.create_messages(state, self.config, author_mask,
                                      self.meta_id(name), payload, aux)

    # ---- dedicated control-message constructors (reference: community.py
    # create_authorize / create_revoke / create_undo /
    # create_dynamic_settings / create_dispersy_destroy_community — thin
    # typed fronts over the generic create path) ----
    def _grant_masks(self, triples) -> dict[int, int]:
        """[(target, meta_name[, permission])] -> {target: nibble mask}.

        Each triple names one permission type from the reference's
        quadruple (u"permit" / u"authorize" / u"revoke" / u"undo",
        timeline.py); a 2-tuple defaults to "permit".  Grants for one
        target pack into one nibble mask (config.perm_mask)."""
        by_target: dict[int, list] = {}
        for t in triples:
            target, name = t[0], t[1]
            perm = t[2] if len(t) > 2 else "permit"
            mid = self.meta_id(name)
            if mid >= self.config.n_meta:
                raise ConfigError(f"cannot grant permissions on control "
                                  f"meta {name!r}")
            by_target.setdefault(int(target), []).append((mid, perm))
        if not by_target:
            # an empty grant/revoke proves and changes nothing
            # (check_grant rejects it too) — refuse to author one
            raise ConfigError("triples must name at least one grant")
        return {t: perm_mask(pairs) for t, pairs in by_target.items()}

    def create_authorize(self, state: PeerState, author_mask,
                         triples) -> PeerState:
        """Grant permissions by [(target_member, meta_name[, permission])]
        triples — the reference's ``Community.create_authorize``
        ([(member, message, permission)]) shape; permission defaults to
        "permit".  Granting "authorize" lets the target extend the chain
        (ops/timeline.check_grant); "revoke" and "undo" convey those
        authorities separably.  Triples for one target pack into ONE
        dispersy-authorize record; distinct targets author consecutive
        records (the packed wire record names a single target — the
        reference packs the whole list into one message; same resulting
        Timeline state)."""
        n = self.config.n_peers
        for target, mask in sorted(self._grant_masks(triples).items()):
            state = self.create(state, "dispersy-authorize", author_mask,
                                payload=jnp.full(n, target, jnp.uint32),
                                aux=jnp.full(n, mask, jnp.uint32))
        return state

    def create_revoke(self, state: PeerState, author_mask,
                      triples) -> PeerState:
        """Revoke permissions by [(target_member, meta_name[, permission])]
        triples from the author's next global_time on (reference:
        Community.create_revoke).  Issuing a revoke needs the REVOKE
        authority on every named meta (or the founder) — separable from
        the authorize authority, exactly the reference's u"revoke"
        permission type."""
        n = self.config.n_peers
        for target, mask in sorted(self._grant_masks(triples).items()):
            state = self.create(state, "dispersy-revoke", author_mask,
                                payload=jnp.full(n, target, jnp.uint32),
                                aux=jnp.full(n, mask, jnp.uint32))
        return state

    def create_undo_own(self, state: PeerState, author_mask,
                        target_gt) -> PeerState:
        """Each masked author undoes ITS OWN record at ``target_gt``
        (reference: Community.create_undo on an own message ->
        dispersy-undo-own)."""
        n = self.config.n_peers
        return self.create(
            state, "dispersy-undo-own", author_mask,
            payload=jnp.arange(n, dtype=jnp.uint32),
            aux=jnp.broadcast_to(jnp.asarray(target_gt, jnp.uint32), (n,)))

    def create_undo_other(self, state: PeerState, author_mask, member,
                          target_gt) -> PeerState:
        """Undo another member's record at (member, target_gt) — founder
        authority, or the UNDO permission on the target record's meta
        (reference: dispersy-undo-other; timeline.py checks u"undo"
        against the target message's meta)."""
        n = self.config.n_peers
        return self.create(
            state, "dispersy-undo-other", author_mask,
            payload=jnp.full(n, member, jnp.uint32),
            aux=jnp.full(n, target_gt, jnp.uint32))

    def create_dynamic_settings(self, state: PeerState, author_mask,
                                meta_name: str, policy: str) -> PeerState:
        """Flip ``meta_name``'s resolution policy from the author's next
        global_time on; ``policy`` is "public" or "linear" (reference:
        Community.create_dynamic_settings with [(meta, policy)] pairs)."""
        if policy not in ("public", "linear"):
            raise ConfigError(f"policy must be 'public' or 'linear', "
                              f"got {policy!r}")
        mid = self.meta_id(meta_name)
        if not (self.config.dynamic_meta_mask >> mid) & 1:
            raise ConfigError(f"meta {meta_name!r} is not DynamicResolution")
        n = self.config.n_peers
        return self.create(
            state, "dispersy-dynamic-settings", author_mask,
            payload=jnp.full(n, mid, jnp.uint32),
            aux=jnp.full(n, 1 if policy == "linear" else 0, jnp.uint32))

    def create_destroy_community(self, state: PeerState,
                                 author_mask) -> PeerState:
        """Hard-kill the community (reference:
        Community.create_dispersy_destroy_community)."""
        n = self.config.n_peers
        return self.create(state, "dispersy-destroy-community", author_mask,
                           payload=jnp.zeros(n, jnp.uint32))

    def unload_community(self, state: PeerState, mask) -> PeerState:
        """Unload the community instance on the masked peers (reference:
        community.py Community.unload_community): they stop walking,
        serving, and taking records in; candidate tables, delay pens, and
        signature caches — instance memory — are freed; the store (the
        database) persists.  With ``auto_load`` (config) any later
        community packet re-loads them (reference: dispersy.py
        define_auto_load)."""
        return engine.unload_members(state, self.config,
                                     np.asarray(mask, bool))

    def load_community(self, state: PeerState, mask) -> PeerState:
        """Explicitly (re-)load the community instance on the masked
        peers (reference: dispersy.py get_community(load=True) /
        Community.load_community); they re-walk from the trackers, since
        candidates are never persisted."""
        return engine.load_members(state, np.asarray(mask, bool))

    def create_signature_request(self, state: PeerState, name: str,
                                 author_mask, counterparty,
                                 payload) -> PeerState:
        """``Community.create_signature_request`` — open a double-signed
        draft toward each masked peer's chosen counterparty."""
        return engine.create_signature_request(
            state, self.config, author_mask, self.meta_id(name),
            counterparty, payload)

    def step(self, state: PeerState) -> PeerState:
        """One walker interval for the whole overlay."""
        return engine.step(state, self.config)

    def coverage(self, state: PeerState, member: int, gt: int, name: str,
                 payload: int):
        return engine.coverage(state, member, gt, self.meta_id(name),
                               payload)

    # ---- dissemination tracing (dispersy_tpu/traceplane.py;
    # OBSERVABILITY.md "Dissemination tracing") ----
    def track_record(self, state: PeerState, author: int,
                     gt: int) -> tuple[PeerState, int]:
        """Register ``(author, gt)`` for on-device lineage tracing —
        per-peer first-arrival rounds, first-delivery channels, and
        duplicate-delivery counters, updated inside the fused step.
        Call right after the ``create`` that authored the record (the
        author's copy is attributed to the create channel).  Requires
        ``trace.enabled`` (TraceConfig); returns ``(state, slot)``."""
        return engine.track_record(state, self.config, author, gt)

    def trace_totals(self, state: PeerState) -> dict:
        """The trace plane's current coverage/latch/channel totals
        (traceplane.trace_totals) — the host-side snapshot of what the
        telemetry row surfaces per round."""
        from dispersy_tpu.traceplane import trace_totals
        return trace_totals(state, self.config)
