"""The ingress-protection plane: per-sender rate limiting, priority
admission under overflow, and flood-fair drop attribution.

PR 4's chaos harness proved the saturation attack (byzantine flooders
blasting junk through the push channel until victim inboxes overflow),
and PR 9's recovery plane made the aftermath *worse for the wrong
party*: the overflow drops land in the VICTIM's ``msgs_dropped``, trip
its ``health_drop_limit`` sentinel, and recovery then backs off,
candidate-flushes, and finally quarantines the flooded victim — a
wiped-disk rebirth — while the attacker keeps walking untouched.
Deployed gossip stacks defend this seam with per-sender admission
control and message-class prioritization (the flood-protection and
peer-scoring machinery formalized in *Verification of GossipSub in
ACL2s*, with *PeerSwap* motivating why sampler randomness must not be
starvable by a loud minority — PAPERS.md); the reference's
bounded-UDP-buffer endpoint (``endpoint.py``, SURVEY §2) is exactly the
layer the defense belongs to.  This module declares the static half;
the jit-traced kernels live in :mod:`dispersy_tpu.ops.overload` and the
engine composes them into the fused round only when
``OverloadConfig.enabled`` — all defaults compile to *exactly* the
protection-free step (zero-width leaves, the faults/recovery/telemetry
pattern).

Three mechanisms (OVERLOAD.md's tables):

1. **Priority admission** (``priority_admission``): when a receiver's
   push inbox overflows, packets are shed lowest-admission-class-first
   instead of first-come-first-kept.  The class is derived from the
   wire-visible meta byte (:func:`admission_class`): control records
   (authorize/revoke/undo/dynamic/destroy/malicious-proof) outrank user
   gossip, bulk identity records rank below it, and a meta byte that is
   valid for neither band — most flood junk — ranks dead last.  The
   class folds into the delivery kernel's packed ``(dst, pos)`` sort
   key (``ops/inbox.deliver``'s ``cls`` operand), so admission costs
   one extra key field, not a second sort.  The walk/puncture/signature
   control channels already own dedicated inboxes (architectural
   priority); the class ordering bites where classes actually mix — the
   push inbox, which is also where the flood lands.
2. **Per-sender token buckets** (``bucket_rate`` / ``bucket_depth``): a
   u8 credit column per peer (``PeerState.bucket``) refilled by
   ``bucket_rate`` credits per round (integer part deterministic,
   fractional part one Bernoulli counter-draw — ``rng.P_OVERLOAD`` —
   so the oracle replays it exactly and the rate is traced-liftable,
   :data:`TRACED_OVERLOAD_KNOBS`), capped at ``bucket_depth``.  Every
   push/flood packet a sender *attempts* (pre-loss, the sendto
   accounting boundary) consumes one credit in emission order; packets
   beyond the balance are shed at intake — they never occupy any
   victim's inbox slot, so one sender cannot take more than its credit
   share of the overlay's ingress no matter its fanout.
3. **Flood-fair drop attribution** (``msgs_shed_rate`` /
   ``msgs_shed_priority``): shed-by-admission drops get their own
   counter streams and do NOT count toward ``health_drop_limit``.
   Rate-gate sheds are attributed to the SENDER (``msgs_shed_rate`` —
   a flooder's counter balloons while its exhausted bucket shows up in
   :func:`overload_report`); priority-admission overflow sheds are
   recorded at the receiver (``msgs_shed_priority``) but kept out of
   the drop sentinel, so recovery stops quarantining flood victims and
   starts starving flooders.

Persistence: ``bucket`` is the *overlay's* rate-limiter view of the
sender identity — like the NAT type and the GE channel it survives a
churn rebirth (a wiped-disk restart does not refill the neighborhood's
patience with that peer).  It rides checkpoints at format v13.
"""

from __future__ import annotations

import dataclasses

from dispersy_tpu.exceptions import ConfigError

# Overload knobs the fleet plane can lift into TRACED per-replica
# scalars (the faults.TRACED_FAULT_KNOBS discipline): the refill rate
# is a pure numeric knob whose value never decides program structure.
# ``enabled`` / ``priority_admission`` / ``bucket_depth`` are
# structural (leaf shapes, sort-key layout, u8 clamp) and stay static
# compile-group keys — FLEET.md's traced-vs-static table.
TRACED_OVERLOAD_KNOBS = ("bucket_rate",)


@dataclasses.dataclass(frozen=True)
class OverloadConfig:
    """Static ingress-protection knobs, composed into
    ``CommunityConfig`` (fourth-to-last field, before recovery /
    telemetry / faults — checkpoint fingerprint compat).

    Frozen + hashable (a static jit argument).  All defaults off
    compile to exactly the protection-free step; every leaf the plane
    adds (``bucket`` and the ``msgs_shed_*`` counters) is zero-width
    while ``enabled`` is off.
    """

    # Master switch: compose the rate gate, admission classes, and the
    # shed-attribution counter streams into the fused round.
    enabled: bool = False
    # Shed push-inbox overflow lowest-class-first instead of
    # first-come-first-kept (admission_class; OVERLOAD.md class table).
    priority_admission: bool = True
    # Credits refilled per sender per round (may be fractional: the
    # integer part is deterministic, the remainder one Bernoulli draw
    # per peer per round).  Traced-liftable (TRACED_OVERLOAD_KNOBS).
    bucket_rate: float = 8.0
    # Burst cap: the u8 credit balance never exceeds this.
    bucket_depth: int = 32

    def __post_init__(self) -> None:
        if not (1 <= self.bucket_depth <= 255):
            raise ConfigError(
                f"bucket_depth must be in [1, 255] (a u8 credit "
                f"balance), got {self.bucket_depth}")
        if not (0.0 <= self.bucket_rate <= self.bucket_depth):
            raise ConfigError(
                f"bucket_rate must be in [0, bucket_depth="
                f"{self.bucket_depth}], got {self.bucket_rate} (a "
                "refill beyond the burst cap can never land)")

    def replace(self, **kw) -> "OverloadConfig":
        return dataclasses.replace(self, **kw)


def admission_class(meta: int, n_meta: int, priorities) -> int:
    """Admission class of one wire meta byte (scalar form; the traced
    form is ``ops/overload.admission_class`` and the oracle mirrors
    this one) — LOWER class wins inbox slots under overflow:

    - valid user meta (< ``n_meta``): ``255 - declared priority``
      (DEFAULT_PRIORITY=128 -> class 127);
    - dispersy-identity: ``255 - IDENTITY_PRIORITY`` = 239 (bulk data
      ranks below user gossip, the reference's low identity priority);
    - any other control-band meta (0xF0..0xF7): ``255 -
      CONTROL_PRIORITY`` = 31 (authorize proofs, convictions, destroy
      must survive a flooded inbox);
    - everything else — a meta byte valid for NEITHER band, which is
      what most flood junk carries — 255, dead last.  The receiver
      needs no crypto for this: the meta id is protocol knowledge read
      straight off the wire, exactly the check ``conversion.py``'s
      decode front-end performs before any signature work.

    In-band metas invert ``config.priority_of`` — ONE priority table
    serves the sync responder's ordering, the forward-buffer selection,
    and this admission class, so they can never drift.
    """
    from dispersy_tpu.config import (META_AUTHORIZE, META_MALICIOUS,
                                     priority_of)
    if meta < n_meta or META_AUTHORIZE <= meta <= META_MALICIOUS:
        return 255 - priority_of(meta, n_meta, priorities)
    return 255


def adapt_state(state, old_cfg, new_cfg):
    """Resize the overload-plane leaves across a ``SetOverload`` swap.

    ``bucket`` and the ``stats.msgs_shed_*`` counters are zero-width
    while the plane is compiled out (state.py), so a flip of
    ``overload.enabled`` must resize them before the next step traces.
    Enabling starts clean (empty buckets — the first round's refill
    seeds them — and zero shed counters); disabling discards.  A swap
    that leaves ``enabled`` alone is an identity — the numeric knobs
    gate computation only.
    """
    import jax.numpy as jnp

    if old_cfg.overload.enabled == new_cfg.overload.enabled:
        return state
    n = new_cfg.n_peers if new_cfg.overload.enabled else 0
    state = state.replace(
        bucket=jnp.zeros((n,), jnp.uint8),
        stats=state.stats.replace(
            msgs_shed_rate=jnp.zeros((n,), jnp.uint32),
            msgs_shed_priority=jnp.zeros((n,), jnp.uint32)))
    # The shed/bucket telemetry words are conditional on the flipped
    # knob, so with telemetry on the packed-row SCHEMA changed width.
    from dispersy_tpu.telemetry import adapt_row_leaves
    return adapt_row_leaves(state, old_cfg, new_cfg)


def shed_totals(stats) -> dict:
    """Overlay-wide shed totals from a ``Stats`` pytree (zero-width
    compiled-out leaves read as zeros).  THE one host-side aggregation
    — :func:`overload_report` and the legacy ``metrics.snapshot`` path
    both read it (the fused telemetry row reduces the same leaves on
    device), so the two paths cannot drift."""
    import numpy as np

    out = {}
    for nm in ("msgs_shed_rate", "msgs_shed_priority"):
        col = np.asarray(getattr(stats, nm), np.uint64)
        out[nm] = int(col.sum()) if col.size else 0
    return out


def overload_report(state, cfg, top: int = 4) -> dict:
    """Host-side summary of the ingress-protection plane's live state:
    shed totals, exhausted/min/max bucket levels, and the ``top``
    heaviest rate-shed senders — under a flood these are the attackers,
    surfaced by name instead of their victims' health bits.  Cheap (a
    couple of [N] transfers); all-zero when the plane is compiled out.
    """
    import numpy as np

    bk = np.asarray(state.bucket)
    out = {
        "bucket_exhausted": int((bk == 0).sum()) if bk.size else 0,
        "bucket_min": int(bk.min()) if bk.size else 0,
        "bucket_max": int(bk.max()) if bk.size else 0,
    }
    out.update(shed_totals(state.stats))
    shed = np.asarray(state.stats.msgs_shed_rate, np.uint64)
    if shed.size:
        order = np.argsort(shed, kind="stable")[::-1][:top]
        out["top_shed_senders"] = [
            (int(i), int(shed[i])) for i in order if shed[i] > 0]
    else:
        out["top_shed_senders"] = []
    return out


def shed_report(rows) -> dict:
    """Ingress-protection summary from a per-round row log (the
    telemetry ring drained through ``telemetry.ring_rows``, a
    ``MetricsLog``'s rows, or a decoded artifact's row dicts) — the
    overload analogue of ``recovery.mttr_report``, consumed by
    ``tools/telemetry.py gate --overload``.

    ``shed_rate`` / ``shed_priority`` are the window's shed deltas (the
    cumulative counters' first->last difference; a log starting at
    round 1 sees them from zero, so the delta IS the total).
    ``flagged_peer_rounds`` rides along because the plane's whole
    point is keeping the victim health curve quiet under flood.
    """
    rows = [r for r in rows if isinstance(r, dict)]
    out: dict = {"rounds": len(rows)}
    if not rows:
        return out
    for key, name in (("msgs_shed_rate", "shed_rate"),
                      ("msgs_shed_priority", "shed_priority")):
        vals = [int(r[key]) for r in rows if key in r]
        if not vals:
            out[name] = 0
        elif int(rows[0].get("round", 1)) <= 1:
            out[name] = vals[-1]
        else:
            out[name] = vals[-1] - vals[0]
    out["max_bucket_exhausted"] = max(
        (int(r.get("bucket_exhausted", 0)) for r in rows), default=0)
    out["flagged_peer_rounds"] = sum(
        int(r.get("health_flagged", 0)) for r in rows)
    return out
