"""Binary wire format: the conversion.py analogue, for conformance only.

The core simulation deliberately has NO wire format (SURVEY §7 anti-goals:
no byte format "except where conformance tests need golden packets") — on
device a message is five uint32 columns.  This module packs those columns
into reference-shaped packets so that (a) golden-packet tests pin the
layout, and (b) tiny-N conformance runs can sign/verify real bytes with
real keys (:mod:`dispersy_tpu.crypto`), putting the reference's
decode+verify semantics under test without ever entering the hot path.

Layout (reference: conversion.py BinaryConversion — 23 B common header =
1 B dispersy version + 1 B community version + 20 B master-member mid +
1 B message id; then authentication / distribution / payload; trailing
signature):

    [0]     dispersy version        (1 B)  -- 0x00 for this framework
    [1]     community version       (1 B)
    [2:22]  master-member mid       (20 B)
    [22]    message id              (1 B)  -- the meta id byte
    [23:43] author mid              (20 B) -- MemberAuthentication("sha1")
    [43:51] global_time             (8 B, big-endian u64)
    [51:55] payload word            (4 B, big-endian u32)
    [55:59] aux word                (4 B, big-endian u32)
    [59:]   signature over [0:59]

Sequence-enabled metas insert 4 B of sequence number (the aux word re-used)
after global_time in the reference; here aux always rides explicitly, so
one layout serves every policy — a documented simplification, pinned by the
golden packets below.
"""

from __future__ import annotations

from typing import NamedTuple

from dispersy_tpu.config import EMPTY_U32
from dispersy_tpu.crypto import ECCrypto, Member, MemberRegistry

DISPERSY_VERSION = 0x00
HEADER_LEN = 23
BODY_LEN = HEADER_LEN + 20 + 8 + 4 + 4    # 59 bytes before the signature


class Packet(NamedTuple):
    """A decoded packet (reference: message.Packet / Placeholder stages)."""
    community_mid: bytes
    community_version: int
    meta: int
    author_mid: bytes
    global_time: int
    payload: int
    aux: int
    signature: bytes
    valid_signature: bool


def encode_record(community_mid: bytes, community_version: int, meta: int,
                  member: Member, global_time: int, payload: int, aux: int,
                  crypto: ECCrypto) -> bytes:
    """Pack one sim record into a reference-shaped signed packet.

    Mirrors BinaryConversion.encode_message: header, authentication (the
    author's 20-byte mid), distribution (global_time), payload words, then
    the author's signature over everything before it.
    """
    if len(community_mid) != 20:
        raise ValueError("community mid must be 20 bytes (SHA1)")
    if not (0 <= meta <= 0xFF):
        raise ValueError("meta id must fit one byte")
    body = bytes([DISPERSY_VERSION, community_version & 0xFF])
    body += community_mid
    body += bytes([meta])
    body += member.mid
    body += int(global_time).to_bytes(8, "big")
    body += int(payload).to_bytes(4, "big")
    body += int(aux).to_bytes(4, "big")
    assert len(body) == BODY_LEN
    return body + crypto.create_signature(member.key, body)


def decode_record(data: bytes, registry: MemberRegistry,
                  crypto: ECCrypto) -> Packet:
    """Unpack + verify one packet (BinaryConversion.decode_message).

    Stages mirror the reference's Placeholder decode: fixed header, then
    authentication (mid -> member via the registry, the member-table
    lookup), then distribution/payload, then signature verification with
    the resolved member's real public key.  An unresolvable mid or bad
    signature yields ``valid_signature=False`` (the reference raises
    DelayPacketByMissingMember / DropPacket — the caller decides).
    """
    if len(data) < BODY_LEN:
        raise ValueError(f"packet too short: {len(data)} < {BODY_LEN}")
    if data[0] != DISPERSY_VERSION:
        raise ValueError(f"unknown dispersy version {data[0]:#x}")
    community_mid = data[2:22]
    meta = data[22]
    author_mid = data[23:43]
    global_time = int.from_bytes(data[43:51], "big")
    payload = int.from_bytes(data[51:55], "big")
    aux = int.from_bytes(data[55:59], "big")
    signature = data[BODY_LEN:]
    member = registry.by_mid(author_mid)
    ok = (member is not None
          and crypto.is_valid_signature(member.key, data[:BODY_LEN],
                                        signature))
    return Packet(community_mid=community_mid,
                  community_version=data[1], meta=meta,
                  author_mid=author_mid, global_time=global_time,
                  payload=payload, aux=aux, signature=signature,
                  valid_signature=ok)


def encode_malicious_proof(packet_a: bytes, packet_b: bytes) -> bytes:
    """Pack two conflicting signed packets into one dispersy-malicious-
    proof blob (reference: dispersy.py spreads the packet PAIR so
    receivers re-verify the double-signing independently instead of
    trusting the claim).  Layout: version byte + 2 B length + packet A +
    2 B length + packet B."""
    for p in (packet_a, packet_b):
        if len(p) > 0xFFFF:
            raise ValueError("packet too long for a 2-byte length prefix")
    return (bytes([DISPERSY_VERSION])
            + len(packet_a).to_bytes(2, "big") + packet_a
            + len(packet_b).to_bytes(2, "big") + packet_b)


def verify_malicious_proof(blob: bytes, registry: MemberRegistry,
                           crypto: ECCrypto) -> bytes | None:
    """Verify a malicious-proof blob; the convicted author's mid, or
    ``None`` if the proof does not hold.

    The receiver-side re-verification the reference performs before
    convicting (reference: dispersy.py's malicious-proof handling): BOTH
    packets must carry valid signatures from the SAME resolvable member
    of the SAME community at the SAME global_time while differing in
    content — a forged signature, a mismatched pair, or two copies of
    one packet convict nobody.  The simulation's META_MALICIOUS record
    (engine gossip path) carries (member, global_time) structurally; this
    is the tiny-N conformance bridge proving the byte-level pair check
    (PARITY.md "Malicious-proof trust is structural" boundary)."""
    if len(blob) < 3 or blob[0] != DISPERSY_VERSION:
        return None
    off = 1
    packets = []
    for _ in range(2):
        if off + 2 > len(blob):
            return None
        ln = int.from_bytes(blob[off:off + 2], "big")
        off += 2
        if off + ln > len(blob):
            return None
        packets.append(blob[off:off + ln])
        off += ln
    if off != len(blob):
        return None
    try:
        a = decode_record(packets[0], registry, crypto)
        b = decode_record(packets[1], registry, crypto)
    except ValueError:
        return None
    if not (a.valid_signature and b.valid_signature):
        return None
    if a.author_mid != b.author_mid or a.global_time != b.global_time:
        return None
    if a.community_mid != b.community_mid:
        return None
    if packets[0] == packets[1]:
        return None       # one packet twice proves nothing
    return a.author_mid


def encode_store(state, cfg, registry: MemberRegistry, crypto: ECCrypto,
                 peer: int, community_mid: bytes | None = None,
                 community_version: int = 1) -> list[bytes]:
    """Serialize one peer's whole store to signed packets — the conformance
    bridge: a tiny-N device run's records become reference-shaped,
    individually verifiable bytes (the reference's sync table holds exactly
    these packets in its ``packet`` BLOB column)."""
    import numpy as np
    if community_mid is None:
        import hashlib
        community_mid = hashlib.sha1(b"dispersy-tpu-community").digest()
    gt = np.asarray(state.store_gt[peer])
    member = np.asarray(state.store_member[peer])
    meta = np.asarray(state.store_meta[peer])
    payload = np.asarray(state.store_payload[peer])
    aux = np.asarray(state.store_aux[peer])
    out = []
    for j in range(gt.shape[0]):
        if gt[j] == EMPTY_U32:
            continue
        out.append(encode_record(
            community_mid, community_version, int(meta[j]) & 0xFF,
            registry.member(int(member[j])), int(gt[j]), int(payload[j]),
            int(aux[j]), crypto))
    return out
