"""Device-mesh sharding for the peer axis.

The reference scales by running one OS process per peer over real UDP
networks (reference: endpoint.py ``StandaloneEndpoint``; tool/scenarioscript.py
drives DAS4-cluster deployments) — its "distributed backend" is hand-rolled
datagrams, no NCCL/MPI (SURVEY.md §5.8).  The TPU rebuild's distribution
model is SPMD instead: the leading *peer axis* of every ``PeerState`` array
is sharded over a 1-D ``jax.sharding.Mesh``, the whole round ``step`` runs
under jit on that sharded state, and XLA inserts the collectives where data
crosses shards:

- the delivery kernel's global ``lax.sort`` by destination
  (:mod:`dispersy_tpu.ops.inbox`) lowers to an all-to-all style exchange over
  ICI — exactly where the reference's UDP fan-out sat;
- everything else in the step (bloom build/query, store merge, candidate
  bookkeeping) is embarrassingly row-parallel and stays shard-local.

No TP/PP is warranted: the model is 1M+ independent peer rows, so
peer-sharding *is* the data parallelism (SURVEY.md §2, "Parallelism
strategies").  Multi-host: the same mesh spans hosts via
``jax.distributed.initialize``; DCN traffic only occurs inside the one sort,
at the round boundary — matching the design rule that cross-slice hops ride
DCN once per round.

Caveat (virtual CPU meshes only): XLA's in-process CPU communicator can
deadlock when several async-dispatched sharded executions overlap — call
``jax.block_until_ready`` between steps when looping on a
``xla_force_host_platform_device_count`` mesh.  Real TPU streams order
collectives correctly and need no such serialization.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dispersy_tpu.state import PeerState

PEER_AXIS = "peers"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} present")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (PEER_AXIS,))


def state_sharding(state: PeerState, mesh: Mesh, n_peers: int):
    """A ``PeerState``-shaped pytree of NamedShardings.

    Every leaf whose leading dimension is the peer axis is sharded over the
    mesh; scalars and the RNG key are replicated.  The peer axis is
    recognized by its length, so ``n_peers`` must differ from the small
    fixed dims (the uint32[2] key — guaranteed for any real population).
    """
    if n_peers <= 2:
        # The peer axis is detected by leading-dim length; n_peers <= 2
        # collides with fixed dims (the uint32[2] RNG key) and would shard
        # scalars.  No real population is this small.
        raise ValueError(f"n_peers={n_peers} is too small to shard "
                         "unambiguously (collides with fixed-size leaves)")

    def spec(leaf):
        if leaf.ndim >= 1 and leaf.shape[0] == n_peers:
            return NamedSharding(mesh, P(PEER_AXIS, *([None] * (leaf.ndim - 1))))
        return NamedSharding(mesh, P())
    return jax.tree.map(spec, state)


def shard_state(state: PeerState, mesh: Mesh, n_peers: int) -> PeerState:
    """Place ``state`` on the mesh, peer axis sharded, scalars replicated."""
    return jax.device_put(state, state_sharding(state, mesh, n_peers))


def sharded_shape_structs(shapes, mesh: Mesh, n_peers: int):
    """Attach the peer-axis sharding to a ``ShapeDtypeStruct`` pytree.

    ``state_sharding``'s placement rule, but for ABSTRACT shapes: the
    returned structs let ``jit(step).lower(...)`` compile the sharded
    program without materializing a byte — how the cost ledger
    (``dispersy_tpu/costmodel.py``) and ``profiling.sharded_step_cost``
    price a multi-chip round on a host that has no chips.
    """
    shardings = state_sharding(shapes, mesh, n_peers)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)
