"""Device-mesh sharding for the peer axis: the partition-rule registry.

The reference scales by running one OS process per peer over real UDP
networks (reference: endpoint.py ``StandaloneEndpoint``; tool/scenarioscript.py
drives DAS4-cluster deployments) — its "distributed backend" is hand-rolled
datagrams, no NCCL/MPI (SURVEY.md §5.8).  The TPU rebuild's distribution
model is SPMD instead: the leading *peer axis* of every ``PeerState`` array
is sharded over a ``jax.sharding.Mesh``, the whole round ``step`` runs
under jit on that sharded state, and the ONLY data that crosses shards is
the delivery exchange (:mod:`dispersy_tpu.ops.inbox`) — exactly where the
reference's UDP fan-out sat.

**Partition rules** (the SNIPPETS.md [2]/[3] idiom: regex rules over leaf
names → ``PartitionSpec``): every ``PeerState`` leaf is classified BY NAME,
first match wins — :data:`PARTITION_RULES`.  Peer-axis leaves shard their
leading dim over every mesh axis; the round-synchronous scalars (clock,
round counter), the replicated RNG key, and the tracker-/host-indexed
observability leaves (``trace_member``/``trace_gt``/``trace_latch``,
``tele_*``, ``fr_*``) replicate.  Zero-width plane leaves (the ``health``
idiom) shard like their full-width selves — 0 rows split 8 ways is still
0 rows.  A NEW leaf that matches no replicated rule must carry the peer
axis, or :func:`state_sharding` refuses loudly — which is the point: the
old length-heuristic silently replicated any leaf whose leading dim
happened not to equal ``n_peers``, and would have silently *sharded*
host-indexed leaves whose dim happened to match.

**Pins**: :func:`pin_peers` / :func:`pin_replicated` are
``with_sharding_constraint`` wrappers the engine drops at phase
boundaries so XLA never invents an [8,1] <-> [2,4] reshard or an
involuntary rematerialization mid-round (profiling.sharded_step_cost
gates both mesh shapes at ZERO warnings, tests/test_ledger.py).  Outside
an ambient mesh (``with mesh:``) they are identity — the single-device
step's HLO stays byte-identical.

Caveat (virtual CPU meshes only): XLA's in-process CPU communicator can
deadlock when several async-dispatched sharded executions overlap — use
:func:`sharded_step`, which blocks between rounds, when looping on a
``xla_force_host_platform_device_count`` mesh (the satellite fix for the
footgun this docstring used to merely document).  Real TPU streams order
collectives correctly and need no such serialization.
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dispersy_tpu.ops.contracts import Spec, contract, host_helper
from dispersy_tpu.state import PeerState

PEER_AXIS = "peers"
# Second mesh axis for 2-D meshes (make_mesh((2, 4))): the peer axis is
# sharded over BOTH, modeling a pod slice whose chips are reached via
# two interconnect dimensions.  Name only — the partition rules place
# every peer leaf over all mesh axes, whatever their count.
CHIP_AXIS = "chips"

# (leaf-name regex, placement) — FIRST match wins; placement is
# "replicated" or "peers".  Leaf names are the checkpoint's path names
# ("stats/walk_success" style, checkpoint._leaves_with_paths).  The
# table is deliberately exhaustive about what replicates; everything
# else MUST be peer-axis (validated against the leaf's leading dim).
PARTITION_RULES: tuple[tuple[str, str], ...] = (
    (r"^key$", "replicated"),            # RNG key uint32[2]: one shared
    #   counter-based stream — every shard derives identical per-peer
    #   streams from it (ops/rng.py), so sharding it would be wrong, not
    #   just slow
    (r"^time$", "replicated"),           # round-synchronous sim clock
    (r"^round_index$", "replicated"),    # round-synchronous counter
    (r"^trace_(member|gt|latch)$", "replicated"),  # tracked-record
    #   registry + coverage latches: [tracked_slots, ...] — indexed by
    #   record, not peer (traceplane.py)
    (r"^tele_(row|ring)$", "replicated"),  # telemetry row/history:
    #   [row_words] / [history, row_words] community-wide sums
    (r"^fr_(ring|pos)$", "replicated"),  # flight recorder: [depth, W]
    #   host-diagnostic ring + its scalar cursor
    (r".*", "peers"),                    # EVERYTHING else carries the
    #   peer axis in dim 0 (zero-width plane leaves included).  The
    #   cohort-stagger leaves (``cohort``/``epoch``, storediet.py) land
    #   here on purpose: cohorts are assigned STRIDED (idx % cohorts),
    #   so every shard holds an equal slice of each cohort and the
    #   active-cohort block ops (ops/store.cohort_take/put) reshape the
    #   peer axis to [N//C, C] and slice the trailing NON-peer axis —
    #   no cross-shard bytes, no resharding warnings.
)


@host_helper
def partition_kind(name: str) -> str:
    """``"peers"`` or ``"replicated"`` for one leaf name — the registry
    lookup, shared with checkpoint.save_sharded's shard-vs-meta split."""
    for pat, kind in PARTITION_RULES:
        if re.match(pat, name):
            return kind
    raise ValueError(f"no partition rule matches leaf {name!r}")


def _named_leaves(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    named = [("/".join(str(getattr(k, "name", k)) for k in path), leaf)
             for path, leaf in flat]
    return named, treedef


def _check_peer_leaf(name: str, leaf, n_peers: int) -> None:
    if leaf.ndim < 1 or leaf.shape[0] not in (0, n_peers):
        raise ValueError(
            f"leaf {name!r} matched the peer-axis rule but its shape is "
            f"{tuple(leaf.shape)} (n_peers={n_peers}) — add a "
            "PARTITION_RULES entry for it "
            "(dispersy_tpu/parallel/mesh.py)")


@host_helper
def partition_table(state, n_peers: int) -> dict:
    """leaf name -> (placement, shape, dtype) for a state/shape pytree —
    the registry applied and VALIDATED (docs + tests; PARALLEL.md's
    partition-rule table is generated from this)."""
    named, _ = _named_leaves(state)
    out = {}
    for name, leaf in named:
        kind = partition_kind(name)
        if kind == "peers":
            _check_peer_leaf(name, leaf, n_peers)
        out[name] = (kind, tuple(leaf.shape), str(leaf.dtype))
    return out


@host_helper
def make_mesh(shape: int | tuple | None = None, devices=None) -> Mesh:
    """A peer-axis mesh over the available devices.

    ``shape``: an int (1-D mesh over the first n devices, the common
    case), a tuple like ``(2, 4)`` (a 2-D ``(peers, chips)`` mesh — the
    peer axis shards over both axes), or None (all devices, 1-D).
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = len(devices)
    if isinstance(shape, int):
        shape = (shape,)
    if len(shape) > 2:
        raise ValueError(f"mesh shape {shape}: at most 2 axes supported")
    need = int(np.prod(shape))
    if need > len(devices):
        raise ValueError(
            f"requested {need} devices, only {len(devices)} present")
    axes = (PEER_AXIS, CHIP_AXIS)[:len(shape)]
    return Mesh(np.asarray(devices[:need]).reshape(shape), axes)


@host_helper
def peer_spec(mesh: Mesh, ndim: int) -> P:
    """The peer-leaf PartitionSpec on ``mesh``: dim 0 sharded over every
    mesh axis, trailing dims replicated."""
    axes = tuple(mesh.axis_names)
    lead = axes[0] if len(axes) == 1 else axes
    return P(lead, *([None] * (ndim - 1)))


@host_helper
def state_sharding(state: PeerState, mesh: Mesh, n_peers: int):
    """A ``PeerState``-shaped pytree of NamedShardings, from the
    partition-rule registry (:data:`PARTITION_RULES`) — name-classified,
    leading dims validated, unknown scalars refused."""
    named, treedef = _named_leaves(state)
    shardings = []
    for name, leaf in named:
        if partition_kind(name) == "peers":
            _check_peer_leaf(name, leaf, n_peers)
            shardings.append(
                NamedSharding(mesh, peer_spec(mesh, leaf.ndim)))
        else:
            shardings.append(NamedSharding(mesh, P()))
    return jax.tree_util.tree_unflatten(treedef, shardings)


@host_helper
def shard_state(state: PeerState, mesh: Mesh, n_peers: int) -> PeerState:
    """Place ``state`` on the mesh, peer axis sharded, scalars replicated."""
    return jax.device_put(state, state_sharding(state, mesh, n_peers))


@host_helper
def sharded_shape_structs(shapes, mesh: Mesh, n_peers: int):
    """Attach the peer-axis sharding to a ``ShapeDtypeStruct`` pytree.

    ``state_sharding``'s placement rule, but for ABSTRACT shapes: the
    returned structs let ``jit(step).lower(...)`` compile the sharded
    program without materializing a byte — how the cost ledger
    (``dispersy_tpu/costmodel.py``) and ``profiling.sharded_step_cost``
    price a multi-chip round on a host that has no chips.
    """
    shardings = state_sharding(shapes, mesh, n_peers)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)


@host_helper
def ambient_mesh() -> Mesh | None:
    """The mesh this trace runs under (``with mesh:``), or None.

    The engine's phase-boundary pins key off this: no ambient mesh ->
    every pin is identity and the single-device HLO stays byte-identical
    (the step_cost_1M_baseline.json guarantee)."""
    from jax._src import mesh as _mesh_internal

    m = _mesh_internal.thread_resources.env.physical_mesh
    return None if m.empty else m


@contract(out=Spec("uint32", ("N",)), x=Spec("uint32", ("N",)))
def pin_peers(x):
    """Pin dim 0 of ``x`` to the peer-axis layout of the ambient mesh
    (identity when unsharded).  Dropped at the engine's phase
    boundaries so XLA propagates ONE layout through the round instead
    of inventing [8,1] <-> [2,4] transitions."""
    mesh = ambient_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, peer_spec(mesh, x.ndim)))


@contract(out=Spec("uint32", ("N",)), x=Spec("uint32", ("N",)))
def pin_replicated(x):
    """Pin ``x`` fully replicated on the ambient mesh (identity when
    unsharded) — for tracker-row and reduction intermediates whose
    tensors carry no peer axis."""
    mesh = ambient_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))


@host_helper
def sharded_step(state: PeerState, cfg, mesh: Mesh):
    """ONE round of ``engine.step`` under ``mesh``, fully synchronized.

    The supported way to loop a sharded step host-side: runs the jitted
    step inside the mesh context (arming the partition pins) and calls
    ``jax.block_until_ready`` on the result — virtual CPU meshes
    deadlock without the barrier (module docstring), and on real chips
    a host-side loop gains nothing from async dispatch because round
    r+1's donation aliases round r's buffers anyway.
    """
    from dispersy_tpu import engine

    with mesh:
        out = engine.step(state, cfg)
    return jax.block_until_ready(out)
