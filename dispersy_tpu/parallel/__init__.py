from dispersy_tpu.parallel.mesh import (  # noqa: F401
    PEER_AXIS, make_mesh, shard_state, state_sharding)
