from dispersy_tpu.parallel.mesh import (  # noqa: F401
    CHIP_AXIS, PARTITION_RULES, PEER_AXIS, ambient_mesh, make_mesh,
    partition_kind, partition_table, peer_spec, pin_peers,
    pin_replicated, shard_state, sharded_shape_structs, sharded_step,
    state_sharding)
