"""Byte-diet store plane: incremental maintenance config + cadence helpers.

ROADMAP item 1 (the byte-diet fused round): the PR-11 cost ledger proved
the 1M-peer round moves ~74.5 KB/peer/round against a ~1.7 KB store
read+write floor because the sorted-ring store is fully rewritten every
round to land B«M records, and the sync responder re-scans the whole
ring for every request slot.  This module holds the static knobs that
amortize both:

- **Staging buffer** (``StoreConfig.staging`` slots/peer): accepted
  records land in a small per-peer append-only buffer in delivery
  order; the sorted ring is only merged (``ops/store.store_insert``)
  every ``compact_every`` rounds.  Between compactions the logical
  store is ring ∪ staging.  A full staging buffer drops (and counts)
  overflow arrivals exactly like every bounded inbox in this repo —
  UDP-style backpressure that the Bloom pull repairs at the next sync
  round.
- **Cadenced sync** : the Bloom claim/serve exchange runs on *sync
  rounds* (one round in ``compact_every``; the compaction round), the
  push channel every round.  This is the per-round communication bound
  of the gossip literature (PAPERS.md: *Time- and
  Communication-Efficient Overlay Network Construction via Gossip*
  bounds per-round communication; *The Algorithm of Pipelined
  Gossiping* amortizes sustained throughput) applied to HBM bytes.
- **Incremental Bloom digest** (``PeerState.digest``): the claimed
  slice's bloom is a device-resident digest, OR-updated each round from
  the staged arrivals' precomputed ``probe_bits`` and fully rebuilt
  from the ring only on compaction rounds — the claim itself is a pure
  ``bloom_words`` read instead of the 4-column re-hash + rebuild of the
  full store (the old engine.py claim block).  The digest doubles as
  the intake's freshness filter (:func:`digest_fresh` semantics below).

Bloom **salting** under the diet is per-*epoch* instead of per-round:
``salt = round // compact_every`` (:func:`epoch_of`).  Requester and
responder derive the identical salt from the shared round counter, and
a false positive against one epoch's digest re-randomizes at the next
compaction — the same repair-convergence argument as the per-round
claim prefix, at epoch granularity.  With ``compact_every == 1`` the
salt, the claim, the merge cadence and the served set all degenerate to
exactly the legacy every-round path (pinned bit-identical in
tests/test_storediet.py).

**Freshness via the digest** : the intake's "already stored?" test
under the diet is a digest membership query instead of the exact
[N, B, M] key compare against the ring — quiet rounds touch ZERO ring
bytes.  Consequences, all mirrored bit-exactly by the oracle:

- false positive (~bloom_error_rate): a genuinely fresh record is
  dropped as a duplicate and counted in ``msgs_dropped``; the pull
  re-offers it under the next epoch's salt, so convergence still
  reaches 100% (the per-claim-prefix argument).
- false negative (a ring record outside the claimed slice re-arrives):
  the record is re-staged and re-pushed once, then dies as a duplicate
  at the next compaction (``store_insert``'s UNIQUE rule, existing
  wins) — the store never corrupts, and the echo decays because the
  re-arrival entered the digest.

The plane composes like faults/telemetry/recovery/overload: all
defaults (``staging=0``) compile to exactly the legacy every-round
step, checkpoint v14 carries the staging + digest leaves, and the
oracle mirrors every path bit-for-bit.
"""

from __future__ import annotations

import dataclasses

from dispersy_tpu.exceptions import ConfigError
from dispersy_tpu.ops.contracts import host_helper


@dataclasses.dataclass(frozen=True)
class StoreConfig:
    """Static byte-diet knobs, composed into ``CommunityConfig.store``.

    Frozen + hashable (a static jit argument, like ``FaultModel``).
    All defaults compile to exactly the legacy every-round-merge step;
    every leaf the plane adds (``sta_*``, ``digest``) is zero-width
    while ``staging`` is 0.
    """

    # Staging-buffer slots per peer; 0 = legacy every-round full merge.
    staging: int = 0
    # Compaction/sync cadence in rounds: the staging buffer merges into
    # the sorted ring — and the Bloom claim/serve exchange runs — on
    # rounds r with r % compact_every == compact_every - 1.  1 = merge
    # and sync every round (bit-identical to the legacy path).
    compact_every: int = 8
    # Store the ``aux`` record column in 16 bits instead of 32.  Only
    # legal when no configured meta interprets aux (the staging gates
    # below already exclude timeline/seq/double metas); values above
    # 2^16-1 silently truncate at the store boundary, so this is an
    # explicit opt-in for communities whose payloads fit.
    aux_bits: int = 32

    def __post_init__(self) -> None:
        if self.staging < 0:
            raise ConfigError("store.staging must be >= 0")
        if self.compact_every < 1:
            raise ConfigError("store.compact_every must be >= 1")
        if self.aux_bits not in (16, 32):
            raise ConfigError("store.aux_bits must be 16 or 32")
        if self.aux_bits != 32 and self.staging == 0:
            raise ConfigError(
                "store.aux_bits narrowing rides the staged store layout "
                "— set store.staging > 0 too")


@host_helper
def epoch_of(cfg, rnd):
    """The bloom-salt epoch of round ``rnd`` (host int or traced u32):
    ``rnd // compact_every``.  Requesters build/maintain the digest with
    this salt and responders query with it — both sides derive it from
    the same round counter, so the exchange stays round-synchronous."""
    return rnd // cfg.store.compact_every


@host_helper
def sync_round_of(cfg, rnd):
    """Cadence predicate (host int or traced u32, like ``epoch_of``):
    does round ``rnd`` run the sync exchange + compaction?  Always True
    without the diet."""
    if cfg.store.staging == 0:
        return True
    c = cfg.store.compact_every
    return (rnd % c) == c - 1


@host_helper
def phase_of(cfg, rnd: int) -> str:
    """The static ``engine.step`` phase for round ``rnd`` ("sync" or
    "quiet") — for drivers that know the round index host-side and want
    the statically-specialized step instead of the dynamic cond."""
    return "sync" if sync_round_of(cfg, rnd) else "quiet"
