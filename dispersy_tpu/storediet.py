"""Byte-diet store plane: incremental maintenance config + cadence helpers.

ROADMAP item 1 (the byte-diet fused round): the PR-11 cost ledger proved
the 1M-peer round moves ~74.5 KB/peer/round against a ~1.7 KB store
read+write floor because the sorted-ring store is fully rewritten every
round to land B«M records, and the sync responder re-scans the whole
ring for every request slot.  This module holds the static knobs that
amortize both:

- **Staging buffer** (``StoreConfig.staging`` slots/peer): accepted
  records land in a small per-peer append-only buffer in delivery
  order; the sorted ring is only merged (``ops/store.store_insert``)
  every ``compact_every`` rounds.  Between compactions the logical
  store is ring ∪ staging.  A full staging buffer drops (and counts)
  overflow arrivals exactly like every bounded inbox in this repo —
  UDP-style backpressure that the Bloom pull repairs at the next sync
  round.
- **Cadenced sync** : the Bloom claim/serve exchange runs on *sync
  rounds* (one round in ``compact_every``; the compaction round), the
  push channel every round.  This is the per-round communication bound
  of the gossip literature (PAPERS.md: *Time- and
  Communication-Efficient Overlay Network Construction via Gossip*
  bounds per-round communication; *The Algorithm of Pipelined
  Gossiping* amortizes sustained throughput) applied to HBM bytes.
- **Incremental Bloom digest** (``PeerState.digest``): the claimed
  slice's bloom is a device-resident digest, OR-updated each round from
  the staged arrivals' precomputed ``probe_bits`` and fully rebuilt
  from the ring only on compaction rounds — the claim itself is a pure
  ``bloom_words`` read instead of the 4-column re-hash + rebuild of the
  full store (the old engine.py claim block).  The digest doubles as
  the intake's freshness filter (:func:`digest_fresh` semantics below).

Bloom **salting** under the diet is per-*epoch* instead of per-round:
``salt = round // compact_every`` (:func:`epoch_of`).  Requester and
responder derive the identical salt from the shared round counter, and
a false positive against one epoch's digest re-randomizes at the next
compaction — the same repair-convergence argument as the per-round
claim prefix, at epoch granularity.  With ``compact_every == 1`` the
salt, the claim, the merge cadence and the served set all degenerate to
exactly the legacy every-round path (pinned bit-identical in
tests/test_storediet.py).

**Freshness via the digest** : the intake's "already stored?" test
under the diet is a digest membership query instead of the exact
[N, B, M] key compare against the ring — quiet rounds touch ZERO ring
bytes.  Consequences, all mirrored bit-exactly by the oracle:

- false positive (~bloom_error_rate): a genuinely fresh record is
  dropped as a duplicate and counted in ``msgs_dropped``; the pull
  re-offers it under the next epoch's salt, so convergence still
  reaches 100% (the per-claim-prefix argument).
- false negative (a ring record outside the claimed slice re-arrives):
  the record is re-staged and re-pushed once, then dies as a duplicate
  at the next compaction (``store_insert``'s UNIQUE rule, existing
  wins) — the store never corrupts, and the echo decays because the
  re-arrival entered the digest.

The plane composes like faults/telemetry/recovery/overload: all
defaults (``staging=0``) compile to exactly the legacy every-round
step, checkpoint v14 carries the staging + digest leaves, and the
oracle mirrors every path bit-for-bit.
"""

from __future__ import annotations

import dataclasses

from dispersy_tpu.exceptions import ConfigError
from dispersy_tpu.ops.contracts import host_helper


@dataclasses.dataclass(frozen=True)
class StoreConfig:
    """Static byte-diet knobs, composed into ``CommunityConfig.store``.

    Frozen + hashable (a static jit argument, like ``FaultModel``).
    All defaults compile to exactly the legacy every-round-merge step;
    every leaf the plane adds (``sta_*``, ``digest``) is zero-width
    while ``staging`` is 0.
    """

    # Staging-buffer slots per peer; 0 = legacy every-round full merge.
    staging: int = 0
    # Compaction/sync cadence in rounds: the staging buffer merges into
    # the sorted ring — and the Bloom claim/serve exchange runs — on
    # rounds r with r % compact_every == compact_every - 1.  1 = merge
    # and sync every round (bit-identical to the legacy path).
    compact_every: int = 8
    # Store the ``aux`` record column in 16 bits instead of 32.  Only
    # legal when no configured meta interprets aux (the staging gates
    # below already exclude timeline/seq/double metas); values above
    # 2^16-1 silently truncate at the store boundary, so this is an
    # explicit opt-in for communities whose payloads fit.
    aux_bits: int = 32
    # NOTE: checkpoint._want_fingerprint reconstructs pre-v17 config
    # fingerprints by stripping ", cohorts=1, cand_bits=32" from this
    # dataclass's repr — the two v17 fields below MUST stay last and in
    # this order.
    #
    # Compaction cohorts (PR 20): stagger the sync/compaction cadence
    # so peer p (cohort ``p % cohorts``) runs its claim/serve/compact
    # round when ``rnd % compact_every == cohort_phase(cohort)`` instead
    # of fleet-synchronized — each sync round touches only the active
    # cohort's N/cohorts ring block and the per-round byte spike
    # flattens to the amortized average.  1 = the fleet-synchronized
    # PR-12 cadence, bit-identical to the pre-cohort path.
    cohorts: int = 1
    # Candidate-table timestamp width: 32 keeps the legacy f32
    # sim-second columns (``cand_last_walk/stumble/intro``); 16 stores
    # them as quantized u16 ROUND-stamps (``round + 1``, 0 = never) and
    # dequantizes at the store boundary (``(stamp - 1) * walk_interval``).
    # Quantization is exact — every timestamp the walker writes is some
    # round's ``r * walk_interval`` — except at the u16 boundary, where
    # the stamp SATURATES into [1, 65535] (the aux_bits narrowing rule,
    # with saturation instead of wrap so a pre-epoch seed stamp or a
    # >65534-round run degrades to a stale-but-ordered timestamp, never
    # the ``never`` sentinel); an explicit opt-in for runs that fit.
    cand_bits: int = 32

    def __post_init__(self) -> None:
        if self.staging < 0:
            raise ConfigError("store.staging must be >= 0")
        if self.compact_every < 1:
            raise ConfigError("store.compact_every must be >= 1")
        if self.aux_bits not in (16, 32):
            raise ConfigError("store.aux_bits must be 16 or 32")
        if self.aux_bits != 32 and self.staging == 0:
            raise ConfigError(
                "store.aux_bits narrowing rides the staged store layout "
                "— set store.staging > 0 too")
        if self.cohorts < 1:
            raise ConfigError("store.cohorts must be >= 1")
        if self.cohorts > 1 and self.staging == 0:
            raise ConfigError(
                "store.cohorts staggering rides the staged store layout "
                "— set store.staging > 0 too")
        if self.cohorts > 1 and self.compact_every % self.cohorts:
            raise ConfigError(
                "store.cohorts must divide compact_every: the cohort "
                "phases interleave one sync round every "
                "compact_every/cohorts rounds")
        if self.cand_bits not in (16, 32):
            raise ConfigError("store.cand_bits must be 16 or 32")
        if self.cand_bits != 32 and self.staging == 0:
            raise ConfigError(
                "store.cand_bits narrowing rides the staged store "
                "layout — set store.staging > 0 too")


@host_helper
def epoch_of(cfg, rnd):
    """The bloom-salt epoch of round ``rnd`` (host int or traced u32):
    ``rnd // compact_every``.  Requesters build/maintain the digest with
    this salt and responders query with it — both sides derive it from
    the same round counter, so the exchange stays round-synchronous.
    Cohort 0's epoch; under staggering (``cohorts > 1``) the per-peer
    generalization is :func:`epoch_of_cohort`."""
    return rnd // cfg.store.compact_every


@host_helper
def stagger_of(cfg) -> bool:
    """Is the cohort-staggered cadence compiled in?  True exactly when
    the diet is on AND ``cohorts > 1`` — the ``cohorts=1`` default keeps
    the fleet-synchronized PR-12 code path bit-identical."""
    return cfg.store.staging > 0 and cfg.store.cohorts > 1


@host_helper
def cohort_of(cfg, idx):
    """Peer ``idx``'s compaction cohort: ``idx % cohorts`` (host int or
    traced i32/u32 array).  The mod (strided) assignment keeps every
    device shard holding an equal slice of each cohort, so the active
    cohort's block extraction (a reshape + dynamic-slice on a NON-peer
    axis, ops/store.cohort_take) moves no bytes across shards."""
    return idx % cfg.store.cohorts


@host_helper
def cohort_phase(cfg, k):
    """The round-within-window on which cohort ``k`` runs its sync/
    compaction: ``compact_every - 1 - k * (compact_every // cohorts)``.
    Cohort 0 keeps the fleet-synchronized PR-12 phase (``C - 1``); the
    others interleave one sync round every ``C // cohorts`` rounds."""
    c = cfg.store.compact_every
    return c - 1 - k * (c // cfg.store.cohorts)


@host_helper
def active_cohort(cfg, rnd):
    """Which cohort syncs/compacts on round ``rnd`` (host int or traced
    u32) — the inverse of :func:`cohort_phase` on sync rounds.  Only
    meaningful where :func:`sync_round_of` holds."""
    c = cfg.store.compact_every
    stride = c // cfg.store.cohorts
    return (c - 1 - rnd % c) // stride


@host_helper
def epoch_of_cohort(cfg, rnd, k):
    """Cohort ``k``'s bloom-salt epoch at round ``rnd``: the number of
    compactions it has completed, ``(rnd + k * (C // cohorts)) // C``.
    Zero for every cohort at round 0, +1 immediately after the cohort's
    own sync round — cohort 0 degenerates to :func:`epoch_of`.  Works
    for host ints or traced u32 (``k`` may be a per-peer array, giving
    the per-peer salt vector the quiet-round digest update uses)."""
    c = cfg.store.compact_every
    return (rnd + k * (c // cfg.store.cohorts)) // c


@host_helper
def sync_round_of(cfg, rnd):
    """Cadence predicate (host int or traced u32, like ``epoch_of``):
    does round ``rnd`` run the sync exchange + compaction for SOME
    cohort?  Always True without the diet; with ``cohorts > 1`` one
    cohort syncs every ``compact_every // cohorts`` rounds (which
    cohort: :func:`active_cohort`)."""
    if cfg.store.staging == 0:
        return True
    stride = cfg.store.compact_every // cfg.store.cohorts
    return (rnd % stride) == stride - 1


@host_helper
def phase_of(cfg, rnd: int) -> str:
    """The static ``engine.step`` phase for round ``rnd`` ("sync" or
    "quiet") — for drivers that know the round index host-side and want
    the statically-specialized step instead of the dynamic cond."""
    return "sync" if sync_round_of(cfg, rnd) else "quiet"
