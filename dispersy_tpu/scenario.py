"""Scenario driver: scripted experiment timelines over the overlay.

The reference runs cluster experiments from per-peer scenario scripts —
timelines of "at T, do X" lines parsed by ``ScenarioScript`` subclasses
(reference: tool/scenarioscript.py: scenario_start / scenario_churn /
scenario-defined app events, with results decoded offline by
tool/ldecoder.py).  The TPU recast schedules *vectorized* events at round
boundaries — each event acts on a peer mask instead of one process — and
logs per-round aggregate metrics (:mod:`dispersy_tpu.metrics`) plus the
coverage of tracked records, which is exactly what the reference's
experiment pipeline extracted from its logs.

Events that change the fault model (churn/loss) swap the static config,
which recompiles the step — a few compiles per scenario, amortized over
the rounds between events (the reference pays process restarts at the
same points).

Use the library directly::

    sc = Scenario(rounds=40, events=[
        (0,  Create(meta=1, authors=[5], payload=42, track="post")),
        (10, SetFault(churn_rate=0.05)),
        (20, Authorize(members=[5], metas=0b10)),
        (30, Destroy()),
    ])
    state, log = run(cfg, sc)

or from JSON via ``tools/scenario.py`` (the CLI form of scenarioscript).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dispersy_tpu import checkpoint as ckpt
from dispersy_tpu import engine
from dispersy_tpu.config import (META_AUTHORIZE, META_DESTROY,
                                 META_DYNAMIC,
                                 META_REVOKE, META_UNDO_OTHER, META_UNDO_OWN,
                                 CommunityConfig, perm_mask)
from dispersy_tpu.metrics import MetricsLog
from dispersy_tpu.state import PeerState, init_state


def _mask(cfg: CommunityConfig, peers) -> jnp.ndarray:
    """int | sequence of ints | bool array -> bool[N]."""
    if isinstance(peers, (int, np.integer)):
        return jnp.arange(cfg.n_peers) == int(peers)
    arr = np.asarray(peers)
    if arr.dtype == bool:
        return jnp.asarray(arr)
    m = np.zeros(cfg.n_peers, bool)
    m[arr.astype(np.int64)] = True
    return jnp.asarray(m)


def _full(cfg: CommunityConfig, value) -> jnp.ndarray:
    return jnp.full(cfg.n_peers, value, jnp.uint32)


@dataclasses.dataclass
class Create:
    """App-level publish (scenarioscript's per-peer publish events)."""
    meta: int
    authors: object
    payload: int = 0
    aux: int = 0
    track: str | None = None  # label: per-round coverage of this record


@dataclasses.dataclass
class SignatureRequest:
    """Open double-signed drafts author -> counterparty."""
    meta: int
    authors: object
    counterparty: int
    payload: int = 0


@dataclasses.dataclass
class Authorize:
    """Grant permissions for the metas in the ``metas`` bitmask to
    `members`.  ``perms`` names which of the reference's four permission
    types each meta bit conveys ("permit" / "authorize" / "revoke" /
    "undo" — timeline.py's quadruple; "authorize" lets the target extend
    the chain).  ``by`` picks the granting member (default: the
    founder); a non-founder granter must hold the authorize authority
    for every named meta or the engine's author gate refuses the create,
    exactly like a live overlay."""
    members: Sequence[int]
    metas: int
    perms: Sequence[str] = ("permit",)
    by: int | None = None


@dataclasses.dataclass
class Revoke:
    """Remove the named permissions; a non-founder ``by`` must hold the
    REVOKE authority (separable from authorize) on every named meta."""
    members: Sequence[int]
    metas: int
    perms: Sequence[str] = ("permit",)
    by: int | None = None


@dataclasses.dataclass
class Undo:
    """Mark (member, gt) undone; own=True means the author undoes itself,
    else ``by`` (default: the founder; a non-founder needs the UNDO
    permission on the target's meta) undoes it."""
    member: int
    gt: int
    own: bool = True
    by: int | None = None


@dataclasses.dataclass
class DynamicSettings:
    """Founder flips user meta `meta` to Linear (linear=True) or Public."""
    meta: int
    linear: bool


@dataclasses.dataclass
class Identity:
    """Masked members publish dispersy-identity records (crypto.py
    create_identities: payload = mid32 from the member registry; the
    scenario's registry is derived from the config's peer count).
    ``peers=None`` = every non-tracker member — see create_identities'
    caveat about mass same-gt joins saturating the Bloom slice."""
    peers: object = None


@dataclasses.dataclass
class Destroy:
    """Founder hard-kills the community."""


@dataclasses.dataclass
class SetFault:
    """Swap the fault model mid-run (config change -> recompile)."""
    churn_rate: float | None = None
    packet_loss: float | None = None


@dataclasses.dataclass
class Unload:
    """Unload `members`' community instances (reference:
    Community.unload_community): they stop walking, serving, and taking
    records in; their candidate tables, delay pens, and signature caches
    — community-instance memory — are freed, while the store (the
    database) persists.  Tracker rows are silently excluded: the
    reference's TrackerCommunity auto-joins any community generically
    and has no unload path (tool/tracker.py).  With cfg.auto_load (the reference's
    define_auto_load default) any later community packet re-loads them;
    otherwise only an explicit Load event does.

    Behavior change (round 4): this event now routes through
    engine.unload_members, which also clears pending forward queues
    (fwd_*) and the mal_member conviction scratch — community-instance
    memory the old scenario-local wipe preserved.  Replays of pre-round-4
    timelines that unload a peer with forwards in flight can diverge
    from their old traces."""
    members: Sequence[int]


@dataclasses.dataclass
class Load:
    """Explicitly re-load `members`' community instances (reference:
    Dispersy.get_community(load=True) / Community.load_community).  A
    re-loaded peer re-walks from the trackers — candidates were not
    persisted, exactly the reference's restart rule."""
    members: Sequence[int]


@dataclasses.dataclass
class Checkpoint:
    path: str


@dataclasses.dataclass
class Scenario:
    rounds: int
    events: Sequence[tuple]          # (round, event) pairs
    seed_degree: int | None = 8
    snapshot_every: int = 1


def _apply(state: PeerState, cfg: CommunityConfig, ev, tracked: dict,
           ctx: dict):
    founder = cfg.founder
    if isinstance(ev, Create):
        m = _mask(cfg, ev.authors)
        authors = np.flatnonzero(np.asarray(m))
        if ev.track is not None and len(authors) == 0:
            raise ValueError(
                f"Create(track={ev.track!r}) has an empty author set — "
                "nothing to track")
        gt_before = (int(state.global_time[authors[0]])
                     if len(authors) else 0)
        state = engine.create_messages(state, cfg, m, ev.meta,
                                       _full(cfg, ev.payload),
                                       _full(cfg, ev.aux))
        if ev.track is not None:
            author = int(authors[0])
            gt_after = int(state.global_time[author])
            if gt_after == gt_before:
                # The timeline gate refused the creation (e.g. protected
                # meta scheduled before its authorize): a silent garbage
                # coverage curve would be worse than failing the scenario.
                raise ValueError(
                    f"Create(track={ev.track!r}): author {author}'s "
                    f"creation of meta {ev.meta} was refused by the "
                    "timeline gate — reorder the scenario's events")
            tracked[ev.track] = (author, gt_after, ev.meta, ev.payload)
    elif isinstance(ev, SignatureRequest):
        state = engine.create_signature_request(
            state, cfg, _mask(cfg, ev.authors), ev.meta,
            jnp.full(cfg.n_peers, ev.counterparty, jnp.int32),
            _full(cfg, ev.payload))
    elif isinstance(ev, (Authorize, Revoke)):
        meta = META_AUTHORIZE if isinstance(ev, Authorize) else META_REVOKE
        granter = founder if ev.by is None else ev.by
        nibbles = perm_mask([(k, p) for k in range(32)
                             if (ev.metas >> k) & 1 for p in ev.perms])
        for member in ev.members:   # one record per target member
            state = engine.create_messages(
                state, cfg, _mask(cfg, granter), meta,
                _full(cfg, member), _full(cfg, nibbles))
    elif isinstance(ev, Undo):
        meta = META_UNDO_OWN if ev.own else META_UNDO_OTHER
        author = ev.member if ev.own else (
            founder if ev.by is None else ev.by)
        state = engine.create_messages(
            state, cfg, _mask(cfg, author), meta,
            _full(cfg, ev.member), _full(cfg, ev.gt))
    elif isinstance(ev, DynamicSettings):
        state = engine.create_messages(
            state, cfg, _mask(cfg, founder), META_DYNAMIC,
            _full(cfg, ev.meta), _full(cfg, int(ev.linear)))
    elif isinstance(ev, Identity):
        from dispersy_tpu import crypto
        # One registry per run: derived members are cached across events
        # (staggered-join scenarios re-use earlier derivations).
        registry = ctx.setdefault(
            "registry", crypto.MemberRegistry(n_peers=cfg.n_peers))
        state = crypto.create_identities(
            state, cfg, registry,
            mask=None if ev.peers is None else _mask(cfg, ev.peers))
    elif isinstance(ev, Destroy):
        state = engine.create_messages(
            state, cfg, _mask(cfg, founder), META_DESTROY,
            _full(cfg, 0))
    elif isinstance(ev, Unload):
        m = np.isin(np.arange(cfg.n_peers), list(ev.members))
        state = engine.unload_members(state, cfg, jnp.asarray(m))
    elif isinstance(ev, Load):
        m = np.isin(np.arange(cfg.n_peers), list(ev.members))
        state = engine.load_members(state, jnp.asarray(m))
    elif isinstance(ev, SetFault):
        kw = {}
        if ev.churn_rate is not None:
            kw["churn_rate"] = ev.churn_rate
        if ev.packet_loss is not None:
            kw["packet_loss"] = ev.packet_loss
        cfg = cfg.replace(**kw)
    elif isinstance(ev, Checkpoint):
        ckpt.save(ev.path, state, cfg)
    else:
        raise TypeError(f"unknown scenario event {ev!r}")
    return state, cfg


def run(cfg: CommunityConfig, scenario: Scenario, key=None,
        log: MetricsLog | None = None) -> tuple[PeerState, MetricsLog]:
    """Execute the scenario; returns the final state and the metrics log.

    Every logged row carries ``cov_<label>`` for each tracked record —
    the convergence curves the reference's experiment pipeline mined from
    its logs.
    """
    state = init_state(cfg, key if key is not None else jax.random.PRNGKey(0))
    if scenario.seed_degree:
        state = engine.seed_overlay(state, cfg, scenario.seed_degree)
    log = log or MetricsLog(meta={"scenario_rounds": scenario.rounds})
    by_round: dict[int, list] = {}
    for rnd, ev in scenario.events:
        if not (0 <= int(rnd) < scenario.rounds):
            # Silently skipping a scripted event would make the artifact
            # describe a different experiment than the scenario file.
            raise ValueError(
                f"event {ev!r} scheduled at round {rnd}, outside the "
                f"scenario's [0, {scenario.rounds}) range")
        if isinstance(ev, Identity) and not cfg.identity_enabled:
            # Fail before round 0, not when the event's round is reached
            # — a late crash wastes every compiled round before it.
            raise ValueError(
                f"Identity event at round {rnd} requires "
                "config.identity_enabled=True")
        by_round.setdefault(int(rnd), []).append(ev)
    tracked: dict[str, tuple] = {}
    ctx: dict = {}

    for rnd in range(scenario.rounds):
        for ev in by_round.get(rnd, ()):
            state, cfg = _apply(state, cfg, ev, tracked, ctx)
        state = engine.step(state, cfg)
        if rnd % scenario.snapshot_every == 0:
            covs = {f"cov_{label}": float(engine.coverage(state, *spec))
                    for label, spec in tracked.items()}
            log.append(state, cfg, **covs)
    return jax.block_until_ready(state), log
