"""Scenario driver: scripted experiment timelines over the overlay.

The reference runs cluster experiments from per-peer scenario scripts —
timelines of "at T, do X" lines parsed by ``ScenarioScript`` subclasses
(reference: tool/scenarioscript.py: scenario_start / scenario_churn /
scenario-defined app events, with results decoded offline by
tool/ldecoder.py).  The TPU recast schedules *vectorized* events at round
boundaries — each event acts on a peer mask instead of one process — and
logs per-round aggregate metrics (:mod:`dispersy_tpu.metrics`) plus the
coverage of tracked records, which is exactly what the reference's
experiment pipeline extracted from its logs.

Events that change the fault model (churn/loss) swap the static config,
which recompiles the step — a few compiles per scenario, amortized over
the rounds between events (the reference pays process restarts at the
same points).

Use the library directly::

    sc = Scenario(rounds=40, events=[
        (0,  Create(meta=1, authors=[5], payload=42, track="post")),
        (10, SetFault(churn_rate=0.05)),
        (20, Authorize(members=[5], metas=0b10)),
        (30, Destroy()),
    ])
    state, log = run(cfg, sc)

or from JSON via ``tools/scenario.py`` (the CLI form of scenarioscript).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dispersy_tpu import checkpoint as ckpt
from dispersy_tpu import engine
from dispersy_tpu import faults as flts
from dispersy_tpu.config import (META_AUTHORIZE, META_DESTROY,
                                 META_DYNAMIC,
                                 META_REVOKE, META_UNDO_OTHER, META_UNDO_OWN,
                                 CommunityConfig, perm_mask)
from dispersy_tpu.exceptions import CheckpointError
from dispersy_tpu.metrics import MetricsLog
from dispersy_tpu.state import PeerState, init_state

logger = logging.getLogger(__name__)

AUTOSAVE_PREFIX = "auto" + "_"   # autosave file stem: auto_<round>.npz


def _mask(cfg: CommunityConfig, peers) -> jnp.ndarray:
    """int | sequence of ints | bool array -> bool[N]."""
    if isinstance(peers, (int, np.integer)):
        return jnp.arange(cfg.n_peers) == int(peers)
    arr = np.asarray(peers)
    if arr.dtype == bool:
        return jnp.asarray(arr)
    m = np.zeros(cfg.n_peers, bool)
    m[arr.astype(np.int64)] = True
    return jnp.asarray(m)


def _full(cfg: CommunityConfig, value) -> jnp.ndarray:
    return jnp.full(cfg.n_peers, value, jnp.uint32)


@dataclasses.dataclass
class Create:
    """App-level publish (scenarioscript's per-peer publish events)."""
    meta: int
    authors: object
    payload: int = 0
    aux: int = 0
    track: str | None = None  # label: per-round coverage of this record


@dataclasses.dataclass
class TrackRecord:
    """Register an existing record ``(author, gt)`` for on-device
    dissemination tracing (dispersy_tpu/traceplane.py;
    ``engine.track_record``).  Requires ``cfg.trace.enabled``; peers
    already holding the record at registration are attributed to the
    create channel, so schedule it at (or right after) the record's
    creation — ``Create(track=...)`` does exactly that automatically
    when the trace plane is on.  Unlike ``Create.track``'s host-query
    fallback, a TrackRecord label's coverage curve always comes from
    the telemetry rows (``trace_cov_<slot> / alive_members``), so
    tracked runs keep the batched ring fast path."""
    label: str
    author: int
    gt: int


@dataclasses.dataclass
class SignatureRequest:
    """Open double-signed drafts author -> counterparty."""
    meta: int
    authors: object
    counterparty: int
    payload: int = 0


@dataclasses.dataclass
class Authorize:
    """Grant permissions for the metas in the ``metas`` bitmask to
    `members`.  ``perms`` names which of the reference's four permission
    types each meta bit conveys ("permit" / "authorize" / "revoke" /
    "undo" — timeline.py's quadruple; "authorize" lets the target extend
    the chain).  ``by`` picks the granting member (default: the
    founder); a non-founder granter must hold the authorize authority
    for every named meta or the engine's author gate refuses the create,
    exactly like a live overlay."""
    members: Sequence[int]
    metas: int
    perms: Sequence[str] = ("permit",)
    by: int | None = None


@dataclasses.dataclass
class Revoke:
    """Remove the named permissions; a non-founder ``by`` must hold the
    REVOKE authority (separable from authorize) on every named meta."""
    members: Sequence[int]
    metas: int
    perms: Sequence[str] = ("permit",)
    by: int | None = None


@dataclasses.dataclass
class Undo:
    """Mark (member, gt) undone; own=True means the author undoes itself,
    else ``by`` (default: the founder; a non-founder needs the UNDO
    permission on the target's meta) undoes it."""
    member: int
    gt: int
    own: bool = True
    by: int | None = None


@dataclasses.dataclass
class DynamicSettings:
    """Founder flips user meta `meta` to Linear (linear=True) or Public."""
    meta: int
    linear: bool


@dataclasses.dataclass
class Identity:
    """Masked members publish dispersy-identity records (crypto.py
    create_identities: payload = mid32 from the member registry; the
    scenario's registry is derived from the config's peer count).
    ``peers=None`` = every non-tracker member — see create_identities'
    caveat about mass same-gt joins saturating the Bloom slice."""
    peers: object = None


@dataclasses.dataclass
class Destroy:
    """Founder hard-kills the community."""


@dataclasses.dataclass
class SetFault:
    """Swap the fault model mid-run (config change -> recompile).

    ``None`` leaves a knob unchanged.  Beyond the original churn/loss
    pair, every chaos-harness knob (dispersy_tpu/faults.py FaultModel)
    can be swapped: Gilbert-Elliott burst parameters, region
    partitions (heal a netsplit by passing ``partitions=()``),
    duplication/corruption rates, byzantine flooders, and the health
    sentinels.  Knob flips that enable/disable a whole subsystem
    resize its state leaves via ``faults.adapt_state`` (enabling
    starts clean; disabling discards the latch/counter)."""
    churn_rate: float | None = None
    packet_loss: float | None = None
    ge_p_bad: float | None = None
    ge_p_good: float | None = None
    ge_loss_good: float | None = None
    ge_loss_bad: float | None = None
    partitions: tuple | None = None
    dup_rate: float | None = None
    corrupt_rate: float | None = None
    flood_senders: tuple | None = None
    flood_fanout: int | None = None
    health_checks: bool | None = None
    health_drop_limit: int | None = None


_FAULT_KNOBS = ("ge_p_bad", "ge_p_good", "ge_loss_good", "ge_loss_bad",
                "partitions", "dup_rate", "corrupt_rate", "flood_senders",
                "flood_fanout", "health_checks", "health_drop_limit")


@dataclasses.dataclass
class SetRecovery:
    """Swap the recovery plane mid-run (config change -> recompile;
    dispersy_tpu/recovery.py RecoveryConfig — the ``SetFault`` shape).

    ``None`` leaves a knob unchanged.  Flipping ``enabled`` across the
    boundary resizes the recovery state leaves via
    ``recovery.adapt_state`` (enabling starts clean; disabling discards
    backoff/quarantine/repair history and the action counters).  The
    applied flips are recorded in the autosave JSON sidecar
    (``recovery_history``) so ``run(resume=True)`` replays them even
    when the resume straddles the flip round."""
    enabled: bool | None = None
    soft_repair: bool | None = None
    backoff_limit: int | None = None
    backoff_decay: float | None = None
    quarantine_rounds: int | None = None
    requarantine_window: int | None = None


_RECOVERY_KNOBS = ("enabled", "soft_repair", "backoff_limit",
                   "backoff_decay", "quarantine_rounds",
                   "requarantine_window")


def _setrecovery_kw(ev: "SetRecovery") -> dict:
    return {k: getattr(ev, k) for k in _RECOVERY_KNOBS
            if getattr(ev, k) is not None}


def _setrecovery_cfg(cfg: CommunityConfig,
                     ev: "SetRecovery") -> CommunityConfig:
    """The pure config half of a SetRecovery — shared by the live event
    interpreter and the resume-time replay (run())."""
    kw = _setrecovery_kw(ev)
    return cfg.replace(recovery=cfg.recovery.replace(**kw)) if kw else cfg


@dataclasses.dataclass
class SetOverload:
    """Swap the ingress-protection plane mid-run (config change ->
    recompile; dispersy_tpu/overload.py OverloadConfig — the
    ``SetRecovery`` shape).

    ``None`` leaves a knob unchanged.  Flipping ``enabled`` across the
    boundary resizes the overload state leaves via
    ``overload.adapt_state`` (enabling starts with empty buckets and
    zero shed counters; disabling discards).  The applied flips are
    recorded in the autosave JSON sidecar (``overload_history``) so
    ``run(resume=True)`` replays them even when the resume straddles
    the flip round."""
    enabled: bool | None = None
    priority_admission: bool | None = None
    bucket_rate: float | None = None
    bucket_depth: int | None = None


_OVERLOAD_KNOBS = ("enabled", "priority_admission", "bucket_rate",
                   "bucket_depth")


def _setoverload_kw(ev: "SetOverload") -> dict:
    return {k: getattr(ev, k) for k in _OVERLOAD_KNOBS
            if getattr(ev, k) is not None}


def _setoverload_cfg(cfg: CommunityConfig,
                     ev: "SetOverload") -> CommunityConfig:
    """The pure config half of a SetOverload — shared by the live event
    interpreter and the resume-time replay (run())."""
    kw = _setoverload_kw(ev)
    return cfg.replace(overload=cfg.overload.replace(**kw)) if kw else cfg


def _deep_tuple(v):
    """JSON lists -> tuples, recursively (FaultModel fields must stay
    hashable for the jitted step's static config argument)."""
    if isinstance(v, (list, tuple)):
        return tuple(_deep_tuple(x) for x in v)
    return v


def _setfault_cfg(cfg: CommunityConfig, ev: "SetFault") -> CommunityConfig:
    """The pure config half of a SetFault — shared by the live event
    interpreter and the resume-time config replay (run())."""
    kw = {}
    if ev.churn_rate is not None:
        kw["churn_rate"] = ev.churn_rate
    if ev.packet_loss is not None:
        kw["packet_loss"] = ev.packet_loss
    fkw = {k: _deep_tuple(getattr(ev, k)) for k in _FAULT_KNOBS
           if getattr(ev, k) is not None}
    if fkw:
        kw["faults"] = cfg.faults.replace(**fkw)
    return cfg.replace(**kw) if kw else cfg


@dataclasses.dataclass
class Unload:
    """Unload `members`' community instances (reference:
    Community.unload_community): they stop walking, serving, and taking
    records in; their candidate tables, delay pens, and signature caches
    — community-instance memory — are freed, while the store (the
    database) persists.  Tracker rows are silently excluded: the
    reference's TrackerCommunity auto-joins any community generically
    and has no unload path (tool/tracker.py).  With cfg.auto_load (the reference's
    define_auto_load default) any later community packet re-loads them;
    otherwise only an explicit Load event does.

    Behavior change (round 4): this event now routes through
    engine.unload_members, which also clears pending forward queues
    (fwd_*) and the mal_member conviction scratch — community-instance
    memory the old scenario-local wipe preserved.  Replays of pre-round-4
    timelines that unload a peer with forwards in flight can diverge
    from their old traces."""
    members: Sequence[int]


@dataclasses.dataclass
class Load:
    """Explicitly re-load `members`' community instances (reference:
    Dispersy.get_community(load=True) / Community.load_community).  A
    re-loaded peer re-walks from the trackers — candidates were not
    persisted, exactly the reference's restart rule."""
    members: Sequence[int]


@dataclasses.dataclass
class Checkpoint:
    path: str


@dataclasses.dataclass
class Scenario:
    rounds: int
    events: Sequence[tuple]          # (round, event) pairs
    seed_degree: int | None = 8
    snapshot_every: int = 1
    # Crash-resume (FAULTS.md): every `autosave_every` rounds the runner
    # checkpoints state (CRC-protected, checkpoint.py — single-run
    # archives at the current format, v12) plus a JSON sidecar (metrics
    # rows, tracked records, applied SetRecovery flips, next round)
    # into `autosave_dir`;
    # run(..., resume=True) restarts from the latest snapshot that
    # passes CRC — a corrupt/torn autosave is rejected with
    # CheckpointError and the previous one is used.  0 = off.  Autosave
    # snapshots being ordinary single-run archives, any of them also
    # loads as a 1-replica fleet (checkpoint.restore_fleet; FLEET.md)
    # when a crashed scenario's state should seed a fleet study.
    autosave_every: int = 0
    autosave_dir: str | None = None


def _apply(state: PeerState, cfg: CommunityConfig, ev, tracked: dict,
           ctx: dict, trace_slots: dict | None = None, rnd: int = 0):
    trace_slots = trace_slots if trace_slots is not None else {}
    founder = cfg.founder
    if isinstance(ev, TrackRecord):
        # On-device lineage registration (traceplane.py): the label's
        # coverage rides the telemetry rows, never a host store query.
        if not cfg.trace.enabled:
            raise ValueError(
                f"TrackRecord({ev.label!r}) requires cfg.trace.enabled "
                "(the dissemination-tracing plane)")
        state, slot = engine.track_record(state, cfg, int(ev.author),
                                          int(ev.gt))
        trace_slots[ev.label] = (slot, rnd)
        return state, cfg
    if isinstance(ev, Create):
        m = _mask(cfg, ev.authors)
        authors = np.flatnonzero(np.asarray(m))
        if ev.track is not None and len(authors) == 0:
            raise ValueError(
                f"Create(track={ev.track!r}) has an empty author set — "
                "nothing to track")
        gt_before = (int(state.global_time[authors[0]])
                     if len(authors) else 0)
        state = engine.create_messages_jit(state, cfg, m, ev.meta,
                                       _full(cfg, ev.payload),
                                       _full(cfg, ev.aux))
        if ev.track is not None:
            author = int(authors[0])
            gt_after = int(state.global_time[author])
            if gt_after == gt_before:
                # The timeline gate refused the creation (e.g. protected
                # meta scheduled before its authorize): a silent garbage
                # coverage curve would be worse than failing the scenario.
                raise ValueError(
                    f"Create(track={ev.track!r}): author {author}'s "
                    f"creation of meta {ev.meta} was refused by the "
                    "timeline gate — reorder the scenario's events")
            tracked[ev.track] = (author, gt_after, ev.meta, ev.payload)
            if cfg.trace.enabled:
                # With the trace plane on, the label's coverage comes
                # from the on-device lineage (registration stamps the
                # author as the create-channel arrival) and the run
                # keeps the ring fast path — the host-query spec above
                # stays only as the cross-check the parity tests use.
                # SLOT EXHAUSTION degrades gracefully: the overflow
                # label falls back to the legacy host-query path the
                # runner still supports for unregistered labels (the
                # run slows, it does not abort mid-scenario); the
                # explicit TrackRecord event stays strict.
                try:
                    state, slot = engine.track_record(state, cfg,
                                                      author, gt_after)
                except ValueError:
                    logger.warning(
                        "Create(track=%r): all %d trace.tracked_slots "
                        "taken — label falls back to per-round host "
                        "store queries (off the ring fast path)",
                        ev.track, cfg.trace.tracked_slots)
                else:
                    trace_slots[ev.track] = (slot, rnd)
    elif isinstance(ev, SignatureRequest):
        state = engine.create_signature_request_jit(
            state, cfg, _mask(cfg, ev.authors), ev.meta,
            jnp.full(cfg.n_peers, ev.counterparty, jnp.int32),
            _full(cfg, ev.payload))
    elif isinstance(ev, (Authorize, Revoke)):
        meta = META_AUTHORIZE if isinstance(ev, Authorize) else META_REVOKE
        granter = founder if ev.by is None else ev.by
        nibbles = perm_mask([(k, p) for k in range(32)
                             if (ev.metas >> k) & 1 for p in ev.perms])
        for member in ev.members:   # one record per target member
            state = engine.create_messages_jit(
                state, cfg, _mask(cfg, granter), meta,
                _full(cfg, member), _full(cfg, nibbles))
    elif isinstance(ev, Undo):
        meta = META_UNDO_OWN if ev.own else META_UNDO_OTHER
        author = ev.member if ev.own else (
            founder if ev.by is None else ev.by)
        state = engine.create_messages_jit(
            state, cfg, _mask(cfg, author), meta,
            _full(cfg, ev.member), _full(cfg, ev.gt))
    elif isinstance(ev, DynamicSettings):
        state = engine.create_messages_jit(
            state, cfg, _mask(cfg, founder), META_DYNAMIC,
            _full(cfg, ev.meta), _full(cfg, int(ev.linear)))
    elif isinstance(ev, Identity):
        from dispersy_tpu import crypto
        # One registry per run: derived members are cached across events
        # (staggered-join scenarios re-use earlier derivations).
        registry = ctx.setdefault(
            "registry", crypto.MemberRegistry(n_peers=cfg.n_peers))
        state = crypto.create_identities(
            state, cfg, registry,
            mask=None if ev.peers is None else _mask(cfg, ev.peers))
    elif isinstance(ev, Destroy):
        state = engine.create_messages_jit(
            state, cfg, _mask(cfg, founder), META_DESTROY,
            _full(cfg, 0))
    elif isinstance(ev, Unload):
        m = np.isin(np.arange(cfg.n_peers), list(ev.members))
        state = engine.unload_members_jit(state, cfg, jnp.asarray(m))
    elif isinstance(ev, Load):
        m = np.isin(np.arange(cfg.n_peers), list(ev.members))
        state = engine.load_members_jit(state, jnp.asarray(m))
    elif isinstance(ev, SetFault):
        new_cfg = _setfault_cfg(cfg, ev)
        # Knob flips across the enablement boundary resize the
        # chaos-harness leaves (zero-width while compiled out).
        state = flts.adapt_state(state, cfg, new_cfg)
        cfg = new_cfg
    elif isinstance(ev, SetRecovery):
        from dispersy_tpu import recovery as rcv
        new_cfg = _setrecovery_cfg(cfg, ev)
        state = rcv.adapt_state(state, cfg, new_cfg)
        cfg = new_cfg
    elif isinstance(ev, SetOverload):
        from dispersy_tpu import overload as ovl
        new_cfg = _setoverload_cfg(cfg, ev)
        state = ovl.adapt_state(state, cfg, new_cfg)
        cfg = new_cfg
    elif isinstance(ev, Checkpoint):
        ckpt.save(ev.path, state, cfg)
    else:
        raise TypeError(f"unknown scenario event {ev!r}")
    return state, cfg


def _autosave(dirpath: str, next_round: int, state: PeerState,
              cfg: CommunityConfig, tracked: dict, log: MetricsLog,
              recovery_hist: list | None = None,
              overload_hist: list | None = None,
              trace_slots: dict | None = None) -> None:
    """One crash-resume snapshot: CRC-protected state archive + a JSON
    sidecar carrying everything the runner itself holds (metrics rows,
    tracked-record specs, the round to resume at, and the applied
    SetRecovery/SetOverload flips so resume replays the config
    history).  Both writes are atomic (tmp + replace), so a crash
    mid-autosave leaves the previous snapshot intact and the torn one
    detectably invalid."""
    os.makedirs(dirpath, exist_ok=True)
    base = os.path.join(dirpath, f"{AUTOSAVE_PREFIX}{next_round:06d}")
    ckpt.save(base + ".npz", state, cfg)
    doc = {"next_round": next_round,
           "tracked": {k: list(v) for k, v in tracked.items()},
           "trace_slots": {k: list(v)
                           for k, v in (trace_slots or {}).items()},
           "recovery_history": list(recovery_hist or ()),
           "overload_history": list(overload_hist or ()),
           "meta": log.meta, "rows": log.rows}
    # Same tmp hygiene as checkpoint._atomic_npz: sweep orphans from
    # crashed savers, unlink our own tmp on any failure — a kill between
    # write and replace must not leak auto_*.json.tmp.<pid> forever.
    ckpt._clean_stale_tmps(base + ".json")
    tmp = f"{base}.json.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, base + ".json")
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _cfg_at_round(cfg: CommunityConfig, by_round: dict, upto: int,
                  recovery_history: list | None = None,
                  overload_history: list | None = None
                  ) -> CommunityConfig:
    """Replay the schedule's config-affecting events (SetFault /
    SetRecovery / SetOverload) for rounds < ``upto``: the config a
    snapshot taken after round ``upto - 1`` was saved under.  Pure — no
    state is touched.  When an autosave sidecar's ``recovery_history``
    / ``overload_history`` is given it is the authority for that
    plane's flips (the flips that actually ran), applied instead of
    scanning ``by_round`` for the matching event type."""
    for rnd in sorted(r for r in by_round if r < upto):
        for ev in by_round[rnd]:
            if isinstance(ev, SetFault):
                cfg = _setfault_cfg(cfg, ev)
            elif isinstance(ev, SetRecovery) and recovery_history is None:
                cfg = _setrecovery_cfg(cfg, ev)
            elif isinstance(ev, SetOverload) and overload_history is None:
                cfg = _setoverload_cfg(cfg, ev)
    for rnd, kw in (recovery_history or ()):
        if rnd < upto:
            cfg = cfg.replace(recovery=cfg.recovery.replace(**kw))
    for rnd, kw in (overload_history or ()):
        if rnd < upto:
            cfg = cfg.replace(overload=cfg.overload.replace(**kw))
    return cfg


def _load_latest_autosave(dirpath: str, cfg0: CommunityConfig,
                          by_round: dict):
    """Newest-first scan of the autosave directory; returns
    ``(state, cfg, next_round, sidecar)`` from the latest snapshot whose
    archive passes the CRC/config checks, or None when no usable
    snapshot exists.  Corrupt/torn snapshots (CheckpointError) are
    logged and SKIPPED — never silently restored — so a crash during
    autosave falls back to the previous good one.  ``*.tmp.*`` leftovers
    never match the ``.npz`` glob."""
    import glob as _glob

    def _snap_round(path: str) -> int:
        stem = os.path.basename(path)[len(AUTOSAVE_PREFIX):-len(".npz")]
        return int(stem) if stem.isdigit() else -1

    snaps = sorted(_glob.glob(os.path.join(
        dirpath, AUTOSAVE_PREFIX + "*.npz")), key=_snap_round, reverse=True)
    for path in snaps:
        sidecar = path[:-len(".npz")] + ".json"
        try:
            with open(sidecar) as f:
                doc = json.load(f)
            next_round = int(doc["next_round"])
            cfg = _cfg_at_round(cfg0, by_round, next_round,
                                doc.get("recovery_history"),
                                doc.get("overload_history"))
            state = ckpt.restore(path, cfg)
        except (CheckpointError, OSError, ValueError, KeyError) as e:
            logger.warning("autosave %s unusable (%s: %s); falling back "
                           "to the previous snapshot", path,
                           type(e).__name__, e)
            continue
        return state, cfg, next_round, doc
    return None


def _ring_chunk(cfg: CommunityConfig, scenario: Scenario, by_round: dict,
                tracked: dict, rnd: int,
                trace_slots: dict | None = None) -> int:
    """Rounds safely batchable through ``engine.multi_step`` + one ring
    drain, starting at ``rnd`` (1 = take the per-round path).

    Batchable only when the ring is deep enough to hold every skipped
    round, per-round logging is the plain snapshot (snapshot_every=1),
    every tracked coverage curve is served on-device (its label is
    registered with the trace plane, so ``cov_<label>`` derives from
    the row's ``trace_cov_<slot>`` word — traceplane.py; a label
    WITHOUT a trace slot still needs the legacy host-side store query
    each round), and the span crosses no scheduled event.  An autosave
    boundary only bounds the chunk (the snapshot happens at its exact
    round either way)."""
    h = cfg.telemetry.history
    host_tracked = [lbl for lbl in tracked
                    if lbl not in (trace_slots or {})]
    if h <= 1 or scenario.snapshot_every != 1 or host_tracked:
        return 1
    limit = min(h, scenario.rounds - rnd)
    for k in range(1, limit):
        if (rnd + k) in by_round:
            limit = k
            break
    if scenario.autosave_every:
        limit = min(limit,
                    scenario.autosave_every - rnd % scenario.autosave_every)
    return max(limit, 1)


def _attach_trace_covs(row: dict, trace_slots: dict) -> None:
    """Derive ``cov_<label>`` for every trace-registered label from the
    row's on-device coverage words: ``trace_cov_<slot> /
    max(alive_members, 1)`` in float32 — the same f32 division
    ``engine.coverage``'s host query computes, so the two paths emit
    identical curves as long as no tracked record is ever EVICTED from
    a ring (lineage is arrival history, the host query is current
    residency — traceplane.py; a LastSync/capacity eviction would keep
    the trace curve high where the host query dips).  Pinned
    round-for-round equal at non-evicting capacity in
    tests/test_trace.py.  Rows from before a label's registration
    round carry no key for it, exactly like the legacy per-round
    path."""
    for label, (slot, reg_rnd) in trace_slots.items():
        if int(row.get("round", 0)) <= int(reg_rnd):
            continue
        cov = row.get(f"trace_cov_{slot}")
        if cov is None:
            continue
        alive = max(int(row.get("alive_members", 0)), 1)
        row[f"cov_{label}"] = float(np.float32(cov) / np.float32(alive))


def run(cfg: CommunityConfig, scenario: Scenario, key=None,
        log: MetricsLog | None = None,
        resume: bool = False) -> tuple[PeerState, MetricsLog]:
    """Execute the scenario; returns the final state and the metrics log.

    Every logged row carries ``cov_<label>`` for each tracked record —
    the convergence curves the reference's experiment pipeline mined from
    its logs.

    With ``resume=True`` (and ``scenario.autosave_dir`` populated by an
    earlier autosaving run) execution restarts from the latest valid
    snapshot and the finished run is BIT-IDENTICAL — final state and
    metrics log — to an uninterrupted one: restore is the byte-exact
    ``fresh_candidates=False`` mode, the RNG key/round ride in the
    archive, and the sidecar restores the metrics rows and tracked
    records (JSON round-trips Python floats exactly).
    """
    log = log or MetricsLog(meta={"scenario_rounds": scenario.rounds})
    by_round: dict[int, list] = {}
    for rnd, ev in scenario.events:
        if not (0 <= int(rnd) < scenario.rounds):
            # Silently skipping a scripted event would make the artifact
            # describe a different experiment than the scenario file.
            raise ValueError(
                f"event {ev!r} scheduled at round {rnd}, outside the "
                f"scenario's [0, {scenario.rounds}) range")
        if isinstance(ev, Identity) and not cfg.identity_enabled:
            # Fail before round 0, not when the event's round is reached
            # — a late crash wastes every compiled round before it.
            raise ValueError(
                f"Identity event at round {rnd} requires "
                "config.identity_enabled=True")
        by_round.setdefault(int(rnd), []).append(ev)
    if scenario.autosave_every and not scenario.autosave_dir:
        raise ValueError("autosave_every requires autosave_dir")
    tracked: dict[str, tuple] = {}
    trace_slots: dict[str, tuple] = {}   # label -> (slot, reg round)
    ctx: dict = {}
    recovery_hist: list = []   # applied SetRecovery flips: [round, kw]
    overload_hist: list = []   # applied SetOverload flips: [round, kw]
    start_round = 0
    state = None
    if resume:
        if not scenario.autosave_dir:
            raise ValueError("resume=True requires scenario.autosave_dir")
        got = _load_latest_autosave(scenario.autosave_dir, cfg, by_round)
        if got is not None:
            state, cfg, start_round, doc = got
            tracked = {k: tuple(v) for k, v in doc["tracked"].items()}
            trace_slots = {k: (int(v[0]), int(v[1])) for k, v in
                           doc.get("trace_slots", {}).items()}
            recovery_hist = [[int(r), dict(kw)] for r, kw in
                             doc.get("recovery_history", ())]
            overload_hist = [[int(r), dict(kw)] for r, kw in
                             doc.get("overload_history", ())]
            log.meta = doc.get("meta", log.meta)
            log.rows = list(doc.get("rows", ()))
            logger.info("resuming scenario at round %d from %s",
                        start_round, scenario.autosave_dir)
    if state is None:
        state = init_state(cfg, key if key is not None
                           else jax.random.PRNGKey(0))
        if scenario.seed_degree:
            state = engine.seed_overlay(state, cfg, scenario.seed_degree)

    rnd = start_round
    while rnd < scenario.rounds:
        for ev in by_round.get(rnd, ()):
            state, cfg = _apply(state, cfg, ev, tracked, ctx,
                                trace_slots, rnd)
            if isinstance(ev, SetRecovery):
                # Record the applied flip for the autosave sidecar so a
                # resume that straddles it replays the same config.
                recovery_hist.append([rnd, _setrecovery_kw(ev)])
            elif isinstance(ev, SetOverload):
                overload_hist.append([rnd, _setoverload_kw(ev)])
        # Device-resident fast path (telemetry ring, OBSERVABILITY.md):
        # with a round-history ring compiled in and nothing forcing a
        # per-round host visit (no tracked coverage, snapshot_every=1),
        # whole event-free spans run as ONE multi_step dispatch and the
        # per-round metrics history drains from the ring in a single
        # transfer — rounds never cross the host at all in between.
        chunk = _ring_chunk(cfg, scenario, by_round, tracked, rnd,
                            trace_slots)
        if chunk > 1:
            state = engine.multi_step(state, cfg, chunk)
            for row in log.extend_from_ring(state, cfg):
                _attach_trace_covs(row, trace_slots)
            rnd += chunk
        else:
            state = engine.step(state, cfg)
            if rnd % scenario.snapshot_every == 0:
                # Host-side store queries only for labels WITHOUT an
                # on-device trace slot (traceplane.py moved tracked
                # coverage into the fused step; _attach_trace_covs
                # derives those labels' curves from the row words).
                covs = {f"cov_{label}": float(engine.coverage(state, *spec))
                        for label, spec in tracked.items()
                        if label not in trace_slots}
                row = log.append(state, cfg, **covs)
                _attach_trace_covs(row, trace_slots)
            rnd += 1
        if scenario.autosave_every and rnd % scenario.autosave_every == 0:
            _autosave(scenario.autosave_dir, rnd, state, cfg,
                      tracked, log, recovery_hist, overload_hist,
                      trace_slots)
    return jax.block_until_ready(state), log
