"""dispersy-tpu: a TPU-native re-design of the Dispersy epidemic overlay.

Dispersy (reference: ``lfdversluis/dispersy``) is a decentralized,
NAT-traversing epidemic message-synchronization overlay: peers discover each
other via a random walk (``dispersy-introduction-request/-response`` +
``dispersy-puncture``) and reconcile message stores via Bloom-filter sync
(``Community.dispersy_claim_sync_bloom_filter``).

This package recasts that overlay as a massively batched JAX simulation:

- every peer is a row of a device-sharded ``PeerState`` pytree,
- one ``pjit``-compiled ``step`` function advances *all* peers one walker
  interval at a time,
- UDP delivery becomes :mod:`dispersy_tpu.ops.inbox` (sort-by-receiver
  scatter into bounded inboxes — the ``JaxSimEndpoint`` seam),
- Bloom filters become packed-uint32 bit kernels (:mod:`dispersy_tpu.ops.bloom`),
- the SQLite ``sync`` table becomes a sorted fixed-capacity ring store
  (:mod:`dispersy_tpu.ops.store`),
- the ``Community`` subclass API survives at the rim
  (:mod:`dispersy_tpu.community`) and compiles policy declarations down to
  static kernel configuration.

See ``SURVEY.md`` for the reference's layer map and the provenance caveat
(the reference mount was empty during the survey; citations are
symbol-level).
"""

__version__ = "0.4.0"

from dispersy_tpu.config import CommunityConfig  # noqa: F401
from dispersy_tpu.community import Community  # noqa: F401

__all__ = ["CommunityConfig", "Community"]
# Deeper layers by module (imported on demand, not re-exported):
#   dispersy_tpu.engine      step / multi_step / create_* / coverage
#   dispersy_tpu.state       PeerState / init_state
#   dispersy_tpu.crypto      ECCrypto / Member / MemberRegistry / identities
#   dispersy_tpu.conversion  packet encode/decode (conformance)
#   dispersy_tpu.checkpoint  save / restore
#   dispersy_tpu.metrics     snapshot / MetricsLog (+ extend_from_ring)
#   dispersy_tpu.telemetry   TelemetryConfig / row schema / flight records
#   dispersy_tpu.recovery    RecoveryConfig / mttr_report (RECOVERY.md)
#   dispersy_tpu.overload    OverloadConfig / overload_report /
#                            shed_report (OVERLOAD.md)
#   dispersy_tpu.traceplane  TraceConfig / trace_report / channel codes
#                            (OBSERVABILITY.md "Dissemination tracing")
#   dispersy_tpu.binlog      packed binary round logs (ldecoder analogue)
#   dispersy_tpu.scenario    Scenario / run + event types
#   dispersy_tpu.parallel    make_mesh / shard_state
