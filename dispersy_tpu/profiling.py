"""Per-phase cost accounting for the fused round.

The round is memory-bandwidth-bound (BENCH.md roofline), so the number
that matters for every perf PR is *bytes moved per round* — wall clock
through the TPU tunnel is noise-dominated (±50% on identical configs,
BENCH.md r2), but XLA's static cost analysis of the compiled executable
is exact and available on any backend, including compile-only runs at
populations this host could never execute (the 1M-peer bench shape).

Three layers, consumed by ``tools/profile_round.py``:

- :func:`step_cost` — lower + compile the REAL fused ``engine.step`` at a
  config's exact shapes from ``jax.ShapeDtypeStruct``s (no state is ever
  materialized, so 1M-peer cost analysis runs on a laptop) and report
  XLA's flops / bytes-accessed totals.
- :func:`phase_kernels` — the step's named phases (churn, walk, deliver,
  bloom, store-merge, timeline) as standalone jitted calls of the SAME
  ops functions at the step's exact shapes, each with its own cost
  analysis and optional wall timing.  Phases are honest proxies, and no
  bracketing vs the step total holds in EITHER direction: fusion in the
  full step shares reads (pushing phases high), while the table covers
  the dominant kernels rather than every phase (pushing the sum low —
  measured, the 64k phase sum is ~0.4x the step total).  They answer
  "where do the bytes go", not "what adds up"; tests/test_ledger.py
  pins the sanity band.  (An earlier revision of this docstring claimed
  phases "sum past the step" — the generated cost ledger disproved it.)
- :func:`bench_config` — the bench.py worker's config shape at a chosen
  population, so profile numbers and bench numbers describe one shape.
"""

from __future__ import annotations

import functools
import time

from dispersy_tpu.config import CommunityConfig


def bench_config(n_peers: int, platform: str = "tpu") -> CommunityConfig:
    """bench.py's worker config at ``n_peers`` — THE shared definition
    (bench.py imports this), so profile numbers and bench numbers always
    describe one shape per platform.

    ``platform="tpu"``: the 1M-peer roofline shape (M=48 store slots,
    bloom_capacity=48 -> 480 filter bits = 15 words).  ``"cpu"``: the
    64k fallback rung's shape (M=64, bloom_capacity=64).  Tracker counts
    scale with population, capped at each platform's recorded values.
    """
    from dispersy_tpu.storediet import StoreConfig

    # The byte-diet store plane (PR 12; storediet.py) is ON for the
    # bench shapes: staging=8 slots, compaction/sync one round in 12,
    # aux narrowed to u16, candidate stamps quantized to u16, and the
    # sync/compaction cadence staggered over 4 cohorts (PR 20) — the
    # layout the committed cost ledger prices (BENCH.md "Byte diet").
    # cohorts=4 is the largest value dividing both compact_every=12 and
    # the bench populations (1M = 2^6*5^6, 64k = 2^16); it flattens the
    # worst single round from ~4.1x to ~1.7x the quiet round at 1M.
    # Legacy-layout numbers are reproducible with
    # cfg.replace(store=StoreConfig()).
    diet = StoreConfig(staging=8, compact_every=12, aux_bits=16,
                       cohorts=4, cand_bits=16)
    if platform == "cpu":
        return CommunityConfig(
            n_peers=n_peers, n_trackers=max(2, min(4, n_peers // 1024)),
            k_candidates=16, msg_capacity=64, bloom_capacity=64,
            request_inbox=4,
            tracker_inbox=max(64, min(256, n_peers // 64)),
            response_budget=8, churn_rate=0.0, store=diet)
    return CommunityConfig(
        n_peers=n_peers, n_trackers=max(2, min(8, n_peers // 1024)),
        k_candidates=16, msg_capacity=48, bloom_capacity=48,
        request_inbox=4, tracker_inbox=max(64, min(1024, n_peers // 64)),
        response_budget=8, churn_rate=0.0, store=diet)


def _flatten_cost_analysis(ca) -> list:
    """Every per-device cost dict inside ``cost_analysis()``'s return,
    whatever nesting this JAX version uses (a dict, a list of dicts, or
    nested per-device lists)."""
    if isinstance(ca, dict):
        return [ca]
    if isinstance(ca, (list, tuple)):
        out = []
        for entry in ca:
            out.extend(_flatten_cost_analysis(entry))
        return out
    return []


def _extract_cost(compiled) -> dict:
    """flops / bytes-accessed out of ``compiled.cost_analysis()``.

    Costs are SUMMED across devices: on a multi-device compile the
    nested per-device lists each report one shard's share, and taking
    ``ca[0]`` (the old behavior) silently divided every number by the
    device count — a 1/8th-cost "measurement" on an 8-chip mesh.
    Single-device returns are a one-element sum, unchanged.
    """
    entries = _flatten_cost_analysis(compiled.cost_analysis())
    out: dict = {}
    for ca in entries:
        for key, name in (("flops", "flops"),
                          ("bytes accessed", "bytes_accessed"),
                          ("transcendentals", "transcendentals"),
                          ("optimal_seconds", "optimal_seconds")):
            if key in ca:
                out[name] = out.get(name, 0.0) + float(ca[key])
    return out


def state_shapes(cfg: CommunityConfig):
    """A ``jax.ShapeDtypeStruct`` pytree of ``PeerState`` at ``cfg``'s
    shapes — lets ``step`` lower/compile without materializing a byte."""
    import jax

    from dispersy_tpu.state import init_state

    key = jax.random.PRNGKey(0)
    return jax.eval_shape(functools.partial(init_state, cfg), key)


def step_cost(cfg: CommunityConfig, phase: str | None = None) -> dict:
    """Compile the fused round at ``cfg`` and return
    ``{"flops", "bytes_accessed", "compile_seconds"}``.

    Works at any population: only abstract shapes flow into the compiler.
    ``phase`` (byte-diet configs only — storediet.py): ``"quiet"`` /
    ``"sync"`` compile the statically-specialized round kind, so the
    ledger can price each separately and report the honest amortized
    mean — the dynamic (``None``) form carries BOTH kinds behind one
    ``lax.cond``, whose untaken branch XLA's cost analysis still sums.
    """
    import jax

    from dispersy_tpu import engine

    shapes = state_shapes(cfg)
    t0 = time.perf_counter()
    compiled = (jax.jit(engine.step.__wrapped__, static_argnums=(1, 3))
                .lower(shapes, cfg, None, phase).compile())
    out = _extract_cost(compiled)
    out["compile_seconds"] = round(time.perf_counter() - t0, 2)
    return out


def _amortize(measure, store) -> dict:
    """Cadence-weighted cost over one compaction window from a
    per-phase measuring callable: quiet and sync round kinds priced
    separately plus their window mean AND the worst single round — the
    one formula both the single-step and fleet ledgers record.

    Without cohorts the window is ``compact_every`` rounds holding ONE
    sync round: ``((C-1)*quiet + sync) / C``.  Under cohort staggering
    (``store.cohorts > 1``, storediet.py) one cohort syncs every
    ``C // cohorts`` rounds, so the window holds ``cohorts`` sync
    rounds: ``((C-cohorts)*quiet + cohorts*sync) / C`` — each sync
    round far cheaper than the fleet-synchronized one because the
    claim/serve/compact path touches only the active cohort's
    ``N/cohorts`` block.  ``bytes_worst`` is the number the staggering
    exists to flatten: the most expensive single round in the window,
    i.e. what the link/HBM must be provisioned for (vs the amortized
    mean it is billed at)."""
    c, k = store.compact_every, store.cohorts
    quiet = measure("quiet")
    sync = measure("sync")
    bq, bs = quiet["bytes_accessed"], sync["bytes_accessed"]
    fq, fs = quiet["flops"], sync["flops"]
    return {
        "compact_every": c,
        "cohorts": k,
        "bytes_quiet": bq,
        "bytes_sync": bs,
        "flops_quiet": fq,
        "flops_sync": fs,
        "bytes_worst": max(bq, bs),
        "flops_worst": max(fq, fs),
        "bytes_accessed": ((c - k) * bq + k * bs) / c,
        "flops": ((c - k) * fq + k * fs) / c,
        "compile_seconds": round(quiet["compile_seconds"]
                                 + sync["compile_seconds"], 2),
    }


def _plain_window(out: dict) -> dict:
    """Annotate a legacy (non-diet) per-round cost as its degenerate
    one-round window: every round is a sync round, so the worst round
    IS the mean — keeps the ledger's worst-vs-amortized gate uniform
    across diet and legacy cells."""
    out["compact_every"] = 1
    out["cohorts"] = 1
    out["bytes_worst"] = out["bytes_accessed"]
    out["flops_worst"] = out["flops"]
    return out


def step_cost_amortized(cfg: CommunityConfig) -> dict:
    """Byte-diet step cost over one compaction window: the quiet and
    sync (compaction) round kinds measured separately plus their
    cadence-weighted mean — THE per-round number the ledger records
    (``((C-1)*quiet + sync) / C``).  For legacy configs this is just
    :func:`step_cost` (every round is a sync round)."""
    if not cfg.store_diet:
        return _plain_window(step_cost(cfg))
    return _amortize(lambda ph: step_cost(cfg, ph), cfg.store)


def sharded_step_cost(cfg: CommunityConfig,
                      n_devices: int | tuple = 8,
                      phase: str | None = None) -> dict:
    """Compile the fused round peer-sharded over an ``n_devices`` mesh
    (an int for 1-D, a tuple like ``(2, 4)`` for 2-D; virtual CPU
    devices suffice) and return the flops/bytes dict with costs SUMMED
    across devices (see ``_extract_cost`` — taking one device's share
    used to under-report an 8-way mesh by 8x).  Abstract shapes only;
    the multichip datapoint for the cost ledger.

    The compile runs INSIDE the mesh context so the engine's
    partition-rule pins (parallel/mesh.py) are armed — the same HLO a
    real ``sharded_step`` loop executes, which is what lets
    tests/test_ledger.py gate this compile at ZERO involuntary-remat /
    resharding warnings on both mesh shapes.
    """
    import jax

    from dispersy_tpu import engine
    from dispersy_tpu.parallel.mesh import make_mesh, sharded_shape_structs

    mesh = make_mesh(n_devices)
    shapes = sharded_shape_structs(state_shapes(cfg), mesh, cfg.n_peers)
    t0 = time.perf_counter()
    with mesh:
        compiled = (jax.jit(engine.step.__wrapped__,
                            static_argnums=(1, 3))
                    .lower(shapes, cfg, None, phase).compile())
    out = _extract_cost(compiled)
    out["devices"] = (list(n_devices) if isinstance(n_devices, tuple)
                      else n_devices)
    out["compile_seconds"] = round(time.perf_counter() - t0, 2)
    return out


def sharded_step_cost_amortized(cfg: CommunityConfig,
                                n_devices: int | tuple = 8) -> dict:
    """:func:`step_cost_amortized` compiled peer-sharded: the quiet and
    sync round kinds each priced under the mesh (same zero-warning HLO
    the SPMD gate pins) and cadence-averaged — the mesh cell's number
    in the cost ledger."""
    if not cfg.store_diet:
        return _plain_window(sharded_step_cost(cfg, n_devices))
    out = _amortize(
        lambda ph: sharded_step_cost(cfg, n_devices, phase=ph),
        cfg.store)
    out["devices"] = (list(n_devices) if isinstance(n_devices, tuple)
                      else n_devices)
    return out


def fleet_step_cost_amortized(cfg: CommunityConfig,
                              replicas: int) -> dict:
    """:func:`step_cost_amortized` for the vmapped fleet round: quiet
    and sync round kinds priced separately (replicas advance in
    lockstep, so the cadence is fleet-global) and cadence-averaged.
    Legacy configs fall through to one :func:`fleet_step_cost`."""
    if not cfg.store_diet:
        return _plain_window(fleet_step_cost(cfg, replicas))
    return _amortize(lambda ph: fleet_step_cost(cfg, replicas, phase=ph),
                     cfg.store)


def fleet_step_cost(cfg: CommunityConfig, replicas: int,
                    phase: str | None = None) -> dict:
    """Compile the vmapped fleet round (``fleet.fleet_step``, no
    overrides) at ``replicas`` x ``cfg`` and return the same
    flops/bytes dict as :func:`step_cost` — the fleet-on cost-analysis
    datapoint BENCH.md records against ``replicas`` x the single-step
    baseline.  Abstract shapes only, so an 8 x 1M fleet costs out on a
    laptop."""
    import jax

    from dispersy_tpu import fleet

    shapes = state_shapes(cfg)
    fshapes = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct((replicas,) + tuple(s.shape),
                                       s.dtype), shapes)
    t0 = time.perf_counter()
    compiled = (jax.jit(fleet.fleet_step.__wrapped__,
                        static_argnums=(1, 3))
                .lower(fshapes, cfg, None, phase).compile())
    out = _extract_cost(compiled)
    out["compile_seconds"] = round(time.perf_counter() - t0, 2)
    return out


def _timed(fn, *args, reps: int = 3) -> float:
    """Median wall seconds per call of an already-compiled jitted fn."""
    import jax

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def phase_kernels(cfg: CommunityConfig, time_phases: bool = False) -> dict:
    """Cost-analyze (and optionally wall-time) the step's named phases.

    Each phase is the REAL ops kernel at the engine's call-site shapes
    (engine.py phase comments name the sites).  Returns
    ``{phase: {"bytes_accessed", "flops"[, "seconds"]}}``.

    ``time_phases=True`` additionally executes each kernel (inputs
    materialize), so only use it at populations the host holds.
    """
    import jax
    import jax.numpy as jnp

    from dispersy_tpu.ops import bloom as bl
    from dispersy_tpu.ops import candidates as cand
    from dispersy_tpu.ops import inbox as ib
    from dispersy_tpu.ops import rng as prng
    from dispersy_tpu.ops import store as st
    from dispersy_tpu.state import NEVER

    n, w, m = cfg.n_peers, cfg.bloom_words, cfg.msg_capacity
    # One key per synthetic input (graftlint R5): reusing a single key
    # across draws makes the "random" benchmark inputs correlated —
    # e.g. store gt and member columns tracking each other, which skews
    # any value-dependent path (sort duplicate groups, bloom collisions).
    key = jax.random.PRNGKey(7)
    k_dst, k_push, k_items, k_gt, k_member = jax.random.split(key, 5)
    out = {}

    def run(name, fn, *args):
        jitted = jax.jit(fn)
        compiled = jitted.lower(*args).compile()
        entry = _extract_cost(compiled)
        if time_phases:
            entry["seconds"] = round(_timed(jitted, *args), 4)
        out[name] = entry

    # --- phase 0: churn — the rebirth wipe's where-pass over the state
    # columns (engine.py phase 0; only the store half, the dominant bytes).
    def churn_wipe(reborn, gt, member, meta, payload, aux, flags):
        r1 = reborn[:, None]
        return st.StoreCols(
            gt=jnp.where(r1, jnp.uint32(0xFFFFFFFF), gt),
            member=jnp.where(r1, jnp.uint32(0xFFFFFFFF), member),
            meta=jnp.where(r1, jnp.uint8(0xFF), meta),
            payload=jnp.where(r1, jnp.uint32(0xFFFFFFFF), payload),
            aux=jnp.where(r1, jnp.zeros((), aux.dtype), aux),
            flags=jnp.where(r1, jnp.uint8(0), flags))

    # The ring carries the REAL aux width (cfg.aux_dtype) so the
    # store_merge/store_compact/churn cells reprice mechanically when
    # the byte diet narrows the column; batches stay u32 (wire width).
    stc = st.empty_records((n, m), aux_dtype=cfg.aux_dtype)
    reborn = jnp.zeros((n,), bool)
    run("churn", churn_wipe, reborn, *stc)

    # --- phase 1: walker sampling (dispersy_get_walk_candidate).
    tab = cand.CandTable(
        peer=jnp.zeros((n, cfg.k_candidates), jnp.int32),
        last_walk=jnp.full((n, cfg.k_candidates), NEVER, jnp.float32),
        last_stumble=jnp.full((n, cfg.k_candidates), NEVER, jnp.float32),
        last_intro=jnp.full((n, cfg.k_candidates), NEVER, jnp.float32))
    idx = jnp.arange(n, dtype=jnp.int32)
    boot_base = jnp.zeros((n,), jnp.int32)
    boot_count = jnp.full((n,), cfg.n_trackers, jnp.int32)
    def walk_sample(tab_, now, seed, rnd, idx_, bb, bc):
        return cand.sample_walk_target(tab_, now, cfg, seed, rnd, idx_,
                                       bb, bc)

    run("walk", walk_sample,
        tab, jnp.float32(0.0), jnp.uint32(1), jnp.uint32(3), idx,
        boot_base, boot_count)

    # --- deliver: the request fan-in (E = N edges, 6 u32 scalars + the
    # [E, W] bloom payload) and the push fan-out (E = N·F·C edges).
    dst = jax.random.randint(k_dst, (n,), -1, n, jnp.int32)
    scalars = [jnp.ones((n,), jnp.uint32) for _ in range(6)]
    bloom_col = jnp.ones((n, w), jnp.uint32)
    valid = jnp.ones((n,), bool)
    run("deliver_request",
        functools.partial(ib.deliver, n_peers=n,
                          inbox_size=cfg.request_inbox),
        dst, scalars + [bloom_col], valid)
    e = n * cfg.forward_buffer * cfg.forward_fanout
    if e:
        pdst = jax.random.randint(k_push, (e,), 0, n, jnp.int32)
        pcols = [jnp.ones((e,), jnp.uint32) for _ in range(4)] \
            + [jnp.ones((e,), jnp.uint8)]
        run("deliver_push",
            functools.partial(ib.deliver, n_peers=n,
                              inbox_size=cfg.push_inbox),
            pdst, pcols, jnp.ones((e,), bool))

    # --- bloom build (claim) + query (responder membership test).
    items = (jax.random.randint(k_items, (n, m), 0, 1 << 30, jnp.int32)
             .astype(jnp.uint32))
    imask = jnp.ones((n, m), bool)
    build = functools.partial(bl.bloom_build, n_bits=cfg.bloom_bits,
                              n_hashes=cfg.bloom_hashes)
    run("bloom_build", build, items, imask)
    bits = jax.jit(build)(items, imask) if time_phases else \
        jnp.zeros((n, w), jnp.uint32)
    run("bloom_query",
        functools.partial(bl.bloom_query, n_bits=cfg.bloom_bits,
                          n_hashes=cfg.bloom_hashes),
        bits, items)

    # --- store merge (phase 5 insert: [N, M] store + [N, B] batch).
    b = cfg.request_inbox * cfg.response_budget + cfg.push_inbox
    batch = st.StoreCols(
        gt=(jax.random.randint(k_gt, (n, b), 1, 1000, jnp.int32)
            .astype(jnp.uint32)),
        member=(jax.random.randint(k_member, (n, b), 0, n, jnp.int32)
                .astype(jnp.uint32)),
        meta=jnp.ones((n, b), jnp.uint8),
        payload=jnp.zeros((n, b), jnp.uint32),
        aux=jnp.zeros((n, b), jnp.uint32),
        flags=jnp.zeros((n, b), jnp.uint8))
    run("store_merge",
        functools.partial(st.store_insert, history=cfg.history),
        stc, batch, jnp.ones((n, b), bool))

    if cfg.store_diet:
        # --- byte-diet store plane (storediet.py): the quiet round's
        # staging append + digest OR-update, and the compaction round's
        # ring merge of the staged batch — the engine's store_stage /
        # digest_update / store_compact named scopes.
        s_w = cfg.store.staging
        qb = cfg.push_inbox                   # quiet-round arrival width
        k_sgt, k_smem, k_qgt, k_qmem = jax.random.split(
            jax.random.PRNGKey(11), 4)
        sta = st.StoreCols(
            gt=(jax.random.randint(k_sgt, (n, s_w), 1, 1000, jnp.int32)
                .astype(jnp.uint32)),
            member=(jax.random.randint(k_smem, (n, s_w), 0, n, jnp.int32)
                    .astype(jnp.uint32)),
            meta=jnp.ones((n, s_w), jnp.uint8),
            payload=jnp.zeros((n, s_w), jnp.uint32),
            aux=jnp.zeros((n, s_w), cfg.aux_dtype),
            flags=jnp.zeros((n, s_w), jnp.uint8))
        qbatch = st.StoreCols(
            gt=(jax.random.randint(k_qgt, (n, qb), 1, 1000, jnp.int32)
                .astype(jnp.uint32)),
            member=(jax.random.randint(k_qmem, (n, qb), 0, n, jnp.int32)
                    .astype(jnp.uint32)),
            meta=jnp.ones((n, qb), jnp.uint8),
            payload=jnp.zeros((n, qb), jnp.uint32),
            aux=jnp.zeros((n, qb), jnp.uint32),
            flags=jnp.zeros((n, qb), jnp.uint8))
        run("store_stage", st.store_stage,
            st.empty_records((n, s_w), aux_dtype=cfg.aux_dtype), qbatch,
            jnp.ones((n, qb), bool))
        run("store_compact",
            functools.partial(st.store_insert, history=cfg.history),
            stc, sta, jnp.ones((n, s_w), bool))
        if cfg.sync_enabled:
            from dispersy_tpu.ops import hashing as hsh

            def dig_update(dig, member, gt, meta, payload, mask):
                probes = bl.probe_bits(
                    hsh.record_hash(member, gt, meta, payload),
                    cfg.bloom_bits, cfg.bloom_hashes, salt=jnp.uint32(1))
                return bl.digest_update(dig, probes, mask,
                                        cfg.bloom_bits)
            run("digest_update", dig_update,
                jnp.zeros((n, w), jnp.uint32), qbatch.member, qbatch.gt,
                qbatch.meta, qbatch.payload, jnp.ones((n, qb), bool))

    # --- timeline: the retro re-walk's table rebuild (only compiled in
    # for permission communities; engine._retro_pass).
    if cfg.timeline_enabled:
        from dispersy_tpu import engine as eng
        founder_col = jnp.full((n,), cfg.founder, jnp.uint32)

        def rebuild(stc_, founder_):
            return eng._rebuild_valid_table(stc_, cfg, founder_,
                                            cfg.k_authorized)

        run("timeline", rebuild, stc, founder_col)
    return out


def profile_round(cfg: CommunityConfig, time_phases: bool = False,
                  rounds: int = 0, trace_dir: str | None = None) -> dict:
    """The full report: whole-step cost analysis + per-phase table, and
    optionally measured step wall time (``rounds > 0``) and a
    ``jax.profiler`` trace dump."""
    import jax

    result = {"n_peers": cfg.n_peers,
              "platform": jax.devices()[0].platform,
              "step": step_cost(cfg),
              "phases": phase_kernels(cfg, time_phases=time_phases)}
    if rounds > 0:
        import jax.numpy as jnp

        from dispersy_tpu import engine
        from dispersy_tpu.state import init_state

        state = init_state(cfg, jax.random.PRNGKey(0))
        state = engine.seed_overlay(state, cfg, degree=8)
        authors = jnp.arange(cfg.n_peers) % 64 == 63
        state = engine.create_messages(
            state, cfg, author_mask=authors, meta=1,
            payload=jnp.arange(cfg.n_peers, dtype=jnp.uint32))
        for _ in range(2):     # compile + warm stores
            state = engine.step(state, cfg)
        jax.block_until_ready(state)

        def timed_rounds():
            nonlocal state
            t0 = time.perf_counter()
            for _ in range(rounds):
                state = engine.step(state, cfg)
            jax.block_until_ready(state)
            return (time.perf_counter() - t0) / rounds

        if trace_dir:
            import os
            os.makedirs(trace_dir, exist_ok=True)
            with jax.profiler.trace(trace_dir,
                                    create_perfetto_trace=True):
                result["step"]["seconds"] = round(timed_rounds(), 4)
            result["trace_dir"] = trace_dir
        else:
            result["step"]["seconds"] = round(timed_rounds(), 4)
        result["step"]["rounds_per_sec"] = round(
            1.0 / result["step"]["seconds"], 3)
    return result
