"""Machine-checked cost ledger, roofline projection, and compile tracer.

ROADMAP items 1 (byte-diet store) and 2 (sharding-clean multichip) are
judged by numbers this repo used to produce BY HAND: the per-phase
roofline table in BENCH.md was prose arithmetic (and had already gone
stale — it still priced the store columns as six u32s after PR 1
narrowed meta/flags to u8), and the ``[SPMD] Involuntary full
rematerialization`` warnings that define item 2's acceptance lived as
raw text tails in ``MULTICHIP_r0*.json``.  This module makes all of it
mechanical:

- :func:`build_ledger` — run ``profiling.step_cost`` /
  ``profiling.phase_kernels`` over a committed (shape x plane) grid and
  emit ``artifacts/cost_ledger.json``: per-cell bytes/flops with derived
  bytes/peer/round, per-phase breakdowns, the analytical
  full-state-read+write floor computed from the REAL leaf dtypes (so
  u8-packing a column moves the generated number, not a doc edit), and
  a roofline rounds/s projection from the committed :data:`HARDWARE`
  model — replacing BENCH.md's hand-computed ~210-340 r/s bound.
- :func:`compare_ledgers` — the tier-1 gate: every cell carries its
  committed byte/flop budget and a regression OR an unrecorded
  improvement fails loudly (``tools/ledger.py gate``).  A perf PR lands
  by regenerating the ledger, never by editing prose.
- :class:`CompileTracer` — a context manager counting XLA backend
  compiles and jaxpr (re)traces via ``jax.monitoring`` events, so
  "one compile per sweep group" (FLEET.md) is an asserted counter.
- :func:`spmd_warning_counts` — a structured parser for
  involuntary-remat / resharding warnings in multichip dryrun logs,
  making ROADMAP item 2's "zero involuntary-remat warnings" a checkable
  numeric field (``tools/ledger.py spmd``; wired into
  ``tools/multihost.py`` and ``__graft_entry__``'s dryrun even when the
  run times out).

Everything here is host-side tooling: jax imports are lazy, so the
module is importable from jax-free parents (the axon-tunnel discipline,
see ``cpuenv.py``).
"""

from __future__ import annotations

import json
import math
import re

# ---------------------------------------------------------------------------
# The committed hardware model (roofline denominator).  The fused round
# is pure elementwise/compare/sort work on narrow integer columns — no
# MXU terms — so the ONLY roofline that binds is HBM bandwidth
# (BENCH.md "Roofline / device-utilization accounting").  Keep this
# table tiny and sourced: adding a chip is a one-line diff that
# regenerates every projection.
HARDWARE = {
    "v5e": {"hbm_gbps": 819.0, "chip_counts": (1, 8)},
}

# The ledger grid.  Shapes are the two populations every recorded
# artifact speaks in: the 1M-peer TPU roofline shape and the 64k CPU
# fallback rung (profiling.bench_config).  Planes are the compiled-in
# feature sets whose overhead BENCH.md tracks — defaults, telemetry,
# trace (the dissemination-tracing plane on top of telemetry — its
# row words and lineage folds ride the fused round), chaos+health,
# recovery, overload (the fault planes superset each other, mirroring
# how the overhead artifacts were measured), plus a 2-replica fleet of
# the default plane.
SHAPES = {
    "1M_tpu": (1_000_000, "tpu"),
    "64k_cpu": (65_536, "cpu"),
}
PLANES = ("default", "telemetry", "trace", "faults_health", "recovery",
          "overload", "fleet_r2")
# Sharded cells: a third cell component naming a mesh shape.  A mesh
# cell prices the SAME fused round compiled peer-sharded over that mesh
# (profiling.sharded_step_cost_amortized — the zero-SPMD-warning HLO the
# tier-1 gate pins), keyed "shape/plane/meshN".  Under the explicit
# partition rules the peer axis splits the state evenly, so the cell
# additionally records bytes_per_chip_round = bytes / chips — ROADMAP
# item 2's "per-chip bytes ~ bytes/8" as a gated number.
MESHES = {"mesh8": 8}
LEDGER_PATH = "artifacts/cost_ledger.json"
LEDGER_SCHEMA = 1


def plane_config(shape: str, plane: str):
    """(CommunityConfig, replicas) for one ledger cell.

    Planes are cumulative — ``recovery`` includes ``faults_health``,
    ``overload`` includes ``recovery`` — matching the layering the
    overhead artifacts (telemetry/recovery/overload ``*_overhead_1M``)
    measured, so each cell's delta over the previous plane is that
    plane's own cost.
    """
    from dispersy_tpu import profiling
    from dispersy_tpu.faults import FaultModel
    from dispersy_tpu.overload import OverloadConfig
    from dispersy_tpu.recovery import RecoveryConfig
    from dispersy_tpu.telemetry import TelemetryConfig

    n_peers, platform = SHAPES[shape]
    cfg = profiling.bench_config(n_peers, platform)
    if plane in ("default", "fleet_r2"):
        return cfg, (2 if plane == "fleet_r2" else 1)
    if plane == "telemetry":
        return cfg.replace(telemetry=TelemetryConfig(
            enabled=True, history=64, histograms=True)), 1
    if plane == "trace":
        # The dissemination-tracing plane prices ON TOP of the
        # telemetry plane (its coverage/latch/channel words ride the
        # fused row): the cell's delta over `telemetry` is the
        # lineage folds + row growth at the default 4 tracked slots.
        from dispersy_tpu.traceplane import TraceConfig
        return cfg.replace(
            telemetry=TelemetryConfig(enabled=True, history=64,
                                      histograms=True),
            trace=TraceConfig(enabled=True)), 1
    faults = FaultModel(
        ge_p_bad=0.05, ge_p_good=0.3, ge_loss_good=0.01, ge_loss_bad=0.5,
        dup_rate=0.02, corrupt_rate=0.02,
        flood_senders=(3, 5), flood_fanout=4,
        health_checks=True)
    cfg = cfg.replace(packet_loss=0.1, faults=faults)
    if plane == "faults_health":
        return cfg, 1
    cfg = cfg.replace(recovery=RecoveryConfig(enabled=True))
    if plane == "recovery":
        return cfg, 1
    if plane == "overload":
        return cfg.replace(overload=OverloadConfig(enabled=True)), 1
    raise ValueError(f"unknown ledger plane {plane!r}")


def _leaf_nbytes(struct) -> int:
    import numpy as np
    return int(math.prod(struct.shape)) * np.dtype(struct.dtype).itemsize


def state_byte_report(cfg) -> dict:
    """Analytical state-size accounting from the REAL leaf shapes/dtypes
    (``jax.eval_shape`` — nothing materializes).

    ``state_bytes`` is the whole resident ``PeerState``;
    ``store_bytes`` just the six store columns.  ``*_rw_per_peer`` are
    the read+write-once-per-round bytes/peer — the full-fusion floor
    BENCH.md's roofline table hand-computed (and mispriced after PR 1's
    u8 packing: the generated store number reflects the real dtypes).
    """
    import jax

    from dispersy_tpu import profiling

    shapes = profiling.state_shapes(cfg)
    leaves = {
        ".".join(str(getattr(p, "name", p)) for p in path): _leaf_nbytes(s)
        for path, s in jax.tree_util.tree_flatten_with_path(shapes)[0]}
    total = sum(leaves.values())
    store = sum(v for k, v in leaves.items() if k.startswith("store_"))
    n = cfg.n_peers
    return {
        "state_bytes": total,
        "store_bytes": store,
        "state_rw_per_peer_round": round(2 * total / n, 1),
        "store_rw_per_peer_round": round(2 * store / n, 1),
    }


def active_floor(cfg) -> dict:
    """Analytical per-round HBM floor from the REAL leaf shapes/dtypes
    — the ``fullfuse`` numerator since the byte diet (PR 12).

    The pre-diet model charged 2 x the whole resident state every round
    ("one read+write pass over everything").  Under the incremental
    store plane (storediet.py) that is provably NOT what a round must
    move, so each leaf family carries an access class, all derived
    mechanically from ``jax.eval_shape`` (a dtype narrowing or a
    plane-sizing change moves the generated number, never a doc edit):

    - ``store_*`` (the sorted ring): touched ONLY at compaction — one
      read+write pass amortized over ``compact_every`` rounds.  (The
      quiet round's freshness test reads the DIGEST, not ring keys.)
    - ``sta_*`` (the staging buffer): the append reads the occupancy
      column (gt) and writes at most one inbound batch of records.
    - ``digest``: read (the claim / freshness view) + written (the OR
      update) every round.
    - ``cand_*``: the walk reads every slot; an ideal fused round
      writes only the touched slots (<= request_inbox stumbles + the
      walk + intro stamps).
    - everything else (scalars, fwd, stats — already plane-sized to
      the compiled-in features): read + write every round.

    Without the diet every class degenerates to 2 x bytes — the legacy
    fullfuse model, unchanged.  Returns per-peer-round byte terms and
    the total.
    """
    import jax

    from dispersy_tpu import profiling

    shapes = profiling.state_shapes(cfg)
    leaves = {
        ".".join(str(getattr(p, "name", p)) for p in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(shapes)[0]}
    sizes = {k: _leaf_nbytes(s) for k, s in leaves.items()}
    n = cfg.n_peers
    total = sum(sizes.values())
    ring = sum(v for k, v in sizes.items() if k.startswith("store_"))
    sta = sum(v for k, v in sizes.items() if k.startswith("sta_"))
    dig = sizes.get("digest", 0)
    cand = sum(v for k, v in sizes.items() if k.startswith("cand_"))
    other = total - ring - sta - dig - cand
    if not cfg.store_diet:
        terms = {"ring": 2.0 * ring, "staging": 0.0, "digest": 0.0,
                 "cand": 2.0 * cand, "other": 2.0 * other}
    else:
        c = cfg.store.compact_every
        s_w = cfg.store.staging
        rec_bytes = sta / max(n * s_w, 1)
        sta_gt = _leaf_nbytes(leaves["sta_gt"])
        append = n * min(s_w, cfg.push_inbox) * rec_bytes
        k = cfg.k_candidates
        slot_bytes = cand / max(n * k, 1)
        touched = min(k, cfg.request_inbox + 2)
        terms = {
            "ring": 2.0 * ring / c,
            "staging": sta_gt + append,
            "digest": 2.0 * dig,
            "cand": cand + n * touched * slot_bytes,
            "other": 2.0 * other,
        }
    floor_total = sum(terms.values())
    return {
        "per_peer_round": {k: round(v / n, 1) for k, v in terms.items()},
        "floor_bytes_per_peer_round": round(floor_total / n, 1),
        "floor_bytes_per_round": floor_total,
    }


def roofline(cost_bytes: float, floor_bytes: float,
             replicas: int = 1) -> dict:
    """Rounds/s projection per :data:`HARDWARE` entry.

    Two bounds bracket reality (per replica-round):

    - ``fullfuse``: every kernel fuses into ONE pass over the round's
      ACTIVE state — HBM traffic = :func:`active_floor` bytes (for
      legacy configs that is exactly the old 2 x state model).  The
      optimistic bound.
    - ``nofuse``: XLA's cost-analysis bytes taken at face value (every
      op pays its operands and results to HBM); for byte-diet configs
      the cadence-amortized mean.  The pessimistic bound; real fusion
      lands in between.

    Chip scaling assumes the peer axis splits bytes evenly (the
    sharding story, MULTICHIP/ROADMAP item 2).
    """
    out = {}
    per_replica_cost = cost_bytes / max(replicas, 1)
    rw = floor_bytes / max(replicas, 1)
    for hw, spec in HARDWARE.items():
        bw = spec["hbm_gbps"] * 1e9
        for chips in spec["chip_counts"]:
            out[f"{hw}_x{chips}"] = {
                "rounds_per_sec_fullfuse": round(bw * chips / rw, 1),
                "rounds_per_sec_nofuse": round(
                    bw * chips / per_replica_cost, 1),
            }
    return out


def cell_cost(shape: str, plane: str, mesh: str | None = None) -> dict:
    """One ledger cell: cost-analyze the REAL fused step (or vmapped
    fleet step, or the peer-sharded step when ``mesh`` names a
    :data:`MESHES` entry) at the cell's config; abstract shapes only,
    so the 1M cells run on any host (mesh cells need the virtual-device
    count, tools/ledger.py's cpu_env(8))."""
    from dispersy_tpu import profiling

    cfg, replicas = plane_config(shape, plane)
    if mesh is not None:
        if replicas > 1:
            raise ValueError("mesh cells price the single-community "
                             "sharded step; fleet planes have no mesh "
                             "variant")
        cost = profiling.sharded_step_cost_amortized(cfg, MESHES[mesh])
    else:
        cost = (profiling.fleet_step_cost_amortized(cfg, replicas)
                if replicas > 1 else profiling.step_cost_amortized(cfg))
    sb = state_byte_report(cfg)
    fl = active_floor(cfg)
    n = cfg.n_peers
    chips = 1
    if mesh is not None:
        d = MESHES[mesh]
        chips = int(math.prod(d)) if isinstance(d, tuple) else int(d)
    cell = {
        "shape": shape,
        "plane": plane,
        "n_peers": n,
        "replicas": replicas,
        **({"mesh": mesh, "chips": chips,
            "bytes_per_chip_round": round(
                cost["bytes_accessed"] / chips, 1)}
           if mesh is not None else {}),
        # Cadence-amortized mean over one compaction window for
        # byte-diet configs (profiling.step_cost_amortized); the plain
        # per-round cost otherwise.  The quiet/sync split AND the worst
        # single round are recorded so the tier-1 amortization test can
        # hold EACH round kind — and the provisioning spike the cohort
        # staggering flattens — to its budget (tests/test_storediet.py).
        "bytes_accessed": cost["bytes_accessed"],
        "flops": cost["flops"],
        "compact_every": cost.get("compact_every", 1),
        "cohorts": cost.get("cohorts", 1),
        **({k: cost[k] for k in ("bytes_quiet", "bytes_sync",
                                 "flops_quiet", "flops_sync",
                                 "bytes_worst", "flops_worst")
            if k in cost}),
        "bytes_per_peer_round": round(
            cost["bytes_accessed"] / (n * replicas), 1),
        **({"bytes_worst_per_peer_round": round(
                cost["bytes_worst"] / (n * replicas), 1)}
           if "bytes_worst" in cost else {}),
        "state": sb,
        "floor": fl,
        "roofline": roofline(cost["bytes_accessed"],
                             fl["floor_bytes_per_round"] * replicas,
                             replicas),
        # THE gate contract: tools/ledger.py gate holds a fresh
        # measurement to these numbers, both directions.
        "budget": {"bytes_accessed": cost["bytes_accessed"],
                   "flops": cost["flops"],
                   **({"bytes_quiet": cost["bytes_quiet"],
                       "bytes_sync": cost["bytes_sync"],
                       "flops_quiet": cost["flops_quiet"],
                       "flops_sync": cost["flops_sync"],
                       "bytes_worst": cost["bytes_worst"],
                       "flops_worst": cost["flops_worst"]}
                      if "bytes_quiet" in cost else {})},
    }
    return cell


def shape_phases(shape: str) -> dict:
    """Per-phase breakdown for one shape (plane-independent: the phase
    kernels are the raw ops at the shape's sizes), with derived
    bytes/peer/round — the generated replacement for BENCH.md's
    hand-maintained per-kernel table."""
    from dispersy_tpu import profiling

    cfg, _ = plane_config(shape, "default")
    phases = profiling.phase_kernels(cfg)
    n = cfg.n_peers
    out = {}
    for name, entry in phases.items():
        out[name] = {
            "bytes_accessed": entry.get("bytes_accessed", 0.0),
            "flops": entry.get("flops", 0.0),
            "bytes_per_peer_round": round(
                entry.get("bytes_accessed", 0.0) / n, 1),
        }
    return out


def cell_key(shape: str, plane: str, mesh: str | None = None) -> str:
    return (f"{shape}/{plane}/{mesh}" if mesh else f"{shape}/{plane}")


def default_cells() -> list:
    cells = [(s, p) for s in SHAPES for p in PLANES]
    cells.append(("1M_tpu", "default", "mesh8"))
    return cells


def build_ledger(cells=None, with_phases: bool = True,
                 progress=None) -> dict:
    """The full ledger document.  ``cells`` defaults to the committed
    grid; pass a subset (e.g. the cheap 64k cells) for the tier-1 gate
    rebuild.  ``progress`` is an optional ``print``-like callback."""
    import jax

    cells = list(cells) if cells is not None else default_cells()
    doc = {
        "schema": LEDGER_SCHEMA,
        "jax_version": jax.__version__,
        "hardware_model": HARDWARE,
        "note": ("XLA cost-analysis bytes/flops of the compiled fused "
                 "round per (shape, plane) cell; 'nofuse'/'fullfuse' "
                 "roofline bounds bracket achievable rounds/s.  "
                 "Regenerate: python tools/ledger.py build"),
        "shapes": {},
        "cells": {},
    }
    for shape in sorted({c[0] for c in cells}):
        if with_phases:
            if progress:
                progress(f"[ledger] phases @ {shape}")
            doc["shapes"][shape] = {
                "n_peers": SHAPES[shape][0],
                "platform_shape": SHAPES[shape][1],
                "phases": shape_phases(shape),
            }
    for cell in cells:
        shape, plane = cell[0], cell[1]
        mesh = cell[2] if len(cell) > 2 else None
        if progress:
            progress(f"[ledger] cell {cell_key(shape, plane, mesh)}")
        doc["cells"][cell_key(shape, plane, mesh)] = cell_cost(
            shape, plane, mesh)
    return doc


def compare_ledgers(measured: dict, committed: dict,
                    rtol: float = 0.0) -> list:
    """Gate a measured ledger (possibly a cell subset) against the
    committed one.  Returns a list of failure strings — empty means the
    gate passes.

    Semantics: each measured cell must match the committed cell's
    BUDGET within ``rtol``, in BOTH directions — a regression fails,
    and so does an unrecorded improvement (the byte-diet PR lands by
    committing its >=3x reduction into the ledger, not by sailing
    under it).  Cost analysis is deterministic per jaxlib, so the
    default tolerance is exact.
    """
    failures = []
    for key, cell in measured.get("cells", {}).items():
        ref = committed.get("cells", {}).get(key)
        if ref is None:
            failures.append(f"{key}: not in committed ledger "
                            "(new cell? regenerate the ledger)")
            continue
        budget = ref.get("budget", ref)
        for metric in ("bytes_accessed", "flops", "bytes_quiet",
                       "bytes_sync", "flops_quiet", "flops_sync",
                       "bytes_worst", "flops_worst"):
            if metric not in budget:
                continue
            if metric not in cell:
                failures.append(f"{key}: {metric} missing from the "
                                "fresh measurement")
                continue
            want, got = float(budget[metric]), float(cell[metric])
            tol = rtol * abs(want)
            if abs(got - want) > tol:
                direction = ("REGRESSED" if got > want
                             else "improved (unrecorded)")
                failures.append(
                    f"{key}: {metric} {direction}: measured {got:.0f} "
                    f"vs budget {want:.0f} "
                    f"({(got - want) / want * 100.0:+.2f}%)")
    for shape, entry in measured.get("shapes", {}).items():
        ref = committed.get("shapes", {}).get(shape)
        if ref is None:
            failures.append(f"shape {shape}: not in committed ledger")
            continue
        for phase, pe in entry.get("phases", {}).items():
            rp = ref.get("phases", {}).get(phase)
            if rp is None:
                failures.append(f"{shape} phase {phase}: not in "
                                "committed ledger")
                continue
            for metric in ("bytes_accessed", "flops"):
                want, got = float(rp[metric]), float(pe[metric])
                if abs(got - want) > rtol * abs(want):
                    failures.append(
                        f"{shape} phase {phase}: {metric} drifted: "
                        f"measured {got:.0f} vs committed {want:.0f}")
    return failures


def load_ledger(path: str = LEDGER_PATH) -> dict:
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Compile tracer: XLA compiles / jaxpr retraces as asserted counters.


class CompileTracer:
    """Counts XLA backend compiles and jaxpr (re)traces inside a scope.

    Uses ``jax.monitoring``'s duration events — process-global, so the
    counts cover EVERYTHING compiled while the scope is open (including
    incidental helper jits); scope tightly around the dispatch under
    test.  The fleet sweep compiler's one-compile-per-group promise is
    asserted with this (tools/fleet.py records ``xla_compiles`` per
    group; tests/test_fleet.py pins it in tier-1), and scenario/sweep
    harnesses can wrap whole runs to catch retrace storms (graftlint R2
    finds static hazards; this counts the dynamic reality).

    Zero cost when not in use: nothing registers at import, and the
    listener is removed on exit — the disabled 1M step stays pinned
    byte-identical to ``artifacts/step_cost_1M_baseline.json``.
    """

    _COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
    _TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

    def __init__(self):
        self.compiles = 0
        self.traces = 0
        self.compile_seconds = 0.0
        self._cb = None
        self._active = False

    def __enter__(self):
        from jax._src import monitoring

        def _on_duration(name, secs, **kw):
            if not self._active:
                return        # scope closed: never count, even if the
            #                   unregister below was unavailable
            if name == self._COMPILE_EVENT:
                self.compiles += 1
                self.compile_seconds += float(secs)
            elif name == self._TRACE_EVENT:
                self.traces += 1

        self._cb = _on_duration
        self._active = True
        monitoring.register_event_duration_secs_listener(_on_duration)
        return self

    def __exit__(self, *exc):
        from jax._src import monitoring

        # Deactivate FIRST: counting stops at scope exit even when the
        # jax._src private unregister helper is missing (it has no
        # public counterpart; a jax upgrade may move it) — a leaked but
        # inert listener is a tiny callback cost, never a double count.
        self._active = False
        unregister = getattr(
            monitoring,
            "_unregister_event_duration_listener_by_callback", None)
        if unregister is not None:
            unregister(self._cb)
        self._cb = None
        return False

    def counts(self) -> dict:
        return {"xla_compiles": self.compiles,
                "jaxpr_traces": self.traces,
                "compile_seconds": round(self.compile_seconds, 2)}


# ---------------------------------------------------------------------------
# Multichip-log SPMD warning parser: item 2's acceptance as numbers.

# Two wordings in the wild for the SAME spmd_partitioner warning: the
# axon-TPU builds in MULTICHIP_r0*.json say "[SPMD] ... The compiler
# cannot go from sharding {A} to {B} efficiently for HLO operation
# %op.N"; this image's XLA:CPU says "[spmd] ... was not able to go from
# sharding {A} to {B} without doing a full rematerialization ... for
# HLO operation: %op.N".  Match both.
_REMAT_RE = re.compile(r"\[spmd\] involuntary full rematerialization",
                       re.IGNORECASE)
_TRANSITION_RE = re.compile(
    r"go from sharding \{(devices=[^}]*)\}(?:[^{}]*)to "
    r"(?:sharding )?\{(devices=[^}]*)\}")
_OP_RE = re.compile(r"for HLO operation:? %([a-zA-Z_\-]+)[.\d]*")


def spmd_warning_counts(text: str) -> dict:
    """Structured counts of SPMD partitioner warnings in a log text.

    ``involuntary_remat`` is ROADMAP item 2's acceptance number ("zero
    involuntary-remat warnings in the dryrun"); ``resharding`` counts
    every forced sharding transition the partitioner complained about,
    keyed by (from -> to) pair in ``transitions`` and by HLO op family
    in ``ops`` — the bisect map for making the peer-axis sharding
    explicit end-to-end.
    """
    remat = len(_REMAT_RE.findall(text))
    transitions: dict[str, int] = {}
    for src, dst in _TRANSITION_RE.findall(text):
        key = f"{src} -> {dst}"
        transitions[key] = transitions.get(key, 0) + 1
    ops: dict[str, int] = {}
    for op in _OP_RE.findall(text):
        ops[op] = ops.get(op, 0) + 1
    return {
        "involuntary_remat": remat,
        "resharding": sum(transitions.values()),
        "transitions": transitions,
        "ops": ops,
    }


def annotate_multichip_record(path: str, write: bool = False) -> dict:
    """Parse one MULTICHIP_*.json record's ``tail`` (or a raw log file)
    into :func:`spmd_warning_counts`; ``write=True`` folds the counts
    back into the JSON as a ``spmd_warnings`` field so "zero
    involuntary-remat warnings" is a greppable, diffable number even
    for runs that timed out (rc 124) with only a partial tail."""
    with open(path) as f:
        text = f.read()
    doc = None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        pass
    source = doc.get("tail", "") if isinstance(doc, dict) else text
    counts = spmd_warning_counts(source or "")
    if isinstance(doc, dict):
        counts["tail_truncated"] = len(source or "") >= 2000
    if write and isinstance(doc, dict):
        doc["spmd_warnings"] = counts
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    return counts
