"""The fleet plane: vmapped multi-replica simulation (FLEET.md).

Every multi-run workload the repo grew — fuzz draws, convergence
curves with confidence intervals, FaultModel grids — executed one
simulation per host-loop iteration, leaving the chip idle between
small runs and paying one full compile per grid point.  This module
recasts N-seeds-per-config as data-parallel ensemble execution:

- **Replica axis**: R independent ``PeerState`` pytrees stack along a
  NEW leading axis (``state.stack_states``) and advance together under
  one jitted ``vmap(engine.step)`` — bit-identical, leaf for leaf, to
  R sequential single runs (pinned in tests/test_fleet.py).  Replicas
  never interact; the per-replica RNG seed already lives in the state
  (``PeerState.key``), so distinct seeds ride the stack for free.
- **Traced per-replica knobs**: :class:`FleetOverrides` lifts the
  numeric fault rates (``packet_loss``, ``dup_rate``, ``corrupt_rate``,
  the GE ``ge_*`` probabilities — ``faults.TRACED_FAULT_KNOBS``) into
  per-replica f32 scalars read inside ``engine.step`` via
  ``engine.effective_faults``.  A whole fault grid with a shared
  structural signature (``faults.enablement_signature``) runs in ONE
  compile; which fields are overridden is pytree structure, so the
  fleet-off path stays compiled out entirely.
- **Cross-replica statistics**: the per-replica packed telemetry rows
  reduce on device into one [3, RW] min/max/sum band
  (``ops.fleet.band_reduce``); :func:`band` / the ring form keep an
  R-replica convergence band at ONE host transfer per drain.
- **Checkpointing**: ``checkpoint.save_fleet`` / :func:`load` persist a
  whole fleet (format v11) with its overrides; :func:`replica` /
  ``checkpoint.restore_replica`` split any single replica back out for
  post-mortem with every existing single-run tool.

The sweep compiler over all of this lives in ``tools/fleet.py``: it
partitions a sweep-spec JSON into compile groups (static knobs x
structural signature) x traced grids (seeds + rates) and executes each
group as one fleet.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from dispersy_tpu import checkpoint as _ckpt
from dispersy_tpu import engine
from dispersy_tpu import telemetry as tlm
from dispersy_tpu.config import CommunityConfig
from dispersy_tpu.exceptions import ConfigError
from dispersy_tpu.faults import TRACED_FAULT_KNOBS
from dispersy_tpu.ops import fleet as ops_fleet
from dispersy_tpu.overload import TRACED_OVERLOAD_KNOBS
from dispersy_tpu.recovery import TRACED_RECOVERY_KNOBS
from dispersy_tpu.state import (PeerState, index_state, init_state,
                                stack_states)


class FleetOverrides(NamedTuple):
    """Traced per-replica fault-knob columns (``f32[R]`` each, or
    ``None`` = keep the static config value on every replica).

    Which fields are set is part of the jit cache key (pytree
    structure); the VALUES are traced, so re-running a fleet with new
    rates never recompiles.  Structural requirements (GE overrides need
    ``cfg.faults.ge_enabled``; a corrupt override needs the corrupt
    counter compiled in) are enforced by :func:`make_overrides` and
    again at trace time by ``engine.effective_faults``.
    """

    packet_loss: Any = None
    dup_rate: Any = None
    corrupt_rate: Any = None
    ge_p_bad: Any = None
    ge_p_good: Any = None
    ge_loss_good: Any = None
    ge_loss_bad: Any = None
    # recovery plane (recovery.TRACED_RECOVERY_KNOBS; RECOVERY.md)
    backoff_decay: Any = None
    # ingress-protection plane (overload.TRACED_OVERLOAD_KNOBS;
    # OVERLOAD.md) — NOT a probability: credits/round in
    # [0, bucket_depth]
    bucket_rate: Any = None


TRACED_KNOBS = (TRACED_FAULT_KNOBS + TRACED_RECOVERY_KNOBS
                + TRACED_OVERLOAD_KNOBS)
assert FleetOverrides._fields == TRACED_KNOBS, \
    "FleetOverrides must mirror faults.TRACED_FAULT_KNOBS + " \
    "recovery.TRACED_RECOVERY_KNOBS + overload.TRACED_OVERLOAD_KNOBS " \
    "exactly"


def make_overrides(cfg: CommunityConfig, **knobs) -> FleetOverrides:
    """Validated :class:`FleetOverrides` from per-knob value sequences.

    Every supplied knob must be a length-R sequence of probabilities in
    [0, 1]; all knobs must agree on R.  Raises ``ConfigError`` on an
    unknown knob name, a ragged grid, an out-of-range value, or a
    structural mismatch with ``cfg`` (FLEET.md's traced-vs-static
    table).
    """
    unknown = set(knobs) - set(TRACED_KNOBS)
    if unknown:
        raise ConfigError(
            f"not traced-liftable: {sorted(unknown)} (liftable knobs: "
            f"{TRACED_KNOBS}; everything else is structural — "
            "sweep it as a static axis / compile group instead)")
    lens = {name: len(v) for name, v in knobs.items()}
    if len(set(lens.values())) > 1:
        raise ConfigError(f"override grids must share one replica "
                          f"count, got {lens}")
    fm = cfg.faults
    if any(name.startswith("ge_") for name in knobs) and not fm.ge_enabled:
        raise ConfigError(
            "traced GE overrides need cfg.faults.ge_enabled (set "
            "representative non-zero ge_* rates in the fleet config so "
            "the ge_bad leaf exists)")
    if "corrupt_rate" in knobs and not (fm.corrupt_rate > 0.0
                                        or fm.flood_enabled):
        raise ConfigError(
            "a traced corrupt_rate needs cfg.faults.corrupt_rate > 0 "
            "(representative value) so stats.msgs_corrupt_dropped is "
            "full-width")
    if "backoff_decay" in knobs and not cfg.recovery.enabled:
        raise ConfigError(
            "a traced backoff_decay needs cfg.recovery.enabled — the "
            "recovery leaves are zero-width otherwise (FLEET.md)")
    if "bucket_rate" in knobs and not cfg.overload.enabled:
        raise ConfigError(
            "a traced bucket_rate needs cfg.overload.enabled — the "
            "bucket leaf is zero-width otherwise (FLEET.md)")
    cols = {}
    for name, vals in knobs.items():
        arr = np.asarray(vals, np.float32)
        if arr.ndim != 1:
            raise ConfigError(f"{name}: override grid must be 1-D "
                              f"(one value per replica), got shape "
                              f"{arr.shape}")
        # bucket_rate is credits/round (capped at the static burst
        # depth); every other liftable knob is a probability.
        hi = (cfg.overload.bucket_depth
              if name == "bucket_rate" else 1)
        if not ((arr >= 0.0) & (arr <= float(hi))).all():
            raise ConfigError(f"{name}: override values must be in "
                              f"[0, {hi}], got {vals}")
        cols[name] = jnp.asarray(arr)
    return FleetOverrides(**cols)


def n_replicas(fstate: PeerState) -> int:
    """Replica count of a fleet-stacked state (leading axis of the
    per-replica round counter)."""
    return int(fstate.round_index.shape[0])


def init_fleet(cfg: CommunityConfig, seeds) -> PeerState:
    """A fresh R-replica fleet: one :func:`~dispersy_tpu.state.init_state`
    per RNG seed, stacked along the replica axis.  Every replica shares
    the static ``cfg`` (one compiled program); only the key leaf — and
    anything later seeded from it — differs."""
    seeds = list(seeds)
    if not seeds:
        raise ConfigError("init_fleet needs at least one seed")
    return stack_states([init_state(cfg, jax.random.PRNGKey(s))
                         for s in seeds])


def replica(fstate: PeerState, i: int) -> PeerState:
    """Split replica ``i`` out of the fleet (``state.index_state``): an
    ordinary single-run ``PeerState`` for post-mortem tooling."""
    return index_state(fstate, i)


@functools.partial(jax.jit, static_argnums=(1, 3), donate_argnums=0)
def fleet_step(fstate: PeerState, cfg: CommunityConfig,
               overrides: FleetOverrides | None = None,
               phase: str | None = None) -> PeerState:
    """Advance every replica one round under ONE compiled program.

    ``vmap`` over the replica axis of the REAL ``engine.step`` — no
    fleet-specific physics exists anywhere; bit-identity to single runs
    is structural, not re-implemented.  ``overrides`` columns map one
    scalar to each replica.

    ``phase`` (byte-diet configs, storediet.py): replicas advance in
    round lockstep, so the cadence is fleet-global — pass the static
    round kind to skip the dynamic cond, which under ``vmap`` lowers to
    a both-branches ``select`` (correct but paying both round kinds).
    """
    if overrides is None:
        return jax.vmap(
            lambda s: engine.step.__wrapped__(s, cfg, None, phase))(fstate)
    return jax.vmap(
        lambda s, o: engine.step.__wrapped__(s, cfg, o, phase))(
            fstate, overrides)


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=0)
def fleet_multi_step(fstate: PeerState, cfg: CommunityConfig, k: int,
                     overrides: FleetOverrides | None = None) -> PeerState:
    """``k`` fleet rounds in one dispatch (``engine.multi_step``'s
    batching economics, replicated: surface to the host only when you
    want to look)."""
    from jax import lax

    body = fleet_step.__wrapped__
    return lax.fori_loop(0, k, lambda i, s: body(s, cfg, overrides),
                         fstate)


def compile_count() -> int:
    """How many distinct fleet-step programs this process has compiled —
    the sweep compiler's one-compile-per-group assertion reads deltas
    of this (tools/fleet.py; pinned in tests/test_fleet.py)."""
    return int(fleet_step._cache_size())


# ---- cross-replica on-device statistics --------------------------------

def rows(fstate: PeerState) -> jnp.ndarray:
    """The fleet's per-replica packed telemetry rows, ``u32[R, RW]`` —
    one host transfer for every replica's full snapshot row."""
    return fstate.tele_row


def band(fstate: PeerState, cfg: CommunityConfig) -> jnp.ndarray:
    """``u32[3, RW]`` on-device min/max/sum band of the replicas' last
    rows (``ops.fleet.band_reduce``); decode with
    ``telemetry.band_to_dict``.  Requires ``cfg.telemetry.enabled``."""
    if not cfg.telemetry.enabled:
        raise ConfigError("fleet band statistics ride the packed "
                          "telemetry row — set telemetry.enabled")
    return ops_fleet.band_reduce(fstate.tele_row, tlm.word_kinds(cfg))


def band_snapshot(fstate: PeerState, cfg: CommunityConfig) -> dict:
    """Host dict ``{field: {"min", "max", "sum", "mean"}}`` across the
    fleet — the cross-replica ``metrics.snapshot`` analogue, still ONE
    device->host transfer."""
    return tlm.band_to_dict(np.asarray(band(fstate, cfg)), cfg,
                            n_replicas(fstate))


def history_band(fstate: PeerState, cfg: CommunityConfig) -> jnp.ndarray:
    """``u32[H, 3, RW]`` per-round bands over the device round-history
    ring (``ops.fleet.ring_band``) — a multi-round convergence band in
    one transfer.  Requires ``cfg.telemetry.history > 0``."""
    if cfg.telemetry.history <= 0:
        raise ConfigError("history_band needs telemetry.history > 0 "
                          "(the device ring is compiled out)")
    return ops_fleet.ring_band(fstate.tele_ring, tlm.word_kinds(cfg))


# ---- checkpointing (format v11; dispersy_tpu/checkpoint.py) ------------

def save(path: str, fstate: PeerState, cfg: CommunityConfig,
         overrides: FleetOverrides | None = None) -> None:
    """Persist a whole fleet + its traced overrides
    (``checkpoint.save_fleet``)."""
    ov = None if overrides is None else {
        k: v for k, v in overrides._asdict().items() if v is not None}
    _ckpt.save_fleet(path, fstate, cfg, overrides=ov)


def load(path: str, cfg: CommunityConfig):
    """Restore ``(fstate, FleetOverrides | None)`` from a v11 fleet
    archive — or from any accepted single-run archive (v7-v11), which
    loads as a 1-replica fleet with no overrides."""
    fstate, ov = _ckpt.restore_fleet(path, cfg)
    if ov is not None:
        ov = FleetOverrides(**{k: jnp.asarray(v, jnp.float32)
                               for k, v in ov.items()})
    return fstate, ov
