"""Parallel plane: static multichip knobs for the sharding-clean step.

ROADMAP item 1 (multi-chip scale-out): the PR-11/PR-12 ledger projects
6,264 r/s on 8 chips, but three compiler-level obstacles stood between
the projection and a measured number:

1. **Involuntary resharding** — ``jit(step)`` over a peer-sharded mesh
   let XLA invent [8,1] <-> [2,4] layout transitions and full
   rematerializations around the tracker fast path.  Fixed by the
   partition-rule registry + ``with_sharding_constraint`` pins in
   :mod:`dispersy_tpu.parallel.mesh` (no knob here: the pins are
   free-standing and engage whenever an ambient mesh is present).
2. **Cross-shard delivery** — the delivery kernel's single global
   ``lax.sort`` by destination makes XLA materialize every edge on
   every chip before the exchange.  ``shards > 1`` switches every
   full-population delivery to the *ragged exchange*
   (:func:`dispersy_tpu.ops.inbox.deliver_ragged`): shard-local sort,
   per-(shard, destination-shard) send buckets, ONE explicit
   all-to-all (a [S, S, B] transpose), then a shard-local landing
   scatter.  ``cross_shard_budget`` caps the bucket depth; overflow is
   shed at the SENDER and counted (``stats.xshard_shed``) — the same
   bounded-inbox backpressure contract as ``store_stage`` overflow,
   and the oracle mirrors the shed set bit-exactly.
3. **The 2^31 scatter-index cap** — XLA refuses scatters with more
   than 2^31-1 scatter indices, which is what the R-replica fleet hits
   building R x N x M x K bloom probe bits in one scatter (FLEET.md
   "scale ceiling": R=7 at 1M peers was the wall).  ``scatter_chunks``
   splits that one scatter into ``chunks`` row-chunk scatters so each
   stays under the cap; an 8 x 1M fleet lowers with
   ``scatter_chunks=8``.

The plane composes like store/overload/telemetry: all defaults
(``shards=0``) compile to exactly the legacy single-device HLO, the
oracle mirrors the armed paths bit-for-bit, checkpoint v16 carries the
fingerprint, and the sharded==unsharded identity is pinned in
tests/test_parallel.py.  See PARALLEL.md for the wire format and the
scale-ceiling math.
"""

from __future__ import annotations

import dataclasses

from dispersy_tpu.exceptions import ConfigError


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Static multichip knobs, composed into ``CommunityConfig.parallel``.

    Frozen + hashable (a static jit argument, like ``StoreConfig``).
    All defaults compile to exactly the legacy step; ``shards`` and
    ``cross_shard_budget`` change *which* HLO is emitted, never which
    bits come out — the ragged exchange is pinned bit-identical to the
    global sort whenever nothing sheds, and deterministic (lowest
    (class, edge) first per bucket) when something does.
    """

    # Number of peer-axis shards the delivery kernels assume.  0 or 1 =
    # plane off: every delivery is the legacy global sort.  > 1 requires
    # n_peers % shards == 0 and switches full-population deliveries to
    # the ragged cross-shard exchange.  Purely static — the same value
    # must be used for the mesh (``make_mesh(shards)``) for the exchange
    # transpose to lower to the one all-to-all.
    shards: int = 0
    # Per-(source-shard, destination-shard) send-bucket depth for the
    # PUSH exchange, in edges per round.  0 = exact (worst-case bucket =
    # ceil(E / shards), never sheds — bit-identical to the global sort).
    # > 0 = bounded: within each bucket the lowest (admission class,
    # global edge index) entries win, overflow is shed at the sender and
    # counted in ``stats.xshard_shed`` (bounded-inbox backpressure; the
    # bloom pull repairs the loss, exactly like staging overflow).
    cross_shard_budget: int = 0
    # Row-chunk count for the bloom probe-bit build scatter
    # (ops/bloom.bloom_build_from).  XLA caps one scatter at 2^31-1
    # scatter indices; the R-replica fleet's vmapped build scatters
    # R x N x M x K indices and hits the cap at R=7 for the 1M-peer
    # bench shape.  chunks=c splits the build into c scatters over row
    # chunks (identical bits; c-1 extra scatter ops).  1 = legacy single
    # scatter.
    scatter_chunks: int = 1

    def __post_init__(self) -> None:
        if self.shards < 0:
            raise ConfigError("parallel.shards must be >= 0")
        if self.cross_shard_budget < 0:
            raise ConfigError("parallel.cross_shard_budget must be >= 0")
        if self.cross_shard_budget > 0 and self.shards <= 1:
            raise ConfigError(
                "parallel.cross_shard_budget caps the cross-shard "
                "exchange — set parallel.shards > 1 too")
        if self.scatter_chunks < 1:
            raise ConfigError("parallel.scatter_chunks must be >= 1")
