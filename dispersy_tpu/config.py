"""Static simulation configuration.

The reference has no config system: tunables are class attributes on
``Community`` overridden by subclasses (reference: ``community.py`` —
``dispersy_sync_bloom_filter_error_rate``, ``dispersy_sync_response_limit``,
``dispersy_walker_interval``-style properties; see SURVEY.md §5.6).  Here the
same knobs live in one frozen, hashable dataclass so they can be passed as a
static argument to ``jit`` and vmapped over a community axis.

All *times* are simulated seconds; one simulation round == one walker
interval (reference: ~5 s between ``Dispersy._take_step`` calls per
community).  All *sizes* are records/bits, chosen so every array in the hot
step has a static shape.
"""

from __future__ import annotations

import dataclasses
import math

from dispersy_tpu.exceptions import ConfigError
from dispersy_tpu.faults import FaultModel
from dispersy_tpu.overload import OverloadConfig
from dispersy_tpu.recovery import RecoveryConfig
from dispersy_tpu.shardplane import ParallelConfig
from dispersy_tpu.storediet import StoreConfig
from dispersy_tpu.telemetry import MAX_TELEMETRY_PEERS, TelemetryConfig
from dispersy_tpu.traceplane import TraceConfig

# Sentinel for "empty slot" in uint32 record fields: sorts after every real
# global_time, so ascending sort pushes holes to the end of the store ring.
EMPTY_U32 = 0xFFFFFFFF
# Sentinel peer index for "no peer" in int32 index fields.
NO_PEER = -1

# ---- narrowed record-column dtypes (the byte diet, BENCH.md roofline) ----
# The fused round is memory-bandwidth-bound, so persistent columns whose
# value range provably fits a narrower word are stored narrow.  Meta ids
# fit 8 bits: user metas stay < MAX_USER_META (24), the dispersy-* control
# band tops out at META_MALICIOUS (0xF7), and the empty-slot sentinel is
# EMPTY_META = 0xFF — exactly the low byte of EMPTY_U32, so plain uint32
# <-> uint8 truncation is the lossless up/down conversion on the reachable
# value set (checkpoint.restore uses this to load pre-narrowing archives).
# Flags carry single bits (bit 0 = undone).  gt / member / payload / aux
# stay uint32: clocks and payloads are genuinely 32-bit, and aux carries
# full permission-nibble masks (4 bits x 8 metas).
EMPTY_META = 0xFF
META_DTYPE = "uint8"
FLAGS_DTYPE = "uint8"

# Candidate categories (reference: candidate.py WalkCandidate tracks separate
# walk/stumble/intro timestamps; categories drive the walk split).
CAT_NONE = 0
CAT_WALKED = 1
CAT_STUMBLED = 2
CAT_INTRODUCED = 3

# Reserved control meta-message ids (reference: community.py
# _initialize_meta_messages registers the dispersy-* control messages beside
# the app's metas; here user metas take ids [0, n_meta) and controls live in
# a reserved band well above them).  A record's columns are overloaded per
# meta:
#   dispersy-authorize / dispersy-revoke: payload = target member,
#       aux = per-meta permission NIBBLES over user meta ids: bit
#       (4*meta + p) grants (or revokes) permission p for that meta, with
#       p in {0=permit, 1=authorize, 2=revoke, 3=undo} — the reference's
#       four permission types (timeline.py Timeline.check resolves
#       (member, message, permission) triples; message.py Authorize/
#       RevokePayload carries [(member, message, permission)] lists,
#       TPU-packed here as one nibble mask per target).  The AUTHORIZE
#       bit for a meta lets its holder issue further authorize records
#       covering that meta — the reference's permission *chains*
#       (timeline.py Timeline.check walks authorize proofs recursively;
#       here chains grow one fold per round, unbounded across rounds —
#       see ops/timeline.check_grant); the REVOKE bit gates issuing
#       revoke records for that meta, separably from AUTHORIZE; the UNDO
#       bit gates dispersy-undo-other on that meta's records.
#   dispersy-undo-own / dispersy-undo-other: payload = target member,
#       aux = target global_time (reference: payload.py UndoPayload
#       (member, global_time, packet))
#   dispersy-dynamic-settings: payload = target user meta id, aux bit 0 =
#       new resolution policy (0 = PublicResolution, 1 = LinearResolution)
#       taking effect for records with global_time > this record's
#       (reference: payload.py DynamicSettingsPayload [(meta, policy)];
#       timeline.py Timeline.get_resolution_policy)
#   dispersy-destroy-community: payload/aux unused — once stored, the
#       peer's community is hard-killed (reference: community.py
#       HardKilledCommunity + DestroyCommunityPayload)
META_AUTHORIZE = 0xF0
META_REVOKE = 0xF1
META_UNDO_OWN = 0xF2
META_UNDO_OTHER = 0xF3
META_DYNAMIC = 0xF4
META_DESTROY = 0xF5
#   dispersy-identity: payload = mid32 (first 4 bytes of SHA1(pubkey));
#       see dispersy_tpu/crypto.py create_identities.
META_IDENTITY = 0xF6
#   dispersy-malicious-proof: payload = the convicted member, aux = the
#       global_time at which it provably double-signed.  Authored by an
#       EYEWITNESS the moment it observes a conflicting pair (a record
#       matching a stored row's (member, global_time) with different
#       content) and spread at CONTROL_PRIORITY, so convictions converge
#       network-wide instead of staying per-observer (reference:
#       dispersy.py malicious-member machinery spreads the conflicting
#       packet pair).  Structural-trust divergence, documented: the
#       reference's proof carries both signed packets for receivers to
#       re-verify; this simulation's wire records carry no signatures to
#       re-check (identity is structural everywhere — SURVEY §7 stage 9),
#       so the claim record IS the recast of the verified pair.
META_MALICIOUS = 0xF7
# Max user metas: per-meta config bitmasks (seq/double/direct/protected)
# live in the low bits of a uint32.
MAX_USER_META = 24
# Timeline grants pack FOUR bits per meta (the permission quadruple below)
# into a u32 table mask, capping timeline communities at 8 user metas.
MAX_TIMELINE_META = 8

# Permission types within one grant nibble (reference: timeline.py
# resolves u"permit" / u"authorize" / u"revoke" / u"undo" per meta).
PERM_PERMIT = 0
PERM_AUTHORIZE = 1
PERM_REVOKE = 2
PERM_UNDO = 3
PERM_NAMES = {"permit": PERM_PERMIT, "authorize": PERM_AUTHORIZE,
              "revoke": PERM_REVOKE, "undo": PERM_UNDO}


def perm_bit(meta: int, perm) -> int:
    """The aux/table-mask bit granting ``perm`` for user meta ``meta``;
    ``perm`` is a PERM_* id or one of the reference's permission strings
    (timeline.py u"permit" etc.)."""
    if isinstance(perm, str):
        try:
            perm = PERM_NAMES[perm]
        except KeyError:
            raise ConfigError(
                f"unknown permission {perm!r}; expected one of "
                f"{sorted(PERM_NAMES)}") from None
    if not 0 <= meta < MAX_TIMELINE_META:
        raise ConfigError(
            f"timeline permissions cover metas [0, {MAX_TIMELINE_META}), "
            f"got {meta}")
    if not 0 <= perm <= PERM_UNDO:
        raise ConfigError(f"unknown permission id {perm}")
    return 1 << (4 * meta + perm)


def perm_mask(pairs) -> int:
    """Nibble mask from [(meta_id, perm)] pairs (see :func:`perm_bit`)."""
    mask = 0
    for meta, perm in pairs:
        mask |= perm_bit(meta, perm)
    return mask


def user_perm_mask(n_meta: int) -> int:
    """All grantable nibble bits for ``n_meta`` user metas."""
    return (1 << (4 * min(n_meta, MAX_TIMELINE_META))) - 1

# Sync-response ordering priorities (reference: distribution.py — each
# Distribution carries a `priority`; community.py gives the permission
# control messages a high one so proofs outrun the records they permit,
# and dispersy-identity a LOW one: identities are bulk data, not urgent —
# without this, an identity flood starves permission records of the
# bounded forward slots and the sync budget).
DEFAULT_PRIORITY = 128
CONTROL_PRIORITY = 224
IDENTITY_PRIORITY = 16

# Byte-equivalent packet sizes for the traffic counters (reference:
# conversion.py wire shapes — 23 B common header = 1 B dispersy version +
# 1 B community version + 20 B master mid + 1 B message id; addresses are
# 6 B sockaddrs).  The simulation has no real wire format (declared
# anti-goal, SURVEY §7); these model the reference's packet sizes so
# total_up/total_down are comparable, not byte-exact.
HEADER_BYTES = 23
ADDR_BYTES = 6
# introduction-request: header + dest/lan/wan addrs + flags byte +
# 2 B identifier + sync tuple (time_low/high 8+8, modulo 2, offset 2)
# + the Bloom bitset (added per-config: bloom_words * 4).
INTRO_REQUEST_BASE_BYTES = HEADER_BYTES + 3 * ADDR_BYTES + 1 + 2 + 20
# introduction-response: header + dest/lan/wan + introduced lan/wan +
# flags + identifier.
INTRO_RESPONSE_BYTES = HEADER_BYTES + 5 * ADDR_BYTES + 1 + 2
# puncture-request: header + target lan/wan + identifier.
PUNCTURE_REQUEST_BYTES = HEADER_BYTES + 2 * ADDR_BYTES + 2
# puncture: header + own lan/wan + identifier.
PUNCTURE_BYTES = HEADER_BYTES + 2 * ADDR_BYTES + 2
# one sync record on the wire: header + 5 uint32 columns.
RECORD_BYTES = HEADER_BYTES + 20
# missing-proof request: header + 2 B identifier + (member, global_time)
# (reference: payload.py MissingProofPayload).
MISSING_PROOF_BYTES = HEADER_BYTES + 2 + 8
# missing-sequence request: header + 2 B identifier + member + 1 B meta +
# (missing_low, missing_high) (reference: payload.py
# MissingSequencePayload (member, message, missing_low, missing_high)).
MISSING_SEQ_BYTES = HEADER_BYTES + 2 + 4 + 1 + 8
# missing-message request: header + 2 B identifier + (member, global_time)
# (reference: payload.py MissingMessagePayload — member + one global_time
# in the round-synchronous recast).
MISSING_MSG_BYTES = HEADER_BYTES + 2 + 8
# missing-identity request: header + 2 B identifier + the 20-byte member
# id (reference: payload.py MissingIdentityPayload carries the mid).
MISSING_IDENTITY_BYTES = HEADER_BYTES + 2 + 20
# signature-request: header + 2 B identifier + the draft record's columns
# (reference: conversion.py packs the half-signed message inside
# dispersy-signature-request; the response carries it back countersigned).
SIGNATURE_REQUEST_BYTES = HEADER_BYTES + 2 + 20
SIGNATURE_RESPONSE_BYTES = HEADER_BYTES + 2 + 20


def priority_of(meta: int, n_meta: int, priorities) -> int:
    """Serving/forwarding priority of one meta id (scalar form; the engine
    computes the same thing vectorized).  User metas carry their declared
    priority; the control band is CONTROL_PRIORITY except low-priority
    dispersy-identity."""
    if meta < n_meta:
        return priorities[meta]
    return IDENTITY_PRIORITY if meta == META_IDENTITY else CONTROL_PRIORITY


def bloom_size_for(error_rate: float, capacity: int) -> tuple[int, int]:
    """(n_bits, n_hashes) for a Bloom filter with the given design point.

    Mirrors the reference's constructor-from-(error_rate, capacity)
    (reference: bloomfilter.py ``BloomFilter.__init__``): standard formulas
    m = -n·ln(p)/ln(2)^2, k = m/n·ln(2); n_bits rounded up to a multiple of
    32 so the bitset packs exactly into uint32 words.
    """
    if not (0.0 < error_rate < 1.0):
        raise ConfigError(f"error_rate must be in (0,1), got {error_rate}")
    if capacity <= 0:
        raise ConfigError(f"capacity must be positive, got {capacity}")
    m = -capacity * math.log(error_rate) / (math.log(2) ** 2)
    n_bits = int(math.ceil(m / 32.0)) * 32
    k = max(1, int(round(n_bits / capacity * math.log(2))))
    return n_bits, k


@dataclasses.dataclass(frozen=True)
class CommunityConfig:
    """All static knobs for one simulated community.

    Field defaults mirror the reference's protocol constants (BASELINE.md
    table; symbol-level citations in each comment).
    """

    # ---- population ----
    n_peers: int = 1024
    n_trackers: int = 2  # bootstrap peers, indices [0, n_trackers)
    #   (reference: bootstrap.py tracker list -> BootstrapCandidate)
    # Multi-community layout (reference: dispersy.py multiplexes many
    # Community instances over one runtime; the sync table is keyed by
    # community).  Each entry is (n_members, n_trackers) for one community;
    # the row axis is laid out as [all trackers, community-major][all
    # members, community-major], so every community is a contiguous block
    # with its own trackers inside the global tracker prefix and the whole
    # multiplex runs as ONE fused step — walks, candidates, stores and
    # clocks never cross blocks because candidates only ever enter through
    # in-block walks/bootstraps.  A physical peer joining k communities
    # contributes one row per membership, exactly like the reference's one
    # Community instance per joined overlay.  Empty = single community
    # (n_peers, n_trackers).
    communities: tuple = ()

    # ---- walker (reference: community.py walker task + candidate.py) ----
    walk_interval: float = 5.0          # seconds per round / per step
    walk_timeout: float = 10.5          # IntroductionRequestCache.timeout_delay
    walk_lifetime: float = 57.5         # WalkCandidate walk/stumble lifetime
    intro_lifetime: float = 27.5        # lifetime of introduced candidates
    eligibility_delay: float = 27.5     # min age before re-walking a candidate
    # Category split for dispersy_get_walk_candidate (reference:
    # community.py; ≈49.75% walked / 24.875% stumbled / 24.875% introduced /
    # 0.5% bootstrap).
    p_revisit_walked: float = 0.4975
    p_stumbled: float = 0.24875
    p_introduced: float = 0.24875
    p_bootstrap: float = 0.005
    k_candidates: int = 16              # candidate-table slots per peer
    walker_enabled: bool = True         # dispersy_enable_candidate_walker

    # ---- bloom sync (reference: community.py dispersy_claim_sync_bloom_filter,
    #      bloomfilter.py; bloom sized to fit one ~1500B UDP payload) ----
    sync_enabled: bool = True           # dispersy_enable_bloom_filter_sync
    sync_strategy: str = "largest"      # "largest" | "modulo" claim strategy
    #   (reference: _dispersy_claim_sync_bloom_filter_largest / _modulo)
    bloom_error_rate: float = 0.01      # dispersy_sync_bloom_filter_error_rate
    bloom_capacity: int = 256           # entries per sync slice / bloom
    response_budget: int = 16           # records per sync response
    #   (reference: dispersy_sync_response_limit ≈ 5 KB / packet size)

    # ---- message store (reference: the SQLite `sync` table;
    #      UNIQUE(community, member, global_time)) ----
    msg_capacity: int = 256             # store ring slots per peer
    request_inbox: int = 8              # intro-requests processed per peer/round
    tracker_inbox: int = 512            # intro-requests a *tracker* serves/round
    #   (reference: tool/tracker.py runs dedicated high-capacity introduction
    #    servers; a flash-crowd of bootstrapping peers is their design load.
    #    Size this near n_peers/n_trackers for cold flash-crowd starts: an
    #    undersized tracker leaves the overlay storm-locked — everyone
    #    bootstraps, drops, and removes candidates forever.  The tracker
    #    inbox is a compact [n_trackers, tracker_inbox] array, so large
    #    values are cheap.)
    # Sync intake needs no separate inbox knob: records flow back only
    # along the request edge, so per-round intake is exactly
    # request-count x response_budget by construction.

    # ---- push forwarding (reference: dispersy.py store_update_forward ->
    #      _forward: every freshly accepted/created sync message is pushed
    #      to `node_count` random verified candidates, per
    #      destination.py CommunityDestination(node_count=10)) ----
    forward_fanout: int = 3             # candidates pushed to per record batch
    forward_buffer: int = 4             # fresh records buffered per peer/round
    push_inbox: int = 16                # pushed records accepted per peer/round

    # ---- distribution policies per user meta (reference: distribution.py
    #      FullSyncDistribution / LastSyncDistribution / DirectDistribution;
    #      message.py binds one policy per meta) ----
    # keep-last-k per (member, meta): 0 = FullSync (keep everything);
    # k > 0 = LastSyncDistribution(history_size=k).  Empty tuple = all 0.
    last_sync_history: tuple = ()
    # Bit i set: user meta i is FullSync with enable_sequence_number — the
    # author stamps consecutive sequence numbers in `aux` and receivers
    # accept strictly in order; gaps are repaired by the Bloom pull (the
    # record stays out of the requester's bloom until accepted, so the
    # responder keeps re-offering it — the round-synchronous equivalent of
    # dispersy-missing-sequence).
    seq_meta_mask: int = 0
    # Bit i set: user meta i is DirectDistribution — delivered by one push
    # hop to sampled verified candidates (CommunityDestination shape),
    # never stored, never synced, never re-forwarded; receipt is counted in
    # stats.msgs_direct.
    direct_meta_mask: int = 0
    # Sync-response ordering (reference: the responder's ORDER BY
    # (priority DESC, global_time ASC|DESC per meta)).  Empty tuple = all
    # DEFAULT_PRIORITY.  Control metas are fixed at CONTROL_PRIORITY.
    meta_priority: tuple = ()
    # Bit i set: user meta i syncs newest-first (DESC).
    desc_meta_mask: int = 0

    # ---- double-signed messages (reference: authentication.py
    #      DoubleMemberAuthentication + the dispersy-signature-request/
    #      -response flow, SURVEY §3.5; stored rows land in
    #      double_signed_sync) ----
    # Bit i set: user meta i needs two signatures — the author drafts the
    # record and a chosen counterparty countersigns before it enters the
    # store (record's `aux` column carries the countersigner id).
    double_meta_mask: int = 0
    # Outstanding signature request lifetime (reference: the signature
    # RequestCache timeout; the request is sent ONCE — no retransmit — and
    # the cache slot frees on timeout, exactly like the reference).
    sig_timeout: float = 10.5
    # signature-requests a peer processes per round (bounded inbox).
    sig_inbox: int = 4
    # Probability the counterparty agrees to countersign — the simulation
    # knob standing in for the app-supplied allow_signature_func
    # (reference: community.py on_signature_request delegates the decision
    # to the application).  Deterministic per (peer, round, slot) draw.
    countersign_rate: float = 1.0

    # ---- delayed messages (reference: message.py ``DelayMessageByProof``
    #      + community.py on_missing_proof / dispersy-missing-proof): a
    #      record rejected ONLY because its permission proof has not
    #      arrived yet is parked in a bounded per-peer pen and re-enters
    #      the intake batch every round until the authorize record lands,
    #      the pen overflows, or it times out.  The round-synchronous
    #      recast of "delay the batch, request the proof, release on
    #      arrival": the proof request itself is subsumed by the timeline
    #      records' CONTROL_PRIORITY spread; the *delay semantics* — the
    #      record is not lost while the proof is in flight — live here.
    #      0 disables the pen (rejected records are dropped and re-learned
    #      only when a Bloom re-offer happens to repeat them). ----
    delay_inbox: int = 0                # pen slots per peer
    delay_timeout: float = 52.5         # seconds a record may wait
    #   (reference: DelayMessage lifetimes are request-cache timeouts;
    #    10.5 s x ~5 retries is the missing-proof retry window)
    # Active missing-proof round trips (reference: community.py
    # on_missing_proof / the dispersy-missing-proof exchange): each round
    # a peer with parked records asks each record's DELIVERING peer for
    # the author's grant chain; the server answers with its stored
    # authorize/revoke records targeting that author, returned by receipt
    # in the same round — pen residence becomes one round trip instead of
    # Bloom re-offer luck.  Off by default (the passive pen alone matches
    # the r2 semantics; this knob adds the reference's active request).
    proof_requests: bool = False
    proof_inbox: int = 4                # proof requests served per round
    proof_budget: int = 2               # control records returned per request
    # Active missing-sequence round trips (reference: community.py
    # on_missing_sequence / message.py DelayMessageBySequence): a
    # sequence-gapped record PARKS in the same pen instead of being
    # rejected, and each round its deliverer is asked for the missing
    # range [holder's max+1, gap-1]; the server answers with its stored
    # in-range records (ascending — chains accept bottom-up), returned by
    # receipt in the same round.  Gap-fill latency becomes a round trip
    # instead of Bloom re-offer luck.  Shares the pen and the
    # proof_inbox/proof_budget channel bounds.
    seq_requests: bool = False
    # Active missing-message round trips (reference: community.py
    # on_missing_message / payload.py MissingMessagePayload, via
    # message.py DelayPacketByMissingMessage): a dispersy-undo-other
    # whose check fails (target record not yet stored, or undoer's grant
    # chain unseen) PARKS in the pen instead of being rejected, and each
    # round its deliverer is asked for the exact (member, global_time)
    # record it names; the stored record rides back by receipt and joins
    # the same round's intake — the undo re-checks against it next round.
    # Shares the pen and the proof_inbox channel bound (budget 1: the
    # UNIQUE(member, global_time) store key makes the reply a single
    # record).
    msg_requests: bool = False
    # Unknown-member gate (reference: member.py — a packet whose author's
    # public key is unknown cannot be verified; conversion.py raises
    # DelayPacketByMissingMember): a USER record from an author whose
    # dispersy-identity record is not stored parks in the pen (or, with
    # the pen disabled/full, is rejected and re-learned by Bloom
    # re-offer).  Control records stay exempt — their authority is
    # structural in the simulation (SURVEY §7 stage 9).
    identity_required: bool = False
    # Active missing-identity round trips (reference: community.py
    # on_missing_identity / payload.py MissingIdentityPayload): each
    # round an identity-parked record's deliverer is asked for the
    # author's stored dispersy-identity record, returned by receipt in
    # the same round.  Shares the pen and proof_inbox bound (budget 1:
    # one identity record per member).
    identity_requests: bool = False

    # ---- clock (reference: community.py claim_global_time /
    #      dispersy_acceptable_global_time_range) ----
    acceptable_global_time_range: int = 10000

    # ---- environment / fault model (reference: failure handling *is* the
    #      protocol — candidate timeouts, walk timeouts; SURVEY.md §5.3) ----
    churn_rate: float = 0.0             # fraction of peers replaced per round
    packet_loss: float = 0.0            # Bernoulli drop per logical packet
    #   (traced-liftable under the fleet plane: a per-replica override
    #    may replace this VALUE inside one compiled multi-replica
    #    program while the config stays static — faults.
    #    TRACED_FAULT_KNOBS / engine.effective_faults; FLEET.md)
    # ---- NAT model (reference: candidate.py ``connection_type`` —
    #      u"public" vs u"symmetric-NAT", advertised in every
    #      introduction request/response; community.py
    #      dispersy_get_introduce_candidate never introduces two
    #      symmetric-NAT peers to each other because the puncture
    #      exchange cannot open a mapping between two address-dependent
    #      NATs).  ``p_symmetric``: fraction of members behind a
    #      symmetric NAT, assigned statically per identity (the NAT is
    #      the router's property — it survives churn rebirth; trackers
    #      are public infrastructure).  Effects when > 0: responders and
    #      trackers never introduce symmetric<->symmetric, and a
    #      puncture between two symmetric peers is dropped (so even a
    #      stray pairing cannot hole-punch) — symmetric peers reach each
    #      other's records via public intermediaries, exactly the
    #      reference's behavior. ----
    p_symmetric: float = 0.0

    # ---- identity (reference: member.py / dispersy-identity; see
    #      dispersy_tpu/crypto.py) ----
    # Declares that dispersy-identity records are in play, which folds
    # IDENTITY_PRIORITY into the serving/forwarding order so an identity
    # flood cannot starve other records of the bounded budgets.
    # create_identities refuses to run without it.
    identity_enabled: bool = False

    # ---- malicious-member bookkeeping (reference: dispersy.py's
    #      malicious-member machinery + dispersy-malicious-proof: a member
    #      provably signing two DIFFERENT messages at one global_time is
    #      blacklisted).  Detection is local-per-peer: a conflicting
    #      arrival against the store convicts the author on the receiving
    #      peer, which then rejects all its records at intake and ejects
    #      it from the candidate table.  With malicious_gossip on, an
    #      eyewitness additionally AUTHORS a dispersy-malicious-proof
    #      record (META_MALICIOUS: the reference spreads the conflicting
    #      packet pair) that sync-spreads at CONTROL_PRIORITY; accepting
    #      peers convict too, so blacklists converge network-wide instead
    #      of per-observer. ----
    malicious_enabled: bool = False
    k_malicious: int = 8                # blacklist slots per peer
    malicious_gossip: bool = False      # spread convictions as records

    # ---- community load/unload (reference: dispersy.py define_auto_load
    #      / get_community(load=True) + Community.load_community /
    #      unload_community, tests/test_classification.py) ----
    # True (the reference's default): a community packet arriving at a
    # peer whose instance is unloaded loads it for the next round.  False:
    # only an explicit load (scenario Load event / Community.load) does.
    auto_load: bool = True

    # ---- permissions (reference: timeline.py; bounded table of authorized
    #      members — real overlays authorize a handful of members) ----
    timeline_enabled: bool = False
    k_authorized: int = 16              # authorized-member slots per peer
    n_meta: int = 8                     # distinct user meta-message ids
    # Bit i set: user meta i is LinearResolution-protected — a record is
    # accepted only if its author holds the permit permission at the
    # record's global_time (reference: resolution.py LinearResolution +
    # timeline.py Timeline.check).  Unset bits are PublicResolution.
    protected_meta_mask: int = 0
    # Bit i set: user meta i is DynamicResolution — its policy can be
    # flipped at runtime by founder-sent dispersy-dynamic-settings records
    # (reference: resolution.py DynamicResolution, community.py
    # create_dynamic_settings).  The meta's protected_meta_mask bit is its
    # *initial* policy; a record is checked against the policy in force at
    # the record's own global_time, i.e. the highest-global_time flip at or
    # below it, replayed from the store exactly like the reference rebuilds
    # Timeline policy state from the database.
    dynamic_meta_mask: int = 0
    # The community founder: implicit holder of every permission, the root
    # of authority (reference: community.py master member).  Authorize/
    # revoke records are accepted from the founder or from any member
    # holding the AUTHORIZE/REVOKE permission for every granted meta
    # (nibble grants — ops/timeline.check_grant, mirroring
    # Timeline.check's recursive proof walk); undo-other needs the UNDO
    # permission on the target's meta, dynamic-settings the AUTHORIZE
    # permission on the flipped meta; destroy stays founder-only
    # (reference: the master member signs dispersy-destroy-community).
    # -1 = auto: the first non-tracker peer (index n_trackers).
    founder_member: int = -1

    # ---- parallel plane (dispersy_tpu/shardplane.py: shard-count +
    #      cross-shard exchange budget + chunked bloom scatters for the
    #      sharding-clean multichip step; PARALLEL.md).  All defaults
    #      compile to exactly the legacy single-device step.  MUST stay
    #      the SEVENTH-TO-LAST field, directly before ``trace`` (then
    #      ``store``, ``overload``, ``recovery``, ``telemetry``,
    #      ``faults``): checkpoint.py reconstructs pre-v16 config
    #      fingerprints by stripping the trailing ``parallel=...`` repr
    #      component (then ``trace=`` pre-v15, ``store=`` pre-v14,
    #      ``overload=`` pre-v13, ``recovery=`` pre-v12, ``telemetry=``
    #      pre-v10, ``faults=`` pre-v9). ----
    parallel: ParallelConfig = ParallelConfig()

    # ---- dissemination-tracing plane (dispersy_tpu/traceplane.py:
    #      on-device record lineage — per-peer first-arrival rounds,
    #      first-delivery channel codes, duplicate-delivery counters,
    #      coverage-percentile latches; OBSERVABILITY.md "Dissemination
    #      tracing").  All defaults compile to exactly the trace-free
    #      step.  MUST stay the SIXTH-TO-LAST field, directly before
    #      ``store`` (then ``overload``, ``recovery``, ``telemetry``,
    #      ``faults``): checkpoint.py reconstructs pre-v15 config
    #      fingerprints by stripping the trailing ``trace=...`` repr
    #      component (then ``store=`` pre-v14, ``overload=`` pre-v13,
    #      ``recovery=`` pre-v12, ``telemetry=`` pre-v10, ``faults=``
    #      pre-v9). ----
    trace: TraceConfig = TraceConfig()

    # ---- byte-diet store plane (dispersy_tpu/storediet.py: staging
    #      buffer + amortized compaction, cadenced sync, incremental
    #      Bloom digest — the ROADMAP item 1 byte diet).  All defaults
    #      compile to exactly the legacy every-round-merge step.  MUST
    #      stay the FIFTH-TO-LAST field, directly before ``overload``
    #      (then ``recovery``, ``telemetry``, ``faults``):
    #      checkpoint.py reconstructs pre-v14 config fingerprints by
    #      stripping the trailing ``store=...`` repr component (then
    #      ``overload=`` pre-v13, ``recovery=`` pre-v12, ``telemetry=``
    #      pre-v10, ``faults=`` pre-v9). ----
    store: StoreConfig = StoreConfig()

    # ---- ingress-protection plane (dispersy_tpu/overload.py:
    #      per-sender token buckets, priority admission under inbox
    #      overflow, flood-fair drop attribution; OVERLOAD.md).  All
    #      defaults compile to exactly the protection-free step.  MUST
    #      stay the FOURTH-TO-LAST field, directly before ``recovery``
    #      (then ``telemetry``, then ``faults``): checkpoint.py
    #      reconstructs pre-v13 config fingerprints by stripping the
    #      trailing ``overload=...`` repr component (then
    #      ``recovery=`` pre-v12, ``telemetry=`` pre-v10, ``faults=``
    #      pre-v9). ----
    overload: OverloadConfig = OverloadConfig()

    # ---- recovery plane (dispersy_tpu/recovery.py: staged repair of
    #      health-flagged peers — soft repair, walk backoff, quarantine
    #      with hysteresis; RECOVERY.md).  All defaults compile to
    #      exactly the recovery-free step.  MUST stay the THIRD-TO-LAST
    #      field, directly before ``telemetry`` (which precedes
    #      ``faults``): checkpoint.py reconstructs pre-v12 config
    #      fingerprints by stripping the trailing ``recovery=...`` repr
    #      component (then ``telemetry=`` pre-v10, ``faults=``
    #      pre-v9). ----
    recovery: RecoveryConfig = RecoveryConfig()

    # ---- telemetry plane (dispersy_tpu/telemetry.py: fused in-step
    #      metrics row, device-resident round-history ring, on-device
    #      histograms, flight recorder — OBSERVABILITY.md).  All
    #      defaults compile to exactly the telemetry-free step.  MUST
    #      stay the SECOND-TO-LAST field, directly before ``faults``:
    #      checkpoint.py reconstructs pre-v10 config fingerprints by
    #      stripping the trailing ``telemetry=...`` (and, pre-v9,
    #      ``faults=...``) repr components. ----
    telemetry: TelemetryConfig = TelemetryConfig()

    # ---- correlated fault channel + health sentinels (the chaos
    #      harness — dispersy_tpu/faults.py: Gilbert–Elliott bursty
    #      loss, region partitions, duplication, corruption, byzantine
    #      flooders, on-device health bits).  All-defaults compiles to
    #      exactly the fault-free step (FAULTS.md).  MUST stay the LAST
    #      field (with ``telemetry`` directly before it): checkpoint.py
    #      reconstructs pre-v10/pre-v9 config fingerprints by stripping
    #      the trailing repr components. ----
    faults: FaultModel = FaultModel()

    # ------------------------------------------------------------------
    @property
    def bloom_bits(self) -> int:
        return bloom_size_for(self.bloom_error_rate, self.bloom_capacity)[0]

    @property
    def bloom_hashes(self) -> int:
        return bloom_size_for(self.bloom_error_rate, self.bloom_capacity)[1]

    @property
    def bloom_words(self) -> int:
        return self.bloom_bits // 32

    @property
    def store_diet(self) -> bool:
        """Is the incremental (staging + digest + cadenced-sync) store
        plane compiled in?  (dispersy_tpu/storediet.py)"""
        return self.store.staging > 0

    @property
    def aux_dtype(self) -> str:
        """The persistent ``aux`` record-column dtype: u16 under the
        byte-diet opt-in (store.aux_bits=16), u32 otherwise.  Wire/batch
        aux stays u32 everywhere; the store boundary truncates (the
        meta/flags narrowing pattern, ops/store.store_insert)."""
        return "uint16" if self.store.aux_bits == 16 else "uint32"

    @property
    def store_stagger(self) -> bool:
        """Is the cohort-staggered compaction cadence compiled in?
        (store.cohorts > 1 riding the diet; storediet.stagger_of)"""
        return self.store.staging > 0 and self.store.cohorts > 1

    @property
    def cand_stamp_dtype(self) -> str:
        """The persistent candidate-timestamp dtype: u16 round-stamps
        under the byte-diet opt-in (store.cand_bits=16), f32 sim-seconds
        otherwise.  The walker always computes on f32 seconds; the store
        boundary (de)quantizes (engine._tab / engine's wrap-up)."""
        return "uint16" if self.store.cand_bits == 16 else "float32"

    @property
    def walk_lifetime_rounds(self) -> float:
        return self.walk_lifetime / self.walk_interval

    @property
    def intro_lifetime_rounds(self) -> float:
        return self.intro_lifetime / self.walk_interval

    @property
    def eligibility_delay_rounds(self) -> float:
        return self.eligibility_delay / self.walk_interval

    @property
    def sig_timeout_rounds(self) -> int:
        """Signature-request lifetime in whole rounds (>= 1 when enabled)."""
        return int(self.sig_timeout / self.walk_interval)

    @property
    def delay_enabled(self) -> bool:
        """Is the DelayMessageByProof pen compiled in?"""
        return self.delay_inbox > 0

    @property
    def delay_timeout_rounds(self) -> int:
        """Pen-record lifetime in whole rounds (>= 1 when enabled)."""
        return int(self.delay_timeout / self.walk_interval)

    @property
    def founder(self) -> int:
        """Resolved founder index (founder_member with -1 defaulted)."""
        return self.n_trackers if self.founder_member < 0 else self.founder_member

    @property
    def history(self) -> tuple:
        """last_sync_history with the empty default expanded."""
        return self.last_sync_history or (0,) * self.n_meta

    @property
    def priorities(self) -> tuple:
        """meta_priority with the empty default expanded."""
        return self.meta_priority or (DEFAULT_PRIORITY,) * self.n_meta

    @property
    def any_last_sync(self) -> bool:
        return any(k > 0 for k in self.history)

    @property
    def n_communities(self) -> int:
        return len(self.communities) or 1

    def layout(self):
        """Per-row community layout arrays (numpy, computed per config).

        Returns ``(community, boot_base, boot_count, mem_base, mem_count)``
        int32[n_peers] arrays: each row's community id, its community's
        tracker range [boot_base, boot_base + boot_count) and member range
        [mem_base, mem_base + mem_count) in global row indices.  Used as
        trace-time constants by the engine and directly by the oracle, so
        both derive identical structure from one place.
        """
        import numpy as np
        n = self.n_peers
        if not self.communities:
            t = self.n_trackers
            return (np.zeros(n, np.int32),
                    np.zeros(n, np.int32),
                    np.full(n, t, np.int32),
                    np.full(n, t, np.int32),
                    np.full(n, n - t, np.int32))
        community = np.zeros(n, np.int32)
        boot_base = np.zeros(n, np.int32)
        boot_count = np.zeros(n, np.int32)
        mem_base = np.zeros(n, np.int32)
        mem_count = np.zeros(n, np.int32)
        t_off = 0
        m_off = self.n_trackers
        for c, (m_c, t_c) in enumerate(self.communities):
            for lo, hi in ((t_off, t_off + t_c), (m_off, m_off + m_c)):
                community[lo:hi] = c
                boot_base[lo:hi] = t_off
                boot_count[lo:hi] = t_c
                mem_base[lo:hi] = m_off
                mem_count[lo:hi] = m_c
            t_off += t_c
            m_off += m_c
        return community, boot_base, boot_count, mem_base, mem_count

    @property
    def needs_priority_forward(self) -> bool:
        """Does the forward-buffer selection need priority ordering?  The
        bounded push buffer admits the F highest-priority fresh records
        (control metas outrank user metas), so a dispersy-authorize or
        dynamic-settings record cannot lose its only push to bulk traffic.
        Plain communities (no timeline, no identities, uniform priorities)
        keep cheap batch-order selection."""
        return (self.timeline_enabled or self.identity_enabled
                or len(set(self.priorities)) > 1)

    @property
    def needs_response_order(self) -> bool:
        """Does the sync responder need a non-store-order view?  True when
        priorities differ across metas (incl. control metas outranking user
        metas under the timeline, or low-priority identity records being
        in play) or any meta syncs DESC."""
        if self.desc_meta_mask:
            return True
        if len(set(self.priorities)) > 1:
            return True
        if self.identity_enabled and self.priorities[0] != IDENTITY_PRIORITY:
            return True
        return self.timeline_enabled and self.priorities[0] != CONTROL_PRIORITY

    def __post_init__(self) -> None:
        if self.n_peers <= 0:
            raise ConfigError("n_peers must be positive")
        if not (0 <= self.n_trackers <= self.n_peers):
            raise ConfigError("n_trackers must be in [0, n_peers]")
        p = (self.p_revisit_walked + self.p_stumbled + self.p_introduced
             + self.p_bootstrap)
        if abs(p - 1.0) > 1e-6:
            raise ConfigError(f"walk category probabilities sum to {p}, not 1")
        if self.forward_fanout > self.k_candidates:
            raise ConfigError("forward_fanout cannot exceed k_candidates")
        if self.forward_fanout > 0 and (self.forward_buffer < 1
                                        or self.push_inbox < 1):
            raise ConfigError("forward_fanout > 0 requires forward_buffer >= 1 "
                             "and push_inbox >= 1")
        if not (1 <= self.n_meta <= MAX_USER_META):
            raise ConfigError(f"n_meta must be in [1, {MAX_USER_META}]")
        if self.protected_meta_mask >> self.n_meta:
            raise ConfigError("protected_meta_mask has bits above n_meta")
        if self.dynamic_meta_mask:
            if self.dynamic_meta_mask >> self.n_meta:
                raise ConfigError("dynamic_meta_mask has bits above n_meta")
            if not self.timeline_enabled:
                raise ConfigError("dynamic_meta_mask requires "
                                 "timeline_enabled (policy flips are "
                                 "timeline state)")
        for name, mask in (("seq_meta_mask", self.seq_meta_mask),
                           ("direct_meta_mask", self.direct_meta_mask),
                           ("desc_meta_mask", self.desc_meta_mask),
                           ("double_meta_mask", self.double_meta_mask)):
            if mask >> self.n_meta:
                raise ConfigError(f"{name} has bits above n_meta")
        if self.seq_meta_mask & self.direct_meta_mask:
            raise ConfigError("a meta cannot be both sequenced and direct")
        if self.double_meta_mask & (self.seq_meta_mask
                                    | self.direct_meta_mask):
            # aux carries the countersigner for double metas, so it cannot
            # also carry a sequence number; Direct never stores, so a
            # double signature would protect nothing.
            raise ConfigError("a double-signed meta cannot be sequenced or "
                             "direct")
        if self.double_meta_mask:
            if self.sig_inbox < 1:
                raise ConfigError("double_meta_mask requires sig_inbox >= 1")
            if self.sig_timeout_rounds < 1:
                raise ConfigError("sig_timeout must cover >= 1 round")
            if not (0.0 <= self.countersign_rate <= 1.0):
                raise ConfigError("countersign_rate must be in [0, 1]")
        if self.seq_meta_mask & self.desc_meta_mask:
            # DESC would deliver newest-first and leave permanent sequence
            # gaps; the reference pairs enable_sequence_number with ASC.
            raise ConfigError("sequenced metas must sync ASC")
        if self.last_sync_history and len(self.last_sync_history) != self.n_meta:
            raise ConfigError("last_sync_history length must equal n_meta")
        if self.meta_priority and len(self.meta_priority) != self.n_meta:
            raise ConfigError("meta_priority length must equal n_meta")
        if any(not (0 <= p <= 255) for p in self.priorities):
            raise ConfigError("meta_priority entries must be in [0, 255]")
        for i, k in enumerate(self.history):
            if k < 0:
                raise ConfigError("last_sync_history entries must be >= 0")
            if k > 0 and ((self.seq_meta_mask >> i) & 1
                          or (self.direct_meta_mask >> i) & 1):
                raise ConfigError("a LastSync meta cannot be sequenced/direct")
        if self.communities:
            if any(m < 0 or t < 0 for m, t in self.communities):
                raise ConfigError("community sizes must be non-negative")
            if sum(m + t for m, t in self.communities) != self.n_peers:
                raise ConfigError("community blocks must sum to n_peers")
            if sum(t for _, t in self.communities) != self.n_trackers:
                raise ConfigError(
                    "community tracker counts must sum to n_trackers")
            if self.timeline_enabled and self.founder_member >= 0:
                raise ConfigError(
                    "multi-community timelines use per-community founders "
                    "(each block's first member); founder_member must stay "
                    "auto (-1)")
        if self.timeline_enabled:
            f = self.founder
            if not (self.n_trackers <= f < self.n_peers):
                raise ConfigError("founder_member must be a non-tracker peer")
            if self.k_authorized < 1:
                raise ConfigError("timeline_enabled requires k_authorized >= 1")
            if self.n_meta > MAX_TIMELINE_META:
                raise ConfigError(
                    f"timeline grants pack 4 permission bits per meta into "
                    f"a u32, so timeline_enabled caps n_meta at "
                    f"{MAX_TIMELINE_META} (got {self.n_meta})")
        if self.malicious_enabled and self.k_malicious < 1:
            raise ConfigError("malicious_enabled requires k_malicious >= 1")
        if self.malicious_gossip and not self.malicious_enabled:
            raise ConfigError("malicious_gossip requires malicious_enabled "
                              "(gossip spreads convictions the local "
                              "detector produces)")
        if not (0.0 <= self.p_symmetric <= 1.0):
            raise ConfigError("p_symmetric must be in [0, 1]")
        if self.delay_inbox < 0:
            raise ConfigError("delay_inbox must be >= 0")
        if self.delay_inbox > 0:
            if not self.timeline_enabled:
                raise ConfigError("delay_inbox requires timeline_enabled "
                                 "(only permission-rejected records are "
                                 "delayable — DelayMessageByProof)")
            if self.delay_timeout_rounds < 1:
                raise ConfigError("delay_timeout must cover >= 1 round")
        if self.proof_requests:
            if not self.delay_enabled:
                raise ConfigError("proof_requests requires delay_inbox > 0 "
                                 "(only parked records request proofs)")
            if self.proof_inbox < 1 or self.proof_budget < 1:
                raise ConfigError("proof_requests requires proof_inbox >= 1 "
                                 "and proof_budget >= 1")
        if self.seq_requests:
            if not self.seq_meta_mask:
                raise ConfigError("seq_requests needs a seq_meta_mask "
                                  "(no sequenced metas, no gaps to fill)")
            if not self.delay_enabled:
                raise ConfigError("seq_requests requires delay_inbox > 0 "
                                  "(gapped records park in the pen; note "
                                  "the pen itself needs timeline_enabled)")
            if self.proof_inbox < 1 or self.proof_budget < 1:
                raise ConfigError("seq_requests shares the proof channel: "
                                  "proof_inbox/proof_budget must be >= 1")
        if self.msg_requests:
            if not self.timeline_enabled:
                raise ConfigError("msg_requests serves undo-other targets, "
                                  "which need timeline_enabled")
            if not self.delay_enabled:
                raise ConfigError("msg_requests requires delay_inbox > 0 "
                                  "(target-less undos park in the pen)")
            if self.proof_inbox < 1:
                raise ConfigError("msg_requests shares the proof channel: "
                                  "proof_inbox must be >= 1")
        if self.identity_required and not self.identity_enabled:
            raise ConfigError("identity_required gates on stored "
                              "dispersy-identity records — set "
                              "identity_enabled and create_identities first")
        fm = self.faults
        if not isinstance(fm, FaultModel):
            raise ConfigError("faults must be a FaultModel")
        for (a_lo, a_hi), (b_lo, b_hi) in fm.partitions:
            if a_hi > self.n_peers or b_hi > self.n_peers:
                raise ConfigError(
                    f"partition ranges must stay inside [0, {self.n_peers})")
            if not (a_hi <= b_lo or b_hi <= a_lo):
                raise ConfigError(
                    f"partition sides [{a_lo},{a_hi}) and [{b_lo},{b_hi}) "
                    "overlap — a peer on both sides would be cut off from "
                    "its own side; sides must be disjoint")
        if fm.flood_enabled:
            if any(s >= self.n_peers for s in fm.flood_senders):
                raise ConfigError("flood_senders must be peer indices "
                                  f"< n_peers ({self.n_peers})")
            if self.n_peers <= self.n_trackers:
                raise ConfigError("flooding needs at least one non-tracker "
                                  "victim")
            if self.push_inbox < 1:
                raise ConfigError("flooding rides the push channel: "
                                  "push_inbox must be >= 1")
        tr = self.trace
        if not isinstance(tr, TraceConfig):
            raise ConfigError("trace must be a TraceConfig")
        if tr.enabled:
            # The lineage channel table covers exactly create /
            # walk-sync / push / flood (traceplane.CHANNEL_NAMES), so
            # the plane refuses configs that open OTHER intake
            # segments or create sites — attribution would silently
            # have no code for them (traceplane.py scope gate).
            for flag, why in (
                    (self.delay_enabled,
                     "the delay pen re-enters records through its own "
                     "intake segment (and carries the proof/seq/msg/"
                     "identity request channels)"),
                    (bool(self.double_meta_mask),
                     "double-signed completions arrive through the "
                     "signature segment"),
                    (self.malicious_gossip,
                     "eyewitness proofs are authored inside the fused "
                     "step, a create site the lineage fold cannot "
                     "attribute")):
                if flag:
                    raise ConfigError(
                        "trace.enabled (the dissemination-tracing "
                        f"plane) is incompatible with this knob: {why}; "
                        "its channel table covers create/walk-sync/"
                        "push/flood only")
        sd = self.store
        if not isinstance(sd, StoreConfig):
            raise ConfigError("store must be a StoreConfig")
        if sd.staging > 0:
            # The incremental store serves/queries through the epoch
            # digest and defers ring merges; the full-feature check
            # pipeline (timeline folds, sequence chains, conviction
            # scans, the delay pen) reads the every-round-merged store
            # directly and stays on the legacy path.  Gate loudly
            # instead of silently diverging (STORE.md scope table).
            for flag, why in (
                    (self.timeline_enabled,
                     "timeline folds re-walk the merged store"),
                    (self.malicious_enabled,
                     "conviction scans compare arrivals against the "
                     "merged store"),
                    (bool(self.seq_meta_mask),
                     "sequence chains read stored maxima every round"),
                    (bool(self.double_meta_mask),
                     "the signature flow stores completions directly"),
                    (self.delay_enabled,
                     "the delay pen re-checks against the merged "
                     "store"),
                    (self.identity_required,
                     "the identity gate queries stored identities "
                     "every round")):
                if flag:
                    raise ConfigError(
                        "store.staging (the incremental byte-diet "
                        f"store) is incompatible with this knob: {why}; "
                        "use the legacy store (store.staging=0) for "
                        "full-feature communities")
            if self.sync_enabled and self.sync_strategy != "largest":
                raise ConfigError(
                    "store.staging requires sync_strategy='largest': "
                    "the digest covers the newest-window slice; a "
                    "modulo stripe changes per epoch and would leave "
                    "digest false negatives for out-of-stripe records")
            if sd.cohorts > 1:
                # The staggered cadence extracts the active cohort's
                # rows as one reshape + dynamic-slice block
                # (ops/store.cohort_take), which needs the mod
                # assignment to tile the peer axis exactly.
                if self.n_peers % sd.cohorts:
                    raise ConfigError(
                        "store.cohorts must divide n_peers: cohort "
                        "blocks are extracted as equal reshape slices "
                        f"({self.n_peers} % {sd.cohorts} != 0)")
                if not self.sync_enabled:
                    raise ConfigError(
                        "store.cohorts > 1 staggers the SYNC cadence — "
                        "meaningless with sync_enabled=False; leave "
                        "cohorts=1")
        ov = self.overload
        if not isinstance(ov, OverloadConfig):
            raise ConfigError("overload must be an OverloadConfig")
        rc = self.recovery
        if not isinstance(rc, RecoveryConfig):
            raise ConfigError("recovery must be a RecoveryConfig")
        if rc.enabled and not fm.health_checks:
            raise ConfigError(
                "recovery.enabled maps latched health-sentinel bits to "
                "repair actions — it requires faults.health_checks=True")
        pl = self.parallel
        if not isinstance(pl, ParallelConfig):
            raise ConfigError("parallel must be a ParallelConfig")
        if pl.shards > 1 and self.n_peers % pl.shards != 0:
            raise ConfigError(
                f"parallel.shards={pl.shards} must divide n_peers "
                f"({self.n_peers}): the ragged exchange addresses "
                "destination shards as key // (n_peers // shards)")
        tl = self.telemetry
        if not isinstance(tl, TelemetryConfig):
            raise ConfigError("telemetry must be a TelemetryConfig")
        if tl.enabled and self.n_peers > MAX_TELEMETRY_PEERS:
            raise ConfigError(
                f"telemetry's byte-lane u64 sums are exact only up to "
                f"{MAX_TELEMETRY_PEERS} peers (got {self.n_peers})")
        if tl.flight_recorder > 0 and not fm.health_checks:
            raise ConfigError(
                "telemetry.flight_recorder records health-sentinel "
                "latches — it requires faults.health_checks=True")
        if self.identity_requests:
            if not self.identity_required:
                raise ConfigError("identity_requests without "
                                  "identity_required has nothing to ask "
                                  "for (no record ever parks on identity)")
            if not self.delay_enabled:
                raise ConfigError("identity_requests requires delay_inbox "
                                  "> 0 (identity-less records park in the "
                                  "pen; note the pen needs "
                                  "timeline_enabled)")
            if self.proof_inbox < 1:
                raise ConfigError("identity_requests shares the proof "
                                  "channel: proof_inbox must be >= 1")

    def replace(self, **kw) -> "CommunityConfig":
        return dataclasses.replace(self, **kw)
