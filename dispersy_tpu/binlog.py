"""Binary round logs: compact experiment logging + offline decoder.

The reference logs cluster experiments as packed binary event streams and
ships a decoder for offline analysis (reference: tool/ldecoder.py
``Parser`` — scenarioscript runs write binary logs precisely because
per-event text/JSON is too heavy at experiment rate).  The rebuild's
equivalent: :class:`BinaryLog` writes one fixed-width packed record per
round (field schema in the header, float64 values — exact for every u32
counter), and :func:`decode` streams them back as dicts.  At 1M peers a
round snapshot is ~30 scalars; the binary row is ~240 bytes vs ~1 KB of
JSON, and decode is a single ``numpy.frombuffer``.

Format (little-endian):
  magic b"DTPL" | u16 version | u16 n_fields
  n_fields x (u16 name_len | utf-8 name)
  u32 meta_len | utf-8 JSON metadata blob
  then n_fields x f64 per appended row, to EOF.
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

MAGIC = b"DTPL"
VERSION = 1


class BinaryLog:
    """Append-per-round packed log (the experiment-rate MetricsLog form).

    ``fields`` fixes the schema at open; ``append`` takes any mapping and
    writes the schema's fields (missing -> NaN, extras ignored — scenario
    rows carry run-specific extras that a fixed binary schema drops by
    design; use MetricsLog's JSON dump when you need them all).

    ``strict=True`` turns a missing schema field into an immediate
    ``ValueError`` naming it instead of a silent NaN — the mode
    ``MetricsLog.dump_binary`` uses after validating its rows, so a
    schema drift can never reach the file as NaN holes.
    """

    def __init__(self, path: str, fields: list[str],
                 meta: dict | None = None, strict: bool = False):
        if not fields:
            raise ValueError("BinaryLog needs at least one field")
        self.path = path
        self.strict = strict
        self.fields = list(fields)
        self._fmt = "<" + "d" * len(self.fields)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        blob = json.dumps(meta or {}).encode()
        with open(path, "wb") as f:
            f.write(MAGIC)
            f.write(struct.pack("<HH", VERSION, len(self.fields)))
            for name in self.fields:
                nb = name.encode()
                f.write(struct.pack("<H", len(nb)))
                f.write(nb)
            f.write(struct.pack("<I", len(blob)))
            f.write(blob)
        self._f = open(path, "ab")

    def append(self, row: dict) -> None:
        if self.strict:
            missing = [k for k in self.fields if k not in row]
            if missing:
                raise ValueError(
                    f"BinaryLog(strict): row is missing schema "
                    f"field(s) {missing}")
        vals = [float(row.get(k, float("nan"))) for k in self.fields]
        self._f.write(struct.pack(self._fmt, *vals))
        # Rows arrive at experiment rate (one per round), not event rate:
        # flushing each keeps a killed run's loss to the one torn row
        # decode() already tolerates, instead of a whole stdio buffer.
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "BinaryLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def decode(path: str) -> tuple[dict, list[dict]]:
    """Read a :class:`BinaryLog` file -> (meta, rows).

    Integer-valued fields come back as ints (every Stats counter is a u32,
    exact in f64), float-valued ones as floats — matching what
    ``metrics.snapshot`` produced.
    """
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 8 or data[:4] != MAGIC:
        raise ValueError(f"{path}: not a DTPL binary log")
    version, n_fields = struct.unpack_from("<HH", data, 4)
    if version != VERSION:
        raise ValueError(f"{path}: format version {version}, "
                         f"expected {VERSION}")
    try:
        # A file killed mid-header can end anywhere inside the name table
        # or meta blob; surface every such truncation as ValueError.
        off = 8
        fields = []
        for _ in range(n_fields):
            (nl,) = struct.unpack_from("<H", data, off)
            off += 2
            fields.append(data[off:off + nl].decode())
            off += nl
        (ml,) = struct.unpack_from("<I", data, off)
        off += 4
        if off + ml > len(data):
            raise ValueError("meta blob truncated")
        meta = json.loads(data[off:off + ml].decode() or "{}")
        off += ml
    except (struct.error, UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"{path}: torn header ({e})") from e
    body = data[off:]
    row_bytes = 8 * n_fields
    if len(body) % row_bytes:
        # a torn trailing row (killed run) is dropped, not an error — the
        # reference's decoder likewise tolerates truncated logs
        body = body[:len(body) - (len(body) % row_bytes)]
    mat = np.frombuffer(body, dtype="<f8").reshape(-1, n_fields)
    rows = []
    for r in mat:
        row = {}
        for k, v in zip(fields, r):
            if np.isnan(v):
                row[k] = None
            elif np.isfinite(v) and v == int(v):
                row[k] = int(v)
            else:
                row[k] = float(v)  # incl. ±inf, which int() would reject
        rows.append(row)
    return meta, rows
