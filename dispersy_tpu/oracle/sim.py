"""Pure-Python CPU oracle of one full simulation round.

The rebuild's answer to the reference's in-process behavioral test harness
(reference: tests/dispersytestclass.py ``DispersyTestFunc`` drives real
stacks on loopback; tests/debugcommunity/node.py ``DebugNode`` hand-crafts
packets and asserts on what comes back): a slow, loop-and-list
implementation of the *same semantics* as :func:`dispersy_tpu.engine.step`,
replayable **bit-for-bit** because every stochastic draw in the engine is a
counter-based hash of (seed, round, peer, purpose, salt) — see
:mod:`dispersy_tpu.ops.rng`.

The trace-equality tests (driver config #1: tiny-N sync vs CPU reference)
step this oracle and the jitted engine side by side and require identical
state arrays after every round.  Divergence in any field — a candidate
timestamp, a stats counter, one store record — fails the suite, which is
what makes the TPU kernels trustworthy at 1M peers where nothing is
inspectable by eye.

Float32 discipline: candidate timestamps and sim-time are float32 on
device, so every time comparison here goes through ``np.float32`` exactly
once per arithmetic step, mirroring the engine's dtype flow.
"""

from __future__ import annotations

import numpy as np

from dispersy_tpu.config import (CONTROL_PRIORITY, EMPTY_META, EMPTY_U32,
                                 INTRO_REQUEST_BASE_BYTES,
                                 INTRO_RESPONSE_BYTES, MAX_TIMELINE_META,
                                 META_AUTHORIZE,
                                 META_DESTROY, META_DYNAMIC, META_MALICIOUS,
                                 META_REVOKE,
                                 META_UNDO_OTHER, META_UNDO_OWN,
                                 META_IDENTITY, MISSING_IDENTITY_BYTES,
                                 MISSING_MSG_BYTES,
                                 MISSING_PROOF_BYTES, MISSING_SEQ_BYTES,
                                 NO_PEER,
                                 PERM_AUTHORIZE, PERM_PERMIT, PERM_REVOKE,
                                 PERM_UNDO,
                                 PUNCTURE_BYTES, PUNCTURE_REQUEST_BYTES,
                                 RECORD_BYTES, SIGNATURE_REQUEST_BYTES,
                                 SIGNATURE_RESPONSE_BYTES, CommunityConfig,
                                 priority_of, user_perm_mask)
from dispersy_tpu import telemetry as tlm
from dispersy_tpu.oracle.bloom import OracleBloom, record_hash
from dispersy_tpu.recovery import NUM_HEALTH_BITS
from dispersy_tpu.state import stats_gates as _stats_gates
from dispersy_tpu.storediet import (active_cohort, cohort_of,
                                    epoch_of, epoch_of_cohort,
                                    stagger_of, sync_round_of)
from dispersy_tpu.traceplane import (CH_CREATE, CH_PUSH, CH_WALK_SYNC,
                                     CHANNEL_NAMES, LATCH_PCTS,
                                     NUM_CHANNELS, redundancy_f32)
from dispersy_tpu.ops import rng as _jrng

FLAG_UNDONE = 1

M32 = 0xFFFFFFFF
NEVER = np.float32(-1.0e9)
_NEVER_ACT = np.float32(-2.0e9)

# Mirrors of the engine's loss-salt blocks (engine.py module constants).
_LOSS_REQUEST = 0 << 16
_LOSS_RESPONSE = 1 << 16
_LOSS_PUNCTURE_REQ = 2 << 16
_LOSS_PUNCTURE = 3 << 16
_LOSS_SYNC = 4 << 16
_LOSS_FORWARD = 5 << 16
_LOSS_SIGREQ = 6 << 16
_LOSS_SIGRESP = 7 << 16
_LOSS_PROOF_REQ = 8 << 16
_LOSS_PROOF_RESP = 9 << 16
_LOSS_SEQ_REQ = 10 << 16
_LOSS_SEQ_RESP = 11 << 16
_LOSS_MSG_REQ = 12 << 16
_LOSS_MSG_RESP = 13 << 16
_LOSS_ID_REQ = 14 << 16
_LOSS_ID_RESP = 15 << 16
_TRACKER_SALT = 1 << 15
_TRACKER_INTRO_SALT = 1 << 20
# Chaos-harness salt blocks (engine.py mirror).
_LOSS_FLOOD = 16 << 16
_FAULT_SYNC = 0 << 16
_FAULT_PUSH = 1 << 16

# Purpose tags (ops/rng.py).
P_CATEGORY, P_SLOT, P_INTRO, P_BOOTSTRAP = 1, 2, 3, 4
P_CHURN, P_LOSS, P_GOSSIP, P_SIGN, P_NAT = 5, 6, 7, 8, 9
P_GE, P_GE_LOSS, P_CORRUPT, P_DUP, P_FLOOD = 10, 11, 12, 13, 14
P_RECOVERY = 15
P_OVERLOAD = 16

KIND_WALK, KIND_STUMBLE, KIND_INTRO = 0, 1, 2
CAT_NONE, CAT_WALKED, CAT_STUMBLED, CAT_INTRODUCED = 0, 1, 2, 3


def _fmix32(x: int) -> int:
    x &= M32
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & M32
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & M32
    x ^= x >> 16
    return x


def _combine(h: int, v: int) -> int:
    h &= M32
    return (h ^ ((_fmix32(v) + 0x9E3779B9 + ((h << 6) & M32) + (h >> 2)) & M32)) & M32


def fold_seed(key0: int, key1: int) -> int:
    return _combine(_fmix32(key0), key1)


def rand_u32(seed: int, rnd: int, peer: int, purpose: int, salt: int = 0) -> int:
    h = _combine(seed & M32, rnd & M32)
    h = _combine(h, purpose)
    h = _combine(h, peer & M32)
    return _combine(h, salt & M32)


def rand_uniform(seed, rnd, peer, purpose, salt=0) -> float:
    """Exact mirror of ops/rng.rand_uniform's float32 value (which is exact
    in float64 too: a 24-bit integer scaled by a power of two)."""
    return (rand_u32(seed, rnd, peer, purpose, salt) >> 8) / float(1 << 24)


def _f32(x) -> np.float32:
    return np.float32(x)


class Record:
    """One sync-table row: (global_time, member, meta, payload, aux, flags)."""

    __slots__ = ("gt", "member", "meta", "payload", "aux", "flags")

    def __init__(self, gt, member, meta, payload, aux=0, flags=0):
        self.gt, self.member, self.meta = int(gt), int(member), int(meta)
        self.payload, self.aux = int(payload), int(aux)
        self.flags = int(flags)

    def key(self):
        return (self.gt, self.member, self.meta, self.payload)

    def copy(self) -> "Record":
        return Record(self.gt, self.member, self.meta, self.payload,
                      self.aux, self.flags)

    def hash(self) -> int:
        return record_hash(self.member, self.gt, self.meta, self.payload)


class AuthRow:
    """One grant/revoke row (ops/timeline.py AuthTable mirror): ``mask``
    holds per-meta permission nibbles, ``rev`` flags a revoke row,
    ``issuer`` the member that signed it (the retro re-walk handle)."""

    __slots__ = ("member", "mask", "gt", "rev", "issuer")

    def __init__(self, member, mask, gt, rev=False, issuer=0):
        self.member, self.mask, self.gt = int(member), int(mask), int(gt)
        self.rev = bool(rev)
        self.issuer = int(issuer)


class Slot:
    """One candidate-table slot (candidate.py WalkCandidate mirror)."""

    __slots__ = ("peer", "walk", "stumble", "intro")

    def __init__(self):
        self.peer = NO_PEER
        self.walk = self.stumble = self.intro = NEVER


class OraclePeer:
    def __init__(self, cfg: CommunityConfig):
        self.alive = True
        self.loaded = True
        self.session = 0
        self.global_time = 1
        self.slots = [Slot() for _ in range(cfg.k_candidates)]
        self.store: list[Record] = []   # kept sorted by Record.key()
        # Byte-diet store plane (dispersy_tpu/storediet.py): the staging
        # buffer (delivery order, bounded at cfg.store.staging) and the
        # incremental epoch digest — both mirror engine leaves
        # bit-exactly; empty/None when the plane is compiled out.
        self.staging: list[Record] = []
        self.digest = (OracleBloom(cfg.bloom_bits, cfg.bloom_hashes)
                       if cfg.store_diet and cfg.sync_enabled else None)
        # Cohort-staggered cadence (storediet.py, engine cohort/epoch
        # leaves): the compaction cohort is the peer identity's (i %
        # cohorts, assigned by OracleSim) and survives churn; the epoch
        # counts this peer's completed compactions (disk-like — churn
        # re-derives it from the shared round counter, a value
        # identity).  Both stay 0 when staggering is compiled out.
        self.cohort = 0
        self.epoch = 0
        self.fwd: list[Record] = []     # forward batch for next round
        self.auth: list[AuthRow] = []   # bounded at cfg.k_authorized
        # delayed-message pen: (record, round first parked, delivering
        # peer) triples, bounded at cfg.delay_inbox (engine dly_* fields,
        # incl. dly_src — the missing-proof request target)
        self.delay: list[tuple[Record, int, int]] = []
        # signature request cache (one in flight; engine sig_* fields)
        self.sig_target = NO_PEER
        self.sig_meta = self.sig_payload = 0
        self.sig_gt = self.sig_since = 0
        # malicious-member blacklist (engine mal_member)
        self.mal: list[int] = []
        # stats
        self.walk_success = self.walk_fail = 0
        self.msgs_stored = self.msgs_dropped = 0
        self.requests_dropped = self.punctures = 0
        self.msgs_forwarded = self.msgs_rejected = 0
        self.msgs_direct = 0
        self.msgs_delayed = 0
        self.msgs_corrupt_dropped = 0
        self.health = 0        # latched sentinel bits (faults.HEALTH_*)
        # recovery plane (engine backoff/quar_until/repair_round leaves
        # + the stats recov_* counters; dispersy_tpu/recovery.py)
        self.backoff = 0
        self.quar_until = 0
        self.repair_round = 0
        self.recov_soft = self.recov_backoff = 0
        self.recov_quarantine = 0
        self.recov_cleared = [0] * NUM_HEALTH_BITS
        # ingress-protection plane (engine bucket leaf + the stats
        # msgs_shed_* counters; dispersy_tpu/overload.py).  The bucket
        # is the overlay's rate-limiter view of the sender identity —
        # like ge_bad it survives churn rebirth.
        self.bucket = 0
        self.msgs_shed_rate = self.msgs_shed_priority = 0
        # parallel plane (engine stats.xshard_shed): push edges this
        # sender lost to a full cross-shard send bucket
        # (parallel.cross_shard_budget overflow) — exchange
        # backpressure, not inbox overflow.
        self.xshard_shed = 0
        # dissemination-tracing plane (engine trace_first/trace_chan/
        # trace_dups per-peer lineage + the stats trace_delivered/
        # trace_dup channel counters; dispersy_tpu/traceplane.py).
        # Lineage is disk-like: wiped with the store on churn/
        # quarantine rebirth; the counters survive like every stat.
        t_w = (cfg.trace.tracked_slots if cfg.trace.enabled else 0)
        self.trace_first = [0] * t_w
        self.trace_chan = [0] * t_w
        self.trace_dups = [0] * t_w
        self.trace_delivered = [0] * NUM_CHANNELS
        self.trace_dup = [0] * NUM_CHANNELS
        self.proof_requests = self.proof_records = 0
        self.seq_requests = self.seq_records = 0
        self.mm_requests = self.mm_records = 0
        self.id_requests = self.id_records = 0
        self.sig_signed = self.sig_done = self.sig_expired = 0
        self.conflicts = 0
        self.convictions_rx = 0
        self.auth_unwound = 0
        self.msgs_retro = 0
        self.bytes_up = self.bytes_down = 0          # wrap mod 2^32
        self.accepted_by_meta = [0] * (cfg.n_meta + 1)


class OracleSim:
    """Mirror of engine.step at Python speed; usable up to a few hundred peers.

    The fleet plane (dispersy_tpu/fleet.py) needs no oracle of its own:
    a fleet replica is DEFINED as bit-identical to the single run whose
    static config carries its traced values, so this oracle stays the
    ground truth for any replica — tests/test_faults.py re-pins the
    fleet-routed fuzz draws against it, and a fleet post-mortem is
    ``fleet.replica(fstate, i)`` diffed here like any single run.
    """

    def __init__(self, cfg: CommunityConfig, key_data) -> None:
        self.cfg = cfg
        self.seed = fold_seed(int(key_data[0]), int(key_data[1]))
        self.rnd = 0
        self.now = np.float32(0.0)
        self.peers = [OraclePeer(cfg) for _ in range(cfg.n_peers)]
        if cfg.store_stagger:
            # strided cohort assignment (state.init_state mirror)
            for i, p in enumerate(self.peers):
                p.cohort = cohort_of(cfg, i)
        # Gilbert–Elliott channel state (engine: PeerState.ge_bad) —
        # the link's property, surviving churn rebirth.
        self.ge_bad = [False] * cfg.n_peers
        # Telemetry plane (engine wrap-up mirror; dispersy_tpu/telemetry).
        # The streak is tracked unconditionally (cheap here) and exposed
        # zero-width when the histogram knob is off, like the device leaf.
        self.walk_streak = [0] * cfg.n_peers
        self.tele_row = np.zeros((tlm.row_width(cfg),), np.uint32)
        self.tele_ring = np.zeros(
            (cfg.telemetry.history, tlm.row_width(cfg)), np.uint32)
        self.fr_ring = np.zeros(
            (cfg.telemetry.flight_recorder, tlm.FLIGHT_WIDTH), np.uint32)
        self.fr_pos = 0
        # Dissemination-tracing plane (engine trace_member/trace_gt key
        # registry + trace_latch coverage percentiles;
        # dispersy_tpu/traceplane.py).
        t_w = cfg.trace.tracked_slots if cfg.trace.enabled else 0
        self.trace_member = [EMPTY_U32] * t_w
        self.trace_gt = [EMPTY_U32] * t_w
        self.trace_latch = [[0, 0, 0] for _ in range(t_w)]
        # Multi-community layout (engine._layout_cols mirror, same source).
        (self.community, self.boot_base, self.boot_count,
         self.mem_base, self.mem_count) = cfg.layout()

    def track_record(self, author: int, gt: int) -> int:
        """engine.track_record mirror: register (author, gt) into the
        first free tracked slot (idempotent) and stamp current holders'
        lineage as create-channel arrivals."""
        assert self.cfg.trace.enabled
        for k, (km, kg) in enumerate(zip(self.trace_member,
                                         self.trace_gt)):
            if km == author and kg == gt:
                return k
        free = [k for k, km in enumerate(self.trace_member)
                if km == EMPTY_U32]
        assert free, "all tracked slots taken"
        k = free[0]
        self.trace_member[k] = author
        self.trace_gt[k] = gt
        for p in self.peers:
            holds = any(r.member == author and r.gt == gt
                        for r in p.store) \
                or any(r.member == author and r.gt == gt
                       for r in p.staging)
            if holds and p.trace_first[k] == 0:
                p.trace_first[k] = self.rnd + 1
                p.trace_chan[k] = CH_CREATE
                p.trace_delivered[CH_CREATE - 1] += 1
        return k

    def _founder(self, owner: int) -> int:
        """The founder row the owner's community answers to
        (engine._founder_col mirror)."""
        if self.cfg.communities:
            return int(self.mem_base[owner])
        return self.cfg.founder

    def set_config(self, new_cfg: CommunityConfig) -> None:
        """Swap the static config mid-run (the SetFault shape) — the
        mirror of ``faults.adapt_state``: a knob flip that crosses a
        chaos subsystem's enablement boundary resets that subsystem's
        state (enabling starts clean, disabling discards the
        latch/counter/channel), everything else carries over.  A swap
        that stays on one side of every boundary is an identity."""
        of, nf = self.cfg.faults, new_cfg.faults
        if of.ge_enabled != nf.ge_enabled:
            self.ge_bad = [False] * new_cfg.n_peers
        if of.health_checks != nf.health_checks:
            for p in self.peers:
                p.health = 0
        if ((of.corrupt_rate > 0.0 or of.flood_enabled)
                != (nf.corrupt_rate > 0.0 or nf.flood_enabled)):
            for p in self.peers:
                p.msgs_corrupt_dropped = 0
        if self.cfg.recovery.enabled != new_cfg.recovery.enabled:
            # the SetRecovery shape — recovery.adapt_state mirror:
            # enabling starts clean, disabling discards.
            for p in self.peers:
                p.backoff = p.quar_until = p.repair_round = 0
                p.recov_soft = p.recov_backoff = 0
                p.recov_quarantine = 0
                p.recov_cleared = [0] * NUM_HEALTH_BITS
        if self.cfg.overload.enabled != new_cfg.overload.enabled:
            # the SetOverload shape — overload.adapt_state mirror:
            # enabling starts with empty buckets (the first round's
            # refill seeds them), disabling discards.
            for p in self.peers:
                p.bucket = 0
                p.msgs_shed_rate = p.msgs_shed_priority = 0
        if tlm.row_width(new_cfg) != tlm.row_width(self.cfg):
            # A recovery/overload flip changed the packed-row SCHEMA
            # (their words are conditional) — overload.
            # _resize_telemetry_rows mirror: row and ring reset to the
            # new width, all-zero ("no step has run yet").
            self.tele_row = np.zeros((tlm.row_width(new_cfg),),
                                     np.uint32)
            self.tele_ring = np.zeros(
                (new_cfg.telemetry.history, tlm.row_width(new_cfg)),
                np.uint32)
        self.cfg = new_cfg

    # ---- helpers mirroring ops/candidates.py --------------------------------

    def _category(self, s: Slot) -> int:
        cfg = self.cfg
        if s.peer == NO_PEER:
            return CAT_NONE
        if _f32(self.now - s.walk) < _f32(cfg.walk_lifetime):
            return CAT_WALKED
        if _f32(self.now - s.stumble) < _f32(cfg.walk_lifetime):
            return CAT_STUMBLED
        if _f32(self.now - s.intro) < _f32(cfg.intro_lifetime):
            return CAT_INTRODUCED
        return CAT_NONE

    def _eligible(self, s: Slot) -> bool:
        return (self._category(s) != CAT_NONE
                and _f32(self.now - s.walk) >= _f32(self.cfg.eligibility_delay))

    def _pick_by_priority(self, mask: list[bool], prio: list[int]) -> int:
        """argmax of (prio >> 1 | mask << 31), first max on ties."""
        best, best_score = -1, -1
        for i, (m, p) in enumerate(zip(mask, prio)):
            score = (p >> 1) | ((1 << 31) if m else 0)
            if score > best_score:
                best, best_score = i, score
        return best if any(mask) else -1

    def _upsert(self, owner: int, peer: int, kind: int) -> None:
        """upsert_many semantics for a single observation."""
        cfg = self.cfg
        if peer == NO_PEER or peer == owner or peer < cfg.n_trackers:
            return
        slots = self.peers[owner].slots
        # engine's upsert_many stamps EVERY slot matching the peer (there is
        # at most one by invariant, but mirror the kernel exactly)
        matches = [s for s in slots if s.peer == peer]
        if not matches:
            # least-recently-active victim, ties -> lowest index
            def activity(s: Slot) -> np.float32:
                if s.peer == NO_PEER:
                    return _NEVER_ACT
                return max(s.walk, s.stumble, s.intro)
            victim = min(slots, key=lambda s: (activity(s),))
            # min with ties -> first occurrence matches argmin
            victim.peer = peer
            victim.walk = victim.stumble = victim.intro = NEVER
            matches = [victim]
        for target in matches:
            if kind == KIND_WALK:
                target.walk = self._qts(self.now)
            elif kind == KIND_STUMBLE:
                target.stumble = self._qts(self.now)
            else:
                target.intro = self._qts(self.now)

    def _remove(self, owner: int, peer: int) -> None:
        for s in self.peers[owner].slots:
            if s.peer == peer:
                s.peer = NO_PEER
                s.walk = s.stumble = s.intro = NEVER

    def _cand_stamp(self, x) -> int:
        """engine._cand_quant for one value: the u16 round-stamp the
        leaf stores for sim-second ``x`` (0 = never; saturates into
        [1, 65535] — storediet.StoreConfig.cand_bits)."""
        if x == NEVER:
            return 0
        q = int(np.round(np.float32(x)
                         / np.float32(self.cfg.walk_interval))) + 1
        return min(max(q, 1), 65535)

    def _qts(self, x) -> np.float32:
        """Candidate-timestamp store round-trip (engine's wrap-up
        ``_cand_quant`` then next-round ``_cand_deq``): under
        cand_bits=16 every sim-second written to a slot passes through
        the u16 round-stamp on its way into the leaf, so the oracle
        saturates at each write exactly like the engine."""
        if self.cfg.store.cand_bits != 16:
            return x
        s = self._cand_stamp(x)
        if s == 0:
            return NEVER
        return _f32((np.float32(s) - np.float32(1.0))
                    * np.float32(self.cfg.walk_interval))

    def _sample_walk_target(self, i: int) -> int:
        cfg = self.cfg
        slots = self.peers[i].slots
        k = cfg.k_candidates
        prio = [rand_u32(self.seed, self.rnd, i, P_SLOT, j) for j in range(k)]
        elig = [self._eligible(s) for s in slots]
        cats = [self._category(s) for s in slots]
        picks = []
        for cat in (CAT_WALKED, CAT_STUMBLED, CAT_INTRODUCED):
            mask = [e and c == cat for e, c in zip(elig, cats)]
            j = self._pick_by_priority(mask, prio)
            picks.append(slots[j].peer if j >= 0 else NO_PEER)
        if cfg.n_trackers > 0:
            base = int(self.boot_base[i])
            cnt = int(self.boot_count[i])
            c = max(cnt, 1)
            tdraw = base + rand_u32(self.seed, self.rnd, i, P_BOOTSTRAP) % c
            if tdraw == i:
                tdraw = base + (tdraw - base + 1) % c
            picks.append(NO_PEER if (tdraw == i or cnt == 0) else tdraw)
        else:
            picks.append(NO_PEER)
        r = rand_uniform(self.seed, self.rnd, i, P_CATEGORY)
        if r < np.float32(cfg.p_revisit_walked):
            c0 = 0
        elif r < np.float32(cfg.p_revisit_walked + cfg.p_stumbled):
            c0 = 1
        elif r < np.float32(1.0 - cfg.p_bootstrap):
            c0 = 2
        else:
            c0 = 3
        for off in range(4):
            p = picks[(c0 + off) % 4]
            if p != NO_PEER:
                return p
        return NO_PEER

    def _recovery_walk_ok(self, i: int) -> bool:
        """Recovery-plane walk gates (engine phase 1: ops/recovery
        backoff_gate + quarantine_active): a backed-off peer walks one
        round in 2^backoff; a quarantined peer sits out until its
        release round."""
        rc = self.cfg.recovery
        if not rc.enabled:
            return True
        p = self.peers[i]
        if rc.backoff_limit > 0 \
                and (self.rnd & ((1 << p.backoff) - 1)) != 0:
            return False
        if rc.quarantine_rounds > 0 and self.rnd < p.quar_until:
            return False
        return True

    def _store_repair(self, owner: int) -> None:
        """Soft store repair (ops/recovery.store_repair mirror): stable
        re-sort by the canonical key, drop later (gt, member)
        duplicates, survivors compacted to the front."""
        p = self.peers[owner]
        p.store.sort(key=lambda r: (r.gt, r.member, r.meta, r.payload))
        out, seen = [], set()
        for r in p.store:
            if (r.gt, r.member) in seen:
                continue
            seen.add((r.gt, r.member))
            out.append(r)
        p.store = out

    def _admission_class(self, meta: int) -> int:
        """ops/overload.admission_class mirror (via the one scalar
        definition, overload.admission_class); 0 — pure arrival order —
        when priority admission is off."""
        cfg = self.cfg
        if not cfg.overload.priority_admission:
            return 0
        from dispersy_tpu.overload import admission_class
        return admission_class(meta, cfg.n_meta, cfg.priorities)

    def _nat_sym(self, peer: int) -> bool:
        """engine's ``nat_sym``/``sym_of`` mirror: symmetric-NAT iff the
        static round-0 draw says so; trackers and NO_PEER read public."""
        cfg = self.cfg
        if cfg.p_symmetric <= 0.0 or peer < cfg.n_trackers:
            return False
        return (rand_uniform(self.seed, 0, peer, P_NAT)
                < np.float32(cfg.p_symmetric))

    def _sample_intro(self, owner: int, slots: list[Slot], s_ix: int,
                      exclude: int, salt_base: int,
                      req_sym: bool = False) -> int:
        """sample_introductions for one (owner, request-slot);
        ``req_sym``: the requester is behind a symmetric NAT, so
        symmetric candidates are filtered (engine's req_sym/slot_sym)."""
        k = len(slots)
        mask, prio = [], []
        for j, s in enumerate(slots):
            cat = self._category(s)
            ok = (cat in (CAT_WALKED, CAT_STUMBLED)) and s.peer != exclude
            if ok and req_sym and self._nat_sym(s.peer):
                ok = False
            mask.append(ok)
            prio.append(rand_u32(self.seed, self.rnd, owner, P_INTRO,
                                 s_ix * k + j + salt_base))
        j = self._pick_by_priority(mask, prio)
        return slots[j].peer if j >= 0 else NO_PEER

    def _lost(self, peer: int, salt_base: int, salt: int) -> bool:
        """engine._lost mirror: base Bernoulli OR the Gilbert–Elliott
        state-dependent loss, independent counter streams."""
        cfg = self.cfg
        lost = False
        if cfg.packet_loss > 0.0:
            u = rand_uniform(self.seed, self.rnd, peer, P_LOSS,
                             salt + salt_base)
            lost = u < np.float32(cfg.packet_loss)
        fm = cfg.faults
        if fm.ge_enabled:
            pr = fm.ge_loss_bad if self.ge_bad[peer] else fm.ge_loss_good
            ug = rand_uniform(self.seed, self.rnd, peer, P_GE_LOSS,
                              salt + salt_base)
            lost = lost or (ug < np.float32(pr))
        return lost

    def _blocked(self, src: int, dst: int) -> bool:
        """ops/faults.partition_blocked mirror: is the directed edge
        severed by any static partition pair (both directions)?"""
        for (a_lo, a_hi), (b_lo, b_hi) in self.cfg.faults.partitions:
            src_a = a_lo <= src < a_hi
            src_b = b_lo <= src < b_hi
            dst_a = a_lo <= dst < a_hi
            dst_b = b_lo <= dst < b_hi
            if (src_a and dst_b) or (src_b and dst_a):
                return True
        return False

    # ---- store (ops/store.py mirror) ----------------------------------------

    def _aux_store(self, v: int) -> int:
        """Store-boundary aux truncation (config.aux_dtype): mask to u16
        under the byte-diet opt-in, identity otherwise — the astype in
        ops/store.store_insert/store_stage and the fwd-buffer narrowing
        in engine intake (wire/batch aux stays full-width u32)."""
        return v & 0xFFFF if self.cfg.store.aux_bits == 16 else v

    def _store_insert(self, owner: int, batch: list[Record],
                      count_drops: bool = True) -> None:
        """store_insert semantics: merge-sort, UNIQUE(member, gt) with the
        existing entry winning, capacity keeps lowest-sorting records.

        ``count_drops=False`` mirrors engine.create_messages, which folds
        only n_inserted into the stats (an author's own insert never counts
        as a drop there)."""
        p = self.peers[owner]
        m = self.cfg.msg_capacity
        n_before = len(p.store)
        n_new_valid = len(batch)
        for r in batch:
            # In place on purpose: create/malicious-gossip records are
            # buffered into p.fwd AFTER this call, and the engine's
            # forward buffer persists the narrowed store width too.
            r.aux = self._aux_store(r.aux)
        # (record_key, origin); sort by (gt, member, position-in-concat) —
        # the engine's keys (store rows precede batch rows, so a stable
        # sort on (gt, member, origin) IS position order).  Ties between
        # same-(gt, member) batch records resolve by DELIVERY order
        # (first-seen wins — the reference keeps the first-seen packet),
        # not by record content as before v8.
        rows = ([(r, 0) for r in p.store] + [(r, 1) for r in batch])
        rows.sort(key=lambda ro: (ro[0].gt, ro[0].member, ro[1]))
        kept: list[tuple[Record, int]] = []
        for r, o in rows:
            if kept and kept[-1][0].gt == r.gt and kept[-1][0].member == r.member:
                continue  # duplicate (gt, member): first (existing) wins
            kept.append((r, o))
        history = self.cfg.history
        if any(k > 0 for k in history):
            # LastSync keep-last-k per (member, meta), counted against the
            # post-dedup merged set (the engine's `newer` count).
            def k_of(meta: int) -> int:
                return history[meta] if meta < len(history) else 0

            def survives(r: Record) -> bool:
                k = k_of(r.meta)
                if k == 0:
                    return True
                newer = sum(1 for q, _ in kept
                            if q.member == r.member and q.meta == r.meta
                            and q.gt > r.gt)
                return newer < k
            kept = [(r, o) for r, o in kept if survives(r)]
        kept = kept[:m]
        p.store = [r for r, _ in kept]
        n_inserted = sum(1 for _, o in kept if o == 1)
        n_surviving_old = sum(1 for _, o in kept if o == 0)
        p.msgs_stored += n_inserted
        if count_drops:
            p.msgs_dropped += ((n_new_valid - n_inserted)
                               + (n_before - n_surviving_old))

    def _serve_order(self, store: list[Record]) -> list[Record]:
        """engine._response_order mirror: the responder's serving view."""
        cfg = self.cfg
        if not cfg.needs_response_order:
            return store
        nm = cfg.n_meta
        pr = cfg.priorities

        def key(r: Record):
            prio = priority_of(r.meta, nm, pr)
            desc = r.meta < nm and ((cfg.desc_meta_mask >> r.meta) & 1)
            k2 = (M32 - r.gt) if desc else r.gt
            return (255 - prio, k2, r.gt, r.member)
        return sorted(store, key=key)

    def _claim_slice(self, owner: int):
        """(time_low, time_high, modulo, offset) — claim_slice_largest/_modulo."""
        cfg = self.cfg
        store = self.peers[owner].store
        if cfg.sync_strategy == "modulo":
            n_valid = len(store)
            modulo = max((n_valid + cfg.bloom_capacity - 1) // cfg.bloom_capacity, 1)
            return 1, 0, modulo, self.rnd % modulo
        start = max(len(store) - cfg.bloom_capacity, 0)
        if start == 0:
            time_low = 1
        else:
            time_low = store[start].gt
        return time_low, 0, 1, 0

    def _in_slice(self, r: Record, sl) -> bool:
        tlow, thigh, mod, off = sl
        if r.gt < tlow:
            return False
        if thigh != 0 and r.gt > thigh:
            return False
        return (r.gt % max(mod, 1)) == off

    def _fold_gt(self, owner: int, seen: list[int]) -> None:
        p = self.peers[owner]
        rng_range = self.cfg.acceptable_global_time_range
        acceptable = [g for g in seen if g <= p.global_time + rng_range]
        if acceptable:
            p.global_time = max(p.global_time, max(acceptable))

    # ---- timeline (ops/timeline.py mirror) ----------------------------------

    def _auth_bit(self, owner: int, member: int, meta: int, gt: int,
                  perm: int) -> bool:
        """Latest-wins table test on bit (4*meta + perm) — tl.check /
        tl.check_grant's shared per-meta rule, WITHOUT the founder
        shortcut (callers compose founder-or-granted)."""
        if not 0 <= meta < MAX_TIMELINE_META:
            return False
        bit = 4 * meta + perm
        matches = [r for r in self.peers[owner].auth
                   if r.member == member and ((r.mask >> bit) & 1)
                   and r.gt <= gt]
        if not matches:
            return False
        best = max(r.gt for r in matches)
        at_best = [r for r in matches if r.gt == best]
        grant = any(not r.rev for r in at_best)
        revoke = any(r.rev for r in at_best)
        return grant and not revoke

    def _auth_check(self, owner: int, member: int, meta: int, gt: int,
                    perm: int = PERM_PERMIT) -> bool:
        """tl.check for one record vs one peer's table (founder included)."""
        if member == self._founder(owner):
            return True
        return self._auth_bit(owner, member, meta, gt, perm)

    def _grant_ok(self, owner: int, member: int, mask: int, gt: int,
                  perm: int = PERM_AUTHORIZE) -> bool:
        """tl.check_grant mirror: may ``member`` issue a grant/revoke
        covering nibble-``mask`` at ``gt``?  Every meta with a non-empty
        nibble needs the ``perm`` authority bit (PERM_AUTHORIZE for
        authorize records, PERM_REVOKE for revokes); an empty mask proves
        nothing."""
        if mask == 0:
            return False
        return all(self._auth_bit(owner, member, k, gt, perm)
                   for k in range(self.cfg.n_meta)
                   if (mask >> (4 * k)) & 0xF)

    def _undo_other_ok(self, owner: int, member: int, target: int,
                       target_gt: int, gt: int) -> bool:
        """Engine's undo_ok: founder, or the UNDO permission on the
        target record's meta, resolved from the owner's own store
        (ik.stored_meta_of; absent target -> refused this round)."""
        if member == self._founder(owner):
            return True
        tmeta = next((r.meta for r in self.peers[owner].store
                      if r.member == target and r.gt == target_gt
                      and r.meta < 32), None)
        if tmeta is None:
            return False
        return self._auth_bit(owner, member, tmeta, gt, PERM_UNDO)

    def _auth_fold(self, owner: int, target: int, mask: int, gt: int,
                   is_revoke: bool, issuer: int,
                   count: bool = True) -> bool:
        """tl.fold for one accepted authorize/revoke record.  Returns True
        when an existing row was EVICTED (the engine's retro trigger).

        Overflow keeps the top-A rows by (gt, member, mask, rev, issuer)
        — the deterministic window (tl.fold docstring): the arriving row
        replaces the minimum row in place when it keys above it, else it
        is dropped; either loss counts as msgs_dropped."""
        p = self.peers[owner]
        for r in p.auth:
            if (r.member == target and r.mask == mask and r.gt == gt
                    and r.rev == is_revoke and r.issuer == issuer):
                return False  # idempotent: row already folded
        if len(p.auth) < self.cfg.k_authorized:
            p.auth.append(AuthRow(target, mask, gt, is_revoke, issuer))
            return False

        def key(r):
            return (r.gt, r.member, r.mask, int(r.rev), r.issuer)
        mi = min(range(len(p.auth)), key=lambda j: key(p.auth[j]))
        newk = (int(gt), int(target), int(mask), int(bool(is_revoke)),
                int(issuer))
        if count:                  # a row is lost either way; the retro
            p.msgs_dropped += 1    # REBUILD's bookkeeping is not a loss
        if key(p.auth[mi]) < newk:
            p.auth[mi] = AuthRow(target, mask, gt, is_revoke, issuer)
            return True
        return False

    def _retro_pass(self, owner: int) -> None:
        """engine._retro_pass mirror: re-walk the table to its fixed point
        (tl.revalidate — k_authorized iterations, greatest-fixed-point,
        diagonal excluded), unwind failed rows, then retro-reject stored
        records whose authority is gone (control rows first, then
        protected user rows under the surviving flip set)."""
        cfg, p = self.cfg, self.peers[owner]
        f = self._founder(owner)
        # step 0: REBUILD the table from the store's control records in
        # store order (engine._retro_pass step 0 — the bounded window is
        # only order-independent as a pure function of the store);
        # rebuild bookkeeping is not counted as a loss
        gmask0 = user_perm_mask(cfg.n_meta)
        p.auth = []
        for r in p.store:
            if r.meta in (META_AUTHORIZE, META_REVOKE):
                self._auth_fold(owner, r.payload, r.aux & gmask0, r.gt,
                                r.meta == META_REVOKE, issuer=r.member,
                                count=False)
        rows = p.auth
        keep = self._revalidate_keep(owner, rows)
        p.auth_unwound += sum(1 for kk in keep if not kk)
        p.auth = [r for r, kk in zip(rows, keep) if kk]

        # stage 1: stored control records re-checked vs the cleaned table
        gmask = user_perm_mask(cfg.n_meta)
        survivors = []
        for r in p.store:
            if r.meta in (META_AUTHORIZE, META_REVOKE):
                perm = (PERM_REVOKE if r.meta == META_REVOKE
                        else PERM_AUTHORIZE)
                ok = (r.member == f
                      or self._grant_ok(owner, r.member, r.aux & gmask,
                                        r.gt, perm))
            elif cfg.dynamic_meta_mask and r.meta == META_DYNAMIC:
                ok = self._auth_check(owner, r.member, r.payload, r.gt,
                                      PERM_AUTHORIZE)
            else:
                ok = True
            if ok:
                survivors.append(r)
            else:
                p.msgs_retro += 1
        p.store = survivors

        # stage 2: protected user records under the surviving flip set
        survivors = []
        for r in p.store:
            prot = (r.meta < 32
                    and bool((cfg.protected_meta_mask >> min(r.meta, 31))
                             & 1))
            if (cfg.dynamic_meta_mask and r.meta < cfg.n_meta
                    and (cfg.dynamic_meta_mask >> r.meta) & 1):
                prot = self._linear_at(owner, r.meta, r.gt)
            ok = True
            if prot:
                ok = self._auth_check(owner, r.member, r.meta, r.gt)
                if ok and (cfg.double_meta_mask
                           & (cfg.protected_meta_mask
                              | cfg.dynamic_meta_mask)) \
                        and r.meta < cfg.n_meta \
                        and (cfg.double_meta_mask >> r.meta) & 1:
                    ok = self._auth_check(owner, r.aux, r.meta, r.gt)
            if ok:
                survivors.append(r)
            else:
                p.msgs_retro += 1
        p.store = survivors

        # stage 3: stored undo-other records — the undoer's UNDO grant
        # may be unwound, or the target retro-removed (resolved against
        # the post-stage-2 store, mirroring engine._retro_pass)
        survivors = []
        for r in p.store:
            if r.meta == META_UNDO_OTHER:
                ok = self._undo_other_ok(owner, r.member, r.payload,
                                         r.aux, r.gt)
            else:
                ok = True
            if ok:
                survivors.append(r)
            else:
                p.msgs_retro += 1
        p.store = survivors
        # undone marks are derived from SURVIVING undo records; removed
        # undos take their marks with them (revoke-first peers never
        # marked)
        undos = {(r.payload, r.aux) for r in p.store
                 if r.meta in (META_UNDO_OWN, META_UNDO_OTHER)}
        for r in p.store:
            if r.meta < 32:
                if (r.member, r.gt) in undos:
                    r.flags |= FLAG_UNDONE
                else:
                    r.flags &= ~FLAG_UNDONE
        # final rebuild from the POST-prune store (engine mirror): freed
        # window slots must be claimable by stored rows
        p.auth = []
        for r in p.store:
            if r.meta in (META_AUTHORIZE, META_REVOKE):
                self._auth_fold(owner, r.payload, r.aux & gmask0, r.gt,
                                r.meta == META_REVOKE, issuer=r.member,
                                count=False)
        rows = p.auth
        keep = self._revalidate_keep(owner, rows)
        p.auth = [r for r, kk in zip(rows, keep) if kk]

    def _revalidate_keep(self, owner: int, rows) -> list:
        """tl.revalidate mirror over ``rows`` (k_authorized iterations,
        greatest fixed point, diagonal excluded)."""
        cfg = self.cfg
        f = self._founder(owner)
        keep = [True] * len(rows)
        for _ in range(cfg.k_authorized):
            new_keep = []
            for ri, r in enumerate(rows):
                if r.issuer == f:
                    new_keep.append(True)
                    continue
                if r.mask == 0:
                    new_keep.append(False)
                    continue
                perm = PERM_REVOKE if r.rev else PERM_AUTHORIZE
                ok = True
                for k in range(cfg.n_meta):
                    if not (r.mask >> (4 * k)) & 0xF:
                        continue
                    sup = [s for si, s in enumerate(rows)
                           if keep[si] and si != ri
                           and s.member == r.issuer
                           and (s.mask >> (4 * k + perm)) & 1
                           and s.gt <= r.gt]
                    if not sup:
                        ok = False
                        break
                    best = max(s.gt for s in sup)
                    at_best = [s for s in sup if s.gt == best]
                    if not (any(not s.rev for s in at_best)
                            and not any(s.rev for s in at_best)):
                        ok = False
                        break
                new_keep.append(ok)
            keep = new_keep
        return keep

    def _has_identity(self, owner: int, member: int) -> bool:
        """ik.identity_stored for one member vs one peer's store."""
        return any(r.meta == META_IDENTITY and r.member == member
                   for r in self.peers[owner].store)

    def _id_ok(self, owner: int, rec: Record) -> bool:
        """Engine's identity_required gate: USER records need the
        author's (and, double-signed, the countersigner's) stored
        dispersy-identity record; control records are exempt."""
        cfg = self.cfg
        if not cfg.identity_required or not rec.meta < cfg.n_meta:
            return True
        ok = self._has_identity(owner, rec.member)
        if ok and cfg.double_meta_mask \
                and (cfg.double_meta_mask >> rec.meta) & 1:
            ok = self._has_identity(owner, rec.aux)
        return ok

    def _dbl_struct_ok(self, owner: int, rec: Record) -> bool:
        """Engine's structural countersigner check (phase 5): for a
        double-signed meta, ``aux`` must name a real, distinct member of
        the receiver's community.  True for every other meta."""
        cfg = self.cfg
        if not (rec.meta < cfg.n_meta
                and (cfg.double_meta_mask >> rec.meta) & 1):
            return True
        base = int(self.mem_base[owner])
        cnt = int(self.mem_count[owner])
        return rec.aux != rec.member and base <= rec.aux < base + cnt

    def _linear_at(self, owner: int, meta: int, gt: int,
                   batch_flips=()) -> bool:
        """Resolution policy for ``meta`` at ``gt``: the highest-gt
        dynamic-settings flip at or below it (store + this batch's fresh
        accepted flips), defaulting to the static protected bit (engine's
        gt*2|policy key-max)."""
        cfg = self.cfg
        linear = bool((cfg.protected_meta_mask >> meta) & 1)
        if not (meta < cfg.n_meta and (cfg.dynamic_meta_mask >> meta) & 1):
            return linear
        best = 0
        for r in self.peers[owner].store:
            if (r.meta == META_DYNAMIC and r.payload == meta
                    and r.gt <= gt):
                best = max(best, r.gt * 2 + (r.aux & 1))
        for fgt, ftarget, faux in batch_flips:
            if ftarget == meta and fgt <= gt:
                best = max(best, fgt * 2 + (faux & 1))
        return bool(best & 1) if best > 0 else linear

    def _intake_accept(self, owner: int, rec: Record,
                       batch_flips=(), deleg_ok: bool = False) -> bool:
        """The engine's timeline accept mask for one in_ok record.  Pure:
        the batch's fresh authorize/revoke records must already be folded
        (the engine folds the whole batch before any check runs);
        ``deleg_ok`` is this record's precomputed pass-B chain verdict
        (engine: ``ctrl_ok = ctrl_ok0 | deleg_ok``, evaluated against the
        post-pass-A table snapshot)."""
        cfg = self.cfg
        if not cfg.timeline_enabled:
            return True
        m = rec.meta
        if m in (META_AUTHORIZE, META_REVOKE):
            return rec.member == self._founder(owner) or deleg_ok
        if m == META_UNDO_OTHER:
            return self._undo_other_ok(owner, rec.member, rec.payload,
                                       rec.aux, rec.gt)
        if m == META_DYNAMIC:
            # Engine's flip_grant_ok: founder, or the AUTHORIZE authority
            # on the flipped meta.
            return self._auth_check(owner, rec.member, rec.payload,
                                    rec.gt, PERM_AUTHORIZE)
        if m == META_DESTROY:
            return rec.member == self._founder(owner)
        if m == META_UNDO_OWN:
            return rec.member == rec.payload
        if m < 32 and self._linear_at(owner, m, rec.gt, batch_flips):
            ok = self._auth_check(owner, rec.member, m, rec.gt)
            if (m < cfg.n_meta and (cfg.double_meta_mask >> m) & 1):
                # Both signers need the permit (engine mirrors
                # Timeline.check over every authentication member).
                ok = ok and self._auth_check(owner, rec.aux, m, rec.gt)
            return ok
        return True

    # ---- setup mirrors ------------------------------------------------------

    def create_messages(self, author_mask, meta: int, payload,
                        aux=None) -> None:
        """engine.create_messages mirror (incl. the timeline author gate)."""
        cfg = self.cfg
        assert not (meta < cfg.n_meta and (cfg.double_meta_mask >> meta) & 1), \
            "double-signed metas go through create_signature_request"
        created_rev = False
        for i, p in enumerate(self.peers):
            if not author_mask[i] or not p.loaded:
                continue          # engine: author_mask &= state.loaded
            gt = p.global_time + 1
            av = int(aux[i]) if aux is not None else 0
            pv = int(payload[i])
            if cfg.timeline_enabled:
                if any(r.meta == META_DESTROY for r in p.store):
                    continue          # hard-killed: community unloaded
                if meta in (META_AUTHORIZE, META_REVOKE):
                    if (i != self._founder(i)
                            and not self._grant_ok(
                                i, i, av & user_perm_mask(cfg.n_meta), gt,
                                PERM_REVOKE if meta == META_REVOKE
                                else PERM_AUTHORIZE)):
                        continue
                elif meta == META_UNDO_OTHER:
                    if not self._undo_other_ok(i, i, pv, av, gt):
                        continue
                elif meta == META_DYNAMIC:
                    if not self._auth_check(i, i, pv, gt, PERM_AUTHORIZE):
                        continue
                elif meta == META_DESTROY:
                    if i != self._founder(i):
                        continue
                elif meta == META_UNDO_OWN:
                    if pv != i:
                        continue
                elif (meta < cfg.n_meta
                      and (cfg.dynamic_meta_mask >> meta) & 1):
                    if (self._linear_at(i, meta, gt)
                            and not self._auth_check(i, i, meta, gt)):
                        continue
                elif meta < 32 and (cfg.protected_meta_mask >> meta) & 1:
                    if not self._auth_check(i, i, meta, gt):
                        continue
            if meta < cfg.n_meta and (cfg.seq_meta_mask >> meta) & 1:
                av = max((r.aux for r in p.store
                          if r.member == i and r.meta == meta), default=0) + 1
            rec = Record(gt, i, meta, pv, av)
            if not (meta < cfg.n_meta and (cfg.direct_meta_mask >> meta) & 1):
                if cfg.trace.enabled:
                    # engine create_messages' lineage stamp: a created
                    # record matching a pre-registered tracked key is a
                    # create-channel arrival (capacity drops still
                    # count — arrival history, traceplane.py).
                    for k, (km, kg) in enumerate(zip(self.trace_member,
                                                     self.trace_gt)):
                        if (km == i and kg == gt
                                and p.trace_first[k] == 0):
                            p.trace_first[k] = self.rnd + 1
                            p.trace_chan[k] = CH_CREATE
                            p.trace_delivered[CH_CREATE - 1] += 1
                self._store_insert(i, [rec], count_drops=False)
                if p.digest is not None:
                    # Byte-diet: the digest learns the authored record
                    # under the CURRENT epoch's salt, store_mask-wide —
                    # engine create_messages' digest_update mirror
                    # (under staggering: the author's own epoch leaf).
                    p.digest.salt = (p.epoch if cfg.store_stagger
                                     else epoch_of(cfg, self.rnd))
                    p.digest.add(rec.hash())
            if cfg.timeline_enabled and meta in (META_AUTHORIZE, META_REVOKE):
                ev = self._auth_fold(i, pv, av & user_perm_mask(cfg.n_meta),
                                     gt, meta == META_REVOKE, issuer=i)
                created_rev = created_rev or meta == META_REVOKE or ev
            if cfg.timeline_enabled and meta in (META_UNDO_OWN,
                                                 META_UNDO_OTHER):
                for r in p.store:
                    if r.member == pv and r.gt == av and r.meta < 32:
                        r.flags |= FLAG_UNDONE
            if len(p.fwd) < cfg.forward_buffer:
                p.fwd.append(rec.copy())
            elif cfg.forward_buffer > 0:
                # own creation displaces the newest relayed entry (engine:
                # create_messages always buffers at min(fslot, F-1))
                p.fwd[cfg.forward_buffer - 1] = rec.copy()
            p.global_time = gt
            p.accepted_by_meta[min(meta, cfg.n_meta)] += 1
        if created_rev:
            # engine: a self-created revoke can pre-date table rows learned
            # from faster peers — same global-trigger re-walk as the intake
            for i in range(cfg.n_peers):
                self._retro_pass(i)

    def create_signature_request(self, author_mask, meta: int, counterparty,
                                 payload) -> None:
        """engine.create_signature_request mirror."""
        cfg = self.cfg
        assert meta < cfg.n_meta and (cfg.double_meta_mask >> meta) & 1
        for i, p in enumerate(self.peers):
            if not author_mask[i]:
                continue
            cp = int(counterparty[i])
            base = int(self.mem_base[i])
            cnt = int(self.mem_count[i])
            gt_new = p.global_time + 1
            if not (p.alive and p.loaded and i >= cfg.n_trackers
                    and p.sig_target == NO_PEER and cp != i
                    and base <= cp < base + cnt):
                continue
            if cfg.timeline_enabled and any(
                    r.meta == META_DESTROY for r in p.store):
                continue
            if (cfg.timeline_enabled
                    and self._linear_at(i, meta, gt_new)
                    and not self._auth_check(i, i, meta, gt_new)):
                continue
            p.sig_target = cp
            p.sig_meta = meta
            p.sig_payload = int(payload[i])
            p.sig_gt = gt_new
            p.sig_since = self.rnd
            p.global_time = gt_new

    def seed_overlay(self, degree: int) -> None:
        """engine.seed_overlay mirror (per-community member blocks)."""
        cfg = self.cfg
        # Under cand_bits=16 the pre-epoch stamp saturates to round 0
        # (sim-second 0.0) — the documented narrowing degradation
        # (storediet.StoreConfig.cand_bits), mirrored via _qts.
        eligible_at = self._qts(
            _f32(np.float32(0.0) - np.float32(cfg.eligibility_delay)))
        for i, p in enumerate(self.peers):
            base = int(self.mem_base[i])
            span = max(int(self.mem_count[i]), 1)
            seen: set[int] = set()
            for j in range(degree):
                nbr = base + rand_u32(self.seed, 0xE1, i, P_GOSSIP, j) % span
                if nbr == i:
                    nbr = base + (nbr - base + 1) % span
                if nbr in seen:   # one slot per neighbor (engine dedup)
                    continue
                seen.add(nbr)
                s = p.slots[j]
                s.peer = nbr
                s.walk = eligible_at
                s.stumble = s.intro = NEVER

    def unload(self, members) -> None:
        """engine.unload_members mirror (Community.unload_community):
        loaded off, community-instance memory (candidate slots, delay
        pen, sig cache, forward batch, convictions) freed, store kept;
        tracker rows excluded — TrackerCommunity has no unload path
        (tool/tracker.py)."""
        cfg = self.cfg
        for i in members:
            if i < cfg.n_trackers:
                continue
            p = self.peers[i]
            p.loaded = False
            p.slots = [Slot() for _ in range(cfg.k_candidates)]
            p.delay = []
            p.fwd = []
            p.mal = []
            p.sig_target = NO_PEER
            p.sig_meta = p.sig_payload = p.sig_gt = p.sig_since = 0

    def load(self, members) -> None:
        """scenario.Load mirror (Community.load_community)."""
        for i in members:
            self.peers[i].loaded = True

    # ---- the round ----------------------------------------------------------

    def step(self) -> None:
        cfg = self.cfg
        n, t = cfg.n_peers, cfg.n_trackers
        r = cfg.request_inbox
        rt = cfg.tracker_inbox
        seed, rnd = self.seed, self.rnd
        fm = cfg.faults
        # Byte-diet cadence (engine._step_impl's diet/sync_on/compact_now
        # — dispersy_tpu/storediet.py): quiet rounds stage arrivals and
        # update the digest; sync rounds run the claim/serve exchange
        # and compact the staging into the ring.
        diet = cfg.store_diet
        stagger = stagger_of(cfg)
        sync_round = sync_round_of(cfg, rnd) if diet else True
        ep = epoch_of(cfg, rnd)
        sync_on = cfg.sync_enabled and sync_round
        compact_now = diet and sync_round
        # Cohort staggering (engine stagger/a_coh/ep_a): on a sync round
        # exactly one cohort runs the claim/serve/compact path; its
        # bloom salt is its own epoch (== every member's epoch leaf by
        # the round-start invariant).
        a_coh = active_cohort(cfg, rnd) if (stagger and sync_round) else 0
        ep_a = epoch_of_cohort(cfg, rnd, a_coh) if stagger else ep
        # community packets seen by each peer this round (auto-load
        # trigger — engine `arrivals`)
        arrivals = [False] * n

        # Gilbert–Elliott channel advance (engine: flt.ge_advance at the
        # top of step — this round's loss draws see the new state).
        if fm.ge_enabled:
            for i in range(n):
                u = rand_uniform(seed, rnd, i, P_GE)
                if self.ge_bad[i]:
                    self.ge_bad[i] = not (u < np.float32(fm.ge_p_good))
                else:
                    self.ge_bad[i] = u < np.float32(fm.ge_p_bad)
        if fm.health_checks:
            # Round-start counter snapshots for the wrap / drop sentinels.
            bu0 = [p.bytes_up & M32 for p in self.peers]
            bd0 = [p.bytes_down & M32 for p in self.peers]
        if fm.health_checks or cfg.telemetry.histograms:
            # Shared with the telemetry round_drops histogram (engine rd0).
            rd0 = [p.requests_dropped + p.msgs_dropped
                   for p in self.peers]

        # phase 0: churn
        if cfg.churn_rate > 0.0:
            for i, p in enumerate(self.peers):
                if (p.alive and i >= t
                        and rand_uniform(seed, rnd, i, P_CHURN)
                        < np.float32(cfg.churn_rate)):
                    p.slots = [Slot() for _ in range(cfg.k_candidates)]
                    p.store = []
                    p.staging = []
                    if p.digest is not None:
                        p.digest = OracleBloom(cfg.bloom_bits,
                                               cfg.bloom_hashes)
                    p.fwd = []
                    p.auth = []
                    p.delay = []
                    if stagger:
                        # the epoch wipes with the store and is
                        # immediately re-derived from the shared round
                        # counter (engine phase 0) — a value identity
                        # with the round-start invariant, kept explicit
                        # for the documented rebirth semantics
                        p.epoch = epoch_of_cohort(cfg, rnd, p.cohort)
                    p.sig_target = NO_PEER
                    p.sig_meta = p.sig_payload = p.sig_gt = p.sig_since = 0
                    p.mal = []
                    if cfg.trace.enabled:
                        # lineage wipes with the store (traceplane.py)
                        t_w = cfg.trace.tracked_slots
                        p.trace_first = [0] * t_w
                        p.trace_chan = [0] * t_w
                        p.trace_dups = [0] * t_w
                    p.global_time = 1
                    p.session += 1
                    # rebirth = new participant; its join IS an explicit
                    # load, auto_load notwithstanding (engine.unload_members)
                    p.loaded = True
                    if fm.health_checks:
                        # wiped-disk restart: clean health latch (the GE
                        # channel is the LINK's and survives)
                        p.health = 0
                    if cfg.recovery.enabled:
                        # rebirth resets the PROCESS-memory recovery
                        # state; the quarantine ostracism is the
                        # OVERLAY's and survives (engine phase 0)
                        p.backoff = 0
                        p.repair_round = 0

        # hard-kill state (engine mirror: derived from the post-churn store)
        if cfg.timeline_enabled:
            killed = [any(r.meta == META_DESTROY for r in p.store)
                      for p in self.peers]
        else:
            killed = [False] * n

        # phase 1: walker send + sync claim
        targets = [NO_PEER] * n
        if cfg.walker_enabled:
            for i, p in enumerate(self.peers):
                if p.alive and p.loaded and i >= t and not killed[i] \
                        and self._recovery_walk_ok(i):
                    targets[i] = self._sample_walk_target(i)

        slices, blooms = [None] * n, [None] * n
        if sync_on and stagger:
            # Cohort-staggered claim: only the ACTIVE cohort walks with
            # a sync tuple this round; its digest salt is the cohort's
            # epoch ep_a (== each member's own epoch leaf by the
            # round-start invariant).  The engine's digest-serve
            # responder gathers the requester's slice and digest at the
            # block during serve — the ring is unchanged until
            # compaction, so claiming here is equivalent.  Non-active
            # peers keep (None, None): their requests carry no claim
            # and the serve below skips them.
            for i, p in enumerate(self.peers):
                if p.cohort == a_coh:
                    p.digest.salt = ep_a
                    slices[i], blooms[i] = self._claim_slice(i), p.digest
        elif sync_on and diet:
            # Byte-diet claim: the slice is the ring's largest-window
            # (ring unchanged since the last compaction) and the bloom
            # is the persistent digest under the epoch salt — no
            # per-round rebuild (engine's my_bloom = dig).
            for i, p in enumerate(self.peers):
                p.digest.salt = ep
                slices[i], blooms[i] = self._claim_slice(i), p.digest
        elif sync_on:
            for i, p in enumerate(self.peers):
                sl = self._claim_slice(i)
                # Per-round salt = the per-claim filter prefix (engine
                # passes salt=rnd to bloom_build/bloom_query).
                bloom = OracleBloom(cfg.bloom_bits, cfg.bloom_hashes,
                                    salt=rnd)
                for rec in p.store:
                    if self._in_slice(rec, sl):
                        bloom.add(rec.hash())
                slices[i], blooms[i] = sl, bloom

        # byte-equivalent sizes (engine mirror).  Under staggering only
        # the active cohort's walkers carry the sync tuple — req_bytes
        # becomes per-SENDER (engine's req_bytes vector); responders
        # charge each accepted request's own size below.
        full_req = INTRO_REQUEST_BASE_BYTES + 4 * (cfg.bloom_bits // 32)
        if stagger and sync_on:
            req_bytes_of = [full_req if p.cohort == a_coh
                            else INTRO_REQUEST_BASE_BYTES - 20
                            for p in self.peers]
        else:
            req_bytes_of = [full_req if sync_on
                            else INTRO_REQUEST_BASE_BYTES - 20] * n

        send_ok = [False] * n
        for i in range(n):
            if self.peers[i].alive and targets[i] != NO_PEER:
                self.peers[i].bytes_up += req_bytes_of[i]    # sendto, pre-loss
            send_ok[i] = (self.peers[i].alive and targets[i] != NO_PEER
                          and not self._lost(i, _LOSS_REQUEST, 0)
                          and not self._blocked(i, targets[i]))

        # phase 1f: push forwarding (engine phase 1f — last round's fresh
        # records to forward_fanout distinct verified candidates, targets
        # sampled from the pre-stumble candidate table)
        # entries are (record, sender, is_junk) — the sender is the pen's
        # missing-proof target should the record park (engine ph_src);
        # is_junk marks byzantine flood packets, which always fail the
        # intake hash re-check (engine ph_junk)
        push_inbox: list[list[tuple[Record, int, bool]]] = \
            [[] for _ in range(n)]
        # Ingress protection (engine phase 1f overload blocks;
        # dispersy_tpu/overload.py): per-sender credits refill and every
        # attempted push/flood packet consumes one ordinal — beyond the
        # balance the packet sheds at the SENDER (msgs_shed_rate) and
        # never reaches any inbox.  Delivered packets collect per victim
        # and the bounded inbox admits them lowest-admission-class-first
        # ((cls, pos) — the engine's class-aware delivery sort), excess
        # shedding to the RECEIVER's msgs_shed_priority instead of
        # msgs_dropped.
        ovc = cfg.overload
        ov_on = ovc.enabled and (cfg.forward_fanout > 0
                                 or fm.flood_enabled)
        # Every SENT push/flood packet collects here as
        # (pos, cls, record, sender, dst, junk) — pos is the engine's
        # flat edge-list position (forward segment i*F*C + fi*C + ci,
        # flood segment appended after), cls the admission class (0
        # when priority admission is off — pure arrival order).  The
        # cross-shard exchange cap and the inbox admission both run
        # over this list AFTER enumeration, because the cap keeps
        # bucket winners by (dst, cls, pos) — a later edge with a
        # smaller destination can displace an earlier one, so shedding
        # cannot be decided inline.
        push_edges: list[tuple] = []
        if ov_on:
            ratef = np.float32(ovc.bucket_rate)
            whole = int(np.floor(ratef))
            frac = np.float32(ratef - np.float32(whole))
            credit = [0] * n
            for i, p in enumerate(self.peers):
                u = rand_uniform(seed, rnd, i, P_OVERLOAD)
                extra = 1 if u < frac else 0
                credit[i] = min(p.bucket + whole + extra,
                                ovc.bucket_depth)
            att_count = [0] * n
            # per-victim pending deliveries: (cls, record, sender, junk)
            push_pend: list[list] = [[] for _ in range(n)]
        if cfg.forward_fanout > 0:
            cc = cfg.forward_fanout
            k = cfg.k_candidates
            for i, p in enumerate(self.peers):
                score = []
                for j, s in enumerate(p.slots):
                    ver = self._category(s) in (CAT_WALKED, CAT_STUMBLED)
                    pr = rand_u32(seed, rnd, i, P_GOSSIP, j + (1 << 8))
                    score.append(((pr >> 1) | ((1 << 31) if ver else 0), ver))
                order = sorted(range(k), key=lambda j: (-score[j][0], j))[:cc]
                tgts = [p.slots[j].peer if score[j][1] else NO_PEER
                        for j in order]
                sent = 0
                for fi, rec in enumerate(p.fwd):
                    # killed peers push only destroy records (engine
                    # send_rec_ok)
                    rec_ok = not killed[i] or rec.meta == META_DESTROY
                    for ci, tc in enumerate(tgts):
                        if p.alive and p.loaded and rec_ok \
                                and tc != NO_PEER:
                            p.bytes_up += RECORD_BYTES       # pre-loss
                            if ov_on:
                                o = att_count[i]
                                att_count[i] += 1
                                if o >= credit[i]:
                                    # rate-gate shed, attributed to the
                                    # sender (loss-independent)
                                    p.msgs_shed_rate += 1
                                    continue
                            if not self._lost(i, _LOSS_FORWARD,
                                              fi * cc + ci) \
                                    and not self._blocked(i, tc):
                                sent += 1
                                push_edges.append(
                                    ((i * cfg.forward_buffer + fi) * cc
                                     + ci,
                                     self._admission_class(rec.meta),
                                     rec, i, tc, False))
                p.msgs_forwarded += sent
        if fm.flood_enabled:
            # Byzantine junk blast (engine phase 1f flood segment): junk
            # edges append AFTER every real push edge, so inbox slot
            # order matches the fused delivery sort exactly.  Under the
            # overload plane the blasts spend the SAME bucket, ordinals
            # continuing after the flooder's real-push attempts.
            ff = fm.flood_fanout
            fbase = (n * cfg.forward_buffer * cfg.forward_fanout
                     if cfg.forward_fanout > 0 else 0)
            for fs_ix, fs in enumerate(fm.flood_senders):
                fp = self.peers[fs]
                if fp.alive:
                    # the flooder's NIC moves every blast, pre-loss
                    fp.bytes_up += ff * RECORD_BYTES
                for j in range(ff):
                    victim = t + rand_u32(seed, rnd, fs, P_FLOOD, j) \
                        % (n - t)
                    if not fp.alive:
                        continue
                    if ov_on:
                        o = att_count[fs]
                        att_count[fs] += 1
                        if o >= credit[fs]:
                            fp.msgs_shed_rate += 1
                            continue
                    if self._lost(fs, _LOSS_FLOOD, j):
                        continue
                    if self._blocked(fs, victim):
                        continue
                    rec = Record(
                        rand_u32(seed, rnd, fs, P_FLOOD, j + (1 << 12)),
                        rand_u32(seed, rnd, fs, P_FLOOD, j + (2 << 12)),
                        rand_u32(seed, rnd, fs, P_FLOOD,
                                 j + (3 << 12)) & 0xFF,
                        rand_u32(seed, rnd, fs, P_FLOOD, j + (4 << 12)),
                        rand_u32(seed, rnd, fs, P_FLOOD, j + (5 << 12)))
                    push_edges.append(
                        (fbase + fs_ix * ff + j,
                         self._admission_class(rec.meta), rec, fs,
                         victim, True))
        pp = cfg.parallel
        if pp.shards > 1 and pp.cross_shard_budget > 0 and push_edges:
            # Ragged-exchange cap mirror (engine _deliver capped=True;
            # ops/inbox.deliver_ragged): the edge list pads to
            # `shards` rows of ceil(E/S) positions; each (source row,
            # destination shard) send bucket keeps the first
            # `cross_shard_budget` edges in the kernel's bucket sort
            # order (dst, cls, pos), the rest shed IN the exchange —
            # bytes_up already paid, never reaching any inbox, counted
            # at the SENDER (stats.xshard_shed backpressure, the
            # store_stage bounded-inbox idiom).
            etot = fbase if fm.flood_enabled else (
                n * cfg.forward_buffer * cfg.forward_fanout)
            if fm.flood_enabled:
                etot += len(fm.flood_senders) * ff
            el = -(-etot // pp.shards)
            nl = n // pp.shards
            kept: list[tuple] = []
            bucket_fill: dict[tuple[int, int], int] = {}
            for e in sorted(push_edges,
                            key=lambda e: (e[4], e[1], e[0])):
                bkt = (e[0] // el, e[4] // nl)
                if bucket_fill.get(bkt, 0) < pp.cross_shard_budget:
                    bucket_fill[bkt] = bucket_fill.get(bkt, 0) + 1
                    kept.append(e)
                else:
                    self.peers[e[3]].xshard_shed += 1
            push_edges = sorted(kept, key=lambda e: e[0])
        if not ov_on:
            # unbounded-rate path: first-come (edge-position) admission
            # into the bounded push inbox, overflow to the RECEIVER's
            # msgs_dropped
            for _, _, rec, src, dst, junk in push_edges:
                if len(push_inbox[dst]) < cfg.push_inbox:
                    push_inbox[dst].append((rec, src, junk))
                    if not junk:
                        # junk never decodes: no auto-load arrival
                        arrivals[dst] = True
                    qv = self.peers[dst]
                    if qv.alive and qv.loaded:
                        qv.bytes_down += RECORD_BYTES
                else:
                    self.peers[dst].msgs_dropped += 1
        else:
            for _, cls_, rec, src, dst, junk in push_edges:
                push_pend[dst].append((cls_, rec, src, junk))
            # Priority admission + flood-fair attribution: per victim,
            # the inbox admits the lowest-class packets (ties by edge
            # position — the pend list is already in global edge order,
            # so a stable sort on class alone mirrors the engine's
            # packed (dst, cls, pos) key); overflow sheds to
            # msgs_shed_priority, which never feeds health_drop_limit.
            for v in range(n):
                pend = push_pend[v]
                order2 = sorted(range(len(pend)),
                                key=lambda ti: (pend[ti][0], ti))
                for t_ix in order2[:cfg.push_inbox]:
                    _, rec, src, junk = pend[t_ix]
                    push_inbox[v].append((rec, src, junk))
                    if not junk:
                        arrivals[v] = True
                    qv = self.peers[v]
                    if qv.alive and qv.loaded:
                        qv.bytes_down += RECORD_BYTES
                self.peers[v].msgs_shed_priority += max(
                    len(pend) - cfg.push_inbox, 0)
            # Spend: in-budget attempts drain the balance; refill
            # happens at the next round's credit computation.
            for i, p in enumerate(self.peers):
                p.bucket = credit[i] - min(att_count[i], credit[i])

        # request delivery (normal peers): edge order = sender order
        req_inbox: list[list[int]] = [[] for _ in range(n)]   # sender ids
        req_slot = [-1] * n                                    # sender's receipt
        for i in range(n):
            d = targets[i]
            if send_ok[i] and not (0 <= d < t):
                if len(req_inbox[d]) < r:
                    req_slot[i] = len(req_inbox[d])
                    req_inbox[d].append(i)
                else:
                    self.peers[d].requests_dropped += 1
        # rq_ok also requires the *receiver* participating (act)
        for d, box in enumerate(req_inbox):
            if box:
                arrivals[d] = True
        rq_ok = [[self.peers[d].alive and self.peers[d].loaded
                  for _ in box]
                 for d, box in enumerate(req_inbox)]
        tele_nrq = [0] * n     # telemetry req_inbox histogram (engine n_rq)
        for d in range(n):
            n_rq = sum(rq_ok[d])
            tele_nrq[d] = n_rq
            # handled requests: request bytes in (each request's own
            # per-sender size), one response each out
            self.peers[d].bytes_down += sum(
                req_bytes_of[src] for s_ix, src in enumerate(req_inbox[d])
                if rq_ok[d][s_ix])
            self.peers[d].bytes_up += n_rq * INTRO_RESPONSE_BYTES

        # snapshot sender clocks as they rode the request packet
        req_gt = {i: self.peers[i].global_time for i in range(n)}

        # phase 2: stumble + clock fold at the responder
        for d in range(n):
            for s_ix, src in enumerate(req_inbox[d]):
                if rq_ok[d][s_ix]:
                    self._upsert(d, src, KIND_STUMBLE)
            self._fold_gt(d, [req_gt[src] for s_ix, src in enumerate(req_inbox[d])
                              if rq_ok[d][s_ix]])

        # phase 2t: tracker fast path
        tq_inbox: list[list[int]] = [[] for _ in range(t)]
        tq_slot = [-1] * n
        intro_t: list[list[int]] = [[] for _ in range(t)]
        if t > 0:
            for i in range(n):
                d = targets[i]
                if send_ok[i] and 0 <= d < t:
                    if len(tq_inbox[d]) < rt:
                        tq_slot[i] = len(tq_inbox[d])
                        tq_inbox[d].append(i)
                    else:
                        self.peers[d].requests_dropped += 1
            tq_ok = [[self.peers[d].alive and self.peers[d].loaded
                      for _ in box]
                     for d, box in enumerate(tq_inbox)]
            k = cfg.k_candidates
            kr = min(rt, k)
            for d in range(t):
                ring_slots = [((rnd * rt + j) % k) for j in range(kr)]
                ring_src = [tq_inbox[d][j] if j < len(tq_inbox[d]) and tq_ok[d][j]
                            else NO_PEER for j in range(kr)]
                # stale clearing: returning requester's old entry wiped first
                fresh = {s for s in ring_src if s != NO_PEER}
                for s in self.peers[d].slots:
                    if s.peer in fresh:
                        s.peer = NO_PEER
                        s.walk = s.stumble = s.intro = NEVER
                for slot_ix, src in zip(ring_slots, ring_src):
                    if src != NO_PEER:
                        s = self.peers[d].slots[slot_ix]
                        s.peer = src
                        s.walk = s.intro = NEVER
                        s.stumble = self._qts(self.now)
                # introduction picks for each served request
                for s_ix, src in enumerate(tq_inbox[d]):
                    src_m = src if tq_ok[d][s_ix] else NO_PEER
                    ring_pick = self._sample_intro(
                        d, self.peers[d].slots, s_ix, src, _TRACKER_INTRO_SALT,
                        req_sym=self._nat_sym(src_m))
                    if rt > 1:
                        j = ((s_ix + 1 + rand_u32(seed, rnd, d, P_INTRO,
                                                  s_ix + _TRACKER_INTRO_SALT
                                                  + (1 << 18))
                              % (rt - 1)) % rt)
                    else:
                        j = 0
                    inbox_pick = (tq_inbox[d][j]
                                  if j < len(tq_inbox[d]) and tq_ok[d][j]
                                  else NO_PEER)
                    if inbox_pick == src:
                        inbox_pick = NO_PEER
                    if (inbox_pick != NO_PEER and self._nat_sym(src_m)
                            and self._nat_sym(inbox_pick)):
                        # never pair two symmetric-NAT requesters (engine's
                        # inbox-introduction NAT filter)
                        inbox_pick = NO_PEER
                    intro_t[d].append(inbox_pick if inbox_pick != NO_PEER
                                      else ring_pick)
                self._fold_gt(d, [req_gt[src] for s_ix, src in
                                  enumerate(tq_inbox[d]) if tq_ok[d][s_ix]])
                n_tq = sum(tq_ok[d])
                self.peers[d].bytes_down += sum(
                    req_bytes_of[src] for s_ix, src in
                    enumerate(tq_inbox[d]) if tq_ok[d][s_ix])
                self.peers[d].bytes_up += (
                    n_tq * INTRO_RESPONSE_BYTES
                    + sum(1 for s_ix in range(len(tq_inbox[d]))
                          if tq_ok[d][s_ix] and intro_t[d][s_ix] != NO_PEER)
                    * PUNCTURE_REQUEST_BYTES)

        # introduction picks at normal responders
        intro: list[list[int]] = [[] for _ in range(n)]
        for d in range(n):
            for s_ix, src in enumerate(req_inbox[d]):
                ex = src if rq_ok[d][s_ix] else NO_PEER
                intro[d].append(self._sample_intro(
                    d, self.peers[d].slots, s_ix, ex, 0,
                    req_sym=self._nat_sym(ex)))
                if rq_ok[d][s_ix] and intro[d][s_ix] != NO_PEER:
                    self.peers[d].bytes_up += PUNCTURE_REQUEST_BYTES

        # puncture-request edges: normal responders (row-major), then trackers
        pr_edges = []  # (dst=C, named requester A)
        for d in range(n):
            for s_ix in range(len(req_inbox[d])):
                c = intro[d][s_ix]
                a = req_inbox[d][s_ix]
                if (rq_ok[d][s_ix] and c != NO_PEER
                        and not self._lost(d, _LOSS_PUNCTURE_REQ, s_ix)
                        and not self._blocked(d, c)):
                    pr_edges.append((c, a))
        for d in range(t):
            for s_ix in range(len(tq_inbox[d])):
                c = intro_t[d][s_ix]
                a = tq_inbox[d][s_ix]
                if (tq_ok[d][s_ix] and c != NO_PEER
                        and not self._lost(d, _LOSS_PUNCTURE_REQ,
                                           s_ix + _TRACKER_SALT)
                        and not self._blocked(d, c)):
                    pr_edges.append((c, a))
        punc_req_inbox: list[list[int]] = [[] for _ in range(n)]
        for c, a in pr_edges:
            if 0 <= c < n:
                if len(punc_req_inbox[c]) < r:
                    punc_req_inbox[c].append(a)
                else:
                    self.peers[c].requests_dropped += 1
        for c, box in enumerate(punc_req_inbox):
            if box:
                arrivals[c] = True
        pq_ok = [[self.peers[c].alive and self.peers[c].loaded
                  for _ in box]
                 for c, box in enumerate(punc_req_inbox)]
        for c in range(n):
            n_pq = sum(pq_ok[c])
            self.peers[c].punctures += n_pq
            self.peers[c].bytes_down += n_pq * PUNCTURE_REQUEST_BYTES
            self.peers[c].bytes_up += n_pq * PUNCTURE_BYTES

        # phase 4: puncture hop C -> A
        pu_edges = []
        for c in range(n):
            for s_ix, a in enumerate(punc_req_inbox[c]):
                if (pq_ok[c][s_ix] and not self._lost(c, _LOSS_PUNCTURE, s_ix)
                        and not self._blocked(c, a)
                        and not (self._nat_sym(c) and self._nat_sym(a))):
                    # symmetric<->symmetric punctures never land (engine's
                    # puncture NAT gate)
                    pu_edges.append((a, c))
        punc_inbox: list[list[int]] = [[] for _ in range(n)]
        for a, c in pu_edges:
            if 0 <= a < n:
                if len(punc_inbox[a]) < r:
                    punc_inbox[a].append(c)
                else:
                    self.peers[a].requests_dropped += 1
        for a, box in enumerate(punc_inbox):
            if box:
                arrivals[a] = True
        pu_ok = [[self.peers[a].alive and self.peers[a].loaded
                  for _ in box]
                 for a, box in enumerate(punc_inbox)]
        for a in range(n):
            self.peers[a].bytes_down += sum(pu_ok[a]) * PUNCTURE_BYTES

        # phase 3: response pickup by receipt
        got_resp = [False] * n
        introduced = [NO_PEER] * n
        resp_gt = [0] * n
        for i in range(n):
            d = targets[i]
            if 0 <= d < t:
                sl = tq_slot[i]
                got = sl >= 0 and tq_ok[d][sl]
                pick = intro_t[d][sl] if got else NO_PEER
            else:
                sl = req_slot[i]
                got = sl >= 0 and rq_ok[d][sl] if d >= 0 else False
                pick = intro[d][sl] if got else NO_PEER
            got = (got and not self._lost(i, _LOSS_RESPONSE, 0)
                   and self.peers[i].alive and self.peers[i].loaded)
            got_resp[i] = got
            if got:
                self.peers[i].bytes_down += INTRO_RESPONSE_BYTES
            introduced[i] = pick if got else NO_PEER
            resp_gt[i] = self.peers[d].global_time if d >= 0 else 0

        for i in range(n):
            if got_resp[i]:
                self._upsert(i, targets[i], KIND_WALK)
            if introduced[i] != NO_PEER:
                self._upsert(i, introduced[i], KIND_INTRO)
            for s_ix, c in enumerate(punc_inbox[i]):
                if pu_ok[i][s_ix]:
                    self._upsert(i, c, KIND_STUMBLE)
            if got_resp[i]:
                self._fold_gt(i, [resp_gt[i]])
            walked_ok = (self.peers[i].alive and self.peers[i].loaded
                         and targets[i] != NO_PEER)
            if walked_ok and got_resp[i]:
                self.peers[i].walk_success += 1
                self.walk_streak[i] += 1       # telemetry walk_streak
            elif walked_ok:
                self.peers[i].walk_fail += 1
                self.walk_streak[i] = 0
                self._remove(i, targets[i])

        # phase 3s: signature-request/-response exchange (engine phase 3s)
        sig_completed: list = [None] * n
        if cfg.double_meta_mask:
            s_sz = cfg.sig_inbox
            sig_inbox_: list[list[int]] = [[] for _ in range(n)]
            sig_slot = [-1] * n
            sending = [False] * n
            for i, p in enumerate(self.peers):
                sending[i] = (p.alive and p.loaded and not killed[i]
                              and p.sig_target != NO_PEER
                              and p.sig_since == rnd)
                if sending[i]:
                    p.bytes_up += SIGNATURE_REQUEST_BYTES
                    if not self._lost(i, _LOSS_SIGREQ, 0) \
                            and not self._blocked(i, p.sig_target):
                        d = p.sig_target
                        if len(sig_inbox_[d]) < s_sz:
                            sig_slot[i] = len(sig_inbox_[d])
                            sig_inbox_[d].append(i)
                            arrivals[d] = True
                        else:
                            self.peers[d].requests_dropped += 1
            countersign: list[list[bool]] = [[] for _ in range(n)]
            for d in range(n):
                pd = self.peers[d]
                # trackers and hard-killed peers never countersign
                ok_d = pd.alive and pd.loaded and d >= t and not killed[d]
                n_sq = n_cs = 0
                for s_ix, src in enumerate(sig_inbox_[d]):
                    if ok_d:
                        n_sq += 1
                    if cfg.countersign_rate >= 1.0:
                        agree = True
                    elif cfg.countersign_rate <= 0.0:
                        agree = False
                    else:
                        agree = rand_uniform(
                            seed, rnd, d, P_SIGN, s_ix) < np.float32(
                                cfg.countersign_rate)
                    sp = self.peers[src]
                    if (cfg.timeline_enabled
                            and ((cfg.protected_meta_mask
                                  | cfg.dynamic_meta_mask)
                                 & cfg.double_meta_mask)):
                        m = sp.sig_meta
                        if (m < cfg.n_meta
                                and self._linear_at(d, m, sp.sig_gt)):
                            agree = (agree
                                     and self._auth_check(d, src, m,
                                                          sp.sig_gt)
                                     and self._auth_check(d, d, m,
                                                          sp.sig_gt))
                    cs = ok_d and agree
                    if cs:
                        n_cs += 1
                    countersign[d].append(cs)
                pd.bytes_down += n_sq * SIGNATURE_REQUEST_BYTES
                pd.bytes_up += n_cs * SIGNATURE_RESPONSE_BYTES
                pd.sig_signed += n_cs
            for i, p in enumerate(self.peers):
                completed = False
                if sending[i] and sig_slot[i] >= 0:
                    if (countersign[p.sig_target][sig_slot[i]]
                            and not self._lost(i, _LOSS_SIGRESP, 0)):
                        completed = True
                if completed:
                    p.bytes_down += SIGNATURE_RESPONSE_BYTES
                    p.sig_done += 1
                    sig_completed[i] = Record(p.sig_gt, i, p.sig_meta,
                                              p.sig_payload, p.sig_target)
                expired = (p.alive and p.sig_target != NO_PEER
                           and not completed
                           and rnd - p.sig_since >= cfg.sig_timeout_rounds)
                if expired:
                    p.sig_expired += 1
                if completed or expired:
                    p.sig_target = NO_PEER
                    p.sig_meta = p.sig_payload = 0
                    p.sig_gt = p.sig_since = 0

        # phase 2b: sync responder outboxes (served in the ordered view;
        # byte-diet quiet rounds serve nothing — the claim never rode the
        # request)
        outbox: dict[tuple[int, int], list[Record]] = {}
        if sync_on:
            b = cfg.response_budget
            for d in range(n):
                view = self._serve_order(self.peers[d].store)
                if killed[d]:
                    # HardKilledCommunity serves only the destroy record
                    view = [r for r in view if r.meta == META_DESTROY]
                for s_ix, src in enumerate(req_inbox[d]):
                    sel: list[Record] = []
                    # under staggering a non-active requester's packet
                    # is the 2-col quiet layout — no claim to serve
                    if rq_ok[d][s_ix] and blooms[src] is not None:
                        sl, bl = slices[src], blooms[src]
                        for rec in view:
                            if len(sel) >= b:
                                break
                            # killed responder: destroy served without the
                            # Bloom test (engine: present &= ~killed)
                            if self._in_slice(rec, sl) and (
                                    killed[d] or rec.hash() not in bl):
                                sel.append(rec)
                    outbox[(d, s_ix)] = sel
                    # served records leave the responder pre-loss (engine
                    # counts obox_ok at the sender)
                    self.peers[d].bytes_up += len(sel) * RECORD_BYTES

        # phase 4p: active missing-proof round trip (engine phase 4p) —
        # computed for ALL peers against the pre-intake stores before any
        # intake mutation, exactly like the fused engine phase.
        delay_on = cfg.delay_inbox > 0
        pr_batch: list[list[tuple[Record, int]]] = [[] for _ in range(n)]
        if delay_on and cfg.proof_requests:
            proof_inbox: list[list[tuple[int, int]]] = [[] for _ in range(n)]
            for i in range(n):
                p = self.peers[i]
                for d, (rec, since, src) in enumerate(p.delay):
                    if not (p.alive and p.loaded) or src == NO_PEER:
                        continue
                    p.bytes_up += MISSING_PROOF_BYTES       # sendto, pre-loss
                    if self._lost(i, _LOSS_PROOF_REQ, d) \
                            or self._blocked(i, src):
                        continue
                    if 0 <= src < n:
                        if len(proof_inbox[src]) < cfg.proof_inbox:
                            proof_inbox[src].append((i, d))
                            arrivals[src] = True
                        else:
                            self.peers[src].requests_dropped += 1
            replies: dict[tuple[int, int], list[Record]] = {}
            for sv in range(n):
                psv = self.peers[sv]
                if not (psv.alive and psv.loaded) \
                        or (cfg.timeline_enabled and killed[sv]):
                    continue
                for (ri, d_slot) in proof_inbox[sv]:
                    psv.proof_requests += 1
                    psv.bytes_down += MISSING_PROOF_BYTES
                    author = self.peers[ri].delay[d_slot][0].member
                    served = [r for r in reversed(psv.store)
                              if r.meta in (META_AUTHORIZE, META_REVOKE)
                              and r.payload == author][:cfg.proof_budget]
                    psv.bytes_up += len(served) * RECORD_BYTES
                    replies[(ri, d_slot)] = served
            for i in range(n):
                p = self.peers[i]
                for d, entry in enumerate(p.delay):
                    for b_ix, r in enumerate(replies.get((i, d), [])):
                        if not (p.alive and p.loaded) or self._lost(
                                i, _LOSS_PROOF_RESP,
                                d * cfg.proof_budget + b_ix):
                            continue
                        pr_batch[i].append(
                            (Record(r.gt, r.member, r.meta, r.payload,
                                    r.aux), entry[2]))
                        p.proof_records += 1
                        p.bytes_down += RECORD_BYTES

        # phase 4s: active missing-sequence round trip (engine phase 4s) —
        # every SEQ-parked pen entry asks its deliverer for the missing
        # range; replies served ASCENDING from the sorted store.
        mq_batch: list[list[tuple[Record, int]]] = [[] for _ in range(n)]
        if delay_on and cfg.seq_requests:
            seq_inbox: list[list[tuple[int, int, int, int, int, int]]] = \
                [[] for _ in range(n)]
            for i in range(n):
                p = self.peers[i]
                for d, (rec, since, src) in enumerate(p.delay):
                    is_seq = (rec.meta < cfg.n_meta
                              and (cfg.seq_meta_mask >> rec.meta) & 1)
                    if not (p.alive and p.loaded) or src == NO_PEER \
                            or not is_seq:
                        continue
                    low = max((r.aux for r in p.store
                               if r.member == rec.member
                               and r.meta == rec.meta), default=0) + 1
                    high = rec.aux - 1
                    if low > high:
                        continue
                    p.bytes_up += MISSING_SEQ_BYTES     # sendto, pre-loss
                    if self._lost(i, _LOSS_SEQ_REQ, d) \
                            or self._blocked(i, src):
                        continue
                    if 0 <= src < n:
                        if len(seq_inbox[src]) < cfg.proof_inbox:
                            seq_inbox[src].append(
                                (i, d, rec.member, rec.meta, low, high))
                            arrivals[src] = True
                        else:
                            self.peers[src].requests_dropped += 1
            sreplies: dict[tuple[int, int], list[Record]] = {}
            for sv in range(n):
                psv = self.peers[sv]
                if not (psv.alive and psv.loaded) \
                        or (cfg.timeline_enabled and killed[sv]):
                    continue
                for (ri, d_slot, member, meta, low, high) in seq_inbox[sv]:
                    psv.seq_requests += 1
                    psv.bytes_down += MISSING_SEQ_BYTES
                    served = [r for r in psv.store
                              if r.member == member and r.meta == meta
                              and low <= r.aux <= high][:cfg.proof_budget]
                    psv.bytes_up += len(served) * RECORD_BYTES
                    sreplies[(ri, d_slot)] = served
            for i in range(n):
                p = self.peers[i]
                for d, entry in enumerate(p.delay):
                    for b_ix, r in enumerate(sreplies.get((i, d), [])):
                        if not (p.alive and p.loaded) or self._lost(
                                i, _LOSS_SEQ_RESP,
                                d * cfg.proof_budget + b_ix):
                            continue
                        mq_batch[i].append(
                            (Record(r.gt, r.member, r.meta, r.payload,
                                    r.aux), entry[2]))
                        p.seq_records += 1
                        p.bytes_down += RECORD_BYTES

        # phase 4m: active missing-message round trip (engine phase 4m) —
        # every UNDO-OTHER pen entry asks its deliverer for the exact
        # (member, global_time) record it names; budget 1 (UNIQUE key).
        sm_batch: list[list[tuple[Record, int]]] = [[] for _ in range(n)]
        if delay_on and cfg.msg_requests:
            mm_inbox: list[list[tuple[int, int]]] = [[] for _ in range(n)]
            for i in range(n):
                p = self.peers[i]
                for d, (rec, since, src) in enumerate(p.delay):
                    if not (p.alive and p.loaded) or src == NO_PEER \
                            or rec.meta != META_UNDO_OTHER:
                        continue
                    p.bytes_up += MISSING_MSG_BYTES     # sendto, pre-loss
                    if self._lost(i, _LOSS_MSG_REQ, d) \
                            or self._blocked(i, src):
                        continue
                    if 0 <= src < n:
                        if len(mm_inbox[src]) < cfg.proof_inbox:
                            mm_inbox[src].append((i, d))
                            arrivals[src] = True
                        else:
                            self.peers[src].requests_dropped += 1
            mreplies: dict[tuple[int, int], list[Record]] = {}
            for sv in range(n):
                psv = self.peers[sv]
                if not (psv.alive and psv.loaded) \
                        or (cfg.timeline_enabled and killed[sv]):
                    continue
                for (ri, d_slot) in mm_inbox[sv]:
                    psv.mm_requests += 1
                    psv.bytes_down += MISSING_MSG_BYTES
                    q = self.peers[ri].delay[d_slot][0]
                    served = [r for r in psv.store
                              if r.meta < 32 and r.member == q.payload
                              and r.gt == q.aux][:1]
                    psv.bytes_up += len(served) * RECORD_BYTES
                    mreplies[(ri, d_slot)] = served
            for i in range(n):
                p = self.peers[i]
                for d, entry in enumerate(p.delay):
                    for r in mreplies.get((i, d), []):
                        if not (p.alive and p.loaded) or self._lost(
                                i, _LOSS_MSG_RESP, d):
                            continue
                        sm_batch[i].append(
                            (Record(r.gt, r.member, r.meta, r.payload,
                                    r.aux), entry[2]))
                        p.mm_records += 1
                        p.bytes_down += RECORD_BYTES

        # phase 4i: active missing-identity round trip (engine phase 4i) —
        # every pen entry still lacking its author's identity record asks
        # its deliverer for it; budget 1 (one identity per member).
        si_batch: list[list[tuple[Record, int]]] = [[] for _ in range(n)]
        if delay_on and cfg.identity_requests:
            id_inbox: list[list[tuple[int, int]]] = [[] for _ in range(n)]
            for i in range(n):
                p = self.peers[i]
                for d, (rec, since, src) in enumerate(p.delay):
                    if not (p.alive and p.loaded) or src == NO_PEER \
                            or not rec.meta < cfg.n_meta \
                            or self._has_identity(i, rec.member):
                        continue
                    p.bytes_up += MISSING_IDENTITY_BYTES
                    if self._lost(i, _LOSS_ID_REQ, d) \
                            or self._blocked(i, src):
                        continue
                    if 0 <= src < n:
                        if len(id_inbox[src]) < cfg.proof_inbox:
                            id_inbox[src].append((i, d))
                            arrivals[src] = True
                        else:
                            self.peers[src].requests_dropped += 1
            ireplies: dict[tuple[int, int], list[Record]] = {}
            for sv in range(n):
                psv = self.peers[sv]
                if not (psv.alive and psv.loaded) \
                        or (cfg.timeline_enabled and killed[sv]):
                    continue
                for (ri, d_slot) in id_inbox[sv]:
                    psv.id_requests += 1
                    psv.bytes_down += MISSING_IDENTITY_BYTES
                    q = self.peers[ri].delay[d_slot][0]
                    served = [r for r in psv.store
                              if r.meta == META_IDENTITY
                              and r.member == q.member][:1]
                    psv.bytes_up += len(served) * RECORD_BYTES
                    ireplies[(ri, d_slot)] = served
            for i in range(n):
                p = self.peers[i]
                for d, entry in enumerate(p.delay):
                    for r in ireplies.get((i, d), []):
                        if not (p.alive and p.loaded) or self._lost(
                                i, _LOSS_ID_RESP, d):
                            continue
                        si_batch[i].append(
                            (Record(r.gt, r.member, r.meta, r.payload,
                                    r.aux), entry[2]))
                        p.id_records += 1
                        p.bytes_down += RECORD_BYTES

        # phase 5: combined intake (delayed pen + sync pull + push) ->
        # store + fwd batch + rebuilt pen
        retro_trigger = False   # any fresh revoke folded anywhere (engine:
        #   the scalar lax.cond predicate over all peers)
        for i in range(n):
            p = self.peers[i]
            # On-the-wire records: (gt, member, meta, payload, aux) — flags
            # are receiver-local and never travel (engine sends 5 columns).
            # Each batch entry carries the record, the round it (first)
            # arrived (pen entries keep their parking round — engine
            # in_since), its deliverer (engine in_src; the future
            # missing-proof target should it park), and its delivery-
            # channel code (engine chan_code — static per segment;
            # traceplane.CH_*, 0 for segments the trace plane's config
            # gate excludes).
            batch: list[tuple[Record, int, int, int]] = []
            sy_dups: list[tuple[Record, int, int, int]] = []
            ph_dups: list[tuple[Record, int, int, int]] = []
            if delay_on and p.alive and p.loaded:
                # pen first (engine: dl segment leads the concat)
                batch.extend((drec, ds, dsc, 0)
                             for drec, ds, dsc in p.delay)
            if sync_on and p.alive and p.loaded \
                    and req_slot[i] >= 0:
                recs = outbox.get((targets[i], req_slot[i]), [])
                for j, r in enumerate(recs):
                    if self._lost(i, _LOSS_SYNC, j):
                        continue
                    # recvfrom before the hash check can reject (engine
                    # counts bdown from pre-corrupt sy_ok)
                    p.bytes_down += RECORD_BYTES
                    if fm.corrupt_rate > 0.0 and rand_uniform(
                            seed, rnd, i, P_CORRUPT,
                            j + _FAULT_SYNC) < np.float32(fm.corrupt_rate):
                        p.msgs_corrupt_dropped += 1
                        continue
                    batch.append((Record(r.gt, r.member, r.meta,
                                         r.payload, r.aux), rnd,
                                  targets[i], CH_WALK_SYNC))
                    if fm.dup_rate > 0.0 and rand_uniform(
                            seed, rnd, i, P_DUP,
                            j + _FAULT_SYNC) < np.float32(fm.dup_rate):
                        sy_dups.append((Record(r.gt, r.member, r.meta,
                                               r.payload, r.aux), rnd,
                                        targets[i], CH_WALK_SYNC))
                        p.bytes_down += RECORD_BYTES
            if p.alive and p.loaded:
                for slot, (r, src, junk) in enumerate(push_inbox[i]):
                    bad = junk
                    if not bad and fm.corrupt_rate > 0.0 and rand_uniform(
                            seed, rnd, i, P_CORRUPT,
                            slot + _FAULT_PUSH) < np.float32(
                                fm.corrupt_rate):
                        bad = True
                    if bad:
                        # failed the intake hash re-check: dropped and
                        # counted, never ingested (engine ph bad mask)
                        p.msgs_corrupt_dropped += 1
                        continue
                    batch.append((Record(r.gt, r.member, r.meta,
                                         r.payload, r.aux), rnd, src,
                                  CH_PUSH))
                    if fm.dup_rate > 0.0 and rand_uniform(
                            seed, rnd, i, P_DUP,
                            slot + _FAULT_PUSH) < np.float32(fm.dup_rate):
                        ph_dups.append((Record(r.gt, r.member, r.meta,
                                               r.payload, r.aux), rnd,
                                        src, CH_PUSH))
                        p.bytes_down += RECORD_BYTES
            if sig_completed[i] is not None:
                # the record's aux IS the countersigner it came back from
                batch.append((sig_completed[i], rnd,
                              sig_completed[i].aux, 0))
            batch.extend((rec, rnd, src, 0) for rec, src in pr_batch[i])
            batch.extend((rec, rnd, src, 0) for rec, src in mq_batch[i])
            batch.extend((rec, rnd, src, 0) for rec, src in sm_batch[i])
            batch.extend((rec, rnd, src, 0) for rec, src in si_batch[i])
            # delivery duplicates ride at the batch tail, sync then push
            # (engine: segs_* += [sy_dup, ph_dup])
            batch.extend(sy_dups)
            batch.extend(ph_dups)
            # clock-jump defense (engine: post-walk-fold clock), plus the
            # structural countersigner check for double-signed metas
            ok_pairs = [(rec, s, sc, ch) for rec, s, sc, ch in batch
                        if rec.gt <= (p.global_time
                                      + cfg.acceptable_global_time_range)
                        and self._dbl_struct_ok(i, rec)]
            if cfg.timeline_enabled and killed[i]:
                # engine: in_ok &= ~killed before ANY intake bookkeeping —
                # a hard-killed peer convicts nobody and counts nothing
                # (delivery bytes were already counted at recvfrom above)
                ok_pairs = []
            gossip_pick = None
            if cfg.malicious_enabled:
                # engine: conviction + blacklist run AFTER the killed gate
                # (a killed peer's emptied batch convicts nobody), in
                # batch order (fold_set semantics)
                pre_mal = set(p.mal)      # pre-batch blacklist snapshot
                for rec, *_ in ok_pairs:
                    conflict = any(
                        r.member == rec.member and r.gt == rec.gt
                        and (r.meta != rec.meta or r.payload != rec.payload
                             or r.aux != rec.aux)
                        for r in p.store)
                    if conflict and rec.member not in pre_mal \
                            and gossip_pick is None:
                        # engine gospick: first conflict naming a member
                        # not blacklisted before this batch
                        gossip_pick = (rec.member, rec.gt)
                    if conflict and rec.member not in p.mal:
                        if len(p.mal) < cfg.k_malicious:
                            p.mal.append(rec.member)
                            p.conflicts += 1
                        else:
                            p.msgs_dropped += 1
                if cfg.malicious_gossip:
                    # Gossiped conviction claims fold next — unless the
                    # claimant is already blacklisted post-eyewitness-fold
                    # (engine black0).
                    black0 = set(p.mal)
                    for rec, *_ in ok_pairs:
                        if (rec.meta == META_MALICIOUS
                                and rec.member not in black0
                                and rec.payload not in p.mal):
                            if len(p.mal) < cfg.k_malicious:
                                p.mal.append(rec.payload)
                                p.convictions_rx += 1
                            else:
                                p.msgs_dropped += 1
                n_black = sum(1 for rec, *_ in ok_pairs
                              if rec.member in p.mal)
                p.msgs_rejected += n_black
                ok_pairs = [(rec, s, sc, ch)
                            for rec, s, sc, ch in ok_pairs
                            if rec.member not in p.mal]
            ok_batch = [rec for rec, *_ in ok_pairs]
            ok_since = [s for _, s, *_ in ok_pairs]
            ok_src = [sc for _, _, sc, _ in ok_pairs]
            ok_chan = [ch for *_, ch in ok_pairs]
            # freshness: not stored yet, not a dup of an earlier batch entry
            store_keys = {(r.gt, r.member) for r in p.store}
            if diet and cfg.sync_enabled:
                # Byte-diet freshness: membership in the epoch digest
                # (engine's bloom_query against the dig leaf) — with its
                # documented false-positive/negative behavior; the
                # digest is only UPDATED after the whole batch is
                # judged, so in-batch ordering matches the engine's
                # phase order exactly (dup_earlier handles in-batch).
                # Under staggering the salt is the peer's OWN epoch
                # (engine: salt = state.epoch[:, None]).
                p.digest.salt = p.epoch if stagger else ep
                have = [rec.hash() in p.digest for rec in ok_batch]
            elif diet:
                union_keys = store_keys | {(r.gt, r.member)
                                           for r in p.staging}
                have = [(rec.gt, rec.member) in union_keys
                        for rec in ok_batch]
            else:
                have = [(rec.gt, rec.member) in store_keys
                        for rec in ok_batch]
            fresh0: list[bool] = []
            seen: set[tuple[int, int]] = set()
            for rec, hv in zip(ok_batch, have):
                k2 = (rec.gt, rec.member)
                fresh0.append(not hv and k2 not in seen)
                seen.add(k2)
            batch_flips = []
            deleg_flags = [False] * len(ok_batch)
            if cfg.timeline_enabled:
                # Fold the whole batch's fresh authorize/revoke records
                # before any check runs (engine: tl.fold precedes tl.check).
                # Pass A: root (founder) grants; pass B: delegated grants,
                # ALL judged against the post-pass-A table snapshot, then
                # folded in batch order (engine's fr/fr2 two-pass).
                gmask = user_perm_mask(cfg.n_meta)
                for rec, f0 in zip(ok_batch, fresh0):
                    if (rec.meta in (META_AUTHORIZE, META_REVOKE) and f0
                            and rec.member == self._founder(i)):
                        ev = self._auth_fold(i, rec.payload, rec.aux & gmask,
                                             rec.gt, rec.meta == META_REVOKE,
                                             issuer=rec.member)
                        retro_trigger = (retro_trigger or ev
                                         or rec.meta == META_REVOKE)
                deleg_flags = [
                    rec.meta in (META_AUTHORIZE, META_REVOKE)
                    and rec.member != self._founder(i)
                    and self._grant_ok(i, rec.member, rec.aux & gmask,
                                       rec.gt,
                                       PERM_REVOKE if rec.meta == META_REVOKE
                                       else PERM_AUTHORIZE)
                    for rec in ok_batch]
                for rec, f0, dg in zip(ok_batch, fresh0, deleg_flags):
                    if dg and f0:
                        ev = self._auth_fold(i, rec.payload, rec.aux & gmask,
                                             rec.gt, rec.meta == META_REVOKE,
                                             issuer=rec.member)
                        retro_trigger = (retro_trigger or ev
                                         or rec.meta == META_REVOKE)
                if cfg.dynamic_meta_mask:
                    # this batch's fresh accepted dynamic-settings flips
                    # (engine: flip_ok = fresh0 & is_flip
                    #  & (ctrl_ok0 | flip_grant_ok) — founder or the
                    #  AUTHORIZE authority on the flipped meta, judged
                    #  against the post-fold table)
                    for rec, f0 in zip(ok_batch, fresh0):
                        if (rec.meta == META_DYNAMIC and f0
                                and self._auth_check(i, rec.member,
                                                     rec.payload, rec.gt,
                                                     PERM_AUTHORIZE)):
                            batch_flips.append((rec.gt, rec.payload,
                                                rec.aux))
            accept = [self._intake_accept(i, rec, batch_flips, dg)
                      and self._id_ok(i, rec)
                      for rec, dg in zip(ok_batch, deleg_flags)]
            if cfg.seq_meta_mask:
                # Sequence-chain intake (engine's fori scan, in batch order).
                acc_state: dict[tuple[int, int], int] = {}
                seq_ok_l = []
                for rec, a in zip(ok_batch, accept):
                    is_seq = (rec.meta < cfg.n_meta
                              and (cfg.seq_meta_mask >> rec.meta) & 1)
                    chk = is_seq and (rec.gt, rec.member) not in store_keys
                    if chk:
                        gkey = (rec.member, rec.meta)
                        cur = acc_state.get(gkey)
                        if cur is None:
                            cur = max((r.aux for r in p.store
                                       if r.member == rec.member
                                       and r.meta == rec.meta), default=0)
                        ok_i = rec.aux == cur + 1
                        if a and ok_i:
                            acc_state[gkey] = max(cur, rec.aux)
                    else:
                        ok_i = True
                    seq_ok_l.append(ok_i)
            else:
                seq_ok_l = [True] * len(ok_batch)

            if delay_on:
                # DelayMessageByProof pen — plus, with seq_requests,
                # DelayMessageBySequence (engine: waiting/parked masks).
                # A non-control record failing only the permission check
                # (or only the sequence chain), not already covered
                # (fresh0), and still inside its waiting window parks;
                # first-fit into the bounded pen.
                ctrl = (META_AUTHORIZE, META_REVOKE, META_UNDO_OWN,
                        META_UNDO_OTHER, META_DYNAMIC, META_DESTROY)
                new_delay: list[tuple[Record, int, int]] = []
                parked_flags: list[bool] = []
                for rec, s, sc, a, sok, f0 in zip(ok_batch, ok_since, ok_src,
                                                  accept, seq_ok_l, fresh0):
                    gap = cfg.seq_requests and a and not sok
                    # msg_requests: a failing undo-other parks (engine
                    # undo_park) — phase 4m fetches its target by name
                    parkable = (rec.meta not in ctrl
                                or (cfg.msg_requests and not a
                                    and rec.meta == META_UNDO_OTHER))
                    waiting = ((not a or gap) and parkable
                               and f0
                               and rnd - s < cfg.delay_timeout_rounds)
                    parked = waiting and len(new_delay) < cfg.delay_inbox
                    if parked:
                        new_delay.append(
                            (Record(rec.gt, rec.member, rec.meta,
                                    rec.payload, rec.aux), s, sc))
                        if s == rnd:
                            p.msgs_delayed += 1
                    parked_flags.append(parked)
                p.delay = new_delay
            else:
                parked_flags = [False] * len(ok_batch)
            accept = [a and sok for a, sok in zip(accept, seq_ok_l)]
            p.msgs_rejected += sum(1 for a, pk in zip(accept, parked_flags)
                                   if not a and not pk)

            if cfg.direct_meta_mask:
                accept_store = []
                for rec, a in zip(ok_batch, accept):
                    is_dir = (rec.meta < cfg.n_meta
                              and (cfg.direct_meta_mask >> rec.meta) & 1)
                    if a and is_dir:
                        p.msgs_direct += 1
                    accept_store.append(a and not is_dir)
            else:
                accept_store = accept

            def pre_undone(rec: Record) -> bool:
                # Control records (meta >= 32) are never markable, matching
                # the post-insert undo path.
                return rec.meta < 32 and any(
                    r.meta in (META_UNDO_OWN, META_UNDO_OTHER)
                    and r.payload == rec.member and r.aux == rec.gt
                    for r in p.store)
            ins_batch = [
                Record(rec.gt, rec.member, rec.meta, rec.payload, rec.aux,
                       FLAG_UNDONE if (cfg.timeline_enabled
                                       and pre_undone(rec)) else 0)
                for rec, a in zip(ok_batch, accept_store) if a]
            fresh = [rec for rec, a, f0 in zip(ok_batch, accept_store, fresh0)
                     if a and f0]
            # Per-meta acceptance counters (engine: accepted_by_meta —
            # fresh stored records plus direct receipts, disjoint sets).
            for rec in fresh:
                p.accepted_by_meta[min(rec.meta, cfg.n_meta)] += 1
            if cfg.direct_meta_mask:
                for rec, a in zip(ok_batch, accept):
                    if (a and rec.meta < cfg.n_meta
                            and (cfg.direct_meta_mask >> rec.meta) & 1):
                        p.accepted_by_meta[min(rec.meta, cfg.n_meta)] += 1
            if diet:
                # Byte-diet landing (engine store_stage): fresh records
                # append to the staging buffer in delivery order; dup
                # and in-batch-dup kills count where the legacy merge
                # counted them, overflow drops like any bounded inbox.
                # Digest adds are DEFERRED past the batch (engine
                # updates the digest leaf once, at the wrap-up).
                landed_hashes: list[int] = []
                landed_flags = [False] * len(ok_batch)
                for e, (rec, a, f0) in enumerate(zip(ok_batch,
                                                     accept_store,
                                                     fresh0)):
                    if not a:
                        continue
                    if not f0:
                        p.msgs_dropped += 1
                    elif len(p.staging) < cfg.store.staging:
                        p.staging.append(Record(rec.gt, rec.member,
                                                rec.meta, rec.payload,
                                                self._aux_store(rec.aux)))
                        landed_hashes.append(rec.hash())
                        landed_flags[e] = True
                    else:
                        p.msgs_dropped += 1
                if (cfg.sync_enabled and (stagger or not compact_now)
                        and landed_hashes):
                    # Under staggering the incremental update runs
                    # EVERY round at the peer's own salt — the active
                    # cohort's digest is rebuilt (overwritten) by its
                    # compaction just below, same as the engine's
                    # update-then-rebuild ordering.
                    p.digest.salt = p.epoch if stagger else ep
                    for h in landed_hashes:
                        p.digest.add(h)
                if ok_batch:
                    self._fold_gt(i, [rec.gt
                                      for rec, a in zip(ok_batch, accept)
                                      if a])
            elif ok_batch:
                self._store_insert(i, ins_batch)
                self._fold_gt(i, [rec.gt for rec, a in zip(ok_batch, accept)
                                  if a])
            if not diet:
                # Legacy landing flags for the lineage fold below:
                # accepted-fresh counts as landed even when the ring's
                # capacity drop kills it at insert (arrival history —
                # engine ln_landed = fresh; traceplane.py).
                landed_flags = [a and f0 for a, f0 in
                                zip(accept_store, fresh0)]
            if cfg.trace.enabled:
                # engine trace_lineage mirror (ops/trace.slot_lineage):
                # the first same-key occurrence is the only one that
                # can land, so this in-order walk equals the engine's
                # set-based fold bit-for-bit.  Keys are unique across
                # slots (track_record is idempotent), so an entry
                # matches at most one slot.
                for rec, a, ld, ch in zip(ok_batch, accept_store,
                                          landed_flags, ok_chan):
                    if not a:
                        continue
                    for k, (km, kg) in enumerate(zip(self.trace_member,
                                                     self.trace_gt)):
                        if km != rec.member or kg != rec.gt:
                            continue
                        if ld and p.trace_first[k] == 0:
                            p.trace_first[k] = rnd + 1
                            p.trace_chan[k] = ch
                            p.trace_delivered[ch - 1] += 1
                        else:
                            p.trace_dups[k] += 1
                            p.trace_dup[ch - 1] += 1
                        break
            if cfg.timeline_enabled:
                # Post-insert: this batch's accepted undo records mark their
                # targets (now possibly just inserted).
                for rec, a in zip(ok_batch, accept):
                    if a and rec.meta in (META_UNDO_OWN, META_UNDO_OTHER):
                        for r in p.store:
                            if (r.member == rec.payload and r.gt == rec.aux
                                    and r.meta < 32):
                                r.flags |= FLAG_UNDONE
            grec = None
            if (cfg.malicious_enabled and cfg.malicious_gossip
                    and gossip_pick is not None):
                # Eyewitness authors dispersy-malicious-proof post-insert
                # (engine: after the batch landed and the clock folded).
                gm, gg = gossip_pick
                p.global_time += 1
                grec = Record(p.global_time, i, META_MALICIOUS, gm, gg)
                self._store_insert(i, [grec])
                p.accepted_by_meta[min(META_MALICIOUS, cfg.n_meta)] += 1
            fresh_ix = [(j, rec) for j, (rec, a, f0) in
                        enumerate(zip(ok_batch, accept_store, fresh0))
                        if a and f0]
            if cfg.needs_priority_forward:
                # engine: F slots to the highest-priority fresh records,
                # ties by delivery order ((255-prio)*4096 + idx key)
                def fkey(jr):
                    j, rec = jr
                    prio = priority_of(rec.meta, cfg.n_meta, cfg.priorities)
                    return (255 - prio) * 4096 + j
                fresh_ix.sort(key=fkey)
            p.fwd = [Record(rec.gt, rec.member, rec.meta, rec.payload,
                            self._aux_store(rec.aux), rec.flags)
                     for _, rec in fresh_ix[:cfg.forward_buffer]]
            if grec is not None and cfg.forward_buffer > 0:
                # The proof record claims a forward slot like a create
                # (engine: first free, displacing the newest relay entry).
                if len(p.fwd) < cfg.forward_buffer:
                    p.fwd.append(grec.copy())
                else:
                    p.fwd[cfg.forward_buffer - 1] = grec.copy()
            if compact_now and (not stagger or p.cohort == a_coh):
                # Byte-diet compaction (engine store_compact +
                # digest_rebuild): the staging merges through the
                # unchanged insert semantics — msgs_stored counts here,
                # where records actually enter the ring — and the
                # digest rebuilds from the fresh ring under the NEXT
                # epoch's salt.  Under staggering only the ACTIVE
                # cohort's block compacts (ep_a == ep when cohorts==1;
                # the epoch bump itself runs for every active-cohort
                # row, alive or not, in the wrap-up loop below).
                self._store_insert(i, p.staging)
                p.staging = []
                if cfg.sync_enabled:
                    sl_n = self._claim_slice(i)
                    nb = OracleBloom(cfg.bloom_bits, cfg.bloom_hashes,
                                     salt=ep_a + 1)
                    for rec in p.store:
                        if self._in_slice(rec, sl_n):
                            nb.add(rec.hash())
                    p.digest = nb

        if compact_now and stagger:
            # The active cohort's epoch advances for EVERY row — alive,
            # unloaded or dead alike (the engine's elementwise
            # `epoch + (cohort == a_coh)` bump) — keeping the leaf
            # uniform per cohort and on the round-start invariant.
            for p in self.peers:
                if p.cohort == a_coh:
                    p.epoch += 1

        if cfg.timeline_enabled and retro_trigger:
            # Retroactive re-walk — the engine's lax.cond branch taken
            # whenever a fresh revoke folded anywhere this round.
            for i in range(n):
                self._retro_pass(i)

        # wrap up: eject convicted members from candidate tables (engine)
        if cfg.malicious_enabled:
            for i, p in enumerate(self.peers):
                if not p.mal:
                    continue
                for s in p.slots:
                    if s.peer != NO_PEER and s.peer in p.mal:
                        s.peer = NO_PEER
                        s.walk = s.stumble = s.intro = NEVER

        if cfg.auto_load:
            # engine wrap-up: any arrival loads the instance next round
            for i, p in enumerate(self.peers):
                if arrivals[i] and p.alive:
                    p.loaded = True

        tele_new = [0] * n     # health bits newly latched this round
        hb_l = [0] * n         # this round's sentinel bits (recovery)
        prev_l = [0] * n       # pre-latch health (recovery `prev`)
        if fm.health_checks:
            # engine wrap-up health sentinels (faults.HEALTH_* bits,
            # latched): counter wrap, store invariant, drop rate, Bloom
            # saturation.
            for i, p in enumerate(self.peers):
                bits = 0
                if ((p.bytes_up & M32) < bu0[i]
                        or (p.bytes_down & M32) < bd0[i]):
                    bits |= 1                      # HEALTH_COUNTER_WRAP
                for a, b2 in zip(p.store, p.store[1:]):
                    if not (a.gt < b2.gt
                            or (a.gt == b2.gt and a.member < b2.member)):
                        bits |= 2                  # HEALTH_STORE_INVARIANT
                        break
                if (p.requests_dropped + p.msgs_dropped - rd0[i]
                        >= fm.health_drop_limit):
                    bits |= 4                      # HEALTH_INBOX_DROP
                if cfg.sync_enabled:
                    # under the diet the live claim view is the digest
                    # (engine: popcount(dig)); quiet rounds have no
                    # per-round bloom at all
                    fill = sum(p.digest.bits if diet
                               else blooms[i].bits)
                    if fill * 8 >= cfg.bloom_bits * 7:
                        bits |= 8                  # HEALTH_BLOOM_SAT
                tele_new[i] = bits & ~p.health     # flight recorder
                prev_l[i] = p.health
                hb_l[i] = bits
                p.health |= bits

        rc = cfg.recovery
        if rc.enabled:
            # engine wrap-up recovery pass (dispersy_tpu/recovery.py;
            # RECOVERY.md): staged repair of bits latched since a
            # PREVIOUS round, quarantine escalation on a re-latch
            # within the hysteresis window, backoff decay on clean
            # rounds, and neighbor ejection of quarantined peers.
            rpost = self.rnd + 1
            for i, p in enumerate(self.peers):
                prev, hb = prev_l[i], hb_l[i]
                esc = (rc.quarantine_rounds > 0 and prev != 0
                       and p.repair_round > 0
                       and (rpost - p.repair_round)
                       <= rc.requarantine_window)
                rep = rc.soft_repair and prev != 0 and not esc
                bumped = False
                if rep:
                    if prev & 2:                   # STORE_INVARIANT
                        self._store_repair(i)
                    if prev & 4:                   # INBOX_DROP
                        p.slots = [Slot()
                                   for _ in range(cfg.k_candidates)]
                        if rc.backoff_limit > 0 \
                                and p.backoff < rc.backoff_limit:
                            p.backoff += 1
                            bumped = True
                    p.repair_round = rpost
                if esc:
                    # deterministic wiped-disk rebirth (the churn wipe;
                    # `loaded`/`alive` untouched — the process is up)
                    p.slots = [Slot() for _ in range(cfg.k_candidates)]
                    p.store = []
                    p.staging = []
                    if p.digest is not None:
                        p.digest = OracleBloom(cfg.bloom_bits,
                                               cfg.bloom_hashes)
                    p.fwd = []
                    p.auth = []
                    p.delay = []
                    p.sig_target = NO_PEER
                    p.sig_meta = p.sig_payload = 0
                    p.sig_gt = p.sig_since = 0
                    p.mal = []
                    if cfg.trace.enabled:
                        # lineage wipes with the store (traceplane.py;
                        # the churn-wipe rule)
                        t_w = cfg.trace.tracked_slots
                        p.trace_first = [0] * t_w
                        p.trace_chan = [0] * t_w
                        p.trace_dups = [0] * t_w
                    p.global_time = 1
                    p.session += 1
                    p.backoff = 0
                    p.repair_round = 0
                    p.quar_until = rpost + rc.quarantine_rounds
                cleared = ((prev if rep else 0)
                           | ((prev | hb) if esc else 0))
                if esc:
                    p.health = 0
                elif rep:
                    p.health = hb
                if rc.backoff_limit > 0 and (prev | hb) == 0 \
                        and p.backoff > 0:
                    u = rand_uniform(seed, rnd, i, P_RECOVERY)
                    if u < np.float32(rc.backoff_decay):
                        p.backoff -= 1
                p.recov_soft += 1 if rep else 0
                p.recov_backoff += 1 if bumped else 0
                p.recov_quarantine += 1 if esc else 0
                for b in range(NUM_HEALTH_BITS):
                    p.recov_cleared[b] += (cleared >> b) & 1
            if rc.quarantine_rounds > 0:
                quar = [rpost < q.quar_until for q in self.peers]
                for p in self.peers:
                    for s in p.slots:
                        if s.peer != NO_PEER and quar[s.peer]:
                            s.peer = NO_PEER
                            s.walk = s.stumble = s.intro = NEVER

        # engine wrap-up dissemination coverage + percentile latches
        # (trace_coverage scope: AFTER the recovery wipes, BEFORE the
        # telemetry row packs the counts — traceplane.py)
        if cfg.trace.enabled:
            members_tr = [p.alive and i >= t
                          for i, p in enumerate(self.peers)]
            alive_cnt = sum(members_tr)
            for k in range(cfg.trace.tracked_slots):
                cov = sum(1 for i, p in enumerate(self.peers)
                          if members_tr[i] and p.trace_first[k] != 0)
                for j, pct in enumerate(LATCH_PCTS):
                    if (self.trace_latch[k][j] == 0
                            and self.trace_member[k] != EMPTY_U32
                            and alive_cnt > 0
                            and cov * 100 >= pct * alive_cnt):
                        self.trace_latch[k][j] = rnd + 1

        # engine wrap-up telemetry (engine._telemetry_row + ring + flight
        # recorder; rows packed through the SAME schema via pack_row_host)
        tl = cfg.telemetry
        if tl.enabled:
            self.tele_row = tlm.pack_row_host(
                self._telemetry_values(tele_nrq,
                                       rd0 if (fm.health_checks
                                               or tl.histograms) else None,
                                       blooms), cfg)
            if tl.history:
                self.tele_ring[self.rnd % tl.history] = self.tele_row
            if tl.flight_recorder:
                taken = 0
                depth = tl.flight_recorder
                for i, p in enumerate(self.peers):
                    if taken >= tl.flight_per_round:
                        break
                    if not tele_new[i]:
                        continue
                    self.fr_ring[self.fr_pos % depth] = np.asarray(
                        [i, (self.rnd + 1) & M32, tele_new[i], p.health,
                         p.requests_dropped & M32, p.msgs_dropped & M32,
                         (p.requests_dropped + p.msgs_dropped
                          - rd0[i]) & M32,
                         len(p.store) + len(p.staging)], np.uint32)
                    self.fr_pos += 1
                    taken += 1

        self.now = _f32(self.now + np.float32(cfg.walk_interval))
        self.rnd += 1

    def _telemetry_values(self, tele_nrq, rd0, blooms) -> dict:
        """The fused row's field values, as plain ints (engine
        ``_telemetry_row`` mirror; packed by ``telemetry.pack_row_host``
        so layout cannot drift).  Per-peer counters sum WRAPPED (mod
        2^32), exactly what the device's u32 leaves hold."""
        cfg = self.cfg
        n, t = cfg.n_peers, cfg.n_trackers
        tl = cfg.telemetry
        members = [p.alive and i >= t for i, p in enumerate(self.peers)]
        vals = {
            "round": (self.rnd + 1) & M32,
            "sim_time": float(_f32(self.now
                                   + np.float32(cfg.walk_interval))),
            "alive_members": sum(members),
            "killed": sum(1 for p in self.peers
                          if any(r.meta == META_DESTROY for r in p.store)),
        }
        for nm in tlm.U64_COUNTERS:
            vals[nm] = sum(getattr(p, nm) & M32 for p in self.peers)
        vals["store_live"] = sum(len(p.store) + len(p.staging)
                                 for p in self.peers)
        vals["cand_live"] = sum(
            sum(1 for s in p.slots if s.peer != NO_PEER)
            for i, p in enumerate(self.peers) if members[i])
        or_v = 0
        for b, nm in enumerate(tlm.HEALTH_NAMES):
            cnt = sum(1 for p in self.peers if (p.health >> b) & 1)
            vals[f"health_{nm}"] = cnt
            if cnt:
                or_v |= 1 << b
        vals["health_or"] = or_v
        vals["health_flagged"] = sum(1 for p in self.peers
                                     if p.health != 0)
        for i in range(cfg.n_meta + 1):
            vals[f"accepted_by_meta_{i}"] = sum(
                p.accepted_by_meta[i] & M32 for p in self.peers)
        if cfg.trace.enabled:
            # dissemination-tracing words (engine _telemetry_row's
            # trace block; redundancy via the SHARED
            # traceplane.redundancy_f32 f32 sequence)
            for k in range(cfg.trace.tracked_slots):
                vals[f"trace_cov_{k}"] = sum(
                    1 for i, p in enumerate(self.peers)
                    if members[i] and p.trace_first[k] != 0)
                for j, pct in enumerate(LATCH_PCTS):
                    vals[f"trace_r{pct}_{k}"] = self.trace_latch[k][j]
            delivered = [sum(p.trace_delivered[c] & M32
                             for p in self.peers)
                         for c in range(NUM_CHANNELS)]
            dup = [sum(p.trace_dup[c] & M32 for p in self.peers)
                   for c in range(NUM_CHANNELS)]
            for c, nm in enumerate(CHANNEL_NAMES):
                vals[f"trace_delivered_{nm}"] = delivered[c]
                vals[f"trace_dup_{nm}"] = dup[c]
            vals["trace_redundancy"] = redundancy_f32(delivered, dup)
        if cfg.overload.enabled:
            vals["msgs_shed_rate"] = sum(p.msgs_shed_rate & M32
                                         for p in self.peers)
            vals["msgs_shed_priority"] = sum(p.msgs_shed_priority & M32
                                             for p in self.peers)
            vals["bucket_exhausted"] = sum(1 for p in self.peers
                                           if p.bucket == 0)
        if cfg.recovery.enabled:
            for nm in ("recov_soft", "recov_backoff",
                       "recov_quarantine"):
                vals[nm] = sum(getattr(p, nm) & M32
                               for p in self.peers)
            for b, nm in enumerate(tlm.HEALTH_NAMES):
                vals[f"recov_cleared_{nm}"] = sum(
                    p.recov_cleared[b] & M32 for p in self.peers)
        if tl.histograms:
            hb = tl.hist_buckets
            ones = [True] * n
            data = {
                "store_fill": ([len(p.store) + len(p.staging)
                               for p in self.peers], ones),
                "cand_fill": ([sum(1 for s in p.slots
                                   if s.peer != NO_PEER)
                               for p in self.peers], members),
                "req_inbox": (tele_nrq, [i >= t for i in range(n)]),
                "round_drops": ([(p.requests_dropped + p.msgs_dropped
                                  - rd0[i]) & M32
                                 for i, p in enumerate(self.peers)], ones),
                "bloom_fill": ([sum(self.peers[i].digest.bits
                                    if cfg.store_diet
                                    else blooms[i].bits)
                                if cfg.sync_enabled else 0
                                for i in range(n)],
                               [cfg.sync_enabled] * n),
                "walk_streak": ([s & M32 for s in self.walk_streak],
                                members),
            }
            for name, kind, cap in tlm.hist_specs(cfg):
                vs, mask = data[name]
                counts = [0] * hb
                for v, m in zip(vs, mask):
                    if not m:
                        continue
                    if kind == "linear":
                        counts[min(v * hb // (cap + 1), hb - 1)] += 1
                    else:
                        counts[min(int(v).bit_length(), hb - 1)] += 1
                vals[f"hist_{name}"] = counts
        return vals

    # ---- comparison ---------------------------------------------------------

    def state_arrays(self) -> dict:
        """Dense arrays shaped like PeerState for trace-equality asserts."""
        cfg = self.cfg
        n, k, m = cfg.n_peers, cfg.k_candidates, cfg.msg_capacity
        # Plane-sized leaves (state.py init_state): the auth table,
        # blacklist and signature cache are zero-width when their
        # feature is compiled out; feature-gated stats counters follow
        # state.stats_gates.
        a = cfg.k_authorized if cfg.timeline_enabled else 0
        km = cfg.k_malicious if cfg.malicious_enabled else 0
        ns = n if cfg.double_meta_mask else 0
        s_w = cfg.store.staging
        aux_dt = np.dtype(cfg.aux_dtype)
        gates = _stats_gates(cfg)
        # Narrowed candidate-timestamp leaves (storediet cand_bits=16):
        # the device leaf holds u16 round-stamps (0 = never); the
        # oracle's f32 sim-seconds already passed through _qts at each
        # write, so _cand_stamp here is an exact inverse.
        cand_u16 = cfg.store.cand_bits == 16
        cand_dt = np.uint16 if cand_u16 else np.float32
        cand_never = 0 if cand_u16 else NEVER
        # Cohort-stagger leaves (zero-width when cohorts == 1; state.py)
        st_n = n if cfg.store_stagger else 0

        def gated(name, vals_u32):
            return (np.array(vals_u32, np.uint32) if gates[name]
                    else np.zeros((0,), np.uint32))
        out = {
            "alive": np.array([p.alive for p in self.peers]),
            "loaded": np.array([p.loaded for p in self.peers]),
            "session": np.array([p.session for p in self.peers], np.uint32),
            "global_time": np.array([p.global_time for p in self.peers],
                                    np.uint32),
            "cand_peer": np.full((n, k), NO_PEER, np.int32),
            "cand_last_walk": np.full((n, k), cand_never, cand_dt),
            "cand_last_stumble": np.full((n, k), cand_never, cand_dt),
            "cand_last_intro": np.full((n, k), cand_never, cand_dt),
            "store_gt": np.full((n, m), EMPTY_U32, np.uint32),
            "store_member": np.full((n, m), EMPTY_U32, np.uint32),
            # meta/flags mirror the engine's narrowed column dtypes
            # (config.META_DTYPE / FLAGS_DTYPE): u8 with EMPTY_META holes.
            "store_meta": np.full((n, m), EMPTY_META, np.uint8),
            "store_payload": np.full((n, m), EMPTY_U32, np.uint32),
            "store_aux": np.zeros((n, m), aux_dt),
            "sta_gt": np.full((n, s_w), EMPTY_U32, np.uint32),
            "sta_member": np.full((n, s_w), EMPTY_U32, np.uint32),
            "sta_meta": np.full((n, s_w), EMPTY_META, np.uint8),
            "sta_payload": np.full((n, s_w), EMPTY_U32, np.uint32),
            "sta_aux": np.zeros((n, s_w), aux_dt),
            "sta_flags": np.zeros((n, s_w), np.uint8),
            "digest": (np.array([p.digest.words() for p in self.peers],
                                np.uint32).reshape(n, cfg.bloom_bits // 32)
                       if (cfg.store_diet and cfg.sync_enabled)
                       else np.zeros((0, 0), np.uint32)),
            "cohort": np.array([p.cohort for p in self.peers][:st_n],
                               np.uint16),
            "epoch": np.array([p.epoch for p in self.peers][:st_n],
                              np.uint32),
            "store_flags": np.zeros((n, m), np.uint8),
            "fwd_gt": np.full((n, cfg.forward_buffer), EMPTY_U32, np.uint32),
            "fwd_member": np.full((n, cfg.forward_buffer), EMPTY_U32,
                                  np.uint32),
            "fwd_meta": np.full((n, cfg.forward_buffer), EMPTY_META,
                                np.uint8),
            "fwd_payload": np.full((n, cfg.forward_buffer), EMPTY_U32,
                                   np.uint32),
            "fwd_aux": np.full((n, cfg.forward_buffer),
                               np.iinfo(aux_dt).max, aux_dt),
            "auth_member": np.full((n, a), EMPTY_U32, np.uint32),
            "auth_mask": np.zeros((n, a), np.uint32),
            "auth_gt": np.zeros((n, a), np.uint32),
            "auth_rev": np.zeros((n, a), bool),
            "auth_issuer": np.full((n, a), EMPTY_U32, np.uint32),
            "auth_unwound": gated(
                "auth_unwound", [p.auth_unwound for p in self.peers]),
            "msgs_retro": gated(
                "msgs_retro", [p.msgs_retro for p in self.peers]),
            "dly_gt": np.full((n, cfg.delay_inbox), EMPTY_U32, np.uint32),
            "dly_member": np.full((n, cfg.delay_inbox), EMPTY_U32,
                                  np.uint32),
            "dly_meta": np.full((n, cfg.delay_inbox), EMPTY_META, np.uint8),
            "dly_payload": np.full((n, cfg.delay_inbox), EMPTY_U32,
                                   np.uint32),
            "dly_aux": np.zeros((n, cfg.delay_inbox), np.uint32),
            "dly_since": np.zeros((n, cfg.delay_inbox), np.uint32),
            "dly_src": np.full((n, cfg.delay_inbox), NO_PEER, np.int32),
            "proof_requests": gated(
                "proof_requests", [p.proof_requests for p in self.peers]),
            "proof_records": gated(
                "proof_records", [p.proof_records for p in self.peers]),
            "seq_requests": gated(
                "seq_requests", [p.seq_requests for p in self.peers]),
            "seq_records": gated(
                "seq_records", [p.seq_records for p in self.peers]),
            "mm_requests": gated(
                "mm_requests", [p.mm_requests for p in self.peers]),
            "mm_records": gated(
                "mm_records", [p.mm_records for p in self.peers]),
            "id_requests": gated(
                "id_requests", [p.id_requests for p in self.peers]),
            "id_records": gated(
                "id_records", [p.id_records for p in self.peers]),
            "msgs_delayed": gated(
                "msgs_delayed", [p.msgs_delayed for p in self.peers]),
            # chaos-harness leaves size to their knobs (state.py): a
            # disabled feature's leaf is zero-width
            "msgs_corrupt_dropped": (
                np.array([p.msgs_corrupt_dropped for p in self.peers],
                         np.uint32)
                if (cfg.faults.corrupt_rate > 0.0
                    or cfg.faults.flood_enabled)
                else np.zeros((0,), np.uint32)),
            "health": (np.array([p.health for p in self.peers], np.uint32)
                       if cfg.faults.health_checks
                       else np.zeros((0,), np.uint32)),
            "ge_bad": (np.array(self.ge_bad, bool)
                       if cfg.faults.ge_enabled
                       else np.zeros((0,), bool)),
            # recovery-plane leaves + counters (knob-sized, state.py)
            "backoff": (np.array([p.backoff for p in self.peers],
                                 np.uint8)
                        if cfg.recovery.enabled
                        else np.zeros((0,), np.uint8)),
            "quar_until": (np.array([p.quar_until for p in self.peers],
                                    np.uint32)
                           if cfg.recovery.enabled
                           else np.zeros((0,), np.uint32)),
            "repair_round": (np.array([p.repair_round
                                       for p in self.peers], np.uint32)
                             if cfg.recovery.enabled
                             else np.zeros((0,), np.uint32)),
            "recov_soft": (np.array([p.recov_soft for p in self.peers],
                                    np.uint32)
                           if cfg.recovery.enabled
                           else np.zeros((0,), np.uint32)),
            "recov_backoff": (np.array([p.recov_backoff
                                        for p in self.peers], np.uint32)
                              if cfg.recovery.enabled
                              else np.zeros((0,), np.uint32)),
            "recov_quarantine": (np.array([p.recov_quarantine
                                           for p in self.peers],
                                          np.uint32)
                                 if cfg.recovery.enabled
                                 else np.zeros((0,), np.uint32)),
            "recov_cleared": (np.array([p.recov_cleared
                                        for p in self.peers], np.uint32)
                              if cfg.recovery.enabled
                              else np.zeros((0, NUM_HEALTH_BITS),
                                            np.uint32)),
            # ingress-protection leaves + counters (knob-sized, state.py)
            "bucket": (np.array([p.bucket for p in self.peers],
                                np.uint8)
                       if cfg.overload.enabled
                       else np.zeros((0,), np.uint8)),
            "msgs_shed_rate": (np.array([p.msgs_shed_rate
                                         for p in self.peers], np.uint32)
                               if cfg.overload.enabled
                               else np.zeros((0,), np.uint32)),
            "msgs_shed_priority": (np.array([p.msgs_shed_priority
                                             for p in self.peers],
                                            np.uint32)
                                   if cfg.overload.enabled
                                   else np.zeros((0,), np.uint32)),
            # parallel-plane backpressure counter (state.stats_gates:
            # materialized only when the capped exchange is armed)
            "xshard_shed": gated("xshard_shed",
                                 [p.xshard_shed for p in self.peers]),
            # dissemination-tracing leaves + counters (knob-sized,
            # state.py; dispersy_tpu/traceplane.py)
            "trace_member": np.array(self.trace_member, np.uint32),
            "trace_gt": np.array(self.trace_gt, np.uint32),
            "trace_first": (np.array(
                [p.trace_first for p in self.peers], np.uint32)
                if cfg.trace.enabled
                else np.zeros((0, 0), np.uint32)),
            "trace_chan": (np.array(
                [p.trace_chan for p in self.peers], np.uint8)
                if cfg.trace.enabled
                else np.zeros((0, 0), np.uint8)),
            "trace_dups": (np.array(
                [p.trace_dups for p in self.peers], np.uint32)
                if cfg.trace.enabled
                else np.zeros((0, 0), np.uint32)),
            "trace_latch": (np.array(self.trace_latch, np.uint32)
                            .reshape(len(self.trace_latch), 3)
                            if cfg.trace.enabled
                            else np.zeros((0, 3), np.uint32)),
            "trace_delivered": (np.array(
                [p.trace_delivered for p in self.peers], np.uint32)
                if cfg.trace.enabled
                else np.zeros((0, NUM_CHANNELS), np.uint32)),
            "trace_dup": (np.array(
                [p.trace_dup for p in self.peers], np.uint32)
                if cfg.trace.enabled
                else np.zeros((0, NUM_CHANNELS), np.uint32)),
            # telemetry-plane leaves (knob-sized, state.py)
            "walk_streak": (np.array(self.walk_streak, np.uint32)
                            if cfg.telemetry.histograms
                            else np.zeros((0,), np.uint32)),
            "tele_row": np.array(self.tele_row, np.uint32),
            "tele_ring": np.array(self.tele_ring, np.uint32),
            "fr_ring": np.array(self.fr_ring, np.uint32),
            "fr_pos": (np.array([self.fr_pos & M32], np.uint32)
                       if cfg.telemetry.flight_recorder
                       else np.zeros((0,), np.uint32)),
            "mal_member": np.full((n, km), EMPTY_U32, np.uint32),
            "conflicts": gated("conflicts",
                               [p.conflicts for p in self.peers]),
            "convictions_rx": gated(
                "convictions_rx", [p.convictions_rx for p in self.peers]),
            "sig_target": np.array(
                [p.sig_target for p in self.peers][:ns], np.int32),
            "sig_meta": np.array(
                [p.sig_meta for p in self.peers][:ns], np.uint32),
            "sig_payload": np.array(
                [p.sig_payload for p in self.peers][:ns], np.uint32),
            "sig_gt": np.array(
                [p.sig_gt for p in self.peers][:ns], np.uint32),
            "sig_since": np.array(
                [p.sig_since for p in self.peers][:ns], np.uint32),
            "sig_signed": gated("sig_signed",
                                [p.sig_signed for p in self.peers]),
            "sig_done": gated("sig_done",
                              [p.sig_done for p in self.peers]),
            "sig_expired": gated("sig_expired",
                                 [p.sig_expired for p in self.peers]),
            "bytes_up": np.array([p.bytes_up & M32 for p in self.peers],
                                 np.uint32),
            "bytes_down": np.array([p.bytes_down & M32 for p in self.peers],
                                   np.uint32),
            "accepted_by_meta": np.array(
                [p.accepted_by_meta for p in self.peers], np.uint32),
            "msgs_forwarded": np.array([p.msgs_forwarded for p in self.peers],
                                       np.uint32),
            "msgs_rejected": gated(
                "msgs_rejected", [p.msgs_rejected for p in self.peers]),
            "msgs_direct": gated(
                "msgs_direct", [p.msgs_direct for p in self.peers]),
            "walk_success": np.array([p.walk_success for p in self.peers],
                                     np.uint32),
            "walk_fail": np.array([p.walk_fail for p in self.peers], np.uint32),
            "msgs_stored": np.array([p.msgs_stored for p in self.peers],
                                    np.uint32),
            "msgs_dropped": np.array([p.msgs_dropped for p in self.peers],
                                     np.uint32),
            "requests_dropped": np.array([p.requests_dropped
                                          for p in self.peers], np.uint32),
            "punctures": np.array([p.punctures for p in self.peers], np.uint32),
        }
        for i, p in enumerate(self.peers):
            for j, s in enumerate(p.slots):
                out["cand_peer"][i, j] = s.peer
                if cand_u16:
                    out["cand_last_walk"][i, j] = self._cand_stamp(s.walk)
                    out["cand_last_stumble"][i, j] = \
                        self._cand_stamp(s.stumble)
                    out["cand_last_intro"][i, j] = self._cand_stamp(s.intro)
                else:
                    out["cand_last_walk"][i, j] = s.walk
                    out["cand_last_stumble"][i, j] = s.stumble
                    out["cand_last_intro"][i, j] = s.intro
            for j, rec in enumerate(p.store):
                out["store_gt"][i, j] = rec.gt
                out["store_member"][i, j] = rec.member
                out["store_meta"][i, j] = rec.meta
                out["store_payload"][i, j] = rec.payload
                out["store_aux"][i, j] = rec.aux
                out["store_flags"][i, j] = rec.flags
            for j, rec in enumerate(p.staging):
                out["sta_gt"][i, j] = rec.gt
                out["sta_member"][i, j] = rec.member
                out["sta_meta"][i, j] = rec.meta
                out["sta_payload"][i, j] = rec.payload
                out["sta_aux"][i, j] = rec.aux
                out["sta_flags"][i, j] = rec.flags
            for j, rec in enumerate(p.fwd):
                out["fwd_gt"][i, j] = rec.gt
                out["fwd_member"][i, j] = rec.member
                out["fwd_meta"][i, j] = rec.meta
                out["fwd_payload"][i, j] = rec.payload
                out["fwd_aux"][i, j] = rec.aux
            for j, row in enumerate(p.auth):
                out["auth_member"][i, j] = row.member
                out["auth_mask"][i, j] = row.mask
                out["auth_gt"][i, j] = row.gt
                out["auth_rev"][i, j] = row.rev
                out["auth_issuer"][i, j] = row.issuer
            for j, (rec, since, src) in enumerate(p.delay):
                out["dly_gt"][i, j] = rec.gt
                out["dly_member"][i, j] = rec.member
                out["dly_meta"][i, j] = rec.meta
                out["dly_payload"][i, j] = rec.payload
                out["dly_aux"][i, j] = rec.aux
                out["dly_since"][i, j] = since
                out["dly_src"][i, j] = src
            for j, mb in enumerate(p.mal):
                out["mal_member"][i, j] = mb
        return out


def _self_test_rng():
    """The oracle's rand mirrors ops/rng bit-for-bit (import-time cheap check)."""
    import jax.numpy as jnp
    s = fold_seed(123, 456)
    js = _jrng.fold_seed(jnp.array([123, 456], jnp.uint32))
    assert int(js) == s, (int(js), s)
