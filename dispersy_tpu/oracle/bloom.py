"""Pure-Python Bloom filter oracle — bit-for-bit mirror of ops/bloom.py.

Plays the role the reference's ``bloomfilter.py`` plays for its tests
(reference: tests/test_bloomfilter.py — false-positive rate + round-trip):
an independent, obviously-correct implementation the TPU kernel is checked
against.  Every arithmetic step mirrors :mod:`dispersy_tpu.ops.hashing` /
:mod:`dispersy_tpu.ops.bloom` with explicit ``& 0xFFFFFFFF`` masking.
"""

from __future__ import annotations

M32 = 0xFFFFFFFF
GOLDEN = 0x9E3779B9
_C1 = 0x85EBCA6B
_C2 = 0xC2B2AE35
BLOOM_SEED_1 = 0x8F1BBCDC
BLOOM_SEED_2 = 0xCA62C1D6
BLOOM_SALT_SEED = 0x6ED9EBA1


def fmix32(x: int) -> int:
    x &= M32
    x ^= x >> 16
    x = (x * _C1) & M32
    x ^= x >> 13
    x = (x * _C2) & M32
    x ^= x >> 16
    return x


def hash_u32(x: int, seed: int) -> int:
    return fmix32((x & M32) ^ fmix32(seed))


def combine(h: int, v: int) -> int:
    h &= M32
    return (h ^ ((fmix32(v) + GOLDEN + ((h << 6) & M32) + (h >> 2)) & M32)) & M32


def record_hash(member: int, global_time: int, meta: int, payload: int) -> int:
    h = fmix32(member)
    h = combine(h, global_time)
    h = combine(h, meta)
    h = combine(h, payload)
    return h


def probe_bits(item_hash: int, n_bits: int, n_hashes: int,
               salt: int | None = None) -> list[int]:
    h = item_hash & M32
    if salt is not None:
        h ^= hash_u32(salt, BLOOM_SALT_SEED)
    h1 = hash_u32(h, BLOOM_SEED_1)
    h2 = hash_u32(h, BLOOM_SEED_2) | 1
    return [((h1 + j * h2) & M32) % n_bits for j in range(n_hashes)]


class OracleBloom:
    """Mirror of the packed-uint32 filter; reference: bloomfilter.py
    BloomFilter.  ``salt`` = the per-claim filter prefix (ops/bloom
    ``_h1_h2`` salt), re-randomizing the probe sequence per filter."""

    def __init__(self, n_bits: int, n_hashes: int,
                 salt: int | None = None) -> None:
        assert n_bits % 32 == 0
        self.n_bits = n_bits
        self.n_hashes = n_hashes
        self.salt = salt
        self.bits = [False] * n_bits

    def add(self, item_hash: int) -> None:
        for b in probe_bits(item_hash, self.n_bits, self.n_hashes,
                            self.salt):
            self.bits[b] = True

    def __contains__(self, item_hash: int) -> bool:
        return all(self.bits[b]
                   for b in probe_bits(item_hash, self.n_bits,
                                       self.n_hashes, self.salt))

    def words(self) -> list[int]:
        """Packed uint32 words, same layout as ops.bloom.pack_bits."""
        out = []
        for w in range(self.n_bits // 32):
            word = 0
            for i in range(32):
                if self.bits[32 * w + i]:
                    word |= 1 << i
            out.append(word)
        return out
