"""Pure-Python CPU oracle of the simulation semantics.

The reference's deepest invariants are encoded as behavioral tests
(reference: tests/debugcommunity/ — ``DebugCommunity`` + ``DebugNode`` drive
real stacks on loopback).  The rebuild's analogue is this package: a slow,
obvious, dict-and-loop implementation of the *same semantics* as the TPU
kernels, used by the test suite to check the kernels bit-for-bit (bloom) and
trace-for-trace (sync rounds) at tiny N.
"""
