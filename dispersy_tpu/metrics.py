"""Observability: aggregate metrics snapshots and a round log.

The reference exposes a pull-model statistics snapshot consumed by
Tribler's debug panel (reference: statistics.py ``DispersyStatistics`` /
``CommunityStatistics`` — walk success/failure, per-message-type counts,
drop/delay/success counts, endpoint byte totals) and decodes experiment
logs offline (reference: tool/ldecoder.py).  The rebuild's equivalents:

- :func:`snapshot` — one aggregate dict over the whole overlay (per-peer
  counters reduced on device, a handful of scalars cross to host);
- :class:`MetricsLog` — append per-round snapshots, dump JSON/JSONL — the
  in-repo replacement for the binary experiment logs;
- standard :mod:`logging` integration via the module logger
  ``dispersy_tpu.metrics`` (the reference configures per-module loggers
  the same way — logger.py).
"""

from __future__ import annotations

import json
import logging
import os

import jax.numpy as jnp
import numpy as np

from dispersy_tpu.config import EMPTY_U32, NO_PEER, CommunityConfig
from dispersy_tpu.engine import killed_mask
from dispersy_tpu.faults import health_report
from dispersy_tpu.state import PeerState

logger = logging.getLogger(__name__)


def snapshot(state: PeerState, cfg: CommunityConfig) -> dict:
    """Aggregate overlay metrics (DispersyStatistics snapshot analogue).

    Everything reduces on device first; only scalars cross to host.
    Counters are cumulative (as the reference's are); rates are this
    snapshot's view of them.
    """
    s = state.stats
    members = state.alive & ~state.is_tracker
    n_members = jnp.maximum(jnp.sum(members), 1)

    def total(counter) -> int:
        # Host-side uint64 reduction: on-device sums stay uint32 without
        # jax_enable_x64 and would wrap (1M peers exceed 2^32 aggregate
        # bytes within one round).  Counters are [N]-shaped, so one host
        # transfer per field is cheap next to the step itself.
        return int(np.asarray(counter, dtype=np.uint64).sum())

    walk_success = total(s.walk_success)
    walk_fail = total(s.walk_fail)
    out = {
        "round": int(state.round_index),
        "sim_time": float(state.time),
        "alive_members": int(jnp.sum(members)),
        "killed": int(jnp.sum(killed_mask(state.store_meta))),
        # walker (statistics.py walk_success / walk_failure)
        "walk_success": walk_success,
        "walk_fail": walk_fail,
        "walk_success_rate": walk_success / max(walk_success + walk_fail, 1),
        # store pipeline (drop/delay/success counts)
        "msgs_stored": total(s.msgs_stored),
        "msgs_dropped": total(s.msgs_dropped),
        "msgs_rejected": total(s.msgs_rejected),
        "msgs_forwarded": total(s.msgs_forwarded),
        "msgs_direct": total(s.msgs_direct),
        "msgs_delayed": total(s.msgs_delayed),
        # chaos harness (dispersy_tpu/faults.py): records dropped by the
        # intake hash re-check (corruption / flood junk); 0 when the
        # leaf is compiled out (zero-width)
        "msgs_corrupt_dropped": total(s.msgs_corrupt_dropped),
        "requests_dropped": total(s.requests_dropped),
        "punctures": total(s.punctures),
        # double-signed flow
        "sig_signed": total(s.sig_signed),
        "sig_done": total(s.sig_done),
        "sig_expired": total(s.sig_expired),
        # malicious-member convictions observed (malicious_enabled)
        "conflicts": total(s.conflicts),
        # endpoint byte totals (endpoint.py total_up / total_down).
        # NOTE: the per-peer device counters themselves wrap mod 2^32 by
        # design (state.py); the host reduction is exact over them.
        "bytes_up": total(s.bytes_up),
        "bytes_down": total(s.bytes_down),
        # occupancy (how full the bounded structures run)
        "store_fill": float(jnp.mean(
            jnp.sum(state.store_gt != jnp.uint32(EMPTY_U32), axis=1)
            / cfg.msg_capacity)),
        "candidate_fill": float(jnp.mean(jnp.where(
            members,
            jnp.sum(state.cand_peer != NO_PEER, axis=1) / cfg.k_candidates,
            0)) * (cfg.n_peers / float(n_members))),
        # health sentinels (faults.HEALTH_* latched bits; zero-width
        # leaf -> clean zeros when health_checks is off): health_or /
        # health_flagged / per-bit flagged-peer counts
        **health_report(state, cfg),
        # per-meta acceptance (statistics.py per-message-name counts);
        # bucket n_meta = the dispersy-* control band
        "accepted_by_meta": [
            int(x) for x in
            np.asarray(s.accepted_by_meta, dtype=np.uint64).sum(axis=0)],
    }
    return out


class MetricsLog:
    """Per-round metrics accumulator (tool/ldecoder.py's role, JSON-native).

    ``append`` records a snapshot (plus arbitrary extra fields, e.g. a
    coverage value); ``dump`` writes the whole run as one JSON artifact;
    ``dump_jsonl`` streams one line per round.
    """

    def __init__(self, meta: dict | None = None):
        self.meta = meta or {}
        self.rows: list[dict] = []

    def append(self, state: PeerState, cfg: CommunityConfig,
               **extra) -> dict:
        row = snapshot(state, cfg)
        row.update(extra)
        self.rows.append(row)
        logger.debug("round %d: %s", row["round"], row)
        return row

    def dump(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"meta": self.meta, "rounds": self.rows}, f, indent=1)

    def dump_jsonl(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            for row in self.rows:
                f.write(json.dumps(row) + "\n")

    def dump_binary(self, path: str) -> None:
        """Packed fixed-schema form (see :mod:`dispersy_tpu.binlog`) —
        the experiment-rate format tool/ldecoder.py decodes in the
        reference.  Scalar fields of the first row fix the schema;
        non-scalar extras (e.g. accepted_by_meta) stay JSON-only."""
        from dispersy_tpu import binlog
        if not self.rows:
            raise ValueError("nothing logged")
        fields = [k for k, v in self.rows[0].items()
                  if isinstance(v, (int, float)) and not isinstance(v, bool)]
        with binlog.BinaryLog(path, fields, meta=self.meta) as log:
            for row in self.rows:
                log.append(row)

    def series(self, key: str) -> list:
        """One metric across rounds (curve extraction)."""
        return [row.get(key) for row in self.rows]
