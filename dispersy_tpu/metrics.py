"""Observability: aggregate metrics snapshots and a round log.

The reference exposes a pull-model statistics snapshot consumed by
Tribler's debug panel (reference: statistics.py ``DispersyStatistics`` /
``CommunityStatistics`` — walk success/failure, per-message-type counts,
drop/delay/success counts, endpoint byte totals) and decodes experiment
logs offline (reference: tool/ldecoder.py).  The rebuild's equivalents:

- :func:`snapshot` — one aggregate dict over the whole overlay (per-peer
  counters reduced on device, a handful of scalars cross to host);
- :class:`MetricsLog` — append per-round snapshots, dump JSON/JSONL — the
  in-repo replacement for the binary experiment logs;
- standard :mod:`logging` integration via the module logger
  ``dispersy_tpu.metrics`` (the reference configures per-module loggers
  the same way — logger.py).
"""

from __future__ import annotations

import json
import logging
import os

import jax.numpy as jnp
import numpy as np

from dispersy_tpu import telemetry as tlm
from dispersy_tpu.config import EMPTY_U32, NO_PEER, CommunityConfig
from dispersy_tpu.engine import counter_matrix, killed_mask
from dispersy_tpu.faults import health_report
from dispersy_tpu.state import PeerState

logger = logging.getLogger(__name__)


def snapshot(state: PeerState, cfg: CommunityConfig) -> dict:
    """Aggregate overlay metrics (DispersyStatistics snapshot analogue).

    Two paths:

    - **Fused** (``cfg.telemetry.enabled`` and at least one step has
      run): the jitted step already reduced every aggregate into the
      packed ``state.tele_row`` at its wrap-up, so the snapshot is ONE
      device->host transfer of that row + host-side unpacking — no
      device work at all.  The row reflects the state as of the last
      ``step``; between-step mutations (``create_messages`` & co) show
      up in the next round's row, which is exactly when the scenario
      logger reads it.
    - **Legacy** (telemetry off, or round 0 before any step): per-field
      device reductions, with all ``[N]`` u32 counters crossing in one
      stacked transfer instead of one transfer per field.

    Counters are cumulative (as the reference's are); rates are this
    snapshot's view of them.
    """
    if cfg.telemetry.enabled:
        row = np.asarray(state.tele_row)     # the ONE host transfer
        if int(row[0]):                       # word 0 = post-step round
            return tlm.row_to_snapshot(row, cfg)
    s = state.stats
    members = state.alive & ~state.is_tracker
    n_members = jnp.maximum(jnp.sum(members), 1)
    n = cfg.n_peers

    # Host-side uint64 reduction: on-device sums stay uint32 without
    # jax_enable_x64 and would wrap (1M peers exceed 2^32 aggregate
    # bytes within one round).  ONE stacked [N, C] transfer covers every
    # u32 counter; engine.counter_matrix is the same column stack the
    # fused row reduces, so the two paths cannot drift.
    stacked = np.asarray(counter_matrix(s, n))
    totals = dict(zip(tlm.U64_COUNTERS,
                      stacked.astype(np.uint64).sum(axis=0).tolist()))
    totals = {k: int(v) for k, v in totals.items()}

    walk_success = totals["walk_success"]
    walk_fail = totals["walk_fail"]
    out = {
        "round": int(state.round_index),
        "sim_time": float(state.time),
        "alive_members": int(jnp.sum(members)),
        "killed": int(jnp.sum(killed_mask(state.store_meta))),
        # walker (statistics.py walk_success / walk_failure)
        "walk_success": walk_success,
        "walk_fail": walk_fail,
        "walk_success_rate": walk_success / max(walk_success + walk_fail, 1),
        # store pipeline (drop/delay/success counts), chaos-harness
        # corrupt drops, double-signed flow, convictions, endpoint byte
        # totals — the U64_COUNTERS band (telemetry.py documents each).
        **{nm: totals[nm] for nm in tlm.U64_COUNTERS[2:]},
        # occupancy (how full the bounded structures run); the logical
        # store is ring ∪ staging under the byte diet (storediet.py),
        # so the fraction is over the combined capacity and stays <= 1
        "store_fill": float(jnp.mean(
            (jnp.sum(state.store_gt != jnp.uint32(EMPTY_U32), axis=1)
             + (jnp.sum(state.sta_gt != jnp.uint32(EMPTY_U32), axis=1)
                if cfg.store_diet else 0))
            / (cfg.msg_capacity + cfg.store.staging))),
        "candidate_fill": float(jnp.mean(jnp.where(
            members,
            jnp.sum(state.cand_peer != NO_PEER, axis=1) / cfg.k_candidates,
            0)) * (cfg.n_peers / float(n_members))),
        # health sentinels (faults.HEALTH_* latched bits; zero-width
        # leaf -> clean zeros when health_checks is off): health_or /
        # health_flagged / per-bit flagged-peer counts
        **health_report(state, cfg),
        # per-meta acceptance (statistics.py per-message-name counts);
        # bucket n_meta = the dispersy-* control band
        "accepted_by_meta": [
            int(x) for x in
            np.asarray(s.accepted_by_meta, dtype=np.uint64).sum(axis=0)],
    }
    if cfg.trace.enabled:
        # Dissemination-tracing totals — the SAME key set (and shared
        # definitions, traceplane.trace_totals) the fused row surfaces
        # via telemetry.row_to_snapshot, so the two paths stay
        # schema-identical (dump_binary's contract).
        from dispersy_tpu.traceplane import trace_totals
        out.update(trace_totals(state, cfg))
    if cfg.overload.enabled:
        # Ingress-protection totals — the SAME key set (and shared
        # definitions, overload.shed_totals) the fused row surfaces via
        # telemetry.row_to_snapshot, so the two paths stay
        # schema-identical (dump_binary's contract).
        from dispersy_tpu.overload import shed_totals
        out.update(shed_totals(s))
        bk = np.asarray(state.bucket)
        out["bucket_exhausted"] = int((bk == 0).sum()) if bk.size else 0
    if cfg.recovery.enabled:
        # Recovery-plane totals + instantaneous availability — the SAME
        # key set (and shared definitions, recovery.action_totals /
        # availability_of) the fused row surfaces via
        # telemetry.row_to_snapshot, so the two paths stay
        # schema-identical (dump_binary's contract).
        from dispersy_tpu.recovery import action_totals, availability_of
        out.update(action_totals(s))
        out["availability"] = availability_of(out["health_flagged"],
                                              cfg.n_peers)
    if cfg.telemetry.histograms:
        # Histograms only exist in-step; a pre-first-step snapshot on a
        # histogram-enabled config reports them EMPTY so its key set
        # matches the fused rows that follow (dump_binary validates
        # every row against one schema).
        for name, _, _ in tlm.hist_specs(cfg):
            out[f"hist_{name}_p50"] = 0
            out[f"hist_{name}_p99"] = 0
            out[f"hist_{name}"] = [0] * cfg.telemetry.hist_buckets
    return out


def fleet_snapshot(fstate: PeerState, cfg: CommunityConfig) -> dict:
    """Cross-replica aggregate over a fleet-stacked state
    (dispersy_tpu/fleet.py; FLEET.md): per-field
    ``{"min", "max", "sum", "mean"}`` across the replica axis, reduced
    ON DEVICE (``ops.fleet.band_reduce``) so the whole fleet's
    statistics cross to host in ONE [3, RW] transfer — the replica-
    plane analogue of :func:`snapshot`'s fused path.  Requires
    ``cfg.telemetry.enabled`` and at least one fleet step (raises
    before the first row exists, matching the band's contract that
    word 0 is a real round)."""
    from dispersy_tpu import fleet

    snap = fleet.band_snapshot(fstate, cfg)
    if snap["round"]["min"] == 0:
        raise ValueError("fleet_snapshot before the first fleet_step: "
                         "the packed rows are all-zero (telemetry row "
                         "word 0 is the post-step round, never 0)")
    return snap


class MetricsLog:
    """Per-round metrics accumulator (tool/ldecoder.py's role, JSON-native).

    ``append`` records a snapshot (plus arbitrary extra fields, e.g. a
    coverage value); ``dump`` writes the whole run as one JSON artifact;
    ``dump_jsonl`` streams one line per round.
    """

    def __init__(self, meta: dict | None = None):
        self.meta = meta or {}
        self.rows: list[dict] = []

    def append(self, state: PeerState, cfg: CommunityConfig,
               **extra) -> dict:
        row = snapshot(state, cfg)
        row.update(extra)
        self.rows.append(row)
        logger.debug("round %d: %s", row["round"], row)
        return row

    def extend_from_ring(self, state: PeerState,
                         cfg: CommunityConfig) -> list:
        """Drain the device-resident round-history ring
        (``state.tele_ring``, written inside the jitted step) into the
        log: ONE device->host transfer yields the per-round snapshot of
        every round since the last drain — how a ``multi_step`` batch
        of K rounds reports its full metrics history without K host
        round trips.  Requires ``cfg.telemetry.history > 0``; rounds
        already logged are skipped, and a drain gap longer than the
        ring depth raises (rows would be silently missing otherwise).
        Returns the appended rows.
        """
        if cfg.telemetry.history <= 0:
            raise ValueError("extend_from_ring needs telemetry.history "
                             "> 0 (the device ring is compiled out)")
        ring = np.asarray(state.tele_ring)   # the ONE host transfer
        rows = tlm.ring_rows(ring, cfg)
        last = self.rows[-1]["round"] if self.rows else 0
        fresh = [r for r in rows if r["round"] > last]
        if fresh and fresh[0]["round"] > last + 1:
            raise ValueError(
                f"telemetry ring overflowed: oldest available round is "
                f"{fresh[0]['round']} but the log ends at {last} — "
                f"drain at least every telemetry.history="
                f"{cfg.telemetry.history} rounds")
        for row in fresh:
            self.rows.append(row)
            logger.debug("round %d: %s", row["round"], row)
        return fresh

    def dump(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"meta": self.meta, "rounds": self.rows}, f, indent=1)

    def dump_jsonl(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            for row in self.rows:
                f.write(json.dumps(row) + "\n")

    @staticmethod
    def _scalar_fields(row: dict) -> list:
        return [k for k, v in row.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)]

    def dump_binary(self, path: str) -> None:
        """Packed fixed-schema form (see :mod:`dispersy_tpu.binlog`) —
        the experiment-rate format tool/ldecoder.py decodes in the
        reference.  Scalar fields of the first row fix the schema;
        non-scalar extras (e.g. accepted_by_meta, hist_* bucket lists)
        stay JSON-only.  Every later row is validated against that
        schema BEFORE anything is written: a row with a missing or
        extra scalar key would silently misalign the packed matrix
        (every later field shifted one slot), so the mismatch raises
        with the offending row and field names instead."""
        from dispersy_tpu import binlog
        if not self.rows:
            raise ValueError("nothing logged")
        fields = self._scalar_fields(self.rows[0])
        schema = set(fields)
        for i, row in enumerate(self.rows[1:], start=1):
            got = set(self._scalar_fields(row))
            missing, extra = schema - got, got - schema
            if missing or extra:
                raise ValueError(
                    f"dump_binary: row {i} (round {row.get('round')!r}) "
                    "does not match the schema fixed by row 0 — "
                    f"missing {sorted(missing)}, unexpected "
                    f"{sorted(extra)}; dump_jsonl handles ragged rows")
        with binlog.BinaryLog(path, fields, meta=self.meta,
                              strict=True) as log:
            for row in self.rows:
                log.append(row)

    def series(self, key: str) -> list:
        """One metric across rounds (curve extraction)."""
        return [row.get(key) for row in self.rows]
