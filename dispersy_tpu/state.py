"""PeerState: the entire overlay as one device-sharded pytree.

Everything the reference keeps in Python objects + SQLite becomes dense array
state here (SURVEY.md §7 stage 1):

- the candidate dict per community (reference: candidate.py ``WalkCandidate``
  with walk/stumble/intro timestamps) -> fixed ``k_candidates`` slots per
  peer holding a peer index + three timestamps.  A slot's *category* is
  derived from which timestamps are still within their lifetimes (walked >
  stumbled > introduced, mirroring ``WalkCandidate.get_category``), so no
  separate category field can go stale.
- the SQLite ``sync`` table (reference: dispersydatabase.py — columns
  community, member, global_time, meta_message, packet, undone;
  UNIQUE(community, member, global_time)) -> a fixed-capacity ring of packed
  uint32 records per peer, kept sorted by (global_time, member, meta,
  payload); empty slots hold the ``EMPTY_U32`` sentinel so they sort last.
- the walk ``RequestCache`` entry (reference: requestcache.py
  ``IntroductionRequestCache``, ~10.5 s timeout) -> one outstanding walk
  target + timestamp per peer.
- ``DispersyStatistics`` counters (reference: statistics.py) -> uint32
  counter columns.

The peer axis (leading axis of every array) is the sharding axis: shard it
over a ``jax.sharding.Mesh`` and the whole step runs SPMD with XLA inserting
the collectives at the delivery kernel's sort/scatter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from dispersy_tpu import telemetry as tlm
from dispersy_tpu.config import (EMPTY_META, EMPTY_U32, FLAGS_DTYPE,
                                 META_DTYPE, NO_PEER, CommunityConfig)

from dispersy_tpu.ops.store import empty_of

NEVER = -1.0e9  # "timestamp never happened" for float32 sim-seconds fields


@struct.dataclass
class Stats:
    """Per-peer counters; reference: statistics.py DispersyStatistics."""
    walk_success: jnp.ndarray     # u32[N] intro-responses received in time
    walk_fail: jnp.ndarray        # u32[N] walk timeouts
    msgs_stored: jnp.ndarray      # u32[N] new records inserted into store
    msgs_dropped: jnp.ndarray     # u32[N] records dropped (inbox/store/auth full)
    requests_dropped: jnp.ndarray  # u32[N] intro-requests dropped (inbox full)
    punctures: jnp.ndarray        # u32[N] punctures sent (as introduced peer)
    msgs_forwarded: jnp.ndarray   # u32[N] push-forward packets sent
    msgs_rejected: jnp.ndarray    # u32[N] records refused by the check
    #   pipeline (Timeline permission or sequence-order violations —
    #   reference: statistics.py drop counts from check_callback outcomes)
    msgs_direct: jnp.ndarray      # u32[N] DirectDistribution records received
    msgs_delayed: jnp.ndarray     # u32[N] records parked awaiting a
    #   permission proof (reference: statistics.py delay counts from
    #   check_callback DelayMessageByProof outcomes; config.delay_inbox)
    msgs_corrupt_dropped: jnp.ndarray  # u32[N] delivered records dropped
    #   by the intake hash re-check: in-transit corruption and byzantine
    #   flood junk (dispersy_tpu/faults.py corrupt_rate/flood_senders —
    #   the reference's conversion.py decode/signature failures).
    #   Zero-width when neither channel is enabled (state.py PeerState
    #   `health` note)
    # Ingress-protection shed streams (dispersy_tpu/overload.py;
    # OVERLOAD.md attribution table).  Zero-width unless
    # cfg.overload.enabled — the `health` idiom.  Deliberately OUTSIDE
    # the msgs_dropped/requests_dropped families: admission sheds must
    # never trip the victim's health_drop_limit sentinel.
    msgs_shed_rate: jnp.ndarray   # u32[N] push/flood packets this SENDER
    #   attempted beyond its token-bucket credit (rate-gate shed,
    #   attributed to the sender — a flooder's counter balloons)
    msgs_shed_priority: jnp.ndarray  # u32[N] packets shed from this
    #   RECEIVER's push inbox by class-ordered admission under overflow
    #   (the drops that used to blame the flooded victim)
    # Cross-shard exchange backpressure (dispersy_tpu/shardplane.py;
    # PARALLEL.md).  Zero-width unless the parallel plane caps the
    # exchange (state.stats_gates) — the `health` idiom.  Like the
    # overload sheds, deliberately outside the msgs_dropped family:
    # a full send bucket must never trip anyone's health sentinel.
    xshard_shed: jnp.ndarray      # u32[N] push edges this SENDER lost
    #   to a full per-destination-shard send bucket (ragged-exchange
    #   overflow, ops/inbox.deliver_ragged; repaired by the bloom pull
    #   like staging overflow)
    # Dissemination-tracing delivery accounting (dispersy_tpu/
    # traceplane.py; OBSERVABILITY.md "Dissemination tracing").
    # Zero-width unless cfg.trace.enabled — the `health` idiom.
    # Receiver-side counts over the TRACKED records only, by delivery
    # channel (columns = traceplane.CHANNEL_NAMES order):
    trace_delivered: jnp.ndarray  # u32[N, 4] useful (first-landing)
    #   deliveries this peer received, by channel — ROADMAP item 3's
    #   per-channel usefulness signal
    trace_dup: jnp.ndarray        # u32[N, 4] duplicate deliveries of
    #   tracked records (already known / in-batch dup / digest FP /
    #   staging overflow), by channel — the redundancy numerator
    # Recovery-plane action counters (dispersy_tpu/recovery.py;
    # RECOVERY.md).  All zero-width unless cfg.recovery.enabled — the
    # `health` idiom:
    recov_soft: jnp.ndarray       # u32[N] soft-repair actions (bits
    #   latched >= 1 round acted on + cleared at the wrap-up)
    recov_backoff: jnp.ndarray    # u32[N] walk-backoff exponent bumps
    recov_quarantine: jnp.ndarray  # u32[N] quarantine escalations
    #   (supervised wiped-disk rebirths)
    recov_cleared: jnp.ndarray    # u32[N, NUM_HEALTH_BITS] health bits
    #   cleared by a recovery action, per sentinel bit — the MTTR
    #   denominator (recovery.mttr_report)
    # Active missing-proof round trips (reference: community.py
    # on_missing_proof serving dispersy-missing-proof requests;
    # config.proof_requests):
    proof_requests: jnp.ndarray   # u32[N] missing-proof requests served
    proof_records: jnp.ndarray    # u32[N] proof records received back
    # Active missing-sequence round trips (reference: community.py
    # on_missing_sequence; config.seq_requests):
    seq_requests: jnp.ndarray     # u32[N] missing-sequence requests served
    seq_records: jnp.ndarray      # u32[N] gap-fill records received back
    # Active missing-message round trips (reference: community.py
    # on_missing_message; config.msg_requests):
    mm_requests: jnp.ndarray      # u32[N] missing-message requests served
    mm_records: jnp.ndarray       # u32[N] named records received back
    # Active missing-identity round trips (reference: community.py
    # on_missing_identity; config.identity_requests):
    id_requests: jnp.ndarray      # u32[N] missing-identity requests served
    id_records: jnp.ndarray       # u32[N] identity records received back
    # Double-signed flow counters (reference: statistics.py counts
    # signature-request/-response traffic; SURVEY §3.5):
    sig_signed: jnp.ndarray       # u32[N] countersignatures granted (B side)
    sig_done: jnp.ndarray         # u32[N] double-signed records completed (A)
    sig_expired: jnp.ndarray      # u32[N] signature requests timed out (A)
    conflicts: jnp.ndarray        # u32[N] double-sign conflicts observed
    #   (malicious-member convictions at this peer; malicious_enabled)
    convictions_rx: jnp.ndarray   # u32[N] convictions adopted from gossiped
    #   dispersy-malicious-proof claims (config.malicious_gossip)
    # Retroactive permission re-walk (reference: timeline.py lazy chain
    # re-validation — order-independent verdicts; engine._retro_pass):
    auth_unwound: jnp.ndarray     # u32[N] auth-table rows unwound when a
    #   late revoke invalidated their granting chain
    msgs_retro: jnp.ndarray       # u32[N] stored records retro-rejected
    #   after a revoke unwound the chain that had permitted them
    # Byte-equivalent traffic totals (reference: endpoint.py total_up /
    # total_down).  Sent bytes count at the sender pre-loss (the reference
    # counts at sendto()); received bytes count per accepted inbox slot
    # (recvfrom() — packets lost or overflowing the socket buffer never
    # reach the counter).  uint32, wraps mod 2^32 on very long runs.
    bytes_up: jnp.ndarray         # u32[N]
    bytes_down: jnp.ndarray      # u32[N]
    # Records newly accepted into the store pipeline per meta (pre-capacity;
    # reference: statistics.py per-message-name success counts).  Buckets:
    # [0, n_meta) = user metas, bucket n_meta = the dispersy-* control band.
    accepted_by_meta: jnp.ndarray  # u32[N, n_meta + 1]


@struct.dataclass
class PeerState:
    # ---- liveness / identity ----
    alive: jnp.ndarray        # bool[N]
    loaded: jnp.ndarray       # bool[N]  community instance loaded (reference:
    #   dispersy.py get_community(load=True) / define_auto_load;
    #   Community.load_community/unload_community — an unloaded peer's
    #   process is up and its store persists, but it neither walks,
    #   serves, nor takes records in until (re)loaded)
    is_tracker: jnp.ndarray   # bool[N]  bootstrap peers (tool/tracker.py role)
    session: jnp.ndarray      # u32[N]   bumped on churn rejoin
    global_time: jnp.ndarray  # u32[N]   Lamport clock (community.py claim_global_time)
    health: jnp.ndarray       # u32[N]   latched health-sentinel bitmask
    #   (faults.HEALTH_*; set inside the fused step when
    #   cfg.faults.health_checks, cleared only by churn rebirth — a
    #   wiped-disk restart is a new process — or by a recovery-plane
    #   repair action when cfg.recovery.enabled, RECOVERY.md).  Sized ZERO-WIDTH when
    #   health_checks is off — the dly_* idiom — so the disabled fused
    #   step stays cost-analysis-identical (faults.adapt_state resizes
    #   on a SetFault knob flip).
    ge_bad: jnp.ndarray       # bool[N]  Gilbert–Elliott channel state
    #   (True = bursty-loss bad state; faults.FaultModel.ge_*).  A
    #   property of the peer's access link — like the NAT type it
    #   survives churn rebirth and unload/load.  Zero-width when the GE
    #   channel is disabled (see `health`).

    # ---- recovery plane (dispersy_tpu/recovery.py; RECOVERY.md).
    #      Every leaf is zero-width unless cfg.recovery.enabled — the
    #      `health` idiom (recovery.adapt_state resizes on a
    #      SetRecovery flip). ----
    backoff: jnp.ndarray      # u8[N] walk-backoff exponent: a peer
    #   with exponent e walks one round in 2^e (ops/recovery.
    #   backoff_gate), bumped by drop-limit repairs, decayed on clean
    #   rounds.  Process memory: reset by churn rebirth.
    quar_until: jnp.ndarray   # u32[N] first round the peer may walk /
    #   be selected again after a quarantine escalation (0 = never
    #   quarantined).  The OVERLAY's decision about the peer — like the
    #   NAT type it survives churn rebirth.
    repair_round: jnp.ndarray  # u32[N] post-step round of the last
    #   soft repair (0 = never) — the re-latch hysteresis counter: a
    #   bit re-latching within recovery.requarantine_window of this
    #   escalates to quarantine.  Reset by churn rebirth.

    # ---- ingress-protection plane (dispersy_tpu/overload.py;
    #      OVERLOAD.md).  Zero-width unless cfg.overload.enabled — the
    #      `health` idiom (overload.adapt_state resizes on a
    #      SetOverload flip). ----
    bucket: jnp.ndarray       # u8[N] per-sender token-bucket balance:
    #   refilled bucket_rate/round (ops/overload.bucket_refill), spent
    #   by each attempted push/flood packet, capped at bucket_depth.
    #   The OVERLAY's rate-limiter view of the sender identity — like
    #   the NAT type and ge_bad it survives churn rebirth (a wiped-disk
    #   restart does not refill the neighborhood's patience).

    # ---- telemetry plane (dispersy_tpu/telemetry.py; OBSERVABILITY.md).
    #      Every leaf is zero-width while its TelemetryConfig knob is
    #      off — the `health` idiom — so disabled telemetry keeps the
    #      fused step cost-analysis-identical. ----
    walk_streak: jnp.ndarray  # u32[N] consecutive successful walks
    #   (reset by a walk failure; feeds the walk_streak histogram).
    #   Stats-adjacent runtime state: like the walk_success/walk_fail
    #   counters it derives from, it survives churn rebirth and
    #   unload/load.  Zero-width unless telemetry.histograms.
    tele_row: jnp.ndarray     # u32[RW] the last step's packed metrics
    #   row (telemetry.row_schema layout; word 0 = post-step round, so
    #   all-zero means "no step has run").  metrics.snapshot reads THIS
    #   in one transfer instead of ~25 per-field reductions.  Width
    #   telemetry.row_width(cfg); zero-width unless telemetry.enabled.
    tele_ring: jnp.ndarray    # u32[H, RW] device-resident round-history
    #   ring: the packed rows of the last H rounds, written inside step
    #   at slot round % H — multi_step runs K rounds on device and
    #   MetricsLog.extend_from_ring drains the whole history in one
    #   transfer.  Zero rows unless telemetry.history > 0.
    fr_ring: jnp.ndarray      # u32[D, FLIGHT_WIDTH] flight recorder:
    #   per-peer event records for newly health-flagged peers
    #   (telemetry.FLIGHT_FIELDS).  Zero rows unless
    #   telemetry.flight_recorder > 0 (which requires health_checks).
    fr_pos: jnp.ndarray       # u32[1] flight records ever written (the
    #   decoder's wrap cursor); zero-width with the recorder off.

    # ---- dissemination-tracing plane (dispersy_tpu/traceplane.py;
    #      OBSERVABILITY.md "Dissemination tracing").  Every leaf is
    #      zero-width unless cfg.trace.enabled — the `health` idiom.
    #      Lineage is DISK-like state: it rides checkpoints (v15),
    #      survives unload/load and app restarts, and the per-peer
    #      rows wipe with the store on churn / quarantine rebirth.
    #      The key registry and latches are overlay-global (one row
    #      per tracked slot, not per peer). ----
    trace_member: jnp.ndarray  # u32[T] tracked record's author;
    #   EMPTY_U32 = free slot (engine.track_record assigns)
    trace_gt: jnp.ndarray      # u32[T] tracked record's global_time
    trace_first: jnp.ndarray   # u32[N, T] first-arrival round (the
    #   post-step round the record first landed in this peer's logical
    #   store; 0 = not yet)
    trace_chan: jnp.ndarray    # u8[N, T] first-delivery channel code
    #   (traceplane.CH_*; 0 = none yet)
    trace_dups: jnp.ndarray    # u32[N, T] duplicate deliveries of the
    #   slot's record at this peer
    trace_latch: jnp.ndarray   # u32[T, 3] first post-step round
    #   coverage reached {50, 90, 99}% of alive members
    #   (traceplane.LATCH_PCTS order; 0 = not reached)

    # ---- candidate table [N, K] ----
    # The three timestamp columns are f32 sim-seconds by default, or
    # quantized u16 round-stamps (``round + 1``, 0 = never) under the
    # byte-diet opt-in ``store.cand_bits=16`` — the walker always
    # computes on f32 seconds; engine._tab dequantizes on the way in and
    # the wrap-up quantizes on the way out (truncating at the store
    # boundary, the aux_bits rule).
    cand_peer: jnp.ndarray         # i32, NO_PEER = empty
    cand_last_walk: jnp.ndarray    # sim-seconds of last successful walk to it
    cand_last_stumble: jnp.ndarray  # last time it contacted us
    cand_last_intro: jnp.ndarray   # last time it was introduced to us

    # ---- message store [N, M], sorted by (gt, member, meta, payload) ----
    store_gt: jnp.ndarray      # u32, EMPTY_U32 = hole
    store_member: jnp.ndarray  # u32
    store_meta: jnp.ndarray    # u8, EMPTY_META = hole (config.META_DTYPE)
    store_payload: jnp.ndarray  # u32
    store_aux: jnp.ndarray     # u32 second payload word (see StoreCols.aux);
    #   u16 under the byte-diet opt-in (config.aux_dtype)
    store_flags: jnp.ndarray   # u8 bit0 = undone (sync table's `undone` column)

    # ---- byte-diet staging buffer [N, S] (dispersy_tpu/storediet.py;
    #      STORE section in README).  Accepted records in delivery
    #      order, EMPTY holes at the END (valid-prefix invariant);
    #      merged into the sorted ring every compact_every rounds by
    #      ops/store.store_insert.  Logically part of the store (the
    #      database's write buffer): it survives unload/load like the
    #      ring and is wiped with it on churn/quarantine rebirth.
    #      Every leaf is zero-width unless cfg.store.staging > 0 — the
    #      `health` idiom. ----
    sta_gt: jnp.ndarray       # u32, EMPTY_U32 = free slot
    sta_member: jnp.ndarray   # u32
    sta_meta: jnp.ndarray     # u8, EMPTY_META = free slot
    sta_payload: jnp.ndarray  # u32
    sta_aux: jnp.ndarray      # config.aux_dtype
    sta_flags: jnp.ndarray    # u8
    # Incremental Bloom digest u32[N, bloom_words]: the claimed slice's
    # bloom under the CURRENT epoch's salt (storediet.epoch_of), OR-
    # updated from each round's landed arrivals and fully rebuilt from
    # the ring at compaction.  Doubles as the intake freshness filter.
    # Zero-width unless the diet and sync are both on.
    digest: jnp.ndarray
    # ---- cohort-staggered compaction (storediet.cohorts > 1; PR 20).
    #      Both leaves are zero-width unless cfg.store_stagger — the
    #      `health` idiom.  Checkpoint v17. ----
    cohort: jnp.ndarray   # u16[N] compaction cohort = idx % cohorts —
    #   structural (derived from the row index, like is_tracker):
    #   survives churn rebirth, unload and restart; materialized so the
    #   schema/partition/oracle machinery sees the assignment.
    epoch: jnp.ndarray    # u32[N] the peer's CURRENT bloom-salt epoch =
    #   its completed compaction count, +1 on the peer's own sync round.
    #   Always equal to storediet.epoch_of_cohort(cfg, rnd, cohort) — a
    #   reborn peer re-derives it from the shared round counter (the
    #   overlay's cadence, not the process's), so rebirth wipes it WITH
    #   the store and the re-derived value lands it back on cadence.

    # ---- forward buffer [N, F]: records to push next round -------------
    # (reference: dispersy.py store_update_forward -> _forward sends each
    #  freshly accepted/created sync message to `node_count` candidates,
    #  per CommunityDestination; EMPTY_U32 gt marks an empty slot)
    fwd_gt: jnp.ndarray       # u32
    fwd_member: jnp.ndarray   # u32
    fwd_meta: jnp.ndarray     # u8, EMPTY_META = empty slot
    fwd_payload: jnp.ndarray  # u32
    fwd_aux: jnp.ndarray      # u32

    # ---- timeline (ops/timeline.py AuthTable; folded from stored
    #      authorize/revoke records, wiped with the store on churn) ----
    auth_member: jnp.ndarray     # u32[N, A], EMPTY_U32 = empty slot
    auth_mask: jnp.ndarray       # u32[N, A] per-meta permission nibbles
    auth_gt: jnp.ndarray         # u32[N, A] global_time the row takes effect
    auth_rev: jnp.ndarray        # bool[N, A] True = revoke row
    auth_issuer: jnp.ndarray     # u32[N, A] member that signed the row —
    #   the retro re-walk handle (ops/timeline.revalidate)

    # ---- malicious-member blacklist (reference: dispersy.py malicious-
    #      member bookkeeping; config.malicious_enabled) ----
    mal_member: jnp.ndarray      # u32[N, Bm], EMPTY_U32 = free slot

    # ---- delayed-message pen [N, D] (reference: message.py
    #      DelayMessageByProof — records waiting for their permission
    #      proof re-enter the intake batch each round; in-memory only,
    #      dies with the process on churn; config.delay_inbox) ----
    dly_gt: jnp.ndarray       # u32, EMPTY_U32 = free slot
    dly_member: jnp.ndarray   # u32
    dly_meta: jnp.ndarray     # u8, EMPTY_META = free slot
    dly_payload: jnp.ndarray  # u32
    dly_aux: jnp.ndarray      # u32
    dly_since: jnp.ndarray    # u32 round the record was first parked
    dly_src: jnp.ndarray      # i32 delivering peer of the parked record —
    #   the dispersy-missing-proof request target (config.proof_requests);
    #   NO_PEER when unknown

    # ---- outstanding signature request (reference: requestcache.py — the
    #      dispersy-signature-request cache entry; one in flight per peer,
    #      sent once, freed on response or timeout) ----
    sig_target: jnp.ndarray      # i32[N] counterparty, NO_PEER = no request
    sig_meta: jnp.ndarray        # u32[N] draft meta id
    sig_payload: jnp.ndarray     # u32[N] draft payload word
    sig_gt: jnp.ndarray          # u32[N] global_time claimed at draft
    sig_since: jnp.ndarray       # u32[N] round the request was created

    stats: Stats
    key: jnp.ndarray          # uint32[2] threefry key for this community
    time: jnp.ndarray         # f32 scalar, sim-seconds (round * walk_interval)
    round_index: jnp.ndarray  # u32 scalar; exact round counter (time is
    #                           derived f32 and would lose integer precision
    #                           past ~2^23 rounds)


FLAG_UNDONE = 1


def stats_gates(config: CommunityConfig) -> dict:
    """Which feature-gated ``Stats`` counters are compiled in (True =
    full ``[N]`` width) for one config — the ONE definition shared by
    :func:`init_stats`, the oracle's ``state_arrays`` and the telemetry
    row packer, so a counter can never be written wider than it is
    sized.  Counters absent here are always-on.  The byte-diet
    motivation: a 1M-peer round was carrying ~13 always-zero u32[N]
    counters for features the config compiled out (~52 B/peer of
    resident state and round traffic for nothing)."""
    return {
        "msgs_rejected": (config.timeline_enabled
                          or bool(config.seq_meta_mask)
                          or config.identity_required
                          or config.malicious_enabled),
        "msgs_direct": bool(config.direct_meta_mask),
        "msgs_delayed": config.delay_enabled,
        "proof_requests": config.proof_requests,
        "proof_records": config.proof_requests,
        "seq_requests": config.seq_requests,
        "seq_records": config.seq_requests,
        "mm_requests": config.msg_requests,
        "mm_records": config.msg_requests,
        "id_requests": config.identity_requests,
        "id_records": config.identity_requests,
        "sig_signed": bool(config.double_meta_mask),
        "sig_done": bool(config.double_meta_mask),
        "sig_expired": bool(config.double_meta_mask),
        "conflicts": config.malicious_enabled,
        "convictions_rx": config.malicious_enabled,
        "auth_unwound": config.timeline_enabled,
        "msgs_retro": config.timeline_enabled,
        "xshard_shed": (config.parallel.shards > 1
                        and config.parallel.cross_shard_budget > 0),
    }


def init_stats(config: CommunityConfig) -> Stats:
    # Distinct buffers on purpose: aliased arrays break donation
    # (Execute() rejects the same buffer donated twice).
    from dispersy_tpu.recovery import NUM_HEALTH_BITS

    from dispersy_tpu.traceplane import NUM_CHANNELS

    n, n_meta = config.n_peers, config.n_meta
    n_corrupt = n if (config.faults.corrupt_rate > 0.0
                      or config.faults.flood_enabled) else 0
    n_recov = n if config.recovery.enabled else 0
    n_overload = n if config.overload.enabled else 0
    n_trace = n if config.trace.enabled else 0
    gates = stats_gates(config)

    def z():
        return jnp.zeros((n,), jnp.uint32)

    def g(name):
        # Feature-gated counter: zero-width when its plane is compiled
        # out (the `health` idiom) — every engine write site is guarded
        # by the same config flag (state.stats_gates).
        return jnp.zeros((n if gates[name] else 0,), jnp.uint32)
    return Stats(walk_success=z(), walk_fail=z(), msgs_stored=z(),
                 msgs_dropped=z(), requests_dropped=z(), punctures=z(),
                 msgs_forwarded=z(), msgs_rejected=g("msgs_rejected"),
                 msgs_direct=g("msgs_direct"),
                 msgs_delayed=g("msgs_delayed"),
                 msgs_corrupt_dropped=jnp.zeros((n_corrupt,), jnp.uint32),
                 msgs_shed_rate=jnp.zeros((n_overload,), jnp.uint32),
                 msgs_shed_priority=jnp.zeros((n_overload,), jnp.uint32),
                 xshard_shed=g("xshard_shed"),
                 trace_delivered=jnp.zeros((n_trace, NUM_CHANNELS),
                                           jnp.uint32),
                 trace_dup=jnp.zeros((n_trace, NUM_CHANNELS),
                                     jnp.uint32),
                 recov_soft=jnp.zeros((n_recov,), jnp.uint32),
                 recov_backoff=jnp.zeros((n_recov,), jnp.uint32),
                 recov_quarantine=jnp.zeros((n_recov,), jnp.uint32),
                 recov_cleared=jnp.zeros((n_recov, NUM_HEALTH_BITS),
                                         jnp.uint32),
                 proof_requests=g("proof_requests"),
                 proof_records=g("proof_records"),
                 seq_requests=g("seq_requests"),
                 seq_records=g("seq_records"),
                 mm_requests=g("mm_requests"), mm_records=g("mm_records"),
                 id_requests=g("id_requests"), id_records=g("id_records"),
                 sig_signed=g("sig_signed"), sig_done=g("sig_done"),
                 sig_expired=g("sig_expired"),
                 conflicts=g("conflicts"),
                 convictions_rx=g("convictions_rx"),
                 auth_unwound=g("auth_unwound"),
                 msgs_retro=g("msgs_retro"),
                 bytes_up=z(), bytes_down=z(),
                 accepted_by_meta=jnp.zeros((n, n_meta + 1), jnp.uint32))


# The NAMED WIPE INVENTORY: every PeerState leaf classified by what a
# wiped-disk rebirth (engine._rebirth_wipe — churn phase 0 and the
# recovery plane's quarantine escalation) and a community unload
# (engine.unload_members) do to it.  This is the introspectable registry
# graftlint R7 cross-references against the extracted leaf schema
# (tools/graftlint/schema.py) and tests/test_wipe_inventory.py iterates,
# so a NEW leaf without a classification is a lint failure, not a
# silently-unwiped field.  ``Stats`` counters are implicitly class
# "stats" (accounting survives both events) and carry no entry here.
#
# Classes:
#   "lifecycle" — liveness flags the churn/load machinery drives
#                 directly (alive, loaded).
#   "identity"  — a property of the peer's identity / router / the
#                 overlay's opinion of it: survives BOTH rebirth and
#                 unload (is_tracker, ge_bad, bucket, quar_until).
#   "process"   — process memory reset by a rebirth (a restart is a new
#                 process) but untouched by unload (health, backoff,
#                 repair_round).
#   "clock"     — rebirth-reset round bookkeeping: global_time restarts
#                 at 1, session bumps.
#   "disk"      — database state: survives unload, wiped with the store
#                 by a wiped-disk rebirth (store/staging columns, the
#                 epoch digest, the store-folded auth table, the
#                 per-peer trace lineage rows).
#   "instance"  — community-INSTANCE memory that dies when the instance
#                 goes away while the database persists: wiped by BOTH
#                 rebirth and unload.  Second tuple element is the fill
#                 kind (resolved per dtype in wipe_instance_memory).
#   "stats"     — stats-adjacent runtime state that survives both, like
#                 the counters it derives from (walk_streak).
#   "global"    — host-/slot-indexed leaves with no per-peer row to
#                 wipe (trace registry + latches, telemetry rings, RNG
#                 key, clocks).
WIPE_INVENTORY: dict = {
    "alive": ("lifecycle", None),
    "loaded": ("lifecycle", None),
    "is_tracker": ("identity", None),
    "session": ("clock", None),
    "global_time": ("clock", None),
    "health": ("process", None),
    "ge_bad": ("identity", None),
    "backoff": ("process", None),
    "quar_until": ("identity", None),
    "repair_round": ("process", None),
    "bucket": ("identity", None),
    "walk_streak": ("stats", None),
    "tele_row": ("global", None),
    "tele_ring": ("global", None),
    "fr_ring": ("global", None),
    "fr_pos": ("global", None),
    "trace_member": ("global", None),
    "trace_gt": ("global", None),
    "trace_first": ("disk", None),
    "trace_chan": ("disk", None),
    "trace_dups": ("disk", None),
    "trace_latch": ("global", None),
    "cand_peer": ("instance", "no_peer"),
    "cand_last_walk": ("instance", "never"),
    "cand_last_stumble": ("instance", "never"),
    "cand_last_intro": ("instance", "never"),
    "store_gt": ("disk", None),
    "store_member": ("disk", None),
    "store_meta": ("disk", None),
    "store_payload": ("disk", None),
    "store_aux": ("disk", None),
    "store_flags": ("disk", None),
    "sta_gt": ("disk", None),
    "sta_member": ("disk", None),
    "sta_meta": ("disk", None),
    "sta_payload": ("disk", None),
    "sta_aux": ("disk", None),
    "sta_flags": ("disk", None),
    "digest": ("disk", None),
    "cohort": ("identity", None),   # idx % cohorts — structural, like
    #   is_tracker: rebirth/unload/restart all keep it
    "epoch": ("disk", None),        # wiped with the store by rebirth and
    #   immediately RE-DERIVED from (round, cohort) in the same block
    #   (engine._rebirth_wipe): the reborn peer rejoins the fleet cadence
    #   at the epoch every surviving peer already attributes to it
    "fwd_gt": ("instance", "empty"),
    "fwd_member": ("instance", "empty"),
    "fwd_meta": ("instance", "empty"),
    "fwd_payload": ("instance", "empty"),
    "fwd_aux": ("instance", "empty"),
    "auth_member": ("disk", None),
    "auth_mask": ("disk", None),
    "auth_gt": ("disk", None),
    "auth_rev": ("disk", None),
    "auth_issuer": ("disk", None),
    "mal_member": ("instance", "empty"),
    "dly_gt": ("instance", "empty"),
    "dly_member": ("instance", "empty"),
    "dly_meta": ("instance", "empty"),
    "dly_payload": ("instance", "empty"),
    "dly_aux": ("instance", "zero"),
    "dly_since": ("instance", "zero"),
    "dly_src": ("instance", "no_peer"),
    "sig_target": ("instance", "no_peer"),
    "sig_meta": ("instance", "zero"),
    "sig_payload": ("instance", "zero"),
    "sig_gt": ("instance", "zero"),
    "sig_since": ("instance", "zero"),
    "key": ("global", None),
    "time": ("global", None),
    "round_index": ("global", None),
}

# Community-INSTANCE memory: the fields that die when the community
# instance goes away while the database (store) persists — the
# "instance" rows of WIPE_INVENTORY, with their fill kinds.  Consumed by
# engine.unload_members (Community.unload_community) and
# checkpoint._wipe_ephemeral (app-restart restore); the churn-rebirth
# block in engine.step phase 0 wipes a SUPERSET of this (plus the store,
# clocks, auth table, and loaded — a wiped-disk rebirth).
INSTANCE_MEMORY_FIELDS: tuple = tuple(
    (name, fill) for name, (cls, fill) in WIPE_INVENTORY.items()
    if cls == "instance")


def wipe_instance_memory(state: PeerState, mask) -> PeerState:
    """Fill every INSTANCE_MEMORY_FIELDS leaf with its empty value on the
    masked rows (bool[n]); other rows untouched.

    Array-library-preserving: numpy leaves stay numpy (checkpoint restore
    promises host arrays so a mesh restore can shard before anything
    lands on a device), jax leaves stay jax (engine.unload_members runs
    on live device state)."""
    n = np.shape(mask)[0]
    fills = {"no_peer": NO_PEER, "never": NEVER, "zero": 0}
    updates = {}
    for name, kind in INSTANCE_MEMORY_FIELDS:
        arr = getattr(state, name)
        if arr.ndim >= 1 and arr.shape[0] != n:
            # Plane-sized zero-width leaf (feature compiled out, e.g. a
            # [0]-shaped sig cache when double_meta_mask is 0): nothing
            # to wipe, and the (n,)-mask would not broadcast against it.
            continue
        xp = np if isinstance(arr, np.ndarray) else jnp
        m = xp.reshape(xp.asarray(mask), (n,) + (1,) * (arr.ndim - 1))
        # "empty" is the all-ones sentinel of the column's OWN dtype
        # (EMPTY_U32 for u32 columns, EMPTY_META for narrowed u8 metas);
        # "never" is the f32 NEVER sentinel, or 0 for the quantized u16
        # round-stamp columns (store.cand_bits=16 — stamp 0 = never).
        if kind == "empty":
            fill = np.iinfo(np.dtype(arr.dtype)).max
        elif kind == "never" and np.issubdtype(np.dtype(arr.dtype),
                                               np.integer):
            fill = 0
        else:
            fill = fills[kind]
        updates[name] = xp.where(m, xp.asarray(fill, dtype=arr.dtype),
                                 arr)
    return state.replace(**updates)


def stack_states(states) -> PeerState:
    """Stack R single-run ``PeerState`` pytrees along a NEW leading
    replica axis (the fleet plane's layout, dispersy_tpu/fleet.py): the
    result is a ``PeerState`` whose every leaf carries shape
    ``(R,) + leaf.shape``.  Array-library-preserving like
    :func:`wipe_instance_memory`: all-numpy inputs (checkpoint restores)
    stay numpy, otherwise leaves land on device."""
    if not states:
        raise ValueError("stack_states needs at least one state")
    all_np = all(isinstance(leaf, np.ndarray)
                 for st in states for leaf in jax.tree_util.tree_leaves(st))
    xp = np if all_np else jnp
    return jax.tree_util.tree_map(lambda *xs: xp.stack(xs), *states)


def index_state(fstate: PeerState, i: int) -> PeerState:
    """Split replica ``i`` back out of a fleet-stacked ``PeerState``
    (inverse of :func:`stack_states` for one row) — the post-mortem
    handle: a flagged replica becomes an ordinary single-run state that
    every existing tool (oracle diff, debug_validate, checkpoint.save)
    accepts."""
    return jax.tree_util.tree_map(lambda x: x[i], fstate)


def init_state(config: CommunityConfig, key: jax.Array) -> PeerState:
    """Fresh overlay: everyone alive, empty stores, empty candidate tables.

    Mirrors the reference's cold start (Dispersy.start + load_community with
    an empty database): peers know only the bootstrap trackers, which the
    walker reaches via its 0.5% bootstrap branch.
    """
    n, k, m = config.n_peers, config.k_candidates, config.msg_capacity
    f = config.forward_buffer
    # Plane-sized community-feature leaves (the `health` idiom, applied
    # to the original tables by the byte-diet PR): the timeline's auth
    # table, the malicious blacklist and the signature cache are
    # zero-width when their feature is compiled out — at the 1M bench
    # shape they were ~324 B/peer of resident state (and churn-wipe
    # traffic) for features the config could never exercise.
    a = config.k_authorized if config.timeline_enabled else 0
    km = config.k_malicious if config.malicious_enabled else 0
    ns = n if config.double_meta_mask else 0
    s_w = config.store.staging
    d_w = config.bloom_words if (config.store_diet
                                 and config.sync_enabled) else 0
    # Dissemination-tracing slots (zero-width when the plane is
    # compiled out — the `health` idiom; traceplane.py).
    t_w = config.trace.tracked_slots if config.trace.enabled else 0
    aux_dt = config.aux_dtype

    # Cohort-stagger leaves (zero-width when cohorts == 1): cohort is
    # the structural idx % cohorts assignment, epoch the per-peer
    # completed-compaction count (0 at cold start for every cohort —
    # epoch_of_cohort(cfg, 0, k) == 0).
    st_n = n if config.store_stagger else 0

    def never():  # distinct buffers: aliasing breaks donation
        if config.store.cand_bits == 16:
            # Quantized u16 round-stamps: 0 is the "never" sentinel
            # (stamps are round + 1; storediet.StoreConfig.cand_bits).
            return jnp.zeros((n, k), jnp.uint16)
        return jnp.full((n, k), NEVER, jnp.float32)
    return PeerState(
        alive=jnp.ones((n,), bool),
        loaded=jnp.ones((n,), bool),
        is_tracker=jnp.arange(n) < config.n_trackers,
        session=jnp.zeros((n,), jnp.uint32),
        global_time=jnp.ones((n,), jnp.uint32),
        # Chaos-harness leaves size to their knobs (zero-width when the
        # feature is compiled out — the dly_* idiom — so a disabled
        # fault model adds zero bytes to the fused round; FAULTS.md).
        health=jnp.zeros(
            (n if config.faults.health_checks else 0,), jnp.uint32),
        ge_bad=jnp.zeros((n if config.faults.ge_enabled else 0,), bool),
        # Recovery-plane leaves size to their master knob the same way
        # (zero-width when compiled out; recovery.adapt_state resizes).
        backoff=jnp.zeros(
            (n if config.recovery.enabled else 0,), jnp.uint8),
        quar_until=jnp.zeros(
            (n if config.recovery.enabled else 0,), jnp.uint32),
        repair_round=jnp.zeros(
            (n if config.recovery.enabled else 0,), jnp.uint32),
        # Ingress-protection leaf sizes to its master knob the same way
        # (zero-width when compiled out; overload.adapt_state resizes).
        bucket=jnp.zeros(
            (n if config.overload.enabled else 0,), jnp.uint8),
        # Telemetry-plane leaves size to their knobs the same way
        # (telemetry.row_width is 0 when disabled).
        walk_streak=jnp.zeros(
            (n if config.telemetry.histograms else 0,), jnp.uint32),
        tele_row=jnp.zeros((tlm.row_width(config),), jnp.uint32),
        tele_ring=jnp.zeros(
            (config.telemetry.history, tlm.row_width(config)), jnp.uint32),
        fr_ring=jnp.zeros(
            (config.telemetry.flight_recorder, tlm.FLIGHT_WIDTH),
            jnp.uint32),
        fr_pos=jnp.zeros(
            (1 if config.telemetry.flight_recorder else 0,), jnp.uint32),
        trace_member=jnp.full((t_w,), EMPTY_U32, jnp.uint32),
        trace_gt=jnp.full((t_w,), EMPTY_U32, jnp.uint32),
        trace_first=jnp.zeros((n if t_w else 0, t_w), jnp.uint32),
        trace_chan=jnp.zeros((n if t_w else 0, t_w), jnp.uint8),
        trace_dups=jnp.zeros((n if t_w else 0, t_w), jnp.uint32),
        trace_latch=jnp.zeros((t_w, 3), jnp.uint32),
        cand_peer=jnp.full((n, k), NO_PEER, jnp.int32),
        cand_last_walk=never(),
        cand_last_stumble=never(),
        cand_last_intro=never(),
        store_gt=jnp.full((n, m), EMPTY_U32, jnp.uint32),
        store_member=jnp.full((n, m), EMPTY_U32, jnp.uint32),
        store_meta=jnp.full((n, m), EMPTY_META, META_DTYPE),
        store_payload=jnp.full((n, m), EMPTY_U32, jnp.uint32),
        store_aux=jnp.zeros((n, m), aux_dt),
        store_flags=jnp.zeros((n, m), FLAGS_DTYPE),
        sta_gt=jnp.full((n, s_w), EMPTY_U32, jnp.uint32),
        sta_member=jnp.full((n, s_w), EMPTY_U32, jnp.uint32),
        sta_meta=jnp.full((n, s_w), EMPTY_META, META_DTYPE),
        sta_payload=jnp.full((n, s_w), EMPTY_U32, jnp.uint32),
        sta_aux=jnp.zeros((n, s_w), aux_dt),
        sta_flags=jnp.zeros((n, s_w), FLAGS_DTYPE),
        digest=jnp.zeros((n if d_w else 0, d_w), jnp.uint32),
        cohort=(jnp.arange(n, dtype=jnp.int32)
                % config.store.cohorts).astype(jnp.uint16)[:st_n],
        epoch=jnp.zeros((st_n,), jnp.uint32),
        fwd_gt=jnp.full((n, f), EMPTY_U32, jnp.uint32),
        fwd_member=jnp.full((n, f), EMPTY_U32, jnp.uint32),
        fwd_meta=jnp.full((n, f), EMPTY_META, META_DTYPE),
        fwd_payload=jnp.full((n, f), EMPTY_U32, jnp.uint32),
        fwd_aux=jnp.full((n, f), empty_of(aux_dt), aux_dt),
        dly_gt=jnp.full((n, config.delay_inbox), EMPTY_U32, jnp.uint32),
        dly_member=jnp.full((n, config.delay_inbox), EMPTY_U32, jnp.uint32),
        dly_meta=jnp.full((n, config.delay_inbox), EMPTY_META, META_DTYPE),
        dly_payload=jnp.full((n, config.delay_inbox), EMPTY_U32, jnp.uint32),
        dly_aux=jnp.zeros((n, config.delay_inbox), jnp.uint32),
        dly_since=jnp.zeros((n, config.delay_inbox), jnp.uint32),
        dly_src=jnp.full((n, config.delay_inbox), NO_PEER, jnp.int32),
        auth_member=jnp.full((n, a), EMPTY_U32, jnp.uint32),
        auth_mask=jnp.zeros((n, a), jnp.uint32),
        auth_gt=jnp.zeros((n, a), jnp.uint32),
        auth_rev=jnp.zeros((n, a), bool),
        auth_issuer=jnp.full((n, a), EMPTY_U32, jnp.uint32),
        mal_member=jnp.full((n, km), EMPTY_U32, jnp.uint32),
        sig_target=jnp.full((ns,), NO_PEER, jnp.int32),
        sig_meta=jnp.zeros((ns,), jnp.uint32),
        sig_payload=jnp.zeros((ns,), jnp.uint32),
        sig_gt=jnp.zeros((ns,), jnp.uint32),
        sig_since=jnp.zeros((ns,), jnp.uint32),
        stats=init_stats(config),
        key=jax.random.key_data(key) if key.dtype != jnp.uint32 else key,
        time=jnp.float32(0.0),
        round_index=jnp.uint32(0),
    )
