"""Timeline kernels: the permission engine as a bounded grant table.

The reference's ``Timeline`` (reference: timeline.py — ``Timeline.check``,
``.authorize``, ``.revoke``, ``.get_resolution_policy``) evaluates every
LinearResolution message against the permission state *at that message's
global_time*, where the state is folded from ``dispersy-authorize`` /
``dispersy-revoke`` messages that themselves spread epidemically.  The
proof-chain machinery (DelayMessageByProof, missing-proof round trips)
exists to fetch grants that have not arrived yet; in the round-synchronous
simulation a record whose grant is missing is simply *rejected this round*
— the store never learns it, so the Bloom exchange keeps offering it and it
is accepted on a later round once the authorize record has spread.  Same
fixed point, no delay queue.

TPU recast: each peer holds a bounded ``[A]`` table of grant/revoke rows
(member, per-meta permission-nibble mask, global_time, revoke flag).  The
mask packs the reference's FOUR permission types per user meta — bit
(4*meta + p) with p in {permit, authorize, revoke, undo}
(config.PERM_* ids), mirroring ``Timeline.check``'s (member, message,
permission) triple resolution.  ``check`` is a broadcast-compare over the
table; ``fold`` inserts freshly synced authorize/revoke records.  Rows are
never merged: the latest-at-or-before-gt row carrying the queried bit
decides, with a revoke row beating a grant at the same global_time (the
reference orders equal-time proofs by packet and rejects on conflict; a
deterministic revoke-wins rule is the simulation equivalent).

The founder (``CommunityConfig.founder``) holds every permission implicitly
and is the root of authority.  Grants carrying a meta's AUTHORIZE bit
convey the *authorize permission itself* for that meta, so chains (founder
→ A(authorize) → B(permit) → …) fold to arbitrary depth across rounds —
:func:`check_grant` is the chain-link validity test, the bounded-table
recast of ``Timeline.check``'s recursive proof walk; the REVOKE bit gates
issuing revoke records separably, and the UNDO bit (checked via
:func:`check` with ``perm=PERM_UNDO``) gates dispersy-undo-other.

Order independence (reference: timeline.py ``Timeline.check`` re-walks
proof chains lazily, so every peer converges to the same verdict
regardless of arrival order): a link's validity is still judged at fold
time for *acceptance* (with Bloom re-offers supplying out-of-order
grants), but each row also records its ISSUER, and whenever a revoke
folds the engine re-validates the whole table with :func:`revalidate` —
a bounded fixed-point re-walk that unwinds rows whose granting chain no
longer checks out at their global_time, transitively.  Store records
backed by unwound rows are retro-rejected in the same pass
(engine._retro_pass), so two peers that received {grant-chain, revoke}
in opposite orders converge to identical verdicts AND identical stores.
Remaining documented divergence: mutually-granting same-global_time row
cycles (A grants B authorize while B grants A authorize, both at one gt,
their common root later revoked) survive ``revalidate``'s greatest-fixed-
point iteration where the reference's visited-set walk would reject them
— unreachable through gated intake without an adversarial equal-gt pair.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from dispersy_tpu.config import (EMPTY_U32, MAX_TIMELINE_META, PERM_AUTHORIZE,
                                 PERM_PERMIT, PERM_REVOKE)
from dispersy_tpu.ops.contracts import Spec, contract


class AuthTable(NamedTuple):
    """[N, A] grant/revoke rows; ``member == EMPTY_U32`` marks a free slot."""
    member: jnp.ndarray  # u32[N, A] member the row applies to
    mask: jnp.ndarray    # u32[N, A] per-meta permission nibbles (perm_bit)
    gt: jnp.ndarray      # u32[N, A] global_time the row takes effect
    rev: jnp.ndarray     # bool[N, A] True = revoke row (removes the bits)
    issuer: jnp.ndarray  # u32[N, A] member that signed the grant/revoke —
    #   the re-walk handle: revalidate() re-judges each row by its
    #   issuer's authority (reference: an authorize message's own
    #   authentication member, walked by Timeline.check)


# Canonical [N, A] grant-table spec shared by the timeline contracts.
_TAB = AuthTable(member=Spec("uint32", ("N", "A")),
                 mask=Spec("uint32", ("N", "A")),
                 gt=Spec("uint32", ("N", "A")),
                 rev=Spec("bool", ("N", "A")),
                 issuer=Spec("uint32", ("N", "A")))
_U32_NB = Spec("uint32", ("N", "B"))
_BOOL_NB = Spec("bool", ("N", "B"))


def _latest_row_verdict(match, row_gt_masked, is_rev):
    """Shared latest-wins rule: the highest-gt matching row decides;
    a revoke row beats a grant row at the same global_time."""
    best = jnp.max(row_gt_masked, axis=-1)
    at_best = match & (row_gt_masked == best[..., None])
    return (jnp.any(at_best & ~is_rev, axis=-1)
            & ~jnp.any(at_best & is_rev, axis=-1)
            & jnp.any(match, axis=-1))


@contract(out=_BOOL_NB, tab=_TAB, member=_U32_NB, meta=_U32_NB, gt=_U32_NB,
          founder=1, perm=PERM_PERMIT)
def check(tab: AuthTable, member: jnp.ndarray, meta: jnp.ndarray,
          gt: jnp.ndarray, founder, perm: int = PERM_PERMIT) -> jnp.ndarray:
    """Does ``member`` hold permission ``perm`` for ``meta`` at ``gt``?
    [N, B] verdicts.

    Mirrors ``Timeline.check`` for one permission type: the latest
    grant/revoke row carrying bit (4*meta + perm) for ``member`` at
    global_time <= gt decides; revoke wins a tie at equal global_time; no
    row at all means not held.  The founder always holds everything.

    ``member``/``meta``/``gt`` are [N, B] record fields checked against each
    receiving peer's own table.  ``founder`` is an int (one community) or a
    per-row array broadcastable against [N, B] (multi-community layouts,
    where each block answers to its own founder).
    """
    # Clamped shift: metas outside the nibble range (control ids, or the
    # caller's not-found sentinel) never match a bit, and a shift >= the
    # bit width would be undefined in XLA.
    in_range = meta < MAX_TIMELINE_META
    sh = jnp.minimum(jnp.uint32(4) * meta + jnp.uint32(perm), jnp.uint32(31))
    bit = ((tab.mask[:, None, :] >> sh[:, :, None]) & jnp.uint32(1)
           & in_range[:, :, None].astype(jnp.uint32))               # [N,B,A]
    match = ((tab.member[:, None, :] == member[:, :, None])
             & (tab.member[:, None, :] != jnp.uint32(EMPTY_U32))
             & (bit == 1)
             & (tab.gt[:, None, :] <= gt[:, :, None]))
    row_gt = jnp.where(match, tab.gt[:, None, :], 0)
    granted = _latest_row_verdict(match, row_gt, tab.rev[:, None, :])
    return granted | (member == jnp.asarray(founder, jnp.uint32))


@contract(out=_BOOL_NB, tab=_TAB, member=_U32_NB, mask=_U32_NB, gt=_U32_NB,
          n_meta=2, perm=PERM_AUTHORIZE, impl=None)
def check_grant(tab: AuthTable, member: jnp.ndarray, mask: jnp.ndarray,
                gt: jnp.ndarray, n_meta: int,
                perm: int = PERM_AUTHORIZE,
                impl: str | None = None) -> jnp.ndarray:
    """May ``member`` issue a grant/revoke covering ``mask`` at ``gt``?

    The delegation chain check (reference: timeline.py ``Timeline.check``
    walking authorize proofs — a member granted the *authorize* permission
    for a meta can itself authorize others for it; one granted the
    *revoke* permission can issue revokes, separably).  Per meta whose
    NIBBLE in ``mask`` is non-empty, the latest row carrying that meta's
    ``perm`` authority bit at global_time <= gt decides, revoke winning
    ties — the same latest-wins rule as :func:`check`, evaluated on the
    authority bit (``perm`` = PERM_AUTHORIZE for authorize records,
    PERM_REVOKE for revoke records).  The verdict requires EVERY meta
    named in ``mask`` (and a non-empty mask: an empty grant proves
    nothing).  The founder shortcut is the CALLER's
    (``founder-or-delegated``), keeping this function a pure chain check.

    Chains deepen one table-fold per round: a full chain arriving in one
    batch folds its first link this round and the rest on re-offer —
    deterministic, mirrored by the oracle, and converging because Bloom
    sync keeps re-serving un-stored records (the same fixed-point argument
    as the module docstring's missing-grant story).

    ``member``/``mask``/``gt``: [N, B] query records.
    """
    from dispersy_tpu.ops.intake import _auto_impl  # shared backend gate

    n, b = member.shape
    a = tab.member.shape[-1]
    live = tab.member != jnp.uint32(EMPTY_U32)

    if _auto_impl(impl, n * b * a * n_meta) == "broadcast":
        ok = mask != 0
        for k in range(n_meta):
            need = ((mask >> (4 * k)) & jnp.uint32(0xF)) != 0        # [N, B]
            rows_k = (((tab.mask >> (4 * k + perm)) & jnp.uint32(1)) == 1) \
                & live
            match = (rows_k[:, None, :]
                     & (tab.member[:, None, :] == member[:, :, None])
                     & (tab.gt[:, None, :] <= gt[:, :, None]))       # [N,B,A]
            row_gt = jnp.where(match, tab.gt[:, None, :], 0)
            granted_k = _latest_row_verdict(match, row_gt,
                                            tab.rev[:, None, :])
            ok = ok & (~need | granted_k)
        return ok

    # Chunked form (non-fusing backends at scale — the same memory story
    # as ops/intake.py): one batch column at a time, O(N*A) live per meta.
    def body(j, out):
        mb = lax.dynamic_index_in_dim(member, j, 1)                  # [N, 1]
        mk = lax.dynamic_index_in_dim(mask, j, 1)
        g = lax.dynamic_index_in_dim(gt, j, 1)
        ok_j = (mk != 0)[:, 0]
        for k in range(n_meta):
            need = (((mk >> (4 * k)) & jnp.uint32(0xF)) != 0)[:, 0]  # [N]
            rows_k = (((tab.mask >> (4 * k + perm)) & jnp.uint32(1)) == 1) \
                & live
            match = rows_k & (tab.member == mb) & (tab.gt <= g)      # [N, A]
            row_gt = jnp.where(match, tab.gt, 0)
            granted_k = _latest_row_verdict(match, row_gt, tab.rev)
            ok_j = ok_j & (~need | granted_k)
        return lax.dynamic_update_index_in_dim(out, ok_j, j, 1)

    return lax.fori_loop(0, b, body, jnp.zeros((n, b), bool))


class FoldResult(NamedTuple):
    table: AuthTable
    n_dropped: jnp.ndarray  # i32[N] new rows lost (keyed below the window)
    n_evicted: jnp.ndarray  # i32[N] existing rows displaced by higher keys


def _row_lt(ag, am, ak, ar, ai, bg, bm, bk, br, bi):
    """Lexicographic (gt, member, mask, rev, issuer) strict less-than —
    the ONE total order on table rows (fold eviction + oracle mirror)."""
    return ((ag < bg)
            | ((ag == bg) & ((am < bm)
               | ((am == bm) & ((ak < bk)
                  | ((ak == bk) & ((ar < br)
                     | ((ar == br) & (ai < bi)))))))))


@contract(out=FoldResult(table=_TAB, n_dropped=Spec("int32", ("N",)),
                         n_evicted=Spec("int32", ("N",))),
          tab=_TAB, target=_U32_NB, mask=_U32_NB, gt=_U32_NB,
          is_revoke=_BOOL_NB, valid=_BOOL_NB, issuer=_U32_NB)
def fold(tab: AuthTable, target: jnp.ndarray, mask: jnp.ndarray,
         gt: jnp.ndarray, is_revoke: jnp.ndarray,
         valid: jnp.ndarray, issuer: jnp.ndarray) -> FoldResult:
    """Insert [N, B] accepted authorize/revoke records into each table.

    Mirrors ``Timeline.authorize``/``.revoke`` folding stored proof into the
    permission state.  Idempotent per (issuer, member, mask, gt, revoke)
    row — an evicted record that re-syncs after store overflow must not eat
    a second slot.

    Overflow keeps the A rows with the HIGHEST (gt, member, mask, rev,
    issuer) key: the arriving row replaces the table's minimum row in
    place when it keys above it, else it is dropped; either loss is
    counted.  A first-come-keeps-slot rule would make the table's content
    depend on arrival order — two peers whose tables overflowed in
    different orders would disagree on permissions FOREVER (the bounded
    table's version of the order-dependence the retro re-walk fixes), so
    the window must be a deterministic function of the row SET.  Keeping
    the highest keys also matches ``check``'s latest-wins rule: the rows
    that decide current verdicts are exactly the high-global_time ones.
    The reference's Timeline dict is unbounded; this top-A window is the
    bounded recast, and evictions trigger the engine's retro pass so
    rows proved by an evicted grant unwind deterministically.
    """
    n, b = target.shape
    a = tab.member.shape[-1]
    is_revoke = jnp.broadcast_to(jnp.asarray(is_revoke, bool), (n, b))

    def body(i, carry):
        t, dropped, evicted = carry
        tg = lax.dynamic_index_in_dim(target, i, axis=1)     # [N, 1]
        mk = lax.dynamic_index_in_dim(mask, i, axis=1)
        g = lax.dynamic_index_in_dim(gt, i, axis=1)
        rv = lax.dynamic_index_in_dim(is_revoke, i, axis=1)
        isr = lax.dynamic_index_in_dim(issuer, i, axis=1)
        ok = lax.dynamic_index_in_dim(valid, i, axis=1)      # [N, 1]
        dup = jnp.any((t.member == tg) & (t.mask == mk) & (t.gt == g)
                      & (t.rev == rv) & (t.issuer == isr),
                      axis=1, keepdims=True)
        want = ok & ~dup
        free = t.member == jnp.uint32(EMPTY_U32)             # [N, A]
        has_free = jnp.any(free, axis=1, keepdims=True)
        # minimum live row per peer by the total order (full-table scan:
        # row j is the min iff no other live row keys below it)
        live = ~free
        below = _row_lt(t.gt[:, :, None], t.member[:, :, None],
                        t.mask[:, :, None], t.rev[:, :, None],
                        t.issuer[:, :, None],
                        t.gt[:, None, :], t.member[:, None, :],
                        t.mask[:, None, :], t.rev[:, None, :],
                        t.issuer[:, None, :])                # [N, A, A]
        # below[n, x, y] = row_x < row_y; y is the min iff no live x != y
        # keys below it (keys are unique: identical rows are dups)
        is_min = live & ~jnp.any(below & live[:, :, None]
                                 & (jnp.arange(a)[None, :, None]
                                    != jnp.arange(a)[None, None, :]),
                                 axis=1)                     # [N, A]
        min_slot = jnp.argmax(is_min, axis=1)                # [N]
        rows = jnp.arange(n)
        new_above_min = _row_lt(
            t.gt[rows, min_slot][:, None], t.member[rows, min_slot][:, None],
            t.mask[rows, min_slot][:, None], t.rev[rows, min_slot][:, None],
            t.issuer[rows, min_slot][:, None], g, tg, mk, rv, isr)  # [N, 1]
        slot = jnp.where(has_free[:, 0], jnp.argmax(free, axis=1), min_slot)
        can = want & (has_free | new_above_min)
        hit = (jnp.arange(a) == slot[:, None]) & can
        return (AuthTable(
            member=jnp.where(hit, tg, t.member),
            mask=jnp.where(hit, mk, t.mask),
            gt=jnp.where(hit, g, t.gt),
            rev=jnp.where(hit, rv, t.rev),
            issuer=jnp.where(hit, isr, t.issuer)),
            dropped + (want & ~can)[:, 0].astype(jnp.int32),
            evicted + (can & ~has_free)[:, 0].astype(jnp.int32))

    init = (tab, jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32))
    t, dropped, evicted = lax.fori_loop(0, b, body, init) if b > 0 else init
    return FoldResult(table=t, n_dropped=dropped, n_evicted=evicted)


@contract(out=Spec("bool", ("N", "A")), tab=_TAB, founder=1, n_meta=2)
def revalidate(tab: AuthTable, founder, n_meta: int) -> jnp.ndarray:
    """Re-walk every row's granting chain; bool[N, A] rows that survive.

    The bounded-table recast of ``Timeline.check``'s lazy re-validation
    (reference: timeline.py — a revoke arriving after a grant it pre-dates
    retro-rejects that grant on the next check).  Each row is re-judged by
    whether its ISSUER held the required authority bit (AUTHORIZE for grant
    rows, REVOKE for revoke rows) for every meta named in its mask at the
    row's global_time — the authority computed from surviving rows only,
    iterated A times so invalidation unwinds transitively (a removed grant
    invalidates the rows its grantee issued, one chain level per
    iteration; A rows bound the chain depth).  The verdict is a pure
    function of the row SET, never of arrival order.

    A row cannot witness its own validity (the diagonal is excluded), so a
    direct self-grant dies with its external support.  ``founder`` is an
    int or [N] per-row founder column; founder-issued rows are axiomatic.
    """
    n, a = tab.member.shape
    live = tab.member != jnp.uint32(EMPTY_U32)
    f = jnp.broadcast_to(jnp.asarray(founder, jnp.uint32), (n,))
    by_founder = tab.issuer == f[:, None]                    # [N, A]
    # Authority bit each row's issuer must hold, per row: grants need the
    # AUTHORIZE bit, revokes the REVOKE bit (separable authorities).
    permsel = jnp.where(tab.rev, jnp.uint32(PERM_REVOKE),
                        jnp.uint32(PERM_AUTHORIZE))          # [N, A]
    not_self = ~jnp.eye(a, dtype=bool)[None, :, :]           # [1, Ar, As]

    def body(_, keep):
        ok = tab.mask != 0          # an empty grant proves nothing
        for k in range(n_meta):
            need = ((tab.mask >> jnp.uint32(4 * k))
                    & jnp.uint32(0xF)) != 0                  # [N, Ar]
            sh = (jnp.uint32(4 * k) + permsel)[:, :, None]   # [N, Ar, 1]
            bit = ((tab.mask[:, None, :] >> sh) & jnp.uint32(1)) == 1
            match = (keep[:, None, :] & not_self & bit
                     & (tab.member[:, None, :] == tab.issuer[:, :, None])
                     & (tab.gt[:, None, :] <= tab.gt[:, :, None]))
            row_gt = jnp.where(match, tab.gt[:, None, :], 0)
            granted_k = _latest_row_verdict(match, row_gt,
                                            tab.rev[:, None, :])
            ok = ok & (~need | granted_k)
        return live & (ok | by_founder)

    return lax.fori_loop(0, a, body, live)


class SetFoldResult(NamedTuple):
    table: jnp.ndarray       # u32[N, S] updated member set
    n_inserted: jnp.ndarray  # i32[N] members newly added
    n_dropped: jnp.ndarray   # i32[N] members lost to a full table


@contract(out=SetFoldResult(table=Spec("uint32", ("N", "S")),
                            n_inserted=Spec("int32", ("N",)),
                            n_dropped=Spec("int32", ("N",))),
          tab=Spec("uint32", ("N", "S")), member=_U32_NB, valid=_BOOL_NB)
def fold_set(tab: jnp.ndarray, member: jnp.ndarray,
             valid: jnp.ndarray) -> SetFoldResult:
    """Insert [N, B] member ids into each row's bounded member set.

    The blacklist form of :func:`fold` (reference: dispersy.py keeps a
    malicious-member set keyed by member): idempotent per member, first
    free slot, overflow counted.  ``tab`` is u32[N, S] with ``EMPTY_U32``
    free slots.
    """
    n, b = member.shape

    def body(i, carry):
        t, inserted, dropped = carry
        mb = lax.dynamic_index_in_dim(member, i, axis=1)      # [N, 1]
        ok = lax.dynamic_index_in_dim(valid, i, axis=1)
        dup = jnp.any(t == mb, axis=1, keepdims=True)
        want = ok & ~dup
        free = t == jnp.uint32(EMPTY_U32)
        slot = jnp.argmax(free, axis=1)
        can = jnp.any(free, axis=1, keepdims=True) & want
        hit = (jnp.arange(t.shape[1]) == slot[:, None]) & can
        return (jnp.where(hit, mb, t),
                inserted + can[:, 0].astype(jnp.int32),
                dropped + (want & ~can)[:, 0].astype(jnp.int32))

    init = (tab, jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32))
    t, inserted, dropped = lax.fori_loop(0, b, body, init) if b > 0 else init
    return SetFoldResult(table=t, n_inserted=inserted, n_dropped=dropped)
