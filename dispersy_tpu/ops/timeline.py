"""Timeline kernels: the permission engine as a bounded grant table.

The reference's ``Timeline`` (reference: timeline.py — ``Timeline.check``,
``.authorize``, ``.revoke``, ``.get_resolution_policy``) evaluates every
LinearResolution message against the permission state *at that message's
global_time*, where the state is folded from ``dispersy-authorize`` /
``dispersy-revoke`` messages that themselves spread epidemically.  The
proof-chain machinery (DelayMessageByProof, missing-proof round trips)
exists to fetch grants that have not arrived yet; in the round-synchronous
simulation a record whose grant is missing is simply *rejected this round*
— the store never learns it, so the Bloom exchange keeps offering it and it
is accepted on a later round once the authorize record has spread.  Same
fixed point, no delay queue.

TPU recast: each peer holds a bounded ``[A]`` table of grant/revoke rows
(member, meta-bitmask + revoke flag in bit 31, global_time of the
authorizing record).  ``check`` is a broadcast-compare over the table;
``fold`` inserts freshly synced authorize/revoke records.  Rows are never
merged: the latest-at-or-before-gt row decides, with revoke beating a grant
at the same global_time (the reference orders equal-time proofs by packet
and rejects on conflict; a deterministic revoke-wins rule is the simulation
equivalent).

The founder (``CommunityConfig.founder``) holds every permission implicitly
and is the root of authority — the rebuild models one delegation level
(founder authorizes members) rather than arbitrary proof chains; see
config.py ``founder_member``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from dispersy_tpu.config import EMPTY_U32

# Bit 31 of a table row's mask marks a revoke row.  (Plain int, not a jnp
# scalar: module import must not touch a JAX backend.)
REVOKE_BIT = 1 << 31


class AuthTable(NamedTuple):
    """[N, A] grant/revoke rows; ``member == EMPTY_U32`` marks a free slot."""
    member: jnp.ndarray  # u32[N, A] member the row applies to
    mask: jnp.ndarray    # u32[N, A] user-meta bitmask; bit 31 = revoke row
    gt: jnp.ndarray      # u32[N, A] global_time the row takes effect


def check(tab: AuthTable, member: jnp.ndarray, meta: jnp.ndarray,
          gt: jnp.ndarray, founder) -> jnp.ndarray:
    """Is ``member`` permitted to emit ``meta`` at ``gt``?  [N, B] verdicts.

    Mirrors ``Timeline.check`` for the permit permission: the latest
    grant/revoke row for (member, meta) at global_time <= gt decides;
    revoke wins a tie at equal global_time; no row at all means not
    permitted.  The founder is always permitted.

    ``member``/``meta``/``gt`` are [N, B] record fields checked against each
    receiving peer's own table.  ``founder`` is an int (one community) or a
    per-row array broadcastable against [N, B] (multi-community layouts,
    where each block answers to its own founder).
    """
    # Clamped shift: control metas (>= 32) never match a mask bit, and a
    # shift >= the bit width would be undefined in XLA.
    sh = jnp.minimum(meta, jnp.uint32(31))
    bit = ((tab.mask[:, None, :] >> sh[:, :, None]) & jnp.uint32(1)
           & (meta < 32)[:, :, None].astype(jnp.uint32))             # [N,B,A]
    match = ((tab.member[:, None, :] == member[:, :, None])
             & (tab.member[:, None, :] != jnp.uint32(EMPTY_U32))
             & (bit == 1)
             & (tab.gt[:, None, :] <= gt[:, :, None]))
    row_gt = jnp.where(match, tab.gt[:, None, :], 0)
    best = jnp.max(row_gt, axis=-1)                                   # [N, B]
    at_best = match & (row_gt == best[:, :, None])
    is_revoke = (tab.mask[:, None, :] & jnp.uint32(REVOKE_BIT)) != 0
    granted = (jnp.any(at_best & ~is_revoke, axis=-1)
               & ~jnp.any(at_best & is_revoke, axis=-1)
               & jnp.any(match, axis=-1))
    return granted | (member == jnp.asarray(founder, jnp.uint32))


class FoldResult(NamedTuple):
    table: AuthTable
    n_dropped: jnp.ndarray  # i32[N] rows lost (table full)


def fold(tab: AuthTable, target: jnp.ndarray, mask: jnp.ndarray,
         gt: jnp.ndarray, is_revoke: jnp.ndarray,
         valid: jnp.ndarray) -> FoldResult:
    """Insert [N, B] accepted authorize/revoke records into each table.

    Mirrors ``Timeline.authorize``/``.revoke`` folding stored proof into the
    permission state.  Idempotent per (member, mask, gt) row — an evicted
    record that re-syncs after store overflow must not eat a second slot.
    Overflow drops the new row, counted (bounded state, as everywhere).
    """
    n, b = target.shape
    row_mask = jnp.where(is_revoke, mask | jnp.uint32(REVOKE_BIT),
                         mask).astype(jnp.uint32)

    def body(i, carry):
        t, dropped = carry
        tg = lax.dynamic_index_in_dim(target, i, axis=1)     # [N, 1]
        mk = lax.dynamic_index_in_dim(row_mask, i, axis=1)
        g = lax.dynamic_index_in_dim(gt, i, axis=1)
        ok = lax.dynamic_index_in_dim(valid, i, axis=1)      # [N, 1]
        dup = jnp.any((t.member == tg) & (t.mask == mk) & (t.gt == g),
                      axis=1, keepdims=True)
        want = ok & ~dup
        free = t.member == jnp.uint32(EMPTY_U32)             # [N, A]
        slot = jnp.argmax(free, axis=1)                      # first free
        can = jnp.any(free, axis=1, keepdims=True) & want
        hit = (jnp.arange(t.member.shape[1]) == slot[:, None]) & can
        return (AuthTable(
            member=jnp.where(hit, tg, t.member),
            mask=jnp.where(hit, mk, t.mask),
            gt=jnp.where(hit, g, t.gt)),
            dropped + (want & ~can)[:, 0].astype(jnp.int32))

    init = (tab, jnp.zeros((n,), jnp.int32))
    t, dropped = lax.fori_loop(0, b, body, init) if b > 0 else init
    return FoldResult(table=t, n_dropped=dropped)


class SetFoldResult(NamedTuple):
    table: jnp.ndarray       # u32[N, S] updated member set
    n_inserted: jnp.ndarray  # i32[N] members newly added
    n_dropped: jnp.ndarray   # i32[N] members lost to a full table


def fold_set(tab: jnp.ndarray, member: jnp.ndarray,
             valid: jnp.ndarray) -> SetFoldResult:
    """Insert [N, B] member ids into each row's bounded member set.

    The blacklist form of :func:`fold` (reference: dispersy.py keeps a
    malicious-member set keyed by member): idempotent per member, first
    free slot, overflow counted.  ``tab`` is u32[N, S] with ``EMPTY_U32``
    free slots.
    """
    n, b = member.shape

    def body(i, carry):
        t, inserted, dropped = carry
        mb = lax.dynamic_index_in_dim(member, i, axis=1)      # [N, 1]
        ok = lax.dynamic_index_in_dim(valid, i, axis=1)
        dup = jnp.any(t == mb, axis=1, keepdims=True)
        want = ok & ~dup
        free = t == jnp.uint32(EMPTY_U32)
        slot = jnp.argmax(free, axis=1)
        can = jnp.any(free, axis=1, keepdims=True) & want
        hit = (jnp.arange(t.shape[1]) == slot[:, None]) & can
        return (jnp.where(hit, mb, t),
                inserted + can[:, 0].astype(jnp.int32),
                dropped + (want & ~can)[:, 0].astype(jnp.int32))

    init = (tab, jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32))
    t, inserted, dropped = lax.fori_loop(0, b, body, init) if b > 0 else init
    return SetFoldResult(table=t, n_inserted=inserted, n_dropped=dropped)
