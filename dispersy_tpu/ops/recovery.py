"""Recovery kernels: store repair, walk-backoff gate, quarantine gate.

The jit-traced half of the recovery plane (:mod:`dispersy_tpu.recovery`
declares the static :class:`~dispersy_tpu.recovery.RecoveryConfig`; the
engine composes these into the fused wrap-up only when
``recovery.enabled``, so a disabled recovery plane compiles to the
identical step).  Every op mirrors bit-for-bit in the oracle
(:mod:`dispersy_tpu.oracle.sim` ``_store_repair`` / the walk-gate and
quarantine conditions in ``step``), the same lockstep discipline as
every other ops module.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from dispersy_tpu.config import EMPTY_U32
from dispersy_tpu.ops.contracts import Spec, contract
from dispersy_tpu.ops.store import StoreCols, rank_compact_many, stc_spec

_STORE_NM = stc_spec("N", "M")


@contract(out=_STORE_NM, store=_STORE_NM, mask=Spec("bool", ("N",)))
def store_repair(store: StoreCols, mask: jnp.ndarray) -> StoreCols:
    """Soft repair of the store ring on the masked rows: re-sort by the
    canonical ``(gt, member, meta, payload)`` key (``EMPTY_U32`` holes
    sort last), drop later duplicates of the UNIQUE ``(gt, member)``
    identity, and compact survivors to the front — restoring exactly
    the invariant ``faults.store_invariant_violated`` checks.  Unmasked
    rows pass through untouched, so an all-false mask is an identity
    (the common case: ``HEALTH_STORE_INVARIANT`` is a bug sentinel).
    """
    gt, member, meta, payload, aux, flags = lax.sort(
        (store.gt, store.member, store.meta, store.payload, store.aux,
         store.flags), dimension=-1, num_keys=4)
    live = gt != jnp.uint32(EMPTY_U32)
    dup = jnp.concatenate(
        [jnp.zeros_like(live[:, :1]),
         (gt[:, 1:] == gt[:, :-1]) & (member[:, 1:] == member[:, :-1])
         & live[:, 1:]], axis=1)
    keep = live & ~dup
    m = gt.shape[1]
    rank = jnp.cumsum(keep.astype(jnp.int32), axis=-1) - 1
    slot = jnp.where(keep, rank, m)
    rgt, rmember, rmeta, rpayload, raux, rflags = rank_compact_many(
        [(gt, EMPTY_U32), (member, EMPTY_U32),
         (meta, jnp.uint8(0xFF)), (payload, EMPTY_U32),
         (aux, 0), (flags, 0)], slot, m)
    m1 = mask[:, None]
    return StoreCols(
        gt=jnp.where(m1, rgt, store.gt),
        member=jnp.where(m1, rmember, store.member),
        meta=jnp.where(m1, rmeta, store.meta),
        payload=jnp.where(m1, rpayload, store.payload),
        aux=jnp.where(m1, raux, store.aux),
        flags=jnp.where(m1, rflags, store.flags))


@contract(out=Spec("bool", ("N",)),
          rnd=Spec("uint32", ()), backoff=Spec("uint8", ("N",)))
def backoff_gate(rnd: jnp.ndarray, backoff: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: may each peer walk this round under its backoff
    exponent?  Exponent ``e`` admits one round in ``2^e`` (``rnd``
    aligned: ``rnd & (2^e - 1) == 0``), so a backed-off peer re-probes
    deterministically and cheaply instead of hammering every round —
    the oracle mirrors with the identical integer test.
    """
    mask = (jnp.left_shift(jnp.uint32(1), backoff.astype(jnp.uint32))
            - jnp.uint32(1))
    return (jnp.asarray(rnd, jnp.uint32) & mask) == jnp.uint32(0)


@contract(out=Spec("bool", ("N",)),
          rnd=Spec("uint32", ()), quar_until=Spec("uint32", ("N",)))
def quarantine_active(rnd: jnp.ndarray,
                      quar_until: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: is each peer currently quarantined (``rnd`` strictly
    before its ``quar_until`` release round)?  ``quar_until == 0``
    (never quarantined) is never active because round indices compare
    unsigned."""
    return jnp.asarray(rnd, jnp.uint32) < quar_until
