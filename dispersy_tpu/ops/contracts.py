"""Op-level shape/dtype contracts: machine-checked performance invariants.

The fused round is memory-bandwidth-bound (BENCH.md roofline), and its
byte diet rests on dtype discipline: meta/flags columns are uint8, hashes
and clocks uint32, slot indices int32.  One accidental promotion — a
``jnp.int32`` literal where a ``jnp.uint8`` belonged, a comparison that
widens, a fill value of the wrong width — silently multiplies the bytes a
column moves per round, and nothing crashes.  PR 1's uint8 packing is
exactly the kind of win that erodes this way.

So every public op in ``dispersy_tpu/ops/`` declares its contract:

    @contract(out=Spec("uint32", ("N", "W")),
              item_hashes=Spec("uint32", ("N", "M")),
              mask=Spec("bool", ("N", "M")),
              n_bits=64, n_hashes=3)
    def bloom_build(item_hashes, mask, n_bits, n_hashes, ...): ...

The decorator is METADATA ONLY: it attaches the declaration to the
function and returns it unchanged — zero tracing, zero wrapping, zero
hot-path cost.  ``tools/graftlint`` rule R3 later traces each contracted
op with ``jax.eval_shape`` at the declared canonical sizes (abstract
shapes only — no arrays materialize, safe on any backend including a
CPU-only lint run) and diffs the inferred output dtypes/shapes against
the declaration.  A dtype regression fails lint before it ever reaches a
benchmark.

Vocabulary:

- :class:`Spec` — one abstract array: dtype name + shape of ints and/or
  symbolic dim names resolved through ``DIMS`` (contract-local ``dims=``
  overrides).  Specs nest freely inside tuples / lists / dicts /
  NamedTuples for structured inputs (``StoreCols``, ``CandTable``) and
  outputs (``Delivery``, ``InsertResult``).
- callables as input values — evaluated at CHECK time with the resolved
  dims dict (``lambda d: CommunityConfig(n_peers=d["N"], ...)``), so ops
  needing host-side config objects stay declarable without importing or
  constructing anything at decoration time.
- :func:`host_helper` — marks a public function that is deliberately NOT
  a traced op (backend predicates, static size math).  R3 requires every
  public symbol to carry one of the two markers, so an op added without
  a contract is itself a lint finding.

Canonical sizes are deliberately tiny (tracing cost only) and chosen so
no two dims collide — a transposed output shape cannot masquerade as
correct.
"""

from __future__ import annotations

# Default canonical sizes for symbolic dims.  All PAIRWISE DISTINCT and
# all tiny: eval_shape never materializes data, these only need to make
# shapes unambiguous — distinctness is what lets R3 catch a transposed
# output (two dims sharing a size would make the swap invisible).
# Contracts may override per-op via ``dims={...}``; constraint to keep:
# C (fan-out) <= K (candidate slots), per CommunityConfig.__post_init__.
DIMS = {
    "N": 4,     # peers
    "M": 6,     # store slots per peer
    "B": 3,     # intake batch entries per peer
    "E": 8,     # edges (logical packets) per round
    "W": 2,     # bloom words per filter
    "K": 14,    # candidate-table slots
    "A": 7,     # auth-table rows
    "S": 9,     # per-request slots / member-set slots
    "U": 13,    # candidate observations per round
    "C": 10,    # forward fan-out
    "H": 11,    # bloom hash functions
    "Q": 12,    # inbox slots per destination
}
assert len(set(DIMS.values())) == len(DIMS), "canonical dims must differ"


class Spec:
    """One abstract array in a contract: dtype name + symbolic shape."""

    __slots__ = ("dtype", "shape")

    def __init__(self, dtype: str, shape: tuple = ()):
        self.dtype = dtype
        self.shape = tuple(shape)

    def __repr__(self) -> str:
        dims = ", ".join(str(d) for d in self.shape)
        return f"Spec({self.dtype!r}, ({dims}))"

    def resolve(self, dims: dict) -> tuple:
        """Concrete (dtype, shape) under a dims table."""
        return (self.dtype,
                tuple(dims[d] if isinstance(d, str) else d
                      for d in self.shape))


def contract(out, dims: dict | None = None, **inputs):
    """Attach a shape/dtype contract to an op.  Metadata only — the
    function is returned unchanged; ``tools/graftlint`` R3 does the
    checking offline via ``jax.eval_shape``.

    ``out``: pytree of :class:`Spec` matching the op's return structure.
    ``dims``: per-op overrides of the canonical :data:`DIMS` sizes.
    ``**inputs``: one entry per parameter — a Spec (abstract array), a
    pytree containing Specs (NamedTuple/tuple/list/dict inputs), a
    zero-arg-of-dims callable (host objects built at check time), or any
    concrete value (static args passed through verbatim).
    """
    def mark(fn):
        fn.__graft_contract__ = {"out": out, "dims": dims or {},
                                 "inputs": inputs}
        return fn
    return mark


def host_helper(fn):
    """Mark a public ops-module function as deliberately uncontracted:
    host-side planning math (backend predicates, static size
    computation), never traced, never on the wire."""
    fn.__graft_host_helper__ = True
    return fn


def _materialize(value, dims: dict):
    """Spec -> ShapeDtypeStruct; containers recurse; callables get the
    dims table; everything else passes through as a static value."""
    import jax
    import numpy as np

    if isinstance(value, Spec):
        dtype, shape = value.resolve(dims)
        return jax.ShapeDtypeStruct(shape, np.dtype(dtype))
    if callable(value) and not isinstance(value, type):
        return value(dims)
    if isinstance(value, tuple) and hasattr(value, "_fields"):
        return type(value)(*(_materialize(v, dims) for v in value))
    if isinstance(value, tuple):
        return tuple(_materialize(v, dims) for v in value)
    if isinstance(value, list):
        return [_materialize(v, dims) for v in value]
    if isinstance(value, dict):
        return {k: _materialize(v, dims) for k, v in value.items()}
    return value


def check_contract(fn) -> list:
    """Trace ``fn`` with ``jax.eval_shape`` at its contract's canonical
    sizes and diff declared vs inferred output dtypes/shapes.

    Returns a list of human-readable mismatch strings (empty == clean).
    Tracing only — no array is ever materialized, so this is safe to run
    on any backend, at any declared size.
    """
    import jax

    spec = fn.__graft_contract__
    dims = {**DIMS, **spec["dims"]}
    try:
        kwargs = {k: _materialize(v, dims)
                  for k, v in spec["inputs"].items()}
        declared = _materialize(spec["out"], dims)
    except Exception as e:  # noqa: BLE001 — a typo'd dim/dtype name in
        #   the declaration itself must surface as an R3 finding, not
        #   crash run() and suppress every rule's report
        return [f"contract declaration invalid: {type(e).__name__}: {e}"]
    # Partition: parameters whose value tree carries abstract arrays are
    # traced; everything else (sizes, dtypes, configs, None) is closed
    # over as a static value — exactly how the engine calls these ops.
    traced = {k: v for k, v in kwargs.items()
              if any(isinstance(leaf, jax.ShapeDtypeStruct)
                     for leaf in jax.tree_util.tree_leaves(v))}
    static = {k: v for k, v in kwargs.items() if k not in traced}
    try:
        inferred = jax.eval_shape(
            lambda **kw: fn(**kw, **static), **traced)
    except Exception as e:  # noqa: BLE001 — any trace failure IS the finding
        return [f"eval_shape failed: {type(e).__name__}: {e}"]

    decl_leaves = jax.tree_util.tree_leaves(declared)
    inf_leaves = jax.tree_util.tree_leaves(inferred)
    problems = []
    if len(decl_leaves) != len(inf_leaves):
        problems.append(
            f"output arity: declared {len(decl_leaves)} array leaves, "
            f"inferred {len(inf_leaves)}")
        return problems
    for i, (d, got) in enumerate(zip(decl_leaves, inf_leaves)):
        want_dtype = str(getattr(d, "dtype", d))
        got_dtype = str(got.dtype)
        if want_dtype != got_dtype:
            problems.append(
                f"leaf {i}: dtype {got_dtype}, contract declares "
                f"{want_dtype}")
        want_shape = tuple(getattr(d, "shape", ()))
        got_shape = tuple(got.shape)
        if want_shape != got_shape:
            problems.append(
                f"leaf {i}: shape {got_shape}, contract declares "
                f"{want_shape}")
    return problems
