"""Dissemination-tracing kernels: per-slot lineage folds, coverage
counts, and coverage-percentile latches.

The jit-traced half of the trace plane (:mod:`dispersy_tpu.traceplane`
declares the static :class:`~dispersy_tpu.traceplane.TraceConfig` and
the channel-code table; the engine composes these into the fused round
only when ``trace.enabled``, so a disabled plane compiles to the
identical step).  Every op mirrors bit-for-bit in the oracle
(:mod:`dispersy_tpu.oracle.sim` walks its intake batch sequentially —
the first same-key occurrence is the only one that can land, so the
set-based folds here and the oracle's in-order walk agree exactly), the
same lockstep discipline as every other ops module.
"""

from __future__ import annotations

import jax.numpy as jnp

from dispersy_tpu.ops.contracts import Spec, contract
from dispersy_tpu.traceplane import NUM_CHANNELS

_CODES = tuple(range(1, NUM_CHANNELS + 1))   # CH_CREATE..CH_FLOOD


@contract(out=(Spec("uint32", ("N",)), Spec("uint8", ("N",)),
               Spec("uint32", ("N",)),
               Spec("uint32", ("N", NUM_CHANNELS)),
               Spec("uint32", ("N", NUM_CHANNELS))),
          first=Spec("uint32", ("N",)), chan=Spec("uint8", ("N",)),
          dups=Spec("uint32", ("N",)), match=Spec("bool", ("N", "B")),
          landed=Spec("bool", ("N", "B")),
          arrived=Spec("bool", ("N", "B")),
          chan_code=Spec("uint8", ("B",)), round_post=Spec("uint32", ()),
          dims={"N": 15})
def slot_lineage(first: jnp.ndarray, chan: jnp.ndarray,
                 dups: jnp.ndarray, match: jnp.ndarray,
                 landed: jnp.ndarray, arrived: jnp.ndarray,
                 chan_code: jnp.ndarray, round_post):
    """Fold one intake batch into one tracked slot's lineage columns.

    ``match`` marks batch entries carrying the slot's (author, gt) key;
    ``landed`` the entries that entered the logical store this round
    (staging append under the byte diet, accepted-fresh on the legacy
    path); ``arrived`` every entry that passed intake (``accept_store``
    — the delivery boundary); ``chan_code`` the per-entry channel
    (static per batch segment, traceplane.CH_*).  The USEFUL entry is a
    landed match on a peer with no lineage yet — at most one per batch
    (in-batch dedup keeps only the first same-key occurrence fresh), so
    its channel is exact; every other arrived match is a duplicate
    delivery.  Returns the updated ``(first, chan, dups)`` columns plus
    per-channel useful/duplicate counts (u32[N, 4], channel order
    ``traceplane.CHANNEL_NAMES``).
    """
    useful_e = match & landed & (first == jnp.uint32(0))[:, None]
    any_u = jnp.any(useful_e, axis=1)
    # Exactly one useful entry per row (batch dedup), so max-select
    # recovers its channel code.
    ch_new = jnp.max(jnp.where(useful_e, chan_code[None, :],
                               jnp.uint8(0)), axis=1)
    first = jnp.where(any_u, round_post, first)
    chan = jnp.where(any_u, ch_new, chan)
    dup_e = (match & arrived) & ~useful_e
    dups = dups + jnp.sum(dup_e, axis=1, dtype=jnp.uint32)
    useful_by = jnp.stack(
        [(any_u & (ch_new == jnp.uint8(c))).astype(jnp.uint32)
         for c in _CODES], axis=1)
    dup_by = jnp.stack(
        [jnp.sum(dup_e & (chan_code == jnp.uint8(c))[None, :], axis=1,
                 dtype=jnp.uint32)
         for c in _CODES], axis=1)
    return first, chan, dups, useful_by, dup_by


@contract(out=Spec("uint32", ("T",)),
          first=Spec("uint32", ("N", "T")), members=Spec("bool", ("N",)),
          dims={"T": 5})
def coverage_counts(first: jnp.ndarray,
                    members: jnp.ndarray) -> jnp.ndarray:
    """Per-slot coverage numerators: alive non-tracker peers whose
    first-arrival round is set — exactly ``engine.coverage``'s count,
    reduced on device."""
    return jnp.sum((first != jnp.uint32(0)) & members[:, None], axis=0,
                   dtype=jnp.uint32)


@contract(out=Spec("uint32", ("T", 3)),
          latch=Spec("uint32", ("T", 3)), cov=Spec("uint32", ("T",)),
          registered=Spec("bool", ("T",)),
          alive_cnt=Spec("uint32", ()), round_post=Spec("uint32", ()),
          dims={"T": 5})
def latch_update(latch: jnp.ndarray, cov: jnp.ndarray,
                 registered: jnp.ndarray, alive_cnt,
                 round_post) -> jnp.ndarray:
    """Latch rounds-to-{50,90,99}%-coverage per slot: once a registered
    slot's coverage first reaches ``pct`` percent of the alive members
    (integer math: ``cov * 100 >= pct * alive``), the post-step round
    latches and never moves.  Column order = traceplane.LATCH_PCTS."""
    pcts = jnp.asarray((50, 90, 99), jnp.uint32)
    reach = (cov[:, None] * jnp.uint32(100)
             >= pcts[None, :] * alive_cnt)
    cond = ((latch == jnp.uint32(0)) & registered[:, None]
            & (alive_cnt > jnp.uint32(0)) & reach)
    return jnp.where(cond, round_post, latch)
