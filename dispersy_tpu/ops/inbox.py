"""The delivery kernel: UDP datagrams as a sort-by-receiver scatter.

This is the single most important porting seam (SURVEY.md §5.8): the
reference's ``Endpoint`` hands raw UDP datagrams to
``Dispersy.on_incoming_packets`` (reference: endpoint.py
``StandaloneEndpoint`` select() loop; dispersy.py ``on_incoming_packets``).
The simulation replaces the socket with an *edge list*: every logical packet
this round is a (destination, payload-columns) row, and delivery is

    sort by destination  ->  rank within destination group
    ->  bounded scatter into a [N, B] inbox, slots >= B dropped.

Dropping on overflow is deliberate fidelity, not a limitation: UDP has no
delivery guarantee and the reference's 65k recv buffer drops bursts the same
way (modeled, counted, never an error).  Packet loss is the caller's
Bernoulli mask on ``valid``.

Bandwidth notes (the round is memory-bound, BENCH.md roofline):

- Only the ROUTING information rides the sort.  When ``(destination,
  edge-position)`` packs into one uint32 — ``bits(n_peers) +
  bits(E) <= 32`` — a single packed key is sorted (keys are unique, so
  the sort needs no stability and no tie-break operand); otherwise the
  two-key ``(key, pos)`` form runs.  Both orders are identical:
  lexicographic (key, pos) IS the packed integer order.
- Payload columns never ride the sort at all: each edge's inbox slot is
  scattered back to edge order first, and the columns then scatter
  STRAIGHT from edge order into the inbox — one pass per column instead
  of the previous gather-to-sorted-order + scatter (this is where the
  [E, bloom_words] introduction-request payload used to pay double).

Under a sharded peer axis the ``lax.sort`` + scatter lower to XLA
all-to-all/collective-permute over ICI — exactly where the reference's
UDP fan-out sat.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
from jax import lax

from dispersy_tpu.ops.contracts import Spec, contract, host_helper


class Delivery(NamedTuple):
    inbox: tuple          # tuple of [N, B] arrays, one per payload column
    inbox_valid: jnp.ndarray  # bool[N, B]
    n_dropped: jnp.ndarray    # i32[N] packets lost to inbox overflow per dest
    edge_slot: jnp.ndarray    # i32[E] slot each edge landed in, -1 if dropped


@host_helper
def packed_key_bits(n_peers: int, n_edges: int,
                    cls_bits: int = 0) -> int | None:
    """Bits needed for the packed (destination[, class], position) sort
    key, or None when it cannot fit uint32.  The key space is
    [0, n_peers] (``n_peers`` = the park value for undeliverable
    packets) shifted above ``cls_bits`` admission-class bits (8 when an
    overload-plane ``cls`` operand rides the sort, else 0) shifted
    above ``bits(n_edges - 1)`` position bits."""
    pos_bits = max(1, (n_edges - 1).bit_length()) if n_edges else 1
    key_bits = max(1, n_peers.bit_length())
    total = key_bits + cls_bits + pos_bits
    return pos_bits if total <= 32 else None


@contract(out=Delivery(inbox=(Spec("uint32", ("N", "Q")),
                              Spec("uint32", ("N", "Q", "W"))),
                       inbox_valid=Spec("bool", ("N", "Q")),
                       n_dropped=Spec("int32", ("N",)),
                       edge_slot=Spec("int32", ("E",))),
          dst=Spec("int32", ("E",)),
          cols=[Spec("uint32", ("E",)), Spec("uint32", ("E", "W"))],
          valid=Spec("bool", ("E",)),
          n_peers=lambda d: d["N"], inbox_size=lambda d: d["Q"],
          cls=None)
def deliver(dst: jnp.ndarray, cols: Sequence[jnp.ndarray],
            valid: jnp.ndarray, n_peers: int, inbox_size: int,
            cls: jnp.ndarray | None = None) -> Delivery:
    """Deliver an edge list of logical packets into per-peer inboxes.

    ``dst``: i32[E] destination peer of each packet (any value for invalid
    rows).  ``cols``: payload columns, each [E, ...] (trailing dims allowed —
    e.g. the Bloom word vector riding an introduction request).  ``valid``:
    bool[E] — packets already lost (loss mask, dead sender) are simply
    invalid.

    Delivery order within one destination is edge-list order (the sort
    key carries the edge position as tie-break), so the oracle can
    reproduce inboxes exactly.

    ``cls`` (optional, the overload plane's priority admission —
    dispersy_tpu/overload.py): a u32[E] admission class in [0, 255] per
    edge.  When given, the within-destination order becomes
    ``(cls, pos)`` — LOWER classes claim inbox slots first and overflow
    sheds the highest classes instead of the latest arrivals, modeling
    an endpoint that inspects the wire-visible message class before its
    bounded recv buffer overflows (the reference's ``endpoint.py``
    buffer, made class-aware).  ``None`` (the default) is byte-identical
    to the pre-overload kernel.

    ``edge_slot`` is the *receipt*: the inbox slot each edge landed in (or -1
    for dropped/invalid).  It lets the sender later fetch a per-slot reply
    from the destination by pure gather — request/response round trips
    (introduction response, sync records) need no second global sort, which
    also mirrors the reference: responses are unicast back to the socket
    address the request came from, never re-routed.
    """
    e = dst.shape[0]
    # Invalid packets park at key n_peers: sorted past every real peer, and
    # their scatter index lands out of range -> dropped by mode="drop".
    # Out-of-range destinations (including NO_PEER = -1 from a walker with
    # no target) are undeliverable, not an error — park them too; a negative
    # index must never reach the scatter (it would wrap to another inbox).
    ok = valid & (dst >= 0) & (dst < n_peers)
    key = jnp.where(ok, dst, n_peers).astype(jnp.int32)
    pos = jnp.arange(e, dtype=jnp.int32)  # carries order through the sort
    cls_bits = 8 if cls is not None else 0
    pos_bits = packed_key_bits(n_peers, e, cls_bits)
    if pos_bits is not None and cls is None:
        # One uint32 key: (key << pos_bits) | pos.  Keys are globally
        # unique, so the sort may be unstable and carries ONE operand.
        packed = ((key.astype(jnp.uint32) << pos_bits)
                  | pos.astype(jnp.uint32))
        (spacked,) = lax.sort((packed,), dimension=0, is_stable=False,
                              num_keys=1)
        skey = (spacked >> pos_bits).astype(jnp.int32)
        spos = (spacked & jnp.uint32((1 << pos_bits) - 1)).astype(jnp.int32)
    elif pos_bits is not None:
        # One uint32 key: (key << (8 + pos_bits)) | (cls << pos_bits) |
        # pos — lexicographic (key, cls, pos) IS the packed order.
        packed = ((key.astype(jnp.uint32) << (cls_bits + pos_bits))
                  | (cls.astype(jnp.uint32) << pos_bits)
                  | pos.astype(jnp.uint32))
        (spacked,) = lax.sort((packed,), dimension=0, is_stable=False,
                              num_keys=1)
        skey = (spacked >> (cls_bits + pos_bits)).astype(jnp.int32)
        spos = (spacked & jnp.uint32((1 << pos_bits) - 1)).astype(jnp.int32)
    elif cls is None:
        # (key, pos) pairs are unique, so stability is still unnecessary.
        skey, spos = lax.sort((key, pos), dimension=0, is_stable=False,
                              num_keys=2)
    else:
        skey, _, spos = lax.sort(
            (key, cls.astype(jnp.uint32), pos), dimension=0,
            is_stable=False, num_keys=3)

    # Rank within destination group = index - first index of that key, with
    # the group starts found by a cummax scan (a searchsorted here would be
    # E·log E serialized gathers on TPU; the scan is a handful of passes).
    iota = jnp.arange(e, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), skey[1:] != skey[:-1]])
    first = lax.cummax(jnp.where(is_start, iota, 0))
    slot = iota - first
    keep = (skey < n_peers) & (slot < inbox_size)
    # Each edge's slot back in EDGE order (one i32 scatter); payload
    # columns then go straight from edge order into the inbox without
    # ever being permuted into sorted order.
    edge_slot = (jnp.zeros((e,), jnp.int32)
                 .at[spos].set(jnp.where(keep, slot, -1), mode="drop"))
    kept_e = edge_slot >= 0
    if (n_peers + 1) * inbox_size < 2 ** 31:
        # One flat int32 scatter per column...
        flat = jnp.where(kept_e, key * inbox_size + edge_slot,
                         n_peers * inbox_size)
        inbox = tuple(
            jnp.zeros((n_peers * inbox_size,) + c.shape[1:], c.dtype)
            .at[flat].set(c, mode="drop")
            .reshape((n_peers, inbox_size) + c.shape[1:])
            for c in cols)
        inbox_valid = (jnp.zeros((n_peers * inbox_size,), bool)
                       .at[flat].set(True, mode="drop")
                       .reshape(n_peers, inbox_size))
    else:
        # ...but key*inbox_size overflows int32 past 2^31 elements, so
        # giant populations scatter in the two-coordinate (key, slot)
        # form — same bits, one extra index operand (the ops/bloom.py /
        # ops/store.py two-form rule; graftlint R6).
        sl = jnp.where(kept_e, edge_slot, inbox_size)
        inbox = tuple(
            jnp.zeros((n_peers, inbox_size) + c.shape[1:], c.dtype)
            .at[key, sl].set(c, mode="drop")
            for c in cols)
        inbox_valid = (jnp.zeros((n_peers, inbox_size), bool)
                       .at[key, sl].set(True, mode="drop"))
    overflow = ok & ~kept_e
    n_dropped = (jnp.zeros((n_peers,), jnp.int32)
                 .at[jnp.where(overflow, key, n_peers)]
                 .add(1, mode="drop"))
    return Delivery(inbox=inbox, inbox_valid=inbox_valid, n_dropped=n_dropped,
                    edge_slot=edge_slot)


class RaggedDelivery(NamedTuple):
    delivery: Delivery        # inbox/inbox_valid/n_dropped/edge_slot,
    #                           exactly the global kernel's contract
    shed: jnp.ndarray         # bool[E] edge lost to a full send bucket
    #                           (cross_shard_budget overflow) — the
    #                           SENDER-side attribution stream


@contract(out=RaggedDelivery(
              delivery=Delivery(inbox=(Spec("uint32", ("N", "Q")),
                                       Spec("uint32", ("N", "Q", "W"))),
                                inbox_valid=Spec("bool", ("N", "Q")),
                                n_dropped=Spec("int32", ("N",)),
                                edge_slot=Spec("int32", ("E",))),
              shed=Spec("bool", ("E",))),
          dst=Spec("int32", ("E",)),
          cols=[Spec("uint32", ("E",)), Spec("uint32", ("E", "W"))],
          valid=Spec("bool", ("E",)),
          n_peers=lambda d: d["N"], inbox_size=lambda d: d["Q"],
          shards=2, budget=0, cls=None, need_receipts=True)
def deliver_ragged(dst: jnp.ndarray, cols: Sequence[jnp.ndarray],
                   valid: jnp.ndarray, n_peers: int, inbox_size: int,
                   shards: int, budget: int = 0,
                   cls: jnp.ndarray | None = None,
                   need_receipts: bool = True) -> RaggedDelivery:
    """:func:`deliver`, restructured for a peer axis sharded ``shards``
    ways: shard-local sort + capped send buckets + ONE explicit
    all-to-all exchange + shard-local landing scatter.

    The global kernel's single ``lax.sort`` over every edge makes XLA
    materialize the full edge list on every chip before it can split
    the scatter.  Here each shard handles only its own slice:

    1. The edge list (padded to ``S * ceil(E/S)``) is viewed as
       ``[S, El]`` — row ``r`` is the slice shard ``r`` produced (push
       edges are peer-major, so row == sender shard up to padding).
    2. Each row sorts SHARD-LOCALLY by ``(destination[, class], local
       position)`` — identical order to the global sort restricted to
       the row, since global position is monotone in local position.
    3. Entries bucket by destination shard (``dst // (N/S)``); each
       ``(row, destination-shard)`` bucket holds at most ``B`` entries
       — ``budget`` if > 0, else the exact worst case ``El``.  The
       first ``B`` of a bucket in sorted order win; the rest are SHED
       at the sender (``shed``, counted by the caller into
       ``stats.xshard_shed``) — bounded-inbox backpressure, the
       ``store_stage`` overflow contract.  With ``budget=0`` nothing
       ever sheds and the result is bit-identical to :func:`deliver`.
    4. The ``[S, S, B]`` bucket buffers transpose source<->destination
       axes — THE one collective (an all-to-all over ICI when the peer
       axis is mesh-sharded; a transpose on one device).
    5. Each destination shard merges its ``S * B`` arrivals with one
       LOCAL sort by ``(destination[, class], global position)`` —
       the same admission order as the global kernel — and lands them
       with a SHARD-LOCAL two-coordinate scatter (local destination,
       slot): indices stay < ``(N/S) * Q`` per shard, which is what
       breaks the 2^31 global-flat-index ceiling (graftlint R6).
    6. ``need_receipts``: the ``edge_slot`` receipt needs the reverse
       transpose (a second collective).  One-way channels (push) pass
       False and get ``edge_slot = -1`` everywhere for free.

    Drop accounting is unchanged: ``n_dropped`` counts per-destination
    inbox overflow only; bucket sheds are the sender's loss, reported
    separately in ``shed`` (never both for one edge).
    """
    s = shards
    e = dst.shape[0]
    nl = n_peers // s
    el = -(-e // s)
    ep = el * s
    if ep != e:
        padn = ep - e
        dst = jnp.concatenate([dst, jnp.zeros((padn,), dst.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((padn,), bool)])
        if cls is not None:
            cls = jnp.concatenate([cls, jnp.zeros((padn,), cls.dtype)])
        cols = [jnp.concatenate(
                    [c, jnp.zeros((padn,) + c.shape[1:], c.dtype)])
                for c in cols]
    b = el if budget <= 0 else min(budget, el)

    ok = valid & (dst >= 0) & (dst < n_peers)
    key = jnp.where(ok, dst, n_peers).astype(jnp.int32).reshape(s, el)
    lpos = jnp.broadcast_to(jnp.arange(el, dtype=jnp.int32), (s, el))
    cls_bits = 8 if cls is not None else 0
    scls = None

    # -- 2. shard-local source sort ------------------------------------
    pos_bits = packed_key_bits(n_peers, el, cls_bits)
    if pos_bits is not None:
        packed = (key.astype(jnp.uint32) << (cls_bits + pos_bits)) \
            | lpos.astype(jnp.uint32)
        if cls is not None:
            packed = packed | (cls.astype(jnp.uint32).reshape(s, el)
                               << pos_bits)
        (sp,) = lax.sort((packed,), dimension=1, is_stable=False,
                         num_keys=1)
        skey = (sp >> (cls_bits + pos_bits)).astype(jnp.int32)
        slpos = (sp & jnp.uint32((1 << pos_bits) - 1)).astype(jnp.int32)
        if cls is not None:
            scls = (sp >> pos_bits).astype(jnp.uint32) & jnp.uint32(0xFF)
    elif cls is None:
        skey, slpos = lax.sort((key, lpos), dimension=1,
                               is_stable=False, num_keys=2)
    else:
        skey, scls, slpos = lax.sort(
            (key, cls.astype(jnp.uint32).reshape(s, el), lpos),
            dimension=1, is_stable=False, num_keys=3)

    # -- 3. destination-shard buckets, budget-capped -------------------
    dsh = jnp.where(skey < n_peers, skey // nl, s)
    iota = lpos  # arange(el) per row
    is_start = jnp.concatenate(
        [jnp.ones((s, 1), bool), dsh[:, 1:] != dsh[:, :-1]], axis=1)
    first = lax.cummax(jnp.where(is_start, iota, 0), axis=1)
    rank = iota - first
    keep_src = (dsh < s) & (rank < b)
    shed_sorted = (dsh < s) & (rank >= b)
    rows = jnp.arange(s, dtype=jnp.int32)[:, None]
    # Bucket position of each sorted entry; s*b = "nowhere" (mode=drop).
    bidx = jnp.where(keep_src, dsh * b + rank, s * b)

    def to_bucket(val_sorted, fill, dtype):
        init = jnp.full((s, s * b) + val_sorted.shape[2:], fill, dtype)
        return init.at[rows, bidx].set(val_sorted, mode="drop")

    gpos = rows * el + slpos  # global edge position, computed locally
    bkey = to_bucket(skey, n_peers, jnp.int32)
    bgpos = to_bucket(gpos, 0, jnp.int32)
    bcls = (to_bucket(scls, 0, jnp.uint32) if cls is not None else None)
    bcols = []
    for c in cols:
        cr = c.reshape((s, el) + c.shape[1:])
        ix = slpos.reshape((s, el) + (1,) * (cr.ndim - 2))
        csorted = jnp.take_along_axis(cr, ix, axis=1)
        bcols.append(to_bucket(csorted, 0, c.dtype))

    # -- 4. THE exchange: transpose source <-> destination shard -------
    def exchange(buf):
        return (buf.reshape((s, s, b) + buf.shape[2:])
                .swapaxes(0, 1)
                .reshape((s, s * b) + buf.shape[2:]))

    xkey = exchange(bkey)
    xgpos = exchange(bgpos)
    xcls = exchange(bcls) if cls is not None else None
    xcols = [exchange(bc) for bc in bcols]

    # -- 5. destination merge: local sort + shard-local landing --------
    ei = jnp.broadcast_to(jnp.arange(s * b, dtype=jnp.int32), (s, s * b))
    gpos_bits = packed_key_bits(n_peers, ep, cls_bits)
    if gpos_bits is not None:
        packed2 = (xkey.astype(jnp.uint32) << (cls_bits + gpos_bits)) \
            | xgpos.astype(jnp.uint32)
        if cls is not None:
            packed2 = packed2 | (xcls << gpos_bits)
        sp2, sei = lax.sort((packed2, ei), dimension=1, is_stable=False,
                            num_keys=1)
        dkey = (sp2 >> (cls_bits + gpos_bits)).astype(jnp.int32)
    elif cls is None:
        dkey, _, sei = lax.sort((xkey, xgpos, ei), dimension=1,
                                is_stable=True, num_keys=2)
    else:
        dkey, _, _, sei = lax.sort((xkey, xcls, xgpos, ei), dimension=1,
                                   is_stable=True, num_keys=3)
    iota2 = ei
    is_start2 = jnp.concatenate(
        [jnp.ones((s, 1), bool), dkey[:, 1:] != dkey[:, :-1]], axis=1)
    first2 = lax.cummax(jnp.where(is_start2, iota2, 0), axis=1)
    slot = iota2 - first2
    real = dkey < n_peers
    keep_dst = real & (slot < inbox_size)
    # Slot of each EXCHANGE entry (sei is a per-row permutation, so
    # every position is written; -1 = dropped/empty).
    entry_slot = (jnp.full((s, s * b), -1, jnp.int32)
                  .at[rows, sei].set(jnp.where(keep_dst, slot, -1),
                                     mode="drop"))
    # Shard-local two-coordinate landing scatter: indices bounded by
    # (N/S) * Q per shard — never a global flat index (graftlint R6).
    lkey = xkey - rows * nl
    lsl = jnp.where(entry_slot >= 0, entry_slot, inbox_size)
    lkey = jnp.where(entry_slot >= 0, lkey, nl)
    inbox = tuple(
        jnp.zeros((s, nl, inbox_size) + c.shape[2:], c.dtype)
        .at[rows, lkey, lsl].set(c, mode="drop")
        .reshape((n_peers, inbox_size) + c.shape[2:])
        for c in xcols)
    inbox_valid = (jnp.zeros((s, nl, inbox_size), bool)
                   .at[rows, lkey, lsl].set(True, mode="drop")
                   .reshape(n_peers, inbox_size))
    ovf = real & (slot >= inbox_size)
    ldst_sorted = jnp.where(ovf, dkey - rows * nl, nl)
    n_dropped = (jnp.zeros((s, nl), jnp.int32)
                 .at[rows, ldst_sorted].add(1, mode="drop")
                 .reshape(n_peers))

    # -- 6. receipts + sender-side shed, back in edge order ------------
    shed_rows = (jnp.zeros((s, el), bool)
                 .at[rows, slpos].set(shed_sorted, mode="drop"))
    shed = shed_rows.reshape(ep)[:e]
    if need_receipts:
        rslot = exchange(entry_slot)  # reverse transpose: same permute
        got = jnp.take_along_axis(
            rslot, jnp.where(keep_src, bidx, 0), axis=1)
        sslot = jnp.where(keep_src, got, -1)
        edge_slot = (jnp.full((s, el), -1, jnp.int32)
                     .at[rows, slpos].set(sslot, mode="drop")
                     .reshape(ep)[:e])
    else:
        edge_slot = jnp.full((e,), -1, jnp.int32)
    return RaggedDelivery(
        delivery=Delivery(inbox=inbox, inbox_valid=inbox_valid,
                          n_dropped=n_dropped, edge_slot=edge_slot),
        shed=shed)
