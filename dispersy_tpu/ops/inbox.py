"""The delivery kernel: UDP datagrams as a sort-by-receiver scatter.

This is the single most important porting seam (SURVEY.md §5.8): the
reference's ``Endpoint`` hands raw UDP datagrams to
``Dispersy.on_incoming_packets`` (reference: endpoint.py
``StandaloneEndpoint`` select() loop; dispersy.py ``on_incoming_packets``).
The simulation replaces the socket with an *edge list*: every logical packet
this round is a (destination, payload-columns) row, and delivery is

    stable sort by destination  ->  rank within destination group
    ->  bounded scatter into a [N, B] inbox, slots >= B dropped.

Dropping on overflow is deliberate fidelity, not a limitation: UDP has no
delivery guarantee and the reference's 65k recv buffer drops bursts the same
way (modeled, counted, never an error).  Packet loss is the caller's
Bernoulli mask on ``valid``.

Under a sharded peer axis the ``lax.sort`` + scatter lower to XLA
all-to-all/collective-permute over ICI — exactly where the reference's
UDP fan-out sat.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
from jax import lax


class Delivery(NamedTuple):
    inbox: tuple          # tuple of [N, B] arrays, one per payload column
    inbox_valid: jnp.ndarray  # bool[N, B]
    n_dropped: jnp.ndarray    # i32[N] packets lost to inbox overflow per dest
    edge_slot: jnp.ndarray    # i32[E] slot each edge landed in, -1 if dropped


def deliver(dst: jnp.ndarray, cols: Sequence[jnp.ndarray],
            valid: jnp.ndarray, n_peers: int, inbox_size: int) -> Delivery:
    """Deliver an edge list of logical packets into per-peer inboxes.

    ``dst``: i32[E] destination peer of each packet (any value for invalid
    rows).  ``cols``: payload columns, each [E, ...] (trailing dims allowed —
    e.g. the Bloom word vector riding an introduction request).  ``valid``:
    bool[E] — packets already lost (loss mask, dead sender) are simply
    invalid.

    Delivery order within one destination is edge-list order (lax.sort is
    stable), so the oracle can reproduce inboxes exactly.

    ``edge_slot`` is the *receipt*: the inbox slot each edge landed in (or -1
    for dropped/invalid).  It lets the sender later fetch a per-slot reply
    from the destination by pure gather — request/response round trips
    (introduction response, sync records) need no second global sort, which
    also mirrors the reference: responses are unicast back to the socket
    address the request came from, never re-routed.
    """
    e = dst.shape[0]
    # Invalid packets park at key n_peers: sorted past every real peer, and
    # their scatter index lands out of range -> dropped by mode="drop".
    # Out-of-range destinations (including NO_PEER = -1 from a walker with
    # no target) are undeliverable, not an error — park them too; a negative
    # index must never reach the scatter (it would wrap to another inbox).
    ok = valid & (dst >= 0) & (dst < n_peers)
    key = jnp.where(ok, dst, n_peers).astype(jnp.int32)
    pos = jnp.arange(e, dtype=jnp.int32)  # carries stability through sort
    skey, spos = lax.sort((key, pos), dimension=0, num_keys=2)
    # Only (key, pos) ride the sort; payload columns follow via one gather —
    # this is what lets columns carry trailing dims.
    scols = tuple(jnp.take(c, spos, axis=0) for c in cols)

    # Rank within destination group = index - first index of that key, with
    # the group starts found by a cummax scan (a searchsorted here would be
    # E·log E serialized gathers on TPU; the scan is a handful of passes).
    iota = jnp.arange(e, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), skey[1:] != skey[:-1]])
    first = lax.cummax(jnp.where(is_start, iota, 0))
    slot = iota - first
    keep = (skey < n_peers) & (slot < inbox_size)
    flat = jnp.where(keep, skey * inbox_size + slot, n_peers * inbox_size)

    inbox = tuple(
        jnp.zeros((n_peers * inbox_size,) + c.shape[1:], c.dtype)
        .at[flat].set(c, mode="drop")
        .reshape((n_peers, inbox_size) + c.shape[1:])
        for c in scols)
    inbox_valid = (jnp.zeros((n_peers * inbox_size,), bool)
                   .at[flat].set(True, mode="drop")
                   .reshape(n_peers, inbox_size))
    overflow = (skey < n_peers) & (slot >= inbox_size)
    n_dropped = (jnp.zeros((n_peers,), jnp.int32)
                 .at[jnp.where(overflow, skey, n_peers)]
                 .add(1, mode="drop"))
    edge_slot = (jnp.zeros((e,), jnp.int32)
                 .at[spos].set(jnp.where(keep, slot, -1)))
    return Delivery(inbox=inbox, inbox_valid=inbox_valid, n_dropped=n_dropped,
                    edge_slot=edge_slot)
