"""The delivery kernel: UDP datagrams as a sort-by-receiver scatter.

This is the single most important porting seam (SURVEY.md §5.8): the
reference's ``Endpoint`` hands raw UDP datagrams to
``Dispersy.on_incoming_packets`` (reference: endpoint.py
``StandaloneEndpoint`` select() loop; dispersy.py ``on_incoming_packets``).
The simulation replaces the socket with an *edge list*: every logical packet
this round is a (destination, payload-columns) row, and delivery is

    sort by destination  ->  rank within destination group
    ->  bounded scatter into a [N, B] inbox, slots >= B dropped.

Dropping on overflow is deliberate fidelity, not a limitation: UDP has no
delivery guarantee and the reference's 65k recv buffer drops bursts the same
way (modeled, counted, never an error).  Packet loss is the caller's
Bernoulli mask on ``valid``.

Bandwidth notes (the round is memory-bound, BENCH.md roofline):

- Only the ROUTING information rides the sort.  When ``(destination,
  edge-position)`` packs into one uint32 — ``bits(n_peers) +
  bits(E) <= 32`` — a single packed key is sorted (keys are unique, so
  the sort needs no stability and no tie-break operand); otherwise the
  two-key ``(key, pos)`` form runs.  Both orders are identical:
  lexicographic (key, pos) IS the packed integer order.
- Payload columns never ride the sort at all: each edge's inbox slot is
  scattered back to edge order first, and the columns then scatter
  STRAIGHT from edge order into the inbox — one pass per column instead
  of the previous gather-to-sorted-order + scatter (this is where the
  [E, bloom_words] introduction-request payload used to pay double).

Under a sharded peer axis the ``lax.sort`` + scatter lower to XLA
all-to-all/collective-permute over ICI — exactly where the reference's
UDP fan-out sat.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
from jax import lax

from dispersy_tpu.ops.contracts import Spec, contract, host_helper


class Delivery(NamedTuple):
    inbox: tuple          # tuple of [N, B] arrays, one per payload column
    inbox_valid: jnp.ndarray  # bool[N, B]
    n_dropped: jnp.ndarray    # i32[N] packets lost to inbox overflow per dest
    edge_slot: jnp.ndarray    # i32[E] slot each edge landed in, -1 if dropped


@host_helper
def packed_key_bits(n_peers: int, n_edges: int,
                    cls_bits: int = 0) -> int | None:
    """Bits needed for the packed (destination[, class], position) sort
    key, or None when it cannot fit uint32.  The key space is
    [0, n_peers] (``n_peers`` = the park value for undeliverable
    packets) shifted above ``cls_bits`` admission-class bits (8 when an
    overload-plane ``cls`` operand rides the sort, else 0) shifted
    above ``bits(n_edges - 1)`` position bits."""
    pos_bits = max(1, (n_edges - 1).bit_length()) if n_edges else 1
    key_bits = max(1, n_peers.bit_length())
    total = key_bits + cls_bits + pos_bits
    return pos_bits if total <= 32 else None


@contract(out=Delivery(inbox=(Spec("uint32", ("N", "Q")),
                              Spec("uint32", ("N", "Q", "W"))),
                       inbox_valid=Spec("bool", ("N", "Q")),
                       n_dropped=Spec("int32", ("N",)),
                       edge_slot=Spec("int32", ("E",))),
          dst=Spec("int32", ("E",)),
          cols=[Spec("uint32", ("E",)), Spec("uint32", ("E", "W"))],
          valid=Spec("bool", ("E",)),
          n_peers=lambda d: d["N"], inbox_size=lambda d: d["Q"],
          cls=None)
def deliver(dst: jnp.ndarray, cols: Sequence[jnp.ndarray],
            valid: jnp.ndarray, n_peers: int, inbox_size: int,
            cls: jnp.ndarray | None = None) -> Delivery:
    """Deliver an edge list of logical packets into per-peer inboxes.

    ``dst``: i32[E] destination peer of each packet (any value for invalid
    rows).  ``cols``: payload columns, each [E, ...] (trailing dims allowed —
    e.g. the Bloom word vector riding an introduction request).  ``valid``:
    bool[E] — packets already lost (loss mask, dead sender) are simply
    invalid.

    Delivery order within one destination is edge-list order (the sort
    key carries the edge position as tie-break), so the oracle can
    reproduce inboxes exactly.

    ``cls`` (optional, the overload plane's priority admission —
    dispersy_tpu/overload.py): a u32[E] admission class in [0, 255] per
    edge.  When given, the within-destination order becomes
    ``(cls, pos)`` — LOWER classes claim inbox slots first and overflow
    sheds the highest classes instead of the latest arrivals, modeling
    an endpoint that inspects the wire-visible message class before its
    bounded recv buffer overflows (the reference's ``endpoint.py``
    buffer, made class-aware).  ``None`` (the default) is byte-identical
    to the pre-overload kernel.

    ``edge_slot`` is the *receipt*: the inbox slot each edge landed in (or -1
    for dropped/invalid).  It lets the sender later fetch a per-slot reply
    from the destination by pure gather — request/response round trips
    (introduction response, sync records) need no second global sort, which
    also mirrors the reference: responses are unicast back to the socket
    address the request came from, never re-routed.
    """
    e = dst.shape[0]
    # Invalid packets park at key n_peers: sorted past every real peer, and
    # their scatter index lands out of range -> dropped by mode="drop".
    # Out-of-range destinations (including NO_PEER = -1 from a walker with
    # no target) are undeliverable, not an error — park them too; a negative
    # index must never reach the scatter (it would wrap to another inbox).
    ok = valid & (dst >= 0) & (dst < n_peers)
    key = jnp.where(ok, dst, n_peers).astype(jnp.int32)
    pos = jnp.arange(e, dtype=jnp.int32)  # carries order through the sort
    cls_bits = 8 if cls is not None else 0
    pos_bits = packed_key_bits(n_peers, e, cls_bits)
    if pos_bits is not None and cls is None:
        # One uint32 key: (key << pos_bits) | pos.  Keys are globally
        # unique, so the sort may be unstable and carries ONE operand.
        packed = ((key.astype(jnp.uint32) << pos_bits)
                  | pos.astype(jnp.uint32))
        (spacked,) = lax.sort((packed,), dimension=0, is_stable=False,
                              num_keys=1)
        skey = (spacked >> pos_bits).astype(jnp.int32)
        spos = (spacked & jnp.uint32((1 << pos_bits) - 1)).astype(jnp.int32)
    elif pos_bits is not None:
        # One uint32 key: (key << (8 + pos_bits)) | (cls << pos_bits) |
        # pos — lexicographic (key, cls, pos) IS the packed order.
        packed = ((key.astype(jnp.uint32) << (cls_bits + pos_bits))
                  | (cls.astype(jnp.uint32) << pos_bits)
                  | pos.astype(jnp.uint32))
        (spacked,) = lax.sort((packed,), dimension=0, is_stable=False,
                              num_keys=1)
        skey = (spacked >> (cls_bits + pos_bits)).astype(jnp.int32)
        spos = (spacked & jnp.uint32((1 << pos_bits) - 1)).astype(jnp.int32)
    elif cls is None:
        # (key, pos) pairs are unique, so stability is still unnecessary.
        skey, spos = lax.sort((key, pos), dimension=0, is_stable=False,
                              num_keys=2)
    else:
        skey, _, spos = lax.sort(
            (key, cls.astype(jnp.uint32), pos), dimension=0,
            is_stable=False, num_keys=3)

    # Rank within destination group = index - first index of that key, with
    # the group starts found by a cummax scan (a searchsorted here would be
    # E·log E serialized gathers on TPU; the scan is a handful of passes).
    iota = jnp.arange(e, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), skey[1:] != skey[:-1]])
    first = lax.cummax(jnp.where(is_start, iota, 0))
    slot = iota - first
    keep = (skey < n_peers) & (slot < inbox_size)
    # Each edge's slot back in EDGE order (one i32 scatter); payload
    # columns then go straight from edge order into the inbox without
    # ever being permuted into sorted order.
    edge_slot = (jnp.zeros((e,), jnp.int32)
                 .at[spos].set(jnp.where(keep, slot, -1), mode="drop"))
    kept_e = edge_slot >= 0
    flat = jnp.where(kept_e, key * inbox_size + edge_slot,
                     n_peers * inbox_size)

    inbox = tuple(
        jnp.zeros((n_peers * inbox_size,) + c.shape[1:], c.dtype)
        .at[flat].set(c, mode="drop")
        .reshape((n_peers, inbox_size) + c.shape[1:])
        for c in cols)
    inbox_valid = (jnp.zeros((n_peers * inbox_size,), bool)
                   .at[flat].set(True, mode="drop")
                   .reshape(n_peers, inbox_size))
    overflow = ok & ~kept_e
    n_dropped = (jnp.zeros((n_peers,), jnp.int32)
                 .at[jnp.where(overflow, key, n_peers)]
                 .add(1, mode="drop"))
    return Delivery(inbox=inbox, inbox_valid=inbox_valid, n_dropped=n_dropped,
                    edge_slot=edge_slot)
