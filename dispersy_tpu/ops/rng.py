"""Counter-based deterministic randomness for the simulation.

The reference draws from Python's global ``random.random()`` wherever it
needs chance (reference: community.py ``dispersy_get_walk_candidate`` category
split, ``dispersy_get_introduce_candidate`` third-peer pick).  The rebuild
cannot reproduce that draw *order* (everything is batched), and SURVEY.md §7
stage 9 explicitly licenses the divergence: only the *distributions* must
match, verified by convergence curves.

What the rebuild adds on top is **bit-exact reproducibility between the TPU
kernels and the CPU oracle**: every stochastic choice is a pure function of

    (seed, round_index, peer, purpose[, salt])

mixed through the same murmur3-style finalizer as the Bloom hashes
(:mod:`dispersy_tpu.ops.hashing`), so the pure-Python oracle
(:mod:`dispersy_tpu.oracle.sim`) replays the identical choices without
jax — the property the trace-equality tests (driver config #1) rely on.
``jax.random`` is deliberately *not* used on the hot path: threefry is ~10×
the ALU work per draw and impossible to mirror in ten lines of Python.
"""

from __future__ import annotations

import jax.numpy as jnp

from dispersy_tpu.ops.contracts import Spec, contract
from dispersy_tpu.ops.hashing import combine, fmix32

# Purpose tags: domain separation between independent random streams.
P_CATEGORY = 1   # walk-category draw (walked/stumbled/introduced/bootstrap)
P_SLOT = 2       # which eligible candidate slot to walk to
P_INTRO = 3      # which verified candidate to introduce (third peer)
P_BOOTSTRAP = 4  # which tracker to bootstrap from
P_CHURN = 5      # does this peer churn out this round
P_LOSS = 6       # per-packet Bernoulli loss
P_GOSSIP = 7     # forwarding fan-out choice (CommunityDestination)
P_SIGN = 8       # counterparty's countersign decision (allow_signature_func)
P_NAT = 9        # connection-type assignment (public vs symmetric NAT);
#                  drawn at round 0 so the type is static per identity —
#                  NAT is the router's property, surviving churn rebirth
# Chaos-harness streams (dispersy_tpu/faults.py FaultModel):
P_GE = 10        # Gilbert–Elliott channel transition (one draw/peer/round)
P_GE_LOSS = 11   # state-dependent per-packet loss (same salt blocks as
#                  P_LOSS, independent stream so base loss stays bit-exact)
P_CORRUPT = 12   # per-delivered-record payload corruption
P_DUP = 13       # per-delivered-record duplication
P_FLOOD = 14     # byzantine flood victim + junk-field draws
# Recovery-plane stream (dispersy_tpu/recovery.py RecoveryConfig):
P_RECOVERY = 15  # walk-backoff decay draw (one per peer per clean round)
# Ingress-protection stream (dispersy_tpu/overload.py OverloadConfig):
P_OVERLOAD = 16  # token-bucket fractional-refill draw (one per peer
#                  per push-phase round; ops/overload.bucket_refill)


@contract(out=Spec("uint32", ()), key=Spec("uint32", (2,)))
def fold_seed(key: jnp.ndarray) -> jnp.ndarray:
    """uint32[2] state key -> one uint32 stream seed."""
    return combine(fmix32(key[..., 0]), key[..., 1])


@contract(out=Spec("uint32", ("N",)),
          seed=Spec("uint32", ()), round_index=Spec("uint32", ()),
          peer=Spec("int32", ("N",)), purpose=P_SLOT, salt=0)
def rand_u32(seed: jnp.ndarray, round_index: jnp.ndarray, peer: jnp.ndarray,
             purpose: int, salt: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Deterministic uint32 draw; broadcasts over peer/salt shapes."""
    h = combine(jnp.asarray(seed, jnp.uint32), jnp.asarray(round_index, jnp.uint32))
    h = combine(h, jnp.uint32(purpose))
    h = combine(h, jnp.asarray(peer, jnp.uint32))
    return combine(h, jnp.asarray(salt, jnp.uint32))


@contract(out=Spec("float32", ("N",)),
          seed=Spec("uint32", ()), round_index=Spec("uint32", ()),
          peer=Spec("int32", ("N",)), purpose=P_CATEGORY, salt=0)
def rand_uniform(seed, round_index, peer, purpose: int, salt=0) -> jnp.ndarray:
    """float32 in [0, 1) from the same counter stream."""
    u = rand_u32(seed, round_index, peer, purpose, salt)
    # 24-bit mantissa path: exact in float32, matches the oracle's
    # (u >> 8) / 2**24 arithmetic bit-for-bit.
    return (u >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))
