"""Fault-channel kernels: GE chain advance, partition gate, health math.

The jit-traced half of the chaos harness (:mod:`dispersy_tpu.faults`
declares the static :class:`~dispersy_tpu.faults.FaultModel`; the engine
composes these into the fused round only when the matching knob is
non-zero, so a disabled fault model compiles to the identical step).
Every op mirrors bit-for-bit in the oracle (:mod:`dispersy_tpu.oracle.sim`
``_ge_advance`` / ``_blocked`` / ``_popcount`` / the store-invariant
walk), the same lockstep discipline as every other ops module.
"""

from __future__ import annotations

import jax.numpy as jnp

from dispersy_tpu.ops import rng
from dispersy_tpu.ops.contracts import Spec, contract

_U32_N = Spec("uint32", ("N",))


@contract(out=Spec("bool", ("N",)),
          ge_bad=Spec("bool", ("N",)), seed=Spec("uint32", ()),
          rnd=Spec("uint32", ()), idx=Spec("int32", ("N",)),
          p_bad=0.25, p_good=0.5)
def ge_advance(ge_bad: jnp.ndarray, seed, rnd, idx: jnp.ndarray,
               p_bad: float, p_good: float) -> jnp.ndarray:
    """One Gilbert–Elliott transition for every peer's channel.

    In the good state the channel turns bad with ``p_bad``; in the bad
    state it recovers with ``p_good``.  One uniform draw per peer per
    round from the counter stream (purpose ``P_GE``), so the oracle
    replays the chain exactly; the loss draws themselves then condition
    on the post-transition state (this round's weather, not last
    round's).
    """
    u = rng.rand_uniform(seed, rnd, idx, rng.P_GE)
    return jnp.where(ge_bad,
                     ~(u < jnp.float32(p_good)),
                     u < jnp.float32(p_bad))


@contract(out=Spec("bool", ("N",)),
          src=Spec("int32", ("N",)), dst=Spec("int32", ("N",)),
          partitions=(((0, 1), (2, 3)),))
def partition_blocked(src: jnp.ndarray, dst: jnp.ndarray,
                      partitions: tuple) -> jnp.ndarray:
    """bool mask: is the directed edge src -> dst severed by a partition?

    ``partitions`` is the static ``FaultModel.partitions`` tuple of
    ``((lo_a, hi_a), (lo_b, hi_b))`` range pairs; an edge is blocked when
    its endpoints fall in opposite ranges of any pair (both directions —
    a netsplit has no good side).  Broadcasts over any matching
    src/dst shapes; NO_PEER / out-of-range endpoints are never inside a
    range, hence never blocked (their packets are already undeliverable).
    """
    out = None
    for (a_lo, a_hi), (b_lo, b_hi) in partitions:
        src_a = (src >= a_lo) & (src < a_hi)
        src_b = (src >= b_lo) & (src < b_hi)
        dst_a = (dst >= a_lo) & (dst < a_hi)
        dst_b = (dst >= b_lo) & (dst < b_hi)
        hit = (src_a & dst_b) | (src_b & dst_a)
        out = hit if out is None else out | hit
    if out is None:
        return jnp.zeros(jnp.broadcast_shapes(jnp.shape(src),
                                              jnp.shape(dst)), bool)
    return out


@contract(out=_U32_N, x=_U32_N)
def popcount_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Per-element set-bit count of a uint32 array (SWAR form — wraps
    mod 2^32 at every step, mirrored with explicit masks in the
    oracle's ``_popcount``).  Drives the Bloom-saturation sentinel."""
    x = x.astype(jnp.uint32)
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> jnp.uint32(24)


@contract(out=Spec("bool", ("N",)),
          gt=Spec("uint32", ("N", "M")), member=Spec("uint32", ("N", "M")))
def store_invariant_violated(gt: jnp.ndarray,
                             member: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: does any adjacent store-row pair break the sorted /
    UNIQUE(member, gt) / holes-last invariant?

    The store ring's contract is ascending ``(gt, member)`` with
    ``EMPTY_U32`` holes compacted to the end; because the hole sentinel
    sorts after every real clock, a live row following a hole also fails
    the strict-ascending test — one comparison covers all three clauses.
    The ``HEALTH_STORE_INVARIANT`` sentinel latches on this instead of
    letting a corrupt ring silently poison every later merge.
    """
    from dispersy_tpu.config import EMPTY_U32

    g0, g1 = gt[:, :-1], gt[:, 1:]
    m0, m1 = member[:, :-1], member[:, 1:]
    ok = ((g1 == jnp.uint32(EMPTY_U32))
          | (g0 < g1) | ((g0 == g1) & (m0 < m1)))
    return jnp.any(~ok, axis=1)
