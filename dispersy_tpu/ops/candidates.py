"""Candidate-table kernels: peer bookkeeping + walk-target sampling.

The reference keeps one ``WalkCandidate`` object per known address with three
activity timestamps and derives a *category* from which are still fresh
(reference: candidate.py — ``WalkCandidate.walk/.stumble/.intro``,
``get_category``: walked if walked within ~57.5 s, stumbled within ~57.5 s,
intro within ~27.5 s; ``is_eligible_for_walk`` additionally requires the last
walk to be older than the ~27.5 s eligibility delay).  The category drives
``Community.dispersy_get_walk_candidate``'s split (≈49.75% walked / 24.875%
stumbled / 24.875% introduced / 0.5% bootstrap) and
``dispersy_get_introduce_candidate``'s third-peer pick.

TPU recast: a fixed ``[N, K]`` slot table per peer (peer index + the three
timestamps); category is *derived* from timestamps each round so it can never
go stale; upserts are a short static loop of vectorized scatter steps (U is a
small compile-time constant); sampling uses hashed per-slot priorities so the
oracle replays choices bit-for-bit.  Unlike the reference's unbounded dict,
the table evicts the least-recently-active slot on overflow — bounded state
is the price of static shapes, and K is a config knob
(``CommunityConfig.k_candidates``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from dispersy_tpu.config import (CAT_INTRODUCED, CAT_NONE, CAT_STUMBLED,
                                 CAT_WALKED, NO_PEER, CommunityConfig)
from dispersy_tpu.ops import rng
from dispersy_tpu.ops.contracts import Spec, contract

# Update kinds for upsert_many (which timestamp an observation refreshes).
KIND_WALK = 0     # we walked to it and got a response
KIND_STUMBLE = 1  # it contacted us (intro request / puncture)
KIND_INTRO = 2    # a third party introduced it to us
_NEVER = -1.0e9


class CandTable(NamedTuple):
    """[N, K] candidate slots; ``peer == NO_PEER`` marks an empty slot."""
    peer: jnp.ndarray          # i32[N, K]
    last_walk: jnp.ndarray     # f32[N, K]
    last_stumble: jnp.ndarray  # f32[N, K]
    last_intro: jnp.ndarray    # f32[N, K]


# Canonical contract inputs: an [N, K] table and a config whose table
# sizes agree with the canonical dims (one tracker so the bootstrap
# branch traces; fan-out C <= K as __post_init__ requires).
_TAB = CandTable(peer=Spec("int32", ("N", "K")),
                 last_walk=Spec("float32", ("N", "K")),
                 last_stumble=Spec("float32", ("N", "K")),
                 last_intro=Spec("float32", ("N", "K")))


def _canon_cfg(d) -> CommunityConfig:
    return CommunityConfig(n_peers=d["N"], n_trackers=1,
                           k_candidates=d["K"], forward_fanout=d["C"])


_NOW = Spec("float32", ())
_SEED = Spec("uint32", ())
_ROUND = Spec("uint32", ())
_SELF = Spec("int32", ("N",))


@contract(out=Spec("int32", ("N", "K")), tab=_TAB, now=_NOW, cfg=_canon_cfg)
def categories(tab: CandTable, now: jnp.ndarray,
               cfg: CommunityConfig) -> jnp.ndarray:
    """Per-slot category, derived from timestamp freshness.

    Precedence walked > stumbled > introduced mirrors
    ``WalkCandidate.get_category``; a slot whose every timestamp has expired
    is CAT_NONE (the reference would have garbage-collected the candidate).
    """
    occupied = tab.peer != NO_PEER
    walked = occupied & (now - tab.last_walk < cfg.walk_lifetime)
    stumbled = occupied & (now - tab.last_stumble < cfg.walk_lifetime)
    intro = occupied & (now - tab.last_intro < cfg.intro_lifetime)
    return jnp.where(
        walked, CAT_WALKED,
        jnp.where(stumbled, CAT_STUMBLED,
                  jnp.where(intro, CAT_INTRODUCED, CAT_NONE)))


@contract(out=Spec("bool", ("N", "K")), tab=_TAB,
          cats=Spec("int32", ("N", "K")), now=_NOW, cfg=_canon_cfg)
def is_eligible(tab: CandTable, cats: jnp.ndarray, now: jnp.ndarray,
                cfg: CommunityConfig) -> jnp.ndarray:
    """``WalkCandidate.is_eligible_for_walk``: fresh category + walk cooldown."""
    cooled = now - tab.last_walk >= cfg.eligibility_delay
    return (cats != CAT_NONE) & cooled


def _activity(tab: CandTable) -> jnp.ndarray:
    """Most recent activity per slot; empty slots -> -inf so they evict first."""
    act = jnp.maximum(tab.last_walk,
                      jnp.maximum(tab.last_stumble, tab.last_intro))
    return jnp.where(tab.peer == NO_PEER, _NEVER * 2.0, act)


@contract(out=_TAB, tab=_TAB, upd_peer=Spec("int32", ("N", "U")),
          upd_kind=Spec("int32", ("N", "U")),
          upd_valid=Spec("bool", ("N", "U")), now=_NOW, self_idx=_SELF,
          n_trackers=1)
def upsert_many(tab: CandTable, upd_peer: jnp.ndarray, upd_kind: jnp.ndarray,
                upd_valid: jnp.ndarray, now: jnp.ndarray,
                self_idx: jnp.ndarray, n_trackers: int = 0) -> CandTable:
    """Apply ``[N, U]`` candidate observations to the ``[N, K]`` table.

    Semantics per update (mirroring WalkCandidate bookkeeping):
    - existing entry for that peer -> refresh the kind's timestamp;
    - otherwise insert into the least-recently-active slot (empty slots
      first), resetting the other timestamps to never;
    - updates naming the owner itself are ignored (the reference never keeps
      itself as a candidate);
    - updates naming a tracker are ignored: bootstrap peers live outside the
      walk categories (reference: candidate.py ``BootstrapCandidate`` is kept
      separate from the ``_candidates`` dict and only reached through the
      walker's 0.5% bootstrap branch) — otherwise every bootstrap walk would
      promote the tracker into the ~49.75% revisit pool and the whole overlay
      would collapse onto it.

    U is static and small (a handful of observations per peer per round), so
    this unrolls into U vectorized scatter steps; duplicates within one batch
    resolve sequentially, exactly like the oracle's Python loop.
    """
    u = upd_peer.shape[-1]
    upd_valid = (upd_valid & (upd_peer != NO_PEER)
                 & (upd_peer != self_idx[:, None])
                 & (upd_peer >= n_trackers))

    def body(i, t: CandTable) -> CandTable:
        p = lax.dynamic_index_in_dim(upd_peer, i, axis=1)        # [N, 1]
        kind = lax.dynamic_index_in_dim(upd_kind, i, axis=1)     # [N, 1]
        ok = lax.dynamic_index_in_dim(upd_valid, i, axis=1)      # [N, 1]
        match = (t.peer == p) & ok                               # [N, K]
        have = jnp.any(match, axis=1, keepdims=True)             # [N, 1]
        # Insertion target: least-recently-active slot (ties -> lowest index).
        victim = jnp.argmin(_activity(t), axis=1)                # [N]
        insert = (jnp.arange(t.peer.shape[1]) == victim[:, None]) & ok & ~have
        hit = match | insert
        new_peer = jnp.where(hit, jnp.where(insert, p, t.peer), t.peer)

        def stamp(ts, k, reset):
            fresh = hit & (kind == k)
            cleared = jnp.where(insert & reset, _NEVER, ts)
            return jnp.where(fresh, now, cleared)

        return CandTable(
            peer=new_peer,
            last_walk=stamp(t.last_walk, KIND_WALK, True),
            last_stumble=stamp(t.last_stumble, KIND_STUMBLE, True),
            last_intro=stamp(t.last_intro, KIND_INTRO, True),
        )

    return lax.fori_loop(0, u, body, tab) if u > 0 else tab


@contract(out=_TAB, tab=_TAB, peer=Spec("int32", ("N",)),
          valid=Spec("bool", ("N",)))
def remove(tab: CandTable, peer: jnp.ndarray, valid: jnp.ndarray) -> CandTable:
    """Drop one candidate per row (walk-timeout eviction).

    Reference: the walk-timeout path treats the candidate as obsolete
    (requestcache.py ``IntroductionRequestCache.on_timeout``).
    """
    kill = (tab.peer == peer[:, None]) & valid[:, None]
    return CandTable(
        peer=jnp.where(kill, NO_PEER, tab.peer),
        last_walk=jnp.where(kill, _NEVER, tab.last_walk),
        last_stumble=jnp.where(kill, _NEVER, tab.last_stumble),
        last_intro=jnp.where(kill, _NEVER, tab.last_intro),
    )


def _pick_by_priority(mask: jnp.ndarray, prio: jnp.ndarray) -> jnp.ndarray:
    """Index of the max-priority True slot per row; -1 if none.

    Mask occupies the MSB (prio keeps 31 bits) so every True slot outranks
    every False slot without needing 64-bit arithmetic (x64 is off).
    """
    score = (prio >> jnp.uint32(1)) | (mask.astype(jnp.uint32) << jnp.uint32(31))
    best = jnp.argmax(score, axis=1)
    any_ = jnp.any(mask, axis=1)
    return jnp.where(any_, best, -1)


@contract(out=Spec("int32", ("N",)), tab=_TAB, now=_NOW, cfg=_canon_cfg,
          seed=_SEED, round_index=_ROUND, self_idx=_SELF,
          boot_base=None, boot_count=None)
def sample_walk_target(tab: CandTable, now: jnp.ndarray, cfg: CommunityConfig,
                       seed: jnp.ndarray, round_index: jnp.ndarray,
                       self_idx: jnp.ndarray,
                       boot_base: jnp.ndarray | None = None,
                       boot_count: jnp.ndarray | None = None) -> jnp.ndarray:
    """One walk destination per peer: ``dispersy_get_walk_candidate``.

    Category chosen by threshold on one uniform draw (≈49.75 / 24.875 /
    24.875 / 0.5 split from the reference); an empty choice falls through by
    rotating from the chosen category in (walked, stumbled, introduced,
    bootstrap) cyclic order — e.g. an empty "introduced" pick tries
    bootstrap, then walked, then stumbled.  Slot choice
    within a category is by hashed per-slot priority (uniform over eligible
    slots, oracle-replayable).  Returns i32[N], NO_PEER where no target
    exists (no eligible candidates and no trackers).

    ``boot_base``/``boot_count`` (i32[N]): each row's community tracker
    range for the bootstrap branch — multi-community layouts bootstrap
    within their own block (reference: each Community resolves its own
    tracker list).  Defaults to the global [0, n_trackers) range.
    """
    n, k = tab.peer.shape
    cats = categories(tab, now, cfg)
    elig = is_eligible(tab, cats, now, cfg)
    prio = rng.rand_u32(seed, round_index, self_idx[:, None], rng.P_SLOT,
                        jnp.arange(k)[None, :])

    picks = []
    for cat in (CAT_WALKED, CAT_STUMBLED, CAT_INTRODUCED):
        slot = _pick_by_priority(elig & (cats == cat), prio)
        picks.append(jnp.where(slot >= 0,
                               jnp.take_along_axis(
                                   tab.peer, jnp.maximum(slot, 0)[:, None],
                                   axis=1)[:, 0],
                               NO_PEER))
    # Bootstrap: a random tracker of the row's own community, never self.
    if cfg.n_trackers > 0:
        if boot_base is None:
            boot_base = jnp.zeros((n,), jnp.int32)
            boot_count = jnp.full((n,), cfg.n_trackers, jnp.int32)
        cnt = jnp.maximum(boot_count, 1).astype(jnp.uint32)
        t = boot_base + (rng.rand_u32(seed, round_index, self_idx,
                                      rng.P_BOOTSTRAP)
                         % cnt).astype(jnp.int32)
        t = jnp.where(t == self_idx,
                      boot_base + (t - boot_base + 1) % jnp.maximum(boot_count, 1),
                      t)
        boot = jnp.where((t == self_idx) | (boot_count == 0), NO_PEER, t)
    else:
        boot = jnp.full((n,), NO_PEER, jnp.int32)
    picks.append(boot)

    r = rng.rand_uniform(seed, round_index, self_idx, rng.P_CATEGORY)
    c0 = jnp.where(
        r < cfg.p_revisit_walked, 0,
        jnp.where(r < cfg.p_revisit_walked + cfg.p_stumbled, 1,
                  jnp.where(r < 1.0 - cfg.p_bootstrap, 2, 3)))
    stacked = jnp.stack(picks, axis=0)                      # [4, N]
    order = (c0[None, :] + jnp.arange(4)[:, None]) % 4      # fallback rotation
    rotated = jnp.take_along_axis(stacked, order, axis=0)   # [4, N]
    avail = rotated != NO_PEER
    first = jnp.argmax(avail, axis=0)
    target = jnp.take_along_axis(rotated, first[None, :], axis=0)[0]
    return jnp.where(jnp.any(avail, axis=0), target, NO_PEER).astype(jnp.int32)


@contract(out=Spec("int32", ("N", "C")), tab=_TAB, now=_NOW, cfg=_canon_cfg,
          seed=_SEED, round_index=_ROUND, self_idx=_SELF)
def sample_forward_targets(tab: CandTable, now: jnp.ndarray,
                           cfg: CommunityConfig, seed: jnp.ndarray,
                           round_index: jnp.ndarray,
                           self_idx: jnp.ndarray) -> jnp.ndarray:
    """``forward_fanout`` distinct verified candidates per peer: the push
    targets for this round's forward batch.

    Reference: dispersy.py ``_forward`` picks ``node_count`` random distinct
    candidates once per message batch (destination.py
    ``CommunityDestination``).  Top-C of per-slot uniform hash priorities
    over the verified slots == uniform sampling without replacement.
    Returns i32[N, C] with NO_PEER filling when fewer candidates exist.
    """
    n, k = tab.peer.shape
    c = cfg.forward_fanout
    cats = categories(tab, now, cfg)
    verified = (cats == CAT_WALKED) | (cats == CAT_STUMBLED)     # [N, K]
    prio = rng.rand_u32(seed, round_index, self_idx[:, None], rng.P_GOSSIP,
                        jnp.arange(k)[None, :] + jnp.uint32(1 << 8))
    score = (prio >> jnp.uint32(1)) | (verified.astype(jnp.uint32)
                                       << jnp.uint32(31))
    top_scores, top_slots = lax.top_k(score, c)                  # [N, C]
    picked = jnp.take_along_axis(tab.peer, top_slots, axis=1)
    ok = (top_scores >> jnp.uint32(31)) == 1                     # was verified
    return jnp.where(ok, picked, NO_PEER).astype(jnp.int32)


@contract(out=Spec("int32", ("N", "S")), tab=_TAB, now=_NOW, cfg=_canon_cfg,
          seed=_SEED, round_index=_ROUND, self_idx=_SELF,
          exclude=Spec("int32", ("N", "S")), salt_base=0,
          req_sym=Spec("bool", ("N", "S")), slot_sym=Spec("bool", ("N", "K")))
def sample_introductions(tab: CandTable, now: jnp.ndarray, cfg: CommunityConfig,
                         seed: jnp.ndarray, round_index: jnp.ndarray,
                         self_idx: jnp.ndarray, exclude: jnp.ndarray,
                         salt_base: int = 0,
                         req_sym: jnp.ndarray | None = None,
                         slot_sym: jnp.ndarray | None = None) -> jnp.ndarray:
    """Third-peer picks for a batch of introduction responses.

    ``dispersy_get_introduce_candidate``: a uniformly random *verified*
    candidate (walked or stumbled — one whose address the responder has
    directly confirmed), excluding the requester.  ``exclude`` is [N, S]
    (one requester per handled request slot); returns i32[N, S] with NO_PEER
    where the responder knows nobody else (the reference then sends a
    response carrying no introduction).  Draws for different slots use
    disjoint salts so they are independent.

    ``req_sym`` (bool[N, S]) / ``slot_sym`` (bool[N, K]), when given, carry
    the NAT connection types of the requesters and of the table's
    candidates: a symmetric-NAT requester is never introduced to a
    symmetric-NAT candidate (reference: candidate.py connection_type +
    dispersy_get_introduce_candidate's filter — hole punching cannot work
    between two address-dependent NATs).
    """
    n, k = tab.peer.shape
    s = exclude.shape[1]
    cats = categories(tab, now, cfg)
    verified = (cats == CAT_WALKED) | (cats == CAT_STUMBLED)     # [N, K]
    mask = verified[:, None, :] & (tab.peer[:, None, :] != exclude[:, :, None])
    if req_sym is not None:
        mask = mask & ~(req_sym[:, :, None] & slot_sym[:, None, :])
    salt = (jnp.arange(s)[:, None] * jnp.uint32(k)
            + jnp.arange(k)[None, :] + jnp.uint32(salt_base))    # [S, K]
    prio = rng.rand_u32(seed, round_index, self_idx[:, None, None],
                        rng.P_INTRO, salt[None, :, :])           # [N, S, K]
    score = (prio >> jnp.uint32(1)) | (mask.astype(jnp.uint32) << jnp.uint32(31))
    best = jnp.argmax(score, axis=-1)                            # [N, S]
    pick = jnp.take_along_axis(tab.peer[:, None, :], best[:, :, None],
                               axis=-1)[..., 0]
    pick = jnp.where(jnp.any(mask, axis=-1), pick, NO_PEER)
    return pick.astype(jnp.int32)
