"""Telemetry kernels: u64-safe counter sums, histograms, flight append.

The jit-traced half of the telemetry plane (:mod:`dispersy_tpu.telemetry`
declares the static :class:`~dispersy_tpu.telemetry.TelemetryConfig` and
the row schema; the engine composes these into the fused round's wrap-up
only when the matching knob is on, so disabled telemetry compiles to the
identical step).  Every op mirrors bit-for-bit in the oracle
(:mod:`dispersy_tpu.oracle.sim` packs its row through
``telemetry.pack_row_host`` from plain-int equivalents), the same
lockstep discipline as every other ops module.

Design notes:

- **u64-safe sums without x64**: per-peer counters are uint32 and their
  overlay-wide totals exceed 2^32 within one 1M-peer round, but
  ``jax_enable_x64`` stays off.  :func:`col_sum_u64` splits each word
  into its four byte lanes, reduces each lane in uint32 (exact while
  ``N * 255 < 2^32`` — ``telemetry.MAX_TELEMETRY_PEERS``, enforced by
  config validation), and recombines the lane totals into a (lo, hi)
  uint32 pair with explicit carries.  The result is the exact 64-bit
  sum of the wrapped per-peer values — bit-identical to the host-side
  ``np.uint64`` reduction ``metrics.snapshot`` used to do.
- **Histograms as scatter-adds**: one ``[N] -> [B]`` scatter-add per
  histogram (``mode="drop"`` routes masked-out entries to the spill
  index), never an ``[N, B]`` one-hot — the row is meant to make
  telemetry CHEAPER, not add an N x B intermediate.
"""

from __future__ import annotations

import jax.numpy as jnp

from dispersy_tpu.ops.contracts import Spec, contract
from dispersy_tpu.ops.faults import popcount_u32


@contract(out=Spec("uint32", (2, "C")), x=Spec("uint32", ("N", "C")))
def col_sum_u64(x: jnp.ndarray) -> jnp.ndarray:
    """Exact 64-bit column sums of a uint32 matrix, as u32 (lo, hi) rows.

    Returns ``[2, C]``: row 0 the low words, row 1 the high words of
    each column's sum over axis 0.  Exact while ``N <= MAX_TELEMETRY_PEERS``
    (byte-lane partial sums must fit uint32).
    """
    lo = jnp.zeros(x.shape[1:], jnp.uint32)
    hi = jnp.zeros(x.shape[1:], jnp.uint32)
    for sh in (0, 8, 16, 24):
        lane = jnp.sum((x >> jnp.uint32(sh)) & jnp.uint32(0xFF), axis=0,
                       dtype=jnp.uint32)            # < N * 255, exact
        add_lo = lane << jnp.uint32(sh)
        new_lo = lo + add_lo
        hi = hi + (new_lo < lo).astype(jnp.uint32)  # carry out of lo
        if sh:
            hi = hi + (lane >> jnp.uint32(32 - sh))
        lo = new_lo
    return jnp.stack([lo, hi])


@contract(out=Spec("uint32", (2,)), x=Spec("uint32", ("N",)))
def sum_u64(x: jnp.ndarray) -> jnp.ndarray:
    """:func:`col_sum_u64` for one vector: ``[2]`` = (lo, hi)."""
    return col_sum_u64(x[:, None])[:, 0]


@contract(out=Spec("uint32", ("G",)),
          val=Spec("uint32", ("N",)), mask=Spec("bool", ("N",)),
          cap=7, n_buckets=lambda d: d["G"], dims={"G": 5})
def hist_linear(val: jnp.ndarray, mask: jnp.ndarray, cap: int,
                n_buckets: int) -> jnp.ndarray:
    """Masked linear histogram over [0, cap]: bucket counts ``u32[B]``.

    Bucket of ``v`` is ``v * B // (cap + 1)`` (values at ``cap`` land in
    the last bucket; ``cap * B`` must fit uint32 — occupancy caps are
    tiny).  Masked-out entries scatter to the out-of-range spill index
    and are dropped.
    """
    b = jnp.minimum((val * jnp.uint32(n_buckets)) // jnp.uint32(cap + 1),
                    jnp.uint32(n_buckets - 1)).astype(jnp.int32)
    idx = jnp.where(mask, b, jnp.int32(n_buckets))
    return (jnp.zeros((n_buckets,), jnp.uint32)
            .at[idx].add(jnp.uint32(1), mode="drop"))


@contract(out=Spec("uint32", ("G",)),
          val=Spec("uint32", ("N",)), mask=Spec("bool", ("N",)),
          n_buckets=lambda d: d["G"], dims={"G": 5})
def hist_log2(val: jnp.ndarray, mask: jnp.ndarray,
              n_buckets: int) -> jnp.ndarray:
    """Masked bit-length histogram: bucket = ``bit_length(v)`` clamped
    to the last bucket (0 -> bucket 0; bucket b holds [2^(b-1), 2^b)).

    Bit length via bit-smear + SWAR popcount (``ops.faults``), all
    uint32 elementwise — the oracle mirrors with ``int.bit_length``.
    """
    v = val.astype(jnp.uint32)
    for sh in (1, 2, 4, 8, 16):
        v = v | (v >> jnp.uint32(sh))
    bl = popcount_u32(v)                 # == bit_length(val)
    b = jnp.minimum(bl, jnp.uint32(n_buckets - 1)).astype(jnp.int32)
    idx = jnp.where(mask, b, jnp.int32(n_buckets))
    return (jnp.zeros((n_buckets,), jnp.uint32)
            .at[idx].add(jnp.uint32(1), mode="drop"))


@contract(out=(Spec("uint32", ("D", "F")), Spec("uint32", (1,))),
          ring=Spec("uint32", ("D", "F")), pos=Spec("uint32", (1,)),
          records=Spec("uint32", ("R", "F")), valid=Spec("bool", ("R",)),
          dims={"D": 15, "F": 5, "R": 17})
def flight_append(ring: jnp.ndarray, pos: jnp.ndarray,
                  records: jnp.ndarray, valid: jnp.ndarray):
    """Append the valid records to the flight-recorder ring.

    ``pos`` is the cumulative record count (u32[1], never reduced mod
    the depth — the host decoder derives wrap state from it); valid
    records land at consecutive slots ``(pos + rank) % depth`` in rank
    order, invalid ones scatter to the spill index and are dropped.
    Callers bound the per-call valid count by the ring depth
    (``flight_per_round <= flight_recorder``, config-validated), so one
    append never overwrites its own records.
    """
    depth = ring.shape[0]
    rank = jnp.cumsum(valid.astype(jnp.uint32)) - jnp.uint32(1)
    slot = ((pos[0] + rank) % jnp.uint32(depth)).astype(jnp.int32)
    slot = jnp.where(valid, slot, jnp.int32(depth))
    ring = ring.at[slot].set(records, mode="drop")
    return ring, pos + jnp.sum(valid.astype(jnp.uint32))
