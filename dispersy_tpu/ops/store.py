"""Message-store kernels: the SQLite ``sync`` table as a sorted ring.

The reference persists every sync-distributed message in one SQLite table
(reference: dispersydatabase.py — ``sync(community, member, global_time,
meta_message, packet, undone)`` with UNIQUE(community, member, global_time))
and serves Bloom-sync slices with ``SELECT ... WHERE global_time BETWEEN ?
AND ?`` (reference: community.py ``dispersy_claim_sync_bloom_filter`` and the
``on_introduction_request`` sync responder).

TPU-native recast: each peer owns ``msg_capacity`` record slots, four uint32
columns (global_time, member, meta, payload) + flags, kept sorted
lexicographically by (global_time, member, meta, payload) with ``EMPTY_U32``
holes at the end.  Sorted order gives us:

- O(log M) slice selection via searchsorted (the BETWEEN query),
- dedup on UNIQUE(member, global_time) as an adjacent-equal test after a
  merge sort (the INSERT OR IGNORE),
- deterministic iteration order for bloom construction.

All functions are batched over the leading peer axis and shape-static, so
they fuse into the round step under jit and shard over the peer axis.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dispersy_tpu.config import EMPTY_META, EMPTY_U32, FLAGS_DTYPE, META_DTYPE
from dispersy_tpu.ops.contracts import Spec, contract, host_helper

_EMPTY = np.uint32(EMPTY_U32)


@host_helper
def empty_of(dtype) -> int:
    """Empty-slot sentinel for one record-column dtype: the all-ones
    value (EMPTY_U32 truncated to the column's width) — EMPTY_U32 for
    u32 columns, EMPTY_META for the narrowed u8 meta column.  One
    definition so every fill site stays correct as columns narrow."""
    return int(np.iinfo(np.dtype(dtype)).max)  # host-ok: static dtype math


class StoreCols(NamedTuple):
    """One peer-store (or record batch): uint32 columns, same shape.

    ``aux`` is the record's second payload word, overloaded per meta
    (config.py reserved-meta table): permission bitmask for authorize/
    revoke, target global_time for undo, sequence number for
    sequence-enabled metas.  ``flags`` is receiver-local derived state
    (bit 0 = undone) and never travels on the wire.
    """
    gt: jnp.ndarray
    member: jnp.ndarray
    meta: jnp.ndarray
    payload: jnp.ndarray
    aux: jnp.ndarray
    flags: jnp.ndarray

    @property
    def valid(self) -> jnp.ndarray:
        return self.gt != _EMPTY


# Canonical contract specs: the [N, M] store and an [N, B] arriving batch,
# both carrying the narrowed uint8 meta/flags columns the byte diet
# depends on — a promotion anywhere in the merge shows up as an R3 diff.
# The ONE StoreCols spec definition: intake.py's contracts import this so
# the next column narrowing is mirrored everywhere by construction.
@host_helper
def stc_spec(*dims) -> StoreCols:
    return StoreCols(gt=Spec("uint32", dims), member=Spec("uint32", dims),
                     meta=Spec("uint8", dims), payload=Spec("uint32", dims),
                     aux=Spec("uint32", dims), flags=Spec("uint8", dims))


_STORE_NM = stc_spec("N", "M")
_BATCH_NB = stc_spec("N", "B")


@contract(out=_STORE_NM, shape=lambda d: (d["N"], d["M"]), aux_dtype=None)
def empty_records(shape, aux_dtype=None) -> StoreCols:
    e = jnp.full(shape, _EMPTY, jnp.uint32)
    return StoreCols(gt=e, member=e,
                     meta=jnp.full(shape, EMPTY_META, META_DTYPE),
                     payload=e,
                     aux=jnp.zeros(shape, aux_dtype or jnp.uint32),
                     flags=jnp.zeros(shape, FLAGS_DTYPE))


@contract(out=Spec("int32", ("N",)), gt=Spec("uint32", ("N", "M")))
def count_valid(gt: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum((gt != _EMPTY).astype(jnp.int32), axis=-1)


@contract(out=Spec("uint32", ("N", "B")), col=Spec("uint32", ("N", "M")),
          slot=Spec("int32", ("N", "M")), width=lambda d: d["B"], fill=0)
def rank_compact(col: jnp.ndarray, slot: jnp.ndarray, width: int,
                 fill) -> jnp.ndarray:
    """Rank-scatter compaction: keep entries whose ``slot`` < ``width``.

    ``col``/``slot`` are [N, W]-shaped; entries scatter to ``slot`` in a
    fresh ``fill``-initialized row, with ``slot == width`` as the shared
    spill column that is trimmed off.  Slots below ``width`` must be unique
    per row (ranks from a cumsum are).  This is the one definition of the
    idiom used by the store merge, the sync-responder outbox, the forward
    buffer, and the delayed-message pen — linear, where a second sort
    would be O(W log W).

    The scatter runs on FLAT indices (row * (width+1) + slot) rather than
    (rows, slot) pairs: one [N, W] i32 index tensor instead of a
    two-component [N, W, 2] one — the responder loop runs 6 of these per
    request slot, so the index traffic is a first-order byte cost
    (measured ~35% of the scatter's bytes at the 1M-peer shape).
    """
    n, w = col.shape
    stride = width + 1
    if n * stride >= 2 ** 31:
        # row*stride would overflow int32 (x64 is off); the 2-D index
        # form costs more index bytes but stays correct at any shape.
        rows = jnp.arange(n)[:, None]
        return (jnp.full((n, stride), fill, col.dtype)
                .at[rows, slot].set(col, mode="drop")[..., :width])
    flat = (jnp.arange(n, dtype=jnp.int32)[:, None] * stride
            + slot.astype(jnp.int32)).reshape(-1)
    return (jnp.full((n * stride,), fill, col.dtype)
            .at[flat].set(col.reshape(-1), mode="drop")
            .reshape(n, stride)[..., :width])


@contract(out=[Spec("uint32", ("N", "B")), Spec("uint8", ("N", "B"))],
          cols_fills=[(Spec("uint32", ("N", "M")), 0),
                      (Spec("uint8", ("N", "M")), 0)],
          slot=Spec("int32", ("N", "M")), width=lambda d: d["B"],
          impl=None)
def rank_compact_many(cols_fills, slot: jnp.ndarray, width: int,
                      impl: str | None = None) -> list:
    """:func:`rank_compact` for SEVERAL same-shaped columns sharing one
    ``slot`` map — ``cols_fills`` is ``[(col, fill), ...]``.

    Two bit-identical forms, picked per backend (``impl=None``) or
    forced for tests:

    - ``"gather"`` (CPU): one permutation scatters once and every
      column follows by row-local gather (gathers are cheap there;
      per-column scatters were the store path's dominant wall cost).
    - ``"scatter"`` (TPU): per-column scatters — cross-lane gathers
      serialize there (ops/bloom.py module note) — with adjacent
      **uint8 column pairs folded into one uint16 scatter** (pack
      ``hi<<8 | lo``, scatter once, unpack): the store merge's
      (meta, flags) pair costs one pass over the slot map instead of
      two.  Packing is value-exact, so the fold is bit-identical to
      the per-column form (tests/test_store.py pins all three against
      each other).
    """
    if impl is None:
        impl = "scatter" if jax.default_backend() == "tpu" else "gather"
    if impl == "scatter":
        out: list = [None] * len(cols_fills)
        u8s = [i for i, (c, _) in enumerate(cols_fills)
               if c.dtype == jnp.uint8]
        for i, j in zip(u8s[0::2], u8s[1::2]):
            a, fa = cols_fills[i]
            b, fb = cols_fills[j]
            packed = ((a.astype(jnp.uint16) << jnp.uint16(8))
                      | b.astype(jnp.uint16))
            pc = rank_compact(
                packed, slot, width,
                (int(fa) << 8) | int(fb))  # host-ok: fills are static
            out[i] = (pc >> jnp.uint16(8)).astype(jnp.uint8)
            out[j] = (pc & jnp.uint16(0xFF)).astype(jnp.uint8)
        for i, (c, f) in enumerate(cols_fills):
            if out[i] is None:
                out[i] = rank_compact(c, slot, width, f)
        return out
    n, w = slot.shape
    src = jnp.broadcast_to(jnp.arange(w, dtype=jnp.int32), (n, w))
    perm = rank_compact(src, slot, width, w)          # w = "empty" slot
    ix = jnp.minimum(perm, w - 1)
    live = perm < w
    return [jnp.where(live, jnp.take_along_axis(c, ix, axis=-1),
                      jnp.asarray(f, c.dtype))
            for c, f in cols_fills]


# Cohort-staggered compaction (dispersy_tpu/storediet.py, PR 20): peer
# idx belongs to cohort ``idx % cohorts`` — a MOD (strided) assignment,
# so reshaping the peer axis [N, ...] -> [N//cohorts, cohorts, ...] is a
# bitcast that groups each cohort into one slice of the NON-leading
# axis.  The active cohort's [N//cohorts, ...] block then extracts with
# a dynamic_slice at the TRACED cohort index — crucially on an axis the
# mesh never shards (parallel/mesh.py shards axis 0 only), so on a
# sharded fleet every device slices its own resident rows and the
# extraction moves zero cross-shard bytes while each shard keeps an
# equal share of every cohort's work.  These two are the ONE
# block-extraction idiom the engine's sync/compact/serve path and the
# cost model both rely on: row j of the block is full row
# ``j * cohorts + a``.


@contract(out=Spec("uint32", (2, "M")), col=Spec("uint32", ("N", "M")),
          a=Spec("uint32", ()), cohorts=2)
def cohort_take(col: jnp.ndarray, a: jnp.ndarray,
                cohorts: int) -> jnp.ndarray:
    """Extract cohort ``a``'s [N//cohorts, ...] row block from a full
    [N, ...] peer-axis array (``a`` traced u32, ``cohorts`` static)."""
    n = col.shape[0]
    blk = n // cohorts
    r = col.reshape((blk, cohorts) + col.shape[1:])
    out = lax.dynamic_slice_in_dim(r, a.astype(jnp.int32), 1, axis=1)
    return out.reshape((blk,) + col.shape[1:])


@contract(out=Spec("uint32", ("N", "M")), col=Spec("uint32", ("N", "M")),
          blk=Spec("uint32", (2, "M")), a=Spec("uint32", ()), cohorts=2)
def cohort_put(col: jnp.ndarray, blk: jnp.ndarray, a: jnp.ndarray,
               cohorts: int) -> jnp.ndarray:
    """Write cohort ``a``'s row block back into the full [N, ...] array
    (inverse of :func:`cohort_take`; other cohorts' rows untouched).
    The dynamic_update_slice updates in place under donation — HLO cost
    analysis charges it the BLOCK's bytes, not the full array's, which
    is exactly the flattening the cohort stagger exists to buy."""
    n = col.shape[0]
    blk_n = n // cohorts
    r = col.reshape((blk_n, cohorts) + col.shape[1:])
    upd = blk.reshape((blk_n, 1) + col.shape[1:])
    starts = (jnp.int32(0), a.astype(jnp.int32)) + tuple(
        jnp.int32(0) for _ in col.shape[1:])
    return lax.dynamic_update_slice(r, upd, starts).reshape(col.shape)


@host_helper
def cohort_take_cols(stc: StoreCols, a, cohorts: int) -> StoreCols:
    """:func:`cohort_take` over every column of one store/staging block
    (host_helper: a trivial per-column map, no dtype surface of its
    own)."""
    return StoreCols(*(cohort_take(c, a, cohorts) for c in stc))


@host_helper
def cohort_put_cols(stc: StoreCols, blk: StoreCols, a,
                    cohorts: int) -> StoreCols:
    """:func:`cohort_put` over every column of one store/staging block."""
    return StoreCols(*(cohort_put(c, b, a, cohorts)
                       for c, b in zip(stc, blk)))


class InsertResult(NamedTuple):
    store: StoreCols
    n_inserted: jnp.ndarray  # i32[N] new records now in the store
    n_dropped: jnp.ndarray   # i32[N] new records lost (dup or overflow)
    n_evicted: jnp.ndarray   # i32[N] existing records lost to overflow


@contract(out=InsertResult(store=_STORE_NM,
                           n_inserted=Spec("int32", ("N",)),
                           n_dropped=Spec("int32", ("N",)),
                           n_evicted=Spec("int32", ("N",))),
          store=_STORE_NM, new=_BATCH_NB, new_mask=Spec("bool", ("N", "B")),
          history=())
def store_insert(store: StoreCols, new: StoreCols,
                 new_mask: jnp.ndarray,
                 history: tuple = ()) -> InsertResult:
    """Merge a batch of records into each peer's sorted store.

    Semantics mirror the reference's store pipeline
    (reference: dispersy.py ``store_update_forward`` -> INSERT into sync):

    - UNIQUE(member, global_time): among records sharing (gt, member) the
      *existing* store entry wins (a second message by the same member at the
      same global_time is dropped — the reference treats that as a conflict
      and keeps the first-seen packet).
    - ``history``: per-user-meta keep-last-k (reference: distribution.py
      ``LastSyncDistribution(history_size=k)`` + the check/clean-up in
      community.py that deletes older rows per (member, meta)): when meta i
      has history[i] = k > 0, only the k highest-global-time records per
      (member, meta) survive the merge — an arriving older record is
      dropped, an arriving newer one evicts the oldest kept.  Empty tuple
      (or all zeros) = FullSync for every meta.
    - capacity overflow keeps the M records that sort first (lowest
      global_time) — modeling a full store the way UDP overflow drops
      packets: counted, never raised.  New records that don't fit are
      reported in n_dropped; *existing* records bumped out by a
      lower-global_time arrival are reported in n_evicted.

    ``store``: [N, M] columns; ``new``: [N, B] columns; ``new_mask``: [N, B].
    """
    m = store.gt.shape[-1]
    # The batch's narrowed columns follow the STORE's dtypes (truncation
    # maps EMPTY_U32 -> EMPTY_META, real values are unchanged — the
    # reachable value set fits either width).  Mixed-width inputs would
    # otherwise make the sort form promote while the merge form
    # truncates, silently breaking their bit-identity.
    if (new.meta.dtype != store.meta.dtype
            or new.flags.dtype != store.flags.dtype
            or new.aux.dtype != store.aux.dtype):
        new = new._replace(meta=new.meta.astype(store.meta.dtype),
                           flags=new.flags.astype(store.flags.dtype),
                           aux=new.aux.astype(store.aux.dtype))
    n_before = count_valid(store.gt)
    meta_empty = jnp.asarray(empty_of(new.meta.dtype), new.meta.dtype)
    masked = StoreCols(
        gt=jnp.where(new_mask, new.gt, _EMPTY),
        member=jnp.where(new_mask, new.member, _EMPTY),
        meta=jnp.where(new_mask, new.meta, meta_empty),
        payload=jnp.where(new_mask, new.payload, _EMPTY),
        aux=jnp.where(new_mask, new.aux, 0),
        flags=jnp.where(new_mask, new.flags, 0),
    )
    # Also guard against EMPTY sentinel gt arriving as a "new" record.
    n_new_valid = count_valid(masked.gt)

    if _prefer_merge(store.gt.shape[-1] + masked.gt.shape[-1]):
        gt, member, origin, meta, payload, aux, flags = \
            _merge_ordered(store, masked)
    else:
        gt, member, origin, meta, payload, aux, flags = \
            _sort_ordered(store, masked)

    dup = jnp.zeros_like(gt, dtype=bool).at[..., 1:].set(
        (gt[..., 1:] == gt[..., :-1]) & (member[..., 1:] == member[..., :-1])
        & (gt[..., 1:] != _EMPTY))
    kill = dup
    if any(k > 0 for k in history):
        # LastSync keep-last-k: evict every record with >= k higher-gt
        # survivors in its (member, meta) group.  gts within a group are
        # unique (UNIQUE(member, gt) holds after the dup kill), so the
        # count is unambiguous.  [.., W, W] pairwise compare, W = M + B —
        # only compiled in for communities that declare a LastSync meta.
        nm = len(history)
        k_arr = jnp.asarray(history, jnp.int32)
        meta_c = jnp.minimum(meta, jnp.uint32(nm - 1)).astype(jnp.int32)
        k_meta = jnp.where(meta < nm, jnp.take(k_arr, meta_c, axis=0), 0)
        live = (gt != _EMPTY) & ~dup
        same = (live[..., :, None] & live[..., None, :]
                & (member[..., :, None] == member[..., None, :])
                & (meta[..., :, None] == meta[..., None, :]))
        newer = jnp.sum(same & (gt[..., None, :] > gt[..., :, None]),
                        axis=-1)
        kill = dup | ((k_meta > 0) & live & (newer >= k_meta))
    # Compact by scatter instead of a second sort: survivors are already
    # in sorted order (UNIQUE(member, gt) holds after the dup kill, so
    # (gt, member) alone determines the order), and a rank-scatter is
    # linear where the sort is O(W log W) — store_insert runs once per
    # round over [N, M+B] columns, so this is a hot-path win.
    keep = (gt != _EMPTY) & ~kill
    rank = jnp.cumsum(keep.astype(jnp.int32), axis=-1) - 1
    # survivors beyond capacity (rank >= m) drop into the spill slot m
    slot = jnp.where(keep & (rank < m), rank, m)
    out = StoreCols(*rank_compact_many(
        [(gt, _EMPTY), (member, _EMPTY), (meta, empty_of(meta.dtype)),
         (payload, _EMPTY), (aux, 0), (flags, 0)], slot, m))
    kept = keep & (rank < m)
    n_inserted = jnp.sum(kept & (origin == 1), axis=-1).astype(jnp.int32)
    n_surviving_old = jnp.sum(kept & (origin == 0),
                              axis=-1).astype(jnp.int32)
    return InsertResult(store=out, n_inserted=n_inserted,
                        n_dropped=n_new_valid - n_inserted,
                        n_evicted=n_before - n_surviving_old)


def _prefer_merge(width: int) -> bool:
    """Pick the merge form of the ordered interleave for this width?

    Backend- and width-dependent, same pattern (and same measurements) as
    ops/bloom._auto_impl: TPU sorts are bitonic (O(w log² w), 7 operands)
    while its compare broadcasts fuse onto the VPU — merge wins at large
    widths; XLA:CPU sorts cheaply and MATERIALIZES the [N, B, M] compare
    tensors — sort wins there (measured: config #3 CPU run 204 s sort vs
    319 s merge, identical outputs).  Both forms are bit-identical
    (cross-form tests, incl. the end-to-end forced-merge run in
    tests/test_store.py that CPU CI executes above this width threshold).

    Keyed off ``jax.default_backend()``, not the operands' device — the
    repo pins one backend per process (cpuenv.py / conftest), the same
    single-backend assumption ops/bloom documents.
    """
    return width > 128 and jax.default_backend() == "tpu"


def _sort_ordered(store: StoreCols, masked: StoreCols):
    """SORT form of the merge step (small stores): one lexicographic sort
    over the concatenation, on keys (gt, member, position-in-concat).

    Position as the tie-break key does three jobs at once: store rows
    precede batch rows in the concat, so the existing entry leads any
    (gt, member) duplicate group (the UNIQUE rule's "existing wins");
    same-keyed BATCH records order by delivery position (first-seen wins
    — exactly the reference's keep-first-packet rule, which the oracle
    mirrors with its stable sort); and the key triple is globally unique,
    so the sort needs no stability and no further content keys — where
    the pre-v8 form paid 6 key passes over 7 operands, this pays 3 keys,
    with the non-key columns either riding as values (TPU, where
    cross-lane gathers serialize) or applied afterwards by row-local
    gather on the recovered position (CPU, where the gather is cheap and
    the sort's data movement is the bottleneck).  Both forms are
    bit-identical.
    """
    cat = StoreCols(*(jnp.concatenate([a, b], axis=-1)
                      for a, b in zip(store, masked)))
    m_w = store.gt.shape[-1]
    w = cat.gt.shape[-1]
    pos = jnp.broadcast_to(jnp.arange(w, dtype=jnp.uint32),
                           cat.gt.shape)
    if jax.default_backend() == "tpu":
        gt, member, spos, meta, payload, aux, flags = lax.sort(
            (cat.gt, cat.member, pos, cat.meta, cat.payload, cat.aux,
             cat.flags), dimension=-1, is_stable=False, num_keys=3)
    else:
        gt, member, spos = lax.sort(
            (cat.gt, cat.member, pos), dimension=-1, is_stable=False,
            num_keys=3)
        ix = spos.astype(jnp.int32)
        meta, payload, aux, flags = (
            jnp.take_along_axis(c, ix, axis=-1)
            for c in (cat.meta, cat.payload, cat.aux, cat.flags))
    origin = (spos >= jnp.uint32(m_w)).astype(jnp.uint32)
    return gt, member, origin, meta, payload, aux, flags


def _merge_ordered(store: StoreCols, masked: StoreCols):
    """MERGE form (large stores), bit-identical to :func:`_sort_ordered`.

    PRECONDITION (unlike the sort form): the store side must already be
    sorted by (gt, member) with EMPTY holes at the end — the round
    invariant every store_insert output satisfies.  A caller handing in an
    unsorted store corrupts silently; the forced-merge end-to-end test in
    tests/test_store.py runs multi-round insert chains through this path
    on CPU so a violated invariant cannot hide behind the TPU-only gate.
    Columns are 2-D [N, W] (rank_compact likewise) — lax.sort's
    arbitrary-leading-dims generality is not preserved here.

    The store side is already sorted — the round invariant — so only the
    [N, B] batch needs a sort; each side's output position is its own
    rank plus a compare-and-count against the other side ([N, B, M]
    reduces, the same shape class as the engine's in_store test).
    Replaces the O((M+B) log²(M+B)) 7-operand bitonic sort with O(M·B)
    fusable compares + two scatters — the store path's cost becomes
    linear in capacity.  Ties between store and batch resolve
    store-first, and ties WITHIN the batch by delivery position — both
    exactly what the sort form's position key encodes; the cross-form
    equality test and every oracle trace pin the identity.
    """
    bpos = jnp.broadcast_to(
        jnp.arange(masked.gt.shape[-1], dtype=jnp.uint32), masked.gt.shape)
    b_gt, b_member, _, b_meta, b_payload, b_aux, b_flags = lax.sort(
        (masked.gt, masked.member, bpos, masked.meta, masked.payload,
         masked.aux, masked.flags), dimension=-1, is_stable=False,
        num_keys=3)
    s_gt, s_member = store.gt, store.member
    # ONE [N, B, M] compare: store_key <= batch_key (equality counts:
    # batch sorts after).  Its complement is batch_key < store_key, so
    # both sides' counts come from the same tensor.
    s_le_b = ((s_gt[..., None, :] < b_gt[..., :, None])
              | ((s_gt[..., None, :] == b_gt[..., :, None])
                 & (s_member[..., None, :] <= b_member[..., :, None])))
    pos_b = (jnp.arange(b_gt.shape[-1])[None, :]
             + jnp.sum(s_le_b, axis=-1))                      # [N, B]
    pos_s = (jnp.arange(s_gt.shape[-1])[None, :]
             + jnp.sum(~s_le_b, axis=-2))                     # [N, M]
    n = s_gt.shape[0]
    width = s_gt.shape[-1] + b_gt.shape[-1]
    if n * width < 2 ** 31:
        # Flat scatter indices (same one-component layout as
        # rank_compact; same int32-overflow guard).
        row0 = jnp.arange(n, dtype=jnp.int32)[:, None] * width
        flat_s = (row0 + pos_s.astype(jnp.int32)).reshape(-1)
        flat_b = (row0 + pos_b.astype(jnp.int32)).reshape(-1)

        def interleave(s_col, b_col):
            out = jnp.zeros((n * width,), s_col.dtype)
            out = out.at[flat_s].set(s_col.reshape(-1), mode="drop")
            return (out.at[flat_b].set(b_col.reshape(-1), mode="drop")
                    .reshape(n, width))
        origin = (jnp.zeros((n * width,), s_gt.dtype)
                  .at[flat_b].set(1, mode="drop").reshape(n, width))
    else:
        rows = jnp.arange(n)[:, None]

        def interleave(s_col, b_col):
            out = jnp.zeros((n, width), s_col.dtype)
            out = out.at[rows, pos_s].set(s_col, mode="drop")
            return out.at[rows, pos_b].set(b_col, mode="drop")
        origin = (jnp.zeros((n, width), s_gt.dtype)
                  .at[rows, pos_b].set(1, mode="drop"))
    return (interleave(store.gt, b_gt),
            interleave(store.member, b_member),
            origin,
            interleave(store.meta, b_meta),
            interleave(store.payload, b_payload),
            interleave(store.aux, b_aux),
            interleave(store.flags, b_flags))


class StageResult(NamedTuple):
    staging: StoreCols
    landed: jnp.ndarray    # bool[N, B] arrivals that took a staging slot
    n_dropped: jnp.ndarray  # i32[N] arrivals lost to staging overflow


@contract(out=StageResult(staging=_STORE_NM,
                          landed=Spec("bool", ("N", "B")),
                          n_dropped=Spec("int32", ("N",))),
          staging=_STORE_NM, new=_BATCH_NB,
          new_mask=Spec("bool", ("N", "B")))
def store_stage(staging: StoreCols, new: StoreCols,
                new_mask: jnp.ndarray) -> StageResult:
    """Append masked arrivals to each peer's staging buffer, in delivery
    order, after the current valid prefix (dispersy_tpu/storediet.py).

    The byte-diet replacement for the every-round :func:`store_insert`:
    a bounded O(S + B) scatter instead of a full sorted-ring rewrite —
    the ring is only merged at compaction, where the staged records
    flow through ``store_insert`` unchanged (UNIQUE / LastSync /
    capacity semantics all apply there).  Overflow arrivals are dropped
    and counted, exactly like every bounded inbox in this repo (UDP
    backpressure; the Bloom pull re-offers them next epoch).

    Preserves the valid-prefix invariant: holes only ever follow the
    appended tail.  ``staging``: [N, S] columns; ``new``: [N, B];
    ``new_mask``: [N, B].  The batch's columns follow the staging
    dtypes (the ``store_insert`` narrowing rule).
    """
    s = staging.gt.shape[-1]
    n = staging.gt.shape[0]
    if (new.meta.dtype != staging.meta.dtype
            or new.flags.dtype != staging.flags.dtype
            or new.aux.dtype != staging.aux.dtype):
        new = new._replace(meta=new.meta.astype(staging.meta.dtype),
                           flags=new.flags.astype(staging.flags.dtype),
                           aux=new.aux.astype(staging.aux.dtype))
    cnt = count_valid(staging.gt)                           # [N]
    rank = jnp.cumsum(new_mask.astype(jnp.int32), axis=-1) - 1
    slot = cnt[:, None] + rank                              # [N, B]
    landed = new_mask & (slot < s)
    if n * s < 2 ** 31:
        # Flat one-component scatter indices (the rank_compact layout,
        # same int32-overflow guard); masked-out/overflow entries point
        # past the buffer and mode="drop" discards them.
        row0 = jnp.arange(n, dtype=jnp.int32)[:, None] * s
        flat = jnp.where(landed, row0 + slot,
                         jnp.int32(n * s)).reshape(-1)

        def put(cur, val):
            return (cur.reshape(-1).at[flat].set(val.reshape(-1),
                                                 mode="drop")
                    .reshape(n, s))
    else:
        # 2-D (row, slot) index form past the int32 flat-index range.
        rows = jnp.arange(n)[:, None]
        tgt = jnp.where(landed, slot, s)   # s = out-of-bounds -> dropped

        def put(cur, val):
            return cur.at[rows, tgt].set(val, mode="drop")
    out = StoreCols(gt=put(staging.gt, new.gt),
                    member=put(staging.member, new.member),
                    meta=put(staging.meta, new.meta),
                    payload=put(staging.payload, new.payload),
                    aux=put(staging.aux, new.aux),
                    flags=put(staging.flags, new.flags))
    n_dropped = jnp.sum(new_mask & ~landed, axis=-1).astype(jnp.int32)
    return StageResult(staging=out, landed=landed, n_dropped=n_dropped)


class RemoveResult(NamedTuple):
    store: StoreCols
    n_removed: jnp.ndarray  # i32[N] records deleted


@contract(out=RemoveResult(store=_STORE_NM,
                           n_removed=Spec("int32", ("N",))),
          store=_STORE_NM, kill=Spec("bool", ("N", "M")))
def store_remove(store: StoreCols, kill: jnp.ndarray) -> RemoveResult:
    """Delete masked records; survivors compact left, holes to the end.

    The retro-reject half of the permission re-walk (reference: timeline.py
    lazy re-validation — a message whose proof chain stops checking out is
    dropped from the database; engine._retro_pass).  Survivors keep their
    sorted order, so a rank-scatter compaction suffices — no re-sort.
    ``kill``: bool[N, M] over the store slots; dead slots in ``kill`` are
    ignored.
    """
    m = store.gt.shape[-1]
    keep = store.valid & ~kill
    rank = jnp.cumsum(keep.astype(jnp.int32), axis=-1) - 1
    slot = jnp.where(keep, rank, m)
    out = StoreCols(*rank_compact_many(
        [(store.gt, _EMPTY), (store.member, _EMPTY),
         (store.meta, empty_of(store.meta.dtype)),
         (store.payload, _EMPTY), (store.aux, 0), (store.flags, 0)],
        slot, m))
    n_removed = jnp.sum((store.valid & kill).astype(jnp.int32), axis=-1)
    return RemoveResult(store=out, n_removed=n_removed)


class SyncSlice(NamedTuple):
    """The sync range advertised in an introduction request.

    Mirrors the reference's IntroductionRequestPayload sync tuple
    (reference: payload.py — (time_low, time_high, modulo, offset, bloom)).
    time_high == 0 means "no upper bound" as in the reference.
    """
    time_low: jnp.ndarray   # u32[N]
    time_high: jnp.ndarray  # u32[N]
    modulo: jnp.ndarray     # u32[N]
    offset: jnp.ndarray     # u32[N]


_SLICE_SPEC = SyncSlice(time_low=Spec("uint32", ("N",)),
                        time_high=Spec("uint32", ("N",)),
                        modulo=Spec("uint32", ("N",)),
                        offset=Spec("uint32", ("N",)))


@contract(out=Spec("bool", ("N", "M")), gt=Spec("uint32", ("N", "M")),
          s=_SLICE_SPEC)
def slice_mask(gt: jnp.ndarray, s: SyncSlice) -> jnp.ndarray:
    """[N, M] membership of store entries in an advertised slice."""
    valid = gt != _EMPTY
    lo = gt >= s.time_low[..., None]
    hi = jnp.where((s.time_high == 0)[..., None], True,
                   gt <= s.time_high[..., None])
    mod = (gt % jnp.maximum(s.modulo, 1)[..., None]) == s.offset[..., None]
    return valid & lo & hi & mod


@contract(out=_SLICE_SPEC, gt=Spec("uint32", ("N", "M")),
          capacity=lambda d: d["B"])
def claim_slice_largest(gt: jnp.ndarray, capacity: int) -> SyncSlice:
    """"Largest" bloom-claim strategy: the most recent ≤capacity entries.

    Reference: community.py ``_dispersy_claim_sync_bloom_filter_largest`` —
    prefer the newest window of the store, open-ended above (time_high=0)
    so freshly created messages are covered by the advertised range.
    time_low aligns to a global_time boundary: every entry with
    gt >= time_low is inside the slice (the reference likewise never splits
    one global_time across a slice edge).
    """
    n_valid = count_valid(gt)                           # [N]
    start = jnp.maximum(n_valid - capacity, 0)          # [N]
    boundary = jnp.take_along_axis(gt, start[..., None], axis=-1)[..., 0]
    time_low = jnp.where(start == 0, 1, boundary).astype(jnp.uint32)
    return SyncSlice(time_low=time_low,
                     time_high=jnp.zeros_like(time_low),
                     modulo=jnp.ones_like(time_low),
                     offset=jnp.zeros_like(time_low))


@contract(out=_SLICE_SPEC, gt=Spec("uint32", ("N", "M")),
          capacity=lambda d: d["B"], round_index=Spec("uint32", ()))
def claim_slice_modulo(gt: jnp.ndarray, capacity: int,
                       round_index: jnp.ndarray) -> SyncSlice:
    """"Modulo" strategy: stripe the whole store across successive rounds.

    Reference: community.py ``_dispersy_claim_sync_bloom_filter_modulo`` —
    when the store exceeds one bloom's capacity, advertise the stripe
    {gt : gt % modulo == offset} with offset cycling per claim, so every
    entry is eventually covered.
    """
    n_valid = count_valid(gt)
    modulo = jnp.maximum((n_valid + capacity - 1) // capacity, 1)
    modulo = modulo.astype(jnp.uint32)
    offset = (round_index.astype(jnp.uint32) % modulo)
    ones = jnp.ones_like(modulo)
    return SyncSlice(time_low=ones, time_high=jnp.zeros_like(modulo),
                     modulo=modulo, offset=offset)
