"""Message-store kernels: the SQLite ``sync`` table as a sorted ring.

The reference persists every sync-distributed message in one SQLite table
(reference: dispersydatabase.py — ``sync(community, member, global_time,
meta_message, packet, undone)`` with UNIQUE(community, member, global_time))
and serves Bloom-sync slices with ``SELECT ... WHERE global_time BETWEEN ?
AND ?`` (reference: community.py ``dispersy_claim_sync_bloom_filter`` and the
``on_introduction_request`` sync responder).

TPU-native recast: each peer owns ``msg_capacity`` record slots, four uint32
columns (global_time, member, meta, payload) + flags, kept sorted
lexicographically by (global_time, member, meta, payload) with ``EMPTY_U32``
holes at the end.  Sorted order gives us:

- O(log M) slice selection via searchsorted (the BETWEEN query),
- dedup on UNIQUE(member, global_time) as an adjacent-equal test after a
  merge sort (the INSERT OR IGNORE),
- deterministic iteration order for bloom construction.

All functions are batched over the leading peer axis and shape-static, so
they fuse into the round step under jit and shard over the peer axis.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dispersy_tpu.config import EMPTY_U32

_EMPTY = np.uint32(EMPTY_U32)


class StoreCols(NamedTuple):
    """One peer-store (or record batch): uint32 columns, same shape.

    ``aux`` is the record's second payload word, overloaded per meta
    (config.py reserved-meta table): permission bitmask for authorize/
    revoke, target global_time for undo, sequence number for
    sequence-enabled metas.  ``flags`` is receiver-local derived state
    (bit 0 = undone) and never travels on the wire.
    """
    gt: jnp.ndarray
    member: jnp.ndarray
    meta: jnp.ndarray
    payload: jnp.ndarray
    aux: jnp.ndarray
    flags: jnp.ndarray

    @property
    def valid(self) -> jnp.ndarray:
        return self.gt != _EMPTY


def empty_records(shape) -> StoreCols:
    e = jnp.full(shape, _EMPTY, jnp.uint32)
    return StoreCols(gt=e, member=e, meta=e, payload=e,
                     aux=jnp.zeros(shape, jnp.uint32),
                     flags=jnp.zeros(shape, jnp.uint32))


def count_valid(gt: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum((gt != _EMPTY).astype(jnp.int32), axis=-1)


def rank_compact(col: jnp.ndarray, slot: jnp.ndarray, width: int,
                 fill) -> jnp.ndarray:
    """Rank-scatter compaction: keep entries whose ``slot`` < ``width``.

    ``col``/``slot`` are [N, W]-shaped; entries scatter to ``slot`` in a
    fresh ``fill``-initialized row, with ``slot == width`` as the shared
    spill column that is trimmed off.  Slots below ``width`` must be unique
    per row (ranks from a cumsum are).  This is the one definition of the
    idiom used by the store merge, the sync-responder outbox, the forward
    buffer, and the delayed-message pen — linear, where a second sort
    would be O(W log W).
    """
    n = col.shape[0]
    rows = jnp.arange(n)[:, None]
    return (jnp.full((n, width + 1), fill, col.dtype)
            .at[rows, slot].set(col)[..., :width])


class InsertResult(NamedTuple):
    store: StoreCols
    n_inserted: jnp.ndarray  # i32[N] new records now in the store
    n_dropped: jnp.ndarray   # i32[N] new records lost (dup or overflow)
    n_evicted: jnp.ndarray   # i32[N] existing records lost to overflow


def store_insert(store: StoreCols, new: StoreCols,
                 new_mask: jnp.ndarray,
                 history: tuple = ()) -> InsertResult:
    """Merge a batch of records into each peer's sorted store.

    Semantics mirror the reference's store pipeline
    (reference: dispersy.py ``store_update_forward`` -> INSERT into sync):

    - UNIQUE(member, global_time): among records sharing (gt, member) the
      *existing* store entry wins (a second message by the same member at the
      same global_time is dropped — the reference treats that as a conflict
      and keeps the first-seen packet).
    - ``history``: per-user-meta keep-last-k (reference: distribution.py
      ``LastSyncDistribution(history_size=k)`` + the check/clean-up in
      community.py that deletes older rows per (member, meta)): when meta i
      has history[i] = k > 0, only the k highest-global-time records per
      (member, meta) survive the merge — an arriving older record is
      dropped, an arriving newer one evicts the oldest kept.  Empty tuple
      (or all zeros) = FullSync for every meta.
    - capacity overflow keeps the M records that sort first (lowest
      global_time) — modeling a full store the way UDP overflow drops
      packets: counted, never raised.  New records that don't fit are
      reported in n_dropped; *existing* records bumped out by a
      lower-global_time arrival are reported in n_evicted.

    ``store``: [N, M] columns; ``new``: [N, B] columns; ``new_mask``: [N, B].
    """
    m = store.gt.shape[-1]
    n_before = count_valid(store.gt)
    masked = StoreCols(
        gt=jnp.where(new_mask, new.gt, _EMPTY),
        member=jnp.where(new_mask, new.member, _EMPTY),
        meta=jnp.where(new_mask, new.meta, _EMPTY),
        payload=jnp.where(new_mask, new.payload, _EMPTY),
        aux=jnp.where(new_mask, new.aux, 0),
        flags=jnp.where(new_mask, new.flags, 0),
    )
    # Also guard against EMPTY sentinel gt arriving as a "new" record.
    n_new_valid = count_valid(masked.gt)

    if _prefer_merge(store.gt.shape[-1] + masked.gt.shape[-1]):
        gt, member, origin, meta, payload, aux, flags = \
            _merge_ordered(store, masked)
    else:
        gt, member, origin, meta, payload, aux, flags = \
            _sort_ordered(store, masked)

    dup = jnp.zeros_like(gt, dtype=bool).at[..., 1:].set(
        (gt[..., 1:] == gt[..., :-1]) & (member[..., 1:] == member[..., :-1])
        & (gt[..., 1:] != _EMPTY))
    kill = dup
    if any(k > 0 for k in history):
        # LastSync keep-last-k: evict every record with >= k higher-gt
        # survivors in its (member, meta) group.  gts within a group are
        # unique (UNIQUE(member, gt) holds after the dup kill), so the
        # count is unambiguous.  [.., W, W] pairwise compare, W = M + B —
        # only compiled in for communities that declare a LastSync meta.
        nm = len(history)
        k_arr = jnp.asarray(history, jnp.int32)
        meta_c = jnp.minimum(meta, jnp.uint32(nm - 1)).astype(jnp.int32)
        k_meta = jnp.where(meta < nm, jnp.take(k_arr, meta_c, axis=0), 0)
        live = (gt != _EMPTY) & ~dup
        same = (live[..., :, None] & live[..., None, :]
                & (member[..., :, None] == member[..., None, :])
                & (meta[..., :, None] == meta[..., None, :]))
        newer = jnp.sum(same & (gt[..., None, :] > gt[..., :, None]),
                        axis=-1)
        kill = dup | ((k_meta > 0) & live & (newer >= k_meta))
    # Compact by scatter instead of a second sort: survivors are already
    # in sorted order (UNIQUE(member, gt) holds after the dup kill, so
    # (gt, member) alone determines the order), and a rank-scatter is
    # linear where the sort is O(W log W) — store_insert runs once per
    # round over [N, M+B] columns, so this is a hot-path win.
    keep = (gt != _EMPTY) & ~kill
    rank = jnp.cumsum(keep.astype(jnp.int32), axis=-1) - 1
    # survivors beyond capacity (rank >= m) drop into the spill slot m
    slot = jnp.where(keep & (rank < m), rank, m)
    out = StoreCols(gt=rank_compact(gt, slot, m, _EMPTY),
                    member=rank_compact(member, slot, m, _EMPTY),
                    meta=rank_compact(meta, slot, m, _EMPTY),
                    payload=rank_compact(payload, slot, m, _EMPTY),
                    aux=rank_compact(aux, slot, m, 0),
                    flags=rank_compact(flags, slot, m, 0))
    kept = keep & (rank < m)
    n_inserted = jnp.sum(kept & (origin == 1), axis=-1).astype(jnp.int32)
    n_surviving_old = jnp.sum(kept & (origin == 0),
                              axis=-1).astype(jnp.int32)
    return InsertResult(store=out, n_inserted=n_inserted,
                        n_dropped=n_new_valid - n_inserted,
                        n_evicted=n_before - n_surviving_old)


def _prefer_merge(width: int) -> bool:
    """Pick the merge form of the ordered interleave for this width?

    Backend- and width-dependent, same pattern (and same measurements) as
    ops/bloom._auto_impl: TPU sorts are bitonic (O(w log² w), 7 operands)
    while its compare broadcasts fuse onto the VPU — merge wins at large
    widths; XLA:CPU sorts cheaply and MATERIALIZES the [N, B, M] compare
    tensors — sort wins there (measured: config #3 CPU run 204 s sort vs
    319 s merge, identical outputs).  Both forms are bit-identical
    (cross-form tests, incl. the end-to-end forced-merge run in
    tests/test_store.py that CPU CI executes above this width threshold).

    Keyed off ``jax.default_backend()``, not the operands' device — the
    repo pins one backend per process (cpuenv.py / conftest), the same
    single-backend assumption ops/bloom documents.
    """
    return width > 128 and jax.default_backend() == "tpu"


def _sort_ordered(store: StoreCols, masked: StoreCols):
    """SORT form of the merge step (small stores): one lexicographic sort
    over the concatenation.  Origin as 3rd key makes the existing entry
    the first of any (gt, member) duplicate group regardless of its
    (meta, payload) relative to the duplicate's.  aux is a key too:
    lax.sort is not stable, so two same-keyed records differing only in
    aux must still order deterministically for the oracle to replay."""
    cat = StoreCols(*(jnp.concatenate([a, b], axis=-1)
                      for a, b in zip(store, masked)))
    origin = jnp.concatenate(
        [jnp.zeros_like(store.gt), jnp.ones_like(masked.gt)], axis=-1)
    return lax.sort(
        (cat.gt, cat.member, origin, cat.meta, cat.payload, cat.aux,
         cat.flags),
        dimension=-1, num_keys=6)


def _merge_ordered(store: StoreCols, masked: StoreCols):
    """MERGE form (large stores), bit-identical to :func:`_sort_ordered`.

    PRECONDITION (unlike the sort form): the store side must already be
    sorted by (gt, member) with EMPTY holes at the end — the round
    invariant every store_insert output satisfies.  A caller handing in an
    unsorted store corrupts silently; the forced-merge end-to-end test in
    tests/test_store.py runs multi-round insert chains through this path
    on CPU so a violated invariant cannot hide behind the TPU-only gate.
    Columns are 2-D [N, W] (rank_compact likewise) — lax.sort's
    arbitrary-leading-dims generality is not preserved here.

    The store side is already sorted — the round invariant — so only the
    [N, B] batch needs a sort; each side's output position is its own
    rank plus a compare-and-count against the other side ([N, B, M]
    reduces, the same shape class as the engine's in_store test).
    Replaces the O((M+B) log²(M+B)) 7-operand bitonic sort with O(M·B)
    fusable compares + two scatters — the store path's cost becomes
    linear in capacity.  Ties between store and batch resolve
    store-first, exactly what the sort form's origin key encodes; the
    cross-form equality test and every oracle trace pin the identity.
    """
    b_gt, b_member, b_meta, b_payload, b_aux, b_flags = lax.sort(
        (masked.gt, masked.member, masked.meta, masked.payload,
         masked.aux, masked.flags), dimension=-1, num_keys=5)
    s_gt, s_member = store.gt, store.member
    # ONE [N, B, M] compare: store_key <= batch_key (equality counts:
    # batch sorts after).  Its complement is batch_key < store_key, so
    # both sides' counts come from the same tensor.
    s_le_b = ((s_gt[..., None, :] < b_gt[..., :, None])
              | ((s_gt[..., None, :] == b_gt[..., :, None])
                 & (s_member[..., None, :] <= b_member[..., :, None])))
    pos_b = (jnp.arange(b_gt.shape[-1])[None, :]
             + jnp.sum(s_le_b, axis=-1))                      # [N, B]
    pos_s = (jnp.arange(s_gt.shape[-1])[None, :]
             + jnp.sum(~s_le_b, axis=-2))                     # [N, M]
    rows = jnp.arange(s_gt.shape[0])[:, None]
    width = s_gt.shape[-1] + b_gt.shape[-1]

    def interleave(s_col, b_col):
        out = jnp.zeros((s_gt.shape[0], width), s_col.dtype)
        out = out.at[rows, pos_s].set(s_col)
        return out.at[rows, pos_b].set(b_col)
    origin = jnp.zeros((s_gt.shape[0], width), s_gt.dtype
                       ).at[rows, pos_b].set(1)
    return (interleave(store.gt, b_gt),
            interleave(store.member, b_member),
            origin,
            interleave(store.meta, b_meta),
            interleave(store.payload, b_payload),
            interleave(store.aux, b_aux),
            interleave(store.flags, b_flags))


class RemoveResult(NamedTuple):
    store: StoreCols
    n_removed: jnp.ndarray  # i32[N] records deleted


def store_remove(store: StoreCols, kill: jnp.ndarray) -> RemoveResult:
    """Delete masked records; survivors compact left, holes to the end.

    The retro-reject half of the permission re-walk (reference: timeline.py
    lazy re-validation — a message whose proof chain stops checking out is
    dropped from the database; engine._retro_pass).  Survivors keep their
    sorted order, so a rank-scatter compaction suffices — no re-sort.
    ``kill``: bool[N, M] over the store slots; dead slots in ``kill`` are
    ignored.
    """
    m = store.gt.shape[-1]
    keep = store.valid & ~kill
    rank = jnp.cumsum(keep.astype(jnp.int32), axis=-1) - 1
    slot = jnp.where(keep, rank, m)
    out = StoreCols(gt=rank_compact(store.gt, slot, m, _EMPTY),
                    member=rank_compact(store.member, slot, m, _EMPTY),
                    meta=rank_compact(store.meta, slot, m, _EMPTY),
                    payload=rank_compact(store.payload, slot, m, _EMPTY),
                    aux=rank_compact(store.aux, slot, m, 0),
                    flags=rank_compact(store.flags, slot, m, 0))
    n_removed = jnp.sum((store.valid & kill).astype(jnp.int32), axis=-1)
    return RemoveResult(store=out, n_removed=n_removed)


class SyncSlice(NamedTuple):
    """The sync range advertised in an introduction request.

    Mirrors the reference's IntroductionRequestPayload sync tuple
    (reference: payload.py — (time_low, time_high, modulo, offset, bloom)).
    time_high == 0 means "no upper bound" as in the reference.
    """
    time_low: jnp.ndarray   # u32[N]
    time_high: jnp.ndarray  # u32[N]
    modulo: jnp.ndarray     # u32[N]
    offset: jnp.ndarray     # u32[N]


def slice_mask(gt: jnp.ndarray, s: SyncSlice) -> jnp.ndarray:
    """[N, M] membership of store entries in an advertised slice."""
    valid = gt != _EMPTY
    lo = gt >= s.time_low[..., None]
    hi = jnp.where((s.time_high == 0)[..., None], True,
                   gt <= s.time_high[..., None])
    mod = (gt % jnp.maximum(s.modulo, 1)[..., None]) == s.offset[..., None]
    return valid & lo & hi & mod


def claim_slice_largest(gt: jnp.ndarray, capacity: int) -> SyncSlice:
    """"Largest" bloom-claim strategy: the most recent ≤capacity entries.

    Reference: community.py ``_dispersy_claim_sync_bloom_filter_largest`` —
    prefer the newest window of the store, open-ended above (time_high=0)
    so freshly created messages are covered by the advertised range.
    time_low aligns to a global_time boundary: every entry with
    gt >= time_low is inside the slice (the reference likewise never splits
    one global_time across a slice edge).
    """
    n_valid = count_valid(gt)                           # [N]
    start = jnp.maximum(n_valid - capacity, 0)          # [N]
    boundary = jnp.take_along_axis(gt, start[..., None], axis=-1)[..., 0]
    time_low = jnp.where(start == 0, 1, boundary).astype(jnp.uint32)
    return SyncSlice(time_low=time_low,
                     time_high=jnp.zeros_like(time_low),
                     modulo=jnp.ones_like(time_low),
                     offset=jnp.zeros_like(time_low))


def claim_slice_modulo(gt: jnp.ndarray, capacity: int,
                       round_index: jnp.ndarray) -> SyncSlice:
    """"Modulo" strategy: stripe the whole store across successive rounds.

    Reference: community.py ``_dispersy_claim_sync_bloom_filter_modulo`` —
    when the store exceeds one bloom's capacity, advertise the stripe
    {gt : gt % modulo == offset} with offset cycling per claim, so every
    entry is eventually covered.
    """
    n_valid = count_valid(gt)
    modulo = jnp.maximum((n_valid + capacity - 1) // capacity, 1)
    modulo = modulo.astype(jnp.uint32)
    offset = (round_index.astype(jnp.uint32) % modulo)
    ones = jnp.ones_like(modulo)
    return SyncSlice(time_low=ones, time_high=jnp.zeros_like(modulo),
                     modulo=modulo, offset=offset)
