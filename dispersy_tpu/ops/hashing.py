"""Deterministic uint32 hashing shared by the TPU kernels and the CPU oracle.

The reference Bloom filter derives its k hash functions from sha1/md5 digests
of the packet bytes (reference: bloomfilter.py — double hashing over a
cryptographic digest).  The simulation has no packet bytes — a message is a
packed record of uint32 fields — so we use a murmur3-style finalizer over the
record fields instead.  What matters for fidelity is the *distribution*
(uniform, independent per seed), not the exact digest family; conformance is
checked by false-positive-rate tests against the pure-Python oracle
(:mod:`dispersy_tpu.oracle.bloom`), which implements the identical mixing so
TPU and oracle agree bit-for-bit.

All functions operate on uint32 and wrap mod 2^32.  They are written so the
same expressions run under jax.numpy (wrapping uint32 arrays) and are
mirrored with explicit ``& 0xFFFFFFFF`` masks in the oracle.
"""

from __future__ import annotations

import jax.numpy as jnp

from dispersy_tpu.ops.contracts import Spec, contract

GOLDEN = 0x9E3779B9
_C1 = 0x85EBCA6B
_C2 = 0xC2B2AE35

# Domain-separation seeds for the two Bloom double-hashing streams.
BLOOM_SEED_1 = 0x8F1BBCDC
BLOOM_SEED_2 = 0xCA62C1D6
# Seed for mixing the per-filter salt (the reference's BloomFilter
# *prefix*: each claimed filter carries a fresh prefix byte so a false
# positive is re-randomized per claim instead of being permanent —
# reference: bloomfilter.py constructor prefix + community.py claim).
BLOOM_SALT_SEED = 0x6ED9EBA1


@contract(out=Spec("uint32", ("B",)), x=Spec("uint32", ("B",)))
def fmix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 32-bit finalizer: a bijective avalanche mix on uint32."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(_C1)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(_C2)
    x = x ^ (x >> 16)
    return x


@contract(out=Spec("uint32", ("B",)), x=Spec("uint32", ("B",)), seed=BLOOM_SEED_1)
def hash_u32(x: jnp.ndarray, seed: int) -> jnp.ndarray:
    """Seeded hash of a uint32 value."""
    return fmix32(x.astype(jnp.uint32) ^ fmix32(jnp.uint32(seed)))


@contract(out=Spec("uint32", ("B",)),
          h=Spec("uint32", ("B",)), v=Spec("uint32", ("B",)))
def combine(h: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Fold value ``v`` into running hash ``h`` (boost::hash_combine-style)."""
    h = h.astype(jnp.uint32)
    return h ^ (fmix32(v) + jnp.uint32(GOLDEN) + (h << 6) + (h >> 2))


@contract(out=Spec("uint32", ("B",)),
          member=Spec("uint32", ("B",)), global_time=Spec("uint32", ("B",)),
          meta=Spec("uint8", ("B",)), payload=Spec("uint32", ("B",)))
def record_hash(member: jnp.ndarray, global_time: jnp.ndarray,
                meta: jnp.ndarray, payload: jnp.ndarray) -> jnp.ndarray:
    """Hash of one sync record — the simulation analogue of the packet sha1.

    The reference identifies a packet by its full binary (and dedups the sync
    table on UNIQUE(community, member, global_time)); here a record is the
    4-tuple (member, global_time, meta, payload) and this hash is its identity
    for Bloom-filter membership.
    """
    h = fmix32(member.astype(jnp.uint32))
    h = combine(h, global_time.astype(jnp.uint32))
    h = combine(h, meta.astype(jnp.uint32))
    h = combine(h, payload.astype(jnp.uint32))
    return h
