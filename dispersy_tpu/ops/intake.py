"""Intake-check kernels: batch-vs-store membership, conflict, dup tests.

The receive pipeline (reference: dispersy.py ``_on_batch_cache`` — the
check step before ``store_update_forward``) asks, for every arriving
record, questions of the receiving peer's own store: *is this (member,
global_time) already stored?*  *does a stored record conflict with it?*
*did an earlier record in this same batch carry the same identity?* — plus
the Timeline's DynamicResolution policy replay over stored flip records
(reference: timeline.py ``Timeline.get_resolution_policy``) and undo
bookkeeping (community.py ``on_undo`` marking sync rows ``undone``).

Every one of these is a per-(batch-entry) reduction over the [N, M] store,
and the natural XLA form is a broadcast compare over [N, B, M].  Whether
that product shape ever *materializes* is backend-dependent — the same
story as ops/bloom.py and ops/store.py:

- **TPU**: the compare fuses into the reduce on the VPU; the product
  tensor never exists.  This is the measured-at-1M-peers bench path.
- **XLA:CPU**: fusion does NOT reliably happen; the [N, B, M] bool tensor
  allocates (the 199.9 GB Bloom incident, BENCH.md r2).  At config #3
  spec shape (N=100k, M=1152, B≈272) one such tensor is ~30 GB and the
  intake needs several live at once.

So each check has two bit-identical forms, picked per backend and size
(:func:`_auto_impl`): ``"broadcast"`` as above, and ``"chunked"`` — a
``lax.fori_loop`` over the batch axis computing one [N, M] compare-reduce
per iteration, bounding live memory at O(N·M) regardless of B.  Reductions
are order-independent (any/max), so the two forms are exactly equal;
tests/test_intake.py pins it, and the engine-level forced-form test pins
it through a full step.

Batch-ORDER note (the ingress-protection plane, OVERLOAD.md): the push
segment of the intake batch arrives in the delivery kernel's slot order,
which under ``overload.priority_admission`` is *(admission class, edge
position)* rather than pure edge position — so ``dup_earlier``'s
first-seen-wins and the sequence-chain scan see control-class records
ahead of bulk gossip whenever the inbox overflowed.  Every op here is
order-agnostic in its contract (the batch order is an input, not an
assumption), but oracle mirrors must build the push segment in the same
admitted order.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dispersy_tpu.config import (EMPTY_U32, META_DYNAMIC, META_IDENTITY,
                                 META_UNDO_OTHER, META_UNDO_OWN)
from dispersy_tpu.ops.contracts import Spec, contract
from dispersy_tpu.ops.store import stc_spec

# Canonical [N, M] receiver-store spec shared by every intake contract —
# store.py's one StoreCols spec definition, so a column narrowing there
# (the byte-diet dtypes R3 exists to defend) propagates here by
# construction.
_STC = stc_spec("N", "M")
_U32_NB = Spec("uint32", ("N", "B"))
_BOOL_NB = Spec("bool", ("N", "B"))

# Live-memory bound for the broadcast form's product tensor, in elements.
# 2**28 bools = 256 MB — comfortably under this host's RAM even with
# several product tensors live, while keeping every test-size shape on the
# (better-fusing, fewer-dispatch) broadcast path.
_BROADCAST_ELEM_LIMIT = 1 << 28


def _auto_impl(impl: str | None, product_elems: int) -> str:
    """``"broadcast"`` or ``"chunked"`` (same selection story as
    ops/bloom._auto_impl: one backend per process, trace-time static)."""
    if impl is not None:
        return impl
    if jax.default_backend() == "tpu":
        return "broadcast"
    return "chunked" if product_elems > _BROADCAST_ELEM_LIMIT else "broadcast"


@contract(out=_BOOL_NB, stc=_STC, member=_U32_NB, gt=_U32_NB, impl=None)
def in_store(stc, member: jnp.ndarray, gt: jnp.ndarray,
             impl: str | None = None) -> jnp.ndarray:
    """bool[N, B]: is (member, gt) already a stored row?  (The UNIQUE
    (member, global_time) identity — reference: the sync table's UNIQUE
    constraint; an arriving duplicate is not fresh.)"""
    n, b = member.shape
    m = stc.gt.shape[-1]
    if _auto_impl(impl, n * b * m) == "broadcast":
        return jnp.any(
            (stc.gt[:, None, :] == gt[:, :, None])
            & (stc.member[:, None, :] == member[:, :, None]), axis=-1)

    def body(j, out):
        g = lax.dynamic_index_in_dim(gt, j, 1)          # [N, 1]
        mb = lax.dynamic_index_in_dim(member, j, 1)
        hit = jnp.any((stc.gt == g) & (stc.member == mb), axis=-1)
        return lax.dynamic_update_index_in_dim(out, hit, j, 1)

    return lax.fori_loop(0, b, body, jnp.zeros((n, b), bool))


@contract(out=_BOOL_NB, stc=_STC, member=_U32_NB, gt=_U32_NB,
          meta=Spec("uint8", ("N", "B")), payload=_U32_NB, aux=_U32_NB,
          impl=None)
def conflict(stc, member: jnp.ndarray, gt: jnp.ndarray, meta: jnp.ndarray,
             payload: jnp.ndarray, aux: jnp.ndarray,
             impl: str | None = None) -> jnp.ndarray:
    """bool[N, B]: does a stored row share (member, gt) but differ in
    content?  (Double-sign conviction evidence — reference: dispersy.py
    malicious-member bookkeeping / dispersy-malicious-proof.)"""
    n, b = member.shape
    m = stc.gt.shape[-1]
    if _auto_impl(impl, n * b * m) == "broadcast":
        same_mg = ((stc.member[:, None, :] == member[:, :, None])
                   & (stc.gt[:, None, :] == gt[:, :, None])
                   & (stc.gt[:, None, :] != jnp.uint32(EMPTY_U32)))
        differs = ((stc.meta[:, None, :] != meta[:, :, None])
                   | (stc.payload[:, None, :] != payload[:, :, None])
                   | (stc.aux[:, None, :] != aux[:, :, None]))
        return jnp.any(same_mg & differs, axis=-1)

    def body(j, out):
        mb = lax.dynamic_index_in_dim(member, j, 1)     # [N, 1]
        g = lax.dynamic_index_in_dim(gt, j, 1)
        mt = lax.dynamic_index_in_dim(meta, j, 1)
        pl = lax.dynamic_index_in_dim(payload, j, 1)
        ax = lax.dynamic_index_in_dim(aux, j, 1)
        same = ((stc.member == mb) & (stc.gt == g)
                & (stc.gt != jnp.uint32(EMPTY_U32)))
        diff = (stc.meta != mt) | (stc.payload != pl) | (stc.aux != ax)
        return lax.dynamic_update_index_in_dim(
            out, jnp.any(same & diff, axis=-1), j, 1)

    return lax.fori_loop(0, b, body, jnp.zeros((n, b), bool))


@contract(out=_BOOL_NB, member=_U32_NB, gt=_U32_NB, ok=_BOOL_NB, impl=None)
def dup_earlier(member: jnp.ndarray, gt: jnp.ndarray, ok: jnp.ndarray,
                impl: str | None = None) -> jnp.ndarray:
    """bool[N, B]: does an EARLIER valid entry of this batch carry the same
    (member, gt)?  (In-batch dedup: the reference's batch handler keeps
    the first of identical-identity messages in one batch window.)"""
    n, b = member.shape
    if _auto_impl(impl, n * b * b) == "broadcast":
        earlier = jnp.arange(b)[None, :] < jnp.arange(b)[:, None]  # [B, B]
        return jnp.any(
            (gt[:, :, None] == gt[:, None, :])
            & (member[:, :, None] == member[:, None, :])
            & ok[:, None, :] & earlier[None, :, :], axis=-1)

    col = jnp.arange(b)

    def body(j, out):
        g = lax.dynamic_index_in_dim(gt, j, 1)          # [N, 1]
        mb = lax.dynamic_index_in_dim(member, j, 1)
        hit = jnp.any((gt == g) & (member == mb) & ok
                      & (col < j)[None, :], axis=-1)
        return lax.dynamic_update_index_in_dim(out, hit, j, 1)

    return lax.fori_loop(0, b, body, jnp.zeros((n, b), bool))


@contract(out=_U32_NB, stc=_STC, q_meta=_U32_NB, q_gt=_U32_NB, impl=None)
def flip_best(stc, q_meta: jnp.ndarray, q_gt: jnp.ndarray,
              impl: str | None = None) -> jnp.ndarray:
    """u32[N, Q]: per (meta, gt) query, the max ``gt*2 | policy`` key over
    stored dispersy-dynamic-settings flips at or below the query gt — the
    DynamicResolution replay (0 = no flip applies; reference: timeline.py
    ``Timeline.get_resolution_policy`` walking the stored flip chain).
    One definition serves the author gate, the countersigner check, and
    the intake check; the oracle mirrors it in ``_linear_at``.  The
    store-side replay IS the batch-side one evaluated over store rows —
    one kernel, two views."""
    return flip_best_batch(
        stc.meta == jnp.uint32(META_DYNAMIC), stc.payload, stc.gt,
        stc.aux, q_meta, q_gt, impl=impl)


@contract(out=_U32_NB, flip_ok=Spec("bool", ("N", "M")),
          payload=Spec("uint32", ("N", "M")), gt=Spec("uint32", ("N", "M")),
          aux=Spec("uint32", ("N", "M")), q_meta=_U32_NB, q_gt=_U32_NB,
          impl=None)
def flip_best_batch(flip_ok: jnp.ndarray, payload: jnp.ndarray,
                    gt: jnp.ndarray, aux: jnp.ndarray,
                    q_meta: jnp.ndarray, q_gt: jnp.ndarray,
                    impl: str | None = None) -> jnp.ndarray:
    """u32[N, B]: :func:`flip_best` over THIS BATCH's fresh accepted
    dynamic-settings flips instead of the store — the same-round half of
    the DynamicResolution replay (a flip and a record it governs arriving
    together must still interact; engine intake pairs this max with the
    store-side one).  The reduce axis is ``payload``'s last dim — B for
    the engine's batch-vs-batch call, M when :func:`flip_best` delegates
    its store-side replay here — so the product estimate must use it,
    not the query count."""
    n, b = q_meta.shape
    m = payload.shape[-1]
    if _auto_impl(impl, n * b * m) == "broadcast":
        hit = (flip_ok[:, None, :]
               & (payload[:, None, :] == q_meta[:, :, None])
               & (gt[:, None, :] <= q_gt[:, :, None]))
        return jnp.max(
            jnp.where(hit, gt[:, None, :] * 2 + (aux[:, None, :] & 1), 0),
            axis=-1)

    key = gt * 2 + (aux & 1)

    def body(j, out):
        qm = lax.dynamic_index_in_dim(q_meta, j, 1)      # [N, 1]
        qg = lax.dynamic_index_in_dim(q_gt, j, 1)
        hit = flip_ok & (payload == qm) & (gt <= qg)
        best = jnp.max(jnp.where(hit, key, 0), axis=-1)
        return lax.dynamic_update_index_in_dim(out, best, j, 1)

    return lax.fori_loop(0, b, body, jnp.zeros((n, b), jnp.uint32))


@contract(out=_BOOL_NB, stc=_STC, member=_U32_NB, gt=_U32_NB, impl=None)
def undo_marked(stc, member: jnp.ndarray, gt: jnp.ndarray,
                impl: str | None = None) -> jnp.ndarray:
    """bool[N, B]: is a stored undo row targeting (member, gt) present?
    (Arrivals whose undo already synced come in pre-undone — reference:
    community.py re-marks on re-insert attempts.)"""
    n, b = member.shape
    m = stc.gt.shape[-1]
    undo_rows = ((stc.meta == jnp.uint32(META_UNDO_OWN))
                 | (stc.meta == jnp.uint32(META_UNDO_OTHER)))   # [N, M]
    if _auto_impl(impl, n * b * m) == "broadcast":
        return jnp.any(
            undo_rows[:, None, :]
            & (stc.payload[:, None, :] == member[:, :, None])
            & (stc.aux[:, None, :] == gt[:, :, None]), axis=-1)

    def body(j, out):
        mb = lax.dynamic_index_in_dim(member, j, 1)      # [N, 1]
        g = lax.dynamic_index_in_dim(gt, j, 1)
        hit = jnp.any(undo_rows & (stc.payload == mb) & (stc.aux == g),
                      axis=-1)
        return lax.dynamic_update_index_in_dim(out, hit, j, 1)

    return lax.fori_loop(0, b, body, jnp.zeros((n, b), bool))


@contract(out=Spec("bool", ("N", "M")), stc=_STC, target_member=_U32_NB,
          target_gt=_U32_NB, valid=_BOOL_NB, impl=None)
def undo_hits_store(stc, target_member: jnp.ndarray,
                    target_gt: jnp.ndarray, valid: jnp.ndarray,
                    impl: str | None = None) -> jnp.ndarray:
    """bool[N, M]: which stored rows does this batch's accepted undo set
    mark?  (The post-insert pass applying dispersy-undo-own/-other to the
    store — reference: community.py ``on_undo`` setting ``sync.undone``.)
    Control rows are excluded by the CALLER (meta < 32 check)."""
    n, b = target_member.shape
    m = stc.gt.shape[-1]
    if _auto_impl(impl, n * b * m) == "broadcast":
        return jnp.any(
            valid[:, None, :]
            & (stc.member[:, :, None] == target_member[:, None, :])
            & (stc.gt[:, :, None] == target_gt[:, None, :]), axis=-1)

    def body(j, out):
        mb = lax.dynamic_index_in_dim(target_member, j, 1)   # [N, 1]
        g = lax.dynamic_index_in_dim(target_gt, j, 1)
        ok = lax.dynamic_index_in_dim(valid, j, 1)
        return out | (ok & (stc.member == mb) & (stc.gt == g))

    return lax.fori_loop(0, b, body, jnp.zeros((n, m), bool))


@contract(out=_BOOL_NB, stc=_STC, member=_U32_NB, impl=None)
def identity_stored(stc, member: jnp.ndarray,
                    impl: str | None = None) -> jnp.ndarray:
    """bool[N, B]: does the receiver's store hold a dispersy-identity
    record for ``member``?  (Reference: member.py ``has_identity`` — the
    unknown-member gate before any signature can verify;
    config.identity_required.)  Same two-form memory story as every
    intake check."""
    n, b = member.shape
    m = stc.gt.shape[-1]
    rows = stc.meta == jnp.uint32(META_IDENTITY)          # [N, M]
    if _auto_impl(impl, n * b * m) == "broadcast":
        return jnp.any(rows[:, None, :]
                       & (stc.member[:, None, :] == member[:, :, None]),
                       axis=-1)

    def body(j, out):
        mb = lax.dynamic_index_in_dim(member, j, 1)       # [N, 1]
        got = jnp.any(rows & (stc.member == mb), axis=-1)
        return lax.dynamic_update_index_in_dim(out, got, j, 1)

    return lax.fori_loop(0, b, body, jnp.zeros((n, b), bool))


@contract(out=_U32_NB, stc=_STC, member=_U32_NB, gt=_U32_NB, impl=None)
def stored_meta_of(stc, member: jnp.ndarray, gt: jnp.ndarray,
                   impl: str | None = None) -> jnp.ndarray:
    """u32[N, B]: meta id of the stored USER row at (member, gt), else
    0xFFFF.  (The undo-other permission check resolves the target
    record's meta — reference: timeline.py checks the u"undo" permission
    against the *target message's* meta; payload.py UndoPayload names the
    target by (member, global_time).)  A target not yet stored returns
    the sentinel: the undo is refused this round and Bloom re-offers it,
    the module-standard missing-proof fixed point."""
    n, b = member.shape
    m = stc.gt.shape[-1]
    user = stc.meta < jnp.uint32(32)                      # [N, M]
    sentinel = jnp.uint32(0xFFFF)
    if _auto_impl(impl, n * b * m) == "broadcast":
        match = (user[:, None, :]
                 & (stc.member[:, None, :] == member[:, :, None])
                 & (stc.gt[:, None, :] == gt[:, :, None]))
        return jnp.min(jnp.where(match, stc.meta[:, None, :], sentinel),
                       axis=-1)

    def body(j, out):
        mb = lax.dynamic_index_in_dim(member, j, 1)       # [N, 1]
        g = lax.dynamic_index_in_dim(gt, j, 1)
        match = user & (stc.member == mb) & (stc.gt == g)
        mt = jnp.min(jnp.where(match, stc.meta, sentinel), axis=-1)
        return lax.dynamic_update_index_in_dim(out, mt, j, 1)

    return lax.fori_loop(0, b, body, jnp.full((n, b), sentinel))


@contract(out=_U32_NB, stc=_STC, member=_U32_NB,
          meta=Spec("uint8", ("N", "B")), impl=None)
def seq_stored_max(stc, member: jnp.ndarray, meta: jnp.ndarray,
                   impl: str | None = None) -> jnp.ndarray:
    """u32[N, B]: per batch entry, the highest stored sequence number
    (``aux``) among rows with its (member, meta).  (The
    enable_sequence_number chain base — reference: distribution.py
    sequence numbers + the in-order intake recast, config.py
    ``seq_meta_mask``.)"""
    n, b = member.shape
    m = stc.gt.shape[-1]
    live = stc.gt != jnp.uint32(EMPTY_U32)               # [N, M]
    if _auto_impl(impl, n * b * m) == "broadcast":
        same = ((stc.member[:, None, :] == member[:, :, None])
                & (stc.meta[:, None, :] == meta[:, :, None])
                & live[:, None, :])
        return jnp.max(jnp.where(same, stc.aux[:, None, :], 0), axis=-1)

    def body(j, out):
        mb = lax.dynamic_index_in_dim(member, j, 1)      # [N, 1]
        mt = lax.dynamic_index_in_dim(meta, j, 1)
        same = (stc.member == mb) & (stc.meta == mt) & live
        mx = jnp.max(jnp.where(same, stc.aux, 0), axis=-1)
        return lax.dynamic_update_index_in_dim(out, mx, j, 1)

    return lax.fori_loop(0, b, body, jnp.zeros((n, b), jnp.uint32))
