"""Ingress-protection kernels: admission classes, token-bucket refill
and spend.

The jit-traced half of the overload plane (:mod:`dispersy_tpu.overload`
declares the static :class:`~dispersy_tpu.overload.OverloadConfig`; the
engine composes these into the fused round's push phase only when
``overload.enabled``, so a disabled plane compiles to the identical
step).  Every op mirrors bit-for-bit in the oracle
(:mod:`dispersy_tpu.oracle.sim` ``_admission_class`` / the credit math
in ``step``'s push phase), the same lockstep discipline as every other
ops module.
"""

from __future__ import annotations

import jax.numpy as jnp

from dispersy_tpu.ops import rng
from dispersy_tpu.ops.contracts import Spec, contract

_U32_N = Spec("uint32", ("N",))


@contract(out=Spec("uint32", ("E",)),
          meta=Spec("uint8", ("E",)), n_meta=4,
          priorities=(128, 128, 128, 128))
def admission_class(meta: jnp.ndarray, n_meta: int,
                    priorities: tuple) -> jnp.ndarray:
    """u32 admission class per wire meta byte — LOWER wins inbox slots
    under overflow (``overload.admission_class`` is the scalar form and
    documents the table; the delivery kernel folds this into its packed
    sort key).  Valid user metas carry ``255 - declared priority``, the
    control band ``255 - CONTROL_PRIORITY`` (identity at its bulk
    ``255 - IDENTITY_PRIORITY``), and a meta valid for neither band —
    most flood junk — ranks dead last at 255."""
    from dispersy_tpu.config import (CONTROL_PRIORITY, IDENTITY_PRIORITY,
                                     META_AUTHORIZE, META_IDENTITY,
                                     META_MALICIOUS)

    prio_arr = jnp.asarray(priorities, jnp.uint32)
    meta_c = jnp.minimum(meta, jnp.uint8(n_meta - 1)).astype(jnp.int32)
    user_cls = jnp.uint32(255) - jnp.take(prio_arr, meta_c, axis=0)
    is_ident = meta == jnp.uint8(META_IDENTITY)
    is_ctrl = ((meta >= jnp.uint8(META_AUTHORIZE))
               & (meta <= jnp.uint8(META_MALICIOUS)) & ~is_ident)
    return jnp.where(
        meta < jnp.uint8(n_meta), user_cls,
        jnp.where(is_ident, jnp.uint32(255 - IDENTITY_PRIORITY),
                  jnp.where(is_ctrl, jnp.uint32(255 - CONTROL_PRIORITY),
                            jnp.uint32(255))))


@contract(out=_U32_N,
          bucket=Spec("uint8", ("N",)), seed=Spec("uint32", ()),
          rnd=Spec("uint32", ()), idx=Spec("int32", ("N",)),
          bucket_rate=2.5, bucket_depth=8)
def bucket_refill(bucket: jnp.ndarray, seed, rnd, idx: jnp.ndarray,
                  bucket_rate, bucket_depth: int) -> jnp.ndarray:
    """This round's spendable credit per sender: the carried u8 balance
    plus the refill, clamped at the burst cap.

    ``bucket_rate`` may be fractional (and TRACED under fleet
    overrides — ``overload.TRACED_OVERLOAD_KNOBS``): the integer part
    refills deterministically, the remainder lands as one Bernoulli
    counter-draw per peer per round (purpose ``P_OVERLOAD``), so the
    oracle replays the credit sequence exactly and a traced rate equal
    to the static knob computes the identical round.  All float math is
    float32 (the oracle mirrors with ``np.float32``).
    """
    ratef = jnp.float32(bucket_rate)
    whole = jnp.floor(ratef)
    frac = ratef - whole
    u = rng.rand_uniform(seed, rnd, idx, rng.P_OVERLOAD)
    refill = whole.astype(jnp.uint32) + (u < frac).astype(jnp.uint32)
    return jnp.minimum(bucket.astype(jnp.uint32) + refill,
                       jnp.uint32(bucket_depth))


@contract(out=Spec("uint8", ("N",)),
          credit=_U32_N, n_attempted=_U32_N)
def bucket_spend(credit: jnp.ndarray,
                 n_attempted: jnp.ndarray) -> jnp.ndarray:
    """The post-round u8 balance: this round's credit minus the packets
    actually chargeable against it (attempts beyond the balance were
    shed, not spent — a flooder cannot drive its bucket below zero, it
    just stays pinned at empty)."""
    return (credit - jnp.minimum(n_attempted, credit)).astype(jnp.uint8)
