"""Packed-uint32 Bloom filter kernels.

TPU-native replacement for the reference's pure-Python ``BloomFilter``
(reference: bloomfilter.py — ``BloomFilter.add / __contains__ / bytes``,
sized to fit one UDP payload, double hashing).  The bitset is a ``uint32[W]``
word array per filter.

Kernel shape: both build and query are **compare-and-reduce** over the word
axis — ``[..., M]`` item hashes broadcast against ``[W]`` word indices and
reduce, one pass per hash function.  Per-row gather/scatter (the obvious
formulation) is catastrophically slow on TPU: a vmapped ``words[idx]``
lowers to millions of serialized 1-element gathers, and a ``[..., M, k]``
probe tensor picks up a (8, 128)-tile layout that pads a k-wide minor dim
128x.  The broadcast-compare form stays in well-tiled ``[..., M]`` /
``[..., W]`` shapes, fuses into the surrounding step, and runs on the VPU at
memory bandwidth (measured ~40x faster than the gather form on v5e).

Double-hashing scheme: bit_j = (h1 + j·h2) mod n_bits with h2 forced odd,
h1/h2 drawn from seeded :func:`dispersy_tpu.ops.hashing.hash_u32` streams.
The CPU oracle (:mod:`dispersy_tpu.oracle.bloom`) mirrors this bit-for-bit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dispersy_tpu.ops.contracts import Spec, contract, host_helper
from dispersy_tpu.ops.hashing import (BLOOM_SALT_SEED, BLOOM_SEED_1,
                                      BLOOM_SEED_2, hash_u32)

# Canonical contract inputs shared by the bloom kernels: n_bits packs
# exactly into W uint32 words, probes carry H hash functions.
_N_BITS = lambda d: 32 * d["W"]  # noqa: E731
_N_HASHES = lambda d: d["H"]  # noqa: E731


def _auto_impl(impl: str | None) -> str:
    """Pick the kernel form: ``"compare"`` (broadcast-compare-reduce) on
    TPU, ``"gather"`` (word gather / bitmap scatter) elsewhere.

    The two forms are bit-identical; they differ only in what the backend
    materializes.  On TPU the compare form fuses into the surrounding step
    and runs at memory bandwidth, while gathers serialize (~40x slower,
    module docstring).  On CPU the fusion does NOT happen: XLA:CPU
    materializes the [..., M, W] compare tensor per hash function — at
    config #3 scale (10k peers x M=1152 x W=77 x 7 hashes x 8 request
    slots) that is a ~200 GB allocation, observed OOM — whereas the
    gather/scatter forms stay at [..., M] / [..., bits].

    Keyed off ``jax.default_backend()``, not the operands' committed
    device: this repo runs ONE backend per process (cpuenv.py pins
    JAX_PLATFORMS in every child; tests/conftest.py pins cpu), so default
    backend == executing backend.  Mixing CPU-placed computations into a
    TPU-default process would pick the compare form on CPU — pass
    ``impl="gather"`` explicitly if that ever becomes a real
    configuration.
    """
    if impl is not None:
        return impl
    return "compare" if jax.default_backend() == "tpu" else "gather"


def _h1_h2(item_hash: jnp.ndarray,
           salt=None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The double-hashing pair: h2 forced odd so successive probes never
    collapse when h2 would be 0 (and cycle through all residues when n_bits
    is a power of two).

    ``salt`` re-randomizes the probe sequence per filter — the reference's
    BloomFilter *prefix* (bloomfilter.py: every claimed sync filter
    carries a fresh prefix so a false positive against one claim is not a
    false positive against the next; without it a static store's missing
    records can collide permanently and pull repair stalls short of
    100%).  Build and query must use the same salt; the round index works
    because the whole exchange is round-synchronous.  ``None`` = unsalted
    (NOT equivalent to salt 0, which mixes hash(0) in — the distinction is
    static, never data-dependent, so it traces).
    """
    h = item_hash.astype(jnp.uint32)
    if salt is not None:
        h = h ^ hash_u32(jnp.asarray(salt, jnp.uint32), BLOOM_SALT_SEED)
    h1 = hash_u32(h, BLOOM_SEED_1)
    h2 = hash_u32(h, BLOOM_SEED_2) | jnp.uint32(1)
    return h1, h2


@contract(out=Spec("int32", ("M", "H")),
          item_hash=Spec("uint32", ("M",)), n_bits=_N_BITS,
          n_hashes=_N_HASHES, salt=None)
def probe_bits(item_hash: jnp.ndarray, n_bits: int, n_hashes: int,
               salt=None) -> jnp.ndarray:
    """Bit indices probed for an item: shape ``item_hash.shape + (n_hashes,)``.

    Oracle/reference view of the probe sequence.  On gather backends
    (:func:`gather_backend`) this tensor is ALSO the hot kernels' shared
    input — the engine computes it once per round and feeds it to both
    the build and every per-request-slot query, instead of re-deriving
    the double-hash chain per call; on TPU the kernels keep the fused
    compare form and never materialize the hash axis (module docstring).
    """
    h1, h2 = _h1_h2(item_hash, salt)
    j = jnp.arange(n_hashes, dtype=jnp.uint32)
    idx = (h1[..., None] + j * h2[..., None]) % jnp.uint32(n_bits)
    return idx.astype(jnp.int32)


@host_helper
def gather_backend(impl: str | None = None) -> bool:
    """Should callers precompute/share :func:`probe_bits` tensors?  True
    exactly when the kernels below pick their gather/scatter forms."""
    return _auto_impl(impl) == "gather"


@contract(out=Spec("uint32", ("N", "W")),
          probes=Spec("int32", ("N", "M", "H")),
          mask=Spec("bool", ("N", "M")), n_bits=_N_BITS, chunks=1)
def bloom_build_from(probes: jnp.ndarray, mask: jnp.ndarray,
                     n_bits: int, chunks: int = 1) -> jnp.ndarray:
    """Gather-form build from precomputed ``probes`` (:func:`probe_bits`,
    ``[..., M, K]`` i32): ONE flat scatter sets every probed bit, then the
    bitmap packs to words.  Bit-identical to :func:`bloom_build`.

    ``chunks > 1`` splits the row axis into that many row-block scatters
    (a Python loop — static, so it just unrolls into the jit).  Two
    DIFFERENT int32 walls make this necessary at fleet scale, and the
    flat/2-D branch below only dodges the first: (a) the flat index
    *value* ``row * stride`` overflows past 2^31 elements; (b) XLA's
    scatter lowering caps the COUNT of update indices in one op at 2^31
    — a vmapped fleet build at R x N x M x K = 8 x 1M x 48 x 7 is ~2.7e9
    updates and refuses to lower no matter how the indices are encoded.
    Chunking divides both.  Bit-identical for any ``chunks`` (row blocks
    are independent); config knob: ``parallel.scatter_chunks``.
    """
    assert n_bits % 32 == 0, "n_bits must pack into uint32 words"
    w = n_bits // 32
    lead = probes.shape[:-2]
    flat = 1
    for d in lead:
        flat *= d
    stride = n_bits + 1
    tgt = jnp.where(mask[..., None], probes,
                    jnp.int32(n_bits)).reshape(flat, -1)   # [flat, M*K]

    def scatter_rows(sub):
        fc = sub.shape[0]
        if fc * stride < 2 ** 31:
            # Flat one-component indices (cheapest scatter layout)...
            row0 = (jnp.arange(fc, dtype=jnp.int32) * stride)[:, None]
            bits = (jnp.zeros((fc * stride,), jnp.bool_)
                    .at[(row0 + sub).reshape(-1)].set(True, mode="drop")
                    .reshape(fc, stride))
        else:
            # ...but row*stride overflows int32 past 2^31 elements (e.g.
            # the default 2464-bit filter above ~870k rows), so large
            # shapes keep the 2-D (row, bit) index form; x64 is off, so
            # no int64 escape.
            rows = jnp.arange(fc, dtype=jnp.int32)[:, None]
            bits = (jnp.zeros((fc, stride), jnp.bool_)
                    .at[rows, sub].set(True, mode="drop"))
        return pack_bits(bits[:, :n_bits])
    if chunks <= 1:
        return scatter_rows(tgt).reshape(*lead, w)
    block = -(-flat // chunks)
    words = jnp.concatenate(
        [scatter_rows(tgt[lo:min(lo + block, flat)])
         for lo in range(0, flat, block)], axis=0)
    return words.reshape(*lead, w)


@contract(out=Spec("bool", ("N", "M")),
          words=Spec("uint32", ("N", "W")),
          probes=Spec("int32", ("N", "M", "H")))
def bloom_query_from(words: jnp.ndarray,
                     probes: jnp.ndarray) -> jnp.ndarray:
    """Gather-form membership test from precomputed ``probes``
    (``[..., M, K]`` i32): per-item word fetches + bit tests, no hash
    re-derivation.  Bit-identical to :func:`bloom_query` — the engine's
    responder uses this to share one probe tensor across all request
    slots."""
    w = words.shape[-1]
    word_ix = probes >> jnp.int32(5)                       # [..., M, K]
    lead_shape = probes.shape[:-2] + (probes.shape[-2] * probes.shape[-1],)
    sel = jnp.take_along_axis(
        jnp.broadcast_to(words, probes.shape[:-2] + (w,)),
        word_ix.reshape(lead_shape), axis=-1).reshape(probes.shape)
    bit = (sel >> (probes.astype(jnp.uint32) & jnp.uint32(31))) \
        & jnp.uint32(1)
    return jnp.all(bit == 1, axis=-1)


@contract(out=Spec("uint32", ("N", "W")),
          item_hashes=Spec("uint32", ("N", "M")),
          mask=Spec("bool", ("N", "M")), n_bits=_N_BITS,
          n_hashes=_N_HASHES, impl=None, salt=None)
def bloom_build(item_hashes: jnp.ndarray, mask: jnp.ndarray,
                n_bits: int, n_hashes: int,
                impl: str | None = None, salt=None) -> jnp.ndarray:
    """Build packed filters from ``[..., M]`` item hashes under a mask.

    Returns ``uint32[..., n_bits // 32]``; leading dims are batch dims (one
    filter per row).  Masked-out items contribute no bits (the reference
    loops ``BloomFilter.add`` over the sync-slice SELECT; here the slice
    mask plays that role).  ``impl``: None = per-backend auto
    (:func:`_auto_impl`); ``"compare"`` / ``"gather"`` force a form — both
    produce identical bits.
    """
    assert n_bits % 32 == 0, "n_bits must pack into uint32 words"
    w = n_bits // 32
    if _auto_impl(impl) == "gather":
        # Bitmap scatter on the probe tensor: ONE flat scatter covers all
        # n_hashes probes (the old per-hash loop rewrote the [N, n_bits]
        # bitmap n_hashes times — the dominant byte cost of the CPU
        # build, measured 5.4 KB/peer at the bench shape).
        return bloom_build_from(
            probe_bits(item_hashes, n_bits, n_hashes, salt), mask, n_bits)
    h1, h2 = _h1_h2(item_hashes, salt)
    w_ix = jnp.arange(w, dtype=jnp.uint32)                    # [W]
    words = jnp.zeros(item_hashes.shape[:-1] + (w,), jnp.uint32)
    for j in range(n_hashes):
        idx = (h1 + jnp.uint32(j) * h2) % jnp.uint32(n_bits)  # [..., M]
        contrib = jnp.where(
            ((idx >> jnp.uint32(5))[..., None] == w_ix) & mask[..., None],
            jnp.uint32(1) << (idx & jnp.uint32(31))[..., None],
            jnp.uint32(0))                                    # [..., M, W]
        words = words | jnp.bitwise_or.reduce(contrib, axis=-2)
    return words


@contract(out=Spec("uint32", ("N", "W")),
          digest=Spec("uint32", ("N", "W")),
          probes=Spec("int32", ("N", "M", "H")),
          mask=Spec("bool", ("N", "M")), n_bits=_N_BITS)
def digest_update(digest: jnp.ndarray, probes: jnp.ndarray,
                  mask: jnp.ndarray, n_bits: int) -> jnp.ndarray:
    """OR the masked items' probe bits into a persistent per-row digest
    (dispersy_tpu/storediet.py: the incremental Bloom digest).

    The byte-diet replacement for rebuilding the claimed slice's bloom
    from 4 re-read store columns every round: the engine keeps the
    digest as a ``PeerState`` leaf, feeds each round's LANDED arrivals
    (their ``probe_bits`` are already computed for the freshness test)
    through this OR, and only falls back to a full :func:`bloom_build`
    at compaction — where the epoch salt rotates, so stale bits never
    survive an epoch.  Bloom builds are monotone ORs of per-item bit
    sets, so ``digest_update(build(A), probes(B))`` equals
    ``build(A ∪ B)`` exactly (the C=1 legacy-identity pin relies on
    it)."""
    return digest | bloom_build_from(probes, mask, n_bits)


# pack/unpack sizes are coupled (BITS = 32·W, PW = N·BITS/32), which the
# Spec grammar cannot express — so the dims are PINNED per-op here rather
# than inherited: a legitimate edit to the global canonical DIMS must not
# fail R3 on these healthy ops.
@contract(out=Spec("uint32", ("PW",)), dense=Spec("bool", ("N", "BITS")),
          dims={"N": 4, "BITS": 64, "PW": 8})
def pack_bits(dense: jnp.ndarray) -> jnp.ndarray:
    """bool[n_bits] -> uint32[n_bits//32], bit i of word w == bit 32w+i."""
    w = dense.reshape(-1, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (w << shifts).sum(axis=-1, dtype=jnp.uint32)


@contract(out=Spec("bool", ("N", "BITS")), words=Spec("uint32", ("N", "W")),
          dims={"W": 2, "BITS": 64})    # BITS = 32·W, pinned as above
def unpack_bits(words: jnp.ndarray) -> jnp.ndarray:
    """uint32[W] -> bool[32·W] (inverse of :func:`pack_bits`)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (((words[..., None] >> shifts) & 1) > 0).reshape(*words.shape[:-1], -1)


@contract(out=Spec("bool", ("N", "M")),
          words=Spec("uint32", ("N", "W")),
          item_hashes=Spec("uint32", ("N", "M")), n_bits=_N_BITS,
          n_hashes=_N_HASHES, impl=None, salt=None)
def bloom_query(words: jnp.ndarray, item_hashes: jnp.ndarray,
                n_bits: int, n_hashes: int,
                impl: str | None = None, salt=None) -> jnp.ndarray:
    """Membership test: ``words`` uint32[..., W], ``item_hashes`` [..., M]
    -> bool[..., M], batched over matching leading dims.

    Reference: ``BloomFilter.__contains__``.  True means *possibly present*
    (standard Bloom semantics: false positives at the configured error rate,
    never false negatives).  ``impl``/``salt`` as in :func:`bloom_build`.
    """
    if _auto_impl(impl) == "gather":
        # Per-item word fetches on the probe tensor; row-local along the
        # last axis, cheap where gathers are cheap.
        return bloom_query_from(
            words, probe_bits(item_hashes, n_bits, n_hashes, salt))
    h1, h2 = _h1_h2(item_hashes, salt)
    ok = jnp.ones(item_hashes.shape, jnp.bool_)
    w_ix = jnp.arange(words.shape[-1], dtype=jnp.uint32)      # [W]
    for j in range(n_hashes):
        idx = (h1 + jnp.uint32(j) * h2) % jnp.uint32(n_bits)  # [..., M]
        # Select each item's word by broadcast-compare (no gather).
        sel = jnp.sum(jnp.where(
            (idx >> jnp.uint32(5))[..., None] == w_ix,
            words[..., None, :], jnp.uint32(0)),
            axis=-1, dtype=jnp.uint32)                        # [..., M]
        ok = ok & (((sel >> (idx & jnp.uint32(31))) & jnp.uint32(1)) == 1)
    return ok
