"""Packed-uint32 Bloom filter kernels.

TPU-native replacement for the reference's pure-Python ``BloomFilter``
(reference: bloomfilter.py — ``BloomFilter.add / __contains__ / bytes``,
sized to fit one UDP payload, double hashing).  The bitset is a ``uint32[W]``
word array per filter; building scatters into a dense boolean bit vector and
packs it, querying gathers words and tests bits — both shapes are static so
the whole thing fuses under jit/vmap.

Double-hashing scheme: bit_j = (h1 + j·h2) mod n_bits with h2 forced odd,
h1/h2 drawn from seeded :func:`dispersy_tpu.ops.hashing.hash_u32` streams.
The CPU oracle (:mod:`dispersy_tpu.oracle.bloom`) mirrors this bit-for-bit.
"""

from __future__ import annotations

import jax.numpy as jnp

from dispersy_tpu.ops.hashing import BLOOM_SEED_1, BLOOM_SEED_2, hash_u32


def probe_bits(item_hash: jnp.ndarray, n_bits: int, n_hashes: int) -> jnp.ndarray:
    """Bit indices probed for an item: shape ``item_hash.shape + (n_hashes,)``.

    uint32 arithmetic throughout; h2 is forced odd so successive probes do not
    collapse when h2 would be 0 (and cycle through all residues when n_bits is
    a power of two).
    """
    h = item_hash.astype(jnp.uint32)
    h1 = hash_u32(h, BLOOM_SEED_1)
    h2 = hash_u32(h, BLOOM_SEED_2) | jnp.uint32(1)
    j = jnp.arange(n_hashes, dtype=jnp.uint32)
    idx = (h1[..., None] + j * h2[..., None]) % jnp.uint32(n_bits)
    return idx.astype(jnp.int32)


def bloom_build(item_hashes: jnp.ndarray, mask: jnp.ndarray,
                n_bits: int, n_hashes: int) -> jnp.ndarray:
    """Build one packed filter from ``[M]`` item hashes under a validity mask.

    Returns ``uint32[n_bits // 32]``.  Masked-out items are routed to an
    out-of-range index and dropped by the scatter, so the shape stays static
    (the reference loops ``BloomFilter.add`` over the sync-slice SELECT; here
    the slice mask plays that role).
    """
    assert n_bits % 32 == 0, "n_bits must pack into uint32 words"
    idx = probe_bits(item_hashes, n_bits, n_hashes)          # [M, k]
    idx = jnp.where(mask[..., None], idx, n_bits)            # park masked items
    dense = jnp.zeros((n_bits,), jnp.bool_).at[idx.reshape(-1)].set(
        True, mode="drop")
    return pack_bits(dense)


def pack_bits(dense: jnp.ndarray) -> jnp.ndarray:
    """bool[n_bits] -> uint32[n_bits//32], bit i of word w == bit 32w+i."""
    w = dense.reshape(-1, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (w << shifts).sum(axis=-1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray) -> jnp.ndarray:
    """uint32[W] -> bool[32·W] (inverse of :func:`pack_bits`)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (((words[..., None] >> shifts) & 1) > 0).reshape(*words.shape[:-1], -1)


def bloom_query(words: jnp.ndarray, item_hashes: jnp.ndarray,
                n_bits: int, n_hashes: int) -> jnp.ndarray:
    """Membership test: ``words`` uint32[W], ``item_hashes`` [...] -> bool[...].

    Reference: ``BloomFilter.__contains__``.  True means *possibly present*
    (standard Bloom semantics: false positives at the configured error rate,
    never false negatives).
    """
    idx = probe_bits(item_hashes, n_bits, n_hashes)          # [..., k]
    word = idx >> 5
    bit = (idx & 31).astype(jnp.uint32)
    present = (words[word] >> bit) & jnp.uint32(1)
    return jnp.all(present == 1, axis=-1)
