"""Packed-uint32 Bloom filter kernels.

TPU-native replacement for the reference's pure-Python ``BloomFilter``
(reference: bloomfilter.py — ``BloomFilter.add / __contains__ / bytes``,
sized to fit one UDP payload, double hashing).  The bitset is a ``uint32[W]``
word array per filter.

Kernel shape: both build and query are **compare-and-reduce** over the word
axis — ``[..., M]`` item hashes broadcast against ``[W]`` word indices and
reduce, one pass per hash function.  Per-row gather/scatter (the obvious
formulation) is catastrophically slow on TPU: a vmapped ``words[idx]``
lowers to millions of serialized 1-element gathers, and a ``[..., M, k]``
probe tensor picks up a (8, 128)-tile layout that pads a k-wide minor dim
128x.  The broadcast-compare form stays in well-tiled ``[..., M]`` /
``[..., W]`` shapes, fuses into the surrounding step, and runs on the VPU at
memory bandwidth (measured ~40x faster than the gather form on v5e).

Double-hashing scheme: bit_j = (h1 + j·h2) mod n_bits with h2 forced odd,
h1/h2 drawn from seeded :func:`dispersy_tpu.ops.hashing.hash_u32` streams.
The CPU oracle (:mod:`dispersy_tpu.oracle.bloom`) mirrors this bit-for-bit.
"""

from __future__ import annotations

import jax.numpy as jnp

from dispersy_tpu.ops.hashing import BLOOM_SEED_1, BLOOM_SEED_2, hash_u32


def _h1_h2(item_hash: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The double-hashing pair: h2 forced odd so successive probes never
    collapse when h2 would be 0 (and cycle through all residues when n_bits
    is a power of two)."""
    h = item_hash.astype(jnp.uint32)
    h1 = hash_u32(h, BLOOM_SEED_1)
    h2 = hash_u32(h, BLOOM_SEED_2) | jnp.uint32(1)
    return h1, h2


def probe_bits(item_hash: jnp.ndarray, n_bits: int, n_hashes: int) -> jnp.ndarray:
    """Bit indices probed for an item: shape ``item_hash.shape + (n_hashes,)``.

    Reference/oracle view of the probe sequence; the hot kernels below never
    materialize this axis (see module docstring).
    """
    h1, h2 = _h1_h2(item_hash)
    j = jnp.arange(n_hashes, dtype=jnp.uint32)
    idx = (h1[..., None] + j * h2[..., None]) % jnp.uint32(n_bits)
    return idx.astype(jnp.int32)


def bloom_build(item_hashes: jnp.ndarray, mask: jnp.ndarray,
                n_bits: int, n_hashes: int) -> jnp.ndarray:
    """Build packed filters from ``[..., M]`` item hashes under a mask.

    Returns ``uint32[..., n_bits // 32]``; leading dims are batch dims (one
    filter per row).  Masked-out items contribute no bits (the reference
    loops ``BloomFilter.add`` over the sync-slice SELECT; here the slice
    mask plays that role).
    """
    assert n_bits % 32 == 0, "n_bits must pack into uint32 words"
    w = n_bits // 32
    w_ix = jnp.arange(w, dtype=jnp.uint32)                    # [W]
    h1, h2 = _h1_h2(item_hashes)
    words = jnp.zeros(item_hashes.shape[:-1] + (w,), jnp.uint32)
    for j in range(n_hashes):
        idx = (h1 + jnp.uint32(j) * h2) % jnp.uint32(n_bits)  # [..., M]
        contrib = jnp.where(
            ((idx >> jnp.uint32(5))[..., None] == w_ix) & mask[..., None],
            jnp.uint32(1) << (idx & jnp.uint32(31))[..., None],
            jnp.uint32(0))                                    # [..., M, W]
        words = words | jnp.bitwise_or.reduce(contrib, axis=-2)
    return words


def pack_bits(dense: jnp.ndarray) -> jnp.ndarray:
    """bool[n_bits] -> uint32[n_bits//32], bit i of word w == bit 32w+i."""
    w = dense.reshape(-1, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (w << shifts).sum(axis=-1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray) -> jnp.ndarray:
    """uint32[W] -> bool[32·W] (inverse of :func:`pack_bits`)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (((words[..., None] >> shifts) & 1) > 0).reshape(*words.shape[:-1], -1)


def bloom_query(words: jnp.ndarray, item_hashes: jnp.ndarray,
                n_bits: int, n_hashes: int) -> jnp.ndarray:
    """Membership test: ``words`` uint32[..., W], ``item_hashes`` [..., M]
    -> bool[..., M], batched over matching leading dims.

    Reference: ``BloomFilter.__contains__``.  True means *possibly present*
    (standard Bloom semantics: false positives at the configured error rate,
    never false negatives).
    """
    w_ix = jnp.arange(words.shape[-1], dtype=jnp.uint32)      # [W]
    h1, h2 = _h1_h2(item_hashes)
    ok = jnp.ones(item_hashes.shape, jnp.bool_)
    for j in range(n_hashes):
        idx = (h1 + jnp.uint32(j) * h2) % jnp.uint32(n_bits)  # [..., M]
        # Select each item's word by broadcast-compare (no gather).
        sel = jnp.sum(jnp.where((idx >> jnp.uint32(5))[..., None] == w_ix,
                                words[..., None, :], jnp.uint32(0)),
                      axis=-1, dtype=jnp.uint32)              # [..., M]
        ok = ok & (((sel >> (idx & jnp.uint32(31))) & jnp.uint32(1)) == 1)
    return ok
