"""Fleet-plane kernels: cross-replica telemetry-row reductions.

The jit-traced half of the replica plane (:mod:`dispersy_tpu.fleet`
stacks R independent overlays along a leading axis and advances them
with one ``vmap(step)``; FLEET.md).  A fleet's per-replica packed
telemetry rows (``PeerState.tele_row``, [R, RW]) reduce ON DEVICE into
one ``[3, RW]`` min/max/sum band laid out exactly like a row, so an
R-replica convergence band is still ONE device->host transfer per
drain — the same economy the PR-6 row bought the single-run snapshot.

The reduction is schema-aware through a static per-word kind plan
(:func:`dispersy_tpu.telemetry.word_kinds`):

- ``KIND_U32`` words (plain counts, health words, hist buckets):
  elementwise min/max; sum mod 2^32 (exact for the schema's count
  ranges while ``R * max < 2^32``).
- ``KIND_F32`` words (``sim_time``): bitcast to f32, reduce, bitcast
  back.
- ``KIND_U64_LO``/``KIND_U64_HI`` pairs (counter totals): true 64-bit
  semantics without ``jax_enable_x64`` — min/max compare
  lexicographically on (hi, lo); the sum reuses the byte-lane
  carry-exact :func:`~dispersy_tpu.ops.telemetry.col_sum_u64` on each
  half and folds the high half's carry in (exact while the fleet total
  fits u64).

The host derives means (``sum / R``) and per-field dicts in
``telemetry.band_to_dict`` — device code never divides.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dispersy_tpu.ops.contracts import Spec, contract
from dispersy_tpu.ops.telemetry import col_sum_u64
from dispersy_tpu.telemetry import (KIND_F32, KIND_U32, KIND_U64_HI,
                                    KIND_U64_LO)

_M32 = 0xFFFFFFFF

# Canonical 5-word example plan for the contract checks: one u32 word,
# one f32 word, one u64 (lo, hi) pair, one trailing u32 (hist-style).
_KINDS_EXAMPLE = (KIND_U32, KIND_F32, KIND_U64_LO, KIND_U64_HI, KIND_U32)


def _idx(kinds: tuple, code: int) -> tuple:
    return tuple(i for i, k in enumerate(kinds) if k == code)


@contract(out=Spec("uint32", (3, "V")),
          rows=Spec("uint32", ("R", "V")), kinds=_KINDS_EXAMPLE,
          dims={"R": 19, "V": 5})
def band_reduce(rows: jnp.ndarray, kinds: tuple) -> jnp.ndarray:
    """``u32[3, V]`` (min row, max row, sum row) of ``rows`` ([R, V])
    across the replica axis, per the static ``kinds`` word plan.

    Every output row is laid out like an input row, so the host decodes
    all three with the ordinary ``telemetry.unpack_row``.  ``kinds``
    must place every ``KIND_U64_HI`` directly after its ``KIND_U64_LO``
    (the row schema's packing — ``telemetry.word_kinds`` guarantees it).
    """
    v = len(kinds)
    if rows.shape[-1] != v:
        raise ValueError(f"rows have {rows.shape[-1]} words, kind plan "
                         f"covers {v}")
    lo_idx = _idx(kinds, KIND_U64_LO)
    if tuple(i + 1 for i in lo_idx) != _idx(kinds, KIND_U64_HI):
        raise ValueError("kind plan must pair every u64 hi word "
                         "directly after its lo word")
    mn = jnp.zeros((v,), jnp.uint32)
    mx = jnp.zeros((v,), jnp.uint32)
    sm = jnp.zeros((v,), jnp.uint32)

    u32_idx = _idx(kinds, KIND_U32)
    if u32_idx:
        ia = jnp.asarray(u32_idx, jnp.int32)
        col = jnp.take(rows, ia, axis=1)
        mn = mn.at[ia].set(jnp.min(col, axis=0), mode="drop")
        mx = mx.at[ia].set(jnp.max(col, axis=0), mode="drop")
        sm = sm.at[ia].set(jnp.sum(col, axis=0, dtype=jnp.uint32),
                           mode="drop")
    f32_idx = _idx(kinds, KIND_F32)
    if f32_idx:
        ia = jnp.asarray(f32_idx, jnp.int32)
        col = _bitcast(jnp.take(rows, ia, axis=1), jnp.float32)
        back = lambda x: _bitcast(x, jnp.uint32)  # noqa: E731
        mn = mn.at[ia].set(back(jnp.min(col, axis=0)), mode="drop")
        mx = mx.at[ia].set(back(jnp.max(col, axis=0)), mode="drop")
        sm = sm.at[ia].set(back(jnp.sum(col, axis=0)), mode="drop")
    if lo_idx:
        il = jnp.asarray(lo_idx, jnp.int32)
        ih = il + jnp.int32(1)
        lo = jnp.take(rows, il, axis=1)              # [R, C]
        hi = jnp.take(rows, ih, axis=1)
        # Lexicographic (hi, lo) min/max: settle hi first, then reduce
        # lo over exactly the replicas that achieve it.
        mn_hi = jnp.min(hi, axis=0)
        mn_lo = jnp.min(jnp.where(hi == mn_hi[None, :], lo,
                                  jnp.uint32(_M32)), axis=0)
        mx_hi = jnp.max(hi, axis=0)
        mx_lo = jnp.max(jnp.where(hi == mx_hi[None, :], lo,
                                  jnp.uint32(0)), axis=0)
        # Carry-exact u64 sum: sum(value) = sum(lo) + 2^32 * sum(hi).
        losum = col_sum_u64(lo)                      # [2, C] (lo, hi)
        hisum = col_sum_u64(hi)
        sum_lo = losum[0]
        sum_hi = losum[1] + hisum[0]                 # hi's 2^32 weight
        mn = mn.at[il].set(mn_lo, mode="drop").at[ih].set(mn_hi,
                                                          mode="drop")
        mx = mx.at[il].set(mx_lo, mode="drop").at[ih].set(mx_hi,
                                                          mode="drop")
        sm = sm.at[il].set(sum_lo, mode="drop").at[ih].set(sum_hi,
                                                           mode="drop")
    return jnp.stack([mn, mx, sm])


def _bitcast(x: jnp.ndarray, dtype) -> jnp.ndarray:
    """Same-width bitcast (u32 <-> f32)."""
    return lax.bitcast_convert_type(x, dtype)


@contract(out=Spec("uint32", ("H", 3, "V")),
          rings=Spec("uint32", ("R", "H", "V")), kinds=_KINDS_EXAMPLE,
          dims={"R": 19, "V": 5})
def ring_band(rings: jnp.ndarray, kinds: tuple) -> jnp.ndarray:
    """``u32[H, 3, V]``: :func:`band_reduce` applied per ring slot.

    ``rings`` is the fleet-stacked round-history ring
    (``PeerState.tele_ring``, [R, H, RW]); all replicas advance in
    lockstep, so slot h holds the SAME round on every replica and the
    per-slot band is a per-round band.  A whole multi-round convergence
    band therefore drains in one [H, 3, RW] transfer.
    """
    rows_by_slot = jnp.swapaxes(rings, 0, 1)         # [H, R, V]
    return jax.vmap(lambda r: band_reduce(r, kinds))(rows_by_slot)
