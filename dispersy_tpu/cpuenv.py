"""Scrubbed child-process environments pinned to the CPU backend.

Shared by the driver-facing entry points (``__graft_entry__.py``,
``bench.py``): both need to run JAX work in a subprocess that cannot be
hijacked by the axon TPU-tunnel plugin, whose ``sitecustomize`` hook on
PYTHONPATH *prepends* itself to ``jax_platforms`` and whose backend init
can hang when the tunnel is half-up (the round-1 driver artifacts recorded
exactly that: BENCH_r01 rc=1, MULTICHIP_r01 rc=124).

This module must not import jax: it runs in parent processes that may have
no usable backend at all.
"""

from __future__ import annotations

import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Durable in-repo compile cache, pre-warmed at commit time so a driver
# cold start compiles from cache (a /tmp cache does not survive between
# the builder's session and the driver's run).
CACHE_DIR = os.path.join(REPO_ROOT, "artifacts", "jax_cache")
CACHE_MIN_COMPILE_SECS = 0.5


def enable_repo_cache() -> None:
    """Point this process's JAX at the durable in-repo compile cache.

    For processes that already hold the right backend (bench worker, the
    in-process dryrun); subprocess paths get the same cache via
    :func:`cpu_env`'s environment variables.  Imports jax lazily — this
    module must stay importable without a usable backend.
    """
    import jax

    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      CACHE_MIN_COMPILE_SECS)


def cpu_env(n_devices: int | None = None) -> dict:
    """An environment forcing the CPU backend, axon hook removed.

    ``n_devices``: if given, request that many virtual CPU devices via
    ``xla_force_host_platform_device_count`` (any pre-existing count flag is
    replaced); if None, XLA_FLAGS is left alone.
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p) or REPO_ROOT
    if n_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    # Re-use compile caches across driver invocations (see CACHE_DIR).
    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                   str(CACHE_MIN_COMPILE_SECS))
    return env
