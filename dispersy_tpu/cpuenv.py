"""Scrubbed child-process environments pinned to the CPU backend.

Shared by the driver-facing entry points (``__graft_entry__.py``,
``bench.py``): both need to run JAX work in a subprocess that cannot be
hijacked by the axon TPU-tunnel plugin, whose ``sitecustomize`` hook on
PYTHONPATH *prepends* itself to ``jax_platforms`` and whose backend init
can hang when the tunnel is half-up (the round-1 driver artifacts recorded
exactly that: BENCH_r01 rc=1, MULTICHIP_r01 rc=124).

This module must not import jax at top level: it runs in parent processes
that may have no usable backend at all.
"""

from __future__ import annotations

import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Durable in-repo compile cache — TPU ONLY.  TPU executables target the
# chip, so a committed entry is valid wherever the same chip type sits
# behind the tunnel.  XLA:CPU executables instead bake in compile-host
# machine features (including pseudo-features like `+prefer-no-gather`
# that no host ever reports), so every persistent-cache CPU load tripped
# the loader's "could lead to SIGILL" warning in the driver tail — on a
# *different* host it is a real SIGILL risk, and rounds 1-3 committed
# exactly such entries.  The CPU path now always compiles cold in driver
# runs: the full 8-device dry run costs ~58 s cold on a 1-core box,
# ~15x inside its 900 s timeout.  (The test suite keeps its own
# same-session /tmp cache via tests/conftest.py env vars, which
# subprocesses inherit.)
TPU_CACHE_DIR = os.path.join(REPO_ROOT, "artifacts", "jax_cache", "tpu")
CACHE_MIN_COMPILE_SECS = 0.5

# XLA:CPU's parallel LLVM codegen intermittently segfaults mid-compile on
# this 1-core image (observed twice on 2026-07-30, stacks ending in
# backend_compile_and_load; different test each time).  Single-split
# codegen costs nothing on one core and removes the raciest path.  Shared
# by tests/conftest.py and cpu_env so the suite and driver children can
# never drift onto different codegen settings.
CODEGEN_SPLIT_FLAG = "--xla_cpu_parallel_codegen_split_count=1"


def with_codegen_split(flags: str) -> str:
    """Append the single-split codegen mitigation if not already set."""
    if "xla_cpu_parallel_codegen_split_count" in flags:
        return flags
    return (flags + " " + CODEGEN_SPLIT_FLAG).strip()


def _enable_cache(path: str) -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      CACHE_MIN_COMPILE_SECS)


def enable_repo_cache() -> None:
    """Point this process's JAX at the durable in-repo TPU compile cache.

    No-op on non-TPU backends (see the cache note above): a CPU process
    uses whatever ``JAX_COMPILATION_CACHE_DIR`` its environment already
    carries, or compiles cold.  Imports jax lazily — this module must
    stay importable without a usable backend.
    """
    import jax

    if jax.default_backend() == "tpu":
        _enable_cache(TPU_CACHE_DIR)


def enable_bench_cache() -> None:
    """Persistent compile cache for the bench worker: the committed
    chip-targeted cache on TPU (the 26-40 s first-step compiles it
    amortizes are what burned the r04/r05 tunnel windows); NOTHING on
    CPU.  A same-host CPU cache was tried (2026-08-03) and the warm-run
    executable SEGFAULTS deterministically — the AOT-loader hazard
    documented at TPU_CACHE_DIR bites same-host deserialization too, so
    CPU workers always compile cold.  Imports jax lazily."""
    enable_repo_cache()


def enable_tool_cache(path: str = "/tmp/jax_cache") -> None:
    """Compile cache for local tools (scaling/profile sweeps).

    On TPU: the durable in-repo chip cache.  Elsewhere: a same-session
    /tmp cache — safe because it never crosses hosts, unlike the
    committed CPU cache the driver paths no longer use.  Imports jax
    lazily.
    """
    import jax

    _enable_cache(TPU_CACHE_DIR if jax.default_backend() == "tpu" else path)


def cpu_env(n_devices: int | None = None) -> dict:
    """An environment forcing the CPU backend, axon hook removed.

    ``n_devices``: if given, request that many virtual CPU devices via
    ``xla_force_host_platform_device_count`` (any pre-existing count flag is
    replaced); if None, XLA_FLAGS is left alone.
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p) or REPO_ROOT
    if n_devices is not None:
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    # With the persistent CPU cache gone, driver children compile fresh —
    # they need the same codegen-segfault mitigation the suite uses.
    env["XLA_FLAGS"] = with_codegen_split(env.get("XLA_FLAGS", ""))
    # No cache vars are set here: a CPU child caches only if the caller's
    # environment already asks for it (the test suite does, via conftest;
    # driver runs don't, so their tails stay free of the CPU AOT loader's
    # SIGILL warning — see the TPU_CACHE_DIR note above).
    return env
