"""The telemetry plane: static config + packed-row schema + host decoders.

The reference exposes a pull-model statistics snapshot (statistics.py
``DispersyStatistics``) that the rebuild mirrored with ~25 independent
device->host reductions per :func:`dispersy_tpu.metrics.snapshot` call —
a host sync between rounds that fights the north star of batching rounds
on device (``engine.multi_step``).  This module declares the four-layer
replacement (the jit-traced kernels live in
:mod:`dispersy_tpu.ops.telemetry`; the engine composes them into the
fused round's wrap-up only when the matching knob is on, so disabled
telemetry compiles to the identical step — the ``faults`` pattern):

1. **Fused in-step row** (``TelemetryConfig.enabled``): every
   ``snapshot()`` aggregate — counter totals in u64-safe u32-pair form,
   occupancy numerators, health-bit counts — is reduced inside the
   jitted step and packed into one ``uint32[row_width]`` vector
   (``PeerState.tele_row``).  A snapshot becomes ONE device->host
   transfer of that row instead of ~25 per-field reductions.
2. **Device-resident round history** (``history``): a ring
   ``PeerState.tele_ring`` of the last ``history`` packed rows, written
   inside ``step`` at slot ``round % history`` — ``multi_step`` can run
   K rounds entirely on device and the whole per-round metrics history
   drains in a single transfer (:meth:`MetricsLog.extend_from_ring`).
3. **On-device histograms** (``histograms``): bucketed per-round
   distributions (store/candidate/request-inbox occupancy, per-peer
   round drop counts, Bloom popcount, walk-success streaks) appended to
   the row; ``snapshot()`` derives p50/p99 host-side from the buckets.
4. **Flight recorder** (``flight_recorder``): a ring of per-peer event
   records capturing the first ``flight_per_round`` peers whose health
   sentinel (dispersy_tpu/faults.py) NEWLY latched each round — which
   bit, which round, and the key counters at latch time — so a latched
   bit is debuggable after the fact instead of being a bare flag.

Row format: a flat ``uint32`` vector laid out by :func:`row_schema` —
``u32`` fields are one word, ``f32`` one word (IEEE-754 bitcast),
``u64`` two words (lo, hi), ``hist`` ``hist_buckets`` words of bucket
counts.  Word 0 is the post-step round index, which is never 0 — an
all-zero row therefore means "no step has run yet", and ring slots
identify their round from the row itself (no cursor leaf needed).

Everything here is host-side and import-light (no jax): the oracle
packs rows through :func:`pack_row_host` so device and reference rows
are built from ONE schema definition.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from dispersy_tpu.exceptions import ConfigError
from dispersy_tpu.faults import HEALTH_BIT_NAMES
from dispersy_tpu.traceplane import CHANNEL_NAMES, LATCH_PCTS

_M32 = 0xFFFFFFFF

# Counter totals carried as u64 (lo, hi) word pairs — exactly the set
# metrics.snapshot has always reduced, in its order.  Per-peer device
# counters wrap mod 2^32 by design (state.py); the row sums the wrapped
# values exactly (the same totals the host reduction sees).
U64_COUNTERS = (
    "walk_success", "walk_fail", "msgs_stored", "msgs_dropped",
    "msgs_rejected", "msgs_forwarded", "msgs_direct", "msgs_delayed",
    "msgs_corrupt_dropped", "requests_dropped", "punctures",
    "sig_signed", "sig_done", "sig_expired", "conflicts",
    "bytes_up", "bytes_down",
)

# Exact-sum bound of the byte-split u64 reduction (ops/telemetry.py
# col_sum_u64): each byte-lane partial sum must fit uint32, so
# n_peers * 255 < 2^32.
MAX_TELEMETRY_PEERS = (1 << 32) // 255 - 1

# Flight-recorder record layout: FLIGHT_WIDTH u32 words per record.
# ``peer`` is EMPTY (0xFFFFFFFF) on never-written ring slots.
FLIGHT_FIELDS = ("peer", "round", "new_bits", "health",
                 "requests_dropped", "msgs_dropped", "drop_delta",
                 "store_live")
FLIGHT_WIDTH = len(FLIGHT_FIELDS)

# Health-bit word order in the row (insertion order of HEALTH_BIT_NAMES
# == ascending bit) — keep in lockstep with faults.health_report.
HEALTH_NAMES = tuple(HEALTH_BIT_NAMES[b] for b in sorted(HEALTH_BIT_NAMES))


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Static telemetry knobs, composed into ``CommunityConfig``.

    Frozen + hashable (a static jit argument, like ``FaultModel``).  All
    defaults off compile to exactly the telemetry-free step; every leaf
    the plane adds (``tele_row`` / ``tele_ring`` / ``fr_ring`` /
    ``fr_pos`` / ``walk_streak``) is zero-width while its knob is off.
    """

    # Fused in-step row: reduce every snapshot aggregate inside the
    # jitted step and expose it as PeerState.tele_row.
    enabled: bool = False
    # Device-resident round-history ring depth (rows); 0 = off.
    history: int = 0
    # On-device histograms appended to the row (hist_buckets each).
    histograms: bool = False
    hist_buckets: int = 16
    # Flight-recorder ring depth (records); 0 = off.  Requires
    # faults.health_checks (validated by CommunityConfig — the recorder
    # captures health-bit latches).
    flight_recorder: int = 0
    # Newly-flagged peers recorded per round (lowest peer index first).
    flight_per_round: int = 4

    def __post_init__(self) -> None:
        if self.history < 0:
            raise ConfigError("telemetry.history must be >= 0")
        if self.flight_recorder < 0:
            raise ConfigError("telemetry.flight_recorder must be >= 0")
        if not self.enabled and (self.history > 0 or self.histograms
                                 or self.flight_recorder > 0):
            raise ConfigError(
                "telemetry.history/histograms/flight_recorder all ride "
                "the fused in-step row — set telemetry.enabled=True too")
        if not (2 <= self.hist_buckets <= 64):
            raise ConfigError("telemetry.hist_buckets must be in [2, 64]")
        if self.flight_recorder > 0:
            if self.flight_per_round < 1:
                raise ConfigError(
                    "telemetry.flight_per_round must be >= 1")
            if self.flight_per_round > self.flight_recorder:
                raise ConfigError(
                    "telemetry.flight_per_round cannot exceed the ring "
                    "depth (one round's records would overwrite each "
                    "other)")

    def replace(self, **kw) -> "TelemetryConfig":
        return dataclasses.replace(self, **kw)


def hist_specs(cfg) -> tuple:
    """``(name, kind, cap)`` per histogram, in row order.

    ``kind``: ``"linear"`` buckets span [0, cap] uniformly (bucket =
    ``val * B // (cap + 1)``); ``"log2"`` buckets by bit length (bucket
    0 = value 0, bucket b = values in [2^(b-1), 2^b), last bucket
    open-ended).  Masks (who contributes) are part of each histogram's
    definition — engine and oracle apply them identically:

    - ``store_fill``    all peers; live store rows, ring ∪ staging
                        (0..msg_capacity + store.staging)
    - ``cand_fill``     alive non-tracker members; live candidate slots
    - ``req_inbox``     non-tracker rows; intro-requests handled this
                        round (trackers serve the separate high-capacity
                        inbox and would clip this scale)
    - ``round_drops``   all peers; this round's dropped packets/records
                        (request-inbox overflow + push/store drops)
    - ``bloom_fill``    all peers; set bits in this round's claimed
                        Bloom (all-zero when sync is disabled)
    - ``walk_streak``   alive non-tracker members; consecutive
                        successful walks (PeerState.walk_streak)
    """
    return (("store_fill", "linear", cfg.msg_capacity + cfg.store.staging),
            ("cand_fill", "linear", cfg.k_candidates),
            ("req_inbox", "linear", cfg.request_inbox),
            ("round_drops", "log2", 0),
            ("bloom_fill", "linear", cfg.bloom_bits),
            ("walk_streak", "log2", 0))


def row_schema(cfg) -> tuple:
    """``(field, kind)`` pairs describing the packed row, in word order.

    Kinds: ``u32`` (1 word), ``f32`` (1 word, bitcast), ``u64`` (2
    words: lo, hi), ``hist`` (``hist_buckets`` words).  The schema is a
    pure function of the static config, so writer (engine), mirror
    (oracle) and reader (this module) can never disagree.
    """
    entries = [("round", "u32"), ("sim_time", "f32"),
               ("alive_members", "u32"), ("killed", "u32")]
    entries += [(name, "u64") for name in U64_COUNTERS]
    entries += [("store_live", "u64"), ("cand_live", "u64")]
    entries += [("health_or", "u32"), ("health_flagged", "u32")]
    entries += [(f"health_{nm}", "u32") for nm in HEALTH_NAMES]
    entries += [(f"accepted_by_meta_{i}", "u64")
                for i in range(cfg.n_meta + 1)]
    if cfg.trace.enabled:
        # Dissemination-tracing words (dispersy_tpu/traceplane.py;
        # OBSERVABILITY.md "Dissemination tracing").  CONDITIONAL on
        # the master knob so a trace-off row stays byte-identical —
        # the recovery/overload rule.  Declared BEFORE the overload
        # block, matching the config field order (trace precedes
        # store/overload/recovery).
        t = cfg.trace.tracked_slots
        entries += [(f"trace_cov_{k}", "u32") for k in range(t)]
        for k in range(t):
            entries += [(f"trace_r{pct}_{k}", "u32")
                        for pct in LATCH_PCTS]
        entries += [(f"trace_delivered_{nm}", "u64")
                    for nm in CHANNEL_NAMES]
        entries += [(f"trace_dup_{nm}", "u64") for nm in CHANNEL_NAMES]
        entries += [("trace_redundancy", "f32")]
    if cfg.overload.enabled:
        # Ingress-protection words (dispersy_tpu/overload.py;
        # OVERLOAD.md).  CONDITIONAL on the master knob so an
        # overload-off row stays byte-identical — the recovery/
        # histogram rule.  Declared BEFORE the recovery block, matching
        # the config field order (overload precedes recovery).
        entries += [("msgs_shed_rate", "u64"),
                    ("msgs_shed_priority", "u64"),
                    ("bucket_exhausted", "u32")]
    if cfg.recovery.enabled:
        # Recovery-plane action totals (dispersy_tpu/recovery.py;
        # RECOVERY.md).  CONDITIONAL on the master knob so a
        # recovery-off row stays byte-identical to the pre-recovery
        # schema — the same rule histograms follow.
        entries += [("recov_soft", "u64"), ("recov_backoff", "u64"),
                    ("recov_quarantine", "u64")]
        entries += [(f"recov_cleared_{nm}", "u64")
                    for nm in HEALTH_NAMES]
    if cfg.telemetry.histograms:
        entries += [(f"hist_{name}", "hist")
                    for name, _, _ in hist_specs(cfg)]
    return tuple(entries)


def adapt_row_leaves(state, old_cfg, new_cfg):
    """Re-shape the packed-row leaves (``tele_row`` / ``tele_ring``)
    across a config swap that changed the row SCHEMA width — the
    recov_* words are conditional on ``recovery.enabled`` and the
    shed/bucket words on ``overload.enabled``, so those planes'
    ``adapt_state`` implementations both call this.  Old rows are
    undecodable under the new config and cannot even live in the new
    leaf shapes, so both reset to zero (an all-zero row means "no step
    has run" — the ring drain's existing contract).  Identity when
    telemetry is off or the width did not change."""
    import jax.numpy as jnp

    new_w = row_width(new_cfg)
    if new_w == row_width(old_cfg):
        return state
    return state.replace(
        tele_row=jnp.zeros((new_w,), jnp.uint32),
        tele_ring=jnp.zeros((new_cfg.telemetry.history, new_w),
                            jnp.uint32))


def _kind_width(kind: str, cfg) -> int:
    if kind == "u64":
        return 2
    if kind == "hist":
        return cfg.telemetry.hist_buckets
    return 1


def row_width(cfg) -> int:
    """Words in the packed row for this config (0 when disabled)."""
    if not cfg.telemetry.enabled:
        return 0
    return sum(_kind_width(kind, cfg) for _, kind in row_schema(cfg))


def pack_row_host(values: dict, cfg) -> np.ndarray:
    """Pack a ``{field: value}`` dict into the uint32 row (host/numpy).

    The oracle's writer — the device row (engine wrap-up) must be
    bit-identical to this packing of the same values.  ``u64`` values
    are Python ints, ``f32`` floats, ``hist`` length-``hist_buckets``
    count sequences.
    """
    words: list[int] = []
    for name, kind in row_schema(cfg):
        v = values[name]
        if kind == "u32":
            words.append(int(v) & _M32)
        elif kind == "f32":
            words.append(int(np.float32(v).view(np.uint32)))
        elif kind == "u64":
            words += [int(v) & _M32, (int(v) >> 32) & _M32]
        else:  # hist
            if len(v) != cfg.telemetry.hist_buckets:
                raise ValueError(f"{name}: {len(v)} buckets, expected "
                                 f"{cfg.telemetry.hist_buckets}")
            words += [int(x) & _M32 for x in v]
    return np.asarray(words, np.uint32)


def unpack_row(row: np.ndarray, cfg) -> dict:
    """Inverse of the row packing: raw ``{field: value}`` dict.

    ``u64`` fields come back as ints, ``f32`` as floats, ``hist`` as
    bucket-count lists.  Raises on a width mismatch (schema drift
    between writer and reader would silently misalign every later
    field).
    """
    row = np.asarray(row, np.uint32)
    want = row_width(cfg)
    if row.shape != (want,):
        raise ValueError(f"telemetry row shape {row.shape}, config "
                         f"expects ({want},)")
    out: dict = {}
    off = 0
    for name, kind in row_schema(cfg):
        if kind == "u32":
            out[name] = int(row[off])
        elif kind == "f32":
            out[name] = float(row[off:off + 1].view(np.float32)[0])
        elif kind == "u64":
            out[name] = int(row[off]) | (int(row[off + 1]) << 32)
        else:
            hb = cfg.telemetry.hist_buckets
            out[name] = [int(x) for x in row[off:off + hb]]
        off += _kind_width(kind, cfg)
    return out


# Word-kind codes for the fleet plane's cross-replica band reduction
# (dispersy_tpu/ops/fleet.py band_reduce): how each u32 row word reduces
# across the replica axis.  KIND_U64_LO/HI always come in adjacent
# (lo, hi) pairs, in that order — the u64 packing above.
KIND_U32 = 0       # plain u32 word: elementwise min/max, sum mod 2^32
KIND_F32 = 1       # IEEE-754 bitcast: min/max/sum in f32
KIND_U64_LO = 2    # low word of a u64 pair: lexicographic (hi, lo)
KIND_U64_HI = 3    #   min/max, carry-exact sum


def word_kinds(cfg) -> tuple:
    """Per-word kind codes for this config's packed row, in word order
    (length == :func:`row_width`).  The static plan
    ``ops.fleet.band_reduce`` consumes; hist bucket words are plain u32
    counts (the band's sum row is the replica-pooled histogram)."""
    codes: list[int] = []
    for _, kind in row_schema(cfg):
        if kind == "u32":
            codes.append(KIND_U32)
        elif kind == "f32":
            codes.append(KIND_F32)
        elif kind == "u64":
            codes += [KIND_U64_LO, KIND_U64_HI]
        else:  # hist
            codes += [KIND_U32] * cfg.telemetry.hist_buckets
    return tuple(codes)


def band_to_dict(band: np.ndarray, cfg, n_replicas: int) -> dict:
    """Decode a ``[3, row_width]`` min/max/sum band (the fleet plane's
    ONE cross-replica host transfer) into
    ``{field: {"min", "max", "sum", "mean"}}``.

    Each band row is laid out exactly like a telemetry row, so
    :func:`unpack_row` decodes all three; ``mean = sum / n_replicas``
    is derived host-side (u64 sums are carry-exact on device; plain-u32
    and hist-count sums wrap mod 2^32 — fine for the count ranges the
    schema carries).  ``hist`` fields report per-bucket min/max lists
    and the pooled-sum buckets.
    """
    band = np.asarray(band, np.uint32)
    if band.shape != (3, row_width(cfg)):
        raise ValueError(f"band shape {band.shape}, config expects "
                         f"(3, {row_width(cfg)})")
    mn, mx, sm = (unpack_row(row, cfg) for row in band)
    out = {}
    for name, kind in row_schema(cfg):
        if kind == "hist":
            out[name] = {"min": mn[name], "max": mx[name],
                         "sum": sm[name],
                         "mean": [s / n_replicas for s in sm[name]]}
        else:
            out[name] = {"min": mn[name], "max": mx[name],
                         "sum": sm[name],
                         "mean": sm[name] / n_replicas}
    return out


def bucket_upper_bound(kind: str, cap: int, bucket: int,
                       n_buckets: int) -> int:
    """Largest value a histogram bucket can hold (the value p50/p99
    report).  Linear bucket b covers ``v*B//(cap+1) == b``; log2 bucket
    b covers ``bit_length(v) == b`` (0 -> 0, else [2^(b-1), 2^b))."""
    if kind == "linear":
        return min(cap, ((bucket + 1) * (cap + 1) - 1) // n_buckets)
    return (1 << bucket) - 1


def bucket_percentile(counts, q_num: int, q_den: int, kind: str,
                      cap: int) -> int:
    """Percentile (as a bucket upper-bound value) from bucket counts.

    Integer math throughout (``q_num/q_den`` e.g. 50/100): the smallest
    bucket whose cumulative count reaches ``ceil(q * total)``.  0 when
    the histogram is empty.
    """
    counts = [int(c) for c in counts]
    total = sum(counts)
    if total == 0:
        return 0
    need = -(-q_num * total // q_den)        # ceil
    cum = 0
    for b, c in enumerate(counts):
        cum += c
        if cum >= need:
            return bucket_upper_bound(kind, cap, b, len(counts))
    return bucket_upper_bound(kind, cap, len(counts) - 1, len(counts))


def row_to_snapshot(row: np.ndarray, cfg) -> dict:
    """The ``metrics.snapshot`` dict, derived from one packed row.

    Emits the exact key set (and value semantics) of the legacy
    per-field reduction path, plus — with histograms on —
    ``hist_<name>_p50`` / ``hist_<name>_p99`` scalars and the raw
    ``hist_<name>`` bucket lists (non-scalar, so JSON-only in
    ``MetricsLog.dump_binary``, by the same rule as
    ``accepted_by_meta``).
    """
    raw = unpack_row(row, cfg)
    ws, wf = raw["walk_success"], raw["walk_fail"]
    n_members = max(raw["alive_members"], 1)
    out = {
        "round": raw["round"],
        "sim_time": raw["sim_time"],
        "alive_members": raw["alive_members"],
        "killed": raw["killed"],
        "walk_success": ws,
        "walk_fail": wf,
        "walk_success_rate": ws / max(ws + wf, 1),
    }
    for name in U64_COUNTERS[2:]:
        out[name] = raw[name]
    # Occupancy means from exact integer numerators (the legacy path
    # accumulated the same ratios in float32; this is the same quantity
    # computed exactly).
    out["store_fill"] = raw["store_live"] / float(
        cfg.n_peers * (cfg.msg_capacity + cfg.store.staging))
    out["candidate_fill"] = raw["cand_live"] / float(
        cfg.k_candidates * n_members)
    out["health_or"] = raw["health_or"]
    out["health_flagged"] = raw["health_flagged"]
    for nm in HEALTH_NAMES:
        out[f"health_{nm}"] = raw[f"health_{nm}"]
    out["accepted_by_meta"] = [raw[f"accepted_by_meta_{i}"]
                               for i in range(cfg.n_meta + 1)]
    if cfg.trace.enabled:
        # Dissemination-tracing surfacing (traceplane.py): per-slot
        # coverage counts + percentile latches, per-channel delivery
        # accounting, and the redundancy ratio — key-identical to the
        # legacy snapshot path's trace block (traceplane.trace_totals).
        for k in range(cfg.trace.tracked_slots):
            out[f"trace_cov_{k}"] = raw[f"trace_cov_{k}"]
            for pct in LATCH_PCTS:
                out[f"trace_r{pct}_{k}"] = raw[f"trace_r{pct}_{k}"]
        for nm in CHANNEL_NAMES:
            out[f"trace_delivered_{nm}"] = raw[f"trace_delivered_{nm}"]
            out[f"trace_dup_{nm}"] = raw[f"trace_dup_{nm}"]
        out["trace_redundancy"] = raw["trace_redundancy"]
    if cfg.overload.enabled:
        # Ingress-protection surfacing (overload.py; OVERLOAD.md): the
        # shed streams + exhausted-bucket count, key-identical to the
        # legacy snapshot path's overload block.
        for nm in ("msgs_shed_rate", "msgs_shed_priority",
                   "bucket_exhausted"):
            out[nm] = raw[nm]
    if cfg.recovery.enabled:
        # Recovery-plane surfacing (recovery.py; RECOVERY.md): action
        # totals, per-bit clears, and the instantaneous availability
        # (fraction of peers unflagged this round — the peer-round
        # availability over a window comes from recovery.mttr_report).
        from dispersy_tpu.recovery import availability_of
        for nm in ("recov_soft", "recov_backoff", "recov_quarantine"):
            out[nm] = raw[nm]
        for nm in HEALTH_NAMES:
            out[f"recov_cleared_{nm}"] = raw[f"recov_cleared_{nm}"]
        out["availability"] = availability_of(raw["health_flagged"],
                                              cfg.n_peers)
    if cfg.telemetry.histograms:
        for name, kind, cap in hist_specs(cfg):
            counts = raw[f"hist_{name}"]
            out[f"hist_{name}_p50"] = bucket_percentile(
                counts, 50, 100, kind, cap)
            out[f"hist_{name}_p99"] = bucket_percentile(
                counts, 99, 100, kind, cap)
            out[f"hist_{name}"] = counts
    return out


def ring_rows(ring: np.ndarray, cfg) -> list:
    """Decode a drained ``tele_ring`` array into snapshot dicts,
    oldest round first.

    Slots identify themselves: word 0 is the row's post-step round
    index (>= 1), so never-written slots (all-zero) are skipped and no
    cursor has to cross the host boundary.  Every live slot holds one
    of the most recent ``history`` rounds by construction (older rows
    were overwritten in place).
    """
    ring = np.asarray(ring, np.uint32)
    rows = [row for row in ring if int(row[0]) > 0]
    rows.sort(key=lambda r: int(r[0]))
    return [row_to_snapshot(row, cfg) for row in rows]


def flight_records(state, cfg) -> list:
    """Decode the flight-recorder ring into event dicts, oldest first.

    Each dict carries the :data:`FLIGHT_FIELDS` (``new_bits`` /
    ``health`` additionally decoded into sentinel names via
    ``faults.HEALTH_BIT_NAMES``).  ``fr_pos`` counts records ever
    written, so ordering is exact even after the ring wraps.
    """
    if cfg.telemetry.flight_recorder <= 0:
        return []
    ring = np.asarray(state.fr_ring, np.uint32)
    pos = int(np.asarray(state.fr_pos)[0])
    depth = ring.shape[0]
    live = min(pos, depth)
    out = []
    for i in range(pos - live, pos):
        rec = ring[i % depth]
        if int(rec[0]) == _M32:      # never written (defensive)
            continue
        d = {k: int(v) for k, v in zip(FLIGHT_FIELDS, rec)}
        d["new_bit_names"] = [nm for bit, nm in HEALTH_BIT_NAMES.items()
                              if d["new_bits"] & bit]
        d["health_names"] = [nm for bit, nm in HEALTH_BIT_NAMES.items()
                             if d["health"] & bit]
        out.append(d)
    return out
